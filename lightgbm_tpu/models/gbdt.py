"""GBDT boosting orchestrator.

TPU-native analog of the reference boosting layer (reference:
src/boosting/gbdt.cpp GBDT): per-iteration flow mirrors GBDT::TrainOneIter
(gbdt.cpp:369-452):

  boost_from_average (first iter, gbdt.cpp:344-367)
  -> objective gradients (Boosting(), gbdt.cpp:170-179)
  -> bagging (gbdt.cpp:228-262; mask-based here to keep shapes static)
  -> per-class tree growth (models/grower.py)
  -> RenewTreeOutput (objective leaf refresh, gbdt.cpp:433)
  -> Shrinkage (gbdt.cpp:411 tree->Shrinkage(lr))
  -> UpdateScore train + valid (gbdt.cpp:369-452; out-of-bag rows included,
     gbdt.cpp:434-452)

Trees are stored both as device arrays (stacked lazily for batched ensemble
prediction) and as host ``HostTree`` objects for model IO/SHAP.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..basic import Dataset
from ..config import Config
from ..metrics import Metric, create_metric, default_metric_for_objective
from ..objectives import ObjectiveFunction, create_objective
from ..ops.split import SplitParams
from ..utils import log
from .grower import GrowAux, grow_tree
from .tree import (HostTree, TreeArrays, predict_leaf_bins,
                   predict_leaf_bins_depth, predict_value_bins, stack_trees)


import functools


# bit -> source name of the fused step's in-program sentinel flag word
# (see _fused_step_fn: packed NaN/Inf bits computed inside the compiled
# program and fetched with the iteration's own results)
_SENTINEL_SOURCES = (
    (0, "gradients"),
    (1, "hessians"),
    (2, "histogram sums (in-program, Pallas/XLA histogram path)"),
    (3, "leaf outputs"),
    (4, "score delta"),
)


def _chunk_iters_cap(n: int, k: int, itemsize: int) -> int:
    """Iterations per stacked-predict dispatch so the [t, n, k] host buffer
    stays under ~256 MB."""
    return max(1, (256 << 20) // itemsize // max(n * k, 1))


def _chunked_tree_ranges(start_it: int, end_it: int, k: int, n: int,
                         itemsize: int):
    """Yield (a, b) TREE ranges covering [start_it, end_it) iterations in
    buffer-capped chunks (shared by the stacked value/leaf predict paths)."""
    cap = _chunk_iters_cap(n, k, itemsize)
    it = start_it
    while it < end_it:
        ce = min(end_it, it + cap)
        yield it * k, ce * k
        it = ce


@functools.partial(jax.jit, static_argnames=("n",))
def _bagging_mask(key: jax.Array, frac, n: int) -> jax.Array:
    """0/1 bagging mask drawn on device (gbdt.cpp:228-262 Bagging)."""
    u = jax.random.uniform(key, (n,))
    return (u < frac).astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("n",))
def _bagging_mask_rows(key: jax.Array, frac, row_start, n: int) -> jax.Array:
    """Bagging mask for pre-partitioned runs, keyed per GLOBAL row
    (fold_in(key, global_row) -> one uniform draw each): every row's
    keep/drop decision depends only on the period key and the row's global
    index, never on how rows are split across processes — so a gang
    resumed at a DIFFERENT world size re-derives the exact same sample
    the original partition drew (checkpoint.py's elastic resume)."""
    rows = row_start + jnp.arange(n)
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(key, rows)
    u = jax.vmap(lambda k: jax.random.uniform(k, ()))(keys)
    return (u < frac).astype(jnp.float32)


@jax.jit
def _linear_valid_delta(leaf: jax.Array, leaf_value: jax.Array,
                        const: jax.Array, W: jax.Array, used: jax.Array,
                        raw: jax.Array) -> jax.Array:
    """Linear-leaf tree output for valid rows, on device (the device analog
    of ModelTree.predict's linear branch: const + coeff.x, rows with
    NaN/inf in any of their leaf's linear features fall back to the plain
    leaf value, linear_tree_learner.cpp:19-41)."""
    oh = jax.nn.one_hot(leaf, const.shape[0], dtype=jnp.float32)   # [N, L]
    finite = jnp.isfinite(raw)
    raw0 = jnp.where(finite, raw, 0.0)
    w_row = jax.lax.dot_general(oh, W, (((1,), (0,)), ((), ())),
                                precision=jax.lax.Precision.HIGHEST)
    contrib = jnp.sum(w_row * raw0, axis=1)
    used_row = jax.lax.dot_general(oh, used, (((1,), (0,)), ((), ())),
                                   precision=jax.lax.Precision.HIGHEST)
    bad = jnp.sum(used_row * (~finite).astype(jnp.float32), axis=1) > 0
    return jnp.where(bad, leaf_value[leaf], const[leaf] + contrib)


@functools.partial(jax.jit, static_argnames=("k",))
def _bagging_subset(key: jax.Array, bins: jax.Array, k: int):
    """Exact-k bagging selection + subset copy (gbdt.cpp:810-818 /
    Dataset::CopySubrow): the k rows with the smallest random draws are
    gathered into a compact [K, F] matrix so histogram passes scale with
    the bagging fraction instead of full N."""
    n = bins.shape[0]
    r = jax.random.bits(key, (n,), jnp.uint32)
    sub_idx = jnp.argsort(r)[:k].astype(jnp.int32)
    mask = jnp.zeros((n,), jnp.float32).at[sub_idx].set(1.0)
    sub_bins = jnp.take(bins, sub_idx, axis=0)
    return mask, sub_idx, sub_bins, sub_bins.T


def _fma_guard(x: jax.Array, salt_u32: jax.Array) -> jax.Array:
    """Value-preserving rounding fence: bitcast ``x`` to uint32, XOR with
    a RUNTIME-ZERO salt the compiler cannot prove zero, bitcast back.

    Why it exists: inside one compiled program XLA's CPU/TPU backends
    contract a multiply feeding an add into an FMA whose single rounding
    drifts 1 ulp from the two-rounding sequence — and they do it even
    across ``optimization_barrier`` and through a gather whose operand is
    the multiply (both verified here; the PR 3 lesson that forced the
    score add into its own program). The K-block scan cannot split the
    program (the score is its carry), so this fence breaks the FLOAT
    dataflow instead: the multiply's result must round to a concrete f32
    bit pattern to enter the integer domain, and no fmul-fadd pattern
    survives for the backend to contract. The salt (e.g. ``it0 < -1`` on
    a non-negative operand) is what stops the algebraic simplifier from
    cancelling the bitcast pair and re-exposing the multiply."""
    xi = jax.lax.bitcast_convert_type(x, jnp.uint32)
    xi = jnp.bitwise_xor(xi, salt_u32)
    return jax.lax.bitcast_convert_type(xi, jnp.float32)


@functools.partial(jax.jit, donate_argnums=(0,))
def _apply_score_delta(score: jax.Array, delta: jax.Array) -> jax.Array:
    """Score-cache update for the fused iteration, as its OWN tiny program
    with the score buffer DONATED: the add writes in place instead of
    allocating a fresh [N, K] cache every iteration. Kept separate from
    the fused grow program on purpose — inside one XLA loop fusion the
    backend contracts the leaf-value*lr multiply and this add into an FMA
    whose single rounding drifts 1 ulp from the unfused path (observed on
    CPU even across an optimization_barrier), breaking the fused-vs-
    unfused bit-parity the suite asserts. ``delta`` arrives [N] (one
    class) or [K, N] (the fused multiclass scan's stacked layout); the
    column-disjoint adds are bit-identical to the unfused per-class
    ``at[:, c].add`` sequence."""
    return score + (delta.T if delta.ndim == 2 else delta)


@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnames=("depth", "kk"))
def _apply_valid_tree(score: jax.Array, tree: TreeArrays, bins: jax.Array,
                      missing_bin: jax.Array, class_idx, depth: int,
                      kk: int) -> jax.Array:
    """Per-iteration valid-score update as ONE compiled program with the
    score cache DONATED (in-place add): depth-bounded traversal + leaf
    gather + add — the training-time eval leg of the inference engine.
    Previously this was an eager predict_value_bins per tree per valid
    set (an op-by-op dispatch chain); now eval-on-valid costs one
    dispatch. No multiply feeds the add (leaf values arrive pre-shrunk),
    so there is no FMA-contraction parity hazard (see _apply_score_delta)
    and the result is bit-identical to the eager path."""
    leaf = predict_leaf_bins_depth(tree, bins, missing_bin, depth)
    delta = tree.leaf_value[leaf]
    if kk > 1:
        return score.at[:, class_idx].add(delta)
    return score + delta


def _shrink_tree(tree: TreeArrays, lr: float) -> TreeArrays:
    """Apply the learning rate to a tree's value-bearing fields
    (Tree::Shrinkage, tree.h:187). Works on device or host-mirrored
    TreeArrays — the single definition both finalize paths share."""
    return tree._replace(leaf_value=tree.leaf_value * lr,
                         node_value=tree.node_value * lr,
                         shrinkage=tree.shrinkage * lr)


class GBDT:
    """Gradient Boosting Decision Tree (reference: gbdt.h:42, boosting.h:27)."""

    name = "gbdt"
    average_output = False

    def __init__(self, config: Config, train_set: Optional[Dataset] = None,
                 objective: Optional[ObjectiveFunction] = None):
        self.config = config
        self.train_set = train_set
        self.objective = objective
        self.trees: List[TreeArrays] = []       # device trees, leaf_value shrunk
        self._host_trees: List[HostTree] = []
        # host-mirror pipeline: device trees whose host fetch is in flight
        # (index into _host_trees, device TreeArrays). See host_trees below.
        self._pending_host: List[Tuple[int, TreeArrays]] = []
        # lagged no-split stop: count splitless flushed trees PER ITERATION
        # group (tree index // num_tree_per_iteration) — the reference stop
        # condition is one whole iteration without a split, so the count
        # must not straddle iteration boundaries
        self._splitless_group = -1
        self._splitless_in_group = 0
        self._lagged_stop = False    # a full splitless iteration was flushed
        self.num_class = max(config.num_class, 1)
        self.num_tree_per_iteration = 1
        self.init_scores: List[float] = []
        self.tree_bias: List[float] = []   # bias folded into each stored tree
        self.iter = 0
        # continued training: a LoadedGBDT whose trees precede ours
        # (reference: gbdt.h num_init_iteration_, engine.py:163-169)
        self.loaded = None
        self.loaded_iters = 0
        # fused-iteration compile cache: static-options tuple (see
        # _fused_step_fn's key) -> (jitted step, dataset-constant bind).
        # Bounded: parallel-learner binds pin padded full-dataset copies,
        # so stale entries from reset_parameter sweeps must be evicted
        self._fused_cache: Dict[tuple, tuple] = {}
        # (learner, forced-splits, padded dataset bind) per binsT flavor —
        # see _fused_parallel_bindings
        self._fused_bind_cache: Dict[bool, tuple] = {}
        self._mt_cache: Dict[int, object] = {}   # host-tree idx -> ModelTree
        self._valid_raw_cache: Dict[int, jax.Array] = {}
        self._stacked_cache: Optional[Tuple[int, TreeArrays]] = None
        # device inference engines keyed by tree count; each entry records
        # the stacked pytree it was built from, so a stacked-cache refresh
        # (new trees, shuffle, rollback, restore) invalidates it by identity
        self._engine_cache: Dict[int, Tuple[TreeArrays, object]] = {}
        # guards engine-cache fill/eviction: two serve threads first-
        # touching a booster used to both build an engine and race the
        # bounded eviction (reentrant — _predict_engine can re-enter via
        # the stacked-cache refresh)
        self._engine_lock = threading.RLock()
        self.valid_sets: List[Dataset] = []
        self.valid_names: List[str] = []
        self._valid_scores: List[jax.Array] = []
        self.metric_names: List[str] = []
        self.best_score: Dict[str, Dict[str, float]] = {}
        # OOM degradation ladder state (see _maybe_degrade_oom): how many
        # rungs this booster has stepped down, and the resulting overrides.
        # Rides the trainer state so a resumed incarnation keeps the
        # degraded (numerics-relevant) configuration — the bit-identical-
        # restart contract, same as the measured histogram method.
        self._oom_level = 0
        self._oom_block = 0            # rung 1: forced smaller hist block
        self._oom_hm: Optional[str] = None   # rung 2: forced XLA fallback
        self._oom_predict_chunk = 0    # rung 3: forced predict chunk rows
        # deferred in-program sentinel words from the fused path: FIFO of
        # (iteration, device flag scalar), judged as their steps complete
        # (_drain_sentinels, non-blocking) so the fetch never stalls the
        # dispatch pipeline; flushed blockingly at every state-capture
        # point (_flush_sentinel)
        self._sentinel_pending: List[tuple] = []
        if train_set is not None:
            self._init_train(train_set)

    # ------------------------------------------------- host-tree pipeline
    @property
    def host_trees(self) -> List["HostTree"]:
        """Host mirrors of ``self.trees``. In the lazy fast path the mirror
        fetch is ASYNC (copy_to_host_async at dispatch time) and pending
        slots hold None until consumed here — every reader goes through
        this property, so no consumer can observe a placeholder. The point:
        a blocking ``jax.device_get`` per iteration costs a full host
        round-trip (~75-93 ms through a TPU tunnel) and serializes the
        dispatch pipeline; deferring it lets XLA queue iterations
        back-to-back (the same reason the reference keeps its tree on the
        training thread and only serializes at save time)."""
        self._flush_sentinel()
        self._flush_pending()
        return self._host_trees

    def _flush_pending(self, only_ready: bool = False) -> None:
        """Materialize pending host mirrors in FIFO order. With
        ``only_ready`` stop at the first tree whose device computation has
        not finished (non-blocking progress check for the lagged no-split
        stop signal)."""
        while self._pending_host:
            idx, tree_dev = self._pending_host[0]
            if only_ready:
                try:
                    if not tree_dev.num_leaves.is_ready():
                        break
                except AttributeError:   # backend without is_ready()
                    break
            self._pending_host.pop(0)
            t_host = jax.device_get(tree_dev)
            self._host_trees[idx] = self._make_host_tree(t_host)
            # the reference stops when an iteration can add no split
            # (gbdt.cpp:404-435); lagged detection: a full iteration of
            # flushed splitless trees arms the stop flag (group = the
            # iteration this tree belongs to; a whole iteration takes the
            # same lazy/sync path, so a flushed group is complete)
            group = idx // self.num_tree_per_iteration
            if group != self._splitless_group:
                self._splitless_group = group
                self._splitless_in_group = 0
            if int(t_host.num_leaves) <= 1:
                self._splitless_in_group += 1
                if self._splitless_in_group >= self.num_tree_per_iteration:
                    self._lagged_stop = True

    def _lazy_host_ok(self, sentinels: bool = False) -> bool:
        """Whether this iteration can defer the host tree fetch: nothing in
        the iteration itself needs host-side tree data. First iteration
        stays synchronous (boost-from-average bias fold + the TIMETAG
        first-iter sample); leaf-renewal objectives rewrite leaf values on
        host before the score update; linear trees fit on host.
        ``sentinels``: the fused path's in-program numerics sentinels
        already cover the leaf outputs, so check_numerics no longer forces
        the synchronous host-mirror fetch there (the unfused path keeps
        it: its leaf check reads the host mirror in _finalize_tree)."""
        return (self._supports_lazy_host
                and self.iter >= 1
                and not self.config.linear_tree
                # check_numerics inspects each tree's leaf outputs in
                # _finalize_tree, which the lazy path skips — unless the
                # in-program sentinels are doing that job
                and not (self.config.check_numerics and not sentinels)
                and not (self.objective is not None
                         and self.objective.need_renew_tree_output))

    _supports_lazy_host = True   # DART/RF override: they touch host trees
    _rows_streamed_dev = 0.0     # overwritten per-train; float for loaded
                                 # boosters that never trained here
    _coll_bytes_dev = 0.0        # ditto (collective-volume telemetry)
    _fault_plan = None           # set per-train (utils/faults injection)
    _flight = None               # per-train flight recorder (telemetry.py);
                                 # None for loaded boosters / when disabled
    _mem_telemetry = True        # per-iteration memory sampling gate
                                 # (telemetry_memory param)
    _bag_stale = False           # fused iterations draw bagging in-program;
                                 # the host mask re-derives on next use
    _serve_mode = False          # ServeFrontend registration flips it on:
                                 # engines built for this booster keep
                                 # donated per-bucket serve buffers

    def enable_serve_mode(self, on: bool = True) -> None:
        """Serving mode for this booster's inference engines: steady-state
        predicts re-use donated per-bucket device buffers (bin matrix +
        carry) instead of allocating per call — see
        predict_engine._serve_chunk. Applied to already-cached engines
        too (the frontend may register a booster that has predicted)."""
        self._serve_mode = bool(on)
        with self._engine_lock:
            for _, eng in self._engine_cache.values():
                eng.serve_mode = self._serve_mode
                if not self._serve_mode:
                    eng.release_serve_slots()

    # ------------------------------------------------------------ setup
    def _init_train(self, train_set: Dataset) -> None:
        from .. import distributed
        from ..utils import faults
        train_set.construct()
        cfg = self.config
        self._fault_plan = faults.plan_from(cfg)
        # a fresh training run starts with a clean process-level
        # degradation log: this booster's health snapshots / checkpoint
        # manifests must not inherit an earlier booster's OOM events
        distributed.reset_degradations()
        # per-iteration flight recorder (telemetry.py): a fresh ring per
        # training run, fed from host-side values only in train_one_iter
        # (the resolved-context header fills lazily at the first record,
        # after autotune has settled the real histogram method)
        from .. import telemetry
        self._flight = telemetry.configure(cfg)
        # per-iteration memory telemetry (profiling.sample_memory rides
        # the flight record): device HBM in-use/peak + host RSS, each
        # field null on backends without memory_stats (the None-tolerance
        # contract) — one cached-device call + one /proc read, never a
        # dispatch
        self._mem_telemetry = bool(getattr(cfg, "telemetry_memory", True))
        # persistent XLA compile cache (compile_cache_dir): pay each
        # program compile once per shape EVER, not once per process
        from .. import compile_cache
        compile_cache.configure(cfg)
        # pre-partitioned mode (distributed.load_partitioned): bins are a
        # global row-sharded array; labels/weights/scores/gradients stay
        # PROCESS-LOCAL (the reference's per-machine score partition,
        # score_updater.hpp) and only the tree + histograms cross hosts
        self._pre_part = bool(getattr(train_set, "is_pre_partitioned",
                                      False))
        if self._pre_part:
            if cfg.tree_learner not in ("data", "voting"):
                log.fatal("pre-partitioned Datasets shard rows: set "
                          "tree_learner=data or voting")
            if cfg.linear_tree:
                log.fatal("linear_tree is not supported with "
                          "pre-partitioned Datasets (raw features are not "
                          "retained)")
        self._setup_learner_features(train_set)
        if cfg.linear_tree and self.name in ("dart", "rf"):
            log.fatal(f"linear_tree is not supported with boosting={self.name}")
        if cfg.linear_tree and train_set.raw_data_np is None:
            log.fatal("linear_tree requires the Dataset's raw data: construct "
                      "the Dataset with linear_tree in its params (a Dataset "
                      "constructed without it did not retain raw features)")
        if self.objective is None:
            self.objective = create_objective(cfg)
        label = train_set.get_label()
        weight = train_set.get_weight()
        if self.objective is not None:
            self.objective.init(label, weight, train_set.get_group())
            self.num_tree_per_iteration = self.objective.num_model_per_iteration
            if cfg.linear_tree and self.objective.need_renew_tree_output:
                log.fatal(f"objective {cfg.objective} is not supported with "
                          f"linear_tree")
        else:
            self.num_tree_per_iteration = max(cfg.num_class, 1)
        # scores cover the PROCESS-LOCAL rows in pre-partitioned mode
        n = (train_set.num_local_data if self._pre_part
             else train_set.num_data)
        self._n_score_rows = n
        k = self.num_tree_per_iteration
        self._score_shape = (n, k) if k > 1 else (n,)
        # boost_from_average init scores (gbdt.cpp:333-367)
        self.init_scores = [0.0] * k
        if self.objective is not None and cfg.boost_from_average:
            for c in range(k):
                self.init_scores[c] = float(self.objective.boost_from_score(c))
            if self._pre_part and jax.process_count() > 1:
                # mean of the per-machine local init scores, the
                # reference's GlobalSyncUpByMean (gbdt.cpp:338-341
                # ObtainAutomaticInitialScore), bit-exact in f64
                from ..distributed import allgather_f64
                all_scores = allgather_f64(np.asarray(self.init_scores))
                self.init_scores = [float(v)
                                    for v in all_scores.mean(axis=0)]
        if (self._pre_part and self.objective is not None
                and self.objective.need_renew_tree_output):
            log.warning("pre-partitioned training: L1-style leaf "
                        "renewal uses each process's local partition "
                        "(the reference syncs only mean-based renewals)")
        init = train_set.init_score
        # the auto init score is folded as a bias into the first tree of each
        # class (gbdt.cpp:414-416 AddBias) UNLESS a user init score is set
        # (gbdt.cpp:348 has_init_score check)
        self._fold_init_bias = (init is None and cfg.boost_from_average
                                and self.objective is not None)
        if init is not None:
            base = np.asarray(init, dtype=np.float32).reshape(self._score_shape)
        else:
            base = np.broadcast_to(
                np.asarray(self.init_scores, dtype=np.float32),
                (n, k)).reshape(self._score_shape) if k > 1 else \
                np.full((n,), self.init_scores[0], dtype=np.float32)
        self.train_score = jnp.asarray(np.ascontiguousarray(base))
        self.shrinkage_rate = cfg.learning_rate
        self.split_params = SplitParams.from_config(cfg)
        # metric setup: one instance per (metric, dataset), created lazily
        self.metric_names = [nm for nm in (cfg.metric or
                                           default_metric_for_objective(cfg.objective))]
        self._metric_cache: Dict[Tuple[str, int], Metric] = {}
        # feature-fraction rng (seed per config.h:307); bagging/GOSS draws
        # come from the device PRNG keyed on bagging_seed
        self._feat_rng = np.random.RandomState(cfg.feature_fraction_seed)
        self._bag_mask = jnp.ones((n,), dtype=jnp.float32)
        self._bag_sub = None
        # compaction / collective telemetry: rows read by histogram passes
        # and histogram-plane collective bytes, accumulated ON DEVICE so
        # the lazy dispatch pipeline never syncs for them (reading the
        # properties below does)
        self._rows_streamed_dev = jnp.float32(0.0)
        self._coll_bytes_dev = jnp.float32(0.0)
        self._need_bagging = (cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0) or \
            (cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0)

    def _setup_learner_features(self, train_set: Dataset) -> None:
        """Static learner-feature flags + arrays for the grower (monotone,
        interaction constraints, CEGB, extra-trees, per-node sampling)."""
        cfg = self.config
        f = train_set.num_used_features()
        used = train_set.used_features
        self._with_monotone = any(int(m) != 0
                                  for m in (cfg.monotone_constraints or []))
        # static used-space indices of monotone-constrained features (the
        # intermediate-mode pair masks are built only for these)
        if self._with_monotone:
            mono_np = np.asarray(train_set.feature_meta.monotone)
            self._mono_features = tuple(int(i)
                                        for i in np.nonzero(mono_np)[0])
        else:
            self._mono_features = ()
        self._mono_mode = "basic"
        if self._with_monotone:
            method = cfg.monotone_constraints_method
            if method in ("intermediate", "advanced"):
                self._mono_mode = method
                # exact output bounds are recomputed from all leaf outputs
                # each phase, which requires strict one-split-per-phase
                # growth (matching the reference's re-search-after-update,
                # monotone_constraints.hpp:565)
                log.warning(
                    f"monotone_constraints_method={self._mono_mode} forces "
                    "strict one-split-per-phase growth: one histogram round "
                    "per split, ~num_leaves/log2(num_leaves) x the batched "
                    "mode's data passes (use 'basic' for speed)")
            elif method not in ("basic",):
                log.warning(f"monotone_constraints_method={method} is not "
                            f"implemented; falling back to basic")
        self._with_interactions = bool(cfg.interaction_constraints)
        self._interaction_groups = None
        if self._with_interactions:
            orig_to_used = {int(j): i for i, j in enumerate(used)}
            groups = np.zeros((len(cfg.interaction_constraints), f), bool)
            for gi, grp in enumerate(cfg.interaction_constraints):
                for j in grp:
                    if int(j) in orig_to_used:
                        groups[gi, orig_to_used[int(j)]] = True
            self._interaction_groups = jnp.asarray(groups)
        # CEGB enable rule (cost_effective_gradient_boosting.hpp:26-33)
        cegb_enabled = (cfg.cegb_tradeoff < 1.0 or cfg.cegb_penalty_split > 0.0
                        or cfg.cegb_penalty_feature_coupled
                        or cfg.cegb_penalty_feature_lazy)
        self._cegb_mode = "off"
        self._cegb_coupled = None
        self._cegb_lazy = None
        # cross-iteration CEGB tracking survives reset_config (the reference
        # Init() keeps its state once init_ is true)
        self._cegb_aux = getattr(self, "_cegb_aux", None)
        if cegb_enabled:
            for name, lst in (("cegb_penalty_feature_coupled",
                               cfg.cegb_penalty_feature_coupled),
                              ("cegb_penalty_feature_lazy",
                               cfg.cegb_penalty_feature_lazy)):
                if lst and len(lst) != train_set.num_total_features:
                    log.fatal(f"{name} should be the same size as feature "
                              f"number ({train_set.num_total_features})")
            self._cegb_mode = "lazy" if cfg.cegb_penalty_feature_lazy else "feat"
            if cfg.cegb_penalty_feature_coupled:
                arr = np.zeros((f,), np.float32)
                for i, j in enumerate(used):
                    if j < len(cfg.cegb_penalty_feature_coupled):
                        arr[i] = cfg.cegb_penalty_feature_coupled[j]
                self._cegb_coupled = jnp.asarray(arr)
            if cfg.cegb_penalty_feature_lazy:
                arr = np.zeros((f,), np.float32)
                for i, j in enumerate(used):
                    if j < len(cfg.cegb_penalty_feature_lazy):
                        arr[i] = cfg.cegb_penalty_feature_lazy[j]
                self._cegb_lazy = jnp.asarray(arr)
        self._use_bynode = cfg.feature_fraction_bynode < 1.0
        self._extra_rng_key = jax.random.PRNGKey(cfg.extra_seed)
        # gpu_use_dp analog: float64 histogram accumulation (the reference
        # CPU's hist_t precision; bin.h:32) — requires jax x64
        self._hist_dp = bool(cfg.gpu_use_dp)
        if cfg.quantized_grad and self._hist_dp:
            # checked on the CONFIG flags, before the x64-availability
            # demotion below — the contradiction is in what was asked for
            raise ValueError(
                "quantized_grad and gpu_use_dp are exclusive: int8 "
                "histograms with stochastic rounding and f64 accumulation "
                "contradict each other — pick one precision model")
        if self._hist_dp and not jax.config.jax_enable_x64:
            log.warning("gpu_use_dp=true needs jax x64 (set JAX_ENABLE_X64=1 "
                        "or jax.config.update('jax_enable_x64', True)); "
                        "falling back to float32 histograms")
            self._hist_dp = False
        self._forced_splits = self._load_forced_splits(train_set)
        self._setup_tree_learner()

    def _load_forced_splits(self, ts: Dataset):
        """Parse forcedsplits_filename JSON into flat preorder arrays for the
        grower's forced phase (reference: serial_tree_learner.cpp:450
        ForceSplits; format {"feature": i, "threshold": v, "left": {...},
        "right": {...}})."""
        fn = self.config.forcedsplits_filename
        if not fn:
            return None
        import json
        from .. import binning
        try:
            with open(fn) as fh:
                data = json.load(fh)
        except OSError:
            log.warning(f"Could not open forced splits file {fn}. "
                        f"Will ignore.")
            return None
        if not data:
            return None
        ts.construct()
        if ts.bundles is not None:
            col_of = {}
            for gi, bd in enumerate(ts.bundles):
                if len(bd.members) == 1:
                    col_of[int(ts.used_features[bd.members[0]])] = gi
        else:
            col_of = {int(j): i for i, j in enumerate(ts.used_features)}
        nodes: List[List[int]] = []

        def rec(node) -> int:
            orig = int(node["feature"])
            col = col_of.get(orig)
            m = ts.mappers[orig] if orig < len(ts.mappers) else None
            if (col is None or m is None
                    or m.bin_type != binning.BIN_TYPE_NUMERICAL):
                log.warning(f"forced split on feature {orig} ignored "
                            f"(unused, bundled or categorical)")
                return -1
            idx = len(nodes)
            nodes.append([col, m.value_to_bin(float(node["threshold"])),
                          -1, -1])
            if node.get("left"):
                nodes[idx][2] = rec(node["left"])
            if node.get("right"):
                nodes[idx][3] = rec(node["right"])
            return idx

        if rec(data) != 0 or not nodes:
            return None
        arr = np.asarray(nodes, np.int32)
        return (jnp.asarray(arr[:, 0]), jnp.asarray(arr[:, 1]),
                jnp.asarray(arr[:, 2]), jnp.asarray(arr[:, 3]))

    def _setup_tree_learner(self) -> None:
        """tree_learner dispatch (reference: TreeLearner factory,
        tree_learner.h:104 + config.h:205). Non-serial learners run the same
        jitted grower under a shard_map over the visible device mesh."""
        cfg = self.config
        mode = cfg.tree_learner
        if mode in ("serial", None, ""):
            self._parallel_grower = None
            return
        from ..parallel.learners import PARALLEL_MODES, ParallelGrower
        if mode not in PARALLEL_MODES:
            log.fatal(f"Unknown tree learner type {mode}")
        unsupported = []
        if getattr(self.train_set, "has_sparse_cols", False):
            # construct() only extracts sparse columns when the params it
            # saw said tree_learner=serial; reaching here means the Booster
            # was configured differently from the Dataset
            unsupported.append("sparse device storage (construct the "
                               "Dataset with enable_sparse=false)")
        if self._cegb_mode != "off":
            unsupported.append("CEGB")
        if self._with_interactions:
            unsupported.append("interaction_constraints")
        if self._use_bynode:
            unsupported.append("feature_fraction_bynode")
        if cfg.linear_tree:
            unsupported.append("linear_tree")
        if mode == "voting" and \
                getattr(self, "_forced_splits", None) is not None:
            # voting keeps histograms local; a forced threshold's sums
            # would come from one shard only
            unsupported.append("forced splits (voting)")
        if unsupported:
            log.fatal(f"tree_learner={mode} does not support: "
                      f"{', '.join(unsupported)}")
        existing = getattr(self, "_parallel_grower", None)
        if existing is not None and existing.mode == mode:
            return  # keep the compiled cache across reset_config
        if len(jax.devices()) == 1:
            log.info(f"tree_learner={mode} with a single device: running the "
                     f"distributed program on a 1-device mesh")
        self._parallel_grower = ParallelGrower(mode)

    def reset_config(self, config: Config) -> None:
        """Apply updated parameters mid-training (reference: GBDT::ResetConfig,
        gbdt.cpp; used by the reset_parameter callback / learning_rates)."""
        self.config = config
        # NOTE: the fused-step cache is keyed on the static grow options
        # (see _fused_step_fn), so a reset that only touches dynamic
        # scalars (learning_rates schedules via reset_parameter — lr and
        # SplitParams are traced arguments) reuses the compiled program
        self.shrinkage_rate = config.learning_rate
        self.split_params = SplitParams.from_config(config)
        if self.train_set is not None:
            # _setup_learner_features ends by re-running _setup_tree_learner,
            # so a config change enabling an option the active parallel
            # learner rejects fails loudly here
            self._setup_learner_features(self.train_set)
        self._need_bagging = (config.bagging_freq > 0 and config.bagging_fraction < 1.0) or \
            (config.pos_bagging_fraction < 1.0 or config.neg_bagging_fraction < 1.0)
        self._bag_frac = None   # fractions may have changed
        if not self._need_bagging:
            # bagging switched off mid-training: drop the frozen subset/mask
            self._bag_sub = None
            self._bag_mask = jnp.ones((self._n_score_rows,),
                                      dtype=jnp.float32) \
                if self.train_set is not None else self._bag_mask

    def add_valid(self, valid_set: Dataset, name: str) -> None:
        valid_set.construct()
        self.valid_sets.append(valid_set)
        self.valid_names.append(name)
        n = valid_set.num_data
        k = self.num_tree_per_iteration
        shape = (n, k) if k > 1 else (n,)
        init = valid_set.init_score
        if init is not None:
            base = np.asarray(init, dtype=np.float32).reshape(shape)
        else:
            base = np.broadcast_to(np.asarray(self.init_scores, dtype=np.float32),
                                   (n, k)).reshape(shape) if k > 1 else \
                np.full((n,), self.init_scores[0], dtype=np.float32)
        self._valid_scores.append(jnp.asarray(np.ascontiguousarray(base)))

    # ---------------------------------------------------------- sampling
    def _bagging_mode(self) -> str:
        """STATIC bagging flavor for the current config: "off" | "mask" |
        "subset". The subset rule mirrors the reference's compact-copy
        heuristic (gbdt.cpp:810-818): small enough fraction that a compact
        row copy beats masked full-N histogram passes; serial learner and
        plain fraction only. The single definition the host refresh below
        and the fused in-program draw share."""
        cfg = self.config
        if not self._need_bagging or cfg.bagging_freq <= 0:
            return "off"
        use_subset = (cfg.bagging_fraction <= 0.5
                      and cfg.pos_bagging_fraction >= 1.0
                      and cfg.neg_bagging_fraction >= 1.0
                      and self._parallel_grower is None
                      and self._cegb_mode == "off"
                      and not cfg.linear_tree
                      # sparse streams index ORIGINAL row ids; the subset
                      # copy compacts rows, so it takes the mask path
                      and not getattr(self.train_set, "has_sparse_cols",
                                      False))
        return "subset" if use_subset else "mask"

    def _subset_rows(self) -> int:
        """Static row count of the bagging subset copy."""
        return max(1, int(round(self._n_score_rows
                                * self.config.bagging_fraction)))

    def _bagging_frac(self):
        """Per-row (pos/neg) or scalar keep-probability for the mask mode
        (config.h:268-280), built lazily and cached until reset_config."""
        cfg = self.config
        if getattr(self, "_bag_frac", None) is None:
            if cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0:
                pos = self.objective.label_np > 0 \
                    if hasattr(self.objective, "label_np") \
                    else self.train_set.get_label() > 0
                self._bag_frac = jnp.asarray(np.where(
                    pos, cfg.pos_bagging_fraction,
                    cfg.neg_bagging_fraction).astype(np.float32))
            else:
                self._bag_frac = jnp.float32(cfg.bagging_fraction)
        return self._bag_frac

    def _update_bagging(self) -> None:
        """Bagging mask refresh (reference: gbdt.cpp:228-262 Bagging;
        pos/neg bagging per config.h:268-280). The mask comes from the
        device PRNG — no per-period host uniform draw + upload. The draw
        is keyed on the PERIOD-START iteration, so it is deterministic in
        the iteration alone: a mid-period resume, or an unfused iteration
        following fused ones (which draw the same key in-program and leave
        the host mask stale), re-derives the exact same mask."""
        cfg = self.config
        mode = self._bagging_mode()
        if mode == "off":
            return
        if self.iter % cfg.bagging_freq != 0 and not self._bag_stale:
            return
        period_start = (self.iter // cfg.bagging_freq) * cfg.bagging_freq
        key = jax.random.fold_in(jax.random.PRNGKey(cfg.bagging_seed),
                                 period_start)
        self._bag_stale = False
        if mode == "subset":
            self._bag_mask, sub_idx, sub_bins, sub_binsT = _bagging_subset(
                key, self.train_set.bins, self._subset_rows())
            self._bag_sub = (sub_idx, sub_bins, sub_binsT)
            return
        self._bag_sub = None
        if self._pre_part:
            # per-global-row draw: partition-invariant, so an elastic
            # resume at a different world size re-derives the same sample
            self._bag_mask = _bagging_mask_rows(
                key, self._bagging_frac(),
                jnp.int32(getattr(self.train_set, "local_row_start", 0) or 0),
                self._n_score_rows)
        else:
            self._bag_mask = _bagging_mask(key, self._bagging_frac(),
                                           self._n_score_rows)

    def _feature_mask(self) -> jax.Array:
        """Per-tree column sampling (reference: col_sampler.hpp:20-50
        feature_fraction by-tree)."""
        f = self.train_set.num_used_features()
        frac = self.config.feature_fraction
        if frac >= 1.0:
            return jnp.ones((f,), dtype=jnp.float32)
        k = max(1, int(round(f * frac)))
        chosen = self._feat_rng.choice(f, size=k, replace=False)
        mask = np.zeros((f,), dtype=np.float32)
        mask[chosen] = 1.0
        return jnp.asarray(mask)

    def _feature_mask_np(self) -> Optional[np.ndarray]:
        """Host-side per-class feature-fraction masks for the fused step
        ([K, F] float32), drawn from the SAME stateful rng in the same
        per-tree order as the unfused path's _feature_mask calls (bit-
        parity). None when column sampling is off — the fused step then
        builds a constant all-ones mask in-program, so a steady-state
        iteration uploads nothing."""
        f = self.train_set.num_used_features()
        frac = self.config.feature_fraction
        if frac >= 1.0:
            return None
        k = self.num_tree_per_iteration
        kk = max(1, int(round(f * frac)))
        masks = np.zeros((k, f), dtype=np.float32)
        for c in range(k):
            masks[c, self._feat_rng.choice(f, size=kk, replace=False)] = 1.0
        return masks

    # ------------------------------------------------------------ train
    def _gradients(self) -> Tuple[jax.Array, jax.Array]:
        return self.objective.get_grad_hess(self.train_score)

    def _fused_ok(self, grad_external) -> bool:
        """Whether this iteration can run gradients -> growth -> score
        update as ONE jitted program (see _fused_step_fn).

        The gate is wide: multiclass (all class trees grow inside the one
        program via a lax.scan over the class axis), the data/feature/
        voting parallel learners (the same shard_map'd grower the unfused
        path uses, embedded in the fused program), the bagging mask AND
        subset copy (drawn in-program from the period-start key), CEGB
        (its cross-iteration aux rides through as device-resident loop
        state), interaction constraints, per-node feature sampling and
        forced splits (constant device tables closed over).

        What remains excluded genuinely interleaves HOST work between the
        phases: externally supplied gradients (fobj), objectives with
        host-side leaf renewal, linear-leaf fitting (host lstsq per leaf),
        the NaN-GRADIENT injection fault (it materializes gradients on
        host by design; the in-program nan_hist fault does not unfuse),
        and multi-controller / pre-partitioned runs (per-process array
        globalization between phases). ``check_numerics`` is NOT excluded
        anymore: the fused step computes an in-program sentinel flag word
        (packed NaN/Inf bits for gradients, hessians, the histogram
        plane, leaf outputs and the score delta) that the host checks
        from the iteration's own results — the guard works WITH the fused
        path instead of gating it off (PR 3's limitation, lifted).
        Subclasses whose only deviation is an in-program-expressible
        sampling scheme (GOSS) opt in via ``_fused_sampling``; DART and
        RF stay host-interleaved."""
        cfg = self.config
        return ((type(self) is GBDT
                 or getattr(self, "_fused_sampling", False))
                and cfg.fused_iteration
                and grad_external is None
                # NaN-gradient injection needs the gradients materialized
                # outside the fused program (check_numerics does not: see
                # the sentinel note above)
                and (self._fault_plan is None
                     or not self._fault_plan.wants_nan_grad)
                and self.objective is not None
                and not self.objective.need_renew_tree_output
                and getattr(self.objective, "jit_safe_gradients", True)
                and not cfg.linear_tree
                and jax.process_count() == 1
                and not getattr(self, "_pre_part", False)
                # 0-feature datasets take _grow_one's constant-tree path
                and (self.train_set.bins.shape[1] > 0
                     or getattr(self.train_set, "has_sparse_cols", False)))

    def _serial_grow_statics(self, hm: str) -> dict:
        """STATIC grow_tree options for the serial learner — the single
        definition the unfused call site and the fused step share, so a
        new option cannot silently diverge between the two paths (the
        suite asserts their bit-parity)."""
        cfg = self.config
        ts = self.train_set
        has_sp = getattr(ts, "has_sparse_cols", False)
        fb = self._feature_block(hm)
        sf = self._split_fusion_on(hm, fb)
        tile, blk = self._hist_tuning(hm, epilogue=sf)
        return dict(
            max_leaves=cfg.num_leaves, num_bins=ts.max_num_bins,
            max_depth=cfg.max_depth, hist_method=hm,
            tile_leaves=tile, hist_block=self._eff_hist_block(blk),
            hist_interpret=self._hist_interpret(),
            numerics_sentinels=cfg.check_numerics,
            feature_block=fb,
            split_fusion=sf,
            exact=cfg.tree_growth_mode == "exact",
            with_categorical=ts.has_categorical,
            with_monotone=self._with_monotone,
            mono_mode=self._mono_mode,
            mono_features=self._mono_features,
            with_interactions=self._with_interactions,
            cegb_mode=self._cegb_mode,
            use_bynode=self._use_bynode,
            extra_trees=cfg.extra_trees,
            hist_dp=self._hist_dp,
            hist_subtraction=cfg.hist_subtraction and fb == 0,
            sp_cols=tuple(int(c) for c in ts.sp_cols) if has_sp else (),
            compaction_ladder=() if fb else self._compaction_ladder())

    def _parallel_grow_statics(self, hm: str) -> dict:
        """STATIC grow options for the configured parallel learner — like
        _serial_grow_statics, the single definition the unfused _grow_one
        call site and the fused step share (the two also share the
        compiled shard_map program through ParallelGrower.get_shard_fn)."""
        cfg = self.config
        ts = self.train_set
        return dict(
            max_leaves=cfg.num_leaves, num_bins=ts.max_num_bins,
            max_depth=cfg.max_depth, hist_method=hm,
            tile_leaves=cfg.tile_leaves,
            hist_block=self._eff_hist_block(cfg.hist_block),
            hist_interpret=self._hist_interpret(),
            numerics_sentinels=cfg.check_numerics,
            exact=cfg.tree_growth_mode == "exact",
            with_categorical=ts.has_categorical,
            with_monotone=self._with_monotone,
            mono_mode=self._mono_mode,
            mono_features=self._mono_features,
            extra_trees=cfg.extra_trees,
            hist_subtraction=cfg.hist_subtraction,
            vote_top_k=cfg.top_k, hist_dp=self._hist_dp)

    def _compaction_ladder(self) -> tuple:
        """Static row-buffer sizes for the grower's leaf-partitioned row
        compaction (see grow_tree's compaction_ladder docstring — the
        DataPartition analog). Rungs are ``hist_compaction_ladder``
        fractions of the histogram row count (the bagging-subset copy's K
        rows when that path is active), rounded up to a 64-row boundary;
        rungs that don't undercut the full count are dropped — the full-N
        pass is always the fallback."""
        cfg = self.config
        ts = self.train_set
        if not cfg.hist_compaction or ts is None:
            return ()
        base = (self._subset_rows() if self._bagging_mode() == "subset"
                else (ts.num_local_data if getattr(self, "_pre_part", False)
                      else ts.num_data))
        rungs = set()
        for fr in (cfg.hist_compaction_ladder or []):
            m = -(-max(int(round(base * float(fr))), 1) // 64) * 64
            if 0 < m < base:
                rungs.add(m)
        return tuple(sorted(rungs))

    def _fused_cegb_state(self) -> Optional[GrowAux]:
        """CEGB's cross-iteration feature-used tracking as an explicit
        fused-step operand (cost_effective_gradient_boosting.hpp Init:
        !init_ reuse). A zero aux is materialized once at the first
        iteration so the step's operand structure stays trace-stable."""
        if self._cegb_mode == "off":
            return None
        if self._cegb_aux is None:
            ts = self.train_set
            f = ts.num_used_features()
            n = self._n_score_rows
            lazy = self._cegb_mode == "lazy"
            self._cegb_aux = GrowAux(
                used_split=jnp.zeros((f,), bool),
                row_used=jnp.zeros((n, f) if lazy else (1, 1), bool),
                rows_streamed=jnp.float32(0.0),
                coll_bytes=jnp.float32(0.0),
                sentinel=jnp.float32(0.0))
        return self._cegb_aux

    def _fused_parallel_bindings(self, hm: str):
        """Padded dataset-constant arrays for the fused parallel step,
        through the SAME ParallelGrower padding/extras helpers the
        unfused ``__call__`` uses (single source of truth) — but built
        ONCE and cached per (learner, binsT-needed) instead of per call;
        the per-iteration grad/hess/mask pads move inside the jitted
        program. The sub-cache is keyed separately from the fused step
        cache so a reset_parameter sweep over step statics never
        duplicates the padded O(N*F) dataset copies."""
        pg = self._parallel_grower
        ts = self.train_set
        use_binsT = hm.startswith(("onehot", "pallas"))
        hit = self._fused_bind_cache.get(use_binsT)
        # identity-checked (not id-keyed): a reset_config can replace the
        # learner or the forced-split tables; the old objects stay alive
        # inside the stale entry, so an `is` match is exact
        if (hit is not None and hit[0] is pg
                and hit[1] is self._forced_splits):
            return hit[2]
        (bins, binsT, meta, missing_bin, bundle_meta,
         n_pad, f_pad) = pg.pad_replicated_inputs(
            ts.bins, ts.bins_T if use_binsT else None, ts.feature_meta,
            ts.missing_bin, ts.bundle_meta)
        extras, extras_spec = pg.build_extras(binsT, bundle_meta,
                                              self._forced_splits)
        pb = dict(bins=bins, extras=extras, extras_spec=extras_spec,
                  meta=meta, missing_bin=missing_bin, n=ts.bins.shape[0],
                  n_pad=n_pad, f_pad=f_pad)
        self._fused_bind_cache[use_binsT] = (pg, self._forced_splits, pb)
        return pb

    def _fused_step_fn(self, hm: str, fmask_on: bool, k_rounds: int = 1):
        """One jitted program per boosting iteration — or per K-iteration
        BLOCK (``k_rounds`` > 1, the ``boost_rounds_per_dispatch`` scan):
        objective gradients -> sampling draw -> per-class tree growth ->
        shrinkage -> score deltas, fused so the host dispatches the whole
        grow phase ONCE (three-plus dispatches otherwise, and per-class
        multiples for multiclass — each a transport round trip through a
        TPU tunnel) and XLA fuses the elementwise gradient math into the
        grower's first histogram pass instead of materializing grad/hess
        through HBM. The reference's TrainOneIter phases
        (gbdt.cpp:369-452) collapse into one program:

        - multiclass grows all ``num_tree_per_iteration`` class trees via
          a ``lax.scan`` over the class axis — the grower (and its
          histogram workspace) is compiled ONCE and reused per class,
          mirroring the reference's single logical TrainOneIter;
        - the parallel learners run the SAME shard_map'd grower the
          unfused path uses (ParallelGrower.get_shard_fn), embedded in
          the fused program, so distributed iterations also collapse to
          one dispatch;
        - bagging (mask or subset copy) is drawn in-program from the
          period-start key, and GOSS's one-side sampling weights from the
          per-iteration key — bit-identical to the host refresh draws
          and never interleaved as separate dispatches;
        - CEGB's cross-iteration aux rides through as device-resident
          loop state (operand in, operand out).

        EVERY dataset-constant array — the bin matrices, the objective's
        label/weight (and derived label_sign/onehot/... tables), feature
        metadata, bundle/forced-split/interaction/CEGB tables — enters
        the program as an OPERAND through the cached ``bind`` dict, never
        as a closure constant: closure constants are embedded in the HLO
        and their label-derived subexpressions become dataset-sized
        constant folds at COMPILE time (BENCH_r04 measured >6 s alarms on
        single instructions at 10.5M rows). The hoist test pins the
        traced jaxpr's constant footprint near zero.

        Per-iteration mode (``k_rounds`` == 1): the score update is the
        SECOND (and last) dispatch — ``_apply_score_delta``, a donated
        in-place add kept out of this program so the backend cannot
        FMA-contract it against the leaf-value shrinkage (see its
        docstring; bit-parity).

        Block mode (``k_rounds`` K > 1): a ``lax.scan`` over the K
        iterations carries the score cache IN-PROGRAM (the donated score
        operand is the carry seed), with each step re-keyed by the
        scanned absolute iteration index — the same fold_in(…, it)
        streams the per-iteration mode draws, so the block is
        bit-identical to K separate fused iterations. The carry update
        keeps the exact two-rounding sequence of the split programs:
        trees are shrunk FIRST (round(leaf_value*lr), the [L]-sized
        multiply), the per-row delta is a GATHER of the pre-shrunk leaf
        values, and the gathered delta passes the ``_fma_guard``
        rounding fence before the add — the backend contracts a multiply
        feeding an add even across ``optimization_barrier`` AND through
        the gather (both re-verified; the PR 3 lesson), so only the
        fence's integer round-trip actually pins the rounding. One
        dispatch grows K*C trees.

        Trees are returned SHRUNK either way. Cached by the STATIC grow
        options (+ objective/constant identities + k_rounds), so
        dynamic-parameter resets (learning_rates schedules) never
        retrace. Returns ``(step, bind)`` where ``bind`` holds the
        dataset-constant operands the caller passes each call."""
        ts = self.train_set
        obj = self.objective
        cfg = self.config
        k = self.num_tree_per_iteration
        kk = max(1, int(k_rounds))
        pg = self._parallel_grower
        bag_mode = self._bagging_mode()
        sub_k = self._subset_rows() if bag_mode == "subset" else 0
        frac_kind = "arr" if (bag_mode == "mask"
                              and (cfg.pos_bagging_fraction < 1.0
                                   or cfg.neg_bagging_fraction < 1.0)) \
            else bag_mode
        grow_kw = self._parallel_grow_statics(hm) if pg is not None \
            else self._serial_grow_statics(hm)
        # in-program numerics sentinels (check_numerics on the fused path)
        # and the traced NaN-injection fault are STATICS of the program:
        # the disarmed trace is byte-identical to a guard-free one
        from ..utils import faults as faults_mod
        sentinels = bool(cfg.check_numerics)
        nan_hist_it = faults_mod.nan_hist_iter(self._fault_plan)
        n = self._n_score_rows
        # GOSS one-side sampling as in-program statics (goss.hpp:105-150):
        # the subclass opts in via _fused_sampling; counts and the
        # 1/learning_rate warm-up gate are static per (n, rates, lr)
        goss_on = bool(getattr(self, "_fused_sampling", False))
        goss_top = max(1, int(n * cfg.top_rate)) if goss_on else 0
        goss_other = max(1, int(n * cfg.other_rate)) if goss_on else 0
        goss_warm = int(1.0 / cfg.learning_rate) if goss_on else 0
        key = (id(obj), k, kk, bag_mode, sub_k, frac_kind, fmask_on,
               pg.mode if pg is not None else "serial",
               sentinels, nan_hist_it,
               goss_on, goss_top, goss_other, goss_warm,
               cfg.bagging_freq, cfg.bagging_seed, cfg.extra_seed,
               # the by-node fraction is closed over below (a constant of
               # the program): key it so a reset_parameter change
               # retraces instead of silently keeping the old fraction
               cfg.feature_fraction_bynode if self._use_bynode else None,
               id(self._interaction_groups), id(self._cegb_coupled),
               id(self._cegb_lazy), id(self._forced_splits),
               ) + tuple(grow_kw[k2] for k2 in sorted(grow_kw))
        hit = self._fused_cache.get(key)
        if hit is not None:
            return hit
        from .tree import leaf_values_of_rows
        f_used = ts.num_used_features()
        freq = cfg.bagging_freq
        extra_key = self._extra_rng_key
        bag_key0 = jax.random.PRNGKey(cfg.bagging_seed)
        has_sp = getattr(ts, "has_sparse_cols", False)
        cegb_on = self._cegb_mode != "off"
        bynode_frac = (jnp.float32(cfg.feature_fraction_bynode)
                       if self._use_bynode else None)
        # dataset-constant OPERANDS (see docstring): one cached dict the
        # caller passes per dispatch — the host-side cost is a pointer
        # walk, the compile-time win is that nothing here can be folded
        if pg is not None:
            pb = self._fused_parallel_bindings(hm)
            shard = pg.get_shard_fn(pb["extras_spec"],
                                    tuple(sorted(grow_kw.items())))
            bind = dict(bins=pb["bins"], binsT=None, sp_rows=None,
                        sp_bins=None, sp_default=None, extras=pb["extras"],
                        meta=pb["meta"], missing_bin=pb["missing_bin"],
                        bundle_meta=None, forced=None, igroups=None,
                        cegb_coupled=None, cegb_lazy=None,
                        obj_consts=obj.device_consts())
        else:
            pb = shard = None
            bind = dict(bins=ts.bins,
                        binsT=ts.bins_T if self._use_binsT(hm) else None,
                        sp_rows=ts.sp_rows if has_sp else None,
                        sp_bins=ts.sp_bins if has_sp else None,
                        sp_default=ts.sp_default if has_sp else None,
                        extras=None,
                        meta=ts.feature_meta, missing_bin=ts.missing_bin,
                        bundle_meta=ts.bundle_meta,
                        forced=self._forced_splits,
                        igroups=self._interaction_groups,
                        cegb_coupled=self._cegb_coupled,
                        cegb_lazy=self._cegb_lazy,
                        obj_consts=obj.device_consts())

        def one_iter(score, it, lr, fmask_it, cegb_state, rows_acc,
                     coll_acc, sparams, bag_frac, b):
            """One boosting iteration's traced body — shared verbatim by
            the per-iteration program and the K-block scan (re-keyed by
            the traced absolute iteration index ``it``)."""
            with obj.bound(b["obj_consts"]):
                g, h = obj.get_grad_hess(score)
            if nan_hist_it >= 0:
                # traced NaN injection (LGBM_TPU_FAULT_NAN_HIST_AT_ITER):
                # poison one gradient value INSIDE the program at the
                # armed iteration — the failure shape the in-program
                # sentinels exist for (a host-side injection would unfuse)
                gf = g.reshape(-1).at[0].set(jnp.nan).reshape(g.shape)
                g = jnp.where(jnp.equal(it, nan_hist_it), gf, g)
            # ---- bagging, derived from the period-start key: the exact
            # draw _update_bagging performs on the host path
            mask = jnp.ones((n,), jnp.float32)
            sub = None
            if bag_mode != "off":
                bkey = jax.random.fold_in(bag_key0, (it // freq) * freq)
                if bag_mode == "mask":
                    u = jax.random.uniform(bkey, (n,))
                    mask = (u < bag_frac).astype(jnp.float32)
                else:
                    r = jax.random.bits(bkey, (n,), jnp.uint32)
                    sub_idx = jnp.argsort(r)[:sub_k].astype(jnp.int32)
                    sub_bins = jnp.take(b["bins"], sub_idx, axis=0)
                    sub = (sub_idx, sub_bins, sub_bins.T)
            if goss_on:
                # GOSS weights from the per-iteration key, exactly the
                # host path's _sample_weights -> goss_weights sequence;
                # the warm-up arm (< 1/learning_rate iterations) skips
                # the draw like the host's early return
                from .goss import goss_weights_impl

                def _sampled(args):
                    g0, h0 = args
                    sc = jnp.sum(jnp.abs(g0 * h0), axis=1) if k > 1 \
                        else jnp.abs(g0 * h0)
                    w = goss_weights_impl(
                        sc, jax.random.fold_in(bag_key0, it),
                        goss_top, goss_other)
                    wk = w[:, None] if k > 1 else w
                    return g0 * wk, h0 * wk, (w > 0).astype(jnp.float32)

                def _warm(args):
                    g0, h0 = args
                    return g0, h0, mask

                g, h, mask = jax.lax.cond(it >= goss_warm, _sampled,
                                          _warm, (g, h))

            def grow_c(gc, hc, fmask_c, key_c, cegb_aux):
                if pg is None:
                    tree, leaf_id, aux = grow_tree(
                        b["bins"], gc, hc, mask, b["meta"], sparams,
                        fmask_c, b["missing_bin"], binsT=b["binsT"],
                        rng_key=key_c, bundle_meta=b["bundle_meta"],
                        forced_splits=b["forced"],
                        sub_idx=sub[0] if sub else None,
                        sub_bins=sub[1] if sub else None,
                        sub_binsT=sub[2] if sub else None,
                        interaction_groups=b["igroups"],
                        cegb_coupled=b["cegb_coupled"],
                        cegb_lazy_penalty=b["cegb_lazy"],
                        cegb_state=cegb_aux,
                        bynode_fraction=bynode_frac,
                        sp_rows=b["sp_rows"], sp_bins=b["sp_bins"],
                        sp_default=b["sp_default"], **grow_kw)
                else:
                    gp = jnp.pad(gc, (0, pb["n_pad"]))
                    hp = jnp.pad(hc, (0, pb["n_pad"]))
                    mp = jnp.pad(mask, (0, pb["n_pad"]))
                    fp = jnp.pad(fmask_c, (0, pb["f_pad"]))
                    tree, leaf_id, aux = shard(
                        b["bins"], gp, hp, mp, b["meta"], sparams, fp,
                        b["missing_bin"], b["extras"], key_c)
                    leaf_id = leaf_id[:n]
                # shrink FIRST, then GATHER the pre-shrunk leaf values:
                # identical bits to gather-then-multiply (gather commutes
                # with the elementwise mul), but the block mode's in-carry
                # score add then sees no multiply to FMA-contract
                tree = _shrink_tree(tree, lr)
                delta = leaf_values_of_rows(tree.leaf_value, leaf_id)
                return tree, delta, aux

            fm = fmask_it if fmask_on else jnp.ones((k, f_used),
                                                    jnp.float32)
            if k == 1:
                key0 = jax.random.fold_in(extra_key, it * k)
                tree, delta, aux = grow_c(g, h, fm[0], key0, cegb_state)
                trees_st = tree
                rows, coll = aux.rows_streamed, aux.coll_bytes
                hist_sent = aux.sentinel
                cegb_out = aux if cegb_on else None
            else:
                keys = jax.vmap(
                    lambda c: jax.random.fold_in(extra_key, it * k + c))(
                        jnp.arange(k, dtype=jnp.int32))

                def body(carry, xs):
                    gc, hc, fmask_c, key_c = xs
                    tree, delta_c, aux = grow_c(gc, hc, fmask_c, key_c,
                                                carry if cegb_on else
                                                cegb_state)
                    return (aux if cegb_on else carry,
                            (tree, delta_c, aux.rows_streamed,
                             aux.coll_bytes, aux.sentinel))

                carry0 = cegb_state if cegb_on else jnp.int32(0)
                carry, (trees_st, delta, rows_st, coll_st, sent_st) = \
                    jax.lax.scan(body, carry0, (g.T, h.T, fm, keys))
                rows, coll = jnp.sum(rows_st), jnp.sum(coll_st)
                hist_sent = jnp.sum(sent_st)
                cegb_out = carry if cegb_on else None
            if sentinels:
                # the per-iteration sentinel flag word: packed NaN/Inf
                # bits per SOURCE (see _SENTINEL_SOURCES), computed as
                # tiny reductions fused into the step's epilogue and
                # fetched by the host with this iteration's results — no
                # extra dispatch, no host round trip of the arrays
                bad = lambda x: jnp.any(~jnp.isfinite(x))  # noqa: E731
                leaf_bad = bad(trees_st.leaf_value)
                u32 = lambda bv: bv.astype(jnp.uint32)     # noqa: E731
                flags = (u32(bad(g)) | (u32(bad(h)) << 1)
                         | (u32(hist_sent > 0) << 2)
                         | (u32(leaf_bad) << 3)
                         | (u32(bad(delta)) << 4))
            else:
                flags = jnp.uint32(0)
            return (trees_st, delta, rows_acc + rows, coll_acc + coll,
                    cegb_out, flags)

        def _unstack_classes(trees_st):
            if k == 1:
                return (trees_st,)
            return tuple(jax.tree.map(lambda x: x[c], trees_st)
                         for c in range(k))

        if kk == 1:
            def _fused_step(score, it, lr, fmask, sparams, bag_frac,
                            cegb_state, rows_acc, coll_acc, b):
                trees_st, delta, rows, coll, cegb_out, flags = one_iter(
                    score, it, lr, fmask, cegb_state, rows_acc, coll_acc,
                    sparams, bag_frac, b)
                return (_unstack_classes(trees_st), delta, rows, coll,
                        cegb_out, flags)

            step = jax.jit(_fused_step)
        else:
            def _fused_block(score, it0, lr, fmask, sparams, bag_frac,
                             cegb_state, rows_acc, coll_acc, b):
                """K boosting iterations per dispatch: scan the fused
                step over the absolute iteration indices, score cache in
                the carry (donated operand in, aliased result out). See
                the outer docstring and _fma_guard for the FMA-safety
                argument."""
                cegb0 = cegb_state if cegb_on else jnp.int32(0)
                # runtime-zero XOR salt (it0 is never negative): the
                # compiler cannot fold it, so the _fma_guard fence around
                # the carry add survives every optimization pass
                salt = (it0 < jnp.int32(-1)).astype(jnp.uint32)

                def body(carry, xs):
                    score_c, cegb_c, rows_c, coll_c = carry
                    if fmask_on:
                        j, fm_it = xs
                    else:
                        j, fm_it = xs, None
                    trees_st, delta, rows_c, coll_c, cegb_out, flags = \
                        one_iter(score_c, it0 + j, lr, fm_it,
                                 cegb_c if cegb_on else cegb_state,
                                 rows_c, coll_c, sparams, bag_frac, b)
                    # the in-carry analog of _apply_score_delta: delta is
                    # a gather of PRE-SHRUNK leaf values, passed through
                    # the _fma_guard rounding fence — the backend cannot
                    # contract the shrinkage multiply into this add, so
                    # the two-rounding sequence (and bit-parity with the
                    # split per-iteration programs) is preserved
                    d = delta.T if delta.ndim == 2 else delta
                    score_c = score_c + _fma_guard(d, salt)
                    return ((score_c, cegb_out if cegb_on else cegb_c,
                             rows_c, coll_c), (trees_st, flags))

                js = jnp.arange(kk, dtype=jnp.int32)
                xs = (js, fmask) if fmask_on else js
                (score_f, cegb_f, rows_f, coll_f), (trees_all, flags) = \
                    jax.lax.scan(body, (score, cegb0, rows_acc, coll_acc),
                                 xs)
                trees = tuple(
                    _unstack_classes(jax.tree.map(lambda x: x[j],
                                                  trees_all))
                    for j in range(kk))
                return (trees, score_f, rows_f, coll_f,
                        cegb_f if cegb_on else None, flags)

            step = jax.jit(_fused_block, donate_argnums=(0,))
        if len(self._fused_cache) >= 8:
            # oldest-entry eviction: each parallel bind can pin a padded
            # O(N*F) dataset copy — a reset_parameter sweep over statics
            # must not accumulate one per swept value
            self._fused_cache.pop(next(iter(self._fused_cache)))
        self._fused_cache[key] = (step, bind)
        return step, bind

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (gbdt.cpp:369-452). Returns True when the
        iteration could not add any tree with a split (early stoppable).

        The body runs inside a watchdog phase: in multi-process training a
        dead or hung peer stalls this step's collectives forever, so the
        collective_deadline watchdog (distributed.CollectiveWatchdog) times
        the fused/unfused step and converts an over-deadline stall into a
        diagnosable DistributedTimeoutError / supervised gang restart.

        It also hosts the OOM degradation ladder: a RESOURCE_EXHAUSTED
        from the histogram programs (compile or execute) steps the booster
        down one documented rung (_maybe_degrade_oom) and RETRIES the
        iteration instead of killing the job — the retry is safe because a
        failed step mutates no trainer state (checked: the tree count must
        be unchanged)."""
        from .. import distributed
        from ..utils import faults, profiling
        it = self.iter
        # flight-recorder bookkeeping (host-side snapshots only — a dict
        # copy and a clock read; the record itself is built in the
        # finally so a failed step still leaves an in-flight record)
        flight = self._flight
        t_rec = time.time() if flight is not None else 0.0
        disp0 = profiling.dispatch_stats() if flight is not None else None
        sc0 = profiling.scopes() \
            if flight is not None and profiling.enabled() else None
        distributed.notify_step_begin(it)
        try:
            while True:
                ntrees_before = len(self.trees)
                try:
                    stop = self._train_one_iter_watched(grad, hess)
                    break
                except Exception as e:
                    if not self._maybe_degrade_oom(e, ntrees_before):
                        raise
                    # the retry recompiles the degraded programs under a
                    # fresh clock — without this the failed attempt +
                    # recompile could trip the collective-deadline
                    # watchdog on the very iteration the ladder rescues
                    distributed.notify_step_retry(it)
        finally:
            # on success self.iter advanced past ``it``: record completion;
            # on an exception the step did NOT complete and last_iter stays
            distributed.notify_step_end(it if self.iter > it else it - 1)
            if flight is not None:
                # telemetry must never kill the run it observes — and in
                # this finally an escaping record error would REPLACE a
                # real training exception. A failing recorder disarms
                # itself (one warning, not one per iteration).
                try:
                    self._record_flight(flight, it, t_rec, disp0, sc0)
                except Exception as e:
                    self._flight = None
                    log.warning(f"flight recorder disabled after record "
                                f"failure: {e}")
        if self._fault_plan is not None:
            # silent-corruption injection (FLIP_SCORE_RANK): one score-
            # cache bit flipped AFTER the iteration completes, on one rank
            # — the divergence check must attribute it to exactly that rank
            flipped = faults.maybe_flip_score(self._fault_plan, it,
                                              self.train_score)
            if flipped is not None:
                self.train_score = flipped
        return stop

    def _train_one_iter_watched(self, grad: Optional[np.ndarray] = None,
                                hess: Optional[np.ndarray] = None) -> bool:
        from ..utils import faults as faults_mod
        from ..utils import profiling
        cfg = self.config
        ts = self.train_set
        k = self.num_tree_per_iteration
        # simulated-OOM injection point for the degradation ladder (raises
        # before any state mutates, so the retry in train_one_iter is safe)
        faults_mod.maybe_oom(self._fault_plan, self.iter)
        if self._fused_ok(grad):
            # the fused program draws its own bagging mask/subset from the
            # period-start key — no host refresh dispatch
            return self._train_one_iter_fused()
        self._update_bagging()
        mask = self._bag_mask
        with profiling.timer("gradients"):
            if grad is None:
                g, h = self._gradients()
            else:
                g = jnp.asarray(np.asarray(grad, dtype=np.float32).reshape(self._score_shape))
                h = jnp.asarray(np.asarray(hess, dtype=np.float32).reshape(self._score_shape))
        if self._fault_plan is not None:
            from ..utils import faults
            g, h = faults.maybe_nan_grad(self._fault_plan, self.iter, g, h)
            # host-path twin of the in-program NaN injection
            g, h = faults.maybe_nan_hist(self._fault_plan, self.iter, g, h)
        if cfg.check_numerics:
            self._check_numerics_grad(g, h)
        sample_weights = self._sample_weights(g, h)
        if sample_weights is not None:
            # GOSS-style reweighting: grad/hess amplified, the 0/1 mask keeps
            # the histogram count channel exact (reference: goss.hpp:103-150
            # multiplies gradients_/hessians_ of sampled small-grad rows).
            w = sample_weights
            g = g * (w[:, None] if k > 1 else w)
            h = h * (w[:, None] if k > 1 else w)
            mask = (w > 0).astype(jnp.float32)
        no_split = True
        hm = self._hist_method()
        for c in range(k):
            gc = g[:, c] if k > 1 else g
            hc = h[:, c] if k > 1 else h
            fmask = self._feature_mask()
            iter_key = jax.random.fold_in(self._extra_rng_key,
                                          self.iter * k + c)
            with profiling.timer_sync("grow_tree") as grow_scope:
                tree, leaf_id, aux = self._grow_one(gc, hc, mask, fmask,
                                                    iter_key, hm)
                grow_scope.sync(tree.num_leaves)
            if aux is not None:
                self._record_aux_counters(aux)
                if cfg.check_numerics and float(aux.sentinel):
                    # same judge as the fused path so the histogram-plane
                    # defect is reported with ONE message either way
                    self._check_sentinel_flags(1 << 2)
            # pre-partitioned: leaf_id comes back row-sharded; keep only
            # this process's rows for the local score update (the
            # reference's per-machine score partition, score_updater.hpp —
            # no O(N_global) array is ever materialized per host)
            leaf_id = self._localize_leaf_id(leaf_id)
            if self._cegb_mode != "off":
                # CEGB feature-used tracking persists across iterations
                # (cost_effective_gradient_boosting.hpp Init: !init_ reuse)
                self._cegb_aux = aux
            lin = None
            if cfg.linear_tree:
                # "first tree" counts loaded init-model trees too
                # (reference: models_.size() < num_tree_per_iteration_)
                first_tree = len(self.trees) < k and self.loaded_iters == 0
                lin = self._fit_linear_leaves(tree, leaf_id, gc, hc, mask,
                                              first_tree)
            lazy = lin is None and self._lazy_host_ok()
            with profiling.timer("finalize_tree"):
                if lazy:
                    # shrink on device only; the host mirror fetch is async
                    # (see host_trees) — no blocking round-trip this iter
                    tree = _shrink_tree(tree, self.shrinkage_rate)
                    t_host, had_split = None, True
                else:
                    tree, t_host, had_split = self._finalize_tree(
                        tree, leaf_id, c)
            no_split = no_split and not had_split
            with profiling.timer("score_update", sync=None):
                if lin is not None:
                    self._add_tree(tree, leaf_id, c, linear=lin, t_host=t_host)
                else:
                    self._add_tree(tree, leaf_id, c, t_host=t_host, lazy=lazy)
                self._bias_after_score(c, had_split)
        self.iter += 1
        # lagged no-split detection for lazy iterations: consume whatever
        # mirrors already finished (non-blocking) and report the stop one
        # or more iterations late — the extra trees are splitless zero
        # trees, prediction-identical to stopping on time
        self._flush_pending(only_ready=True)
        return no_split or self._lagged_stop

    def _block_rounds(self) -> int:
        """How many iterations the NEXT fused dispatch should grow — the
        ``boost_rounds_per_dispatch`` K, clipped so blocks (a) never run
        past the engine's round target and (b) always END on a multiple
        of K (the first block after an unaligned resume truncates to
        re-align), which is what lets a checkpoint callback whose period
        is a multiple of K fire on schedule. 1 unless engine.train has
        opted in for this run (``_block_target``): a manual
        ``Booster.update`` loop or cv() must keep one-iteration-per-call
        semantics, or its round counting would double-train."""
        cfg = self.config
        K = max(1, int(cfg.boost_rounds_per_dispatch))
        if K <= 1:
            return 1
        target = getattr(self, "_block_target", None)
        if target is None or getattr(self, "_block_disable", False):
            return 1
        remaining = int(target) - self.iter
        aligned = K - (self.iter % K)
        return max(1, min(aligned, remaining))

    def _fused_call_args(self, fmask, bind, it=None):
        """The fused step/block argument tuple — ONE definition shared by
        the training dispatch and the AOT warmup (warm_start), so the
        warmed program signature can never drift from the called one."""
        bag_mode = self._bagging_mode()
        bag_frac = self._bagging_frac() if bag_mode == "mask" else None
        cegb_state = self._fused_cegb_state()
        return (self.train_score,
                np.int32(self.iter if it is None else it),
                np.float32(self.shrinkage_rate), fmask, self.split_params,
                bag_frac, cegb_state, self._rows_streamed_dev,
                self._coll_bytes_dev, bind)

    def _train_one_iter_fused(self) -> bool:
        """Fused iteration for every admitted configuration (see
        _fused_step_fn): TWO compiled-program dispatches — the fused grow
        step and the donated in-place score add — versus three-plus (and
        per-class multiples) on the unfused path; everything after
        mirrors the unfused finalize/add/bias flow per class. The step
        returns SHRUNK trees, so on the steady-state lazy path nothing
        else dispatches — the telemetry tests assert it stays that way.

        With ``boost_rounds_per_dispatch`` K > 1 under engine.train, the
        whole K-iteration BLOCK runs instead (_train_block_fused): ONE
        dispatch grows K*C trees with the score carried in-program."""
        K = self._block_rounds()
        if K > 1:
            return self._train_block_fused(K)
        from ..utils import profiling
        hm = self._hist_method()
        fmask = self._feature_mask_np()
        step, bind = self._fused_step_fn(hm, fmask is not None)
        bag_mode = self._bagging_mode()
        if bag_mode != "off":
            self._bag_stale = True   # host mask not refreshed this iter
        prev = None
        if profiling.enabled():
            prev = (float(self._rows_streamed_dev),
                    float(self._coll_bytes_dev))
        with profiling.timer_sync("grow_tree") as grow_scope:
            (trees, delta, self._rows_streamed_dev,
             self._coll_bytes_dev, cegb_aux, sent_flags) = step(
                *self._fused_call_args(fmask, bind))
            grow_scope.sync(trees[0].num_leaves)
        if self.config.check_numerics:
            # the flag word is judged LAZILY (_drain_sentinels below): a
            # blocking scalar fetch here — or even a fixed one-iteration
            # lag — serializes the host against the dispatch queue, the
            # pipelining the fused path exists for (measured ~15-40% at
            # small CPU shapes). Instead the device scalar joins a FIFO
            # judged by non-blocking ready checks, the same lagged
            # pattern as the async host-tree mirrors; every state-capture
            # path (host_trees, get_trainer_state, training end) flushes
            # it blockingly first, so poisoned state can briefly exist in
            # memory but is never read out or written. Still 2
            # dispatches/iter.
            self._sentinel_pending.append((self.iter, sent_flags))
        if cegb_aux is not None:
            self._cegb_aux = cegb_aux
        if prev is not None:
            profiling.counter("hist_rows_streamed",
                              float(self._rows_streamed_dev) - prev[0])
            profiling.counter("hist_coll_bytes",
                              float(self._coll_bytes_dev) - prev[1])
        self.train_score = _apply_score_delta(self.train_score, delta)
        lazy = self._lazy_host_ok(sentinels=True)
        no_split = True
        for c, tree in enumerate(trees):
            with profiling.timer("finalize_tree"):
                if lazy:
                    t_host, had_split = None, True
                else:
                    # trees arrive pre-shrunk; renew/linear are excluded
                    # by _fused_ok and check_numerics is covered by the
                    # in-program sentinels, so finalize reduces to the
                    # host-mirror fetch
                    t_host = jax.device_get(tree)
                    had_split = int(t_host.num_leaves) > 1
            no_split = no_split and not had_split
            with profiling.timer("score_update", sync=None):
                self._add_tree(tree, None, c, t_host=t_host, lazy=lazy,
                               score_updated=True)
                self._bias_after_score(c, had_split)
        self.iter += 1
        self._flush_pending(only_ready=True)
        self._drain_sentinels()
        return (not lazy and no_split) or self._lagged_stop

    def _train_block_fused(self, K: int) -> bool:
        """K boosting iterations in ONE compiled-program dispatch (the
        ``boost_rounds_per_dispatch`` block, _fused_step_fn's scan mode):
        the score cache is donated in and carried through the scan, K*C
        shrunk trees come back stacked, and the host-side finalize/add/
        bias flow then runs per iteration in order — so valid-set scores,
        the bias fold and the lagged-stop bookkeeping are identical to K
        separate fused iterations. Everything external (callbacks, eval,
        checkpoints) happens at block boundaries only; engine.train
        validates the checkpoint period against K and advances its round
        counter by the consumed count."""
        from ..utils import profiling
        hm = self._hist_method()
        fmask_on = self.config.feature_fraction < 1.0
        fmask = None
        if fmask_on:
            # the SAME stateful host rng stream, drawn K iterations ahead
            # in the per-iteration order (bit-parity with K single steps)
            fmask = np.stack([self._feature_mask_np() for _ in range(K)])
        step, bind = self._fused_step_fn(hm, fmask_on, k_rounds=K)
        if self._bagging_mode() != "off":
            self._bag_stale = True   # host mask not refreshed this block
        it0 = self.iter
        prev = None
        if profiling.enabled():
            prev = (float(self._rows_streamed_dev),
                    float(self._coll_bytes_dev))
        with profiling.timer_sync("grow_tree") as grow_scope:
            (trees, self.train_score, self._rows_streamed_dev,
             self._coll_bytes_dev, cegb_aux, sent_flags) = step(
                *self._fused_call_args(fmask, bind))
            grow_scope.sync(trees[0][0].num_leaves)
        if self.config.check_numerics:
            # one [K] flag vector per block, judged lazily like the
            # per-iteration scalars (_drain_sentinels names it0 + j)
            self._sentinel_pending.append((it0, sent_flags))
        if cegb_aux is not None:
            self._cegb_aux = cegb_aux
        if prev is not None:
            profiling.counter("hist_rows_streamed",
                              float(self._rows_streamed_dev) - prev[0])
            profiling.counter("hist_coll_bytes",
                              float(self._coll_bytes_dev) - prev[1])
        lazy = self._lazy_host_ok(sentinels=True)
        stop = False
        for j in range(K):
            no_split = True
            for c, tree in enumerate(trees[j]):
                with profiling.timer("finalize_tree"):
                    if lazy:
                        t_host, had_split = None, True
                    else:
                        t_host = jax.device_get(tree)
                        had_split = int(t_host.num_leaves) > 1
                no_split = no_split and not had_split
                with profiling.timer("score_update", sync=None):
                    self._add_tree(tree, None, c, t_host=t_host, lazy=lazy,
                                   score_updated=True)
                    self._bias_after_score(c, had_split)
            self.iter += 1
            # a splitless iteration anywhere in the block arms the stop;
            # any later trees of the same block are splitless zero trees,
            # prediction-identical to stopping on time (the same argument
            # as the lazy path's lagged stop)
            stop = stop or (not lazy and no_split)
        self._flush_pending(only_ready=True)
        self._drain_sentinels()
        return stop or self._lagged_stop

    # --------------------------------------------------- AOT compile warm
    def warm_start(self, k_rounds: Optional[int] = None) -> bool:
        """AOT-compile the training programs for the current
        configuration — ``jax.jit(...).lower(...).compile()`` on the
        fused step/block (which embeds the grower) and the donated score
        add, with argument shapes taken from the live trainer state so
        the warmed signatures exactly match the first real dispatch.

        With the persistent compilation cache configured
        (``compile_cache_dir``), this is how a restarted supervisor
        incarnation, a resumed elastic gang or a second same-shape
        process starts HOT: the XLA compile the first boosting step would
        pay becomes a disk-cache deserialization here, before the
        training loop begins. Without the cache it still moves the
        compile wall out of the measured first iteration. Returns True
        when a program was AOT-compiled; False (with the reason logged at
        info) when the configuration is not fused-eligible."""
        from .. import compile_cache
        if self.train_set is None or not self._fused_ok(None):
            return False
        try:
            K = k_rounds if k_rounds is not None else self._block_rounds()
            # outside engine.train (_block_target unset) warm the
            # configured block size directly: the warmed program must be
            # the one the training loop will dispatch
            if k_rounds is None and K == 1:
                cfgK = max(1, int(self.config.boost_rounds_per_dispatch))
                if cfgK > 1:
                    K = cfgK - (self.iter % cfgK)
            hm = self._hist_method()
            fmask_on = self.config.feature_fraction < 1.0
            step, bind = self._fused_step_fn(hm, fmask_on,
                                             k_rounds=K)
            k = self.num_tree_per_iteration
            f = self.train_set.num_used_features()
            fmask = None
            if fmask_on:
                shape = (K, k, f) if K > 1 else (k, f)
                fmask = jax.ShapeDtypeStruct(shape, jnp.float32)
            args = self._fused_call_args(fmask, bind)
            ok = compile_cache.aot_compile(step, args, label="fused_step")
            if ok and K == 1:
                # the per-iteration mode's second dispatch: the donated
                # in-place score add (block mode carries it in-program)
                d_shape = ((k, self._n_score_rows) if k > 1
                           else (self._n_score_rows,))
                compile_cache.aot_compile(
                    _apply_score_delta,
                    (jax.ShapeDtypeStruct(self._score_shape, jnp.float32),
                     jax.ShapeDtypeStruct(d_shape, jnp.float32)),
                    label="score_delta")
            return ok
        except Exception as e:   # warmup must never break training
            log.warning(f"AOT compile warmup failed (training will "
                        f"compile lazily instead): {e}")
            return False

    def _grow_one(self, gc: jax.Array, hc: jax.Array, mask: jax.Array,
                  fmask: jax.Array, iter_key: jax.Array, hm: str):
        """Dispatch one tree's growth to the serial grower or the configured
        parallel learner (the analog of TreeLearner::Train through the
        factory-selected learner, tree_learner.h:104)."""
        cfg = self.config
        ts = self.train_set
        if ts.bins.shape[1] == 0 and not getattr(ts, "has_sparse_cols",
                                                 False):
            # every feature pre-filtered as trivial (e.g. min_data_in_leaf
            # too large for the data — the reference's feature_pre_filter,
            # dataset_loader.cpp:647-648): train a splitless constant tree
            # like the reference instead of dispatching a 0-feature grower
            from .tree import empty_tree
            n = (ts.num_local_data if getattr(self, "_pre_part", False)
                 else ts.num_data)
            return (empty_tree(cfg.num_leaves),
                    jnp.zeros((n,), dtype=jnp.int32), None)
        if self._parallel_grower is not None:
            return self._parallel_grower(
                ts.bins, gc, hc, mask,
                ts.feature_meta, self.split_params, fmask, ts.missing_bin,
                binsT=ts.bins_T if hm.startswith(("onehot", "pallas")) else None,
                pre_part=getattr(self, "_pre_part", False),
                rng_key=iter_key,
                bundle_meta=ts.bundle_meta,
                forced_splits=self._forced_splits,
                **self._parallel_grow_statics(hm))
        sub = self._bag_sub
        has_sp = getattr(ts, "has_sparse_cols", False)
        statics = self._serial_grow_statics(hm)
        grow_fn = grow_tree
        from ..utils import profiling
        if (profiling.enabled() and self._forced_splits is None
                and statics["feature_block"] == 0
                and jax.process_count() == 1):
            # TIMETAG runs drive the host-phased grower so the hist_pass /
            # split_search / apply_split sub-scopes are attributable per
            # phase (bit-identical trees; see grow_tree_phased)
            from .grower import grow_tree_phased
            grow_fn = grow_tree_phased
        return grow_fn(
            ts.bins, gc, hc, mask,
            ts.feature_meta, self.split_params, fmask, ts.missing_bin,
            binsT=ts.bins_T if self._use_binsT(hm) else None,
            sub_idx=sub[0] if sub else None,
            sub_bins=sub[1] if sub else None,
            sub_binsT=sub[2] if sub else None,
            interaction_groups=self._interaction_groups,
            cegb_coupled=self._cegb_coupled,
            cegb_lazy_penalty=self._cegb_lazy,
            cegb_state=self._cegb_aux,
            bynode_fraction=jnp.float32(cfg.feature_fraction_bynode)
            if self._use_bynode else None,
            rng_key=iter_key,
            bundle_meta=ts.bundle_meta,
            forced_splits=self._forced_splits,
            sp_rows=ts.sp_rows if has_sp else None,
            sp_bins=ts.sp_bins if has_sp else None,
            sp_default=ts.sp_default if has_sp else None,
            **statics)

    def _use_binsT(self, hm: str) -> bool:
        """The feature-major bins copy doubles the dominant array; above
        ~2 GiB keep only the row-major matrix (pallas kernels then fall
        back to the XLA onehot formulation, with routing slicing rows)."""
        if not hm.startswith(("onehot", "pallas")):
            return False
        ts = self.train_set
        itemsize = 4 if ts.max_num_bins > 256 else 1   # int32 vs uint8 bins
        # per-HOST bytes: pre-partitioned data is row-sharded, so the copy
        # costs each host only its shard
        rows = (ts.num_local_data if getattr(self, "_pre_part", False)
                else ts.num_data)
        bins_bytes = int(rows) * int(ts.num_used_features()) * itemsize
        if bins_bytes <= 2 << 30:
            return True
        if not getattr(self, "_warned_binst", False):
            self._warned_binst = True
            log.warning(
                f"bins matrix is {bins_bytes / 2**30:.1f} GiB: skipping the "
                "feature-major copy (binsT) to halve memory; pallas "
                "histogram kernels fall back to the XLA path")
        return False

    def _feature_block(self, hm: str) -> int:
        """Column-block width for the grower's memory-bounded mode, or 0
        to keep the resident [L, F, B, 3] histogram state.

        Engages when that state would exceed ``histogram_pool_size``
        (the reference's pool cap, config.h histogram_pool_size in MB;
        <= 0 here means a 2 GiB auto cap rather than unlimited — wide
        datasets would otherwise OOM the chip). The analog of the
        reference's HistogramPool LRU (feature_histogram.hpp:1095-1290):
        over-cap leaves pay recomputation instead of residency."""
        cfg = self.config
        ts = self.train_set
        f_cols = ts.num_used_features()
        B = ts.max_num_bins
        hist_bytes = cfg.num_leaves * f_cols * B * 3 * 4
        pool = cfg.histogram_pool_size
        cap = int(pool * 1024 * 1024) if pool and pool > 0 else 2 << 30
        if hist_bytes <= cap:
            return 0
        subset_possible = (cfg.bagging_freq > 0
                           and cfg.bagging_fraction <= 0.5
                           and cfg.pos_bagging_fraction >= 1.0
                           and cfg.neg_bagging_fraction >= 1.0
                           and self._cegb_mode == "off"
                           and not cfg.linear_tree)
        unsupported = (self._cegb_mode != "off"
                       or self._forced_splits is not None
                       or (self._with_monotone
                           and self._mono_mode != "basic")
                       or subset_possible or self._hist_dp
                       or hm.endswith("_q8")
                       or getattr(ts, "has_sparse_cols", False))
        if unsupported:
            if not getattr(self, "_warned_pool", False):
                self._warned_pool = True
                log.warning(
                    f"histogram state ({hist_bytes / 2**20:.0f} MB) exceeds "
                    f"the pool cap ({cap / 2**20:.0f} MB) but the "
                    "memory-bounded mode does not support "
                    "CEGB/forced-splits/box-monotone/subset-bagging/f64/q8 "
                    "here; keeping the resident state (may OOM)")
            return 0
        tile = cfg.tile_leaves or 42
        P = (min(tile, cfg.num_leaves)
             if hm.startswith(("onehot", "pallas")) else cfg.num_leaves)
        # transient per feature column: the [P, B, 3] tile plus ~8
        # search-sized temporaries
        per_f = P * B * 4 * (3 + 8)
        fb = max(16, min(f_cols, cap // per_f))
        if not getattr(self, "_warned_pool", False):
            self._warned_pool = True
            log.warning(
                f"histogram state ({hist_bytes / 2**20:.0f} MB) exceeds the "
                f"pool cap ({cap / 2**20:.0f} MB): memory-bounded growth "
                f"engaged ({fb} feature columns per pass, no histogram "
                "subtraction — ~2x the histogram passes)")
        return fb

    def _localize_leaf_id(self, leaf_id: jax.Array) -> jax.Array:
        """Pre-partitioned mode: slice this process's rows out of the
        row-sharded global leaf-id vector (identity otherwise)."""
        if not getattr(self, "_pre_part", False):
            return leaf_id
        n_local = self.train_set.num_local_data
        if leaf_id.is_fully_addressable:
            return leaf_id[:n_local]
        shards = sorted(leaf_id.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        local = np.concatenate([np.asarray(s.data) for s in shards])
        return jnp.asarray(local[:n_local])

    def _hist_interpret(self) -> bool:
        """Run Pallas histogram kernels through the interpreter: only when
        asked (hist_pallas_interpret) and only off-TPU — on TPU the real
        kernel always wins and the flag is inert."""
        return (self.config.hist_pallas_interpret
                and jax.default_backend() != "tpu")

    def _split_fusion_on(self, hm: str, fb: int = 0) -> bool:
        """Resolve Config.split_fusion for this booster's configuration.

        "auto" engages the fused split-finding epilogue whenever the
        numerical non-bundled search is the whole story (the fused scan
        covers missing-direction both ways, min_data/min_hessian masks
        and basic monotone constraints; categorical / EFB / forced-split
        / CEGB / extra_trees / bynode / advanced-monotone semantics stay
        in find_best_splits, so those configurations keep the classic
        split phase). "on" raises on an unsupported configuration
        instead of silently degrading."""
        cfg = self.config
        mode = getattr(cfg, "split_fusion", "auto")
        if mode == "off" or self.train_set is None:
            return False
        ts = self.train_set
        reasons = []
        if self._parallel_grower is not None:
            reasons.append("parallel learner")
        if ts.has_categorical:
            reasons.append("categorical features")
        if ts.bundle_meta is not None:
            reasons.append("EFB bundles")
        if self._forced_splits is not None:
            reasons.append("forced splits")
        if self._cegb_mode != "off":
            reasons.append("CEGB")
        if cfg.extra_trees:
            reasons.append("extra_trees")
        if self._use_bynode:
            reasons.append("feature_fraction_bynode")
        if self._with_monotone and self._mono_mode != "basic":
            reasons.append(f"{self._mono_mode} monotone constraints")
        if cfg.feature_contri and min(cfg.feature_contri) <= 0:
            # the fused path applies the contri multiplier AFTER the
            # within-feature argmax (find_best_splits applies it per
            # bin); the two commute only for positive multipliers — a
            # zero/negative entry flips or flattens the within-feature
            # order, so those configs keep the classic phase
            reasons.append("non-positive feature_contri")
        if self._hist_dp:
            reasons.append("f64 histograms")
        if getattr(ts, "has_sparse_cols", False):
            reasons.append("sparse device columns")
        if fb:
            reasons.append("memory-bounded (feature-blocked) growth")
        if mode == "on" and reasons:
            raise ValueError(
                "split_fusion=on is unsupported with "
                + ", ".join(reasons)
                + " (these split semantics live in the classic search; "
                "use split_fusion=auto to fall back automatically)")
        return not reasons

    def _hist_tuning(self, hm: str, epilogue: bool = False) -> tuple:
        """(tile_leaves, hist_block) for the serial grow statics: explicit
        config values always win; otherwise the Pallas autotuner supplies
        the measured block size and structural leaf batch for this shape
        bucket (ops/pallas_hist.py autotune_hist — a no-op returning
        defaults off-TPU and for non-Pallas methods). Cached on the
        booster: the statics must stay stable across iterations or every
        tree would re-jit the grower.

        ``epilogue`` (the resolved split_fusion flag) keys the sweep: the
        epilogue changes the kernel's block-shape economics, and a
        ``_hist_tuned`` dict ridden in from a pre-fusion checkpoint
        (trainer state) must NOT replay a block tuned for the
        plane-returning kernel into the epilogue kernel — a cached dict
        whose epilogue key mismatches is discarded and re-measured."""
        cfg = self.config
        tile, blk = cfg.tile_leaves, cfg.hist_block
        if (not cfg.hist_autotune or not hm.startswith("pallas")
                or (tile and blk) or self.train_set is None
                or jax.process_count() > 1):
            return tile, blk
        if blk:
            # only the leaf batch is missing, and that choice is purely
            # structural (widest tile in the 128-lane group) — don't pay
            # the measured block sweep just to discard its winner
            from ..ops.pallas_hist import structural_tile_leaves
            return tile or structural_tile_leaves(), blk
        hit = getattr(self, "_hist_tuned", None)
        if hit is not None and hit.get("epilogue", False) != epilogue:
            # pre-fusion (or cross-mode) ride from a resumed checkpoint:
            # the tuned block belongs to the OTHER kernel form
            log.info("pallas hist autotune: cached shape was tuned with "
                     f"epilogue={hit.get('epilogue', False)}; re-tuning "
                     f"for epilogue={epilogue}")
            hit = None
        if hit is None:
            binsT = (self.train_set.bins_T if self._use_binsT(hm) else None)
            if binsT is None:
                hit = {"block": 0, "tile_leaves": 0, "epilogue": epilogue}
            else:
                from ..ops.pallas_hist import autotune_hist
                hit = autotune_hist(
                    binsT, self.train_set.max_num_bins,
                    mode={"pallas": "highest", "pallas_hilo": "hilo",
                          "pallas_q8": "q8"}[hm], epilogue=epilogue)
            self._hist_tuned = hit
        return tile or hit["tile_leaves"], blk or hit["block"]

    def _hist_method(self) -> str:
        from ..ops.histogram import measured_auto_method, resolve_method
        cfg = self.config
        if self._oom_hm:
            # rung 2 of the OOM degradation ladder: the forced XLA
            # fallback overrides auto/measured selection until the
            # booster (or a resumed incarnation: the override rides the
            # trainer state) is rebuilt
            return self._oom_hm
        if cfg.quantized_grad:
            # the quantized-gradient training mode overrides the measured
            # auto-selection: q8 changes numerics, so it is chosen by the
            # user, never by the timer
            return resolve_method(cfg.histogram_method,
                                  deterministic=cfg.deterministic,
                                  quantized=True,
                                  interpret=self._hist_interpret())
        if (cfg.histogram_method == "auto" and not cfg.deterministic
                and jax.default_backend() == "tpu"
                and self.train_set is not None
                and jax.process_count() == 1):
            # single-process only: per-host wall-clock winners could
            # diverge and the method is a static jit arg — multi-process
            # SPMD programs must match, so those keep the structural choice
            # measured choice (TestMultiThreadingMethod analog): timed once
            # per shape at first use, cached on the booster thereafter
            hit = getattr(self, "_measured_hm", None)
            if hit is None:
                ts = self.train_set
                binsT = ts.bins_T if self._use_binsT("pallas") else None
                hit = measured_auto_method(
                    ts.bins, binsT, ts.max_num_bins,
                    tile_leaves=cfg.tile_leaves or 42,
                    hist_block=cfg.hist_block)
                self._measured_hm = hit
            return hit
        return resolve_method(cfg.histogram_method,
                              deterministic=cfg.deterministic,
                              interpret=self._hist_interpret())

    def _sample_weights(self, g, h) -> Optional[jax.Array]:
        """Hook for GOSS-style reweighted sampling; None = use bag mask."""
        return None

    # ---------------------------------------------------- numerics guard
    def _check_numerics_grad(self, g: jax.Array, h: jax.Array) -> None:
        """check_numerics fail-fast: NaN/Inf gradients or hessians poison
        every histogram they touch and surface much later as garbage
        splits — name the iteration and offending count NOW instead."""
        bad_g = int(jnp.sum(~jnp.isfinite(g)))
        bad_h = int(jnp.sum(~jnp.isfinite(h)))
        if bad_g or bad_h:
            log.fatal(
                f"check_numerics: iteration {self.iter}: {bad_g} non-finite "
                f"gradient and {bad_h} non-finite hessian values out of "
                f"{int(np.prod(g.shape))} — failing fast before they poison "
                f"the histograms (check the objective / custom fobj, "
                f"learning_rate, and input features)")

    def _check_numerics_leaves(self, t_host, num_leaves: int) -> None:
        """check_numerics on a finalized tree's leaf outputs."""
        lv = np.asarray(t_host.leaf_value[:max(num_leaves, 1)])
        bad = int(np.sum(~np.isfinite(lv)))
        if bad:
            log.fatal(
                f"check_numerics: iteration {self.iter}: {bad} of "
                f"{max(num_leaves, 1)} leaf outputs in the new tree are "
                f"non-finite — failing fast before the score caches are "
                f"poisoned")

    def _check_sentinel_flags(self, flags: int,
                              iteration: Optional[int] = None) -> None:
        """Judge the fused step's in-program sentinel flag word: nonzero
        bits name which sources carried NaN/Inf (see _SENTINEL_SOURCES) —
        fail fast with the iteration and sources spelled out."""
        if not flags:
            return
        it = self.iter if iteration is None else iteration
        sources = [name for bit, name in _SENTINEL_SOURCES
                   if flags & (1 << bit)]
        log.fatal(
            f"check_numerics: iteration {it}: in-program sentinels "
            f"flagged non-finite values in {', '.join(sources)} "
            f"(flag word 0b{flags:05b}) — failing fast before they poison "
            f"the model on disk (check the objective / custom fobj, "
            f"learning_rate, and input features)")

    def _drain_sentinels(self) -> None:
        """Judge every pending sentinel word whose step has already
        finished — non-blocking ready checks, oldest first (so the FIRST
        poisoned iteration is the one named), mirroring
        ``_flush_pending(only_ready=True)``. A backend without
        ``is_ready()`` judges everything (blocking) — the guard stays
        correct, just without the pipelined fetch. The FIFO is bounded:
        past 64 pending words the oldest is judged blockingly, which
        bounds both memory and detection lag."""
        q = self._sentinel_pending
        while q:
            it, flags = q[0]
            if len(q) <= 64:
                try:
                    if not flags.is_ready():
                        break
                except AttributeError:
                    pass
            q.pop(0)
            self._judge_sentinel(it, flags)

    def _flush_sentinel(self) -> None:
        """Blocking judge of EVERY deferred in-program sentinel word
        (fused path). The per-iteration fetch is lazy (_drain_sentinels)
        so it never stalls the dispatch pipeline; every state-capture
        path — ``host_trees``, ``get_trainer_state`` (the checkpoint
        capture), rollback, training end — flushes here first, so
        poisoned state is never read out or written."""
        q = self._sentinel_pending
        while q:
            it, flags = q.pop(0)
            self._judge_sentinel(it, flags)

    def _judge_sentinel(self, it: int, flags) -> None:
        """Judge one pending sentinel entry: a scalar word (per-iteration
        fused step) or a [K] vector (one word per iteration of a
        ``boost_rounds_per_dispatch`` block, oldest first so the FIRST
        poisoned iteration is the one named)."""
        arr = np.atleast_1d(np.asarray(flags))
        for j in range(arr.size):
            word = int(arr[j])
            if self._flight is not None:
                # back-fill the verdict into the covering flight record
                # BEFORE judging: a nonzero word raises, and the flushed
                # post-mortem must name the poisoned iteration
                self._flight.note_sentinel(it + j, word)
            self._check_sentinel_flags(word, it + j)

    # ------------------------------------------------ OOM degradation
    def _eff_hist_block(self, blk: int) -> int:
        """Histogram row-block size after the OOM ladder's rung-1 override
        (0 keeps the per-method auto default)."""
        if not self._oom_block:
            return blk
        return self._oom_block if not blk else min(blk, self._oom_block)

    def _predicted_hist_bytes(self) -> Optional[int]:
        """The histogram traffic model's predicted HBM bytes for ONE
        pass under the CURRENT configuration (ops/pallas_hist
        traffic_model — a static model, not a measurement): the number
        that makes an OOM rung step explainable next to the allocator
        snapshot ("the model said this pass moves N bytes; the device
        had M free"). Chooses the formulation the active hist method
        actually runs (fused kernel vs the XLA one-hot materialization);
        None when the shape is not yet known."""
        try:
            from ..ops.pallas_hist import _PAD, traffic_model
            ts = self.train_set
            n = int(ts.num_data)
            f = int(ts.bins.shape[1])
            b = int(ts.max_num_bins)
            s = 3
            mode = "q8" if getattr(self.config, "quantized_grad", False) \
                else "hilo"
            t = traffic_model(n, f, b, _PAD // s, s, mode)
            hm = self._hist_method()
            if "onehot" in hm or hm in ("scatter", "binloop"):
                key = "xla_onehot"
            else:
                # the kernel pass the booster actually dispatches: the
                # epilogue formulation only when split fusion resolved
                # ON for this configuration — the pre-fusion kernel
                # round-trips the RHS planes the epilogue keeps in VMEM
                key = ("fused" if self._split_fusion_on(
                    hm, self._feature_block(hm)) else "prefusion")
            return int(t[key])
        except Exception:
            return None

    def _oom_memory_evidence(self) -> Dict[str, Any]:
        """The explainability payload every OOM degradation event
        carries: the allocator/host snapshot AT failure plus the traffic
        model's predicted per-pass bytes (fields null where a source is
        unavailable — CPU backends have no allocator stats)."""
        from ..utils import profiling
        return {"memory": profiling.sample_memory(),
                "predicted_hist_bytes": self._predicted_hist_bytes()}

    def _maybe_degrade_oom(self, exc: BaseException,
                           ntrees_before: int) -> bool:
        """Step the booster down ONE rung of the documented OOM degradation
        ladder and report whether the failed iteration may be retried:

          1. smaller histogram row block (less transient VMEM/HBM per
             pass, more passes),
          2. ``hist_method`` -> the XLA scatter formulation (no one-hot
             materialization, no Pallas VMEM tiles — the smallest-footprint
             backend; q8 keeps its integer form via onehot_q8),
          3. chunked predict buckets (bounds the eval/serving programs'
             resident rows).

        Every degradation is recorded in ``distributed.health_snapshot()``
        (and therefore every later checkpoint manifest's health section),
        the ``hist_oom_degrade_level`` gauge and a WARNING — the job keeps
        running, but visibly DEGRADED, instead of dying. The degraded
        configuration rides the trainer state (get_trainer_state) so a
        resumed incarnation reuses it — same bit-identical-restart
        contract as the measured histogram method. False (re-raise) when
        the guard is off, the error is not a RESOURCE_EXHAUSTED, an
        earlier class of this multiclass iteration already adopted a tree
        (retry would double-count), or the ladder is exhausted."""
        from .. import distributed
        from ..utils import faults, profiling
        if not self.config.hist_oom_fallback \
                or not faults.is_resource_exhausted(exc):
            return False
        try:
            score_gone = bool(self.train_score.is_deleted())
        except Exception:
            score_gone = False
        if score_gone:
            self._flush_flight(
                f"oom-exhausted: donated score cache consumed at "
                f"iteration {self.iter}")
            # the K-block step DONATES the score cache; an OOM during
            # EXECUTION (not compile — the common case — which fails
            # before any donation) may have consumed the buffer, so the
            # iteration cannot be retried in-process. Fail stop with the
            # real remedy named instead of crashing the retry on a
            # deleted array.
            log.warning(
                f"RESOURCE_EXHAUSTED in boosting iteration {self.iter}: "
                f"the failed K-block dispatch consumed the donated score "
                f"cache, so the degradation ladder cannot retry "
                f"in-process — resume from the last checkpoint (or set "
                f"boost_rounds_per_dispatch=1) with a smaller "
                f"hist_block/scatter fallback")
            return False
        if jax.process_count() > 1:
            # gangs FAIL-STOP on a training OOM instead of degrading: the
            # ladder's rungs change accumulation shape (numerics), so one
            # rank degrading alone would break the rank-symmetric
            # reduction contract — and be named corrupt by the very
            # divergence vote this layer adds. The supervisor's
            # restart/shrink path owns rank-local resource failures.
            self._flush_flight(
                f"oom-exhausted: multi-process fail-stop at iteration "
                f"{self.iter}")
            log.warning(
                f"RESOURCE_EXHAUSTED in boosting iteration {self.iter}: "
                f"per-rank degradation is disabled in multi-process gangs "
                f"(it would silently break the rank-symmetric reductions) "
                f"— failing stop for the supervisor to restart or shrink")
            return False
        if len(self.trees) != ntrees_before:
            return False
        if self._oom_level >= 3:
            # ladder exhausted: the exception re-raises and kills the run
            # — the flushed ring is the post-mortem naming every rung
            # this booster already stepped down
            self._flush_flight(
                f"oom-exhausted: ladder spent at iteration {self.iter} "
                f"(level {self._oom_level}/3)")
            return False
        self._oom_level += 1
        if self._oom_level == 1:
            from ..ops.pallas_hist import oom_shrink_block
            hm = self._hist_method()
            _, blk = self._hist_tuning(hm)
            self._oom_block = oom_shrink_block(blk)
            action = f"hist_block -> {self._oom_block}"
        elif self._oom_level == 2:
            from ..ops.histogram import oom_fallback_method
            self._oom_hm = oom_fallback_method(self._hist_method())
            action = f"hist_method -> {self._oom_hm} (XLA fallback)"
        else:
            base = self.config.predict_chunk_rows or (1 << 22)
            self._oom_predict_chunk = max(1 << 14, base // 4)
            action = f"predict_chunk_rows -> {self._oom_predict_chunk}"
        # degraded statics must recompile: drop every cached program that
        # baked the old histogram configuration in
        self._fused_cache.clear()
        self._engine_cache.clear()
        distributed.record_degradation({
            "kind": "oom", "iteration": int(self.iter),
            "level": int(self._oom_level), "action": action,
            "error": str(exc)[:200], **self._oom_memory_evidence()})
        profiling.set_gauge("hist_oom_degrade_level", self._oom_level)
        log.warning(
            f"RESOURCE_EXHAUSTED in boosting iteration {self.iter}: "
            f"degrading ({action}; ladder rung {self._oom_level}/3) and "
            f"retrying — the job continues DEGRADED (recorded in "
            f"health_snapshot()/gauges and checkpoint manifests)")
        return True

    def _maybe_degrade_predict_oom(self, exc: BaseException) -> bool:
        """Predict-path entry to the ladder's rung 3: halve the effective
        predict chunk (repeatably, floor 16k rows) so the serving program
        holds fewer resident rows, and retry. Deliberately does NOT touch
        ``_oom_level``: predict chunking is numerics-exact and independent
        of the training rungs — a serve-time OOM must not consume the
        hist-block/scatter rungs a later training OOM may still need."""
        from .. import distributed
        from ..utils import faults, profiling
        nxt = faults.next_predict_chunk(
            exc, self._oom_predict_chunk or self.config.predict_chunk_rows,
            self.config.hist_oom_fallback)
        if nxt is None:
            return False
        with self._engine_lock:
            # chunk update + cache clear under the engine lock: a
            # concurrent _predict_engine fill must not read the old chunk
            # and re-publish a stale engine after this clear (the retry
            # would OOM again and burn an extra ladder rung)
            self._oom_predict_chunk = nxt
            self._engine_cache.clear()
        action = f"predict_chunk_rows -> {self._oom_predict_chunk}"
        distributed.record_degradation({
            "kind": "oom_predict", "iteration": int(self.iter),
            "level": int(self._oom_level), "action": action,
            "error": str(exc)[:200], **self._oom_memory_evidence()})
        profiling.set_gauge("predict_oom_chunk_rows",
                            float(self._oom_predict_chunk))
        log.warning(f"RESOURCE_EXHAUSTED in predict: degrading ({action}) "
                    f"and retrying")
        return True

    def _flush_flight(self, reason: str) -> Optional[str]:
        """Flush THIS booster's flight recorder (not the process-global
        one): in multi-booster processes — lgb.cv folds, bench probes —
        the module slot holds the last-configured booster's ring, and a
        fold-0 OOM post-mortem carrying fold k-1's records would
        misattribute the failure. Context-free flush paths (watchdog,
        faults._hard_exit) still use the module recorder, the best
        available without a booster in hand."""
        if self._flight is None:
            return None
        return self._flight.flush(reason)

    def _record_flight(self, flight, it: int, t0: float,
                       disp0, sc0) -> None:
        """Append one flight-recorder record for the update() that began
        at iteration ``it`` (a K-block covers several iterations; a
        failed step records completed=False with the in-flight
        iteration). Reads ONLY host-side state — phase deltas come from
        the TIMETAG scope table (empty when profiling is off), the
        cumulative coll_bytes/rows counters are the host mirrors TIMETAG
        mode already fetched, and the sentinel column is back-filled by
        the lazy drain (_judge_sentinel) when verdicts land — so the
        record never forces a device sync or an extra dispatch."""
        from .. import distributed
        from ..utils import profiling
        consumed = self.iter - it
        phases = None
        if sc0 is not None:
            phases = {}
            for name, sc in profiling.scopes().items():
                d = sc["total_s"] - sc0.get(name, {}).get("total_s", 0.0)
                if d > 0:
                    phases[name] = round(d, 6)
        sentinel = "off"
        if self.config.check_numerics:
            sentinel = "pending" if self._sentinel_pending else "ok"
        counters = profiling.counters() if sc0 is not None else {}
        hb = distributed.heartbeat_ages()
        mem = None
        if self._mem_telemetry:
            # memory snapshot per record (allocator query + /proc read —
            # host-side, zero dispatches): fields stay null where the
            # backend has no memory_stats; the same values feed the
            # always-on gauges so health_snapshot()/manifests/metrics
            # see the latest watermark without touching the ring
            mem = profiling.sample_memory()
            for key, val in mem.items():
                if val is not None:
                    profiling.set_gauge(key, float(val))
            # the peak gauge is VmHWM — the kernel's own process-lifetime
            # watermark, exact across spikes BETWEEN iteration samples
            # (a running max of sampled VmRSS would miss them) and the
            # same source bench.py / memory_snapshot() report
            rss_peak = profiling.host_rss_peak_bytes()
            if rss_peak is not None:
                profiling.set_gauge("host_rss_peak_bytes",
                                    float(rss_peak))
        flight.record(
            iteration=it, iters=max(consumed, 1),
            completed=consumed > 0,
            wall_s=time.time() - t0, phases=phases,
            dispatch=profiling.dispatch_delta(disp0) if disp0 else None,
            sentinel=sentinel, oom_level=self._oom_level,
            coll_bytes=counters.get("hist_coll_bytes"),
            rows_streamed=counters.get("hist_rows_streamed"),
            heartbeat_age=(max(hb.values()) if hb else None),
            mem=mem)
        if not flight.has_context:
            # resolved execution context, filled AFTER the first step so
            # autotune/auto-selection have settled the real method; the
            # split_fusion flag resolves through the SAME feature-block
            # the grower statics used (fb nonzero — memory-bounded
            # growth — disables the fusion, and a post-mortem claiming
            # the fused path ran would misdirect exactly the
            # memory-pressure debugging it exists for)
            hm = self._hist_method()
            fb = self._feature_block(hm)
            flight.set_context(
                backend=jax.default_backend(), boosting=self.name,
                hist_method=hm,
                split_fusion=bool(self._split_fusion_on(hm, fb)),
                quantized_grad=bool(getattr(self.config, "quantized_grad",
                                            False)),
                rounds_per_dispatch=int(getattr(
                    self.config, "boost_rounds_per_dispatch", 1)),
                num_leaves=int(self.config.num_leaves),
                tree_learner=self.config.tree_learner)
            # streaming-construct phase telemetry (sketch/bin/h2d walls,
            # peak resident raw-chunk bytes) rides the header so a
            # post-mortem names how THIS training set was built — read
            # from the dataset's own construct_stats, not the process
            # gauges, so a valid set's (or any later) construct cannot
            # wipe or substitute it; absent when the training set was
            # constructed monolithically
            construct = getattr(self.train_set, "construct_stats", None)
            if construct:
                flight.set_context(construct=dict(construct))

    def _record_aux_counters(self, aux: GrowAux) -> None:
        """Accumulate a tree's histogram-pass row count and collective
        receive volume (device adds, no sync); mirror into the profiling
        counters when TIMETAG is on (the grow_tree scope already synced,
        so the fetch is cheap there)."""
        from ..utils import profiling
        self._rows_streamed_dev = self._rows_streamed_dev + aux.rows_streamed
        self._coll_bytes_dev = self._coll_bytes_dev + aux.coll_bytes
        if profiling.enabled():
            profiling.counter("hist_rows_streamed", float(aux.rows_streamed))
            profiling.counter("hist_coll_bytes", float(aux.coll_bytes))

    @property
    def rows_streamed_total(self) -> float:
        """Rows read by histogram passes across all trees so far — the
        compaction telemetry bench.py reports next to sec_per_iter.
        Reading this syncs the device accumulator."""
        return float(self._rows_streamed_dev)

    @property
    def rows_streamed_per_tree(self) -> float:
        return self.rows_streamed_total / max(len(self.trees), 1)

    @property
    def coll_bytes_total(self) -> float:
        """Histogram-plane collective bytes received per device across all
        trees so far (see GrowAux.coll_bytes; 0 for the serial and
        feature learners). Reading this syncs the device accumulator."""
        return float(self._coll_bytes_dev)

    @property
    def coll_bytes_per_iter(self) -> float:
        return self.coll_bytes_total / max(self.iter, 1)

    def _finalize_tree(self, tree: TreeArrays, leaf_id: jax.Array,
                       class_idx: int) -> Tuple[TreeArrays, TreeArrays, bool]:
        """RenewTreeOutput + Shrinkage (gbdt.cpp:411-433). Returns the device
        tree, a host (numpy) mirror fetched in ONE batched transfer (per-array
        fetches pay a full host round-trip each — ~75ms over a TPU tunnel),
        and whether the tree has any split."""
        cfg = self.config
        t_host = jax.device_get(tree)
        num_leaves = int(t_host.num_leaves)
        had_split = num_leaves > 1
        if (had_split and self.objective is not None
                and self.objective.need_renew_tree_output):
            score = self._renew_score(class_idx)
            new_values = self.objective.renew_tree_output(
                np.asarray(leaf_id), score, num_leaves)
            if new_values is not None:
                lv = np.asarray(t_host.leaf_value).copy()
                lv[:num_leaves] = new_values
                t_host = t_host._replace(leaf_value=lv)
                tree = tree._replace(leaf_value=jnp.asarray(lv))
        lr = self.shrinkage_rate
        tree = _shrink_tree(tree, lr)
        t_host = _shrink_tree(t_host, lr)
        if cfg.check_numerics:
            self._check_numerics_leaves(t_host, num_leaves)
        return tree, t_host, had_split

    def _renew_score(self, class_idx: int) -> np.ndarray:
        """Score array used for objective leaf renewal (RF overrides with the
        constant init score, rf.hpp:133-136)."""
        return np.asarray(self.train_score if self.num_tree_per_iteration == 1
                          else self.train_score[:, class_idx], dtype=np.float64)

    def _bias_after_score(self, class_idx: int, had_split: bool) -> None:
        """Fold the boost-from-average init score into the just-stored tree
        AFTER the score update so scores are not double counted
        (reference: gbdt.cpp:404-435 — AddBias after UpdateScore for split
        trees; AsConstantTree(init) for a splitless first tree). RF overrides
        (it folds its bias per-tree in _finalize_tree, rf.hpp:135-137)."""
        first = len(self.trees) <= self.num_tree_per_iteration
        bias = self.init_scores[class_idx] if (first and self._fold_init_bias) else 0.0
        if abs(bias) <= 1e-15:
            self.tree_bias.append(0.0)
            return
        tree = self.trees[-1]
        if had_split:
            tree = tree._replace(leaf_value=tree.leaf_value + bias,
                                 node_value=tree.node_value + bias)
        else:
            tree = tree._replace(leaf_value=tree.leaf_value.at[0].set(bias))
        self.trees[-1] = tree
        old_ht = self.host_trees[-1]
        new_ht = self._make_host_tree(tree)
        if getattr(old_ht, "is_linear", False):
            # AddBias reaches leaf_const too for linear trees (tree.h:212-231)
            new_ht.is_linear = True
            new_ht.leaf_const = old_ht.leaf_const + bias
            new_ht.leaf_coeff = old_ht.leaf_coeff
            new_ht.leaf_features_raw = old_ht.leaf_features_raw
        self.host_trees[-1] = new_ht
        self._mt_cache.pop(len(self.host_trees) - 1, None)
        self._contrib_tree_cache = None      # in-place replacement
        self.tree_bias.append(bias)
        self._stacked_cache = None

    def _add_tree(self, tree: TreeArrays, leaf_id: jax.Array, class_idx: int,
                  linear: Optional[dict] = None,
                  t_host: Optional[TreeArrays] = None,
                  lazy: bool = False,
                  score_updated: bool = False) -> None:
        """Score updates for train (via leaf ids — no traversal needed) and
        valid sets (tree traversal on their binned matrices). ``linear``
        carries a fitted linear-leaf model: per-row train deltas plus the
        const/coeff tables (reference: Tree::AddPredictionToScore linear
        branch, tree.h). ``t_host`` is the already-fetched numpy mirror;
        with ``lazy`` the mirror is deferred (async copy, see host_trees);
        ``score_updated`` means the train-score update already happened
        inside the fused one-dispatch program (leaf_id may then be None)."""
        from .tree import leaf_values_of_rows
        lr = self.shrinkage_rate
        if not score_updated:
            if linear is not None:
                delta = jnp.asarray(linear["train_delta"] * lr)
            else:
                delta = leaf_values_of_rows(tree.leaf_value, leaf_id)
            if self.num_tree_per_iteration > 1:
                self.train_score = self.train_score.at[:, class_idx].add(
                    delta)
            else:
                self.train_score = self.train_score + delta
        self.trees.append(tree)
        if lazy:
            for leaf in jax.tree_util.tree_leaves(tree):
                try:
                    leaf.copy_to_host_async()
                except AttributeError:
                    pass
            self._host_trees.append(None)
            self._pending_host.append((len(self._host_trees) - 1, tree))
        else:
            self._append_host_tree(t_host if t_host is not None else tree)
        if linear is not None:
            ht = self.host_trees[-1]
            ht.is_linear = True
            ht.leaf_const = linear["const"] * lr
            ht.leaf_coeff = [[c * lr for c in cs] for cs in linear["coeff"]]
            ht.leaf_features_raw = linear["features"]
        lin_tables = None
        mt = None
        if linear is not None and self.valid_sets:
            ht = self.host_trees[-1]
            if all(getattr(vs, "raw_data_np", None) is not None
                   for vs in self.valid_sets):
                # device tables for linear-leaf valid scoring: dense
                # [L, F_total] coefficient matrix + used-feature mask so
                # per-iteration valid deltas stay on device (no host tree
                # walk per valid set per tree)
                # tables padded to the CONFIG leaf budget so the jitted
                # delta kernel compiles once, not per distinct tree size
                L = self.config.num_leaves
                nl = len(ht.leaf_value)
                ftot = self.train_set.num_total_features
                W = np.zeros((L, ftot), np.float32)
                used = np.zeros((L, ftot), np.float32)
                for li, (feats, coefs) in enumerate(
                        zip(ht.leaf_features_raw, ht.leaf_coeff)):
                    for fj, cj in zip(feats, coefs):
                        W[li, int(fj)] = np.float32(cj)
                        used[li, int(fj)] = 1.0
                lv = np.zeros((L,), np.float32)
                lv[:nl] = np.asarray(ht.leaf_value, np.float32)
                lc = np.zeros((L,), np.float32)
                lc[:nl] = np.asarray(ht.leaf_const, np.float32)
                lin_tables = (jnp.asarray(lv), jnp.asarray(lc),
                              jnp.asarray(W), jnp.asarray(used))
            else:
                from ..io.model_text import ModelTree
                mt = ModelTree.from_host(ht, self.train_set.mappers)
        for i, vs in enumerate(self.valid_sets):
            if lin_tables is not None:
                raw_dev = self._valid_raw_cache.get(i)
                if raw_dev is None:
                    raw_dev = jnp.asarray(
                        vs.raw_data_np.astype(np.float32, copy=False))
                    self._valid_raw_cache[i] = raw_dev
                leaf = predict_leaf_bins(tree, vs.bins, vs.missing_bin)
                vdelta = _linear_valid_delta(leaf, *lin_tables, raw_dev)
            elif mt is not None:
                vdelta = jnp.asarray(mt.predict(vs.raw_data_np).astype(np.float32))
            else:
                # inference-engine leg of training-time eval: traversal +
                # donated in-place add as ONE compiled program per valid
                # set (bit-identical to the eager per-op path it replaced)
                self._valid_scores[i] = _apply_valid_tree(
                    self._valid_scores[i], tree, vs.bins, vs.missing_bin,
                    np.int32(class_idx), depth=self._traversal_depth(),
                    kk=self.num_tree_per_iteration)
                continue
            if self.num_tree_per_iteration > 1:
                self._valid_scores[i] = self._valid_scores[i].at[:, class_idx].add(vdelta)
            else:
                self._valid_scores[i] = self._valid_scores[i] + vdelta
        self._stacked_cache = None

    def _fit_linear_leaves(self, tree: TreeArrays, leaf_id: jax.Array,
                           grad: jax.Array, hess: jax.Array, mask: jax.Array,
                           first_tree: bool) -> dict:
        """Fit a linear model per leaf on the raw branch features
        (reference: linear_tree_learner.cpp:173-380 CalculateLinear —
        coefficients = -(X^T H X + lambda)^{-1} X^T g per Eq 3 of
        arXiv:1802.05640, with NaN rows excluded and near-zero coefficients
        dropped). Returns pre-shrinkage const/coeff tables and per-row
        train deltas."""
        ts = self.train_set
        raw = ts.raw_data_np
        ht = self._make_host_tree(tree)
        L = ht.num_leaves
        leaf_np = np.asarray(leaf_id)
        g = np.asarray(grad, np.float64)
        h = np.asarray(hess, np.float64)
        m = np.asarray(mask) > 0
        lam = self.config.linear_lambda
        from ..binning import BIN_TYPE_NUMERICAL, K_ZERO_THRESHOLD

        # branch features per leaf (sorted unique numerical ORIGINAL indices,
        # linear_tree_learner.cpp:195-225)
        leaf_feats: List[List[int]] = [[] for _ in range(L)]
        if L > 1:
            stack = [(0, [])]
            while stack:
                node, path = stack.pop()
                inner = int(ht.split_feature[node])
                orig = int(ht.feature_indices[inner])
                is_num = (ts.mappers[orig].bin_type == BIN_TYPE_NUMERICAL)
                npath = path + ([orig] if is_num else [])
                for child in (int(ht.left_child[node]), int(ht.right_child[node])):
                    if child >= 0:
                        stack.append((child, npath))
                    else:
                        leaf_feats[~child] = sorted(set(npath))

        leaf_value = np.asarray(ht.leaf_value[:L], np.float64)
        consts = leaf_value.copy()
        coeffs: List[List[float]] = [[] for _ in range(L)]
        features: List[List[int]] = [[] for _ in range(L)]
        train_delta = leaf_value[leaf_np]

        if not first_tree:
            for leaf in range(L):
                feats = leaf_feats[leaf]
                if not feats:
                    continue
                rows = (leaf_np == leaf) & m
                Xl = raw[rows][:, feats].astype(np.float64)
                okr = ~np.isnan(Xl).any(axis=1) & ~np.isinf(Xl).any(axis=1)
                if okr.sum() < len(feats) + 1:
                    continue    # keep the plain leaf output as const
                Xl = Xl[okr]
                gl = g[rows][okr]
                hl = h[rows][okr]
                X1 = np.concatenate([Xl, np.ones((len(Xl), 1))], axis=1)
                A = X1.T @ (X1 * hl[:, None])
                A[np.arange(len(feats)), np.arange(len(feats))] += lam
                b = X1.T @ gl
                try:
                    sol = -np.linalg.solve(A, b)
                except np.linalg.LinAlgError:
                    sol = -(np.linalg.pinv(A) @ b)
                keep = [i for i in range(len(feats))
                        if abs(sol[i]) > K_ZERO_THRESHOLD]
                features[leaf] = [feats[i] for i in keep]
                coeffs[leaf] = [float(sol[i]) for i in keep]
                consts[leaf] = float(sol[-1])
                # per-row deltas for rows of this leaf (NaN rows keep the
                # plain leaf output, linear_tree_learner.cpp:19-41 semantics)
                all_rows = leaf_np == leaf
                Xa = raw[all_rows][:, features[leaf]].astype(np.float64) \
                    if features[leaf] else np.zeros((int(all_rows.sum()), 0))
                bad = (np.isnan(Xa).any(axis=1) | np.isinf(Xa).any(axis=1)) \
                    if features[leaf] else np.zeros(int(all_rows.sum()), bool)
                pred = consts[leaf] + (Xa @ np.asarray(coeffs[leaf])
                                       if features[leaf] else 0.0)
                train_delta[all_rows] = np.where(bad, leaf_value[leaf], pred)

        return {"const": consts, "coeff": coeffs, "features": features,
                "train_delta": train_delta.astype(np.float32)}

    def _make_host_tree(self, tree: TreeArrays) -> HostTree:
        ds = self.train_set
        num_leaves = int(tree.num_leaves)
        n_nodes = max(num_leaves - 1, 0)
        feats = np.asarray(tree.node_feature[:n_nodes])
        bins_thr = np.asarray(tree.node_threshold_bin[:n_nodes])
        real_thr = np.zeros(n_nodes, dtype=np.float64)
        missing = np.zeros(n_nodes, dtype=np.int8)
        if ds.bundles is not None:
            # bundle columns: map (column, bundle bin) back to the owning
            # ORIGINAL feature + its bin; the host/model tree is bundle-free
            # (saved models reference original features, like the reference's)
            seg_lo = np.asarray(tree.node_seg_lo[:n_nodes])
            dleft = np.asarray(tree.node_default_left[:n_nodes])
            orig_feats = np.zeros(n_nodes, dtype=np.int32)
            for i in range(n_nodes):
                g, t = int(feats[i]), int(bins_thr[i])
                orig = int(ds._owner_orig[g, t])
                orig_feats[i] = orig
                mapper = ds.mappers[orig]
                missing[i] = mapper.missing_type
                if seg_lo[i] >= 0:      # bundle split: map back to the
                    # member's own bin space (direction-dependent)
                    thr_tab = ds._thr_rev if dleft[i] else ds._thr_fwd
                    real_thr[i] = mapper.bin_to_value(int(thr_tab[g, t]))
                else:
                    real_thr[i] = mapper.bin_to_value(t)
            full_thr = np.zeros(tree.node_threshold_bin.shape[0],
                                dtype=np.float64)
            full_thr[:n_nodes] = real_thr
            ht = HostTree(tree, full_thr,
                          np.arange(ds.num_total_features, dtype=np.int32),
                          missing)
            ht.split_feature = orig_feats
            return ht
        used = ds.used_features
        for i in range(n_nodes):
            mapper = ds.mappers[used[feats[i]]]
            real_thr[i] = mapper.bin_to_value(int(bins_thr[i]))
            missing[i] = mapper.missing_type
        full_thr = np.zeros(tree.node_threshold_bin.shape[0], dtype=np.float64)
        full_thr[:n_nodes] = real_thr
        return HostTree(tree, full_thr, used, missing)

    def _append_host_tree(self, tree: TreeArrays) -> None:
        self.host_trees.append(self._make_host_tree(tree))

    def rollback_one_iter(self) -> None:
        """reference: gbdt.cpp:454-470 RollbackOneIter."""
        if self.iter <= 0:
            return
        self._flush_sentinel()
        self._flush_pending()
        # the popped iteration must not leave a stale stop signal behind
        self._lagged_stop = False
        self._splitless_group = -1
        self._splitless_in_group = 0
        if getattr(self, "_pre_part", False):
            # the rollback delta re-traverses the train bins, which are
            # globally sharded here; per-shard traversal is not wired up
            log.fatal("rollback_one_iter is not supported with "
                      "pre-partitioned Datasets")
        if getattr(self.train_set, "has_sparse_cols", False):
            # same reason: the traversal needs the full-width bin matrix,
            # which sparse storage no longer materializes
            log.fatal("rollback_one_iter is not supported with sparse "
                      "device storage (construct with enable_sparse=false)")
        k = self.num_tree_per_iteration
        # tree count returns to a previously-seen value after retraining,
        # so the count-keyed contrib cache would serve the popped trees
        self._contrib_tree_cache = None
        for c in range(k):
            tree = self.trees.pop()
            self.host_trees.pop()
            self._mt_cache.pop(len(self.host_trees), None)
            bias = self.tree_bias.pop() if self.tree_bias else 0.0
            class_idx = k - 1 - c
            # recompute train deltas via traversal (leaf ids not stored);
            # subtract only the pre-bias contribution (the init-score bias was
            # folded AFTER the score update, see _bias_after_score)
            delta = predict_value_bins(tree, self.train_set.bins,
                                       self.train_set.missing_bin) - bias
            if k > 1:
                self.train_score = self.train_score.at[:, class_idx].add(-delta)
            else:
                self.train_score = self.train_score - delta
            for i, vs in enumerate(self.valid_sets):
                vdelta = predict_value_bins(tree, vs.bins, vs.missing_bin) - bias
                if k > 1:
                    self._valid_scores[i] = self._valid_scores[i].at[:, class_idx].add(-vdelta)
                else:
                    self._valid_scores[i] = self._valid_scores[i] - vdelta
        self.iter -= 1
        self._stacked_cache = None

    # ------------------------------------------------- checkpoint/resume
    def get_trainer_state(self) -> dict:
        """Complete trainer state for checkpointing (see
        lightgbm_tpu/checkpoint.py): everything a resume needs to continue
        BIT-IDENTICALLY — the exact float32 score caches, device tree
        arrays, host mirrors and the stateful RNGs. Device-PRNG draws
        (bagging, GOSS, extra_trees) are fold_in(seed, iter) and need no
        state; the numpy RNGs (feature fraction; DART's drop RNG in the
        subclass) are stateful and serialize their full state."""
        self._flush_sentinel()
        self._flush_pending()
        state = {
            "name": self.name,
            "iter": int(self.iter),
            "trees": jax.device_get(self.trees),
            "host_trees": list(self._host_trees),
            "tree_bias": list(self.tree_bias),
            "init_scores": list(self.init_scores),
            "train_score": (np.asarray(self.train_score)
                            if self.train_score is not None else None),
            "valid_scores": [np.asarray(s) for s in self._valid_scores],
            "feat_rng_state": self._feat_rng.get_state(),
            "splitless_group": self._splitless_group,
            "splitless_in_group": self._splitless_in_group,
            "lagged_stop": self._lagged_stop,
            "rows_streamed": float(self._rows_streamed_dev),
            "coll_bytes": float(self._coll_bytes_dev),
            "best_score": dict(self.best_score),
            # the measured-auto histogram method and the autotuned Pallas
            # kernel shape are timing-dependent: the resumed process must
            # reuse the original run's choices or the compiled program
            # (and float accumulation order) could differ — breaking the
            # bit-identical-restart contract
            "measured_hm": getattr(self, "_measured_hm", None),
            "hist_tuned": getattr(self, "_hist_tuned", None),
            # the OOM degradation ladder's position: a resumed incarnation
            # must train with the SAME degraded configuration (block size /
            # histogram method change the accumulation shape — numerics)
            # or the bit-identical-restart contract breaks
            "oom_degrade": ({"level": self._oom_level,
                             "block": self._oom_block,
                             "hm": self._oom_hm,
                             "predict_chunk": self._oom_predict_chunk}
                            if (self._oom_level
                                or self._oom_predict_chunk) else None),
            "cegb_aux": (jax.device_get(self._cegb_aux)
                         if self._cegb_aux is not None else None),
            "loaded_iters": self.loaded_iters,
            "loaded_model_text": None,
        }
        if self.loaded is not None:
            from ..io.model_text import dump_model_text
            state["loaded_model_text"] = dump_model_text(self.loaded)
        return state

    def set_trainer_state(self, state: dict) -> None:
        """Inverse of :meth:`get_trainer_state`, applied to a freshly
        constructed booster over the same dataset/params."""
        if state.get("name") != self.name:
            log.fatal(f"checkpoint was written by "
                      f"boosting={state.get('name')!r}; this booster is "
                      f"boosting={self.name!r}")
        if len(state["valid_scores"]) != len(self._valid_scores):
            log.fatal(f"checkpoint was written with "
                      f"{len(state['valid_scores'])} validation sets; this "
                      f"run has {len(self._valid_scores)} — pass the same "
                      f"valid_sets in the same order")
        self.iter = int(state["iter"])
        self.trees = [jax.tree.map(jnp.asarray, t) for t in state["trees"]]
        self._host_trees = list(state["host_trees"])
        self._pending_host = []
        self.tree_bias = list(state["tree_bias"])
        self.init_scores = list(state["init_scores"])
        if state["train_score"] is not None:
            self.train_score = jnp.asarray(state["train_score"])
        self._valid_scores = [jnp.asarray(s) for s in state["valid_scores"]]
        self._feat_rng.set_state(state["feat_rng_state"])
        self._splitless_group = state["splitless_group"]
        self._splitless_in_group = state["splitless_in_group"]
        self._lagged_stop = state["lagged_stop"]
        self._rows_streamed_dev = jnp.float32(state["rows_streamed"])
        self._coll_bytes_dev = jnp.float32(state.get("coll_bytes", 0.0))
        self.best_score = dict(state["best_score"])
        if state.get("measured_hm") is not None:
            self._measured_hm = state["measured_hm"]
        if state.get("hist_tuned") is not None:
            self._hist_tuned = state["hist_tuned"]
        od = state.get("oom_degrade")
        if od:
            self._oom_level = int(od.get("level", 0))
            self._oom_block = int(od.get("block", 0))
            self._oom_hm = od.get("hm")
            self._oom_predict_chunk = int(od.get("predict_chunk", 0))
        if state.get("cegb_aux") is not None:
            self._cegb_aux = jax.tree.map(jnp.asarray, state["cegb_aux"])
            if getattr(self._cegb_aux, "sentinel", None) is None:
                # pre-sentinel checkpoint: the pickled aux has no sentinel
                # array; materialize the disarmed zero so the fused step's
                # operand structure stays trace-stable
                self._cegb_aux = self._cegb_aux._replace(
                    sentinel=jnp.float32(0.0))
        if state.get("loaded_model_text"):
            from ..io.model_text import load_model
            self.loaded = load_model(state["loaded_model_text"], self.config)
            self.loaded_iters = int(state["loaded_iters"])
        self._stacked_cache = None
        self._engine_cache.clear()
        self._mt_cache.clear()
        self._contrib_tree_cache = None
        self._bag_frac = None
        self._restore_bagging()

    def _restore_bagging(self) -> None:
        """Recreate the bagging mask/subset active at the restored
        iteration: the draw is keyed on the period-start iteration (see
        _update_bagging), so marking the host state stale makes the next
        iteration re-derive the exact mid-period mask — no RNG state to
        persist."""
        self._bag_stale = True

    # ------------------------------------------------------------- eval
    def eval_set(self, feval=None) -> List[Tuple[str, str, float, bool]]:
        """Evaluate all metrics on train (if configured) and valid sets.
        Returns (dataset_name, metric_name, value, bigger_is_better) tuples
        (analog of GBDT::OutputMetric, gbdt.cpp:517-575)."""
        out = []
        sets = []
        if self.config.is_provide_training_metric:
            sets.append(("training", self.train_set, self.train_score))
        for name, vs, score in zip(self.valid_names, self.valid_sets, self._valid_scores):
            sets.append((name, vs, score))
        for ds_name, ds, score in sets:
            score_np = np.asarray(score, dtype=np.float64)
            out.extend(self.eval_metrics(score_np, ds, ds_name, feval,
                                         cache=True))
        return out

    def eval_metrics(self, score_np, ds, ds_name, feval=None,
                     cache: bool = False):
        """Run every configured metric (+ optional feval) over raw scores
        for one dataset — the single metric-reporting loop eval_set and
        Booster.eval share. ``cache`` keeps the initialized Metric objects
        keyed by dataset identity (safe for the booster's own long-lived
        train/valid sets; arbitrary eval datasets skip it)."""
        out = []
        for name in self.metric_names:
            key = (name, id(ds))
            mm = self._metric_cache.get(key) if cache else None
            if mm is None:
                mm = create_metric(name, self.config)
                if mm is None:
                    continue
                mm.init(ds.get_label(), ds.get_weight(), ds.get_group())
                if cache:
                    self._metric_cache[key] = mm
            val = mm.eval(score_np, self.objective)
            if isinstance(val, (list, tuple)):
                # multi-position metrics (ndcg@k / map@k) report one
                # entry per position (reference: rank_metric.hpp name_)
                names = mm.name if isinstance(mm.name, (list, tuple)) \
                    else [mm.name] * len(val)
                for nm2, v2 in zip(names, val):
                    out.append((ds_name, nm2, float(v2),
                                mm.bigger_is_better))
            else:
                out.append((ds_name, mm.name, val, mm.bigger_is_better))
        if feval is not None:
            out.extend(_call_feval(feval, score_np, ds, self.objective,
                                   ds_name))
        return out

    # ---------------------------------------------------------- predict
    def _prep_predict_X(self, X) -> np.ndarray:
        """Predict-time feature matrix: pandas category columns are mapped
        through the train-time category lists BEFORE any array conversion
        (np.asarray on a category dtype would yield raw values, not codes).
        scipy sparse inputs pass through unchanged (binned column-wise
        without densifying).

        Input hardening: a wrong feature count, a non-numeric column, or a
        non-finite value the trained bin mappers cannot route (NaN in a
        feature trained without missing values; ±Inf in a feature whose
        value range never saw it) raises a ValueError NAMING the offending
        column/row — silently binning such values routes rows through
        arbitrary thresholds and serves garbage scores. NaN in features
        trained WITH missing handling (and in categorical features, whose
        unseen values go to the other-bin by design) stays valid.
        ``predict_disable_shape_check`` opts out of all of it (the
        reference's escape hatch for intentionally truncated inputs)."""
        from ..basic import _is_scipy_sparse, _to_2d_float
        validate = not self.config.predict_disable_shape_check
        if _is_scipy_sparse(X):
            if validate:
                self._validate_predict_matrix(X, sparse=True)
            return X
        raw = X
        X = self.train_set._pandas_to_codes(X)
        try:
            X = _to_2d_float(X)
        except (ValueError, TypeError) as e:
            self._raise_bad_dtype(raw, e)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if validate:
            self._validate_predict_matrix(X, sparse=False)
        return X

    def _raise_bad_dtype(self, raw, cause) -> None:
        """Name the first non-numeric column of a failed conversion."""
        cols = None
        if hasattr(raw, "dtypes"):          # pandas: dtypes are explicit
            for ci, dt in enumerate(raw.dtypes):
                if dt == object or str(dt).startswith(("datetime", "str")):
                    cols = ci
                    break
        elif getattr(raw, "ndim", 0) == 2:
            for ci in range(raw.shape[1]):
                try:
                    np.asarray(raw[:, ci], dtype=np.float64)
                except (ValueError, TypeError):
                    cols = ci
                    break
        where = f"feature column {cols}" if cols is not None \
            else "the input"
        raise ValueError(
            f"predict input has non-numeric data in {where}: {cause}. "
            f"Convert categoricals to codes (or pandas category dtype) "
            f"before predicting.") from cause

    def _validate_predict_matrix(self, X, sparse: bool) -> None:
        """Shape + finiteness validation against the trained mappers."""
        expected = self.train_set.num_total_features
        if X.shape[1] != expected:
            raise ValueError(
                f"predict input has {X.shape[1]} feature columns but the "
                f"model was trained with {expected} (set "
                f"predict_disable_shape_check=true to bypass)")
        mappers = self.train_set.mappers
        if sparse:
            # csr/csc/coo expose a flat numeric .data — check it in place;
            # lil/dok hold object arrays of row lists that isfinite cannot
            # take, so those canonicalize through coo (one copy)
            data = getattr(X, "data", None)
            flat = (data is not None and hasattr(data, "dtype")
                    and data.dtype.kind in "fiu")
            if flat and (data.size == 0 or bool(np.isfinite(data).all())):
                return
            coo = X.tocoo()
            vals = np.asarray(coo.data, dtype=np.float64) \
                if coo.nnz else np.zeros(0)
            bad = ~np.isfinite(vals)          # walk only the offenders
            for r, c, v in zip(coo.row[bad], coo.col[bad], vals[bad]):
                self._check_nonfinite(float(v), int(r), int(c), mappers)
            return
        # fast path: one reduction — any NaN/Inf poisons the f64 sum (an
        # inf pair cancels to NaN, which still fails isfinite); only on
        # failure walk columns. A sum of large FINITE values can overflow
        # to inf — the column scan then finds nothing and the input
        # passes. Per-column work stays vectorized: legitimate
        # missing-heavy inputs (NaN routed to missing bins) cost one
        # isfinite pass, not a Python loop over every NaN.
        with np.errstate(over="ignore", invalid="ignore"):
            total = float(np.sum(X, dtype=np.float64))
        if np.isfinite(total):
            return
        for c in range(X.shape[1]):
            col = X[:, c]
            if np.isfinite(col).all():
                continue
            # one representative per kind (NaN / +inf / -inf route
            # differently) — each either raises or is valid for ALL
            # entries of that kind in this column
            nan_rows = np.flatnonzero(np.isnan(col))
            if nan_rows.size:
                self._check_nonfinite(np.nan, int(nan_rows[0]), c, mappers)
            for sign in (np.inf, -np.inf):
                rows = np.flatnonzero(col == sign)
                if rows.size:
                    self._check_nonfinite(sign, int(rows[0]), c, mappers)

    def _check_nonfinite(self, v: float, row: int, col: int,
                         mappers) -> None:
        """Raise unless the trained mapper can route this non-finite
        value (NaN -> missing bin / categorical other-bin / linear-leaf
        fallback; Inf -> only if the training data contained it)."""
        from .. import binning
        m = mappers[col] if mappers and col < len(mappers) else None
        if m is None:
            return
        if m.bin_type == binning.BIN_TYPE_CATEGORICAL:
            return        # unseen/NaN categoricals route to the other-bin
        if np.isnan(v):
            if self.config.linear_tree:
                # linear trees define NaN prediction: any NaN feature
                # falls back to the leaf's constant output (reference:
                # LeafOutputWithLinearModel's isnan check)
                return
            if m.missing_type == binning.MISSING_NONE and not m.is_trivial:
                raise ValueError(
                    f"predict input has NaN at row {row}, feature column "
                    f"{col}, but the model was trained without missing "
                    f"values in that feature — there is no bin to route "
                    f"it to (set predict_disable_shape_check=true to "
                    f"bin it arbitrarily)")
            return
        # +/-inf: valid only if the training data actually contained it;
        # trivial (constant, unused-by-every-tree) features route nowhere
        # and stay exempt like the NaN branch
        if m.is_trivial:
            return
        seen = m.max_val if v > 0 else m.min_val
        if not np.isinf(seen):
            raise ValueError(
                f"predict input has {v:+g} at row {row}, feature column "
                f"{col}; the training data for that feature was bounded "
                f"([{m.min_val:g}, {m.max_val:g}]) — an infinite value "
                f"would bin to an arbitrary edge bin (set "
                f"predict_disable_shape_check=true to allow)")

    def _stacked(self, num_iteration: Optional[int] = None) -> Optional[TreeArrays]:
        total_iters = len(self.trees) // self.num_tree_per_iteration
        use_iters = total_iters if num_iteration is None or num_iteration <= 0 \
            else min(num_iteration, total_iters)
        n_trees = use_iters * self.num_tree_per_iteration
        if n_trees == 0:
            return None
        if self._stacked_cache is not None and self._stacked_cache[0] == n_trees:
            return self._stacked_cache[1]
        stacked = stack_trees(self.trees[:n_trees])
        self._stacked_cache = (n_trees, stacked)
        return stacked

    # ------------------------------------------------- inference engine
    def _traversal_depth(self) -> int:
        """STATIC trip-count bound for depth-bounded traversal DURING
        training (no host sync to measure the freshly grown tree): a
        leaf's depth is bounded by max_depth when set, and by
        num_leaves - 1 always."""
        cfg = self.config
        if cfg.max_depth and cfg.max_depth > 0:
            return min(cfg.max_depth, cfg.num_leaves - 1)
        return cfg.num_leaves - 1

    def _ensemble_depth(self, n_trees: int) -> int:
        """True max leaf depth over the first n_trees host mirrors — the
        engine's static fori_loop trip count, measured ONCE at engine
        build (not per predict)."""
        from .predict_engine import host_tree_depth
        d = 0
        for ht in self.host_trees[:n_trees]:
            d = max(d, host_tree_depth(ht.left_child, ht.right_child,
                                       ht.num_leaves))
        return d

    def _predict_engine(self, num_iteration: Optional[int] = None):
        """Cached device inference engine over the stacked ensemble (see
        models/predict_engine.py): depth-bounded traversal + on-device
        f64 accumulation + shape-bucketed compile cache + chunked /
        sharded serving. Invalidated by identity against the stacked
        cache, so anything that refreshes the stack (new trees, shuffle,
        rollback, checkpoint restore) rebuilds the engine."""
        from .predict_engine import PredictEngine
        with self._engine_lock:
            stacked = self._stacked(num_iteration)
            if stacked is None:
                return None
            nt = int(stacked.leaf_value.shape[0])
            hit = self._engine_cache.get(nt)
            if hit is not None and hit[0] is stacked:
                return hit[1]
            cfg = self.config
            biases = None
            if len(self.tree_bias) >= nt:
                b = np.asarray(self.tree_bias[:nt], np.float64)
                if b.size and np.any(b):
                    biases = b
            chunk = cfg.predict_chunk_rows
            if self._oom_predict_chunk:
                # OOM ladder rung 3: bound the serving program's resident
                # rows
                chunk = self._oom_predict_chunk if not chunk \
                    else min(chunk, self._oom_predict_chunk)
            eng = PredictEngine(
                stacked, self.num_tree_per_iteration, nt,
                self._ensemble_depth(nt), biases=biases,
                accum=cfg.predict_accum,
                bucket_min_rows=cfg.predict_bucket_min_rows,
                chunk_rows=chunk,
                sharded=cfg.predict_sharded)
            eng.serve_mode = self._serve_mode
            if len(self._engine_cache) >= 2:
                self._engine_cache.pop(next(iter(self._engine_cache)))
            self._engine_cache[nt] = (stacked, eng)
            return eng

    def _convert_output_jit(self):
        """The objective's output conversion as ONE jitted program (the
        eager convert_output is an op-by-op dispatch chain). Input is
        cast to the dtype the legacy host path fed it (f32 unless x64 is
        on globally), so converted outputs keep their historical bits."""
        obj = self.objective
        x64 = bool(jax.config.jax_enable_x64)
        if getattr(self, "_convert_jit_key", None) == (id(obj), x64):
            return self._convert_jit
        dt = jnp.float64 if x64 else jnp.float32
        self._convert_jit = jax.jit(lambda r: obj.convert_output(
            r.astype(dt)))
        # keyed on the objective AND the x64 flag: a flag flip must not
        # serve a stale f32-casting program (obj retained via the closure)
        self._convert_jit_key = (id(obj), x64)
        return self._convert_jit

    def score_dataset(self, ds) -> np.ndarray:
        """Raw scores for a train-aligned Dataset via traversal of its
        BINNED matrix (the mechanism Booster.eval uses for a dataset whose
        raw features were freed — the reference scores added valid sets
        through the same binned representation, score_updater.hpp)."""
        ds.construct()
        ts = self.train_set
        if ts is not None and ds is not ts and ds.reference is not ts \
                and ds.mappers is not ts.mappers:
            # tree thresholds are TRAIN-bin indices; traversing a matrix
            # binned with different mappers silently computes wrong scores
            # (the reference rejects misaligned valid data the same way)
            log.fatal("eval dataset was not binned against the training "
                      "set; construct it with reference=<train Dataset>")
        k = self.num_tree_per_iteration
        n = ds.num_data
        if self.loaded_iters > 0 or self.config.linear_tree:
            # loaded host trees / linear leaves need raw features
            raw = getattr(ds, "raw_data_np", None)
            if raw is None and ds.data is not None:
                from ..basic import _is_scipy_sparse, _to_2d_float
                raw = ds.data if _is_scipy_sparse(ds.data) else \
                    _to_2d_float(ds._pandas_to_codes(ds.data))
            if raw is None:
                log.fatal("eval with a loaded init_model or linear trees "
                          "needs raw features (construct the Dataset with "
                          "free_raw_data=False)")
            return self.predict_raw(raw)
        out = np.broadcast_to(
            np.asarray(self.init_scores, np.float64), (n, k)).copy()
        init = ds.init_score
        if init is not None:
            out = np.asarray(init, np.float64).reshape(n, k).copy()
        stacked = self._stacked()
        if stacked is not None:
            # device-resident engine: traversal + per-tree bias subtraction
            # + f64 accumulation IN TREE ORDER all on device — only the
            # [n, K] result crosses to the host (the [T, n] per-tree value
            # matrix never does), bit-identical to the former host loop
            eng = self._predict_engine()
            base = out if k > 1 else out[:, 0]
            return eng.predict(self._traversal_bins(ds), ds.missing_bin,
                               base=base)
        return out if k > 1 else out[:, 0]

    def _traversal_bins(self, ds) -> jax.Array:
        """Full-width bin matrix for tree traversal. Tree feature ids are
        LOGICAL device-column positions, but a sparse-stored Dataset's
        ``bins`` holds only the dense columns — traversing it directly
        silently scores the wrong columns (ADVICE r5 high: binary_logloss
        0.85 vs the true 0.28). Reconstruct the sparse columns from their
        (row, bin) streams + default bin, the whole-column materialization
        of SparseBin::Split's stream walk (sparse_bin.hpp). Costs the O(N)
        dense matrix sparse storage elided — the price of eval-on-train;
        cached ON the dataset so repeated eval calls pay it once per
        dataset (not per alternation) and the matrix's lifetime follows
        the dataset's (free_dataset releases it with the other device
        storage)."""
        if not getattr(ds, "has_sparse_cols", False):
            return ds.bins
        cache = getattr(ds, "_traversal_bins_cache", None)
        if cache is not None:
            return cache
        n = ds.num_data
        sp = np.asarray(ds.sp_cols)
        f_dense = ds.bins.shape[1]
        fc = f_dense + len(sp)
        dtype = np.uint8 if ds.max_num_bins <= 256 else np.int32
        full = np.zeros((n, fc), dtype)
        dense_cols = np.setdiff1d(np.arange(fc), sp)
        if f_dense:
            full[:, dense_cols] = np.asarray(ds.bins)
        rows = np.asarray(ds.sp_rows)
        vals = np.asarray(ds.sp_bins)
        defaults = np.asarray(ds.sp_default)
        for i, c in enumerate(sp):
            col = np.full(n, defaults[i], dtype)
            ok = rows[i] < n                    # stream pad = out of range
            col[rows[i][ok]] = vals[i][ok]
            full[:, int(c)] = col
        out = jnp.asarray(full)
        ds._traversal_bins_cache = out
        return out

    def predict_raw(self, X, num_iteration: Optional[int] = None,
                    start_iteration: int = 0,
                    pred_early_stop: bool = False,
                    pred_early_stop_freq: int = 10,
                    pred_early_stop_margin: float = 10.0,
                    _postprocess=None) -> np.ndarray:
        """``_predict_raw_impl`` under the OOM degradation ladder's
        predict rung: a RESOURCE_EXHAUSTED from the engine programs
        shrinks the chunk size (recorded in health_snapshot()) and
        retries instead of failing the serve call."""
        while True:
            try:
                return self._predict_raw_impl(
                    X, num_iteration, start_iteration, pred_early_stop,
                    pred_early_stop_freq, pred_early_stop_margin,
                    _postprocess)
            except Exception as e:
                if not self._maybe_degrade_predict_oom(e):
                    raise

    def _predict_raw_impl(self, X, num_iteration: Optional[int] = None,
                          start_iteration: int = 0,
                          pred_early_stop: bool = False,
                          pred_early_stop_freq: int = 10,
                          pred_early_stop_margin: float = 10.0,
                          _postprocess=None) -> np.ndarray:
        """Raw scores for new raw-feature data (binned via the train mappers;
        the analog of GBDT::PredictRaw, gbdt_prediction.cpp:13-53). The
        boost-from-average init score lives inside the first tree's leaves
        (see _bias_after_score), so prediction is a pure sum of tree outputs.
        Iterations from a loaded init model come first (gbdt.h
        num_init_iteration_). ``pred_early_stop``: margin-based per-row
        early exit — rows whose margin exceeds the threshold at a check
        round stop accumulating further trees (reference:
        prediction_early_stop.cpp:25-75, hook in gbdt_prediction.cpp)."""
        from ..utils import faults as faults_mod
        sf = faults_mod.serve_faults(self.config)
        if sf is not None:
            # serve-side injection points (deterministic, re-read per
            # dispatch): a traced delay forcing deadline/shed paths, and a
            # simulated RESOURCE_EXHAUSTED the predict-chunk degradation
            # rung (predict_raw's retry loop) must rescue
            faults_mod.maybe_slow_predict(sf)
            faults_mod.maybe_oom_predict(sf)
        X = self._prep_predict_X(X)
        if self.config.linear_tree or self.train_set.bundles is not None:
            # raw-feature prediction via the model-space trees: linear leaves
            # need raw features, and EFB-bundled datasets must not bin new
            # data through shared bundle columns (new rows may violate the
            # exclusivity the training rows satisfied — the reference also
            # predicts on raw features with real thresholds, predictor.hpp)
            from ..basic import _is_scipy_sparse
            from ..io.model_text import ModelTree
            k = self.num_tree_per_iteration
            total_iters = self.loaded_iters + len(self.trees) // k
            if num_iteration is None or num_iteration <= 0:
                end_iter = total_iters
            else:
                end_iter = min(start_iteration + num_iteration, total_iters)
            if _is_scipy_sparse(X):
                X = np.asarray(X.todense())
            out = np.zeros((X.shape[0], k), dtype=np.float64)
            active = np.ones(X.shape[0], dtype=bool)
            for it in range(start_iteration, end_iter):
                for c in range(k):
                    if it < self.loaded_iters:
                        delta = self.loaded.trees[it * k + c].predict(X)
                    else:
                        idx = (it - self.loaded_iters) * k + c
                        mt = self._mt_cache.get(idx)
                        if mt is None:
                            mt = ModelTree.from_host(self.host_trees[idx],
                                                     self.train_set.mappers)
                            self._mt_cache[idx] = mt
                        delta = mt.predict(X)
                    _accumulate_active(out, c, delta, active, pred_early_stop)
                if pred_early_stop and \
                        (it - start_iteration + 1) % pred_early_stop_freq == 0:
                    active &= ~_early_stop_mask(out, k,
                                                pred_early_stop_margin)
                    if not active.any():
                        break
            return out if k > 1 else out[:, 0]
        bins = self.train_set.bin_new_data(X)
        k = self.num_tree_per_iteration
        n = bins.shape[0]
        total_iters = self.loaded_iters + len(self.trees) // k
        # num_iteration counts iterations used FROM start_iteration
        # (reference: c_api predict semantics, gbdt.h num_iteration_for_pred_)
        if num_iteration is None or num_iteration <= 0:
            end_iter = total_iters
        else:
            end_iter = min(start_iteration + num_iteration, total_iters)
        out = np.zeros((n, k), dtype=np.float64)
        mb = self.train_set.missing_bin
        active = np.ones(n, dtype=bool)
        # iterations from a loaded init model walk host trees (their bin
        # thresholds belong to a different mapper space); the numpy walker
        # needs a dense matrix
        if start_iteration < min(end_iter, self.loaded_iters):
            from ..basic import _is_scipy_sparse
            if _is_scipy_sparse(X):
                X = np.asarray(X.todense())
        it = start_iteration
        while it < min(end_iter, self.loaded_iters):
            for c in range(k):
                delta = self.loaded.trees[it * k + c].predict(X)
                _accumulate_active(out, c, delta, active, pred_early_stop)
            it += 1
            if pred_early_stop and \
                    (it - start_iteration) % pred_early_stop_freq == 0:
                active &= ~_early_stop_mask(out, k, pred_early_stop_margin)
                if not active.any():
                    return out if k > 1 else out[:, 0]
        # own trees: the device-resident inference engine — depth-bounded
        # traversal + f64 accumulation IN TREE ORDER on device, so only
        # the [n, K] result crosses to the host (bit-identical to the
        # former host per-tree accumulation; the [T, n] per-tree value
        # matrix never leaves the device)
        if it < end_iter:
            own_end = end_iter - self.loaded_iters
            eng = self._predict_engine(own_end)
            rng = ((it - self.loaded_iters) * k, own_end * k)
            base = None
            if out.any():        # nonzero only after a loaded-model prefix
                base = out if k > 1 else out[:, 0]
            if not pred_early_stop:
                res = eng.predict(bins, mb, base=base, use_bias=False,
                                  tree_range=rng, postprocess=_postprocess)
                return np.asarray(res)
            out = self._predict_early_stop(
                eng, bins, mb, out, active, base, it, end_iter,
                start_iteration, pred_early_stop_freq,
                pred_early_stop_margin)
        res = out if k > 1 else out[:, 0]
        if _postprocess is not None:
            # degenerate window (no own trees in range): still honor the
            # requested device-side conversion
            res = np.asarray(jax.device_get(_postprocess(jnp.asarray(res))))
        return res

    def _predict_early_stop(self, eng, bins, mb, out, active, base, it,
                            end_iter, start_iteration, freq,
                            margin) -> np.ndarray:
        """Margin-based prediction early stop on the engine: the f64 carry
        stays ON DEVICE across check chunks (accumulation order unchanged
        — bit-identical to the legacy host loop), rows deactivate via a
        device select mask, and the host sees the [n, K] scores only at
        the freq-bounded check points. Rows beyond the streaming chunk
        size are processed in independent row chunks (early stop is
        per-row, so chunking is exact) — the device never holds more
        than one chunk of the feature matrix, like the plain path."""
        k = self.num_tree_per_iteration
        n = bins.shape[0]
        chunk = eng._chunk_rows(n)
        if n > chunk:
            outs = []
            for a0 in range(0, n, chunk):
                b0 = min(n, a0 + chunk)
                outs.append(self._predict_early_stop(
                    eng, bins[a0:b0], mb, out[a0:b0], active[a0:b0],
                    None if base is None else base[a0:b0], it, end_iter,
                    start_iteration, freq, margin))
            return np.concatenate(outs, axis=0)
        bucket = eng.bucket_rows(n)
        pad = bucket - n
        bins_dev = eng.prepare_bins(bins, bucket)
        carry = eng.make_carry(base, bucket)

        def upload_active(a_np):
            return eng._upload_rows(np.pad(a_np, (0, pad)) if pad
                                    else a_np, eng.sharded)

        active_dev = upload_active(active)
        while it < end_iter:
            nxt = start_iteration + ((it - start_iteration) // freq
                                     + 1) * freq
            ce = min(end_iter, nxt)
            a = (it - self.loaded_iters) * k
            b = (ce - self.loaded_iters) * k
            carry = eng.accumulate(bins_dev, mb, carry, active_dev,
                                   tree_range=(a, b), use_bias=False)
            it = ce
            if (it - start_iteration) % freq == 0 and it < end_iter:
                out = eng.fetch(carry, n).reshape(n, k)
                active &= ~_early_stop_mask(out, k, margin)
                if not active.any():
                    return out
                active_dev = upload_active(active)
        return eng.fetch(carry, n).reshape(n, k)

    def _engine_predict_ok(self) -> bool:
        """Whether predict_raw routes the WHOLE ensemble through the
        device engine with the conversion fused before the fetch (no
        host-walked prefix; RF's averaged output divides on host AFTER
        the engine sum, so its conversion cannot fuse)."""
        return (not self.config.linear_tree
                and self.train_set.bundles is None
                and self.loaded_iters == 0
                and not self.average_output
                and len(self.trees) > 0)

    def predict(self, X, raw_score: bool = False,
                num_iteration: Optional[int] = None,
                start_iteration: int = 0,
                pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0) -> np.ndarray:
        if not (raw_score or self.objective is None) \
                and not pred_early_stop and self._engine_predict_ok():
            # conversion fused on device BEFORE the single [n, K] fetch:
            # a converted full-ensemble predict is <= 3 dispatches
            # (ensemble scan, jitted conversion, row-pad slice)
            return self.predict_raw(
                X, num_iteration, start_iteration,
                _postprocess=self._convert_output_jit())
        raw = self.predict_raw(X, num_iteration, start_iteration,
                               pred_early_stop=pred_early_stop,
                               pred_early_stop_freq=pred_early_stop_freq,
                               pred_early_stop_margin=pred_early_stop_margin)
        if raw_score or self.objective is None:
            return raw
        conv = np.asarray(self._convert_output_jit()(jnp.asarray(raw)))
        return conv

    def predict_leaf(self, X, num_iteration: Optional[int] = None,
                     start_iteration: int = 0) -> np.ndarray:
        """Per-tree leaf indices (reference: predict_leaf_index path)."""
        X = self._prep_predict_X(X)
        bundled = self.train_set.bundles is not None
        # bundled datasets traverse raw features via ModelTree (see
        # predict_raw) — don't bin the prediction matrix at all
        bins = None if bundled else self.train_set.bin_new_data(X)
        k = self.num_tree_per_iteration
        total_iters = self.loaded_iters + len(self.trees) // k
        if num_iteration is None or num_iteration <= 0:
            end_iter = total_iters
        else:
            end_iter = min(start_iteration + num_iteration, total_iters)
        mb = self.train_set.missing_bin
        if bundled:
            from ..basic import _is_scipy_sparse
            from ..io.model_text import ModelTree
            if _is_scipy_sparse(X):
                X = np.asarray(X.todense())
        cols = []
        it = start_iteration
        while it < min(end_iter, self.loaded_iters):
            for c in range(k):
                cols.append(self.loaded.trees[it * k + c].leaf_index(X))
            it += 1
        if bundled:
            while it < end_iter:
                for c in range(k):
                    idx = (it - self.loaded_iters) * k + c
                    mt = self._mt_cache.get(idx)
                    if mt is None:
                        mt = ModelTree.from_host(self.host_trees[idx],
                                                 self.train_set.mappers)
                        self._mt_cache[idx] = mt
                    cols.append(mt.leaf_index(X))
                it += 1
        elif it < end_iter:
            # own trees: the engine's depth-bounded stacked traversal
            # (like predict_raw — not one round trip per tree); the [t, n]
            # leaf transfer is inherent to this API, so only the tree-range
            # chunking bounds the host buffer
            own_end = end_iter - self.loaded_iters
            eng = self._predict_engine(own_end)
            n = bins.shape[0]
            # upload the padded bin matrix ONCE; the tree-range chunks
            # below reuse the resident device copy
            bins_dev = eng.prepare_bins(bins, eng.bucket_rows(n))
            for a, b in _chunked_tree_ranges(
                    it - self.loaded_iters, own_end, k, n, itemsize=4):
                leaves = eng.leaves(bins_dev, mb, tree_range=(a, b),
                                    n_rows=n)
                cols.extend(list(leaves))            # [t, n] -> t columns
        return np.stack(cols, axis=1) if cols else np.zeros((X.shape[0], 0),
                                                            np.int32)

    def predict_contrib(self, X, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> np.ndarray:
        """SHAP feature contributions (reference: GBDT::PredictContrib via
        Tree::PredictContrib, tree.h:139; layout [N, (F+1)*k])."""
        from ..io.model_text import ModelTree
        from ..io.shap import predict_contrib_trees
        X = self._prep_predict_X(X)
        k = self.num_tree_per_iteration
        total_iters = self.loaded_iters + len(self.trees) // k
        if num_iteration is None or num_iteration <= 0:
            end_iter = total_iters
        else:
            end_iter = min(start_iteration + num_iteration, total_iters)
        mappers = self.train_set.mappers
        # reuse the converted ModelTree lists across calls, keyed by the
        # iteration window so alternating truncated/full pred_contrib calls
        # don't thrash (stable object identities also let the SHAP stack
        # cache skip its precompute)
        cache_key = (start_iteration, end_iter, len(self.trees),
                     self.loaded_iters)
        cache = getattr(self, "_contrib_tree_cache", None)
        if cache is None:
            cache = self._contrib_tree_cache = {}
        trees = cache.get(cache_key)
        if trees is None:
            trees = []
            for it in range(start_iteration, end_iter):
                for c in range(k):
                    if it < self.loaded_iters:
                        trees.append(self.loaded.trees[it * k + c])
                    else:
                        trees.append(ModelTree.from_host(
                            self.host_trees[(it - self.loaded_iters) * k + c],
                            mappers))
            if len(cache) >= 8:
                cache.pop(next(iter(cache)))
            cache[cache_key] = trees

        return predict_contrib_trees(trees, X,
                                     self.train_set.num_total_features, k,
                                     average=self.average_output)

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        """Split-count or total-gain importance per original feature
        (reference: gbdt.cpp:838+ FeatureImportance)."""
        imp = np.zeros(self.train_set.num_total_features, dtype=np.float64)
        if self.loaded is not None:
            imp += self.loaded.feature_importance(importance_type)
        for ht in self.host_trees:
            for i in range(ht.num_leaves - 1):
                real_feat = int(ht.feature_indices[ht.split_feature[i]])
                if importance_type == "split":
                    imp[real_feat] += 1.0
                else:
                    imp[real_feat] += max(float(ht.split_gain[i]), 0.0)
        return imp

    @property
    def num_trees(self) -> int:
        return len(self.trees) + self.loaded_iters * self.num_tree_per_iteration

    def current_iteration(self) -> int:
        return self.iter + self.loaded_iters


def _accumulate_active(out: np.ndarray, c: int, delta: np.ndarray,
                       active: np.ndarray, early_stop: bool) -> None:
    """Add a tree's outputs to the active rows; plain add on the hot path
    when prediction early stop is off (boolean fancy-indexing costs two
    full-size copies per tree)."""
    if not early_stop or active.all():
        out[:, c] += delta
    else:
        out[active, c] += delta[active]


def _early_stop_mask(out: np.ndarray, k: int,
                     margin_threshold: float) -> np.ndarray:
    """Rows whose prediction margin already exceeds the early-stop threshold
    (reference: prediction_early_stop.cpp — binary margin = 2|pred| (:58-66),
    multiclass margin = top1 - top2 (:29-49))."""
    if k == 1:
        margin = 2.0 * np.abs(out[:, 0])
    else:
        srt = np.sort(out, axis=1)
        margin = srt[:, -1] - srt[:, -2]
    return margin > margin_threshold


def _call_feval(feval, score_np, ds, objective, ds_name="valid"):
    """Adapt a user eval function returning (name, value, is_higher_better)
    or a list of such tuples (reference: engine.py feval protocol)."""
    results = []
    fevals = feval if isinstance(feval, (list, tuple)) else [feval]
    for fe in fevals:
        ret = fe(score_np, ds)
        rets = ret if isinstance(ret, list) else [ret]
        for name, val, bigger in rets:
            results.append((ds_name, name, float(val), bool(bigger)))
    return results
