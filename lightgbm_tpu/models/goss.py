"""Gradient-based One-Side Sampling.

TPU-native re-implementation of the reference GOSS booster
(reference: src/boosting/goss.hpp). Rows are ranked by sum over classes of
|grad * hess|; the ``top_rate`` fraction with the largest values is always
kept, a random ``other_rate`` fraction of the rest is kept with its
grad/hess amplified by (1 - top_rate_cnt/n) ... precisely
``(cnt - top_k) / other_k`` (goss.hpp:119-121), and everything else is
dropped for this iteration. No subsampling happens during the first
``1/learning_rate`` iterations (goss.hpp:158-160).

Here the selection is a vectorized mask + per-row weight (the booster's
``_sample_weights`` hook): weights are 1 for top rows, ``multiply`` for
sampled small-gradient rows, 0 for dropped rows. The histogram count channel
uses the 0/1 support of the weights, so leaf counts stay exact while
grad/hess are amplified exactly like the reference.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..basic import Dataset
from ..config import Config
from ..objectives import ObjectiveFunction
from ..utils import log
from .gbdt import GBDT


class GOSS(GBDT):
    """reference: goss.hpp:25 `class GOSS: public GBDT`."""

    name = "goss"

    def __init__(self, config: Config, train_set: Optional[Dataset] = None,
                 objective: Optional[ObjectiveFunction] = None):
        if config.top_rate + config.other_rate > 1.0:
            log.fatal("top_rate + other_rate cannot be larger than 1.0")
        if config.top_rate <= 0.0 or config.other_rate <= 0.0:
            log.fatal("top_rate and other_rate must be positive")
        if config.bagging_freq > 0 and config.bagging_fraction != 1.0:
            log.fatal("Cannot use bagging in GOSS")
        log.info("Using GOSS")
        super().__init__(config, train_set, objective)

    def _sample_weights(self, g, h) -> Optional[jax.Array]:
        """reference: goss.hpp:105-150 BaggingHelper, vectorized."""
        cfg = self.config
        if self.iter < int(1.0 / cfg.learning_rate):
            return None
        gnp = np.asarray(g, dtype=np.float64)
        hnp = np.asarray(h, dtype=np.float64)
        if gnp.ndim > 1:
            score = np.sum(np.abs(gnp * hnp), axis=1)
        else:
            score = np.abs(gnp * hnp)
        n = score.shape[0]
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        order = np.argsort(-score, kind="stable")
        top_idx = order[:top_k]
        rest_idx = order[top_k:]
        multiply = (n - top_k) / other_k
        chosen = self._bag_rng.choice(rest_idx.shape[0],
                                      size=min(other_k, rest_idx.shape[0]),
                                      replace=False)
        w = np.zeros((n,), dtype=np.float32)
        w[top_idx] = 1.0
        w[rest_idx[chosen]] = multiply
        return jnp.asarray(w)
