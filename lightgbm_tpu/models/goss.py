"""Gradient-based One-Side Sampling.

TPU-native re-implementation of the reference GOSS booster
(reference: src/boosting/goss.hpp). Rows are ranked by sum over classes of
|grad * hess|; the ``top_rate`` fraction with the largest values is always
kept, a random ``other_rate`` fraction of the rest is kept with its
grad/hess amplified by (1 - top_rate_cnt/n) ... precisely
``(cnt - top_k) / other_k`` (goss.hpp:119-121), and everything else is
dropped for this iteration. No subsampling happens during the first
``1/learning_rate`` iterations (goss.hpp:158-160).

Here the selection is a vectorized mask + per-row weight (the booster's
``_sample_weights`` hook): weights are 1 for top rows, ``multiply`` for
sampled small-gradient rows, 0 for dropped rows. The histogram count channel
uses the 0/1 support of the weights, so leaf counts stay exact while
grad/hess are amplified exactly like the reference.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..basic import Dataset
from ..config import Config
from ..objectives import ObjectiveFunction
from ..utils import log
from .gbdt import GBDT

_MAXU = jnp.uint32(0xFFFFFFFF)


def _stable_ranks(x: jax.Array) -> jax.Array:
    """rank[i] = position of element i in the ascending stable sort of x
    (equal values keep row order — argsort of an argsort inverts the
    stable sort permutation)."""
    return jnp.argsort(jnp.argsort(x))


def goss_weights_impl(score: jax.Array, key: jax.Array, top_k: int,
                      other_k: int) -> jax.Array:
    """Traced body of :func:`goss_weights` — the single definition the
    standalone jitted wrapper (unfused path) and the fused step's
    in-program sampling (gbdt._fused_step_fn, ``_fused_sampling``) share,
    so the two paths cannot drift and their draws stay bit-identical."""
    n = score.shape[0]
    svals = jnp.sort(score)
    t = svals[n - top_k]                       # k-th largest value
    strict = score > t
    c1 = jnp.sum(strict.astype(jnp.int32))
    tie = score == t
    # draws are shifted to 31 bits so real candidates always sort ahead of
    # the _MAXU filler on excluded rows
    r = jax.random.bits(key, (n,), jnp.uint32) >> 1
    # pick the (top_k - c1) ties with the smallest tie-break ranks
    rt = jnp.where(tie, r, _MAXU)
    is_top = strict | (tie & (_stable_ranks(rt) < top_k - c1))
    rest = ~is_top
    r2 = jax.random.bits(jax.random.fold_in(key, 1), (n,), jnp.uint32) >> 1
    rr = jnp.where(rest, r2, _MAXU)
    kk = min(other_k, n - top_k)               # rest count is n - top_k
    pick = rest & (_stable_ranks(rr) < kk)
    multiply = jnp.float32((n - top_k) / other_k)   # goss.hpp:119-121
    return (is_top.astype(jnp.float32)
            + pick.astype(jnp.float32) * multiply)


@functools.partial(jax.jit, static_argnames=("top_k", "other_k"))
def goss_weights(score: jax.Array, key: jax.Array, top_k: int,
                 other_k: int) -> jax.Array:
    """Per-row GOSS weights entirely on device (goss.hpp:105-150, without
    the reference's host-side argsort — at 10M rows the score download +
    single-core sort + weight upload serialized every iteration).

    Exact counts: exactly ``top_k`` rows keep weight 1 (threshold = k-th
    largest score; score ties broken by random 31-bit draws, draw
    collisions broken by row index via a stable rank — thresholding the
    draws directly would admit every colliding row, overshooting the
    targets by the collision count at 10M-row scale) and exactly
    ``min(other_k, n - top_k)`` of the rest keep the amplification weight
    (n - top_k)/other_k — the device analog of sampling without
    replacement.
    """
    return goss_weights_impl(score, key, top_k, other_k)


class GOSS(GBDT):
    """reference: goss.hpp:25 `class GOSS: public GBDT`."""

    name = "goss"
    # the fused one-dispatch step (and the boost_rounds_per_dispatch
    # K-block) admits GOSS: its sampling is pure device math keyed on the
    # iteration index, expressed in-program via goss_weights_impl — see
    # gbdt._fused_ok / _fused_step_fn
    _fused_sampling = True

    def __init__(self, config: Config, train_set: Optional[Dataset] = None,
                 objective: Optional[ObjectiveFunction] = None):
        if config.top_rate + config.other_rate > 1.0:
            log.fatal("top_rate + other_rate cannot be larger than 1.0")
        if config.top_rate <= 0.0 or config.other_rate <= 0.0:
            log.fatal("top_rate and other_rate must be positive")
        if config.bagging_freq > 0 and config.bagging_fraction != 1.0:
            log.fatal("Cannot use bagging in GOSS")
        log.info("Using GOSS")
        super().__init__(config, train_set, objective)

    # ------------------------------------------------- checkpoint/resume
    def get_trainer_state(self) -> dict:
        """GOSS adds nothing stateful to the base checkpoint: its sampling
        key is ``fold_in(PRNGKey(bagging_seed), iter)`` — fully determined
        by the restored iteration — and the 1/learning_rate warm-up gate
        depends only on ``iter``. The seed is recorded anyway so a
        tampered sidecar can't silently resample."""
        state = super().get_trainer_state()
        state["goss"] = {"bagging_seed": int(self.config.bagging_seed)}
        return state

    def set_trainer_state(self, state: dict) -> None:
        super().set_trainer_state(state)
        seed = state.get("goss", {}).get("bagging_seed")
        if seed is not None and int(seed) != int(self.config.bagging_seed):
            log.fatal(f"checkpoint GOSS bagging_seed {seed} does not match "
                      f"this run's {self.config.bagging_seed}")

    def _sample_weights(self, g, h) -> Optional[jax.Array]:
        """reference: goss.hpp:105-150 BaggingHelper — selection, weights
        and RNG all stay on device (no per-iteration host round trip)."""
        cfg = self.config
        if self.iter < int(1.0 / cfg.learning_rate):
            return None
        if g.ndim > 1:
            score = jnp.sum(jnp.abs(g * h), axis=1)
        else:
            score = jnp.abs(g * h)
        n = score.shape[0]
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        key = jax.random.fold_in(jax.random.PRNGKey(cfg.bagging_seed),
                                 self.iter)
        return goss_weights(score, key, top_k, other_k)
