"""Leaf-wise tree growth as one jitted XLA program.

TPU-native re-design of the reference's SerialTreeLearner::Train loop
(reference: src/treelearner/serial_tree_learner.cpp:158-209): leaf membership
is a per-row int32 vector instead of a permuted index partition
(data_partition.hpp:21-60), histograms are built for every
histogram-pending leaf in ONE full-data pass (ops/histogram.py), and split
search evaluates all (leaf, feature, threshold) candidates at once
(ops/split.py).

Growth proceeds in ROUNDS inside a ``lax.while_loop``; each round is either

  a TILE PASS — one data pass building histograms for a tile of up to
  ``tile_leaves`` histogram-pending leaves (ops/histogram.py); with
  ``hist_subtraction`` only the SMALLER child of each sibling pair is
  computed and the larger is derived as parent - smaller (the reference's
  subtraction trick, serial_tree_learner.cpp:311-320: the parent's histogram
  is still resident in the slot the left child inherited, tracked by
  ``parent_hist``). With a ``compaction_ladder`` the pass first gathers
  just the tile's rows into the smallest padded buffer that fits (the
  DataPartition analog — see the grow_tree docstring) so non-root passes
  stream O(pending rows), not O(N). Or,

  a SPLIT PHASE (entered when nothing is pending) — vectorized best-split
  search over all leaves, then an inner while_loop splitting leaves in gain
  order (children become histogram-pending for the next tile rounds).

Equivalence to the reference's strict leaf-wise order: tree growth is
order-independent whenever every positive-gain split fits in the
``num_leaves`` budget (the set of splits is the gain>0 closure, regardless of
order). The batched order can differ from strict best-first only in WHICH
leaves receive the final few splits when the budget binds mid-round — the
per-leaf split decisions themselves are identical.

Guards mirror BeforeFindBestSplit (serial_tree_learner.cpp:282-322): a leaf
whose count < 2*min_data_in_leaf or hessian sum < 2*min_sum_hessian_in_leaf
is never histogrammed; max_depth masks at split-search level.

Optional learner features threaded through the same jitted program:

- monotone constraints, basic mode (monotone_constraints.hpp:463-512
  BasicLeafConstraints): per-leaf [min, max] output bounds, updated with the
  children's mid-point at every split on a monotone feature;
- interaction constraints (col_sampler.hpp:20-50): per-leaf allowed-feature
  masks derived from the features used along the path and the constraint
  groups — two boolean matmuls per round;
- CEGB (cost_effective_gradient_boosting.hpp): split/coupled/lazy penalties
  as a per-(leaf, feature) additive gain adjustment;
- extra_trees (feature_histogram.hpp USE_RAND): one random threshold per
  (leaf, feature) per round;
- feature_fraction_bynode (col_sampler.hpp GetByNode): per-leaf random
  feature subset resampled every round.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.histogram import histogram_tiles
from ..ops.split import (FeatureMeta, SplitInfo, SplitParams,
                         find_best_splits)
from .tree import TreeArrays, empty_tree

NEG_INF = -jnp.inf
F32_MAX = jnp.finfo(jnp.float32).max


def advanced_child_bounds(lo, hi, out, act, monotone, num_bins: int,
                          mono_features: tuple):
    """Per-threshold child output bounds for the ADVANCED monotone mode.

    For a split of leaf ``l`` on feature ``g`` at threshold bin ``t``, the
    left child occupies the slice ``[lo[l,g], t]`` of l's region box and
    the right child ``[t+1, hi[l,g]]``. A leaf ``l'`` bounds a child when
    it overlaps the child's region in every feature except exactly one
    monotone feature where it lies strictly on one side — the same
    contiguity relation the intermediate mode applies to whole boxes,
    refined to the child region. This is the vectorized re-derivation of
    the reference's threshold-sliced constraints
    (monotone_constraints.hpp:856-1171 AdvancedLeafConstraints:
    GoUp/GoDownToFindConstrainingLeaves build FeatureMinOrMaxConstraints
    over threshold slices whose CumulativeFeatureConstraint left/right
    extrema equal these arrays at each t).

    Every contribution is monotone in t (a leaf starts or stops
    constraining at one breakpoint bin), so bounds assemble as
    scatter-extremum at the breakpoints followed by prefix/suffix
    cumulative extrema over the bin axis.

    Args:
      lo, hi: [L, F] int32 inclusive leaf region boxes in bin space.
      out: [L] current leaf outputs.
      act: [L] bool active leaves.
      monotone: [F] int8 per-feature direction.
      num_bins: static B (threshold axis length).
      mono_features: static tuple of monotone feature indices.

    Returns:
      (lmin, lmax, rmin, rmax): [L, F, B] f32 output bounds for the
      left/right child as a function of threshold bin.
    """
    L, F = lo.shape
    B = num_bins
    NEG = jnp.float32(-F32_MAX)
    POS = jnp.float32(F32_MAX)
    outf = out.astype(jnp.float32)
    size = L * F * B
    li = jnp.arange(L, dtype=jnp.int32)

    ovl = ((lo[:, None, :] <= hi[None, :, :])
           & (lo[None, :, :] <= hi[:, None, :]))          # [L, L', F]
    cnt = jnp.sum(ovl, axis=2, dtype=jnp.int32)           # [L, L']
    pair = act[:, None] & act[None, :] & ~jnp.eye(L, dtype=bool)

    # scatter planes: pre_* activates for t >= tau (prefix extremum),
    # suf_* for t <= tau (suffix extremum)
    pre_lmin = jnp.full((size,), NEG)
    suf_lmin = jnp.full((size,), NEG)
    pre_lmax = jnp.full((size,), POS)
    suf_lmax = jnp.full((size,), POS)
    pre_rmin = jnp.full((size,), NEG)
    pre_rmax = jnp.full((size,), POS)
    suf_rmin = jnp.full((size,), NEG)
    suf_rmax = jnp.full((size,), POS)

    val2 = jnp.broadcast_to(outf[None, :], (L, L))

    # ---- case A: the separating monotone feature IS the split feature g.
    # l' must overlap l in every other feature; its position relative to
    # the child SLICE in g decides the bound and the breakpoint.
    for m in mono_features:
        caseA = pair & (cnt - ovl[:, :, m].astype(jnp.int32) == F - 1)
        mpos = monotone[m] > 0
        base_idx = (li[:, None] * F + m) * B
        # LEFT child, l' strictly above the slice (lo_g(l') > t):
        # active for t <= lo_g(l') - 1
        tau = jnp.broadcast_to(lo[None, :, m] - 1, (L, L))
        idx = jnp.where(caseA & (tau >= 0), base_idx + tau, size)
        suf_lmax = suf_lmax.at[jnp.where(mpos, idx, size)].min(
            val2, mode="drop")
        suf_lmin = suf_lmin.at[jnp.where(mpos, size, idx)].max(
            val2, mode="drop")
        # LEFT child, l' strictly below the slice (== below the box,
        # since the slice shares the box's lower edge): all t
        belowb = caseA & (hi[None, :, m] < lo[:, None, m])
        idx0 = jnp.where(belowb, base_idx, size)
        pre_lmin = pre_lmin.at[jnp.where(mpos, idx0, size)].max(
            val2, mode="drop")
        pre_lmax = pre_lmax.at[jnp.where(mpos, size, idx0)].min(
            val2, mode="drop")
        # RIGHT child, l' strictly below the slice (hi_g(l') <= t):
        # active for t >= hi_g(l')
        taur = jnp.broadcast_to(hi[None, :, m], (L, L))
        idxr = jnp.where(caseA, base_idx + taur, size)
        pre_rmin = pre_rmin.at[jnp.where(mpos, idxr, size)].max(
            val2, mode="drop")
        pre_rmax = pre_rmax.at[jnp.where(mpos, size, idxr)].min(
            val2, mode="drop")
        # RIGHT child, l' strictly above the slice (== above the box): all t
        aboveb = caseA & (lo[None, :, m] > hi[:, None, m])
        idx0r = jnp.where(aboveb, base_idx, size)
        pre_rmax = pre_rmax.at[jnp.where(mpos, idx0r, size)].min(
            val2, mode="drop")
        pre_rmin = pre_rmin.at[jnp.where(mpos, size, idx0r)].max(
            val2, mode="drop")

    # ---- case B: the separator is a monotone feature m* != g; the
    # t-dependence enters through l' overlapping the child's g-slice.
    Bmin = jnp.zeros((L, L, F), bool)
    Bmax = jnp.zeros((L, L, F), bool)
    for m in mono_features:
        above = lo[None, :, m] > hi[:, None, m]
        below = hi[None, :, m] < lo[:, None, m]
        okF = ((cnt[:, :, None] - ovl.astype(jnp.int32)
                - ovl[:, :, m].astype(jnp.int32)[:, :, None]) == F - 2)
        okF = okF & (pair & (above | below))[:, :, None]
        okF = okF.at[:, :, m].set(False)          # m* == g handled by case A
        mpos = monotone[m] > 0
        is_min = jnp.where(mpos, below, above)[:, :, None]
        Bmin = Bmin | (okF & is_min)
        Bmax = Bmax | (okF & ~is_min)

    gidx = jnp.arange(F, dtype=jnp.int32)
    base3 = (li[:, None, None] * F + gidx[None, None, :]) * B    # [L, 1, F]
    val3 = jnp.broadcast_to(outf[None, :, None], (L, L, F))
    # LEFT child: needs hi_g(l') >= lo_g(l); active for t >= lo_g(l')
    okL = hi[None, :, :] >= lo[:, None, :]
    tauL = jnp.clip(jnp.broadcast_to(lo[None, :, :], (L, L, F)), 0, B - 1)
    idxL_min = jnp.where(Bmin & okL, base3 + tauL, size)
    idxL_max = jnp.where(Bmax & okL, base3 + tauL, size)
    pre_lmin = pre_lmin.at[idxL_min].max(val3, mode="drop")
    pre_lmax = pre_lmax.at[idxL_max].min(val3, mode="drop")
    # RIGHT child: needs lo_g(l') <= hi_g(l); active for t <= hi_g(l') - 1
    okR = lo[None, :, :] <= hi[:, None, :]
    tauR = jnp.broadcast_to(hi[None, :, :] - 1, (L, L, F))
    okR = okR & (tauR >= 0)
    idxR_min = jnp.where(Bmin & okR, base3 + tauR, size)
    idxR_max = jnp.where(Bmax & okR, base3 + tauR, size)
    suf_rmin = suf_rmin.at[idxR_min].max(val3, mode="drop")
    suf_rmax = suf_rmax.at[idxR_max].min(val3, mode="drop")

    def shape(x):
        return x.reshape(L, F, B)

    cmax = functools.partial(jax.lax.cummax, axis=2)
    cmin = functools.partial(jax.lax.cummin, axis=2)
    lmin = jnp.maximum(cmax(shape(pre_lmin)),
                       cmax(shape(suf_lmin), reverse=True))
    lmax = jnp.minimum(cmin(shape(pre_lmax)),
                       cmin(shape(suf_lmax), reverse=True))
    rmin = jnp.maximum(cmax(shape(pre_rmin)),
                       cmax(shape(suf_rmin), reverse=True))
    rmax = jnp.minimum(cmin(shape(pre_rmax)),
                       cmin(shape(suf_rmax), reverse=True))
    return lmin, lmax, rmin, rmax


class GrowAux(NamedTuple):
    """Cross-iteration learner state returned alongside the tree (CEGB's
    feature-used tracking is global across the boosting run,
    cost_effective_gradient_boosting.hpp:90-101), plus per-tree counters."""
    used_split: jax.Array    # [F] bool: feature used in any split (CEGB coupled)
    row_used: jax.Array      # [N, F] bool or [1, 1] dummy (CEGB lazy)
    rows_streamed: jax.Array  # f32 scalar: rows read by this tree's
                              # histogram passes (compaction telemetry)
    coll_bytes: jax.Array    # f32 scalar: histogram-plane collective bytes
                             # RECEIVED per device for this tree (the
                             # psum_scatter'd tiles of the data learner /
                             # the vote + elected-histogram psums of the
                             # voting learner; best-split syncs are O(L)
                             # scalars and not counted). Row-count
                             # independent by construction — the volume
                             # the reference's ReduceScatter moves
                             # (data_parallel_tree_learner.cpp:184-186).
                             # 0 for the serial / feature learners.
    sentinel: jax.Array = None  # f32 scalar numerics sentinel for the
                             # HISTOGRAM PLANE: nonzero when the final
                             # histogram state / per-leaf grad-hess sums /
                             # leaf outputs contain NaN/Inf. Computed
                             # IN-PROGRAM (so it sees what the Pallas/XLA
                             # histogram kernels actually accumulated,
                             # which the host-side gradient check cannot)
                             # only when the ``numerics_sentinels`` static
                             # is on; a constant 0 otherwise — zero cost
                             # and a byte-identical program with the
                             # guard off. The default exists ONLY so
                             # 4-field GrowAux pickles from pre-sentinel
                             # checkpoints (CEGB aux in state.pkl) still
                             # unpickle; set_trainer_state normalizes the
                             # None to a real f32 zero.


class GrowState(NamedTuple):
    leaf_id: jax.Array       # [N] int32
    leaf_id_sub: jax.Array   # [K] int32 (bagging subset) or [1]
    hist: jax.Array          # [L, F, B, 3]
    hist_valid: jax.Array    # [L] bool
    leaf_dead: jax.Array     # [L] bool (guard-failed, never splittable)
    leaf_sum_g: jax.Array    # [L]
    leaf_sum_h: jax.Array
    leaf_cnt: jax.Array
    leaf_output: jax.Array
    leaf_depth: jax.Array    # [L] int32
    leaf_min: jax.Array      # [L] monotone output lower bound
    leaf_max: jax.Array      # [L] monotone output upper bound
    leaf_lo: jax.Array       # [L, F] int32 region box lo (intermediate) or [1,1]
    leaf_hi: jax.Array       # [L, F] int32 region box hi (inclusive)
    used_path: jax.Array     # [L, F] bool (interaction constraints) or [1,1]
    used_split: jax.Array    # [F] bool (CEGB coupled)
    row_used: jax.Array      # [N, F] bool (CEGB lazy) or [1,1]
    sib: jax.Array           # [L] int32 sibling slot (-1 = none); the pair's
                             # parent histogram lives at slot min(l, sib[l])
    parent_hist: jax.Array   # [L] bool: slot's hist holds the PARENT's data
    done: jax.Array          # bool: a split phase found nothing to split
    forced_idx: jax.Array    # int32: next forced-split node to apply
    forced_slot: jax.Array   # [K] int32 leaf slot per forced node (-1 = dead)
    best: SplitInfo
    tree: TreeArrays
    num_leaves: jax.Array    # int32
    rounds: jax.Array        # int32
    rows_streamed: jax.Array  # f32: rows read by histogram passes so far
    coll_bytes: jax.Array    # f32: collective bytes received so far (see
                             # GrowAux.coll_bytes)


def _apply_split(state: GrowState, bins: jax.Array, binsT: jax.Array | None,
                 missing_bin: jax.Array,
                 gain_eff: jax.Array, meta: FeatureMeta, *,
                 with_monotone: bool, with_interactions: bool,
                 cegb_lazy: bool,
                 mono_intermediate: bool = False,
                 sub_bins: jax.Array | None = None,
                 sub_binsT: jax.Array | None = None,
                 sp: tuple | None = None) -> Tuple[GrowState, jax.Array]:
    """Split the current best leaf (reference: SerialTreeLearner::Split,
    serial_tree_learner.cpp:564-682 + Tree::Split, tree.h:62).

    ``sp``: sparse-column pack (sp_rows, sp_bins, sp_default, col2dense,
    col2sp, is_sparse) when some device columns live as streams — the
    split column is then reconstructed on demand for routing (the analog
    of SparseBin::Split's stream walk, sparse_bin.hpp)."""
    l = jnp.argmax(gain_eff).astype(jnp.int32)
    best = state.best
    tree = state.tree
    new_leaf = state.num_leaves
    node = state.num_leaves - 1

    feat = best.feature[l]
    thr = best.threshold[l]
    dleft = best.default_left[l]
    is_cat = best.is_cat[l]
    bitset = best.cat_bitset[l]
    mb = missing_bin[feat]
    seg_lo = best.seg_lo[l]
    seg_hi = best.seg_hi[l]

    # --- rows of leaf l route left/right. A feature-major ``binsT`` makes
    # the column extraction a contiguous dynamic slice instead of a strided
    # read of the whole row-major matrix (matters at 10M+ rows).
    def route(bins_m, binsT_m, leaf_vec):
        fidx = feat if sp is None else sp[3][feat]        # dense position
        if bins_m is not None and bins_m.shape[1] > 0:
            if binsT_m is not None:
                colv = jax.lax.dynamic_slice_in_dim(binsT_m, fidx, 1,
                                                    0)[0].astype(jnp.int32)
            else:
                colv = jnp.take(bins_m, fidx, axis=1).astype(jnp.int32)
        else:
            colv = jnp.zeros((leaf_vec.shape[0],), jnp.int32)
        if sp is not None:
            sp_rows_, sp_bins_, sp_default_, _, col2sp_, is_sp_ = sp
            scol = col2sp_[feat]
            rowsv = jax.lax.dynamic_slice_in_dim(sp_rows_, scol, 1, 0)[0]
            binsv = jax.lax.dynamic_slice_in_dim(sp_bins_, scol, 1, 0)[0]
            base = jnp.full((leaf_vec.shape[0],), sp_default_[scol],
                            jnp.int32)
            # padded stream rows index out of range and are dropped
            colv_sp = base.at[rowsv].set(binsv.astype(jnp.int32),
                                         mode="drop")
            colv = jnp.where(is_sp_[feat], colv_sp, colv)
        numl = jnp.where((colv == mb) & (mb >= 0), dleft, colv <= thr)
        # EFB bundle split: rows outside the owning member's segment are
        # its default mass and route by the default direction
        in_seg = (colv >= seg_lo) & (colv <= seg_hi)
        numl = jnp.where(seg_lo >= 0,
                         jnp.where(in_seg, colv <= thr, dleft), numl)
        # categorical: bitset membership (Tree::CategoricalDecision,
        # tree.h:349)
        word = jnp.take(bitset, colv >> 5)
        catl = ((word >> (colv & 31).astype(jnp.uint32)) & 1) == 1
        gol = jnp.where(is_cat, catl, numl)
        return jnp.where((leaf_vec == l) & ~gol, new_leaf, leaf_vec)

    in_leaf = state.leaf_id == l
    leaf_id = route(bins, binsT, state.leaf_id)
    # bagging-subset mode: the compacted in-bag rows route in parallel so
    # histogram passes stay subset-sized (GBDT subset copy,
    # gbdt.cpp:810-818 / Dataset::CopySubrow)
    leaf_id_sub = state.leaf_id_sub
    if sub_bins is not None:
        leaf_id_sub = route(sub_bins, sub_binsT, state.leaf_id_sub)

    # --- tree arrays: fix the parent link that pointed at leaf l
    parent = tree.leaf_parent[l]
    psafe = jnp.maximum(parent, 0)
    left_match = (parent >= 0) & (tree.node_left[psafe] == ~l)
    right_match = (parent >= 0) & (tree.node_right[psafe] == ~l)
    node_left = tree.node_left.at[psafe].set(
        jnp.where(left_match, node, tree.node_left[psafe]))
    node_right = tree.node_right.at[psafe].set(
        jnp.where(right_match, node, tree.node_right[psafe]))

    tree = tree._replace(
        num_leaves=state.num_leaves + 1,
        node_feature=tree.node_feature.at[node].set(feat),
        node_threshold_bin=tree.node_threshold_bin.at[node].set(thr),
        node_default_left=tree.node_default_left.at[node].set(dleft),
        node_cat=tree.node_cat.at[node].set(is_cat),
        node_cat_bitset=tree.node_cat_bitset.at[node].set(bitset),
        node_seg_lo=tree.node_seg_lo.at[node].set(seg_lo),
        node_seg_hi=tree.node_seg_hi.at[node].set(seg_hi),
        node_left=node_left.at[node].set(~l),
        node_right=node_right.at[node].set(~new_leaf),
        node_gain=tree.node_gain.at[node].set(best.gain[l]),
        node_value=tree.node_value.at[node].set(state.leaf_output[l]),
        node_weight=tree.node_weight.at[node].set(state.leaf_sum_h[l]),
        node_count=tree.node_count.at[node].set(state.leaf_cnt[l]),
        leaf_value=tree.leaf_value.at[l].set(best.left_output[l])
                                   .at[new_leaf].set(best.right_output[l]),
        leaf_weight=tree.leaf_weight.at[l].set(best.left_sum_h[l])
                                    .at[new_leaf].set(best.right_sum_h[l]),
        leaf_count=tree.leaf_count.at[l].set(best.left_count[l])
                                  .at[new_leaf].set(best.right_count[l]),
        leaf_depth=tree.leaf_depth.at[l].set(state.leaf_depth[l] + 1)
                                  .at[new_leaf].set(state.leaf_depth[l] + 1),
        leaf_parent=tree.leaf_parent.at[l].set(node).at[new_leaf].set(node),
    )

    new_depth = state.leaf_depth[l] + 1

    # monotone basic-mode bound update (monotone_constraints.hpp:485-501):
    # children inherit the parent's bounds; a split on a monotone feature
    # tightens them around the children's mid-point
    leaf_min, leaf_max = state.leaf_min, state.leaf_max
    if with_monotone:
        mono = meta.monotone[feat].astype(jnp.int32)
        mono = jnp.where(is_cat, 0, mono)
        mid = (best.left_output[l] + best.right_output[l]) / 2.0
        pmin, pmax = leaf_min[l], leaf_max[l]
        # leaf keeps the LEFT child, new_leaf the RIGHT child
        lmax = jnp.where(mono > 0, jnp.minimum(pmax, mid), pmax)
        lmin = jnp.where(mono < 0, jnp.maximum(pmin, mid), pmin)
        rmin = jnp.where(mono > 0, jnp.maximum(pmin, mid), pmin)
        rmax = jnp.where(mono < 0, jnp.minimum(pmax, mid), pmax)
        leaf_min = leaf_min.at[l].set(lmin).at[new_leaf].set(rmin)
        leaf_max = leaf_max.at[l].set(lmax).at[new_leaf].set(rmax)

    # intermediate monotone mode tracks per-leaf bin-interval boxes: a
    # numerical split partitions the split feature's interval; categorical
    # splits leave both children's boxes unchanged (conservative overlap,
    # like the reference's always-go-down categorical handling,
    # monotone_constraints.hpp GoDownToFindLeavesToUpdate)
    leaf_lo, leaf_hi = state.leaf_lo, state.leaf_hi
    if mono_intermediate:
        parent_lo, parent_hi = leaf_lo[l], leaf_hi[l]
        num_split = ~is_cat
        lhi = jnp.where((jnp.arange(parent_hi.shape[0]) == feat) & num_split,
                        jnp.minimum(parent_hi, thr), parent_hi)
        rlo = jnp.where((jnp.arange(parent_lo.shape[0]) == feat) & num_split,
                        jnp.maximum(parent_lo, thr + 1), parent_lo)
        leaf_lo = leaf_lo.at[new_leaf].set(rlo)
        leaf_hi = leaf_hi.at[l].set(lhi).at[new_leaf].set(parent_hi)

    used_path = state.used_path
    if with_interactions:
        parent_used = state.used_path[l].at[feat].set(True)
        used_path = used_path.at[l].set(parent_used).at[new_leaf].set(parent_used)

    used_split = state.used_split.at[feat].set(True)

    row_used = state.row_used
    if cegb_lazy:
        row_used = row_used | (in_leaf[:, None]
                               & (jnp.arange(row_used.shape[1]) == feat)[None, :])

    state = state._replace(
        leaf_id=leaf_id,
        leaf_id_sub=leaf_id_sub,
        tree=tree,
        hist_valid=state.hist_valid.at[l].set(False).at[new_leaf].set(False),
        leaf_sum_g=state.leaf_sum_g.at[l].set(best.left_sum_g[l])
                                   .at[new_leaf].set(best.right_sum_g[l]),
        leaf_sum_h=state.leaf_sum_h.at[l].set(best.left_sum_h[l])
                                   .at[new_leaf].set(best.right_sum_h[l]),
        leaf_cnt=state.leaf_cnt.at[l].set(best.left_count[l])
                               .at[new_leaf].set(best.right_count[l]),
        leaf_output=state.leaf_output.at[l].set(best.left_output[l])
                                     .at[new_leaf].set(best.right_output[l]),
        leaf_depth=state.leaf_depth.at[l].set(new_depth)
                                   .at[new_leaf].set(new_depth),
        leaf_min=leaf_min, leaf_max=leaf_max,
        leaf_lo=leaf_lo, leaf_hi=leaf_hi,
        used_path=used_path, used_split=used_split, row_used=row_used,
        # slot l inherits the parent's histogram data (the basis of the
        # subtraction trick, serial_tree_learner.cpp:311-320)
        sib=state.sib.at[l].set(new_leaf).at[new_leaf].set(l),
        parent_hist=state.parent_hist.at[l].set(True).at[new_leaf].set(False),
        num_leaves=state.num_leaves + 1,
    )
    gain_eff = gain_eff.at[l].set(NEG_INF).at[new_leaf].set(NEG_INF)
    return state, gain_eff


# the static (compile-time) grow options — ONE definition shared by the
# monolithic grow_tree jit and the phased per-round programs
_GROW_STATICS = ("max_leaves", "num_bins", "max_depth", "hist_method",
                 "exact", "axis_name", "with_categorical", "with_monotone",
                 "mono_mode", "mono_features",
                 "with_interactions", "cegb_mode", "extra_trees",
                 "use_bynode", "tile_leaves", "hist_block",
                 "hist_subtraction", "feature_block",
                 "feature_axis_name", "feature_shards", "voting",
                 "vote_top_k", "hist_dp", "sp_cols",
                 "compaction_ladder", "hist_interpret",
                 "numerics_sentinels", "split_fusion")


def _grower_fns(bins: jax.Array, grad: jax.Array, hess: jax.Array,
              sample_mask: jax.Array, meta: FeatureMeta, params: SplitParams,
              feature_mask: jax.Array, missing_bin: jax.Array, *,
              max_leaves: int, num_bins: int, max_depth: int = -1,
              hist_method: str = "scatter",
              exact: bool = False,
              with_categorical: bool = False,
              with_monotone: bool = False,
              mono_mode: str = "basic",
              mono_features: tuple = (),
              with_interactions: bool = False,
              interaction_groups: jax.Array | None = None,
              cegb_mode: str = "off",
              cegb_coupled: jax.Array | None = None,
              cegb_lazy_penalty: jax.Array | None = None,
              cegb_state: GrowAux | None = None,
              extra_trees: bool = False,
              use_bynode: bool = False,
              bynode_fraction: jax.Array | None = None,
              rng_key: jax.Array | None = None,
              axis_name: str | None = None,
              binsT: jax.Array | None = None,
              sub_idx: jax.Array | None = None,
              sub_bins: jax.Array | None = None,
              sub_binsT: jax.Array | None = None,
              tile_leaves: int = 0,
              hist_block: int = 0,
              hist_subtraction: bool = True,
              feature_block: int = 0,
              feature_axis_name: str | None = None,
              feature_shards: int = 1,
              voting: bool = False,
              vote_top_k: int = 20,
              bundle_meta=None,
              forced_splits=None,
              hist_dp: bool = False,
              sp_cols: tuple = (),
              sp_rows: jax.Array | None = None,
              sp_bins: jax.Array | None = None,
              sp_default: jax.Array | None = None,
              compaction_ladder: tuple = (),
              hist_interpret: bool = False,
              numerics_sentinels: bool = False,
              split_fusion: bool = False,
              ) -> dict:
    """Build the grow program's phase functions (closure factory).

    ``grow_tree`` runs them inside one jitted ``lax.while_loop``;
    ``grow_tree_phased`` runs the SAME functions as separate per-round
    jitted programs so each phase is host-timeable (the hist_pass /
    split_search / apply_split TIMETAG sub-scopes). Grow one tree;
    finalize returns (tree arrays, per-row leaf index, aux state).

    Args:
      bins: [N, F] binned features (device-resident, uint8/int32).
      grad, hess: [N] objective gradients/hessians (weights folded in,
        reference: ObjectiveFunction::GetGradients).
      sample_mask: [N] f32 0/1 bagging mask (mask-based bagging keeps shapes
        static; the analog of GBDT::Bagging's index subset, gbdt.cpp:228-262).
      feature_mask: [F] f32 0/1 from column sampling (col_sampler.hpp).
      missing_bin: [F] int32 default-routed bin per feature or -1.
      exact: strict best-first order (one split per histogram round) — the
        reference's exact leaf-wise semantics even when the num_leaves budget
        binds, at the cost of one histogram pass per split. The default
        batched mode performs all available splits per round (see module
        docstring for the equivalence argument).
      interaction_groups: [G, F] bool group membership when
        with_interactions.
      cegb_mode: "off" | "feat" (split+coupled penalties) | "lazy" (adds the
        per-row on-demand costs); cegb_state carries the cross-iteration
        used-feature tracking.
      rng_key: PRNG key, consumed when extra_trees or use_bynode.
      axis_name: when set, rows are sharded over this mesh axis (shard_map
        context): root sums and histogram tiles are psum'd over it — the SPMD
        analog of the reference data-parallel learner's root allreduce
        (data_parallel_tree_learner.cpp:125-152) and histogram ReduceScatter
        (:184-186). All devices then take identical split decisions with no
        further communication.
      binsT: optional [F, N] feature-major copy of ``bins`` for contiguous
        per-split column extraction during routing (recommended on TPU).
      tile_leaves: max pending leaves per histogram pass (the "onehot"
        backend's pass cost is flat in this up to ~42 at 256 bins x 3 stats;
        scatter/binloop backends use one pass for everything regardless).
      hist_subtraction: build only the smaller sibling's histogram and derive
        the larger by subtraction from the parent (the reference's trick,
        serial_tree_learner.cpp:311-320). Subtraction is exact for the count
        channel and float32-rounded for grad/hess (the reference subtracts in
        float64; its GPU path is float32 like ours).
      compaction_ladder: static ascending tuple of row-buffer sizes for the
        LEAF-PARTITIONED ROW COMPACTION path — the shape-static analog of
        the reference's permuted per-leaf row partition
        (data_partition.hpp:21-60; the optimization both GPU boosting
        papers build on: arXiv:1706.08359 §4, arXiv:1806.11248 §3.3).
        Before a tile pass the pending tile's rows are counted; the first
        rung that fits gets a prefix-sum gather of just those rows
        (ops/histogram.py compact_rows) and the histogram streams only the
        buffer — with ``hist_subtraction`` every non-root pass covers the
        SMALLER siblings, so <= N/2 rows fit from depth 1 and the covered
        row count shrinks geometrically with depth, restoring the
        reference's O(N * depth) histogram asymptotics. The full-N pass
        remains the fallback rung (chosen via lax.cond inside the jitted
        while_loop, so every rung is compiled once). Empty = always
        full-N. Serial learner only.
      split_fusion: the fused split-finding epilogue + frontier batching
        (ISSUE 12): every tile pass ALSO reduces each (leaf, feature) to
        its best numerical split candidate — in kernel on the Pallas
        methods (ops/pallas_hist.py epilogue kernels), via the identical
        XLA twin elsewhere — with sibling pairs sharing the launch on
        adjacent slot pairs and the larger child's plane derived in-pass
        as parent - smaller. state.best is maintained incrementally and
        the split phase consumes it directly: no [L, F, B, S] plane ever
        re-enters the search. Bit-identical trees to the classic phase
        (the parity suite pins it); serial learner, numerical non-bundled
        search only (see the gate asserts — the gbdt layer resolves
        Config.split_fusion="auto" off when unsupported).
      feature_block: > 0 engages the MEMORY-BOUNDED mode for wide datasets:
        no [L, F, B, 3] histogram state is kept at all — each pending leaf
        is histogrammed and searched immediately, ``feature_block`` columns
        at a time into a transient [P, Fb, B, 3] buffer, and only its best
        SplitInfo is retained (the analog of the reference's capped
        HistogramPool, feature_histogram.hpp:1095-1290: a full pool miss
        for every leaf). Costs ~2x the histogram passes (no parent
        subtraction) in exchange for O(P * Fb * B) transient memory.
        Serial learner only; CEGB, forced splits, box-mode monotone
        constraints, voting and the bagging subset copy are unsupported.
      feature_axis_name: feature-ownership mesh axis. Set WITHOUT axis_name
        (rows replicated) = the feature-parallel learner (reference:
        feature_parallel_tree_learner.cpp:59-78): each device histograms and
        searches only its own feature slice and the per-leaf best splits are
        merged with an allreduce-argmax (sync_best_splits). Set EQUAL to
        axis_name (rows sharded too) = the data-parallel learner with the
        reference's ReduceScatter communication pattern
        (data_parallel_tree_learner.cpp:184-186): histogram tiles are
        psum_scatter'd so each device receives only its owned features'
        global histograms, searches those, and syncs the best split —
        1/D the allreduce volume.
      feature_shards: number of feature slices (= size of feature_axis_name
        axis); the caller pads features so F divides evenly.
      voting: voting-parallel learner over ``axis_name`` (reference:
        voting_parallel_tree_learner.cpp PV-tree): histograms stay LOCAL to
        each row shard; each device votes its local top ``vote_top_k``
        features per leaf (local stats, min_data scaled by 1/D,
        voting_parallel_tree_learner.cpp:62-64), the vote elects 2*top_k
        features globally (GlobalVoting, :151-182), and only the elected
        features' histograms are summed across devices before the final
        search (CopyLocalHistogram, :184+).
    """
    n, f_dense = bins.shape
    f_sp = len(sp_cols)
    # f is the LOGICAL device-column count: meta/feature_mask/missing_bin
    # and the histogram planes span all columns; ``bins`` holds only the
    # dense ones (sparse columns live as (row, bin) streams, see
    # Dataset._maybe_extract_sparse). Plane placement and routing go
    # through the static sp_cols positions.
    f = f_dense + f_sp
    if f_sp:
        assert (feature_axis_name is None and axis_name is None
                and not voting and feature_block == 0
                and sub_idx is None), (
            "sparse device storage is serial-only (construct with "
            "enable_sparse=false for parallel learners)")
        sp_np = np.asarray(sp_cols, dtype=np.int32)
        dense_np = np.asarray(
            [c for c in range(f) if c not in set(sp_cols)], dtype=np.int32)
        col2dense_np = np.zeros((f,), dtype=np.int32)
        col2dense_np[dense_np] = np.arange(len(dense_np), dtype=np.int32)
        col2sp_np = np.zeros((f,), dtype=np.int32)
        col2sp_np[sp_np] = np.arange(f_sp, dtype=np.int32)
        is_sp_np = np.zeros((f,), dtype=bool)
        is_sp_np[sp_np] = True
        sp_pack = (sp_rows, sp_bins, sp_default,
                   jnp.asarray(col2dense_np), jnp.asarray(col2sp_np),
                   jnp.asarray(is_sp_np))
    else:
        sp_np = dense_np = None
        sp_pack = None
    if compaction_ladder:
        assert (axis_name is None and feature_axis_name is None
                and not voting and feature_block == 0), (
            "hist compaction is serial-only; the caller must pass an empty "
            "ladder for parallel/blocked learners")
        assert tuple(sorted(compaction_ladder)) == tuple(compaction_ladder), (
            "compaction_ladder must be ascending")
    if split_fusion:
        assert (axis_name is None and feature_axis_name is None
                and not voting and feature_block == 0), (
            "split_fusion is serial-only; the caller resolves 'auto' off "
            "for parallel/blocked learners")
        assert (not with_categorical and bundle_meta is None
                and forced_splits is None and cegb_mode == "off"
                and not extra_trees and not use_bynode and not hist_dp
                and not f_sp), (
            "split_fusion covers the numerical non-bundled search only "
            "(no categorical/EFB/forced-splits/CEGB/extra_trees/bynode/"
            "f64/sparse) — those semantics stay in find_best_splits and "
            "the caller resolves 'auto' off when they apply")
        assert (not with_monotone) or mono_mode == "basic", (
            "split_fusion supports only basic monotone constraints")
    L = max_leaves
    tile_leaves = tile_leaves or 42     # 0 = auto
    P = min(tile_leaves, L) if hist_method.startswith(("onehot", "pallas")) \
        else L
    cat_words = max(1, -(-num_bins // 32))
    cegb_lazy = cegb_mode == "lazy"
    cegb_on = cegb_mode != "off"

    # --- feature-ownership slicing (FP learner, and DP's reduce-scatter)
    fp_mode = feature_axis_name is not None
    dp_scatter = fp_mode and (feature_axis_name == axis_name)
    if voting:
        assert axis_name is not None, "voting requires row sharding"
        assert not fp_mode, "voting and feature slicing are exclusive"
    if fp_mode:
        assert f % feature_shards == 0, (
            f"features {f} not divisible into {feature_shards} shards "
            f"(pad in the caller)")
        f_loc = f // feature_shards
        off = jax.lax.axis_index(feature_axis_name) * f_loc
        meta_s = FeatureMeta(*(jax.lax.dynamic_slice_in_dim(a, off, f_loc, 0)
                               for a in meta))
        missing_bin_s = jax.lax.dynamic_slice_in_dim(missing_bin, off, f_loc, 0)
        # FP replicates rows and histograms only the local slice; DP-scatter
        # histograms the full width locally, then psum_scatter assigns slices
        bins_h = (bins if dp_scatter
                  else jax.lax.dynamic_slice(bins, (jnp.int32(0), off),
                                             (n, f_loc)))
        binsT_h = None if binsT is None else (
            binsT if dp_scatter
            else jax.lax.dynamic_slice_in_dim(binsT, off, f_loc, 0))
    else:
        f_loc, off = f, None
        meta_s, missing_bin_s = meta, missing_bin
        bins_h = bins
        binsT_h = binsT

    def slice_f(arr):
        """Slice a per-feature trailing axis to the local feature shard."""
        if not fp_mode or arr is None:
            return arr
        return jax.lax.dynamic_slice_in_dim(arr, off, f_loc, arr.ndim - 1)

    # EFB bundle structure is per-feature on the LEADING axis; owner shards
    # search their own bundle columns (the reference's distributed learners
    # operate on the same bundled Dataset object on every machine)
    bundle_s = bundle_meta
    if fp_mode and bundle_meta is not None:
        bundle_s = type(bundle_meta)(
            *(jax.lax.dynamic_slice_in_dim(a, off, f_loc, 0)
              for a in bundle_meta))

    # hist_dp: float64 histogram accumulation, the reference CPU precision
    # model (hist_t, bin.h:32) / the gpu_use_dp flag's double mode; needs
    # jax x64 (the caller warns otherwise)
    hist_dtype = jnp.float64 if hist_dp else jnp.float32
    use_subset = sub_idx is not None
    if use_subset:
        # bagging subset copy (gbdt.cpp:810-818): histograms and root sums
        # run over the compacted in-bag rows only — pass cost scales with
        # the bagging fraction instead of full N. Full-row routing still
        # happens for the out-of-bag score update. Serial learner only.
        assert not fp_mode and not voting and axis_name is None, (
            "bagging subset copy is serial-only; distributed learners use "
            "the mask path")
        g_sub = jnp.take(grad, sub_idx)
        h_sub = jnp.take(hess, sub_idx)
        stats = jnp.stack([g_sub, h_sub, jnp.ones_like(g_sub)],
                          axis=1).astype(hist_dtype)
        bins_h = sub_bins
        binsT_h = sub_binsT
    else:
        stats = jnp.stack(
            [grad * sample_mask, hess * sample_mask, sample_mask],
            axis=1).astype(hist_dtype)

    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)

    # quantized-gradient mode (opt-in, histogram_method=*_q8): grad/hess
    # quantize to int8 with per-tree scales and stochastic rounding, so the
    # histogram contraction runs on the int8 MXU path (~2x bf16 rate) with
    # EXACT integer accumulation; counts stay exact 0/1. The re-design of
    # LightGBM 4.x quantized training for the MXU (not in the v3.2
    # reference — a forward-compatible fast path).
    quant8 = hist_method in ("pallas_q8", "onehot_q8")
    q_scale = None
    if quant8:
        assert not hist_dp, "q8 and f64 histograms are exclusive"
        # int32 accumulation bound: a cell summing |q| <= 127 per row wraps
        # past 2^31 only beyond ~16.9M rows per shard (static shape check)
        assert n <= (2 ** 31 - 1) // 127, (
            f"quantized histograms overflow int32 beyond "
            f"{(2**31 - 1) // 127} rows per shard (got {n}); use the "
            f"pallas_hilo method at this scale")
        sg = jnp.maximum(jnp.max(jnp.abs(stats[:, 0])), 1e-12)
        sh = jnp.maximum(jnp.max(jnp.abs(stats[:, 1])), 1e-12)
        if axis_name is not None:
            sg = jax.lax.pmax(sg, axis_name)
            sh = jax.lax.pmax(sh, axis_name)
        q_scale = jnp.stack([sg / 127.0, sh / 127.0,
                             jnp.float32(1.0)]).astype(jnp.float32)
        u = jax.random.uniform(jax.random.fold_in(rng_key, 0x5138),
                               stats.shape)
        stats = jnp.clip(jnp.floor(stats / q_scale[None, :] + u),
                         -127, 127).astype(jnp.int8)
        root = jnp.sum(stats.astype(jnp.float32), axis=0) * q_scale
    else:
        root = jnp.sum(stats, axis=0)
    if axis_name is not None:
        root = jax.lax.psum(root, axis_name)
    from ..ops.split import calculate_leaf_output
    root_out = calculate_leaf_output(root[0], root[1], params, root[2],
                                     jnp.float32(0.0))

    iota_l = jnp.arange(L, dtype=jnp.int32)
    # "intermediate" and "advanced" both maintain leaf region boxes and
    # recompute exact bounds each phase; "advanced" additionally derives
    # per-threshold child bounds for the numerical search
    mono_intermediate = with_monotone and mono_mode in ("intermediate",
                                                        "advanced")
    mono_advanced = with_monotone and mono_mode == "advanced"
    # intermediate-mode constraints are recomputed from ALL current leaf
    # outputs at the start of each split phase, so the strict one-split-per-
    # phase order is required for soundness (the reference re-searches the
    # leaves_to_update set after every split, monotone_constraints.hpp:565)
    exact = exact or mono_intermediate

    blocked = feature_block > 0
    if blocked:
        assert not fp_mode and not voting and axis_name is None, (
            "feature-blocked mode is serial-only")
        assert not cegb_on and forced_splits is None, (
            "feature-blocked mode does not support CEGB or forced splits")
        assert not mono_intermediate, (
            "feature-blocked mode supports only basic monotone constraints")
        assert not use_subset and not hist_dp and not quant8, (
            "feature-blocked mode: bagging subset copy / f64 / q8 "
            "histograms unsupported")
        hist_subtraction = False    # no resident parent histograms

    def _zero_best_direct() -> SplitInfo:
        """All -inf placeholder without materializing a [L, F, B, 3] zeros
        histogram (which is exactly what blocked mode must avoid). Sum and
        output fields carry ``hist_dtype`` so the while_loop state matches
        what split_phase's find_best_splits returns (f64 under hist_dp)."""
        zi = jnp.zeros((L,), jnp.int32)
        zs = jnp.zeros((L,), hist_dtype)
        return SplitInfo(
            gain=jnp.full((L,), NEG_INF, jnp.float32),
            feature=zi, threshold=zi,
            default_left=jnp.zeros((L,), bool),
            left_sum_g=zs, left_sum_h=zs, left_count=zs,
            right_sum_g=zs, right_sum_h=zs, right_count=zs,
            left_output=zs, right_output=zs,
            is_cat=jnp.zeros((L,), bool),
            cat_bitset=jnp.zeros((L, cat_words), jnp.uint32),
            seg_lo=jnp.full((L,), -1, jnp.int32),
            seg_hi=jnp.full((L,), -1, jnp.int32))

    def init_state() -> GrowState:
        zf = functools.partial(jnp.zeros, dtype=hist_dtype)
        # the placeholder best is never read before the first split phase
        # replaces it wholesale (gain_eff also masks on hist_valid, all
        # False here); building it directly instead of running
        # find_best_splits over a constant zero histogram avoids multi-
        # second XLA constant folds of the whole split search at compile
        # time (observed: 6+ s per folded reduce-window in the r4 logs)
        zero_best = _zero_best_direct()
        if cegb_state is not None:
            used_split = cegb_state.used_split
            row_used = cegb_state.row_used
        else:
            used_split = jnp.zeros((f,), bool)
            row_used = jnp.zeros((n, f) if cegb_lazy else (1, 1), bool)
        return GrowState(
            leaf_id=jnp.zeros((n,), jnp.int32),
            leaf_id_sub=jnp.zeros((sub_idx.shape[0],) if use_subset else (1,),
                                  jnp.int32),
            hist=jnp.zeros((1, 1, 1, 1) if blocked
                           else (L, f_loc, num_bins, 3), hist_dtype),
            hist_valid=jnp.zeros((L,), bool),
            leaf_dead=jnp.zeros((L,), bool),
            leaf_sum_g=zf((L,)).at[0].set(root[0]),
            leaf_sum_h=zf((L,)).at[0].set(root[1]),
            leaf_cnt=zf((L,)).at[0].set(root[2]),
            leaf_output=zf((L,)).at[0].set(root_out),
            leaf_depth=jnp.zeros((L,), jnp.int32),
            leaf_min=jnp.full((L,), -F32_MAX, hist_dtype),
            leaf_max=jnp.full((L,), F32_MAX, hist_dtype),
            leaf_lo=jnp.zeros((L, f) if mono_intermediate else (1, 1),
                              jnp.int32),
            leaf_hi=(jnp.broadcast_to(meta.num_bins[None, :] - 1, (L, f))
                     .astype(jnp.int32) if mono_intermediate
                     else jnp.zeros((1, 1), jnp.int32)),
            used_path=jnp.zeros((L, f) if with_interactions else (1, 1), bool),
            used_split=used_split,
            row_used=row_used,
            sib=jnp.full((L,), -1, jnp.int32),
            parent_hist=jnp.zeros((L,), bool),
            done=jnp.bool_(False),
            forced_idx=jnp.int32(0),
            forced_slot=(jnp.full((forced_splits[0].shape[0],), -1,
                                  jnp.int32).at[0].set(0)
                         if forced_splits is not None
                         else jnp.full((1,), -1, jnp.int32)),
            best=zero_best,
            tree=empty_tree(L, cat_words),
            num_leaves=jnp.int32(1),
            rounds=jnp.int32(0),
            rows_streamed=jnp.float32(0.0),
            coll_bytes=jnp.float32(0.0),
        )

    def active_mask(state: GrowState) -> jax.Array:
        return iota_l < state.num_leaves

    def pending_mask(state: GrowState) -> jax.Array:
        return (active_mask(state) & ~state.hist_valid & ~state.leaf_dead)

    # each forced node consumes one round even when its subtree is dead, so
    # the cap grows by the forced-node count (otherwise a forcedsplits file
    # with more nodes than ~3*L silently truncates growth)
    k_forced = forced_splits[0].shape[0] if forced_splits is not None else 0
    max_rounds = 3 * L + 8 + k_forced

    def outer_cond(state: GrowState) -> jax.Array:
        # keep looping while there is histogram work or more splits may come;
        # ``done`` is set by a split phase that split nothing
        more = jnp.any(pending_mask(state)) | ~state.done
        return (state.num_leaves < L) & more & (state.rounds < max_rounds)

    def leaf_feature_mask(state: GrowState, round_key) -> jax.Array:
        """Per-(leaf, feature) validity: global column sampling x interaction
        constraints x per-node sampling."""
        fmask = feature_mask
        if fmask.ndim == 1:
            fmask = jnp.broadcast_to(fmask[None, :], (L, f))
        out = fmask.astype(bool)
        if with_interactions:
            # allowed[l] = union of groups containing every used feature of l
            # (col_sampler.hpp interaction filtering): two boolean matmuls
            grp = interaction_groups.astype(jnp.float32)        # [G, F]
            used = state.used_path.astype(jnp.float32)          # [L, F]
            viol = used @ (1.0 - grp).T                          # [L, G] >0 bad
            ok = (viol < 0.5).astype(jnp.float32)
            allowed = (ok @ grp) > 0.5                           # [L, F]
            out = out & allowed
        if use_bynode:
            # per-leaf random subset of ceil(frac * F) features per round
            # (col_sampler.hpp GetByNode resamples per node)
            u = jax.random.uniform(jax.random.fold_in(round_key, 1), (L, f))
            k = jnp.maximum(
                jnp.ceil(bynode_fraction * f).astype(jnp.int32), 1)
            rank = jnp.argsort(jnp.argsort(u, axis=1), axis=1)
            out = out & (rank < k)
        return out

    def cegb_adjust(state: GrowState) -> jax.Array | None:
        """CEGB delta per (leaf, feature) subtracted from stored gains
        (cost_effective_gradient_boosting.hpp:66-84 DetlaGain)."""
        if not cegb_on:
            return None
        delta = (params.cegb_tradeoff * params.cegb_penalty_split
                 * state.leaf_cnt)[:, None]                      # [L, 1]
        delta = jnp.broadcast_to(delta, (L, f))
        if cegb_coupled is not None:
            delta = delta + jnp.where(state.used_split[None, :], 0.0,
                                      params.cegb_tradeoff
                                      * cegb_coupled[None, :])
        if cegb_lazy and cegb_lazy_penalty is not None:
            onehot = jax.nn.one_hot(state.leaf_id, L, dtype=jnp.float32)
            unused = 1.0 - state.row_used.astype(jnp.float32)    # [N, F]
            cnt_unused = onehot.T @ unused                       # [L, F]
            if axis_name is not None:
                cnt_unused = jax.lax.psum(cnt_unused, axis_name)
            delta = delta + (params.cegb_tradeoff
                             * cegb_lazy_penalty[None, :] * cnt_unused)
        return delta

    def combine_sparse(tile, sel, hist_leaf_ids, stats):
        """Histogram planes for the sparse columns: an O(nnz) scatter-add
        of the non-default (row, bin) stream entries plus reconstruction of
        the elided default bin from per-slot totals — the reference's
        most_freq elision + FixHistogram (reference: sparse_bin.hpp
        ConstructHistogram; FixHistogram decl dataset.h:506). Returns the
        full [P, f, B, S] tile with dense planes at their column ids."""
        acc = jnp.int32 if quant8 else hist_dtype
        S = stats.shape[1]
        valid = sp_rows < n                                   # [F_sp, M]
        rclip = jnp.minimum(sp_rows, n - 1)
        ent_leaf = hist_leaf_ids[rclip]                       # [F_sp, M]
        # leaf -> tile slot via an O(L) lookup table (a [F_sp, M, P]
        # equality tensor would dwarf the histogram itself at scale);
        # inactive sel entries (-1) park their writes at index L, which no
        # ent_leaf value ever reads
        slot_map = jnp.full((L + 1,), P, jnp.int32).at[
            jnp.where(sel >= 0, sel, L)].set(
                jnp.arange(P, dtype=jnp.int32))
        slot = slot_map[ent_leaf]
        st = jnp.where(valid[:, :, None], stats[rclip].astype(acc), 0)
        col = jnp.arange(f_sp, dtype=jnp.int32)[:, None]
        idx = (slot * f_sp + col) * num_bins + sp_bins.astype(jnp.int32)
        flat = jnp.zeros(((P + 1) * f_sp * num_bins, S), acc)
        flat = flat.at[idx.reshape(-1)].add(st.reshape(-1, S))
        sp_t = flat.reshape(P + 1, f_sp, num_bins, S)[:P]
        # per-slot totals: any dense column's plane partitions all rows;
        # without one, reduce the stats by slot directly
        if f_dense > 0:
            totals = tile[:, 0].sum(axis=1)                   # [P, S]
        else:
            eq_all = (hist_leaf_ids[:, None] == sel[None, :])
            totals = jnp.einsum("np,ns->ps", eq_all.astype(acc),
                                stats.astype(acc))
        others = sp_t.sum(axis=2)                             # [P, F_sp, S]
        defm = (jnp.arange(num_bins, dtype=jnp.int32)[None, :]
                == sp_default[:, None])                       # [F_sp, B]
        recon = (totals[:, None, :] - others)[:, :, None, :]
        sp_t = jnp.where(defm[None, :, :, None], recon, sp_t)
        full = jnp.zeros((P, f, num_bins, S), acc)
        full = full.at[:, dense_np].set(tile)
        return full.at[:, sp_np].set(sp_t)

    def tile_pass(state: GrowState) -> GrowState:
        """One histogram pass for a tile of up to P pending leaves, with the
        larger sibling of each computed pair derived by subtraction."""
        pending = pending_mask(state)
        sibc = jnp.maximum(state.sib, 0)
        has_sib = state.sib >= 0
        p_slot = jnp.minimum(iota_l, sibc)
        sib_pending = pending[sibc] & has_sib
        if hist_subtraction:
            # compute only the smaller of a derivable pair (reference picks
            # the smaller child, serial_tree_learner.cpp:311-320)
            derivable = (pending & sib_pending & state.parent_hist[p_slot])
            cnt_sib = state.leaf_cnt[sibc]
            is_smaller = ((state.leaf_cnt < cnt_sib)
                          | ((state.leaf_cnt == cnt_sib) & (iota_l < sibc)))
            cand = pending & (~derivable | is_smaller)
        else:
            cand = pending

        # first P candidate slots (ascending slot id)
        order = jnp.argsort(jnp.where(cand, iota_l, L + iota_l))
        chosen = order[:P].astype(jnp.int32)
        chosen_ok = cand[chosen]
        sel = jnp.where(chosen_ok, chosen, -1)

        hist_leaf_ids = state.leaf_id_sub if use_subset else state.leaf_id
        n_rows = hist_leaf_ids.shape[0]

        def full_pass():
            t = histogram_tiles(bins_h, stats, hist_leaf_ids, sel,
                                num_bins, method=hist_method,
                                dtype=hist_dtype,
                                binsT=binsT_h, block=hist_block,
                                interpret=hist_interpret)
            return t, jnp.float32(n_rows)

        if f_dense > 0 and compaction_ladder:
            # leaf-partitioned row compaction (see the compaction_ladder
            # docstring): count the tile's rows via an O(L) slot lookup,
            # then dispatch to the smallest precompiled rung that fits
            slot_map = jnp.full((L + 1,), P, jnp.int32).at[
                jnp.where(sel >= 0, sel, L)].set(
                    jnp.arange(P, dtype=jnp.int32))
            in_tile = slot_map[hist_leaf_ids] < P
            n_pend = jnp.sum(in_tile, dtype=jnp.int32)

            # every rung hands histogram_tiles the row-INDEX buffer: the
            # Pallas kernels gather the rows IN KERNEL from the
            # HBM-resident full arrays (pallas_hist fusion 2 — no
            # compacted [F, m] copy exists), while the XLA backends expand
            # the same buffer with exactly compact_rows' semantics (same
            # stable order, clamp, -2 leaf fill) — one rung definition,
            # no branch pair to keep in sync
            def compact_pass(m):
                def fn():
                    from ..ops.histogram import compact_indices
                    idx = compact_indices(in_tile, m)
                    t = histogram_tiles(bins_h, stats, hist_leaf_ids,
                                        sel, num_bins,
                                        method=hist_method,
                                        dtype=hist_dtype,
                                        binsT=binsT_h, block=hist_block,
                                        gather_idx=idx,
                                        interpret=hist_interpret)
                    return t, jnp.float32(m)
                return fn

            # nest largest-first so the OUTERMOST cond tests the smallest
            # rung: if n_pend <= m_small take it, else fall through
            branch = full_pass
            for m in sorted(compaction_ladder, reverse=True):
                branch = (lambda m=m, nxt=branch:
                          jax.lax.cond(n_pend <= m, compact_pass(m),
                                       lambda: nxt()))
            tile, streamed = branch()
        elif f_dense > 0:
            tile, streamed = full_pass()
        else:
            tile = jnp.zeros((P, 0, num_bins, stats.shape[1]),
                             jnp.int32 if quant8 else hist_dtype)
            streamed = jnp.float32(n_rows)    # sparse streams still walk
                                              # the full leaf-id vector
        if f_sp:
            tile = combine_sparse(tile, sel, hist_leaf_ids, stats)
        # collective-volume accounting (GrowAux.coll_bytes): logical
        # histogram payload received per device per pass — a STATIC
        # quantity (tile shapes are static), so the counter costs one
        # scalar add and is independent of row count by construction
        hist_itemsize = 4 if quant8 else (8 if hist_dp else 4)
        tile_bytes = int(np.prod(tile.shape)) * hist_itemsize
        coll = 0.0
        if dp_scatter:
            # the reference DP learner reduce-scatters histograms so each
            # machine receives only its owned features' global sums
            # (data_parallel_tree_learner.cpp:184-186) — 1/D the volume of a
            # full allreduce
            tile = jax.lax.psum_scatter(tile, axis_name,
                                        scatter_dimension=1, tiled=True)
            coll = tile_bytes / feature_shards
        elif axis_name is not None and not voting:
            tile = jax.lax.psum(tile, axis_name)
            coll = tile_bytes
        if quant8:
            # collectives ran on exact int32 sums; dequantize once here.
            # The product passes the rounding fence so the sibling
            # subtraction below cannot FMA-contract it (ops/split.py
            # _round_fence — keeps q8 ladder-invariant and bit-matched
            # with the fused epilogue's identically-fenced dequant)
            from ..ops.split import _round_fence
            tile = _round_fence(
                tile.astype(hist_dtype) * q_scale[None, None, None, :],
                params)

        computed = jnp.zeros((L,), bool).at[chosen].set(chosen_ok)
        buf = jnp.zeros_like(state.hist).at[chosen].set(
            jnp.where(chosen_ok[:, None, None, None], tile, 0.0))
        hist = jnp.where(computed[:, None, None, None], buf, state.hist)
        if hist_subtraction:
            # sibling = parent - computed (parent hist still resident at
            # p_slot in state.hist, untouched by this round's writes)
            derived = (pending & ~computed & computed[sibc]
                       & state.parent_hist[p_slot] & has_sib)
            parent_vals = jnp.take(state.hist, p_slot, axis=0)
            sib_vals = jnp.take(buf, sibc, axis=0)
            hist = jnp.where(derived[:, None, None, None],
                             parent_vals - sib_vals, hist)
            resolved = computed | derived
        else:
            resolved = computed
        return state._replace(
            hist=hist,
            hist_valid=state.hist_valid | resolved,
            parent_hist=state.parent_hist & ~resolved,
            rounds=state.rounds + 1,
            rows_streamed=state.rows_streamed + streamed,
            coll_bytes=state.coll_bytes + jnp.float32(coll))

    def tile_pass_fused(state: GrowState) -> GrowState:
        """Frontier-batched histogram pass WITH the fused split epilogue
        (split_fusion): sibling pairs share the launch on adjacent slot
        pairs — the computed (smaller) child at even slots, the derived
        sibling at odd slots, its plane built in-pass as parent - computed
        so it costs no data pass — and the per-(leaf, feature) best-split
        candidates come back alongside the planes
        (ops/histogram.py histogram_tiles_with_candidates). state.best is
        updated in place for every resolved leaf, so the split phase
        never re-reads the [L, F, B, S] planes."""
        from ..ops.histogram import histogram_tiles_with_candidates
        from ..ops.pallas_hist import (pack_feature_meta, pack_leaf_aux,
                                       pack_scan_params)
        from ..ops.split import candidates_to_splitinfo
        pending = pending_mask(state)
        sibc = jnp.maximum(state.sib, 0)
        has_sib = state.sib >= 0
        p_slot = jnp.minimum(iota_l, sibc)
        sib_pending = pending[sibc] & has_sib
        if hist_subtraction:
            derivable = (pending & sib_pending & state.parent_hist[p_slot])
            cnt_sib = state.leaf_cnt[sibc]
            is_smaller = ((state.leaf_cnt < cnt_sib)
                          | ((state.leaf_cnt == cnt_sib) & (iota_l < sibc)))
            cand = pending & (~derivable | is_smaller)
            npairs = max(P // 2, 1)
            order = jnp.argsort(jnp.where(cand, iota_l, L + iota_l))
            chosen = order[:npairs].astype(jnp.int32)
            chosen_ok = cand[chosen]
            sel_even = jnp.where(chosen_ok, chosen, -1)
            partner = sibc[chosen].astype(jnp.int32)
            partner_ok = chosen_ok & derivable[chosen]
            sel_odd = jnp.where(partner_ok, partner, -1)
            sel = jnp.stack([sel_even, sel_odd], axis=1).reshape(-1)
            derive = jnp.stack([jnp.zeros_like(partner_ok), partner_ok],
                               axis=1).reshape(-1)
        else:
            order = jnp.argsort(jnp.where(pending, iota_l, L + iota_l))
            chosen = order[:P].astype(jnp.int32)
            chosen_ok = pending[chosen]
            sel = jnp.where(chosen_ok, chosen, -1)
            derive = jnp.zeros((P,), bool)
        p2 = sel.shape[0]
        selc = jnp.maximum(sel, 0)
        ok = sel >= 0

        hist_leaf_ids = state.leaf_id_sub if use_subset else state.leaf_id
        n_rows = hist_leaf_ids.shape[0]

        # parent planes for the derived slots: the one plane-sized read
        # the in-pass subtraction needs (the parent's histogram is still
        # resident at the slot the left child inherited)
        parent_planes = jnp.where(
            derive[:, None, None, None],
            jnp.take(state.hist, p_slot[selc], axis=0).astype(jnp.float32),
            0.0)

        la = pack_leaf_aux(
            state.leaf_sum_g[selc], state.leaf_sum_h[selc],
            state.leaf_cnt[selc], state.leaf_output[selc],
            state.leaf_min[selc].astype(jnp.float32) if with_monotone
            else None,
            state.leaf_max[selc].astype(jnp.float32) if with_monotone
            else None)
        fm_pack = pack_feature_meta(meta.num_bins, meta.missing_type,
                                    meta.default_bin, meta.monotone)
        pvec = pack_scan_params(params)
        sel_compute = jnp.where(derive, -1, sel)

        from ..ops.histogram import (derive_and_scan, epilogue_supported,
                                     histogram_tiles)
        in_kernel = epilogue_supported(hist_method, binsT_h, p2,
                                       stats.shape[1], hist_dtype,
                                       hist_interpret)

        def fused_pass(gather_idx, streamed):
            def fn():
                if in_kernel:
                    # the whole epilogue runs IN KERNEL: the candidate
                    # table comes back with the planes, per rung branch
                    tile, tab = histogram_tiles_with_candidates(
                        bins_h, stats, hist_leaf_ids, sel, derive,
                        parent_planes, la, fm_pack, pvec, num_bins,
                        method=hist_method, block=hist_block,
                        dtype=hist_dtype, binsT=binsT_h,
                        gather_idx=gather_idx, interpret=hist_interpret,
                        with_monotone=with_monotone, q_scale=q_scale)
                else:
                    # XLA twin: the rung branches return only the raw
                    # tile; the (identical) derive + scan runs ONCE
                    # after the cond, so it compiles once per grower,
                    # not once per rung
                    tile = histogram_tiles(
                        bins_h, stats, hist_leaf_ids, sel_compute,
                        num_bins, method=hist_method, block=hist_block,
                        dtype=hist_dtype, binsT=binsT_h,
                        gather_idx=gather_idx, interpret=hist_interpret)
                    tab = None
                return tile, tab, jnp.float32(streamed)
            return fn

        if f_dense > 0 and compaction_ladder:
            slot_map = jnp.full((L + 1,), p2, jnp.int32).at[
                jnp.where(sel_compute >= 0, sel_compute, L)].set(
                    jnp.arange(p2, dtype=jnp.int32))
            in_tile = slot_map[hist_leaf_ids] < p2
            n_pend = jnp.sum(in_tile, dtype=jnp.int32)

            def compact_pass(m):
                def fn():
                    from ..ops.histogram import compact_indices
                    idx = compact_indices(in_tile, m)
                    return fused_pass(idx, m)()
                return fn

            branch = fused_pass(None, n_rows)
            for m in sorted(compaction_ladder, reverse=True):
                branch = (lambda m=m, nxt=branch:
                          jax.lax.cond(n_pend <= m, compact_pass(m),
                                       lambda: nxt()))
            tile, tab, streamed = branch()
        else:
            tile, tab, streamed = fused_pass(None, n_rows)()
        if not in_kernel:
            tile, tab = derive_and_scan(
                tile, derive, parent_planes, la, fm_pack, pvec,
                q8=quant8, q_scale=q_scale, with_monotone=with_monotone)

        # scatter planes (computed AND derived — both stay resident as
        # the next level's parents) and the per-leaf bests
        slots = jnp.where(ok, sel, L)
        buf = jnp.zeros_like(state.hist).at[slots].set(
            jnp.where(ok[:, None, None, None], tile.astype(hist_dtype),
                      0.0), mode="drop")
        resolved = jnp.zeros((L,), bool).at[slots].set(ok, mode="drop")
        hist = jnp.where(resolved[:, None, None, None], buf, state.hist)

        round_key = jax.random.fold_in(rng_key, state.rounds)
        fmask_sel = leaf_feature_mask(state, round_key)[selc]
        info = candidates_to_splitinfo(
            tab, state.leaf_sum_g[selc], state.leaf_sum_h[selc],
            state.leaf_cnt[selc], state.leaf_output[selc],
            state.leaf_depth[selc], meta, params, fmask_sel, max_depth,
            cat_words, with_monotone=with_monotone,
            leaf_min=(state.leaf_min[selc].astype(jnp.float32)
                      if with_monotone else None),
            leaf_max=(state.leaf_max[selc].astype(jnp.float32)
                      if with_monotone else None))

        def scat(cur, new):
            return cur.at[slots].set(new.astype(cur.dtype), mode="drop")

        new_best = SplitInfo(*(scat(c, nb)
                               for c, nb in zip(state.best, info)))
        return state._replace(
            hist=hist, best=new_best,
            hist_valid=state.hist_valid | resolved,
            parent_hist=state.parent_hist & ~resolved,
            rounds=state.rounds + 1,
            rows_streamed=state.rows_streamed + streamed)

    def intermediate_bounds(state: GrowState) -> GrowState:
        """Exact per-leaf output bounds from ALL current leaf outputs and
        the leaf region boxes — the vectorized re-derivation of the
        reference's intermediate-mode constraint maintenance
        (monotone_constraints.hpp:514-698 IntermediateLeafConstraints: its
        GoUp/GoDown contiguity walk incrementally maintains the same
        pairwise relations this computes from scratch each phase). A pair
        (l, l') constrains l when their boxes overlap in every feature
        except a monotone one where l' lies strictly on one side."""
        out = state.leaf_output.astype(jnp.float32)
        act = active_mask(state)
        lo, hi = state.leaf_lo, state.leaf_hi               # [L, F]
        # overlap COUNT over all features reduces without materializing the
        # [L, L, F] tensor; the per-feature pair masks are only needed for
        # the (static, usually few) monotone-constrained features
        cnt = jnp.sum((lo[:, None, :] <= hi[None, :, :])
                      & (lo[None, :, :] <= hi[:, None, :]),
                      axis=2, dtype=jnp.int32)               # [L, L']
        mf = jnp.asarray(mono_features, jnp.int32)           # [Fm] static
        lo_m, hi_m = lo[:, mf], hi[:, mf]                    # [L, Fm]
        ovl_m = ((lo_m[:, None, :] <= hi_m[None, :, :])
                 & (lo_m[None, :, :] <= hi_m[:, None, :]))
        except_f = (cnt[:, :, None] - ovl_m.astype(jnp.int32)) == (f - 1)
        below = hi_m[None, :, :] < lo_m[:, None, :]          # l' below l
        above = lo_m[None, :, :] > hi_m[:, None, :]
        mono = meta.monotone[mf].astype(jnp.int32)
        up = (mono > 0)[None, None, :]
        dn = (mono < 0)[None, None, :]
        pair_ok = (act[:, None, None] & act[None, :, None] & except_f)
        lb_mask = jnp.any(pair_ok & ((up & below) | (dn & above)), axis=2)
        ub_mask = jnp.any(pair_ok & ((up & above) | (dn & below)), axis=2)
        lb = jnp.max(jnp.where(lb_mask, out[None, :], -F32_MAX), axis=1)
        ub = jnp.min(jnp.where(ub_mask, out[None, :], F32_MAX), axis=1)
        return state._replace(leaf_min=lb.astype(state.leaf_min.dtype),
                              leaf_max=ub.astype(state.leaf_max.dtype))

    def adv_bounds_sliced(state: GrowState):
        """Advanced per-threshold child bounds, built over the GLOBAL
        feature axis (leaf boxes are global state) then sliced to this
        shard's owned feature window like every other per-feature input."""
        adv = advanced_child_bounds(
            state.leaf_lo, state.leaf_hi, state.leaf_output,
            active_mask(state), meta.monotone, num_bins, mono_features)
        if fp_mode:
            adv = tuple(jax.lax.dynamic_slice_in_dim(a, off, f_loc, 1)
                        for a in adv)
        return adv

    def split_search(state: GrowState) -> GrowState:
        """Best-split search over all resident histograms -> state.best.
        Under ``split_fusion`` the search already happened in the tile
        passes' epilogues (state.best is incrementally maintained), so
        this reduces to the round bookkeeping."""
        if split_fusion:
            return state._replace(rounds=state.rounds + 1)
        adv = None
        if mono_intermediate:
            state = intermediate_bounds(state)
            if mono_advanced:
                adv = adv_bounds_sliced(state)
        round_key = jax.random.fold_in(rng_key, state.rounds)
        fmask = slice_f(leaf_feature_mask(state, round_key))
        rand_bin = None
        if extra_trees:
            # one random threshold per (leaf, feature) per search
            # (feature_histogram.hpp USE_RAND rand.NextInt); drawn over the
            # GLOBAL feature space so all shards agree, then sliced
            nbm = jnp.maximum(meta.num_bins - 2, 1)
            u = jax.random.uniform(jax.random.fold_in(round_key, 2), (L, f))
            rand_bin = slice_f((u * nbm[None, :]).astype(jnp.int32))

        search_hist = state.hist
        search_fmask = fmask
        coll = 0.0
        if voting:
            # PV-tree election (voting_parallel_tree_learner.cpp:137-182):
            # local per-feature gains from LOCAL histograms and local leaf
            # sums (min_data guards scaled by 1/D, :62-64) -> local top-k
            # vote -> global top-2k electorate -> psum only elected columns
            lsum = jnp.sum(state.hist[:, 0, :, :], axis=1)     # [L, 3] local
            ndev = jax.lax.psum(jnp.float32(1.0), axis_name)
            params_vote = params._replace(
                min_data_in_leaf=params.min_data_in_leaf / ndev,
                min_sum_hessian_in_leaf=params.min_sum_hessian_in_leaf / ndev)
            _, fgain = find_best_splits(
                state.hist, lsum[:, 0], lsum[:, 1], lsum[:, 2],
                state.leaf_output, state.leaf_depth, meta_s, params_vote,
                fmask, max_depth, with_categorical=with_categorical,
                cat_words=cat_words, rand_bin=rand_bin, bundle=bundle_s,
                return_feature_gains=True)
            kk = min(vote_top_k, f)
            k2 = min(2 * vote_top_k, f)
            rank_local = jnp.argsort(jnp.argsort(-fgain, axis=1), axis=1)
            local_top = (rank_local < kk) & jnp.isfinite(fgain)
            votes = jax.lax.psum(local_top.astype(jnp.float32), axis_name)
            # elect top 2k by vote count, ties to the lower feature index
            key = votes * (f + 1) - jnp.arange(f, dtype=jnp.float32)[None, :]
            el_idx = jnp.argsort(-key, axis=1)[:, :k2].astype(jnp.int32)
            el_onehot = (el_idx[:, :, None]
                         == jnp.arange(f, dtype=jnp.int32)[None, None, :]
                         ).astype(jnp.float32)                  # [L, 2k, F]
            # HIGHEST precision: the selector is exact 0/1 but default TPU
            # matmul precision would bf16-round the histogram values
            hist_el = jnp.einsum("lkf,lfbs->lkbs", el_onehot, state.hist,
                                 precision=jax.lax.Precision.HIGHEST)
            hist_el = jax.lax.psum(hist_el, axis_name)          # [L, 2k, B, S]
            search_hist = jnp.einsum("lkf,lkbs->lfbs", el_onehot, hist_el,
                                     precision=jax.lax.Precision.HIGHEST)
            elected = jnp.sum(el_onehot, axis=1) > 0.5          # [L, F]
            fm2 = fmask if fmask.ndim == 2 else jnp.broadcast_to(
                fmask[None, :], (L, f))
            search_fmask = (fm2.astype(bool) & elected).astype(jnp.float32)
            # GlobalVoting communication: the vote tally allreduce plus the
            # elected columns' histogram sum (CopyLocalHistogram analog) —
            # the only histogram-plane collectives in the voting learner
            hist_itemsize = 8 if hist_dp else 4
            coll = (L * f * 4
                    + L * k2 * num_bins * int(state.hist.shape[3])
                    * hist_itemsize)

        best = find_best_splits(
            search_hist, state.leaf_sum_g, state.leaf_sum_h,
            state.leaf_cnt, state.leaf_output,
            state.leaf_depth, meta_s, params,
            search_fmask, max_depth,
            with_categorical=with_categorical, cat_words=cat_words,
            leaf_min=state.leaf_min if with_monotone else None,
            leaf_max=state.leaf_max if with_monotone else None,
            adv_bounds=adv,
            gain_adjust=slice_f(cegb_adjust(state)),
            rand_bin=rand_bin, bundle=bundle_s)
        if fp_mode:
            # local feature index -> global, then allreduce-argmax of the
            # per-leaf bests (reference: SyncUpGlobalBestSplit,
            # parallel_tree_learner.h:191-214)
            from ..ops.split import sync_best_splits
            best = best._replace(feature=best.feature + off)
            best = sync_best_splits(best, feature_axis_name)
        return state._replace(best=best, rounds=state.rounds + 1,
                              coll_bytes=state.coll_bytes
                              + jnp.float32(coll))

    def split_apply(state: GrowState) -> GrowState:
        """Apply every available split from state.best (gain order via the
        inner while_loop; one split under ``exact``)."""
        num_leaves_before = state.num_leaves
        gain_eff = jnp.where(active_mask(state) & state.hist_valid
                             & ~state.leaf_dead, state.best.gain, NEG_INF)
        state = apply_splits(state, gain_eff, dict(
            with_monotone=with_monotone,
            with_interactions=with_interactions,
            cegb_lazy=cegb_lazy,
            mono_intermediate=mono_intermediate,
            sub_bins=sub_bins, sub_binsT=sub_binsT, sp=sp_pack))
        return state._replace(done=state.num_leaves == num_leaves_before)

    def split_phase(state: GrowState) -> GrowState:
        return split_apply(split_search(state))

    def forced_phase(state: GrowState) -> GrowState:
        """Apply one forced split (reference: SerialTreeLearner::ForceSplits,
        serial_tree_learner.cpp:450-562): the node's (feature, threshold)
        goes through the regular split machinery with the candidate set
        restricted to the forced bin and min_gain disabled, so sums and
        missing/default semantics are exact; a forced split its constraints
        reject is skipped along with its whole subtree."""
        adv = None
        if mono_intermediate:
            state = intermediate_bounds(state)
            if mono_advanced:
                adv = adv_bounds_sliced(state)
        ff, ft, fl, fr = forced_splits
        k_idx = state.forced_idx
        l = state.forced_slot[k_idx]
        lsafe = jnp.maximum(l, 0)
        # ff holds GLOBAL feature indices; under feature slicing only the
        # owning shard's mask lights up and the result syncs below
        fidx = jnp.arange(f_loc, dtype=jnp.int32)
        if fp_mode:
            fidx = fidx + off
        fmask_forced = (fidx == ff[k_idx]).astype(jnp.float32)
        # forced means forced: the reference gathers the threshold's sums
        # directly (GatherInfoForThreshold) without min_gain/min_data
        # screening, aborting only on gain < 0
        params_forced = params._replace(
            min_gain_to_split=jnp.float32(-1e30),
            min_data_in_leaf=jnp.float32(0.0),
            min_sum_hessian_in_leaf=jnp.float32(0.0))
        best = find_best_splits(
            state.hist, state.leaf_sum_g, state.leaf_sum_h,
            state.leaf_cnt, state.leaf_output, state.leaf_depth,
            meta_s, params_forced, fmask_forced, max_depth,
            with_categorical=False, cat_words=cat_words,
            leaf_min=state.leaf_min if with_monotone else None,
            leaf_max=state.leaf_max if with_monotone else None,
            adv_bounds=adv,
            rand_bin=jnp.full((L, f_loc), ft[k_idx], jnp.int32),
            bundle=bundle_s)
        if fp_mode:
            from ..ops.split import sync_best_splits
            best = best._replace(feature=best.feature + off)
            best = sync_best_splits(best, feature_axis_name)
        ok = ((l >= 0) & (state.num_leaves < L)
              & state.hist_valid[lsafe] & ~state.leaf_dead[lsafe]
              & jnp.isfinite(best.gain[lsafe]))
        new_leaf = state.num_leaves
        state = state._replace(best=best, rounds=state.rounds + 1)

        def do_split(st):
            ge = jnp.where(iota_l == lsafe, 1.0, NEG_INF)
            st2, _ = _apply_split(st, bins, binsT, missing_bin, ge, meta,
                                  with_monotone=with_monotone,
                                  with_interactions=with_interactions,
                                  cegb_lazy=cegb_lazy,
                                  mono_intermediate=mono_intermediate,
                                  sub_bins=sub_bins, sub_binsT=sub_binsT,
                                  sp=sp_pack)
            return st2

        state = jax.lax.cond(ok, do_split, lambda s: s, state)
        # children inherit slots (left keeps the split slot, right takes the
        # new one); a skipped node kills its subtree (slot -1)
        slot = state.forced_slot
        flk, frk = fl[k_idx], fr[k_idx]
        slot = slot.at[jnp.maximum(flk, 0)].set(
            jnp.where(flk >= 0, jnp.where(ok, lsafe, -1),
                      slot[jnp.maximum(flk, 0)]))
        slot = slot.at[jnp.maximum(frk, 0)].set(
            jnp.where(frk >= 0, jnp.where(ok, new_leaf, -1),
                      slot[jnp.maximum(frk, 0)]))
        return state._replace(forced_idx=k_idx + 1, forced_slot=slot,
                              done=jnp.bool_(False))

    def merge_best(a: SplitInfo, b: SplitInfo) -> SplitInfo:
        """Cross-block best merge: strictly greater gain replaces, ties keep
        the earlier block = the lower feature index (the reference's
        cross-feature tie rule, serial_tree_learner.cpp:374-448)."""
        take = b.gain > a.gain

        def w(x, y):
            m = take if x.ndim == 1 else take[:, None]
            return jnp.where(m, y, x)

        return SplitInfo(*(w(x, y) for x, y in zip(a, b)))

    def blocked_pass(state: GrowState) -> GrowState:
        """Histogram + search for a tile of pending leaves, one feature
        block at a time; only the winning SplitInfo survives the block."""
        pending = pending_mask(state)
        order = jnp.argsort(jnp.where(pending, iota_l, L + iota_l))
        chosen = order[:P].astype(jnp.int32)
        chosen_ok = pending[chosen]
        sel = jnp.where(chosen_ok, chosen, -1)

        round_key = jax.random.fold_in(rng_key, state.rounds)
        fmask_sel = leaf_feature_mask(state, round_key)[chosen] \
            .astype(jnp.float32)                              # [P, f]
        rand_bin_sel = None
        if extra_trees:
            nbm = jnp.maximum(meta.num_bins - 2, 1)
            u = jax.random.uniform(jax.random.fold_in(round_key, 2), (L, f))
            rand_bin_sel = (u * nbm[None, :]).astype(jnp.int32)[chosen]

        sum_g = state.leaf_sum_g[chosen]
        sum_h = state.leaf_sum_h[chosen]
        cnt = state.leaf_cnt[chosen]
        outp = state.leaf_output[chosen]
        depth = state.leaf_depth[chosen]
        lmin = state.leaf_min[chosen] if with_monotone else None
        lmax = state.leaf_max[chosen] if with_monotone else None

        best_t = None
        for bi in range(-(-f // feature_block)):
            s_, e_ = bi * feature_block, min((bi + 1) * feature_block, f)
            tile = histogram_tiles(
                bins[:, s_:e_], stats, state.leaf_id, sel, num_bins,
                method=hist_method, dtype=hist_dtype,
                binsT=binsT[s_:e_] if binsT is not None else None,
                block=hist_block, interpret=hist_interpret)
            mb = FeatureMeta(*(a[s_:e_] for a in meta))
            bundle_b = (type(bundle_meta)(*(a[s_:e_] for a in bundle_meta))
                        if bundle_meta is not None else None)
            bb = find_best_splits(
                tile, sum_g, sum_h, cnt, outp, depth, mb, params,
                fmask_sel[:, s_:e_], max_depth,
                with_categorical=with_categorical, cat_words=cat_words,
                leaf_min=lmin, leaf_max=lmax,
                rand_bin=(rand_bin_sel[:, s_:e_]
                          if rand_bin_sel is not None else None),
                bundle=bundle_b)
            bb = bb._replace(feature=bb.feature + s_)
            best_t = bb if best_t is None else merge_best(best_t, bb)

        def scat(cur, new):
            m = chosen_ok if new.ndim == 1 else chosen_ok[:, None]
            return cur.at[chosen].set(jnp.where(m, new, cur[chosen]))

        new_best = SplitInfo(*(scat(c, nb)
                               for c, nb in zip(state.best, best_t)))
        return state._replace(
            best=new_best,
            hist_valid=state.hist_valid.at[chosen].set(
                state.hist_valid[chosen] | chosen_ok),
            rounds=state.rounds + 1,
            rows_streamed=state.rows_streamed
            + jnp.float32(n * (-(-f // feature_block))))

    def apply_splits(state: GrowState, gain_eff: jax.Array,
                     apply_kw: dict) -> GrowState:
        """Shared split-application loop: strict best-first (one split per
        phase) under ``exact``, otherwise every positive-gain split this
        round via an inner while_loop."""
        if exact:
            def do_split(carry):
                st, ge = carry
                return _apply_split(st, bins, binsT, missing_bin, ge, meta,
                                    **apply_kw)

            state, _ = jax.lax.cond(
                (state.num_leaves < L) & (jnp.max(gain_eff) > 0.0),
                do_split, lambda c: c, (state, gain_eff))
        else:
            def inner_cond(carry):
                st, ge = carry
                return (st.num_leaves < L) & (jnp.max(ge) > 0.0)

            def inner_body(carry):
                st, ge = carry
                return _apply_split(st, bins, binsT, missing_bin, ge, meta,
                                    **apply_kw)

            state, _ = jax.lax.while_loop(inner_cond, inner_body,
                                          (state, gain_eff))
        return state

    def split_phase_blocked(state: GrowState) -> GrowState:
        """Apply splits from the STORED per-leaf bests (no re-search — the
        histograms are gone). Valid because a leaf's best is invariant
        until it is split: basic-monotone bounds and interaction masks
        only change for the split leaf's children, which are re-searched
        with fresh histograms anyway."""
        num_leaves_before = state.num_leaves
        state = state._replace(rounds=state.rounds + 1)
        gain_eff = jnp.where(active_mask(state) & state.hist_valid
                             & ~state.leaf_dead, state.best.gain, NEG_INF)
        state = apply_splits(state, gain_eff, dict(
            with_monotone=with_monotone,
            with_interactions=with_interactions,
            cegb_lazy=False, mono_intermediate=False,
            sub_bins=None, sub_binsT=None, sp=sp_pack))
        return state._replace(done=state.num_leaves == num_leaves_before)

    hist_phase = tile_pass_fused if split_fusion else tile_pass

    def dead_guard(state: GrowState) -> GrowState:
        # BeforeFindBestSplit guards (serial_tree_learner.cpp:282-322): a
        # leaf failing the 2x min-data/min-hessian check is never
        # histogrammed and never splittable
        active = active_mask(state)
        guard = ((state.leaf_cnt >= 2.0 * params.min_data_in_leaf)
                 & (state.leaf_sum_h >= 2.0 * params.min_sum_hessian_in_leaf))
        newly_dead = active & ~state.hist_valid & ~state.leaf_dead & ~guard
        return state._replace(leaf_dead=state.leaf_dead | newly_dead)

    def outer_body(state: GrowState) -> GrowState:
        state = dead_guard(state)
        if blocked:
            return jax.lax.cond(jnp.any(pending_mask(state)),
                                blocked_pass, split_phase_blocked, state)
        if forced_splits is not None:
            k_total = forced_splits[0].shape[0]

            def no_pending(st):
                return jax.lax.cond(st.forced_idx < k_total,
                                    forced_phase, split_phase, st)

            return jax.lax.cond(jnp.any(pending_mask(state)),
                                hist_phase, no_pending, state)
        return jax.lax.cond(jnp.any(pending_mask(state)),
                            hist_phase, split_phase, state)

    def finalize(state: GrowState):
        rows_streamed = state.rows_streamed
        if axis_name is not None:
            # global rows per tree across the row shards (each shard
            # counted only its local rows)
            rows_streamed = jax.lax.psum(rows_streamed, axis_name)
        # histogram-plane numerics sentinel (see GrowAux.sentinel): judged
        # on the FINAL grow state, in-program — the per-leaf grad/hess
        # sums and outputs integrate every histogram the tree consumed (a
        # NaN entering any pass lands in some leaf's sums), and the
        # resident histogram state is checked directly where it exists
        # (the blocked mode holds only a dummy). A constant 0 when the
        # static is off, so the disarmed program is unchanged.
        if numerics_sentinels:
            bad = (jnp.any(~jnp.isfinite(state.leaf_sum_g))
                   | jnp.any(~jnp.isfinite(state.leaf_sum_h))
                   | jnp.any(~jnp.isfinite(state.leaf_output)))
            if not blocked:
                bad = bad | jnp.any(~jnp.isfinite(state.hist))
            sentinel = bad.astype(jnp.float32)
            if axis_name is not None:
                sentinel = jax.lax.psum(sentinel, axis_name)
        else:
            sentinel = jnp.float32(0.0)
        # coll_bytes is already the per-device receive volume and
        # identical on every shard — no psum (a psum would scale it by
        # the mesh size)
        return state.tree, state.leaf_id, GrowAux(
            state.used_split, state.row_used, rows_streamed,
            state.coll_bytes, sentinel)

    return {"init_state": init_state, "dead_guard": dead_guard,
            "outer_cond": outer_cond, "outer_body": outer_body,
            "hist_phase": hist_phase, "split_search": split_search,
            "split_apply": split_apply, "pending_mask": pending_mask,
            "finalize": finalize, "phased_ok": (not blocked
                                               and forced_splits is None)}


# dynamic (array) grow kwargs, in the canonical order the phased programs
# receive them as one tuple operand
_GROW_DYN = ("interaction_groups", "cegb_coupled", "cegb_lazy_penalty",
             "cegb_state", "bynode_fraction", "rng_key", "binsT", "sub_idx",
             "sub_bins", "sub_binsT", "bundle_meta", "forced_splits",
             "sp_rows", "sp_bins", "sp_default")


@functools.partial(jax.jit, static_argnames=_GROW_STATICS)
def grow_tree(bins: jax.Array, grad: jax.Array, hess: jax.Array,
              sample_mask: jax.Array, meta: FeatureMeta, params: SplitParams,
              feature_mask: jax.Array, missing_bin: jax.Array, *,
              max_leaves: int, num_bins: int, max_depth: int = -1,
              hist_method: str = "scatter",
              exact: bool = False,
              with_categorical: bool = False,
              with_monotone: bool = False,
              mono_mode: str = "basic",
              mono_features: tuple = (),
              with_interactions: bool = False,
              interaction_groups: jax.Array | None = None,
              cegb_mode: str = "off",
              cegb_coupled: jax.Array | None = None,
              cegb_lazy_penalty: jax.Array | None = None,
              cegb_state: GrowAux | None = None,
              extra_trees: bool = False,
              use_bynode: bool = False,
              bynode_fraction: jax.Array | None = None,
              rng_key: jax.Array | None = None,
              axis_name: str | None = None,
              binsT: jax.Array | None = None,
              sub_idx: jax.Array | None = None,
              sub_bins: jax.Array | None = None,
              sub_binsT: jax.Array | None = None,
              tile_leaves: int = 0,
              hist_block: int = 0,
              hist_subtraction: bool = True,
              feature_block: int = 0,
              feature_axis_name: str | None = None,
              feature_shards: int = 1,
              voting: bool = False,
              vote_top_k: int = 20,
              bundle_meta=None,
              forced_splits=None,
              hist_dp: bool = False,
              sp_cols: tuple = (),
              sp_rows: jax.Array | None = None,
              sp_bins: jax.Array | None = None,
              sp_default: jax.Array | None = None,
              compaction_ladder: tuple = (),
              hist_interpret: bool = False,
              numerics_sentinels: bool = False,
              split_fusion: bool = False,
              ) -> Tuple[TreeArrays, jax.Array, GrowAux]:
    """Grow one tree as ONE jitted program (see _grower_fns for the full
    argument contract). Returns (tree arrays, per-row leaf index, aux)."""
    fns = _grower_fns(
        bins, grad, hess, sample_mask, meta, params, feature_mask,
        missing_bin, max_leaves=max_leaves, num_bins=num_bins,
        max_depth=max_depth, hist_method=hist_method, exact=exact,
        with_categorical=with_categorical, with_monotone=with_monotone,
        mono_mode=mono_mode, mono_features=mono_features,
        with_interactions=with_interactions,
        interaction_groups=interaction_groups, cegb_mode=cegb_mode,
        cegb_coupled=cegb_coupled, cegb_lazy_penalty=cegb_lazy_penalty,
        cegb_state=cegb_state, extra_trees=extra_trees,
        use_bynode=use_bynode, bynode_fraction=bynode_fraction,
        rng_key=rng_key, axis_name=axis_name, binsT=binsT, sub_idx=sub_idx,
        sub_bins=sub_bins, sub_binsT=sub_binsT, tile_leaves=tile_leaves,
        hist_block=hist_block, hist_subtraction=hist_subtraction,
        feature_block=feature_block, feature_axis_name=feature_axis_name,
        feature_shards=feature_shards, voting=voting, vote_top_k=vote_top_k,
        bundle_meta=bundle_meta, forced_splits=forced_splits,
        hist_dp=hist_dp, sp_cols=sp_cols, sp_rows=sp_rows, sp_bins=sp_bins,
        sp_default=sp_default, compaction_ladder=compaction_ladder,
        hist_interpret=hist_interpret,
        numerics_sentinels=numerics_sentinels, split_fusion=split_fusion)
    state = jax.lax.while_loop(fns["outer_cond"], fns["outer_body"],
                               fns["init_state"]())
    return fns["finalize"](state)


@functools.lru_cache(maxsize=8)
def _phased_programs(statics_items: tuple):
    """Per-config jitted phase programs for the host-driven grower (the
    hist_pass / split_search / apply_split TIMETAG sub-scopes). Statics
    fold in via this cache's key; arrays arrive as explicit operands, so
    no dataset-sized closure constants reach XLA (the PR 10 lesson).

    Each per-round program also returns (any-pending, continue) flags
    computed on the post-phase state with the next round's dead-guard
    already folded in (idempotent — the guard depends only on leaf
    aggregates), so the host's branch decisions reproduce the monolithic
    while_loop's guard-then-branch order bit-exactly."""
    skw = dict(statics_items)

    def _fns(arrs, dyn):
        bins, grad, hess, sample_mask, meta, params, fmask, missing_bin = \
            arrs
        return _grower_fns(bins, grad, hess, sample_mask, meta, params,
                           fmask, missing_bin,
                           **dict(zip(_GROW_DYN, dyn)), **skw)

    def init(arrs, dyn):
        fns = _fns(arrs, dyn)
        state = fns["dead_guard"](fns["init_state"]())
        return (state, jnp.any(fns["pending_mask"](state)),
                fns["outer_cond"](state))

    def mk(phase):
        def run(state, arrs, dyn):
            fns = _fns(arrs, dyn)
            if phase == "tile":
                state = fns["dead_guard"](fns["hist_phase"](state))
            elif phase == "search":
                state = fns["split_search"](state)
            else:
                state = fns["dead_guard"](fns["split_apply"](state))
            return (state, jnp.any(fns["pending_mask"](state)),
                    fns["outer_cond"](state))
        return jax.jit(run)

    def fin(state, arrs, dyn):
        return _fns(arrs, dyn)["finalize"](state)

    return {"init": jax.jit(init), "tile": mk("tile"),
            "search": mk("search"), "apply": mk("apply"),
            "finalize": jax.jit(fin)}


def grow_tree_phased(bins, grad, hess, sample_mask, meta, params,
                     feature_mask, missing_bin, **kw):
    """Host-driven grow loop with per-phase TIMETAG scopes.

    The SAME _grower_fns phases as grow_tree, but each round is its own
    compiled dispatch so ``hist_pass`` / ``split_search`` / ``apply_split``
    wall time is attributable per phase (bench.py's sub-scope probe; the
    reference's per-phase USE_TIMETAG table). The host fetches two
    booleans per ROUND — with frontier batching that is one histogram
    launch per frontier level, not per leaf (the dispatch-count
    regression pins it). Bit-identical trees to grow_tree; serial
    non-blocked non-forced configurations only (callers fall back to
    grow_tree otherwise).
    """
    from ..utils import profiling
    statics = tuple(sorted((k, v) for k, v in kw.items()
                           if k in _GROW_STATICS))
    dyn = tuple(kw.get(k) for k in _GROW_DYN)
    unknown = set(kw) - set(_GROW_STATICS) - set(_GROW_DYN)
    assert not unknown, f"grow_tree_phased: unsupported kwargs {unknown}"
    assert not kw.get("axis_name") and not kw.get("feature_axis_name"), (
        "grow_tree_phased is serial-only")
    assert kw.get("forced_splits") is None and not kw.get("feature_block"), (
        "grow_tree_phased: forced splits / blocked mode unsupported")
    arrs = (bins, grad, hess, sample_mask, meta, params, feature_mask,
            missing_bin)
    progs = _phased_programs(statics)
    state, pending, cont = progs["init"](arrs, dyn)
    pending, cont = bool(pending), bool(cont)
    while cont:
        if pending:
            with profiling.timer("hist_pass"):
                state, p2, c2 = progs["tile"](state, arrs, dyn)
                pending, cont = bool(p2), bool(c2)
        else:
            with profiling.timer("split_search"):
                state, _, _ = progs["search"](state, arrs, dyn)
                state.best.gain.block_until_ready()
            with profiling.timer("apply_split"):
                state, p2, c2 = progs["apply"](state, arrs, dyn)
                pending, cont = bool(p2), bool(c2)
    return progs["finalize"](state, arrs, dyn)
