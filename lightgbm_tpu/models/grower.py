"""Leaf-wise tree growth as one jitted XLA program.

TPU-native re-design of the reference's SerialTreeLearner::Train loop
(reference: src/treelearner/serial_tree_learner.cpp:158-209): leaf membership
is a per-row int32 vector instead of a permuted index partition
(data_partition.hpp:21-60), histograms are built for every
histogram-pending leaf in ONE full-data pass (ops/histogram.py), and split
search evaluates all (leaf, feature, threshold) candidates at once
(ops/split.py).

Growth proceeds in ROUNDS inside a ``lax.while_loop``:

  round := histogram pass for pending leaves
        -> vectorized best-split search
        -> inner while_loop: split leaves in gain order while their
           histograms are valid (children become histogram-pending).

Equivalence to the reference's strict leaf-wise order: tree growth is
order-independent whenever every positive-gain split fits in the
``num_leaves`` budget (the set of splits is the gain>0 closure, regardless of
order). The batched order can differ from strict best-first only in WHICH
leaves receive the final few splits when the budget binds mid-round — the
per-leaf split decisions themselves are identical. The reference's
histogram-subtraction trick (serial_tree_learner.cpp:311-320) is an
optimization slot here (children are currently both recomputed in the next
round's single pass).

Guards mirror BeforeFindBestSplit (serial_tree_learner.cpp:282-322): a leaf
whose count < 2*min_data_in_leaf or hessian sum < 2*min_sum_hessian_in_leaf
is never histogrammed; max_depth masks at split-search level.

Optional learner features threaded through the same jitted program:

- monotone constraints, basic mode (monotone_constraints.hpp:463-512
  BasicLeafConstraints): per-leaf [min, max] output bounds, updated with the
  children's mid-point at every split on a monotone feature;
- interaction constraints (col_sampler.hpp:20-50): per-leaf allowed-feature
  masks derived from the features used along the path and the constraint
  groups — two boolean matmuls per round;
- CEGB (cost_effective_gradient_boosting.hpp): split/coupled/lazy penalties
  as a per-(leaf, feature) additive gain adjustment;
- extra_trees (feature_histogram.hpp USE_RAND): one random threshold per
  (leaf, feature) per round;
- feature_fraction_bynode (col_sampler.hpp GetByNode): per-leaf random
  feature subset resampled every round.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.histogram import build_histograms
from ..ops.split import (FeatureMeta, SplitInfo, SplitParams,
                         find_best_splits)
from .tree import TreeArrays, empty_tree

NEG_INF = -jnp.inf
F32_MAX = jnp.finfo(jnp.float32).max


class GrowAux(NamedTuple):
    """Cross-iteration learner state returned alongside the tree (CEGB's
    feature-used tracking is global across the boosting run,
    cost_effective_gradient_boosting.hpp:90-101)."""
    used_split: jax.Array    # [F] bool: feature used in any split (CEGB coupled)
    row_used: jax.Array      # [N, F] bool or [1, 1] dummy (CEGB lazy)


class GrowState(NamedTuple):
    leaf_id: jax.Array       # [N] int32
    hist: jax.Array          # [L, F, B, 3]
    hist_valid: jax.Array    # [L] bool
    leaf_dead: jax.Array     # [L] bool (guard-failed, never splittable)
    leaf_sum_g: jax.Array    # [L]
    leaf_sum_h: jax.Array
    leaf_cnt: jax.Array
    leaf_output: jax.Array
    leaf_depth: jax.Array    # [L] int32
    leaf_min: jax.Array      # [L] monotone output lower bound
    leaf_max: jax.Array      # [L] monotone output upper bound
    used_path: jax.Array     # [L, F] bool (interaction constraints) or [1,1]
    used_split: jax.Array    # [F] bool (CEGB coupled)
    row_used: jax.Array      # [N, F] bool (CEGB lazy) or [1,1]
    best: SplitInfo
    tree: TreeArrays
    num_leaves: jax.Array    # int32
    rounds: jax.Array        # int32


def _apply_split(state: GrowState, bins: jax.Array, missing_bin: jax.Array,
                 gain_eff: jax.Array, meta: FeatureMeta, *,
                 with_monotone: bool, with_interactions: bool,
                 cegb_lazy: bool) -> Tuple[GrowState, jax.Array]:
    """Split the current best leaf (reference: SerialTreeLearner::Split,
    serial_tree_learner.cpp:564-682 + Tree::Split, tree.h:62)."""
    l = jnp.argmax(gain_eff).astype(jnp.int32)
    best = state.best
    tree = state.tree
    new_leaf = state.num_leaves
    node = state.num_leaves - 1

    feat = best.feature[l]
    thr = best.threshold[l]
    dleft = best.default_left[l]
    is_cat = best.is_cat[l]
    bitset = best.cat_bitset[l]

    # --- rows of leaf l route left/right
    col = jnp.take(bins, feat, axis=1).astype(jnp.int32)
    mb = missing_bin[feat]
    num_left = jnp.where((col == mb) & (mb >= 0), dleft, col <= thr)
    # categorical: bitset membership (Tree::CategoricalDecision, tree.h:349)
    word = jnp.take(bitset, col >> 5)
    cat_left = ((word >> (col & 31).astype(jnp.uint32)) & 1) == 1
    go_left = jnp.where(is_cat, cat_left, num_left)
    in_leaf = state.leaf_id == l
    leaf_id = jnp.where(in_leaf & ~go_left, new_leaf, state.leaf_id)

    # --- tree arrays: fix the parent link that pointed at leaf l
    parent = tree.leaf_parent[l]
    psafe = jnp.maximum(parent, 0)
    left_match = (parent >= 0) & (tree.node_left[psafe] == ~l)
    right_match = (parent >= 0) & (tree.node_right[psafe] == ~l)
    node_left = tree.node_left.at[psafe].set(
        jnp.where(left_match, node, tree.node_left[psafe]))
    node_right = tree.node_right.at[psafe].set(
        jnp.where(right_match, node, tree.node_right[psafe]))

    tree = tree._replace(
        num_leaves=state.num_leaves + 1,
        node_feature=tree.node_feature.at[node].set(feat),
        node_threshold_bin=tree.node_threshold_bin.at[node].set(thr),
        node_default_left=tree.node_default_left.at[node].set(dleft),
        node_cat=tree.node_cat.at[node].set(is_cat),
        node_cat_bitset=tree.node_cat_bitset.at[node].set(bitset),
        node_left=node_left.at[node].set(~l),
        node_right=node_right.at[node].set(~new_leaf),
        node_gain=tree.node_gain.at[node].set(best.gain[l]),
        node_value=tree.node_value.at[node].set(state.leaf_output[l]),
        node_weight=tree.node_weight.at[node].set(state.leaf_sum_h[l]),
        node_count=tree.node_count.at[node].set(state.leaf_cnt[l]),
        leaf_value=tree.leaf_value.at[l].set(best.left_output[l])
                                   .at[new_leaf].set(best.right_output[l]),
        leaf_weight=tree.leaf_weight.at[l].set(best.left_sum_h[l])
                                    .at[new_leaf].set(best.right_sum_h[l]),
        leaf_count=tree.leaf_count.at[l].set(best.left_count[l])
                                  .at[new_leaf].set(best.right_count[l]),
        leaf_depth=tree.leaf_depth.at[l].set(state.leaf_depth[l] + 1)
                                  .at[new_leaf].set(state.leaf_depth[l] + 1),
        leaf_parent=tree.leaf_parent.at[l].set(node).at[new_leaf].set(node),
    )

    new_depth = state.leaf_depth[l] + 1

    # monotone basic-mode bound update (monotone_constraints.hpp:485-501):
    # children inherit the parent's bounds; a split on a monotone feature
    # tightens them around the children's mid-point
    leaf_min, leaf_max = state.leaf_min, state.leaf_max
    if with_monotone:
        mono = meta.monotone[feat].astype(jnp.int32)
        mono = jnp.where(is_cat, 0, mono)
        mid = (best.left_output[l] + best.right_output[l]) / 2.0
        pmin, pmax = leaf_min[l], leaf_max[l]
        # leaf keeps the LEFT child, new_leaf the RIGHT child
        lmax = jnp.where(mono > 0, jnp.minimum(pmax, mid), pmax)
        lmin = jnp.where(mono < 0, jnp.maximum(pmin, mid), pmin)
        rmin = jnp.where(mono > 0, jnp.maximum(pmin, mid), pmin)
        rmax = jnp.where(mono < 0, jnp.minimum(pmax, mid), pmax)
        leaf_min = leaf_min.at[l].set(lmin).at[new_leaf].set(rmin)
        leaf_max = leaf_max.at[l].set(lmax).at[new_leaf].set(rmax)

    used_path = state.used_path
    if with_interactions:
        parent_used = state.used_path[l].at[feat].set(True)
        used_path = used_path.at[l].set(parent_used).at[new_leaf].set(parent_used)

    used_split = state.used_split.at[feat].set(True)

    row_used = state.row_used
    if cegb_lazy:
        row_used = row_used | (in_leaf[:, None]
                               & (jnp.arange(row_used.shape[1]) == feat)[None, :])

    state = state._replace(
        leaf_id=leaf_id,
        tree=tree,
        hist_valid=state.hist_valid.at[l].set(False).at[new_leaf].set(False),
        leaf_sum_g=state.leaf_sum_g.at[l].set(best.left_sum_g[l])
                                   .at[new_leaf].set(best.right_sum_g[l]),
        leaf_sum_h=state.leaf_sum_h.at[l].set(best.left_sum_h[l])
                                   .at[new_leaf].set(best.right_sum_h[l]),
        leaf_cnt=state.leaf_cnt.at[l].set(best.left_count[l])
                               .at[new_leaf].set(best.right_count[l]),
        leaf_output=state.leaf_output.at[l].set(best.left_output[l])
                                     .at[new_leaf].set(best.right_output[l]),
        leaf_depth=state.leaf_depth.at[l].set(new_depth)
                                   .at[new_leaf].set(new_depth),
        leaf_min=leaf_min, leaf_max=leaf_max,
        used_path=used_path, used_split=used_split, row_used=row_used,
        num_leaves=state.num_leaves + 1,
    )
    gain_eff = gain_eff.at[l].set(NEG_INF).at[new_leaf].set(NEG_INF)
    return state, gain_eff


@functools.partial(
    jax.jit,
    static_argnames=("max_leaves", "num_bins", "max_depth", "hist_method",
                     "exact", "axis_name", "with_categorical", "with_monotone",
                     "with_interactions", "cegb_mode", "extra_trees",
                     "use_bynode", "feature_axis_name", "voting",
                     "vote_top_k"))
def grow_tree(bins: jax.Array, grad: jax.Array, hess: jax.Array,
              sample_mask: jax.Array, meta: FeatureMeta, params: SplitParams,
              feature_mask: jax.Array, missing_bin: jax.Array, *,
              max_leaves: int, num_bins: int, max_depth: int = -1,
              hist_method: str = "scatter",
              exact: bool = False,
              with_categorical: bool = False,
              with_monotone: bool = False,
              with_interactions: bool = False,
              interaction_groups: jax.Array | None = None,
              cegb_mode: str = "off",
              cegb_coupled: jax.Array | None = None,
              cegb_lazy_penalty: jax.Array | None = None,
              cegb_state: GrowAux | None = None,
              extra_trees: bool = False,
              use_bynode: bool = False,
              bynode_fraction: jax.Array | None = None,
              rng_key: jax.Array | None = None,
              axis_name: str | None = None,
              feature_axis_name: str | None = None,
              voting: bool = False,
              vote_top_k: int = 20
              ) -> Tuple[TreeArrays, jax.Array, GrowAux]:
    """Grow one tree. Returns (tree arrays, per-row leaf index, aux state).

    Args:
      bins: [N, F] binned features (device-resident, uint8/int32).
      grad, hess: [N] objective gradients/hessians (weights folded in,
        reference: ObjectiveFunction::GetGradients).
      sample_mask: [N] f32 0/1 bagging mask (mask-based bagging keeps shapes
        static; the analog of GBDT::Bagging's index subset, gbdt.cpp:228-262).
      feature_mask: [F] f32 0/1 from column sampling (col_sampler.hpp).
      missing_bin: [F] int32 default-routed bin per feature or -1.
      exact: strict best-first order (one split per histogram round) — the
        reference's exact leaf-wise semantics even when the num_leaves budget
        binds, at the cost of one histogram pass per split. The default
        batched mode performs all available splits per round (see module
        docstring for the equivalence argument).
      interaction_groups: [G, F] bool group membership when
        with_interactions.
      cegb_mode: "off" | "feat" (split+coupled penalties) | "lazy" (adds the
        per-row on-demand costs); cegb_state carries the cross-iteration
        used-feature tracking.
      rng_key: PRNG key, consumed when extra_trees or use_bynode.
      axis_name: when set, rows are sharded over this mesh axis (shard_map
        context): root sums and histograms are psum'd over it — the SPMD
        analog of the reference data-parallel learner's root allreduce
        (data_parallel_tree_learner.cpp:125-152) and histogram ReduceScatter
        (:184-186). All devices then take identical split decisions with no
        further communication.
      feature_axis_name: feature-parallel mode (reference:
        feature_parallel_tree_learner.cpp): data replicated, each device
        searches only its own feature slice (the caller restricts
        feature_mask), and the per-leaf best splits are allreduce-argmax'd
        (sync_best_splits) — no histogram communication at all.
      voting: voting-parallel mode over ``axis_name`` (reference:
        voting_parallel_tree_learner.cpp PV-tree): rows sharded; each device
        votes for its local top ``vote_top_k`` features per leaf from LOCAL
        histograms, the vote elects 2*top_k features globally, and only the
        elected features' histograms are psum'd before the final search.
    """
    n, f = bins.shape
    L = max_leaves
    cat_words = max(1, -(-num_bins // 32))
    cegb_lazy = cegb_mode == "lazy"
    cegb_on = cegb_mode != "off"

    stats = jnp.stack([grad * sample_mask, hess * sample_mask, sample_mask],
                      axis=1).astype(jnp.float32)
    root = jnp.sum(stats, axis=0)
    if axis_name is not None:
        root = jax.lax.psum(root, axis_name)
    from ..ops.split import calculate_leaf_output
    root_out = calculate_leaf_output(root[0], root[1], params, root[2],
                                     jnp.float32(0.0))

    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)

    def init_state() -> GrowState:
        zero_best = find_best_splits(  # shape-consistent placeholder (all -inf)
            jnp.zeros((L, f, num_bins, 3), jnp.float32),
            jnp.zeros((L,)), jnp.zeros((L,)), jnp.zeros((L,)), jnp.zeros((L,)),
            jnp.zeros((L,), jnp.int32), meta, params,
            feature_mask if feature_mask.ndim == 1 else feature_mask[:1, :],
            max_depth, with_categorical=False, cat_words=cat_words)
        if cegb_state is not None:
            used_split = cegb_state.used_split
            row_used = cegb_state.row_used
        else:
            used_split = jnp.zeros((f,), bool)
            row_used = jnp.zeros((n, f) if cegb_lazy else (1, 1), bool)
        return GrowState(
            leaf_id=jnp.zeros((n,), jnp.int32),
            hist=jnp.zeros((L, f, num_bins, 3), jnp.float32),
            hist_valid=jnp.zeros((L,), bool),
            leaf_dead=jnp.zeros((L,), bool),
            leaf_sum_g=jnp.zeros((L,)).at[0].set(root[0]),
            leaf_sum_h=jnp.zeros((L,)).at[0].set(root[1]),
            leaf_cnt=jnp.zeros((L,)).at[0].set(root[2]),
            leaf_output=jnp.zeros((L,)).at[0].set(root_out),
            leaf_depth=jnp.zeros((L,), jnp.int32),
            leaf_min=jnp.full((L,), -F32_MAX, jnp.float32),
            leaf_max=jnp.full((L,), F32_MAX, jnp.float32),
            used_path=jnp.zeros((L, f) if with_interactions else (1, 1), bool),
            used_split=used_split,
            row_used=row_used,
            best=zero_best,
            tree=empty_tree(L, cat_words),
            num_leaves=jnp.int32(1),
            rounds=jnp.int32(0),
        )

    def active_mask(state: GrowState) -> jax.Array:
        return jnp.arange(L, dtype=jnp.int32) < state.num_leaves

    def outer_cond(state: GrowState) -> jax.Array:
        pending = active_mask(state) & ~state.hist_valid & ~state.leaf_dead
        return (state.num_leaves < L) & jnp.any(pending) & (state.rounds < L)

    def leaf_feature_mask(state: GrowState, round_key) -> jax.Array:
        """Per-(leaf, feature) validity: global column sampling x interaction
        constraints x per-node sampling."""
        fmask = feature_mask
        if fmask.ndim == 1:
            fmask = jnp.broadcast_to(fmask[None, :], (L, f))
        out = fmask.astype(bool)
        if with_interactions:
            # allowed[l] = union of groups containing every used feature of l
            # (col_sampler.hpp interaction filtering): two boolean matmuls
            grp = interaction_groups.astype(jnp.float32)        # [G, F]
            used = state.used_path.astype(jnp.float32)          # [L, F]
            viol = used @ (1.0 - grp).T                          # [L, G] >0 bad
            ok = (viol < 0.5).astype(jnp.float32)
            allowed = (ok @ grp) > 0.5                           # [L, F]
            out = out & allowed
        if use_bynode:
            # per-leaf random subset of ceil(frac * F) features per round
            # (col_sampler.hpp GetByNode resamples per node)
            u = jax.random.uniform(jax.random.fold_in(round_key, 1), (L, f))
            k = jnp.maximum(
                jnp.ceil(bynode_fraction * f).astype(jnp.int32), 1)
            rank = jnp.argsort(jnp.argsort(u, axis=1), axis=1)
            out = out & (rank < k)
        return out

    def cegb_adjust(state: GrowState) -> jax.Array | None:
        """CEGB delta per (leaf, feature) subtracted from stored gains
        (cost_effective_gradient_boosting.hpp:66-84 DetlaGain)."""
        if not cegb_on:
            return None
        delta = (params.cegb_tradeoff * params.cegb_penalty_split
                 * state.leaf_cnt)[:, None]                      # [L, 1]
        delta = jnp.broadcast_to(delta, (L, f))
        if cegb_coupled is not None:
            delta = delta + jnp.where(state.used_split[None, :], 0.0,
                                      params.cegb_tradeoff
                                      * cegb_coupled[None, :])
        if cegb_lazy and cegb_lazy_penalty is not None:
            onehot = jax.nn.one_hot(state.leaf_id, L, dtype=jnp.float32)
            unused = 1.0 - state.row_used.astype(jnp.float32)    # [N, F]
            cnt_unused = onehot.T @ unused                       # [L, F]
            if axis_name is not None:
                cnt_unused = jax.lax.psum(cnt_unused, axis_name)
            delta = delta + (params.cegb_tradeoff
                             * cegb_lazy_penalty[None, :] * cnt_unused)
        return delta

    def outer_body(state: GrowState) -> GrowState:
        active = active_mask(state)
        # BeforeFindBestSplit guards (serial_tree_learner.cpp:282-322)
        guard = ((state.leaf_cnt >= 2.0 * params.min_data_in_leaf)
                 & (state.leaf_sum_h >= 2.0 * params.min_sum_hessian_in_leaf))
        newly_dead = active & ~state.hist_valid & ~state.leaf_dead & ~guard
        leaf_dead = state.leaf_dead | newly_dead
        pending = active & ~state.hist_valid & ~leaf_dead

        row_pending = pending[state.leaf_id]
        new_hist = build_histograms(bins, stats * row_pending[:, None],
                                    state.leaf_id, L, num_bins,
                                    method=hist_method)
        if axis_name is not None:
            new_hist = jax.lax.psum(new_hist, axis_name)
        hist = jnp.where(pending[:, None, None, None], new_hist, state.hist)
        hist_valid = state.hist_valid | pending

        round_key = jax.random.fold_in(rng_key, state.rounds)
        fmask = leaf_feature_mask(state, round_key)
        rand_bin = None
        if extra_trees:
            # one random threshold per (leaf, feature) per search
            # (feature_histogram.hpp USE_RAND rand.NextInt)
            nbm = jnp.maximum(meta.num_bins - 2, 1)
            u = jax.random.uniform(jax.random.fold_in(round_key, 2), (L, f))
            rand_bin = (u * nbm[None, :]).astype(jnp.int32)

        best = find_best_splits(
            hist, state.leaf_sum_g, state.leaf_sum_h,
            state.leaf_cnt, state.leaf_output,
            state.leaf_depth, meta, params,
            fmask, max_depth,
            with_categorical=with_categorical, cat_words=cat_words,
            leaf_min=state.leaf_min if with_monotone else None,
            leaf_max=state.leaf_max if with_monotone else None,
            gain_adjust=cegb_adjust(state),
            rand_bin=rand_bin)
        state = state._replace(hist=hist, hist_valid=hist_valid,
                               leaf_dead=leaf_dead, best=best,
                               rounds=state.rounds + 1)

        gain_eff = jnp.where(active & hist_valid & ~leaf_dead, best.gain, NEG_INF)

        apply_kw = dict(with_monotone=with_monotone,
                        with_interactions=with_interactions,
                        cegb_lazy=cegb_lazy)

        if exact:
            # strict best-first: one split per round, then recompute children
            def do_split(carry):
                st, ge = carry
                return _apply_split(st, bins, missing_bin, ge, meta, **apply_kw)

            state, _ = jax.lax.cond(
                (state.num_leaves < L) & (jnp.max(gain_eff) > 0.0),
                do_split, lambda c: c, (state, gain_eff))
            # mark all remaining splittable-but-unsplit leaves as needing
            # nothing: their hists stay valid; loop continues via pending
            # children. If nothing was split and nothing is pending, the
            # outer cond ends the loop.
            return state

        def inner_cond(carry):
            st, ge = carry
            return (st.num_leaves < L) & (jnp.max(ge) > 0.0)

        def inner_body(carry):
            st, ge = carry
            return _apply_split(st, bins, missing_bin, ge, meta, **apply_kw)

        state, _ = jax.lax.while_loop(inner_cond, inner_body, (state, gain_eff))
        return state

    state = jax.lax.while_loop(outer_cond, outer_body, init_state())
    return state.tree, state.leaf_id, GrowAux(state.used_split, state.row_used)
