"""Leaf-wise tree growth as one jitted XLA program.

TPU-native re-design of the reference's SerialTreeLearner::Train loop
(reference: src/treelearner/serial_tree_learner.cpp:158-209): leaf membership
is a per-row int32 vector instead of a permuted index partition
(data_partition.hpp:21-60), histograms are built for every
histogram-pending leaf in ONE full-data pass (ops/histogram.py), and split
search evaluates all (leaf, feature, threshold) candidates at once
(ops/split.py).

Growth proceeds in ROUNDS inside a ``lax.while_loop``:

  round := histogram pass for pending leaves
        -> vectorized best-split search
        -> inner while_loop: split leaves in gain order while their
           histograms are valid (children become histogram-pending).

Equivalence to the reference's strict leaf-wise order: tree growth is
order-independent whenever every positive-gain split fits in the
``num_leaves`` budget (the set of splits is the gain>0 closure, regardless of
order). The batched order can differ from strict best-first only in WHICH
leaves receive the final few splits when the budget binds mid-round — the
per-leaf split decisions themselves are identical. The reference's
histogram-subtraction trick (serial_tree_learner.cpp:311-320) is an
optimization slot here (children are currently both recomputed in the next
round's single pass).

Guards mirror BeforeFindBestSplit (serial_tree_learner.cpp:282-322): a leaf
whose count < 2*min_data_in_leaf or hessian sum < 2*min_sum_hessian_in_leaf
is never histogrammed; max_depth masks at split-search level.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.histogram import build_histograms
from ..ops.split import (FeatureMeta, SplitInfo, SplitParams,
                         calculate_leaf_output, find_best_splits)
from .tree import TreeArrays, empty_tree

NEG_INF = -jnp.inf


class GrowState(NamedTuple):
    leaf_id: jax.Array       # [N] int32
    hist: jax.Array          # [L, F, B, 3]
    hist_valid: jax.Array    # [L] bool
    leaf_dead: jax.Array     # [L] bool (guard-failed, never splittable)
    leaf_sum_g: jax.Array    # [L]
    leaf_sum_h: jax.Array
    leaf_cnt: jax.Array
    leaf_output: jax.Array
    leaf_depth: jax.Array    # [L] int32
    best: SplitInfo
    tree: TreeArrays
    num_leaves: jax.Array    # int32
    rounds: jax.Array        # int32


def _apply_split(state: GrowState, bins: jax.Array, missing_bin: jax.Array,
                 gain_eff: jax.Array) -> Tuple[GrowState, jax.Array]:
    """Split the current best leaf (reference: SerialTreeLearner::Split,
    serial_tree_learner.cpp:564-682 + Tree::Split, tree.h:62)."""
    l = jnp.argmax(gain_eff).astype(jnp.int32)
    best = state.best
    tree = state.tree
    new_leaf = state.num_leaves
    node = state.num_leaves - 1

    feat = best.feature[l]
    thr = best.threshold[l]
    dleft = best.default_left[l]
    is_cat = best.is_cat[l]
    bitset = best.cat_bitset[l]

    # --- rows of leaf l route left/right
    col = jnp.take(bins, feat, axis=1).astype(jnp.int32)
    mb = missing_bin[feat]
    num_left = jnp.where((col == mb) & (mb >= 0), dleft, col <= thr)
    # categorical: bitset membership (Tree::CategoricalDecision, tree.h:349)
    word = jnp.take(bitset, col >> 5)
    cat_left = ((word >> (col & 31).astype(jnp.uint32)) & 1) == 1
    go_left = jnp.where(is_cat, cat_left, num_left)
    in_leaf = state.leaf_id == l
    leaf_id = jnp.where(in_leaf & ~go_left, new_leaf, state.leaf_id)

    # --- tree arrays: fix the parent link that pointed at leaf l
    parent = tree.leaf_parent[l]
    psafe = jnp.maximum(parent, 0)
    left_match = (parent >= 0) & (tree.node_left[psafe] == ~l)
    right_match = (parent >= 0) & (tree.node_right[psafe] == ~l)
    node_left = tree.node_left.at[psafe].set(
        jnp.where(left_match, node, tree.node_left[psafe]))
    node_right = tree.node_right.at[psafe].set(
        jnp.where(right_match, node, tree.node_right[psafe]))

    tree = tree._replace(
        num_leaves=state.num_leaves + 1,
        node_feature=tree.node_feature.at[node].set(feat),
        node_threshold_bin=tree.node_threshold_bin.at[node].set(thr),
        node_default_left=tree.node_default_left.at[node].set(dleft),
        node_cat=tree.node_cat.at[node].set(is_cat),
        node_cat_bitset=tree.node_cat_bitset.at[node].set(bitset),
        node_left=node_left.at[node].set(~l),
        node_right=node_right.at[node].set(~new_leaf),
        node_gain=tree.node_gain.at[node].set(best.gain[l]),
        node_value=tree.node_value.at[node].set(state.leaf_output[l]),
        node_weight=tree.node_weight.at[node].set(state.leaf_sum_h[l]),
        node_count=tree.node_count.at[node].set(state.leaf_cnt[l]),
        leaf_value=tree.leaf_value.at[l].set(best.left_output[l])
                                   .at[new_leaf].set(best.right_output[l]),
        leaf_weight=tree.leaf_weight.at[l].set(best.left_sum_h[l])
                                    .at[new_leaf].set(best.right_sum_h[l]),
        leaf_count=tree.leaf_count.at[l].set(best.left_count[l])
                                  .at[new_leaf].set(best.right_count[l]),
        leaf_depth=tree.leaf_depth.at[l].set(state.leaf_depth[l] + 1)
                                  .at[new_leaf].set(state.leaf_depth[l] + 1),
        leaf_parent=tree.leaf_parent.at[l].set(node).at[new_leaf].set(node),
    )

    new_depth = state.leaf_depth[l] + 1
    state = state._replace(
        leaf_id=leaf_id,
        tree=tree,
        hist_valid=state.hist_valid.at[l].set(False).at[new_leaf].set(False),
        leaf_sum_g=state.leaf_sum_g.at[l].set(best.left_sum_g[l])
                                   .at[new_leaf].set(best.right_sum_g[l]),
        leaf_sum_h=state.leaf_sum_h.at[l].set(best.left_sum_h[l])
                                   .at[new_leaf].set(best.right_sum_h[l]),
        leaf_cnt=state.leaf_cnt.at[l].set(best.left_count[l])
                               .at[new_leaf].set(best.right_count[l]),
        leaf_output=state.leaf_output.at[l].set(best.left_output[l])
                                     .at[new_leaf].set(best.right_output[l]),
        leaf_depth=state.leaf_depth.at[l].set(new_depth)
                                   .at[new_leaf].set(new_depth),
        num_leaves=state.num_leaves + 1,
    )
    gain_eff = gain_eff.at[l].set(NEG_INF).at[new_leaf].set(NEG_INF)
    return state, gain_eff


@functools.partial(
    jax.jit,
    static_argnames=("max_leaves", "num_bins", "max_depth", "hist_method",
                     "exact", "axis_name", "with_categorical"))
def grow_tree(bins: jax.Array, grad: jax.Array, hess: jax.Array,
              sample_mask: jax.Array, meta: FeatureMeta, params: SplitParams,
              feature_mask: jax.Array, missing_bin: jax.Array, *,
              max_leaves: int, num_bins: int, max_depth: int = -1,
              hist_method: str = "scatter",
              exact: bool = False,
              with_categorical: bool = False,
              axis_name: str | None = None) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree. Returns (tree arrays, per-row leaf index).

    Args:
      bins: [N, F] binned features (device-resident, uint8/int32).
      grad, hess: [N] objective gradients/hessians (weights folded in,
        reference: ObjectiveFunction::GetGradients).
      sample_mask: [N] f32 0/1 bagging mask (mask-based bagging keeps shapes
        static; the analog of GBDT::Bagging's index subset, gbdt.cpp:228-262).
      feature_mask: [F] f32 0/1 from column sampling (col_sampler.hpp).
      missing_bin: [F] int32 default-routed bin per feature or -1.
      exact: strict best-first order (one split per histogram round) — the
        reference's exact leaf-wise semantics even when the num_leaves budget
        binds, at the cost of one histogram pass per split. The default
        batched mode performs all available splits per round (see module
        docstring for the equivalence argument).
      axis_name: when set, rows are sharded over this mesh axis (shard_map
        context): root sums and histograms are psum'd over it — the SPMD
        analog of the reference data-parallel learner's root allreduce
        (data_parallel_tree_learner.cpp:125-152) and histogram ReduceScatter
        (:184-186). All devices then take identical split decisions with no
        further communication.
    """
    n, f = bins.shape
    L = max_leaves
    cat_words = max(1, -(-num_bins // 32))

    stats = jnp.stack([grad * sample_mask, hess * sample_mask, sample_mask],
                      axis=1).astype(jnp.float32)
    root = jnp.sum(stats, axis=0)
    if axis_name is not None:
        root = jax.lax.psum(root, axis_name)
    root_out = calculate_leaf_output(root[0], root[1], params, root[2],
                                     jnp.float32(0.0))

    def init_state() -> GrowState:
        zero_best = find_best_splits(  # shape-consistent placeholder (all -inf)
            jnp.zeros((L, f, num_bins, 3), jnp.float32),
            jnp.zeros((L,)), jnp.zeros((L,)), jnp.zeros((L,)), jnp.zeros((L,)),
            jnp.zeros((L,), jnp.int32), meta, params,
            feature_mask, max_depth, with_categorical=False,
            cat_words=cat_words)
        return GrowState(
            leaf_id=jnp.zeros((n,), jnp.int32),
            hist=jnp.zeros((L, f, num_bins, 3), jnp.float32),
            hist_valid=jnp.zeros((L,), bool),
            leaf_dead=jnp.zeros((L,), bool),
            leaf_sum_g=jnp.zeros((L,)).at[0].set(root[0]),
            leaf_sum_h=jnp.zeros((L,)).at[0].set(root[1]),
            leaf_cnt=jnp.zeros((L,)).at[0].set(root[2]),
            leaf_output=jnp.zeros((L,)).at[0].set(root_out),
            leaf_depth=jnp.zeros((L,), jnp.int32),
            best=zero_best,
            tree=empty_tree(L, cat_words),
            num_leaves=jnp.int32(1),
            rounds=jnp.int32(0),
        )

    def active_mask(state: GrowState) -> jax.Array:
        return jnp.arange(L, dtype=jnp.int32) < state.num_leaves

    def outer_cond(state: GrowState) -> jax.Array:
        pending = active_mask(state) & ~state.hist_valid & ~state.leaf_dead
        return (state.num_leaves < L) & jnp.any(pending) & (state.rounds < L)

    def outer_body(state: GrowState) -> GrowState:
        active = active_mask(state)
        # BeforeFindBestSplit guards (serial_tree_learner.cpp:282-322)
        guard = ((state.leaf_cnt >= 2.0 * params.min_data_in_leaf)
                 & (state.leaf_sum_h >= 2.0 * params.min_sum_hessian_in_leaf))
        newly_dead = active & ~state.hist_valid & ~state.leaf_dead & ~guard
        leaf_dead = state.leaf_dead | newly_dead
        pending = active & ~state.hist_valid & ~leaf_dead

        row_pending = pending[state.leaf_id]
        new_hist = build_histograms(bins, stats * row_pending[:, None],
                                    state.leaf_id, L, num_bins,
                                    method=hist_method)
        if axis_name is not None:
            new_hist = jax.lax.psum(new_hist, axis_name)
        hist = jnp.where(pending[:, None, None, None], new_hist, state.hist)
        hist_valid = state.hist_valid | pending

        best = find_best_splits(hist, state.leaf_sum_g, state.leaf_sum_h,
                                state.leaf_cnt, state.leaf_output,
                                state.leaf_depth, meta, params,
                                feature_mask, max_depth,
                                with_categorical=with_categorical,
                                cat_words=cat_words)
        state = state._replace(hist=hist, hist_valid=hist_valid,
                               leaf_dead=leaf_dead, best=best,
                               rounds=state.rounds + 1)

        gain_eff = jnp.where(active & hist_valid & ~leaf_dead, best.gain, NEG_INF)

        if exact:
            # strict best-first: one split per round, then recompute children
            def do_split(carry):
                st, ge = carry
                return _apply_split(st, bins, missing_bin, ge)

            state, _ = jax.lax.cond(
                (state.num_leaves < L) & (jnp.max(gain_eff) > 0.0),
                do_split, lambda c: c, (state, gain_eff))
            # mark all remaining splittable-but-unsplit leaves as needing
            # nothing: their hists stay valid; loop continues via pending
            # children. If nothing was split and nothing is pending, the
            # outer cond ends the loop.
            return state

        def inner_cond(carry):
            st, ge = carry
            return (st.num_leaves < L) & (jnp.max(ge) > 0.0)

        def inner_body(carry):
            st, ge = carry
            return _apply_split(st, bins, missing_bin, ge)

        state, _ = jax.lax.while_loop(inner_cond, inner_body, (state, gain_eff))
        return state

    state = jax.lax.while_loop(outer_cond, outer_body, init_state())
    return state.tree, state.leaf_id
