"""Feature quantization (binning).

Host-side re-implementation of the reference BinMapper semantics
(reference: src/io/bin.cpp:78-520, include/LightGBM/bin.h:61-225):

- ``greedy_find_bin``: equal-count greedy bin boundaries over sampled distinct
  values (reference ``GreedyFindBin``, bin.cpp:78-155).
- ``find_bin_with_zero_as_one_bin``: dedicated zero bin straddling
  ±kZeroThreshold (reference ``FindBinWithZeroAsOneBin``, bin.cpp:256-314).
- Missing handling ``MissingType {None, Zero, NaN}`` (reference bin.h:26): with
  NaN present and ``use_missing``, the LAST bin is the NaN bin
  (bin.cpp:398-402); with ``zero_as_missing`` the zero/default bin doubles as
  the missing bin.
- Categorical: categories sorted by count descending, bin 0 reserved for
  NaN/other (reference bin.cpp:424-490).

Unlike the reference we do NOT elide the most-frequent bin from histogram
storage (``most_freq_bin`` offset machinery, bin.cpp:497-516 + FixHistogram):
the TPU layout keeps dense ``[num_bins]`` histograms per feature, so
``FixHistogram`` reconstruction is unnecessary. ``most_freq_bin_`` is still
computed for sparsity bookkeeping.

Binning the full data matrix is vectorized with ``np.searchsorted`` per
feature (the analog of the per-value binary search ``BinMapper::ValueToBin``,
bin.h:464-502).
"""

from __future__ import annotations

import functools
import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from .utils import log

# reference: include/LightGBM/bin.h:30 (kZeroThreshold = 1e-35)
K_ZERO_THRESHOLD = 1e-35
# reference: include/LightGBM/bin.h:39 (kSparseThreshold = 0.7)
K_SPARSE_THRESHOLD = 0.7

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

BIN_TYPE_NUMERICAL = 0
BIN_TYPE_CATEGORICAL = 1


def _get_double_upper_bound(a: float) -> float:
    """Smallest double strictly greater than a (reference: common.h:830)."""
    return float(np.nextafter(a, np.inf))


def _check_double_equal_ordered(a: float, b: float) -> bool:
    """reference: common.h:825 CheckDoubleEqualOrdered."""
    upper = _get_double_upper_bound(a)
    return a >= b or b <= upper


def need_filter(cnt_in_bin: np.ndarray, total_cnt: int, filter_cnt: int,
                bin_type: int) -> bool:
    """Pre-filter: no threshold leaves >= filter_cnt on both sides
    (reference: bin.cpp:54-76 NeedFilter)."""
    if bin_type == BIN_TYPE_NUMERICAL:
        sum_left = 0
        for i in range(len(cnt_in_bin) - 1):
            sum_left += int(cnt_in_bin[i])
            if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                return False
    else:
        if len(cnt_in_bin) <= 2:
            for i in range(len(cnt_in_bin) - 1):
                sum_left = int(cnt_in_bin[i])
                if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                    return False
        else:
            return False
    return True


def greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray, max_bin: int,
                    total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Equal-count greedy bin upper bounds (reference: bin.cpp:78-155 GreedyFindBin)."""
    num_distinct = len(distinct_values)
    bin_upper_bound: List[float] = []
    assert max_bin > 0
    if num_distinct <= max_bin:
        cur_cnt_inbin = 0
        for i in range(num_distinct - 1):
            cur_cnt_inbin += counts[i]
            if cur_cnt_inbin >= min_data_in_bin:
                val = _get_double_upper_bound((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bin_upper_bound or not _check_double_equal_ordered(bin_upper_bound[-1], val):
                    bin_upper_bound.append(val)
                    cur_cnt_inbin = 0
        bin_upper_bound.append(math.inf)
        return bin_upper_bound

    if min_data_in_bin > 0:
        max_bin = min(max_bin, total_cnt // min_data_in_bin)
        max_bin = max(max_bin, 1)
    mean_bin_size = total_cnt / max_bin

    rest_bin_cnt = max_bin
    rest_sample_cnt = int(total_cnt)
    is_big_count_value = counts >= mean_bin_size
    rest_bin_cnt -= int(is_big_count_value.sum())
    rest_sample_cnt -= int(counts[is_big_count_value].sum())
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    upper_bounds = [math.inf] * max_bin
    lower_bounds = [math.inf] * max_bin
    bin_cnt = 0
    lower_bounds[0] = float(distinct_values[0])
    cur_cnt_inbin = 0
    for i in range(num_distinct - 1):
        if not is_big_count_value[i]:
            rest_sample_cnt -= counts[i]
        cur_cnt_inbin += counts[i]
        if (is_big_count_value[i] or cur_cnt_inbin >= mean_bin_size or
                (is_big_count_value[i + 1] and cur_cnt_inbin >= max(1.0, mean_bin_size * 0.5))):
            upper_bounds[bin_cnt] = float(distinct_values[i])
            bin_cnt += 1
            lower_bounds[bin_cnt] = float(distinct_values[i + 1])
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt_inbin = 0
            if not is_big_count_value[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = _get_double_upper_bound((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
        if not bin_upper_bound or not _check_double_equal_ordered(bin_upper_bound[-1], val):
            bin_upper_bound.append(val)
    bin_upper_bound.append(math.inf)
    return bin_upper_bound


def find_bin_with_zero_as_one_bin(distinct_values: np.ndarray, counts: np.ndarray,
                                  max_bin: int, total_sample_cnt: int,
                                  min_data_in_bin: int,
                                  forced_bounds: Optional[Sequence[float]] = None) -> List[float]:
    """Bin bounds with a dedicated zero bin (reference: bin.cpp:256-314)."""
    if forced_bounds:
        return _find_bin_with_predefined(distinct_values, counts, max_bin,
                                         total_sample_cnt, min_data_in_bin,
                                         list(forced_bounds))
    left_mask = distinct_values <= -K_ZERO_THRESHOLD
    right_mask = distinct_values > K_ZERO_THRESHOLD
    left_cnt_data = int(counts[left_mask].sum())
    cnt_zero = int(counts[~left_mask & ~right_mask].sum())
    right_cnt_data = int(counts[right_mask].sum())

    nz = np.nonzero(distinct_values > -K_ZERO_THRESHOLD)[0]
    left_cnt = int(nz[0]) if len(nz) else len(distinct_values)

    bin_upper_bound: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        denom = max(total_sample_cnt - cnt_zero, 1)
        left_max_bin = max(1, int(left_cnt_data / denom * (max_bin - 1)))
        bin_upper_bound = greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                                          left_max_bin, left_cnt_data, min_data_in_bin)
        if bin_upper_bound:
            bin_upper_bound[-1] = -K_ZERO_THRESHOLD

    nz = np.nonzero(distinct_values[left_cnt:] > K_ZERO_THRESHOLD)[0]
    right_start = (left_cnt + int(nz[0])) if len(nz) else -1

    right_max_bin = max_bin - 1 - len(bin_upper_bound)
    if right_start >= 0 and right_max_bin > 0:
        right_bounds = greedy_find_bin(distinct_values[right_start:], counts[right_start:],
                                       right_max_bin, right_cnt_data, min_data_in_bin)
        bin_upper_bound.append(K_ZERO_THRESHOLD)
        bin_upper_bound.extend(right_bounds)
    else:
        bin_upper_bound.append(math.inf)
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


def _find_bin_with_predefined(distinct_values: np.ndarray, counts: np.ndarray,
                              max_bin: int, total_sample_cnt: int,
                              min_data_in_bin: int,
                              forced_bounds: List[float]) -> List[float]:
    """Forced bin bounds + proportional greedy fill of each forced segment
    (reference: bin.cpp:157-254 FindBinWithPredefinedBin: zero/inf bounds
    first, forced bounds inserted up to the budget, then the free bins are
    distributed across segments proportional to their sample counts and
    found greedily within each)."""
    nvals = len(distinct_values)
    left_cnt = nvals
    for i in range(nvals):
        if distinct_values[i] > -K_ZERO_THRESHOLD:
            left_cnt = i
            break
    right_start = -1
    for i in range(left_cnt, nvals):
        if distinct_values[i] > K_ZERO_THRESHOLD:
            right_start = i
            break

    bin_upper_bound: List[float] = []
    if max_bin == 2:
        bin_upper_bound.append(K_ZERO_THRESHOLD if left_cnt == 0
                               else -K_ZERO_THRESHOLD)
    elif max_bin >= 3:
        if left_cnt > 0:
            bin_upper_bound.append(-K_ZERO_THRESHOLD)
        if right_start >= 0:
            bin_upper_bound.append(K_ZERO_THRESHOLD)
    bin_upper_bound.append(math.inf)

    max_to_insert = max_bin - len(bin_upper_bound)
    num_inserted = 0
    for b in forced_bounds:
        if num_inserted >= max_to_insert:
            break
        if abs(b) > K_ZERO_THRESHOLD:
            bin_upper_bound.append(float(b))
            num_inserted += 1
    bin_upper_bound.sort()

    free_bins = max_bin - len(bin_upper_bound)
    bounds_to_add: List[float] = []
    value_ind = 0
    for i, ub in enumerate(bin_upper_bound):
        cnt_in_bin = 0
        bin_start = value_ind
        while value_ind < nvals and distinct_values[value_ind] < ub:
            cnt_in_bin += int(counts[value_ind])
            value_ind += 1
        bins_remaining = (max_bin - len(bin_upper_bound)
                          - len(bounds_to_add))
        num_sub_bins = int(round(cnt_in_bin * free_bins
                                 / max(total_sample_cnt, 1)))
        num_sub_bins = min(num_sub_bins, bins_remaining) + 1
        if i == len(bin_upper_bound) - 1:
            num_sub_bins = bins_remaining + 1
        new_ub = greedy_find_bin(distinct_values[bin_start:value_ind],
                                 counts[bin_start:value_ind], num_sub_bins,
                                 cnt_in_bin, min_data_in_bin)
        bounds_to_add.extend(new_ub[:-1])       # last bound is infinity
    out = sorted(bin_upper_bound + bounds_to_add)
    assert len(out) <= max_bin
    return out


class BinMapper:
    """Per-feature value→bin mapping (reference: include/LightGBM/bin.h:61-225)."""

    def __init__(self):
        self.num_bin: int = 1
        self.missing_type: int = MISSING_NONE
        self.bin_type: int = BIN_TYPE_NUMERICAL
        self.is_trivial: bool = True
        self.sparse_rate: float = 1.0
        self.bin_upper_bound: np.ndarray = np.array([math.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}
        self.default_bin: int = 0       # bin of value 0 (bin.h GetDefaultBin)
        self.most_freq_bin: int = 0
        self.min_val: float = 0.0
        self.max_val: float = 0.0

    # ------------------------------------------------------------------ fit
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int = 3, min_split_data: int = 0,
                 pre_filter: bool = False, bin_type: int = BIN_TYPE_NUMERICAL,
                 use_missing: bool = True, zero_as_missing: bool = False,
                 forced_bounds: Optional[Sequence[float]] = None) -> None:
        """Fit the mapper on sampled values (reference: bin.cpp:325-520 FindBin).

        ``values`` are the sampled non-zero entries; ``total_sample_cnt`` is the
        number of sampled rows (zeros implied by the difference, matching the
        reference's sparse sampling protocol, dataset_loader.cpp:953+).
        """
        values = np.asarray(values, dtype=np.float64)
        na_mask = np.isnan(values)
        na_cnt = int(na_mask.sum())
        values = values[~na_mask]
        if len(values):
            vals, counts = np.unique(values, return_counts=True)
        else:
            vals, counts = np.array([]), np.array([], dtype=np.int64)
        self.find_bin_from_distinct(
            vals, counts, na_cnt, total_sample_cnt, max_bin,
            min_data_in_bin=min_data_in_bin, min_split_data=min_split_data,
            pre_filter=pre_filter, bin_type=bin_type, use_missing=use_missing,
            zero_as_missing=zero_as_missing, forced_bounds=forced_bounds)

    def find_bin_from_distinct(self, vals: np.ndarray, counts: np.ndarray,
                               na_cnt: int, total_sample_cnt: int,
                               max_bin: int, min_data_in_bin: int = 3,
                               min_split_data: int = 0,
                               pre_filter: bool = False,
                               bin_type: int = BIN_TYPE_NUMERICAL,
                               use_missing: bool = True,
                               zero_as_missing: bool = False,
                               forced_bounds: Optional[Sequence[float]] = None
                               ) -> None:
        """Fit from a pre-aggregated (sorted distinct values, counts, NaN
        count) summary — the form a streaming :class:`FeatureSketch` holds,
        and exactly what ``find_bin`` computes internally, so a sketch that
        never compacted fits BIT-IDENTICAL mappers to the sampled path.
        ``total_sample_cnt - counts.sum() - na_cnt`` rows are implied zeros
        (the sparse sampling protocol)."""
        vals = np.asarray(vals, dtype=np.float64)
        counts = np.asarray(counts, dtype=np.int64)
        na_cnt = int(na_cnt)

        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            self.missing_type = MISSING_NAN if na_cnt > 0 else MISSING_NONE

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - counts.sum() - na_cnt)

        # distinct values with counts; zero slot positioned in sorted order
        # (reference: bin.cpp:355-395)
        if zero_cnt > 0 or len(vals) == 0:
            if 0.0 not in vals:
                insert_at = int(np.searchsorted(vals, 0.0))
                vals = np.insert(vals, insert_at, 0.0)
                counts = np.insert(counts, insert_at, zero_cnt)
            else:
                counts[np.searchsorted(vals, 0.0)] += zero_cnt
        self.min_val = float(vals[0]) if len(vals) else 0.0
        self.max_val = float(vals[-1]) if len(vals) else 0.0
        counts = counts.astype(np.int64)

        cnt_in_bin: np.ndarray
        if bin_type == BIN_TYPE_NUMERICAL:
            if self.missing_type in (MISSING_ZERO, MISSING_NONE):
                bounds = find_bin_with_zero_as_one_bin(vals, counts, max_bin,
                                                       total_sample_cnt, min_data_in_bin,
                                                       forced_bounds)
                if self.missing_type == MISSING_ZERO and len(bounds) == 2:
                    self.missing_type = MISSING_NONE
            else:  # NaN bin appended as the last bin (bin.cpp:398-402)
                bounds = find_bin_with_zero_as_one_bin(vals, counts, max_bin - 1,
                                                       total_sample_cnt - na_cnt,
                                                       min_data_in_bin, forced_bounds)
                bounds.append(math.nan)
            self.bin_upper_bound = np.asarray(bounds)
            self.num_bin = len(bounds)
            # count per bin (bin.cpp:404-421)
            n_real = self.num_bin - (1 if self.missing_type == MISSING_NAN else 0)
            finite_bounds = self.bin_upper_bound[:n_real]
            cnt_in_bin = np.zeros(self.num_bin, dtype=np.int64)
            if len(vals):
                idx = np.searchsorted(finite_bounds, vals, side="left")
                # value goes to first bin whose upper bound >= value
                np.add.at(cnt_in_bin, np.minimum(idx, n_real - 1), counts)
            if self.missing_type == MISSING_NAN:
                cnt_in_bin[self.num_bin - 1] = na_cnt
        else:
            # categorical (reference: bin.cpp:424-490)
            vals_int = vals.astype(np.int64)
            neg = vals_int < 0
            if neg.any():
                log.warning("Met negative value in categorical features, will convert it to NaN")
                na_cnt += int(counts[neg].sum())
                vals_int, counts = vals_int[~neg], counts[~neg]
            # merge duplicates after int cast
            if len(vals_int):
                vals_int_u, inv = np.unique(vals_int, return_inverse=True)
                counts_u = np.zeros(len(vals_int_u), dtype=np.int64)
                np.add.at(counts_u, inv, counts)
            else:
                vals_int_u, counts_u = vals_int, counts
            rest_cnt = total_sample_cnt - na_cnt
            self.bin_2_categorical = [-1]   # bin 0 = NaN/other bin
            self.categorical_2_bin = {-1: 0}
            cnt_list = [0]
            self.num_bin = 1
            if rest_cnt > 0 and len(vals_int_u):
                order = np.argsort(-counts_u, kind="stable")
                cut_cnt = int(round((total_sample_cnt - na_cnt) * 0.99))
                distinct_cnt = len(vals_int_u) + (1 if na_cnt > 0 else 0)
                eff_max_bin = min(distinct_cnt, max_bin)
                used_cnt = 0
                for rank, j in enumerate(order):
                    if not (used_cnt < cut_cnt or self.num_bin < eff_max_bin):
                        break
                    if counts_u[j] < min_data_in_bin and rank > 1:
                        break
                    cat = int(vals_int_u[j])
                    self.bin_2_categorical.append(cat)
                    self.categorical_2_bin[cat] = self.num_bin
                    used_cnt += int(counts_u[j])
                    cnt_list.append(int(counts_u[j]))
                    self.num_bin += 1
                all_used = (self.num_bin - 1) == len(vals_int_u)
                self.missing_type = MISSING_NONE if (all_used and na_cnt == 0) else MISSING_NAN
                cnt_list[0] = int(total_sample_cnt - used_cnt)
            cnt_in_bin = np.asarray(cnt_list, dtype=np.int64)

        # trivial / pre-filter (bin.cpp:494-503)
        self.is_trivial = self.num_bin <= 1
        if (not self.is_trivial and pre_filter
                and need_filter(cnt_in_bin, int(total_sample_cnt),
                                int(min_split_data), bin_type)):
            self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = self.value_to_bin(0.0)
            self.most_freq_bin = int(np.argmax(cnt_in_bin))
            max_sparse_rate = float(cnt_in_bin[self.most_freq_bin]) / max(total_sample_cnt, 1)
            if self.most_freq_bin != self.default_bin and max_sparse_rate < K_SPARSE_THRESHOLD:
                self.most_freq_bin = self.default_bin
            self.sparse_rate = float(cnt_in_bin[self.most_freq_bin]) / max(total_sample_cnt, 1)
        else:
            self.sparse_rate = 1.0

    # ---------------------------------------------------------------- apply
    def value_to_bin(self, value: float) -> int:
        """Scalar value→bin (reference: bin.h:464-502 ValueToBin)."""
        return int(self.values_to_bins(np.array([value]))[0])

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value→bin for a whole column."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BIN_TYPE_CATEGORICAL:
            out = np.zeros(len(values), dtype=np.int32)
            if self.categorical_2_bin:
                cats = np.array(self.bin_2_categorical[1:], dtype=np.int64)
                bins = np.arange(1, self.num_bin, dtype=np.int32)
                vals_int = np.where(np.isnan(values), -1, values).astype(np.int64)
                if len(cats):
                    sorter = np.argsort(cats)
                    pos = np.searchsorted(cats[sorter], vals_int)
                    pos = np.clip(pos, 0, len(cats) - 1)
                    matched = cats[sorter][pos] == vals_int
                    out = np.where(matched, bins[sorter][pos], 0).astype(np.int32)
            return out
        has_nan_bin = self.missing_type == MISSING_NAN
        n_real = self.num_bin - (1 if has_nan_bin else 0)
        finite_bounds = self.bin_upper_bound[:n_real - 1] if n_real > 0 else np.array([])
        vals = values
        if self.missing_type == MISSING_ZERO:
            # NaN treated as zero → default bin (bin.h:479-481)
            vals = np.where(np.isnan(vals), 0.0, vals)
        idx = np.searchsorted(finite_bounds, vals, side="left").astype(np.int32)
        # value == bound goes to that bin (upper bounds inclusive): searchsorted
        # 'left' puts v==bound at the bound's bin, matching `value <= upper`.
        if has_nan_bin:
            idx = np.where(np.isnan(values), self.num_bin - 1, idx).astype(np.int32)
        return idx

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative threshold value for a bin boundary (used for real-valued
        tree thresholds, reference: tree.h RealThreshold)."""
        if self.bin_type == BIN_TYPE_CATEGORICAL:
            return float(self.bin_2_categorical[bin_idx]) if bin_idx < len(self.bin_2_categorical) else -1.0
        return float(self.bin_upper_bound[bin_idx])

    # ---------------------------------------------------------- serialization
    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "bin_type": self.bin_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_upper_bound": [float(x) for x in self.bin_upper_bound],
            "bin_2_categorical": list(self.bin_2_categorical),
            "default_bin": self.default_bin,
            "most_freq_bin": self.most_freq_bin,
            "min_val": self.min_val,
            "max_val": self.max_val,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.missing_type = int(d["missing_type"])
        m.bin_type = int(d["bin_type"])
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = [int(x) for x in d["bin_2_categorical"]]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.default_bin = int(d["default_bin"])
        m.most_freq_bin = int(d["most_freq_bin"])
        m.min_val = float(d.get("min_val", 0.0))
        m.max_val = float(d.get("max_val", 0.0))
        return m


def sample_indices(num_data: int, sample_cnt: int, seed: int) -> np.ndarray:
    """Row sample for bin finding (reference: dataset_loader.cpp sampling with
    Random::Sample; here a seeded choice without replacement)."""
    if num_data <= sample_cnt:
        return np.arange(num_data)
    rng = np.random.RandomState(seed)
    return np.sort(rng.choice(num_data, size=sample_cnt, replace=False))


def _find_bin_kwargs(j: int, config, cat_set, filter_cnt: int,
                     forced_bounds=None) -> dict:
    """Per-column binning parameters from the config — the ONE kwargs
    assembly both the sampled (``fit_mapper_for_column``) and the
    streaming (``fit_mappers_from_sketches``) fits use, so a new binning
    parameter cannot reach one construct path and miss the other (the
    bit-parity contract between them depends on it)."""
    return dict(
        max_bin=(config.max_bin_by_feature[j]
                 if j < len(config.max_bin_by_feature) else config.max_bin),
        min_data_in_bin=config.min_data_in_bin,
        min_split_data=filter_cnt,
        pre_filter=config.feature_pre_filter,
        bin_type=(BIN_TYPE_CATEGORICAL if j in cat_set
                  else BIN_TYPE_NUMERICAL),
        use_missing=config.use_missing,
        zero_as_missing=config.zero_as_missing,
        forced_bounds=(forced_bounds or {}).get(j),
    )


def fit_mapper_for_column(j: int, vals: np.ndarray, total_sample_cnt: int,
                          config, cat_set, filter_cnt: int,
                          forced_bounds=None) -> BinMapper:
    """Fit one column's BinMapper with the config's binning parameters —
    the single point both the dense and the sparse/EFB construct paths go
    through (reference: DatasetLoader::ConstructBinMappersFromTextData's
    per-feature FindBin call, dataset_loader.cpp:953-1140)."""
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=total_sample_cnt,
               **_find_bin_kwargs(j, config, cat_set, filter_cnt,
                                  forced_bounds))
    return m


def filter_cnt_for_sample(config, sample_cnt: int, num_data: int) -> int:
    """reference: dataset_loader.cpp:647-648 filter_cnt scaling."""
    return int(config.min_data_in_leaf * sample_cnt / max(num_data, 1))


def find_bin_mappers(X: np.ndarray, config, categorical_features: Sequence[int] = (),
                     forced_bounds: Optional[Dict[int, List[float]]] = None) -> List[BinMapper]:
    """Fit one BinMapper per column (reference: DatasetLoader::
    ConstructBinMappersFromTextData, dataset_loader.cpp:953-1140)."""
    num_data, num_features = X.shape
    sample_idx = sample_indices(num_data, config.bin_construct_sample_cnt,
                                config.data_random_seed)
    cat_set = set(int(c) for c in categorical_features)
    filter_cnt = filter_cnt_for_sample(config, len(sample_idx), num_data)
    return [
        fit_mapper_for_column(
            j, np.asarray(X[sample_idx, j], dtype=np.float64),
            len(sample_idx), config, cat_set, filter_cnt, forced_bounds)
        for j in range(num_features)
    ]


# ------------------------------------------------------- streaming sketch
class FeatureSketch:
    """Mergeable per-feature (distinct values, counts, NaN count) summary
    for streaming bin finding — the TPU analog of the reference's
    distributed bin-finding protocol (dataset_loader.cpp:1046-1128:
    feature-sharded FindBin merged by Network::Allgather) crossed with the
    sketch-based quantile binning of the scalable-GPU XGBoost paper
    (PAPERS.md): row chunks fold in one at a time, sketches merge
    associatively (across chunks AND across ranks over
    ``distributed.exchange_host``), and a mapper fitted from the merged
    sketch via :meth:`BinMapper.find_bin_from_distinct` equals the
    sampled-path mapper exactly while the sketch stays EXACT.

    ``max_size`` bounds the distinct-value budget: past it the sketch
    compacts to equal-mass representatives (each kept value is the upper
    edge of its mass group, so ``max_val`` is preserved and every group's
    count collapses onto its edge). Each compaction moves a value's
    cumulative rank by at most ``total/max_size``, so after ``L``
    compactions boundary ranks are within ~``L/max_size`` of exact —
    the documented rank error the parity tests assert. ``max_size=0``
    means unbounded (exact)."""

    __slots__ = ("max_size", "values", "counts", "na_cnt", "total_cnt",
                 "compactions")

    def __init__(self, max_size: int = 0):
        self.max_size = int(max_size)
        self.values = np.zeros((0,), np.float64)
        self.counts = np.zeros((0,), np.int64)
        self.na_cnt = 0
        self.total_cnt = 0
        self.compactions = 0

    def fold(self, column: np.ndarray) -> None:
        """Fold one chunk's raw column values (NaN included) into the
        sketch. Bit-path note: NaNs are stripped and the rest go through
        ``np.unique`` — the same normalization ``find_bin`` applies."""
        col = np.asarray(column, dtype=np.float64).reshape(-1)
        self.total_cnt += len(col)
        na = np.isnan(col)
        n_na = int(na.sum())
        if n_na:
            self.na_cnt += n_na
            col = col[~na]
        if len(col):
            v, c = np.unique(col, return_counts=True)
            self._merge_arrays(v, c.astype(np.int64))

    def merge(self, other: "FeatureSketch") -> "FeatureSketch":
        """Fold another sketch in (rank merge). Associative up to the
        compaction error; exact when neither side ever compacted."""
        self.na_cnt += other.na_cnt
        self.total_cnt += other.total_cnt
        self.compactions = max(self.compactions, other.compactions)
        self._merge_arrays(other.values, other.counts)
        return self

    def _merge_arrays(self, v: np.ndarray, c: np.ndarray) -> None:
        if len(v):
            if len(self.values):
                allv = np.concatenate([self.values, v])
                allc = np.concatenate([self.counts, c])
                uv, inv = np.unique(allv, return_inverse=True)
                uc = np.zeros(len(uv), np.int64)
                np.add.at(uc, inv.reshape(-1), allc)
                self.values, self.counts = uv, uc
            else:
                self.values = np.asarray(v, np.float64).copy()
                self.counts = np.asarray(c, np.int64).copy()
        if self.max_size and len(self.values) > self.max_size:
            self._compact()

    def _compact(self) -> None:
        """Equal-mass compaction to ``max_size`` representatives. The zero
        slot is force-retained when present (the dedicated zero bin of
        ``find_bin_with_zero_as_one_bin`` keys on it)."""
        n = len(self.values)
        m = self.max_size
        cum = np.cumsum(self.counts)
        total = int(cum[-1])
        edges = np.searchsorted(cum, total * (np.arange(1, m + 1) / m),
                                side="left")
        edges = np.clip(edges, 0, n - 1)
        zi = int(np.searchsorted(self.values, 0.0))
        if zi < n and self.values[zi] == 0.0:
            edges = np.append(edges, zi)
        edges = np.unique(edges)
        grp_cnt = np.diff(np.concatenate([[0], cum[edges]]))
        self.values = self.values[edges]
        self.counts = grp_cnt.astype(np.int64)
        self.compactions += 1

    @property
    def exact(self) -> bool:
        return self.compactions == 0

    # JSON payloads for the cross-rank exchange_host merge: repr-based
    # float serialization round-trips f64 bit-exactly, so a merged-then-
    # fitted mapper is identical on every rank
    def to_dict(self) -> dict:
        return {"max_size": self.max_size,
                "values": [float(x) for x in self.values],
                "counts": [int(x) for x in self.counts],
                "na_cnt": int(self.na_cnt),
                "total_cnt": int(self.total_cnt),
                "compactions": int(self.compactions)}

    @classmethod
    def from_dict(cls, d: dict) -> "FeatureSketch":
        sk = cls(int(d.get("max_size", 0)))
        sk.values = np.asarray(d["values"], np.float64)
        sk.counts = np.asarray(d["counts"], np.int64)
        sk.na_cnt = int(d["na_cnt"])
        sk.total_cnt = int(d["total_cnt"])
        sk.compactions = int(d.get("compactions", 0))
        return sk


def split_chunk(chunk):
    """Normalize one chunk to ``(X [rows, F] ndarray, labels-or-None)``.
    Chunk sources may yield bare feature arrays or ``(X, y)`` pairs."""
    y = None
    if isinstance(chunk, (tuple, list)) and len(chunk) == 2:
        chunk, y = chunk
    X = np.asarray(chunk)
    if X.ndim == 1:
        X = X.reshape(-1, 1)
    if y is not None:
        y = np.asarray(y, dtype=np.float64).reshape(-1)
    return X, y


def chunk_factory(source, chunk_rows: int = 0):
    """Normalize a chunk source into a re-iterable factory (the streaming
    construct runs TWO passes — sketch, then bin — so a one-shot
    generator cannot feed it):

    - a callable -> called per pass, must return a fresh iterator of
      chunks (each a ``[rows, F]`` array or an ``(X, y)`` pair);
    - a list/tuple of chunks -> iterated per pass;
    - a 2-D array (or anything array-like with ``.shape``) -> sliced into
      ``chunk_rows`` row views (no copies).
    """
    from .utils import log as _log
    default = int(chunk_rows) if chunk_rows else (1 << 20)
    if callable(source):
        return source
    if isinstance(source, (list, tuple)):
        return lambda: iter(source)
    if hasattr(source, "shape") and getattr(source, "ndim", 0) == 2:
        def _slices():
            n = source.shape[0]
            for s in range(0, max(n, 1), default):
                yield source[s:s + default]
        return _slices
    _log.fatal("chunk source must be re-iterable: a callable returning an "
               "iterator of chunks, a sequence of chunk arrays, or a 2-D "
               f"array (got {type(source).__name__}; a one-shot generator "
               "cannot feed the two construct passes)")


def sketch_chunks(factory, max_size: int = 0, track_bytes=None,
                  fold: bool = True):
    """Pass 1 of streaming construction: fold every chunk into per-feature
    :class:`FeatureSketch` es, holding at most ONE raw chunk at a time.

    Returns ``(sketches, num_data, chunk_sizes, labels)`` where ``labels``
    is the concatenation of per-chunk label parts (None when chunks carry
    no labels). ``track_bytes``: optional callback fed each chunk's raw
    byte size (the construct_peak_bytes gauge). ``fold=False`` skips the
    per-column fold (the dominant wall) but keeps the row/size/label
    accounting and the mid-stream width check — the light pass a
    reference-aligned valid set needs (its mappers come from the
    reference)."""
    sketches: Optional[List[FeatureSketch]] = None
    num_data = 0
    sizes: List[int] = []
    label_parts: List[np.ndarray] = []
    # explicit next() loop so the previous chunk's reference is DROPPED
    # before the source builds the next one — a plain for-loop keeps the
    # loop variable bound across next(), holding two chunks alive
    it = iter(factory())
    while True:
        chunk = next(it, None)
        if chunk is None:
            break
        X, y = split_chunk(chunk)
        chunk = None
        if track_bytes is not None:
            track_bytes(int(getattr(X, "nbytes", 0)))
        if sketches is None:
            sketches = [FeatureSketch(max_size) for _ in range(X.shape[1])]
        elif X.shape[1] != len(sketches):
            from .utils import log as _log
            _log.fatal(f"chunk feature count changed mid-stream: "
                       f"{X.shape[1]} vs {len(sketches)}")
        if fold:
            for j in range(X.shape[1]):
                sketches[j].fold(X[:, j])
        num_data += X.shape[0]
        sizes.append(X.shape[0])
        if y is not None:
            label_parts.append(y)
        X = None
    if sketches is None:
        from .utils import log as _log
        _log.fatal("chunk source yielded no chunks")
    labels = np.concatenate(label_parts) if label_parts else None
    return sketches, num_data, sizes, labels


def fit_mappers_from_sketches(sketches: Sequence[FeatureSketch],
                              num_data: int, config,
                              categorical_features: Sequence[int] = (),
                              forced_bounds: Optional[Dict[int, List[float]]]
                              = None) -> List[BinMapper]:
    """Fit one BinMapper per feature from (possibly rank-merged) sketches
    — the streaming twin of :func:`find_bin_mappers`. With exact sketches
    whose total covers every row, this IS the sampled path's fit (the
    sample being all rows), so mappers are bit-identical whenever
    ``num_data <= bin_construct_sample_cnt``."""
    cat_set = set(int(c) for c in categorical_features)
    total = int(sketches[0].total_cnt) if len(sketches) else 0
    filter_cnt = filter_cnt_for_sample(config, total, num_data)
    out = []
    for j, sk in enumerate(sketches):
        if j in cat_set and sk.compactions > 0:
            # equal-mass compaction merges distinct CODES into their
            # group's upper-edge code — meaningless for unordered
            # categories and silently different from the sampled path.
            # Fail loudly instead of fitting wrong category maps.
            from .utils import log as _log
            _log.fatal(
                f"categorical feature {j} exceeded sketch_max_size "
                f"({sk.max_size}) distinct codes during streaming "
                f"construction and was compacted; raise sketch_max_size "
                f"above the category count (rank-error compaction only "
                f"applies to numerical features)")
        m = BinMapper()
        m.find_bin_from_distinct(
            sk.values, sk.counts, sk.na_cnt, sk.total_cnt,
            **_find_bin_kwargs(j, config, cat_set, filter_cnt,
                               forced_bounds))
        out.append(m)
    return out


def bin_data(X: np.ndarray, mappers: Sequence[BinMapper]) -> np.ndarray:
    """Quantize the full matrix → int32 bin matrix [num_data, num_features]."""
    num_data, num_features = X.shape
    out = np.zeros((num_data, num_features), dtype=np.int32)
    for j, m in enumerate(mappers):
        if m.is_trivial:
            continue
        out[:, j] = m.values_to_bins(np.asarray(X[:, j], dtype=np.float64))
    return out


# ------------------------------------------------------------ device binning
def device_bin_tables(mappers: Sequence[BinMapper]):
    """Per-feature tables for on-device quantization of float32 data.

    The host path compares float64 values against float64 upper bounds
    (``values_to_bins``: idx = #{bounds < v}). For float32 inputs the same
    predicate is computed exactly in f32 by replacing each f64 bound b with
    the largest f32 <= b: for any f32 v, (v > b) == (v > b_dn). Returns
    (bounds_dn [F, Bpad] f32 (+inf padded), nan_to_zero [F] bool,
    nan_bin [F] int32).
    """
    fs = len(mappers)
    finite = []
    nan_to_zero = np.zeros((fs,), dtype=bool)
    nan_bin = np.zeros((fs,), dtype=np.int32)
    for i, m in enumerate(mappers):
        assert m.bin_type == BIN_TYPE_NUMERICAL
        has_nan_bin = m.missing_type == MISSING_NAN
        n_real = m.num_bin - (1 if has_nan_bin else 0)
        fb = np.asarray(m.bin_upper_bound[:n_real - 1], dtype=np.float64) \
            if n_real > 0 else np.zeros((0,), np.float64)
        finite.append(fb)
        nan_to_zero[i] = m.missing_type == MISSING_ZERO
        # NaN routing matches the host semantics: NaN-as-missing gets the
        # top bin; with no NaN handling, searchsorted lands NaN at the end
        # of the finite bounds (bin n_real-1)
        nan_bin[i] = m.num_bin - 1 if has_nan_bin else max(n_real - 1, 0)
    bpad = max(1, max((len(fb) for fb in finite), default=1))
    bounds = np.full((fs, bpad), np.inf, dtype=np.float32)
    for i, fb in enumerate(finite):
        if not len(fb):
            continue
        b32 = fb.astype(np.float32)
        over = b32.astype(np.float64) > fb
        bounds[i, :len(fb)] = np.where(
            over, np.nextafter(b32, np.float32(-np.inf)), b32)
    return bounds, nan_to_zero, nan_bin


def _quantize_block(xs, bd, nz, nb, odt):
    """The device quantize predicate ONE block of float32 rows goes
    through — shared by ``bin_data_device`` and ``StreamingBinWriter``
    so the streaming path's bit-exactness contract (same bins as the
    monolithic device pass, and via ``device_bin_tables`` the host pass)
    is enforced structurally, not by parallel copies staying in sync.
    ``xs [rows, F]`` f32, ``bd [F, Bpad]`` downshifted bounds, ``nz [F]``
    NaN-as-zero mask, ``nb [F]`` NaN routing bin; returns ``[rows, F]``
    of dtype ``odt``."""
    import jax.numpy as jnp
    v = jnp.where(jnp.isnan(xs) & nz[None, :], 0.0, xs)
    cnt = jnp.sum(v[:, :, None] > bd[None, :, :], axis=-1, dtype=jnp.int32)
    cnt = jnp.where(jnp.isnan(v), nb[None, :], cnt)
    return cnt.astype(odt)


def bin_chunks_host(factory, used: Sequence[BinMapper], uf, out: np.ndarray,
                    track=None) -> None:
    """Pass 2's HOST fallback: re-iterate the chunk source and write each
    chunk's per-column ``bin_data`` result into its row slot of ``out``
    — shared by ``Dataset._construct_streaming`` (non-f32/categorical
    streams) and ``distributed.load_partitioned_chunks``. Maintains the
    ref-dropping iteration discipline (<= the current chunk + its f64
    column copy resident, reported through ``track``) and VERIFIES the
    source yielded exactly ``len(out)`` rows — a source that under-yields
    on its second iteration must fail loudly, not train on the zero
    tail."""
    from .utils import log as _log
    row = 0
    it = iter(factory())
    while True:                            # ref-dropping next() loop
        chunk = next(it, None)
        if chunk is None:
            break
        X, _y = split_chunk(chunk)
        chunk = None
        n = X.shape[0]
        if len(uf):
            # subset FIRST, then widen: np.asarray(X, f64)[:, uf] would
            # materialize a full-width f64 temp (2x the chunk) before
            # the column select; f32->f64 is exact so this is
            # bit-equivalent with a smaller transient
            Xu = np.asarray(X[:, uf] if X.shape[1] != len(uf) else X,
                            np.float64)
            if track is not None:
                # resident: the source chunk + its f64 column copy
                track(X.nbytes + Xu.nbytes)
            X = None
            out[row:row + n] = bin_data(Xu, used)
            Xu = None
        else:
            if track is not None:
                track(X.nbytes)
            X = None
        row += n
    if row != len(out):
        _log.fatal(f"chunk source yielded {row} rows on the bin pass but "
                   f"{len(out)} on the sketch pass: the source must be "
                   f"re-iterable and deterministic (a one-shot iterator "
                   f"cannot feed the two construct passes)")


def bin_data_device(X, mappers: Sequence[BinMapper], block: int = 1 << 17):
    """Quantize a float32 matrix on device (the TPU replacement for the
    host ``bin_data`` loop — this box's single CPU core makes the host
    searchsorted pass the construct bottleneck at 10M+ rows; reference
    pushes rows through DenseBin with OpenMP, dense_bin.hpp).

    Bit-exact vs ``bin_data`` for float32 input (see device_bin_tables).
    Returns a DEVICE array [N, F] uint8/int32.
    """
    import jax
    import jax.numpy as jnp

    assert X.dtype == np.float32
    n, fs = X.shape
    bounds, nan_to_zero, nan_bin = device_bin_tables(mappers)
    max_bin = max(m.num_bin for m in mappers) if fs else 2
    out_dtype = jnp.uint8 if max_bin <= 256 else jnp.int32
    c = min(block, n) if n else 1
    pad = -n % c

    @functools.partial(jax.jit, static_argnames=("odt",))
    def run(xd, bd, nz, nb, odt):
        def body(_, xb):
            return _, _quantize_block(xb, bd, nz, nb, odt)

        _, bins = jax.lax.scan(body, 0, xd.reshape(-1, c, fs))
        return bins.reshape(-1, fs)

    xd = jnp.asarray(np.pad(X, ((0, pad), (0, 0))) if pad else X)
    bins = run(xd, jnp.asarray(bounds), jnp.asarray(nan_to_zero),
               jnp.asarray(nan_bin), out_dtype)
    return bins[:n] if pad else bins


class StreamingBinWriter:
    """Pass 2 of streaming construction: quantize float32 row chunks ON
    DEVICE and write each into its row slot of one preallocated (donated)
    ``[N_pad, F]`` bin matrix — the pre-sharded destination of the chunked
    pipeline (SNIPPETS.md [1] naive-sharding: the leading axis is the one
    a row-sharded mesh splits). Every ``write`` is one async jitted
    dispatch (pad to a fixed chunk shape -> one compiled program), so
    chunk k's H2D transfer + device quantize overlap chunk k+1's host
    parse: the double buffer is the dispatch queue itself, and host
    residency stays at the current chunk + its padded copy (<= 2 chunks
    of raw data). Quantization is the ``bin_data_device`` predicate —
    bit-exact vs the host ``bin_data`` path for float32 input (see
    ``device_bin_tables``).

    Residency is HARD-BOUNDED, not best-effort: each ``write`` first
    drains the previous dispatch (at most ONE write in flight — the
    caller's parse of chunk k+1 already overlapped chunk k's transfer
    and compute between the two calls, so the wait costs no overlap) and
    copies the chunk into a fresh staging buffer rather than handing the
    caller's array to jax (which may pin it for the dispatch lifetime).
    Peak host residency: one source chunk + one staged copy — the
    "<= 2 chunks of raw data" acceptance bound; an unbounded dispatch
    queue would instead retain O(queue-depth) chunks.

    Writes past a chunk's true row count spill pad garbage into the NEXT
    chunk's slot, which that chunk's later write overwrites (dispatches
    are ordered by the donated-buffer dependency); the allocation keeps
    ``max_chunk_rows`` spare rows so the LAST chunk's spill stays in
    bounds, and ``finalize`` slices the matrix back to ``total_rows``.
    """

    def __init__(self, mappers: Sequence[BinMapper], total_rows: int,
                 max_chunk_rows: int, sub_block: int = 1 << 15):
        import jax
        import jax.numpy as jnp

        assert all(m.bin_type == BIN_TYPE_NUMERICAL for m in mappers)
        self._num_mappers = len(mappers)
        self.f = max(len(mappers), 1)
        bounds, nan_to_zero, nan_bin = (
            device_bin_tables(mappers) if len(mappers)
            else (np.full((1, 1), np.inf, np.float32),
                  np.zeros((1,), bool), np.zeros((1,), np.int32)))
        max_bin = max((m.num_bin for m in mappers), default=2)
        self.dtype = jnp.uint8 if max_bin <= 256 else jnp.int32
        self.n = int(total_rows)
        c = min(int(sub_block), max(int(max_chunk_rows), 1))
        self.chunk_pad = -(-max(int(max_chunk_rows), 1) // c) * c
        self._sub = c
        self._bounds = jnp.asarray(bounds)
        self._nz = jnp.asarray(nan_to_zero)
        self._nb = jnp.asarray(nan_bin)
        self._out = jnp.zeros((self.n + self.chunk_pad, self.f), self.dtype)
        self._next = 0

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _write(out, xb, start, bd, nz, nb):
            def body(_, xs):
                return _, _quantize_block(xs, bd, nz, nb, out.dtype)

            _, bins = jax.lax.scan(body, 0, xb.reshape(-1, c, xb.shape[1]))
            return jax.lax.dynamic_update_slice(
                out, bins.reshape(-1, xb.shape[1]), (start, 0))

        self._write_fn = _write

    def write(self, chunk: np.ndarray) -> None:
        """Dispatch one chunk's quantize-and-place (async), after
        draining the PREVIOUS write — see the class docstring's
        residency bound."""
        import jax
        import jax.numpy as jnp
        chunk = np.asarray(chunk, dtype=np.float32)
        if chunk.ndim == 1:
            chunk = chunk.reshape(-1, 1)
        rows = chunk.shape[0]
        assert rows <= self.chunk_pad, (rows, self.chunk_pad)
        assert self._next + rows <= self.n, "writer overflow"
        if self._num_mappers != 0:
            assert chunk.shape[1] == self.f, (chunk.shape, self.f)
        if self._next:
            jax.block_until_ready(self._out)   # <= 1 write in flight
        staged = np.zeros((self.chunk_pad, self.f), np.float32)
        if self._num_mappers != 0:
            staged[:rows] = chunk
        del chunk                              # staging owns the only copy
        self._out = self._write_fn(self._out, jnp.asarray(staged),
                                   jnp.int32(self._next), self._bounds,
                                   self._nz, self._nb)
        self._next += rows

    def finalize(self):
        """Drain the dispatch queue and return the device ``[N, F]`` bin
        matrix. The blocking wait here is the NON-overlapped tail of the
        pipeline — callers time it as the ``h2d_overlap`` sub-scope."""
        import jax
        assert self._next == self.n, (self._next, self.n)
        out, self._out = self._out, None
        out = out[:self.n]
        jax.block_until_ready(out)
        return out
