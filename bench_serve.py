"""Serving-layer benchmark: latency percentiles and sustained rows/sec
through the ServeFrontend under concurrent mixed-size load.

bench.py measures the TRAINING plane; this is its serve-plane twin for
ROADMAP item 4 ("serve batched predictions to millions of users"). An
OPEN-LOOP arrival process (request start times are fixed up front at
``--rps``, independent of completions — the load a front end actually
faces, where a slow server does not slow the clients down) submits a
small/large request mix from many client threads; the frontend coalesces
them into bucketed engine dispatches with a ``serve_flush_ms`` deadline.

Prints result JSON lines to stdout in the bench.py shape ({"metric", ...};
parsers take the LAST line) with the serve fields alongside the existing
bench fields (backend, scale, health snapshot):

  serve_p50_ms / serve_p99_ms   end-to-end request latency percentiles
                                (queue wait + coalesced dispatch + split)
  serve_rows_per_sec            successfully answered rows / wall time
  serve_shed_count              admission-control rejections during the
                                measured load (ServeOverloadError)
  serve_timeout_count           deadline misses (ServeTimeoutError)
  serve_coalesce_ratio          requests per engine dispatch (>1 = the
                                micro-batcher is earning its flush delay)

A CPU run (--cpu / --fast) is a functional number, not the benchmark —
the dispatch floor on this 1-core container is milliseconds — but the
MACHINERY measured (admission, coalescing, deadline accounting, donated
serve buffers) is backend-independent, which is what CI asserts via the
fast-knob stanza in tests/run_suite.sh.
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def build_model(args):
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.normal(size=(args.train_rows, args.features))
    y = (X[:, 0] + 0.4 * X[:, 1] - 0.2 * X[:, 2] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": args.num_leaves,
              "min_data_in_leaf": 20, "verbosity": -1, "seed": 3,
              "serve_flush_ms": args.flush_ms,
              "serve_max_queue_rows": args.max_queue_rows}
    booster = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                        args.rounds)
    return booster, X


def request_mix(args, n_requests):
    """Deterministic small/large size mix: mostly single-digit-row
    point-lookups with a heavy tail of batch scorers — the shape that
    makes micro-batching matter (small requests ride along with big
    ones into one bucketed dispatch)."""
    import numpy as np
    rng = np.random.RandomState(11)
    small = rng.choice([1, 2, 4, 8], size=n_requests)
    large = rng.choice(args.large_sizes, size=n_requests)
    is_large = rng.uniform(size=n_requests) < args.large_frac
    return np.where(is_large, large, small)


def run_load(fe, X, sizes, args):
    """Open-loop load: request i starts at t0 + i/rps regardless of how
    the previous ones are doing. Client threads pull the next arrival,
    sleep until its slot, submit, record. If every client is busy when a
    slot comes due the submission is late — counted (late_starts) so a
    saturated client pool is visible instead of silently turning the
    measurement closed-loop."""
    import numpy as np
    from lightgbm_tpu.serving import ServeOverloadError, ServeTimeoutError

    lat_ms = []
    ok_rows = [0]
    sheds = [0]
    timeouts = [0]
    late = [0]
    errors = []
    lock = threading.Lock()
    next_i = [0]
    t0 = time.monotonic()

    def client():
        while True:
            with lock:
                i = next_i[0]
                if i >= len(sizes):
                    return
                next_i[0] += 1
            rows = int(sizes[i])
            slot = t0 + i / args.rps
            now = time.monotonic()
            if now < slot:
                time.sleep(slot - now)
            elif now - slot > 0.5 / args.rps:
                with lock:
                    late[0] += 1
            a = (i * 131) % max(len(X) - rows, 1)
            t_req = time.monotonic()
            try:
                fe.predict(X[a:a + rows],
                           deadline_ms=args.deadline_ms or None)
            except ServeOverloadError:
                with lock:
                    sheds[0] += 1
                continue
            except ServeTimeoutError:
                with lock:
                    timeouts[0] += 1
                continue
            except BaseException as e:     # noqa: BLE001 — reported
                with lock:
                    errors.append(repr(e))
                continue
            dt = (time.monotonic() - t_req) * 1e3
            with lock:
                lat_ms.append(dt)
                ok_rows[0] += rows
    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    lat = np.asarray(lat_ms) if lat_ms else np.asarray([float("nan")])
    return {
        "serve_p50_ms": round(float(np.percentile(lat, 50)), 3),
        "serve_p99_ms": round(float(np.percentile(lat, 99)), 3),
        "serve_rows_per_sec": round(ok_rows[0] / max(wall, 1e-9), 1),
        "serve_shed_count": sheds[0],
        "serve_timeout_count": timeouts[0],
        "serve_requests_ok": len(lat_ms),
        "serve_requests_total": int(len(sizes)),
        "serve_late_starts": late[0],
        "serve_wall_s": round(wall, 3),
        "errors": errors[:5],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=20.0,
                    help="seconds of open-loop load")
    ap.add_argument("--rps", type=float, default=200.0,
                    help="open-loop request arrival rate")
    ap.add_argument("--clients", type=int, default=16,
                    help="client threads submitting the arrival schedule")
    ap.add_argument("--train-rows", type=int, default=20_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--num-leaves", type=int, default=63)
    ap.add_argument("--rounds", type=int, default=50)
    ap.add_argument("--flush-ms", type=float, default=2.0)
    ap.add_argument("--max-queue-rows", type=int, default=65536)
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-request deadline (0 = none)")
    ap.add_argument("--large-frac", type=float, default=0.2)
    ap.add_argument("--large-sizes", type=int, nargs="+",
                    default=[256, 512])
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    ap.add_argument("--fast", action="store_true",
                    help="CI smoke knobs: tiny model, ~3 s of load, CPU")
    args = ap.parse_args()
    if args.fast:
        args.cpu = True
        args.duration = min(args.duration, 3.0)
        args.rps = min(args.rps, 120.0)
        args.train_rows = min(args.train_rows, 3000)
        args.features = min(args.features, 10)
        args.num_leaves = min(args.num_leaves, 15)
        args.rounds = min(args.rounds, 8)
        args.clients = min(args.clients, 8)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    backend = jax.devices()[0].platform
    print(f"# device: {jax.devices()[0]}", file=sys.stderr)

    t_build = time.time()
    booster, X = build_model(args)
    print(f"# model trained in {time.time() - t_build:.1f}s",
          file=sys.stderr)

    from lightgbm_tpu import distributed
    from lightgbm_tpu.serving import ServeFrontend
    fe = ServeFrontend(booster, flush_ms=args.flush_ms,
                       max_queue_rows=args.max_queue_rows)
    try:
        # warm every bucket the mix can hit OUTSIDE the measured window
        # (compiles are a cold-start cost, not a steady-state latency)
        for rows in sorted({1, 8, *args.large_sizes}):
            fe.predict(X[:rows])
        n_requests = max(int(args.duration * args.rps), 1)
        sizes = request_mix(args, n_requests)
        print(f"# open-loop load: {n_requests} requests @ {args.rps:g}/s "
              f"({args.clients} clients, flush {args.flush_ms:g} ms)",
              file=sys.stderr)
        result = run_load(fe, X, sizes, args)
        st = fe.stats()
    finally:
        fe.close()

    batches = max(st["batches"], 1)
    result.update({
        "metric": "serve_bench",
        "backend": backend,
        "train_rows": args.train_rows,
        "features": args.features,
        "num_leaves": args.num_leaves,
        "rounds": args.rounds,
        "rps_target": args.rps,
        "serve_flush_ms": args.flush_ms,
        "serve_deadline_ms": args.deadline_ms,
        "serve_batches": st["batches"],
        "serve_coalesce_ratio": round(st["requests"] / batches, 2),
        "health": distributed.health_snapshot().get("serve"),
    })
    print(json.dumps(result), flush=True)
    if result["errors"]:
        print(f"# FAIL: unexpected request errors: {result['errors']}",
              file=sys.stderr)
        return 1
    if result["serve_requests_ok"] == 0:
        print("# FAIL: no request completed", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
