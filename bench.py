"""Benchmark: Higgs-class GBDT training throughput on one TPU chip.

Mirrors the reference's headline benchmark (docs/Experiments.rst:108-124 —
Higgs 10.5M train rows x 28 features, 255 leaves, lr 0.1, max_bin 255;
130.094 s / 500 iters = 0.260 s/iter on 2x Xeon E5-2690 v4). Data is
synthetic Higgs-shaped (the real HIGGS file isn't in the image); the cost of
a boosting iteration depends on (rows, features, bins, leaves), not label
values, so sec/iter is comparable.

Runs a fallback ladder (10.5M -> 2M -> 500k rows) so an OOM or compile
failure at full scale still reports a number at the largest completing
scale. Prints a per-phase breakdown to stderr and result JSON lines to
stdout: {"metric", "value", "unit", "vs_baseline", ...} where vs_baseline
= reference_sec_per_iter / ours, scaled to the rows actually run (>1 means
faster than the reference CPU baseline at that scale). The headline line
prints as soon as the main run completes (insurance against a tunnel
wedge during the secondary probes) and again, enriched with the probe
fields, at the end — parsers must take the LAST JSON line.

The main run trains with the leaf-partitioned row-compaction ladder ON
(the default) and reports its ``rows_streamed_per_tree`` /
``compact_sec_per_iter``; a compaction-off probe at the same scale emits
``nocompact_sec_per_iter`` + ``nocompact_rows_streamed_per_tree`` so the
headroom (the DataPartition-analog row reduction) is on record on every
backend. The q8 / max_bin=63 probes remain TPU-only.
"""

import argparse
import json
import os
import sys
import time
import traceback

# per-phase timer table (the reference's USE_TIMETAG analog) — enabled
# before the library imports so every run prints the breakdown
os.environ.setdefault("LIGHTGBM_TPU_TIMETAG", "1")

BASELINE_SEC_PER_ITER = 130.094 / 500  # docs/Experiments.rst:108-124
FULL_ROWS = 10_500_000
# v5e peaks (MFU denominator assumptions): the f32 number is the
# conservative legacy denominator; the histogram pipeline's production
# modes run bf16 (hilo: 2-pass) or int8 (q8) MXU passes, whose peaks are
# 2x / 4x higher — reporting MFU against the WRONG peak overstates
# (hilo vs f32) or hides (q8) the remaining headroom, so both
# denominators are emitted and each probe uses its own mode's peak
PEAK_F32_FLOPS = 98e12
PEAK_FLOPS = {"f32": 98e12, "bf16": 197e12, "int8": 394e12}
# histogram_method -> the MXU input rate its contraction actually runs at
MODE_PEAK = {"auto": "bf16", "pallas_hilo": "bf16", "onehot_hilo": "bf16",
             "pallas": "bf16", "onehot": "bf16",      # HIGHEST = bf16 passes
             "pallas_q8": "int8", "onehot_q8": "int8",
             "scatter": "f32", "binloop": "f32"}


def mfu_estimates(sec_per_iter, rows, features, max_bin, num_leaves,
                  hist_method="auto"):
    """Nominal-useful-flops MFU against BOTH the f32 peak (the legacy
    conservative denominator) and the bf16 peak, plus the mode-matched
    number (``mfu_mode``: the peak of the MXU path this method actually
    drives — int8 for q8, so quantized speedups are not flattered by an
    f32 denominator). Nominal work is mode-independent: the dense
    histogram pass's 2*N*F*B*S MACs, ~log2(num_leaves) passes per tree
    with subtraction."""
    import math
    nominal = (2.0 * rows * features * max_bin * 3
               * math.ceil(math.log2(max(num_leaves, 2))))
    per_sec = nominal / max(sec_per_iter, 1e-12)
    return {
        "mfu_f32": per_sec / PEAK_FLOPS["f32"],
        "mfu_bf16": per_sec / PEAK_FLOPS["bf16"],
        "mfu_mode": per_sec / PEAK_FLOPS[MODE_PEAK.get(hist_method, "f32")],
    }


def _compile_totals():
    """Persistent-compile-cache counters (zeros when the hook is off)."""
    try:
        from lightgbm_tpu import compile_cache
        t = compile_cache.totals()
        return {"hits": t.get("hits", 0), "misses": t.get("misses", 0)}
    except Exception:
        return {"hits": 0, "misses": 0}


def _warm_child(cfg):
    """Second-process warm-start measurement (--warm-child, spawned by
    the warm-start probe): rebuild the SAME-shape dataset and booster
    against the SAME persistent compile cache the parent just filled,
    time the first dispatch (data gen/construct excluded — the wall being
    measured is the XLA compile), and report this process's fused-step
    cache counters. Zero fused misses == the compile wall is gone."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu import compile_cache
    rng = np.random.RandomState(0)
    n, f = cfg["rows"], cfg["features"]
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=f)
    logits = X[:, : f // 2] @ w[: f // 2] + 0.5 * np.sin(X[:, f // 2]) * X[:, 0]
    y = (logits + rng.logistic(size=n) > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"max_bin": cfg["max_bin"],
                                         "verbosity": -1})
    ds.construct()
    K = cfg["K"]
    booster = lgb.Booster(params={
        "objective": "binary", "num_leaves": cfg["num_leaves"],
        "learning_rate": 0.1, "max_bin": cfg["max_bin"],
        "min_data_in_leaf": 100, "min_sum_hessian_in_leaf": 100.0,
        "histogram_method": cfg["hist_method"], "verbosity": -1,
        "boost_rounds_per_dispatch": K,
        "compile_cache_dir": cfg["cache_dir"]}, train_set=ds)
    if K > 1:
        booster._boosting._block_target = 1 << 30
    t0 = time.time()
    booster.update()
    warm = time.time() - t0
    print(json.dumps({
        "warm_start_s": round(warm, 3),
        "warm_fused_misses": compile_cache.module_count("misses",
                                                        "jit__fused"),
        "warm_fused_hits": compile_cache.module_count("hits",
                                                      "jit__fused"),
        "warm_cache_hits": _compile_totals()["hits"],
        "warm_cache_misses": _compile_totals()["misses"]}))


def higgs_weights(features, seed=0):
    """The label weight vector every Higgs-shaped datagen site shares —
    ONE definition so the --streaming train stream, its held-out valid
    rows and the monolithic branch stay the same task (a drifted copy
    would silently turn the AUC anchor into a mismatched-distribution
    measurement)."""
    import numpy as np
    return np.random.RandomState(seed).normal(size=features)


def higgs_logits(X, w):
    """Higgs-shaped label logits for feature matrix ``X`` under weight
    vector ``w`` (see higgs_weights)."""
    import numpy as np
    f = X.shape[1]
    return (X[:, : f // 2] @ w[: f // 2]
            + 0.5 * np.sin(X[:, f // 2]) * X[:, 0])


def higgs_chunk_stream(rows, features, chunk_rows, seed=0):
    """Chunked Higgs-shaped datagen: a callable chunk factory yielding
    ``(X_chunk, y_chunk)`` pairs, each generated from its own per-chunk
    RandomState — so the 100M-shape round NEVER holds the raw ``[N, F]``
    matrix in host RAM (the monolithic datagen's 11.8 GB at 100M x 28 f32
    was the other half of the construct ceiling, next to construct
    itself). The label weight vector is seed-deterministic and shared
    across chunks, so the stream is re-iterable (the two construct
    passes) and reproducible."""
    import numpy as np
    w = higgs_weights(features, seed)

    def factory():
        for ci, s in enumerate(range(0, rows, chunk_rows)):
            n = min(chunk_rows, rows - s)
            rng = np.random.RandomState((seed + 1) * 100003 + ci)
            X = rng.normal(size=(n, features)).astype(np.float32)
            y = (higgs_logits(X, w) + rng.logistic(size=n) > 0) \
                .astype(np.float32)
            yield X, y

    return factory


def construct_probe(rows, args):
    """Streaming-vs-monolithic construct at CPU-diagnostic scale: the
    SAME float32 matrix constructed both ways, reporting wall seconds,
    rows/sec, the streaming path's peak resident raw-chunk bytes and its
    sketch/bin/h2d sub-phases (telemetry.construct_snapshot), plus a
    bit-parity verdict over the resulting bin matrices — the
    chunked-ingest acceptance numbers on every backend."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu import telemetry

    n = min(rows, 500_000)
    f = args.features
    rng = np.random.RandomState(3)
    X = rng.normal(size=(n, f)).astype(np.float32)
    chunk_rows = max(1, n // 8)

    # bit-parity preconditions: the sampled monolithic fit equals the
    # all-rows sketch fit only when (a) the sample covers every row and
    # (b) the sketch never compacts — so the probe pins
    # bin_construct_sample_cnt >= n AND sketch_max_size=0 (exact mode) on
    # both sides; without them a >=200k-row probe reports a FALSE parity
    # failure (sampling) or a >=65k-distinct one does (compaction). The
    # compaction regime's quality is covered by the rank-error tests,
    # not this bit-parity probe.
    common = {"max_bin": args.max_bin, "verbosity": -1,
              "bin_construct_sample_cnt": n, "sketch_max_size": 0}
    t0 = time.time()
    ds_m = lgb.Dataset(X, params=dict(common)).construct()
    import jax
    jax.block_until_ready(ds_m.bins)
    mono_sec = time.time() - t0

    t0 = time.time()
    ds_s = lgb.Dataset(X, params={**common,
                                  "construct_chunk_rows": chunk_rows})
    ds_s.construct(streaming=True)
    stream_sec = time.time() - t0
    parity = bool(np.array_equal(np.asarray(ds_m.bins),
                                 np.asarray(ds_s.bins)))
    snap = telemetry.construct_snapshot()
    peak = snap.get("peak_host_bytes")
    return {
        "construct_probe_rows": n,
        "construct_monolithic_sec": round(mono_sec, 3),
        "construct_streaming_sec": round(stream_sec, 3),
        "construct_streaming_rows_per_sec": round(n / max(stream_sec, 1e-9),
                                                  1),
        # probe-scoped key: the MAIN run's construct_peak_host_bytes
        # (the 100M acceptance number on --streaming rounds) must not be
        # clobbered by this diagnostic-scale probe's result.update
        "construct_probe_peak_host_bytes": peak,
        # the acceptance ratio: peak resident raw bytes over ONE chunk's
        # bytes — must stay <= 2 (current chunk + in-flight padded copy),
        # vs the monolithic path's n/chunk_rows chunks resident
        "construct_peak_chunks": (round(peak / (chunk_rows * f * 4), 2)
                                  if peak else None),
        "construct_bins_bit_identical": parity,
        "construct_phases": {k: snap[k] for k in
                             ("sketch_pass", "bin_pass", "h2d_overlap")
                             if k in snap},
    }


def _telemetry_json():
    """The unified telemetry snapshot for the result JSON
    (telemetry.snapshot(): scopes + counters + gauges + dispatch +
    health in ONE versioned schema — replaces the hand-rolled
    health/gauges spellings this file used to assemble)."""
    try:
        from lightgbm_tpu import telemetry
        snap = telemetry.snapshot()
        snap["gauges"] = {k: round(v, 3)
                         for k, v in snap.get("gauges", {}).items()}
        return snap
    except Exception:
        return None


def run_at_scale(rows, args, hist_method="auto", hist_compaction=True,
                 extra_params=None, trace=False):
    import numpy as np
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils import profiling

    # K iterations per dispatch (the compile-wall PR's scan block):
    # booster.update() consumes K iterations per call once the block
    # target is set, so every per-iteration number below divides by K
    K = max(1, int(getattr(args, "rounds_per_dispatch", 1)))

    # TIMETAG scopes force a host sync per phase to attribute wall time —
    # exactly what the async-pipelined steady state must NOT do. Collect
    # the table from the two warmup iterations only, then run the timed
    # loop (and everything after) sync-free.
    profiling.reset()
    profiling.enable(True)
    # dispatch/host-sync telemetry for the timed loop (dispatches_per_iter
    # / host_bytes_per_iter JSON fields): counts compiled-program launches
    # and explicit host<->device transfer bytes — the non-histogram
    # overhead the fused iteration exists to kill
    telemetry = profiling.install_dispatch_hook()

    def mark(name):
        # stream phase completions so a wedged tunnel RPC is attributable
        # to a specific phase in the log (observed 2026-07-31: the axon
        # relay can stall mid-run with no in-VM recovery)
        print(f"# [{time.strftime('%H:%M:%S')}] phase done: {name}",
              file=sys.stderr, flush=True)

    phases = {}
    rng = np.random.RandomState(0)
    # train + held-out valid rows from the same synthetic distribution
    n_valid = min(args.valid_rows, rows // 10)
    n, f = rows, args.features
    streaming = bool(getattr(args, "streaming", False))
    t0 = time.time()
    if streaming:
        # chunked datagen + streaming construct: the raw [N, F] train
        # matrix NEVER materializes — each chunk is generated, sketched
        # and device-binned in O(chunk) host memory (the 100M-row shape's
        # only viable ingest). The held-out rows stay monolithic (small).
        chunk_rows = int(getattr(args, "construct_chunk_rows", 0) or 0) \
            or min(max(1 << 18, n // 8), 1 << 21)
        factory = higgs_chunk_stream(n, f, chunk_rows, seed=0)
        vr = np.random.RandomState(10**6)
        Xv = vr.normal(size=(n_valid, f)).astype(np.float32)
        yv = (higgs_logits(Xv, higgs_weights(f, 0))
              + vr.logistic(size=n_valid) > 0).astype(np.float32)
        phases["datagen"] = time.time() - t0
        mark("datagen (chunked stream)")
        t0 = time.time()
        ds = lgb.Dataset.from_chunks(
            factory, params={"max_bin": args.max_bin, "verbosity": -1,
                             "construct_chunk_rows": chunk_rows})
        ds.construct()
    else:
        # Higgs-shaped synthetic: continuous physics-like features,
        # binary label. NOTE: w here is drawn AFTER X on this rng's
        # stream (the historical monolithic task, kept for round-over-
        # round comparability), so it is a DIFFERENT weight realization
        # than the streaming branch's higgs_weights(f, 0) — compare AUC
        # within a mode across rounds, not across modes
        X = rng.normal(size=(n + n_valid, f)).astype(np.float32)
        w = rng.normal(size=f)
        y = (higgs_logits(X, w)
             + rng.logistic(size=n + n_valid) > 0).astype(np.float32)
        Xv, yv = X[n:], y[n:]
        X, y = X[:n], y[:n]
        phases["datagen"] = time.time() - t0
        mark("datagen")
        t0 = time.time()
        ds = lgb.Dataset(X, label=y, params={"max_bin": args.max_bin,
                                             "verbosity": -1})
        ds.construct()
    phases["construct"] = time.time() - t0
    if streaming:
        from lightgbm_tpu import telemetry as _telemetry
        for k, v in _telemetry.construct_snapshot().items():
            if k in ("sketch_pass", "bin_pass", "h2d_overlap"):
                phases[k] = v
    mark("construct")

    booster = lgb.Booster(params={
        "objective": "binary", "num_leaves": args.num_leaves,
        "learning_rate": 0.1, "max_bin": args.max_bin,
        "min_data_in_leaf": 100, "min_sum_hessian_in_leaf": 100.0,
        "histogram_method": hist_method,
        "hist_compaction": hist_compaction,
        "verbosity": -1,
        "boost_rounds_per_dispatch": K,
        "compile_cache_dir": getattr(args, "compile_cache_dir", "") or "",
        **(extra_params or {}),
    }, train_set=ds)
    if K > 1:
        # opt the manual update loop into K-block consumption (normally
        # only engine.train sets the target)
        booster._boosting._block_target = 1 << 30

    # warmup (jit compile + first real block). With K > 1 the first
    # update grows K trees — first_iter_compile_s stays the whole wall
    # (that is the quantity the persistent cache kills), second_iter is
    # per-iteration steady state
    t0 = time.time()
    booster.update()
    phases["first_iter_incl_compile"] = time.time() - t0
    mark("first_iter_incl_compile")
    t0 = time.time()
    booster.update()
    phases["second_iter"] = (time.time() - t0) / K
    mark("second_iter")
    print(f"# ---- TIMETAG phase table ({hist_method}, warmup iters) ----",
          file=sys.stderr)
    for line in profiling.table().splitlines():
        print(f"# {line}", file=sys.stderr)
    profiling.enable(False)

    # drain outstanding async work so warmup doesn't leak into the timing
    _ = float(booster._boosting.train_score[0].ravel()[0])
    trees0 = len(booster._boosting.trees)
    disp0 = profiling.dispatch_stats()
    t0 = time.time()
    for _ in range(args.iters):
        booster.update()
    # snapshot the counters BEFORE the completion fetch: the fetch is
    # measurement infrastructure, not part of an iteration
    disp1 = profiling.dispatch_stats()
    # force completion: fetch a scalar that depends on the training state
    # (block_until_ready does not reliably block through the axon tunnel)
    _ = float(booster._boosting.train_score[0].ravel()[0])
    sec_per_iter = (time.time() - t0) / (args.iters * K)
    phases["sec_per_iter"] = sec_per_iter
    disp_per_iter = host_bytes_per_iter = trees_per_dispatch = None
    if telemetry:
        d = profiling.dispatch_delta(disp0, disp1)
        disp_per_iter = d["dispatches"] / (args.iters * K)
        host_bytes_per_iter = (d["d2h_bytes"] + d["h2d_bytes"]) \
            / (args.iters * K)
        trees_grown = len(booster._boosting.trees) - trees0
        trees_per_dispatch = trees_grown / max(d["dispatches"], 1)
        mark(f"dispatch telemetry: {disp_per_iter:.1f} dispatches/iter, "
             f"{host_bytes_per_iter:.0f} host bytes/iter, "
             f"{trees_per_dispatch:.1f} trees/dispatch")
    mark(f"timed_iters ({sec_per_iter:.3f} s/iter)")

    # quality anchor: continue to --rounds total iterations, then held-out
    # AUC (speed without a matched-accuracy number is unfalsifiable)
    auc = None
    done = (2 + args.iters) * K
    if args.rounds > done and n_valid > 0:
        t0 = time.time()
        for _ in range(-(-(args.rounds - done) // K)):
            booster.update()
        _ = float(booster._boosting.train_score[0])
        phases["extra_rounds"] = time.time() - t0
        mark("extra_rounds")
    predict_rps = predict_host_bytes = None
    if n_valid > 0:
        t0 = time.time()
        score = booster.predict(Xv, raw_score=True)
        # Mann-Whitney AUC with midranks (tied scores are common: raw
        # scores are sums of discrete leaf values)
        from scipy.stats import rankdata
        npos = yv.sum()
        nneg = len(yv) - npos
        if npos > 0 and nneg > 0:
            ranks = rankdata(score, method="average")
            auc = float((ranks[yv > 0].sum() - npos * (npos + 1) / 2)
                        / (npos * nneg))
        phases["valid_auc_predict"] = time.time() - t0
        mark(f"valid_auc_predict (auc={auc})")
        # serving throughput: a SECOND (warm — the AUC predict above paid
        # the engine compile) full-ensemble predict at the same shape,
        # with dispatch/d2h telemetry: the inference-engine acceptance
        # numbers (constant dispatches, [N, K]-only device->host bytes)
        with profiling.dispatch_scope() as dd:
            t0 = time.time()
            _ = booster.predict(Xv, raw_score=True)
            warm_sec = time.time() - t0
        phases["warm_predict"] = warm_sec
        predict_rps = n_valid / max(warm_sec, 1e-9)
        if telemetry:
            predict_host_bytes = dd["d2h_bytes"]
            mark(f"warm_predict ({predict_rps:.0f} rows/s, "
                 f"{dd['dispatches']} dispatches, "
                 f"{predict_host_bytes} d2h bytes)")
        else:
            mark(f"warm_predict ({predict_rps:.0f} rows/s)")
    # compaction telemetry: rows read by histogram passes per tree (the
    # device-side accumulator syncs here, after the timed loop)
    rows_per_tree = booster._boosting.rows_streamed_per_tree
    mark(f"rows_streamed_per_tree={rows_per_tree:.0f} "
         f"(compaction={'on' if hist_compaction else 'off'})")

    # windowed device-trace capture (--trace-dir/--trace-iters): drive
    # jax.profiler start/stop around N WARM boosting iterations through
    # telemetry.trace_window — the TraceAnnotation scopes mean the
    # grower phases land labeled in the perfetto trace, so a TPU round
    # ships real device timings instead of the modeled mfu_est. Runs on
    # the main booster only (trace=True), tolerant of backends whose
    # profiler cannot start (tw.error lands in the JSON, never a raise).
    trace_info = None
    if trace and getattr(args, "trace_dir", None):
        from lightgbm_tpu import telemetry
        t_iters = max(1, int(getattr(args, "trace_iters", 3)))
        with telemetry.trace_window(args.trace_dir, iters=t_iters) as tw:
            for _ in range(t_iters):
                booster.update()
            _ = float(booster._boosting.train_score[0].ravel()[0])
        trace_info = tw.to_json()
        trace_info["files"] = len(telemetry.trace_files(args.trace_dir))
        mark(f"trace capture ({'ok' if tw.ok else tw.error}, "
             f"{trace_info['files']} artifact files)")

    return {"sec_per_iter": sec_per_iter, "phases": phases, "auc": auc,
            "rounds_run": max(args.rounds, done),
            "rows_per_tree": rows_per_tree,
            "disp_per_iter": disp_per_iter,
            "host_bytes_per_iter": host_bytes_per_iter,
            "predict_rps": predict_rps,
            "predict_host_bytes": predict_host_bytes,
            "trees_per_dispatch": trees_per_dispatch,
            "trace": trace_info}


def phase_scope_probe(rows, args, hist_method="auto", iters=3):
    """Per-phase grow_tree breakdown: train a bounded-scale booster on the
    PHASE-BY-PHASE path (fused_iteration=false) with TIMETAG on, which
    routes growth through the host-phased grower (grow_tree_phased) —
    each round is its own dispatch, so ``hist_pass`` / ``split_search`` /
    ``apply_split`` wall time is attributable per phase on every backend
    (the epilogue's win shows as split_search collapsing). Returns the
    sub-scope dict for the BENCH JSON ``phases`` entry plus the
    dispatch-count frontier check (hist_pass launches per tree — one per
    frontier LEVEL, not per leaf)."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils import profiling
    rng = np.random.RandomState(1)
    n, f = min(rows, 200_000), args.features
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=f)
    y = (X @ w + rng.logistic(size=n) > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"max_bin": args.max_bin,
                                         "verbosity": -1})
    booster = lgb.Booster(params={
        "objective": "binary", "num_leaves": args.num_leaves,
        "learning_rate": 0.1, "max_bin": args.max_bin,
        "min_data_in_leaf": 100, "min_sum_hessian_in_leaf": 100.0,
        "histogram_method": hist_method, "fused_iteration": False,
        "verbosity": -1}, train_set=ds)
    was = profiling.enabled()
    profiling.reset()
    profiling.enable(True)
    try:
        booster.update()          # compile-laden first iteration
        profiling.reset()         # keep only warm per-phase times
        for _ in range(iters):
            booster.update()
        sc = profiling.scopes()
    finally:
        profiling.enable(was)
        profiling.reset()
    out = {}
    for name in ("hist_pass", "split_search", "apply_split"):
        if name in sc:
            out[name] = round(sc[name]["total_s"] / iters, 4)
            out[f"{name}_calls"] = round(sc[name]["calls"] / iters, 1)
    return out


def overhead_probe(rows, args, param, iters=8, repeats=3):
    """Cost of one always-on guard on the fused iteration, measured as
    off-vs-on timed loops at the same scale; returns
    (sec_off, sec_on, overhead_pct). Two consumers:

    - ``param="check_numerics"`` — the in-program numerics sentinels
      (training-integrity layer); budget <= 2% (the flag word is a
      handful of reductions riding the step's epilogue, fetched by lazy
      non-blocking drains);
    - ``param="telemetry_flight_recorder"`` — the per-iteration flight
      recorder; budget <= 2% (host-side dict builds only — the record
      never forces a device sync or an extra dispatch).

    The two arms run as INTERLEAVED timed windows and each arm takes its
    MINIMUM: single-window timing noise on a 1-core container (±15% at
    probe scale) would otherwise swamp the budgets being measured."""
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    n, f = rows, args.features
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=f)
    y = (X @ w + rng.logistic(size=n) > 0).astype(np.float32)
    boosters = {}
    for guard in (False, True):
        ds = lgb.Dataset(X, label=y, params={"max_bin": args.max_bin,
                                             "verbosity": -1})
        booster = lgb.Booster(params={
            "objective": "binary", "num_leaves": args.num_leaves,
            "learning_rate": 0.1, "max_bin": args.max_bin,
            "min_data_in_leaf": 100, "min_sum_hessian_in_leaf": 100.0,
            "verbosity": -1, param: guard,
        }, train_set=ds)
        booster.update()
        booster.update()                        # warmup (compile)
        _ = float(np.asarray(booster._boosting.train_score).ravel()[0])
        boosters[guard] = booster
    times = {False: [], True: []}
    for _ in range(repeats):
        for guard in (False, True):
            booster = boosters[guard]
            t0 = time.time()
            for _ in range(iters):
                booster.update()
            _ = float(np.asarray(booster._boosting.train_score).ravel()[0])
            times[guard].append((time.time() - t0) / iters)
    t_off, t_on = min(times[False]), min(times[True])
    pct = (t_on - t_off) / max(t_off, 1e-12) * 100.0
    return t_off, t_on, pct


def main():
    t_main = time.time()
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=FULL_ROWS)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--num-leaves", type=int, default=255)
    ap.add_argument("--max-bin", type=int, default=255)
    ap.add_argument("--iters", type=int, default=10,
                    help="timed iterations (after 2 warmup)")
    ap.add_argument("--rounds", type=int, default=100,
                    help="total boosting rounds before the AUC readout")
    ap.add_argument("--valid-rows", type=int, default=500_000,
                    help="held-out rows for the AUC readout (0 disables)")
    ap.add_argument("--probe-deadline", type=int, default=2400,
                    help="stop starting secondary probes (q8/bin63) after "
                         "this many seconds of total wall time")
    ap.add_argument("--probe-timeout", type=int, default=180,
                    help="hard deadline (s) on the TPU backend-init probe "
                         "subprocess before falling back to CPU")
    ap.add_argument("--streaming", action="store_true",
                    help="chunked datagen + streaming two-pass construct "
                         "for the MAIN run: the raw [N, F] train matrix "
                         "never materializes in host RAM (required for "
                         "the 100M-row Higgs-shape round; host memory "
                         "stays O(chunk))")
    ap.add_argument("--construct-chunk-rows", type=int, default=0,
                    dest="construct_chunk_rows",
                    help="rows per construct chunk in --streaming mode "
                         "(0 = auto: n/8 clamped to [262144, 2M], so any "
                         "scale above ~262k rows streams multi-chunk)")
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    ap.add_argument("--require-tpu", action="store_true", dest="require_tpu",
                    help="fail LOUDLY (exit 2, error JSON with "
                         "tpu_required=true) instead of falling back to "
                         "CPU — a requested-TPU round must never publish "
                         "CPU numbers under a TPU-looking filename "
                         "(BENCH_r04/r05 did exactly that)")
    ap.add_argument("--no-ladder", action="store_true",
                    help="fail instead of retrying at smaller scales")
    ap.add_argument("--rounds-per-dispatch", type=int, default=4,
                    dest="rounds_per_dispatch",
                    help="boost_rounds_per_dispatch K: iterations grown "
                         "per compiled dispatch (lax.scan block; 1 = the "
                         "pre-PR per-iteration dispatch)")
    ap.add_argument("--compile-cache-dir", default=None,
                    dest="compile_cache_dir",
                    help="persistent XLA compile cache dir (default: a "
                         "fresh temp dir so the warm-start probe can "
                         "measure the cold/warm delta; '' disables)")
    ap.add_argument("--no-warm-probe", action="store_true",
                    help="skip the second-process warm-start probe")
    ap.add_argument("--trace-dir", default=None, dest="trace_dir",
                    help="capture a jax.profiler device trace of "
                         "--trace-iters warm boosting iterations into "
                         "this directory (telemetry.trace_window; the "
                         "TIMETAG TraceAnnotation scopes label the "
                         "grower phases in the perfetto trace). The "
                         "outcome — including WHY a capture failed — "
                         "lands in the result JSON 'trace' field")
    ap.add_argument("--trace-iters", type=int, default=3,
                    dest="trace_iters",
                    help="boosting iterations the trace window covers")
    ap.add_argument("--warm-child", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.warm_child:
        _warm_child(json.loads(args.warm_child))
        return
    if args.compile_cache_dir is None:
        import tempfile
        args.compile_cache_dir = tempfile.mkdtemp(prefix="lgb_compile_cache_")

    # backend-probe outcome for the result JSON: a CPU number that LOOKS
    # like a TPU number poisons round-over-round comparisons, so the
    # backend actually used and WHY the TPU was rejected are first-class
    # fields, not stderr comments
    probe_error = None
    if not args.cpu and os.environ.get("_LGB_TPU_BENCH_PROBED") != "1":
        # the axon tunnel can wedge so that backend init HANGS (observed
        # 2026-07-30: a dead tunnel blocks jax.devices() indefinitely);
        # probe it in a killable subprocess with a hard deadline and fall
        # back to CPU so the bench always reports a number
        import subprocess
        env = dict(os.environ)
        env["_LGB_TPU_BENCH_PROBED"] = "1"
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                env=env, timeout=args.probe_timeout, capture_output=True,
                text=True)
            if probe.returncode != 0:
                tail = (probe.stderr or "").strip().splitlines()[-3:]
                probe_error = (f"probe exited {probe.returncode}: "
                               + " | ".join(tail)[:500])
            elif probe.stdout.strip().splitlines()[-1:] != ["tpu"]:
                probe_error = ("probe found no TPU (platform="
                               f"{probe.stdout.strip()[:100]!r})")
        except subprocess.TimeoutExpired:
            probe_error = (f"probe hung past {args.probe_timeout}s "
                           "(backend init deadlock / dead tunnel)")
        if probe_error:
            print(f"# TPU backend unavailable ({probe_error}); "
                  "falling back to CPU", file=sys.stderr)
            args.cpu = True
            # a CPU run is a diagnostic number, not the benchmark: cap the
            # scale so it completes inside the driver budget
            args.rows = min(args.rows, 500_000)
            args.rounds = min(args.rounds, 20)
            args.valid_rows = min(args.valid_rows, 50_000)
        os.environ["_LGB_TPU_BENCH_PROBED"] = "1"

    def tpu_required_bail(why):
        # --require-tpu: fail loudly with a parseable error record — a
        # requested-TPU round must never publish CPU numbers
        print(json.dumps({"metric": "higgs_sec_per_iter", "value": None,
                          "unit": "s/iter", "vs_baseline": None,
                          "tpu_required": True, "backend": "cpu",
                          "probe_error": probe_error,
                          "error": f"TPU required but unavailable: {why}"}),
              flush=True)
        sys.exit(2)

    if args.require_tpu and args.cpu:
        tpu_required_bail(probe_error or "--cpu forced")
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    dev = jax.devices()[0]
    print(f"# device: {dev}", file=sys.stderr)
    if args.require_tpu and jax.default_backend() != "tpu":
        tpu_required_bail(f"backend is {jax.default_backend()!r}")

    ladder = list(dict.fromkeys(
        r for r in (args.rows, 2_000_000, 500_000) if r <= args.rows))
    if args.no_ladder:
        ladder = [args.rows]
    main_run = used_rows = used_method = None
    # the method ladder guards against a kernel-specific failure: "auto"
    # (the fused Pallas fast path on TPU) falls back to the XLA onehot
    # contraction at the same scale before shrinking rows
    for rows in ladder:
        for hm in ("auto", "onehot"):
            try:
                print(f"# trying rows={rows} hist={hm}", file=sys.stderr)
                main_run = run_at_scale(rows, args, hist_method=hm,
                                        trace=True)
                used_rows = rows
                used_method = hm
                break
            except Exception:
                traceback.print_exc(file=sys.stderr)
                print(f"# rows={rows} hist={hm} failed; falling back",
                      file=sys.stderr)
        if used_rows is not None:
            break

    if main_run is not None:
        sec_per_iter = main_run["sec_per_iter"]
        phases = main_run["phases"]
        auc = main_run["auc"]
        rounds_run = main_run["rounds_run"]
        rows_per_tree = main_run["rows_per_tree"]
        disp_per_iter = main_run["disp_per_iter"]
        host_bytes_per_iter = main_run["host_bytes_per_iter"]
        predict_rps = main_run["predict_rps"]
        predict_host_bytes = main_run["predict_host_bytes"]
        trees_per_dispatch = main_run["trees_per_dispatch"]
    else:
        sec_per_iter = None

    if sec_per_iter is None:
        print(json.dumps({"metric": "higgs_sec_per_iter", "value": None,
                          "unit": "s/iter", "vs_baseline": None,
                          "error": "all ladder scales failed"}))
        sys.exit(1)

    # baseline scaled to the rows actually benchmarked (reference cost is
    # ~linear in rows at fixed features/bins/leaves)
    scaled_baseline = BASELINE_SEC_PER_ITER * used_rows / FULL_ROWS
    # MFU estimates: nominal useful work of dense histogram construction,
    # ~log2(num_leaves) full-data passes per tree with subtraction
    # (2*N*F*B*S flops per pass), over the measured wall time — against
    # BOTH peaks (see mfu_estimates)
    # resolve "auto" to what actually ran before picking the mode peak:
    # on a CPU-fallback round "auto" runs scatter (f32), not the bf16
    # kernel — the mode-matched MFU must use the executed path's peak
    from lightgbm_tpu.ops.histogram import resolve_method
    mfu_d = mfu_estimates(sec_per_iter, used_rows, args.features,
                          args.max_bin, args.num_leaves,
                          resolve_method(used_method))
    mfu = mfu_d["mfu_f32"]
    print(f"# MFU estimate (dense-hist useful flops): "
          f"f32-peak {mfu:.4f} / bf16-peak {mfu_d['mfu_bf16']:.4f} / "
          f"mode-peak {mfu_d['mfu_mode']:.4f}", file=sys.stderr)

    from lightgbm_tpu.utils import profiling as _profiling
    profiling_gauges = _profiling.gauges()
    result = {
        "metric": f"higgs{used_rows/1e6:.1f}M_sec_per_iter",
        "value": round(sec_per_iter, 4),
        "unit": f"s/iter ({used_rows} rows x {args.features} feat, "
                f"{args.num_leaves} leaves, {args.max_bin} bins, binary)",
        "vs_baseline": round(scaled_baseline / sec_per_iter, 4),
        "rows": used_rows,
        # legacy field: f32-peak denominator; the bf16/mode numbers answer
        # "how much of the hardware the production (bf16/int8) MXU paths
        # actually use" — the f32 one alone overstated hilo by 2x
        "mfu_est": round(mfu, 4),
        "mfu_bf16_est": round(mfu_d["mfu_bf16"], 4),
        "mfu_mode_est": round(mfu_d["mfu_mode"], 4),
        "auc": round(auc, 6) if auc is not None else None,
        "auc_rounds": rounds_run,
        "hist_method": used_method,
        # backend-probe outcome (satellite: the fallback reason must be in
        # the JSON, not only a stderr comment); tpu_required records
        # whether this round was allowed to fall back at all
        "backend": jax.default_backend(),
        "probe_error": probe_error,
        "tpu_required": bool(args.require_tpu),
        # dispatch/host-sync telemetry over the timed loop (see
        # utils/profiling.py install_dispatch_hook): compiled-program
        # launches and explicit host<->device transfer bytes per
        # iteration — the fused one-dispatch iteration holds the former
        # at 2 (grow step + donated score add); null when the jax
        # internals hook is unavailable
        "dispatches_per_iter": round(disp_per_iter, 2)
        if disp_per_iter is not None else None,
        "host_bytes_per_iter": round(host_bytes_per_iter, 1)
        if host_bytes_per_iter is not None else None,
        # serving-path telemetry: warm full-ensemble predict throughput at
        # the valid shape and its device->host bytes (the inference engine
        # holds the latter at ~N*K*8: only the result crosses the tunnel)
        "predict_rows_per_sec": round(predict_rps, 1)
        if predict_rps is not None else None,
        "predict_host_bytes": int(predict_host_bytes)
        if predict_host_bytes is not None else None,
        # the main run has compaction ON (the default): these two fields
        # are the compacted numbers; the nocompact_* probe below supplies
        # the uncompacted side of the headroom comparison
        "compact_sec_per_iter": round(sec_per_iter, 4),
        "rows_streamed_per_tree": round(rows_per_tree, 1)
        if rows_per_tree is not None else None,
        # the compile wall (ISSUE 10): the first dispatch's full wall
        # (XLA compile + first block), the K-block shape, and this
        # process's persistent-cache counters; the warm_start_s probe
        # below supplies the second-process (cache-hit) side of the delta
        # construct-phase telemetry (the chunked-ingest tentpole): wall
        # seconds, throughput, and — on --streaming runs — the peak
        # resident raw-chunk bytes (O(chunk), vs O(N*F) monolithic); the
        # streaming-vs-monolithic probe below supplies the comparison
        # fields at diagnostic scale on every backend
        "construct_sec": round(phases.get("construct", 0.0), 3),
        "construct_rows_per_sec": round(
            used_rows / max(phases.get("construct", 0.0), 1e-9), 1),
        "construct_streaming": bool(getattr(args, "streaming", False)),
        "construct_peak_host_bytes": (
            int(profiling_gauges.get("construct_peak_bytes"))
            if profiling_gauges.get("construct_peak_bytes") is not None
            else None),
        # memory watermarks (profiling.sample_memory / VmHWM): the
        # device allocator's process-lifetime HBM peak and the host RSS
        # peak — the round's memory cost next to its speed, and the
        # regression axis scripts/bench_compare.py gates on. Null on
        # backends without Device.memory_stats() (CPU fallback rounds)
        "hbm_peak_bytes": _profiling.sample_memory()["hbm_peak_bytes"],
        "host_rss_peak_bytes": _profiling.host_rss_peak_bytes(),
        "first_iter_compile_s": round(
            phases.get("first_iter_incl_compile", 0.0), 3),
        "trees_per_dispatch": round(trees_per_dispatch, 2)
        if trees_per_dispatch is not None else None,
        "boost_rounds_per_dispatch": args.rounds_per_dispatch,
        "compile_cache_hits": _compile_totals()["hits"],
        "compile_cache_misses": _compile_totals()["misses"],
        "phases": {k: round(v, 3) for k, v in phases.items()},
        # windowed device-trace capture outcome (--trace-dir): where the
        # perfetto trace landed, how many iterations it covers, and —
        # crucially, after BENCH_r04/r05 — WHY it failed when it did
        "trace": main_run.get("trace"),
        # the unified telemetry snapshot (telemetry.snapshot(), one
        # versioned schema): scopes, counters, gauges, dispatch counters
        # and distributed.health_snapshot() — the supervisor restart
        # count, heartbeat table, degradation log and flight-recorder
        # path all live under its "health" key
        "telemetry": _telemetry_json(),
    }
    # insurance: print the headline line NOW — a later probe that wedges
    # the tunnel (observed 2026-07-31) must not cost the round its number.
    # The final enriched line is printed again below; parsers that take
    # the last JSON line get the probes too.
    print(json.dumps(result), flush=True)

    def probe_headroom(label):
        left = args.probe_deadline - (time.time() - t_main)
        if left < 0:
            print(f"# skipping {label} probe: past --probe-deadline "
                  f"({args.probe_deadline}s)", file=sys.stderr)
            return False
        return True

    # per-phase grow_tree sub-scopes (the phased grower's hist_pass /
    # split_search / apply_split TIMETAG scopes at a bounded scale): the
    # fused split epilogue's win is measurable per phase on every backend
    # — split_search collapses to bookkeeping and hist_pass_calls counts
    # ONE launch per frontier level, not per leaf
    if probe_headroom("phase-scopes"):
        try:
            ph = phase_scope_probe(used_rows, args, hist_method=used_method)
            result["phases"].update(ph)
            print(f"# grow_tree phase sub-scopes (per iter): {ph}",
                  file=sys.stderr)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            print("# phase-scope probe failed; omitting", file=sys.stderr)
    print(json.dumps(result), flush=True)

    # streaming-vs-monolithic construct probe (runs on ANY backend at
    # CPU-diagnostic scale): the same matrix constructed both ways —
    # wall seconds, the streaming path's peak resident raw-chunk bytes
    # (acceptance: <= 2 chunks) and a bin-matrix bit-parity verdict
    if probe_headroom("construct"):
        try:
            cp = construct_probe(used_rows, args)
            result.update(cp)
            print(f"# construct probe: monolithic "
                  f"{cp['construct_monolithic_sec']}s vs streaming "
                  f"{cp['construct_streaming_sec']}s at "
                  f"{cp['construct_probe_rows']} rows, peak "
                  f"{cp['construct_peak_chunks']} chunks resident, "
                  f"bit-identical={cp['construct_bins_bit_identical']}",
                  file=sys.stderr)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            print("# construct probe failed; omitting", file=sys.stderr)
    print(json.dumps(result), flush=True)

    # compaction on/off headroom probe (runs on ANY backend — the row
    # reduction shows on the CPU scatter path too): same scale with
    # hist_compaction=false supplies the uncompacted sec_per_iter and
    # rows_streamed_per_tree the acceptance comparison needs
    nc_sec = nc_rows = None
    if probe_headroom("nocompact"):
        try:
            nc = run_at_scale(used_rows, args, hist_method=used_method,
                              hist_compaction=False)
            nc_sec, nc_rows = nc["sec_per_iter"], nc["rows_per_tree"]
            print(f"# nocompact probe: {nc_sec:.3f} s/iter, "
                  f"rows/tree={nc_rows:.0f} (compacted run: "
                  f"{sec_per_iter:.3f} s/iter, {rows_per_tree:.0f})",
                  file=sys.stderr)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            print("# nocompact probe failed; omitting", file=sys.stderr)
    result.update({
        "nocompact_sec_per_iter": round(nc_sec, 4)
        if nc_sec is not None else None,
        "nocompact_rows_streamed_per_tree": round(nc_rows, 1)
        if nc_rows is not None else None,
    })
    print(json.dumps(result), flush=True)

    # in-program numerics-sentinel overhead (the training-integrity
    # layer's guard word on the fused iteration): timed at a bounded
    # probe scale so the number exists on every backend; the acceptance
    # budget is <= 2%
    sent_pct = None
    if probe_headroom("sentinel"):
        try:
            s_off, s_on, sent_pct = overhead_probe(
                min(used_rows, 200_000), args, "check_numerics")
            print(f"# sentinel probe: off {s_off:.4f} s/iter, on "
                  f"{s_on:.4f} s/iter -> {sent_pct:+.2f}%",
                  file=sys.stderr)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            print("# sentinel probe failed; omitting", file=sys.stderr)
    # flight-recorder overhead (the telemetry layer's always-on ring):
    # same interleaved-min off/on measurement, same <= 2% budget — the
    # record is host-side dict builds only, so the number should be
    # noise around zero on every backend
    rec_pct = None
    if probe_headroom("recorder"):
        try:
            r_off, r_on, rec_pct = overhead_probe(
                min(used_rows, 200_000), args, "telemetry_flight_recorder")
            print(f"# recorder probe: off {r_off:.4f} s/iter, on "
                  f"{r_on:.4f} s/iter -> {rec_pct:+.2f}%",
                  file=sys.stderr)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            print("# recorder probe failed; omitting", file=sys.stderr)
    result.update({
        "sentinel_overhead_pct": round(sent_pct, 2)
        if sent_pct is not None else None,
        "recorder_overhead_pct": round(rec_pct, 2)
        if rec_pct is not None else None,
    })
    print(json.dumps(result), flush=True)

    # warm-start probe (the compile wall's other half): a SECOND process
    # at the same shape against the persistent cache this run just
    # filled — its first dispatch should be a cache deserialization
    # (zero fused-step XLA compiles), and first_iter_compile_s vs
    # warm_start_s is the cold/warm delta on record
    warm = None
    if (not args.no_warm_probe and args.compile_cache_dir
            and probe_headroom("warm-start")):
        import subprocess
        env = dict(os.environ)
        env["_LGB_TPU_BENCH_PROBED"] = "1"
        if args.cpu:
            env["JAX_PLATFORMS"] = "cpu"
        cfg = {"rows": used_rows, "features": args.features,
               "max_bin": args.max_bin, "num_leaves": args.num_leaves,
               "hist_method": used_method, "K": args.rounds_per_dispatch,
               "cache_dir": args.compile_cache_dir}
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--warm-child", json.dumps(cfg)],
                capture_output=True, text=True, env=env, timeout=1200)
            lines = [l for l in r.stdout.splitlines()
                     if l.startswith("{")]
            if r.returncode == 0 and lines:
                warm = json.loads(lines[-1])
                print(f"# warm-start probe: cold "
                      f"{result['first_iter_compile_s']}s -> warm "
                      f"{warm['warm_start_s']}s, fused misses "
                      f"{warm['warm_fused_misses']}", file=sys.stderr)
            else:
                tail = (r.stderr or "").strip().splitlines()[-3:]
                print(f"# warm-start probe failed: {' | '.join(tail)}",
                      file=sys.stderr)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            print("# warm-start probe failed; omitting", file=sys.stderr)
    result.update({
        "warm_start_s": warm["warm_start_s"] if warm else None,
        "warm_fused_misses": warm["warm_fused_misses"] if warm else None,
        "warm_cache_hits": warm["warm_cache_hits"] if warm else None,
    })
    print(json.dumps(result), flush=True)

    # secondary probes: the quantized-gradient mode and the max_bin=63
    # configuration. These run on EVERY backend (they were TPU-gated
    # before, which left the q8_*/bin63_* fields permanently null on CPU
    # fallback rounds — BENCH_r05): on TPU they measure the Pallas q8
    # kernel; on CPU the same quantized_grad training resolves to the XLA
    # int8 contraction, so the speed/quality tradeoff is still on record.
    # CPU probes shrink to diagnostic scale so the round fits its budget.
    if jax.default_backend() == "tpu":
        probe_args = args
        probe_rows = used_rows
    else:
        probe_args = argparse.Namespace(**{
            **vars(args),
            "rounds": min(args.rounds, 15),
            "iters": min(args.iters, 5),
            "valid_rows": min(args.valid_rows, 50_000)})
        probe_rows = min(used_rows, 200_000)

    # quantized-gradient training (Config.quantized_grad): int8 grad/hess
    # with stochastic rounding, exact int32 histogram accumulation, f32
    # rescale at split-gain time — WITH its own held-out AUC so
    # quality-at-speed is on record (the promotion gate for folding q8
    # into "auto" is AUC within ~0.002 of the default path — the same
    # kind of tolerance the reference publishes for its GPU
    # float32-histogram mode, docs/GPU-Performance.rst:133-140)
    q8_sec = q8_auc = q8_mfu = q8_ref_auc = None
    if probe_headroom("q8"):
        try:
            q8 = run_at_scale(probe_rows, probe_args, hist_method="auto",
                              extra_params={"quantized_grad": True})
            q8_sec, q8_ph, q8_auc = (q8["sec_per_iter"], q8["phases"],
                                     q8["auc"])
            q8_mfu = mfu_estimates(
                q8_sec, probe_rows, probe_args.features, probe_args.max_bin,
                probe_args.num_leaves, "pallas_q8")["mfu_mode"]
            print(f"# q8 probe: {q8_sec:.3f} s/iter, auc={q8_auc}, "
                  f"int8-peak mfu={q8_mfu:.4f}", file=sys.stderr)
            for kk, vv in q8_ph.items():
                print(f"# q8 phase {kk}: {vv:.3f}s", file=sys.stderr)
            if (probe_rows, probe_args.rounds) == (used_rows, args.rounds):
                q8_ref_auc = auc    # main run IS the matched f32 reference
            elif probe_headroom("q8-f32-ref"):
                # reduced-scale probe (CPU fallback): the q8 AUC needs an
                # f32 reference at the SAME scale to be a quality delta
                q8_ref_auc = run_at_scale(
                    probe_rows, probe_args, hist_method=used_method)["auc"]
                print(f"# q8 f32 reference auc={q8_ref_auc}",
                      file=sys.stderr)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            print("# q8 probe failed; omitting", file=sys.stderr)

    # max_bin=63: the reference's RECOMMENDED GPU configuration with
    # published AUC parity (docs/GPU-Performance.rst:43-47: CPU-255
    # 0.845612 vs GPU-63 0.845209 on Higgs) — ~4x fewer one-hot MACs per
    # histogram pass (and full 128-row MXU tiles via the kernel's
    # feature packing). Timed at the probe scale with its own AUC readout
    # so speed-at-matched-quality is on the record.
    b63_sec = b63_auc = b63q8_sec = b63q8_auc = None
    if args.max_bin != 63 and probe_headroom("bin63"):
        b63_args = argparse.Namespace(**{**vars(probe_args), "max_bin": 63})
        try:
            b63 = run_at_scale(probe_rows, b63_args, hist_method="auto")
            b63_sec, b63_ph, b63_auc = (b63["sec_per_iter"], b63["phases"],
                                        b63["auc"])
            print(f"# max_bin=63: {b63_sec:.3f} s/iter, "
                  f"auc={b63_auc}", file=sys.stderr)
            for kk, vv in b63_ph.items():
                print(f"# b63 phase {kk}: {vv:.3f}s", file=sys.stderr)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            print("# max_bin=63 probe failed; omitting", file=sys.stderr)
        # the two levers COMBINED (4x fewer MACs x 2x int8 MXU rate) —
        # the projected fastest configuration, with its own AUC readout
        if probe_headroom("bin63+q8"):
            try:
                b63q8 = run_at_scale(probe_rows, b63_args,
                                     hist_method="auto",
                                     extra_params={"quantized_grad": True})
                b63q8_sec, b63q8_auc = b63q8["sec_per_iter"], b63q8["auc"]
                print(f"# max_bin=63 + q8: {b63q8_sec:.3f} s/iter, "
                      f"auc={b63q8_auc}", file=sys.stderr)
            except Exception:
                traceback.print_exc(file=sys.stderr)
                print("# max_bin=63+q8 probe failed; omitting",
                      file=sys.stderr)

    result.update({
        # probe scale differs from the main run on CPU fallback rounds —
        # record it so q8/bin63 numbers are compared against the right
        # denominator
        "probe_rows": probe_rows,
        "q8_sec_per_iter": round(q8_sec, 4) if q8_sec is not None else None,
        "q8_auc": round(q8_auc, 6) if q8_auc is not None else None,
        # f32 AUC at the probe's own scale/rounds — the denominator of the
        # q8 quality delta (equals the headline auc when scales match)
        "q8_f32_ref_auc": round(q8_ref_auc, 6)
        if q8_ref_auc is not None else None,
        "q8_mfu_int8_est": round(q8_mfu, 4) if q8_mfu is not None else None,
        "bin63_sec_per_iter": round(b63_sec, 4) if b63_sec is not None
        else None,
        "bin63_auc": round(b63_auc, 6) if b63_auc is not None else None,
        "bin63_q8_sec_per_iter": round(b63q8_sec, 4)
        if b63q8_sec is not None else None,
        "bin63_q8_auc": round(b63q8_auc, 6) if b63q8_auc is not None
        else None,
    })
    print(json.dumps(result))


if __name__ == "__main__":
    main()
