"""Benchmark: Higgs-class GBDT training throughput on one TPU chip.

Mirrors the reference's headline benchmark (docs/Experiments.rst:108-124 —
Higgs 10.5M train rows x 28 features, 255 leaves, lr 0.1, max_bin 255;
130.094 s / 500 iters = 0.260 s/iter on 2x Xeon E5-2690 v4). Data is
synthetic Higgs-shaped (the real HIGGS file isn't in the image); the cost of
a boosting iteration depends on (rows, features, bins, leaves), not label
values, so sec/iter is comparable.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline = reference_sec_per_iter / ours (>1 means faster than the
reference CPU baseline).
"""

import argparse
import json
import sys
import time

BASELINE_SEC_PER_ITER = 130.094 / 500  # docs/Experiments.rst:108-124


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_500_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--num-leaves", type=int, default=255)
    ap.add_argument("--max-bin", type=int, default=255)
    ap.add_argument("--iters", type=int, default=10,
                    help="timed iterations (after 2 warmup)")
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    args = ap.parse_args()

    import numpy as np
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import lightgbm_tpu as lgb

    dev = jax.devices()[0]
    print(f"# device: {dev}", file=sys.stderr)

    rng = np.random.RandomState(0)
    n, f = args.rows, args.features
    # Higgs-shaped synthetic: continuous physics-like features, binary label
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=f)
    logits = X[:, : f // 2] @ w[: f // 2] + 0.5 * np.sin(X[:, f // 2]) * X[:, 0]
    y = (logits + rng.logistic(size=n) > 0).astype(np.float32)

    t0 = time.time()
    ds = lgb.Dataset(X, label=y, params={"max_bin": args.max_bin,
                                         "verbosity": -1})
    ds.construct()
    t_construct = time.time() - t0
    print(f"# dataset construct: {t_construct:.2f}s", file=sys.stderr)

    booster = lgb.Booster(params={
        "objective": "binary", "num_leaves": args.num_leaves,
        "learning_rate": 0.1, "max_bin": args.max_bin,
        "min_data_in_leaf": 100, "min_sum_hessian_in_leaf": 100.0,
        "verbosity": -1,
    }, train_set=ds)

    # warmup (compile)
    for _ in range(2):
        booster.update()
    import jax.numpy as jnp
    booster._boosting.train_score.block_until_ready()

    t0 = time.time()
    for _ in range(args.iters):
        booster.update()
    booster._boosting.train_score.block_until_ready()
    sec_per_iter = (time.time() - t0) / args.iters

    print(json.dumps({
        "metric": "higgs10.5M_sec_per_iter",
        "value": round(sec_per_iter, 4),
        "unit": "s/iter (10.5M rows x 28 feat, 255 leaves, 255 bins, binary)",
        "vs_baseline": round(BASELINE_SEC_PER_ITER / sec_per_iter, 3),
    }))


if __name__ == "__main__":
    main()
