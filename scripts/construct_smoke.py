"""Streaming-construct end-to-end smoke (fast knobs, ~20 s on CPU).

The chunked-ingest acceptance path at its smallest shape:

1. the SAME data constructed monolithically and as a 5-chunk stream
   (``Dataset.from_chunks``) fits BIT-IDENTICAL BinMappers and an
   identical bin matrix;
2. three boosting rounds on each produce bit-identical model text
   (gbdt config — the chunked-vs-monolithic parity bar);
3. host residency of raw chunk data stays O(chunk): the
   ``construct_peak_bytes`` gauge must be <= 2 chunks of raw bytes (the
   current chunk + its in-flight padded copy), NOT O(N*F), and a
   weakref census over a generator-backed source confirms <= 2 chunks
   were ever alive at once;
4. the construct telemetry surfaces: sketch_pass / bin_pass /
   h2d_overlap land in ``telemetry.construct_snapshot()`` and (under
   TIMETAG) in ``profiling.scopes()``;
5. a compacted sketch (sketch_max_size << distinct values) still yields
   boundaries within the documented rank error of the exact fit.

Exercised by tests/run_suite.sh; exits non-zero on any failure.
"""

import os
import sys
import weakref

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu import telemetry  # noqa: E402
from lightgbm_tpu.utils import profiling  # noqa: E402


def fail(msg):
    print(f"FAIL: {msg}")
    sys.exit(1)


def main():
    rng = np.random.RandomState(11)
    n, f, chunk = 6000, 8, 1200
    X = rng.normal(size=(n, f)).astype(np.float32)
    X[:, 5] *= (rng.rand(n) < 0.25)                 # zero-heavy column
    X[rng.rand(n) < 0.03, 7] = np.nan               # NaN column
    y = (X[:, 0] + 0.5 * X[:, 1] - X[:, 5] > 0).astype(np.float64)
    train = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
             "learning_rate": 0.1, "verbosity": -1}

    # --- monolithic reference
    ds_m = lgb.Dataset(X.copy(), label=y, params={"verbosity": -1})
    b_m = lgb.train(dict(train), ds_m, num_boost_round=3)
    model_m = b_m.model_to_string()

    # --- chunked stream through a LIVE-CHUNK CENSUS: a generator-backed
    # factory whose yielded arrays are weakref-tracked, proving the
    # construct loops hold at most 2 chunks of raw data at any moment
    live = set()
    peak_live = [0]

    def factory():
        def gen():
            for s in range(0, n, chunk):
                c = np.array(X[s:s + chunk])        # fresh buffer to track
                yv = np.array(y[s:s + chunk])
                live.add(id(c))
                weakref.finalize(c, live.discard, id(c))
                peak_live[0] = max(peak_live[0], len(live))
                yield c, yv
        return gen()

    profiling.enable(True)
    profiling.reset()
    ds_c = lgb.Dataset.from_chunks(factory, params={"verbosity": -1})
    ds_c.construct()
    profiling.enable(False)
    if peak_live[0] > 2:
        fail(f"{peak_live[0]} raw chunks were alive at once (O(chunk) "
             f"residency requires <= 2)")
    print(f"PASS: raw-chunk census peak {peak_live[0]} <= 2 live chunks")

    gauges = profiling.gauges()
    peak_bytes = gauges.get("construct_peak_bytes")
    chunk_bytes = chunk * f * 4
    if not peak_bytes or peak_bytes > 2 * chunk_bytes:
        fail(f"construct_peak_bytes={peak_bytes} exceeds 2 chunks "
             f"({2 * chunk_bytes})")
    print(f"PASS: construct_peak_bytes {int(peak_bytes)} <= 2 x "
          f"{chunk_bytes} (raw matrix would be {n * f * 4})")

    import json
    if json.dumps([m.to_dict() for m in ds_m.mappers]) != \
            json.dumps([m.to_dict() for m in ds_c.mappers]):
        fail("sketch-fitted mappers differ from the sampled fit")
    if not np.array_equal(np.asarray(ds_m.bins), np.asarray(ds_c.bins)):
        fail("chunked bin matrix differs from monolithic")
    print("PASS: mappers + bin matrix bit-identical to monolithic")

    b_c = lgb.train(dict(train), ds_c, num_boost_round=3)
    if b_c.model_to_string() != model_m:
        fail("chunked-vs-monolithic model text differs")
    print("PASS: 3-round model text bit-identical (gbdt)")

    snap = telemetry.construct_snapshot()
    for k in ("sketch_pass", "bin_pass", "h2d_overlap", "peak_host_bytes",
              "rows", "rows_per_sec"):
        if k not in snap:
            fail(f"telemetry.construct_snapshot missing {k!r}: {snap}")
    scopes = profiling.scopes()
    for k in ("sketch_pass", "bin_pass", "h2d_overlap"):
        if k not in scopes:
            fail(f"TIMETAG scope {k!r} not recorded: {sorted(scopes)}")
    print(f"PASS: construct telemetry on record "
          f"({ {k: snap[k] for k in ('sketch_pass', 'bin_pass')} })")

    # --- compacted-sketch rank error at smoke scale
    from lightgbm_tpu import binning
    from lightgbm_tpu.config import Config
    col = np.random.RandomState(5).normal(size=20000)
    cfg = Config.from_params({"verbosity": -1})
    sk = binning.FeatureSketch(max_size=512)
    for s in range(0, len(col), 2500):
        sk.fold(col[s:s + 2500])
    sv = np.sort(col)
    sketch_rank = np.cumsum(sk.counts) / sk.total_cnt
    true_rank = np.searchsorted(sv, sk.values, side="right") / len(col)
    err = float(np.max(np.abs(sketch_rank - true_rank)))
    budget = 2.0 * sk.compactions / sk.max_size
    if err > budget:
        fail(f"compacted-sketch rank error {err:.4f} > documented budget "
             f"{budget:.4f} (~2*compactions/max_size)")
    approx = binning.fit_mappers_from_sketches([sk], len(col), cfg)[0]
    if abs(approx.num_bin - 255) > 8:
        fail(f"compacted-sketch mapper degenerated: {approx.num_bin} bins")
    print(f"PASS: compacted sketch (512 of {len(np.unique(col))} distinct, "
          f"{sk.compactions} compactions) rank error {err:.4f} <= "
          f"{budget:.4f}; mapper keeps {approx.num_bin} bins")

    # --- free_dataset / re-entry audit on the chunked path
    if ds_c.data is not None or ds_c._chunk_source is not None:
        fail("streaming construct left a raw/chunk-source reference pinned")
    if ds_c.construct() is not ds_c:
        fail("construct re-entry did not no-op")
    b_c.free_dataset()
    if ds_c.bins is not None or ds_c._chunk_source is not None:
        fail("free_dataset left streaming dataset arrays pinned")
    _ = b_c.predict(X[:64])
    print("PASS: free_dataset releases the chunked dataset; predict works")
    print("construct smoke: ALL PASS")


if __name__ == "__main__":
    main()
