#!/usr/bin/env python
"""Post-mortem pipeline smoke: supervised kill -> the analyzer names the
killed rank (fast knobs, ~40 s on CPU).

Drill: a 2-process localhost gang training with per-iteration
checkpoints has rank 1 hard-killed at iteration 2 (os._exit 137 via the
fault harness) with NO restart budget — the supervisor must:

  1. raise ``GangFailedError`` carrying a ``postmortem`` report path it
     generated automatically (the supervisor runs the analyzer on gang
     failure);
  2. the machine report must classify the failure ``kill`` and name
     rank 1 (the exit-137 evidence + rank 1's fault-kill flight flush);
  3. rerunning the analysis offline through ``scripts/postmortem.py``
     over the diag directory must reach the SAME verdict/rank (the
     operator workflow: kill a gang -> run the script -> read the
     verdict) and exit 0 under ``--expect kill``.

Usage:  JAX_PLATFORMS=cpu python scripts/postmortem_smoke.py
Exits 0 on success, 1 with a diagnosis otherwise. Wired into
tests/run_suite.sh; the classification logic itself is covered per
fault class in tests/test_postmortem.py.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

PARAMS = {"objective": "binary", "num_leaves": 8, "min_data_in_leaf": 5,
          "boost_from_average": False, "histogram_method": "scatter",
          "verbosity": -1, "heartbeat_interval": 0.4,
          "collective_deadline": 10.0}
ROUNDS = 4


def train_fn(rank, ckdir):
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(7)
    X = rng.normal(size=(320, 6))
    y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params=dict(PARAMS), free_raw_data=False)
    booster = lgb.train(dict(PARAMS), ds, ROUNDS,
                        callbacks=[lgb.checkpoint_callback(ckdir, period=1)],
                        resume_from=ckdir)
    return booster.model_to_string()


def main() -> int:
    from lightgbm_tpu import supervisor
    t0 = time.time()
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "ck")
        os.environ["LGBM_TPU_FAULT_KILL_RANK_AT_ITER"] = "1:2"
        err = None
        try:
            supervisor.run_supervised(
                train_fn, nproc=2, args=(ck,), devices_per_proc=1,
                checkpoint_dir=ck, max_restarts=0, timeout=180)
        except supervisor.GangFailedError as e:
            err = e
        finally:
            os.environ.pop("LGBM_TPU_FAULT_KILL_RANK_AT_ITER", None)
        if err is None:
            print("FAIL: gang with max_restarts=0 and a killed rank "
                  "did not raise GangFailedError")
            return 1
        if not err.postmortem or not os.path.exists(err.postmortem):
            print(f"FAIL: GangFailedError carries no post-mortem report "
                  f"path (got {err.postmortem!r})")
            return 1
        with open(err.postmortem) as fh:
            report = json.load(fh)
        if report.get("verdict") != "kill" or report.get("rank") != 1:
            print(f"FAIL: expected verdict 'kill' naming rank 1, got "
                  f"{report.get('verdict')!r} rank {report.get('rank')!r}")
            return 1
        if str(err.postmortem) not in str(err):
            print("FAIL: GangFailedError message does not reference the "
                  "report path")
            return 1
        # operator workflow: rerun the analysis offline over the diag dir
        diag_dir = os.path.dirname(err.postmortem)
        r = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "postmortem.py"),
             diag_dir, "--checkpoint-dir", ck, "--expect", "kill"],
            capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            print(f"FAIL: scripts/postmortem.py exited {r.returncode}:\n"
                  f"{r.stdout[-1500:]}\n{r.stderr[-1500:]}")
            return 1
        if "rank 1" not in r.stdout:
            print(f"FAIL: offline report does not name rank 1:\n"
                  f"{r.stdout[-1500:]}")
            return 1
    print(f"OK: killed rank 1 classified 'kill' by the supervisor's "
          f"auto post-mortem AND by the offline scripts/postmortem.py "
          f"rerun ({time.time() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
