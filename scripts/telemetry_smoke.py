#!/usr/bin/env python
"""Telemetry-layer end-to-end smoke (CPU, fast knobs, ~20 s).

Drill: (1) a short recorder-on training run under a durable telemetry
dir, killed mid-run by the fault harness — the flushed flight-recorder
JSONL must exist, parse, schema-validate, and name the in-flight
iteration; (2) a clean run whose train-end flush validates and whose
health snapshot references the JSONL by path; (3) with ``--trace``
(default on), a ``telemetry.trace_window`` capture around two boosting
iterations — on backends whose profiler cannot start the contract is a
recorded error, never a crash (the jax.profiler no-op tolerance);
(4) the Prometheus exposition renders and every line parses.

Wired into tests/run_suite.sh. Exit 0 = all stages passed.
"""

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def log(msg):
    print(f"[telemetry_smoke] {msg}", flush=True)


def check(cond, msg):
    if not cond:
        log(f"FAIL: {msg}")
        sys.exit(1)
    log(f"ok: {msg}")


def stage_kill_flush(tmp):
    """Killed training leaves a valid post-mortem JSONL."""
    from lightgbm_tpu import telemetry
    tele_dir = os.path.join(tmp, "tele_kill")
    code = (
        "import numpy as np, lightgbm_tpu as lgb\n"
        "rng = np.random.RandomState(0)\n"
        "X = rng.normal(size=(3000, 8)).astype(np.float32)\n"
        "y = (X[:, 0] > 0).astype(np.float32)\n"
        "ds = lgb.Dataset(X, label=y, params={'verbosity': -1})\n"
        "lgb.train({'objective': 'binary', 'num_leaves': 15,\n"
        "           'verbosity': -1, 'telemetry_dir': %r,\n"
        "           'fault_kill_at_iter': 4}, ds, 12)\n" % tele_dir)
    r = subprocess.run([sys.executable, "-c", code],
                       env=dict(os.environ, JAX_PLATFORMS="cpu"),
                       capture_output=True, text=True, timeout=300)
    check(r.returncode == 137,
          f"harness kill exits 137 (got {r.returncode})")
    path = os.path.join(tele_dir, "flight_rank0.jsonl")
    check(os.path.exists(path), "kill flushed a flight-recorder JSONL")
    recs, errors = telemetry.validate_flight_jsonl(path)
    check(not errors, f"JSONL schema-validates ({errors[:3]})")
    flush = recs[-1]
    check(flush["type"] == "flush" and "at iteration 4" in flush["reason"],
          f"last record names the in-flight iteration "
          f"({flush.get('reason')!r})")
    iters = [x for x in recs if x["type"] == "iter"]
    check(iters and iters[-1]["iteration"] == 3,
          "per-iteration records cover every completed iteration")


def stage_clean_run(tmp):
    """Clean training: train-end flush + health reference."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu import distributed, telemetry
    tele_dir = os.path.join(tmp, "tele_clean")
    rng = np.random.RandomState(1)
    X = rng.normal(size=(3000, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    booster = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbosity": -1, "telemetry_dir": tele_dir},
                        ds, 5)
    path = os.path.join(tele_dir, "flight_rank0.jsonl")
    check(os.path.exists(path), "clean run flushed at train end")
    recs, errors = telemetry.validate_flight_jsonl(path)
    check(not errors, f"clean-run JSONL validates ({errors[:3]})")
    check(recs[-1]["reason"] == "train-end", "final flush is train-end")
    check(distributed.health_snapshot().get("flight_recorder") == path,
          "health snapshot references the JSONL by path")
    return booster


def stage_trace(tmp, booster):
    """Windowed device-trace capture (jax.profiler no-op tolerance)."""
    from lightgbm_tpu import telemetry
    trace_dir = os.path.join(tmp, "trace")
    with telemetry.trace_window(trace_dir, iters=2) as tw:
        for _ in range(2):
            booster.update()
    if tw.ok:
        check(bool(telemetry.trace_files(trace_dir)),
              "trace capture wrote artifact files")
    else:
        # the tolerance contract: no raise, error recorded
        check(bool(tw.error), f"trace failure recorded ({tw.error!r})")


def stage_prometheus():
    from lightgbm_tpu import telemetry
    text = telemetry.prometheus_text()
    bad = [ln for ln in text.splitlines()
           if ln and not ln.startswith("#")
           and not ln.startswith("lightgbm_tpu_")]
    check(not bad, f"every exposition line is namespaced ({bad[:2]})")
    for ln in text.splitlines():
        if ln and not ln.startswith("#"):
            float(ln.rpartition(" ")[2])
    check(True, "every exposition value parses as a number")
    log("snapshot: " + json.dumps(
        {k: type(v).__name__ for k, v in telemetry.snapshot().items()}))


def main():
    trace = "--no-trace" not in sys.argv
    with tempfile.TemporaryDirectory(prefix="lgbm_tele_smoke_") as tmp:
        stage_kill_flush(tmp)
        booster = stage_clean_run(tmp)
        if trace:
            stage_trace(tmp, booster)
        stage_prometheus()
    log("ALL STAGES PASSED")


if __name__ == "__main__":
    main()
