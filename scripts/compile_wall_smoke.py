"""Compile-wall CI smoke: cold-then-warm two-process drill (CPU).

Phase 1 (cold process): train a K=4-blocks-per-dispatch booster against
a fresh persistent compile cache + checkpoint dir — every fused program
is an XLA compile (cache miss) that lands on disk.

Phase 2 (warm process): a NEW process resumes the same training from the
checkpoint against the same cache — the restore-time AOT warmup and the
first K-block must be pure cache DESERIALIZATIONS: zero fused-step XLA
compiles, and the continued model must be bit-identical to an
uninterrupted single-process run.

This is the supervisor-relaunch / elastic-gang warm path reduced to its
smallest reproducible shape: the persistent cache works on the CPU
backend (where cross-process XLA collectives don't — the same reason
the gang tests run replicated-serial), so CI proves the cold -> warm
transition on every container.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROUNDS_COLD = 4
ROUNDS_FULL = 8
K = 4

_CHILD = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, %(repo)r)
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu import callback as callback_mod
from lightgbm_tpu import compile_cache

cfg = json.loads(sys.argv[1])
rng = np.random.RandomState(3)
X = rng.normal(size=(2000, 8)).astype(np.float32)
y = (X[:, 0] + 0.4 * X[:, 1] + rng.normal(size=2000) * 0.3 > 0)
y = y.astype(np.float32)
p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 10,
     "verbosity": -1, "boost_rounds_per_dispatch": cfg["K"],
     "compile_cache_dir": cfg["cache_dir"]}
cbs = [callback_mod.checkpoint(cfg["ckpt_dir"], period=cfg["K"])] \
    if cfg["ckpt_dir"] else []
t0 = time.time()
b = lgb.train(p, lgb.Dataset(X, label=y, params=p), cfg["rounds"],
              callbacks=cbs,
              resume_from=cfg["ckpt_dir"] if cfg["resume"] else None)
json.dump({
    "wall_s": round(time.time() - t0, 3),
    "iter": b._boosting.iter,
    "model": b.model_to_string(),
    "fused_misses": compile_cache.module_count("misses", "jit__fused"),
    "fused_hits": compile_cache.module_count("hits", "jit__fused"),
}, open(cfg["out"], "w"))
""" % {"repo": REPO}


def run_child(cfg):
    r = subprocess.run([sys.executable, "-c", _CHILD, json.dumps(cfg)],
                       capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        print(r.stderr[-3000:], file=sys.stderr)
        raise SystemExit(f"child failed (rc={r.returncode})")
    with open(cfg["out"]) as fh:
        return json.load(fh)


def strip(model_text):
    drop = ("[boost_rounds_per_dispatch", "[compile_cache_dir")
    return "\n".join(l for l in model_text.splitlines()
                     if not l.startswith(drop))


def main():
    t0 = time.time()
    with tempfile.TemporaryDirectory() as tmp:
        cache = os.path.join(tmp, "cache")
        ckpt = os.path.join(tmp, "ckpt")
        print(f"# cold process: {ROUNDS_COLD} rounds, K={K}, fresh cache")
        cold = run_child({"K": K, "cache_dir": cache, "ckpt_dir": ckpt,
                          "rounds": ROUNDS_COLD, "resume": False,
                          "out": os.path.join(tmp, "cold.json")})
        assert cold["iter"] == ROUNDS_COLD, cold
        assert cold["fused_misses"] >= 1, \
            f"cold run should MISS (and fill) the cache: {cold}"
        print(f"#   wall {cold['wall_s']}s, fused misses "
              f"{cold['fused_misses']} (cache filled)")

        print(f"# warm process: resume -> {ROUNDS_FULL} rounds, same cache")
        warm = run_child({"K": K, "cache_dir": cache, "ckpt_dir": ckpt,
                          "rounds": ROUNDS_FULL, "resume": True,
                          "out": os.path.join(tmp, "warm.json")})
        assert warm["iter"] == ROUNDS_FULL, warm
        assert warm["fused_misses"] == 0, \
            f"warm incarnation recompiled the fused step: {warm}"
        assert warm["fused_hits"] >= 1, warm
        print(f"#   wall {warm['wall_s']}s, fused misses 0, "
              f"fused hits {warm['fused_hits']} (started hot)")

        print("# reference: uninterrupted single process, no cache")
        full = run_child({"K": K, "cache_dir": os.path.join(tmp, "c2"),
                          "ckpt_dir": "", "rounds": ROUNDS_FULL,
                          "resume": False,
                          "out": os.path.join(tmp, "full.json")})
        assert strip(warm["model"]) == strip(full["model"]), \
            "warm continuation diverged from the uninterrupted run"
        print("#   warm continuation BIT-IDENTICAL to uninterrupted run")
    print(f"compile_wall_smoke: PASS ({time.time() - t0:.0f}s)")


if __name__ == "__main__":
    main()
