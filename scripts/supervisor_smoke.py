#!/usr/bin/env python
"""Supervisor gang-restart + elastic-shrink + integrity smoke: fast
knobs, ~90 s on CPU.

Three stanzas:
  1. restart — a 2-process localhost gang training with per-iteration
     checkpoints has rank 1 hard-killed at iteration 3 (os._exit 137 via
     the fault harness); the supervisor must relaunch the gang exactly
     once and the final model text must be BIT-IDENTICAL to an
     uninterrupted gang's.
  2. elastic — rank 1's spawn fails outright (exit 96 via
     LGBM_TPU_FAULT_SPAWN_FAIL_RANK); the supervisor must classify the
     rank permanently lost, SHRINK the gang to world size 1, complete
     training there, and record the shrink in the SupervisorReport.
  3. integrity — one score-cache bit is flipped on rank 1 of a 3-rank
     gang (LGBM_TPU_FAULT_FLIP_SCORE_RANK); the cross-rank divergence
     check must name exactly that rank (exit 95 + a divergence
     diagnosis), the supervisor must restore the gang from the last
     valid checkpoint, and the final model text must be BIT-IDENTICAL
     to the fault-free run's.

Usage:  JAX_PLATFORMS=cpu python scripts/supervisor_smoke.py
Exits 0 on success, 1 with a diagnosis otherwise. The same paths run in
tier-1 as tests/test_supervisor.py::test_gang_kill_rank_mid_iter_bit_identical,
::test_gang_shrink_on_spawn_fail and
tests/test_integrity.py::test_supervised_corrupt_rank_restart_bit_identical.
"""
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PARAMS = {"objective": "binary", "num_leaves": 8, "min_data_in_leaf": 5,
          "boost_from_average": False, "histogram_method": "scatter",
          "verbosity": -1, "heartbeat_interval": 0.4,
          "collective_deadline": 10.0}
# the integrity stanza turns the cross-rank divergence check on (every
# iteration — fast knobs; production cadence is coarser)
INTEG_PARAMS = dict(PARAMS, integrity_check_period=1,
                    collective_deadline=12.0)
ROUNDS = 4


def _make_fn(params):
    def train_fn(rank, ckdir):
        import lightgbm_tpu as lgb
        rng = np.random.RandomState(7)
        X = rng.normal(size=(320, 6))
        y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
        ds = lgb.Dataset(X, label=y, params=dict(params),
                         free_raw_data=False)
        booster = lgb.train(dict(params), ds, ROUNDS,
                            callbacks=[lgb.checkpoint_callback(ckdir,
                                                               period=1)],
                            resume_from=ckdir)
        return booster.model_to_string()
    return train_fn


def train_fn(rank, ckdir):
    return _make_fn(PARAMS)(rank, ckdir)


def integ_train_fn(rank, ckdir):
    return _make_fn(INTEG_PARAMS)(rank, ckdir)


def main() -> int:
    from lightgbm_tpu import supervisor
    t0 = time.time()
    with tempfile.TemporaryDirectory() as td:
        clean = supervisor.run_supervised(
            train_fn, nproc=2, args=(os.path.join(td, "clean"),),
            devices_per_proc=1, timeout=180)
        if clean.restarts != 0:
            print(f"FAIL: clean gang restarted {clean.restarts}x")
            return 1
        ck = os.path.join(td, "ck")
        os.environ["LGBM_TPU_FAULT_KILL_RANK_AT_ITER"] = "1:3"
        try:
            report = supervisor.run_supervised(
                train_fn, nproc=2, args=(ck,), devices_per_proc=1,
                checkpoint_dir=ck, max_restarts=2, timeout=180)
        finally:
            os.environ.pop("LGBM_TPU_FAULT_KILL_RANK_AT_ITER", None)
        if report.restarts != 1:
            print(f"FAIL: expected exactly 1 restart, got {report.restarts}")
            return 1
        if report.result != clean.result:
            print("FAIL: restarted gang's model text differs from the "
                  "uninterrupted run's")
            return 1
        # ---- elastic stanza: rank 1 permanently lost -> gang shrinks
        cke = os.path.join(td, "ck_elastic")
        os.environ["LGBM_TPU_FAULT_SPAWN_FAIL_RANK"] = "1"
        try:
            elastic = supervisor.run_supervised(
                train_fn, nproc=2, args=(cke,), devices_per_proc=1,
                checkpoint_dir=cke, max_restarts=2, timeout=180)
        finally:
            os.environ.pop("LGBM_TPU_FAULT_SPAWN_FAIL_RANK", None)
        if elastic.world_size != 1 or len(elastic.shrinks) != 1 \
                or elastic.shrinks[0].lost_ranks != [1]:
            print(f"FAIL: expected one 2->1 shrink of lost rank 1, got "
                  f"world_size={elastic.world_size} "
                  f"shrinks={elastic.shrinks}")
            return 1
        if elastic.result != clean.result:
            print("FAIL: shrunken gang's model text differs from the "
                  "uninterrupted run's")
            return 1
        # ---- integrity stanza: bit-flip -> divergence detect ->
        # corrupt-rank restart -> complete, bit-identical. The fault-free
        # reference is a single-process run: the gang trains the serial
        # learner on replicated data, so every rank's model equals it.
        import lightgbm_tpu as lgb
        from lightgbm_tpu import distributed
        rng = np.random.RandomState(7)
        X = rng.normal(size=(320, 6))
        y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
        ds = lgb.Dataset(X, label=y, params=dict(INTEG_PARAMS),
                         free_raw_data=False)
        ref = lgb.train(dict(INTEG_PARAMS), ds, ROUNDS).model_to_string()
        cki = os.path.join(td, "ck_integrity")
        os.environ["LGBM_TPU_FAULT_FLIP_SCORE_RANK"] = "1:2"
        try:
            integ = supervisor.run_supervised(
                integ_train_fn, nproc=3, args=(cki,), devices_per_proc=1,
                checkpoint_dir=cki, max_restarts=2, timeout=240)
        finally:
            os.environ.pop("LGBM_TPU_FAULT_FLIP_SCORE_RANK", None)
        if integ.restarts != 1:
            print(f"FAIL: integrity gang expected exactly 1 restart, got "
                  f"{integ.restarts}")
            return 1
        if integ.failures[0].exit_codes.get(1) \
                != distributed.DIVERGENCE_EXIT_CODE:
            print(f"FAIL: expected rank 1 to exit with the divergence "
                  f"code, got {integ.failures[0].exit_codes}")
            return 1
        divs = [d for f in integ.failures for d in f.watchdog
                if d.get("kind") == "divergence"]
        if not divs or divs[0].get("corrupt_ranks") != [1]:
            print(f"FAIL: divergence diagnosis should name exactly rank "
                  f"1, got {divs}")
            return 1
        if integ.result != ref:
            print("FAIL: restored gang's model text differs from the "
                  "fault-free run's")
            return 1
    print(f"OK: gang killed at iter 3, restarted once, model text "
          f"bit-identical; spawn-failed rank 1 shrank the gang 2->1 and "
          f"training completed; bit-flipped rank 1 of a 3-rank gang named "
          f"by the divergence vote, restored from checkpoint, model text "
          f"bit-identical ({time.time() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
