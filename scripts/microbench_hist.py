"""Microbenchmark: one full-data histogram tile pass at Higgs scale.

Compares the histogram backends head-to-head on the real chip (the pass this
framework's sec/iter is made of — reference hot-loop analog:
src/io/dense_bin.hpp:98-141, src/treelearner/kernels/histogram_16_64_256.cu).

Usage: python scripts/microbench_hist.py [--rows 10500000] [--reps 5]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def sync(x):
    return float(np.asarray(x).ravel()[0])


def timeit(fn, reps):
    fn()  # compile
    sync(fn())
    t0 = time.time()
    for _ in range(reps):
        out = fn()
    sync(out)
    return (time.time() - t0) / reps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_500_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--bins", type=int, default=255)
    ap.add_argument("--tile", type=int, default=42)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--only", type=str, default=None,
                    help="comma-separated subset of variant names")
    ap.add_argument("--pend-frac", type=float, default=0.25,
                    help="pending-row fraction for the compacted-pass "
                         "variants (gather + histogram over the rung)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import histogram_tiles

    n, f, b, p = args.rows, args.features, args.bins, args.tile
    print(f"# device={jax.devices()[0]} N={n} F={f} B={b} P={p}")

    rng = np.random.RandomState(0)
    bins_np = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    bins = jnp.asarray(bins_np)
    binsT = jnp.asarray(np.ascontiguousarray(bins_np.T))
    stats = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))
    leaf_ids = jnp.asarray(rng.randint(0, p, size=n).astype(np.int32))
    sel = jnp.arange(p, dtype=jnp.int32)

    results = {}

    def bench(name, fn):
        if args.only and name not in args.only.split(","):
            return
        try:
            dt = timeit(fn, args.reps)
            results[name] = dt
            print(f"{name:32s} {dt*1e3:9.1f} ms/pass")
        except Exception as e:
            print(f"{name:32s} FAILED: {type(e).__name__}: {e}")

    onehot = jax.jit(lambda: histogram_tiles(
        bins, stats, leaf_ids, sel, b, method="onehot"))
    bench("xla_onehot_highest", onehot)

    onehot_hilo = jax.jit(lambda: histogram_tiles(
        bins, stats, leaf_ids, sel, b, method="onehot_hilo"))
    bench("xla_onehot_hilo", onehot_hilo)

    from lightgbm_tpu.ops import pallas_hist

    for blk in (1024, 2048, 4096, 8192):
        bench(f"pallas_highest_blk{blk}", jax.jit(
            lambda blk=blk: pallas_hist.histogram_tiles_pallas(
                binsT, stats, leaf_ids, sel, b, block=blk)))

    if hasattr(pallas_hist, "histogram_tiles_pallas_hilo"):
        for blk in (1024, 2048, 4096, 8192):
            bench(f"pallas_hilo_blk{blk}", jax.jit(
                lambda blk=blk: pallas_hist.histogram_tiles_pallas_hilo(
                    binsT, stats, leaf_ids, sel, b, block=blk)))

    if hasattr(pallas_hist, "histogram_tiles_pallas_mode"):
        stats_q = jnp.asarray(
            rng.randint(-127, 128, size=(n, 3)).astype(np.int8))
        for blk in (2048, 4096):
            bench(f"pallas_q8_blk{blk}", jax.jit(
                lambda blk=blk: pallas_hist.histogram_tiles_pallas_mode(
                    binsT, stats_q, leaf_ids, sel, b, block=blk,
                    mode="q8")))

    # compacted passes (grower ladder analog): leaf ids drawn over 1/frac
    # as many leaves as the tile selects, so ~frac of the rows are pending;
    # the variant times gather (compact_rows) + histogram over the rung —
    # the full end-to-end cost the ladder pays per tile round
    from lightgbm_tpu.ops.histogram import compact_rows

    frac = args.pend_frac
    spread = max(1, int(round(1.0 / max(frac, 1e-6))))
    leaf_wide = jnp.asarray(
        rng.randint(0, spread * p, size=n).astype(np.int32))
    in_tile = leaf_wide < p
    # size the rung from the ACTUAL pending count (the grower's lax.cond
    # guarantees n_pend <= rung before dispatching; the variant must honor
    # the same compact_rows contract or it silently drops pending rows)
    rung = -(-int(np.asarray(jnp.sum(in_tile))) // 512) * 512

    def compacted(method, use_binsT):
        def fn():
            bm, btm, st, lid = compact_rows(
                bins, binsT if use_binsT else None, stats, leaf_wide,
                in_tile, rung)
            from lightgbm_tpu.ops.histogram import histogram_tiles
            return histogram_tiles(bm, st, lid, sel, b, method=method,
                                   binsT=btm)
        return jax.jit(fn)

    bench(f"compact{frac:.2f}_scatter", compacted("scatter", False))
    bench(f"compact{frac:.2f}_onehot_hilo", compacted("onehot_hilo", True))
    bench(f"compact{frac:.2f}_pallas_hilo", compacted("pallas_hilo", True))

    if results:
        best = min(results, key=results.get)
        print(f"# best: {best} ({results[best]*1e3:.1f} ms)")


if __name__ == "__main__":
    main()
