"""Per-kernel roofline microbench for the fused Pallas histogram pipeline.

For each mode (hilo / highest / q8) and kernel form (full pass / in-kernel
gather) at a Higgs-shaped tile pass, reports:

- **bytes moved** (modeled HBM traffic, ops/pallas_hist.py traffic_model)
  and the achieved HBM bandwidth implied by the measured time;
- **MXU passes** (contraction input passes: hilo 2 bf16, highest 6, q8 1
  int8) and the achieved vs peak MXU rate on the mode's input path;
- the **XLA onehot** formulation of the same contraction as the baseline
  (the acceptance comparison: the fused kernel's modeled traffic is
  >= 5x below it, and on TPU the measured time should follow).

On a TPU the numbers are real; on CPU hosts ``--interpret`` runs the
kernels through the Pallas interpreter — times are then meaningless
(interpretation overhead), but the traffic/roofline MODEL columns still
hold and every kernel variant actually executes. The CI smoke
(`tests/run_suite.sh`, ``--fast --interpret``) runs all nine variants
through the interpreter at a tiny shape (~30-60 s) and asserts the
modeled >=5x traffic ratios; ``--model-only`` skips execution entirely
for an instant model-table print.

Usage:
  python scripts/kernel_bench.py                  # Higgs0.5M shape, TPU
  python scripts/kernel_bench.py --rows 10500000  # full Higgs
  python scripts/kernel_bench.py --fast --interpret   # the CI smoke
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# v5e peaks per MXU input path (same assumptions as bench.py)
PEAK = {"f32": 98e12, "bf16": 197e12, "int8": 394e12}
MODE_PATH = {"hilo": "bf16", "highest": "bf16", "q8": "int8"}
# ~819 GB/s HBM per v5e chip
PEAK_HBM = 819e9


def timeit(fn, reps):
    import jax.numpy as jnp
    r = fn()
    float(jnp.sum(r))               # compile + first run
    t0 = time.time()
    for _ in range(reps):
        r = fn()
    float(jnp.sum(r))               # sync via scalar fetch
    return (time.time() - t0) / reps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=500_000,
                    help="tile-pass rows (default: the Higgs0.5M shape)")
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--bins", type=int, default=255)
    ap.add_argument("--tile", type=int, default=42)
    ap.add_argument("--block", type=int, default=2048)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--gather-frac", type=float, default=0.25,
                    help="pending-row fraction for the gather-kernel rows")
    ap.add_argument("--modes", type=str, default="hilo,highest,q8")
    ap.add_argument("--interpret", action="store_true",
                    help="run kernels through the Pallas interpreter "
                         "(CPU hosts; times are interpreter overhead)")
    ap.add_argument("--model-only", action="store_true",
                    help="print the traffic/roofline model without timing "
                         "(works anywhere, instantly)")
    ap.add_argument("--fast", action="store_true",
                    help="CI smoke knobs: tiny shape, 1 rep")
    args = ap.parse_args()
    if args.fast:
        args.rows = min(args.rows, 8192)
        args.features = min(args.features, 6)
        args.bins = min(args.bins, 63)
        args.block = min(args.block, 512)
        args.reps = 1

    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops import pallas_hist
    from lightgbm_tpu.ops.histogram import histogram_tiles

    n, f, b, p = args.rows, args.features, args.bins, args.tile
    s = 3
    m = -(-int(n * args.gather_frac) // 128) * 128
    backend = jax.default_backend()
    interpret = args.interpret and backend != "tpu"
    print(f"# device={jax.devices()[0]} N={n} F={f} B={b} P={p} "
          f"block={args.block} gather_rows={m} interpret={interpret}",
          file=sys.stderr)

    rng = np.random.RandomState(0)
    binsT_np = rng.randint(0, b, size=(f, n)).astype(np.uint8)
    binsT = jnp.asarray(binsT_np)
    bins = jnp.asarray(np.ascontiguousarray(binsT_np.T))
    stats_f = jnp.asarray(rng.normal(size=(n, s)).astype(np.float32))
    stats_i = jnp.asarray(rng.randint(-127, 128, (n, s)).astype(np.int8))
    leaf = jnp.asarray(rng.randint(0, p, size=n).astype(np.int32))
    sel = jnp.asarray(np.arange(p, dtype=np.int32))
    idx = jnp.asarray(np.sort(rng.choice(n, size=m, replace=False))
                      .astype(np.int32))

    # per-pass MAC count of the contraction: every row drives F one-hot
    # columns x 128 output lanes (feature packing keeps the tile full at
    # b <= 64, so lanes-per-row is 128 regardless of b)
    g = max(1, 128 // b) if b <= 128 else 1
    macs_full = n * (-(-f // g)) * max(b * g, 128) * 128
    macs_gather = m * (-(-f // g)) * max(b * g, 128) * 128

    rows = []

    def record(name, mode, kind, sec, traffic, macs):
        path = MODE_PATH.get(mode, "f32")
        passes = pallas_hist.MXU_PASSES.get(mode, 1)
        entry = {
            "variant": name, "mode": mode, "kind": kind,
            "modeled_bytes": traffic,
            "mxu_passes": passes,
            "macs": macs,
            "sec": round(sec, 6) if sec is not None else None,
        }
        if sec is not None and not interpret:
            entry["achieved_hbm_frac"] = round(traffic / sec / PEAK_HBM, 4)
            entry["achieved_mxu_frac"] = round(
                2.0 * macs * passes / sec / PEAK[path], 4)
        rows.append(entry)
        print(json.dumps(entry), flush=True)

    # fused split-EPILOGUE variant inputs (ISSUE 12): a paired tile with
    # the odd slots derived in-pass, dummy-but-valid scan metadata
    from lightgbm_tpu.ops.split import CAND_CHANNELS
    derive = jnp.asarray((np.arange(p) % 2).astype(bool))
    sel_pairs = jnp.asarray(np.arange(p, dtype=np.int32))
    parent = jnp.zeros((p, f, b, s), jnp.float32)
    la = pallas_hist.pack_leaf_aux(
        jnp.zeros((p,)), jnp.ones((p,)), jnp.full((p,), float(n)),
        jnp.zeros((p,)))
    fmeta = pallas_hist.pack_feature_meta(
        jnp.full((f,), b, jnp.int32), jnp.zeros((f,), jnp.int32),
        jnp.zeros((f,), jnp.int32), jnp.zeros((f,), jnp.int32))
    pvec = jnp.zeros((7,), jnp.float32)

    for mode in args.modes.split(","):
        st = stats_i if mode == "q8" else stats_f
        t = pallas_hist.traffic_model(n, f, b, p, s, mode)
        tg = pallas_hist.traffic_model(n, f, b, p, s, mode,
                                       gathered_rows=m)
        sec_full = sec_gather = sec_xla = sec_epi = None
        if not args.model_only:
            sec_full = timeit(lambda: pallas_hist.histogram_tiles_pallas_mode(
                binsT, st, leaf, sel, b, block=args.block, mode=mode,
                interpret=interpret), args.reps)
            sec_gather = timeit(
                lambda: pallas_hist.histogram_tiles_pallas_mode(
                    binsT, st, leaf, sel, b, block=args.block, mode=mode,
                    idx=idx, interpret=interpret), args.reps)
            qsc = (jnp.ones((s,), jnp.float32) if mode == "q8" else None)
            epi_tile, epi_cand = pallas_hist.histogram_tiles_pallas_epilogue(
                binsT, st, leaf, sel_pairs, derive, parent, la, fmeta,
                pvec, b, block=args.block, mode=mode,
                interpret=interpret, q_scale=qsc)
            # acceptance floor from the REAL returned buffers (not the
            # traffic model): per-leaf plane bytes the classic search
            # would stream vs the candidate row the fused search reads
            plane_per_leaf = epi_tile.nbytes // epi_tile.shape[0]
            cand_per_leaf = epi_cand.nbytes // epi_cand.shape[0]
            sratio_real = plane_per_leaf / cand_per_leaf
            print(f"# {mode}: measured split-search bytes/leaf "
                  f"plane={plane_per_leaf} cand={cand_per_leaf} "
                  f"ratio={sratio_real:.1f}x (floor: B/4 = {b / 4:.1f}x)",
                  file=sys.stderr)
            assert sratio_real >= b / 4, (mode, sratio_real, b)
            sec_epi = timeit(
                lambda: pallas_hist.histogram_tiles_pallas_epilogue(
                    binsT, st, leaf, sel_pairs, derive, parent, la, fmeta,
                    pvec, b, block=args.block, mode=mode,
                    interpret=interpret, q_scale=qsc)[1], args.reps)
            xla_m = {"hilo": "onehot_hilo", "highest": "onehot",
                     "q8": "onehot_q8"}[mode]
            sec_xla = timeit(lambda: histogram_tiles(
                bins, st, leaf, sel, b, method=xla_m,
                block=args.block), args.reps)
        record(f"pallas_{mode}", mode, "full", sec_full, t["fused"],
               macs_full)
        record(f"pallas_{mode}_gather", mode, "gather", sec_gather,
               tg["fused"], macs_gather)
        record(f"pallas_{mode}_epilogue", mode, "epilogue", sec_epi,
               t["fused"], macs_full)
        record(f"xla_onehot_{mode}", mode, "xla-baseline", sec_xla,
               t["xla_onehot"], macs_full)
        ratio = t["xla_onehot"] / t["fused"]
        print(f"# {mode}: modeled traffic fused={t['fused']/1e6:.1f}MB "
              f"xla={t['xla_onehot']/1e6:.1f}MB ratio={ratio:.0f}x "
              f"(acceptance floor: 5x)", file=sys.stderr)
        assert ratio >= 5, (mode, ratio)
        # split-search consumer bytes: per-leaf [F, B, 4] planes vs the
        # epilogue's [F, CAND_CHANNELS] candidate row — the ISSUE 12
        # acceptance floor is a >= B/4x reduction
        sratio = t["search_in_planes"] / t["search_in_cand"]
        print(f"# {mode}: split-search bytes planes="
              f"{t['search_in_planes']} cand={t['search_in_cand']} "
              f"ratio={sratio:.1f}x (floor: B/4 = {b / 4:.1f}x, "
              f"CAND_CHANNELS={CAND_CHANNELS})", file=sys.stderr)
        assert sratio >= b / 4, (mode, sratio, b)
        if sec_full is not None and sec_xla is not None and not interpret:
            print(f"# {mode}: measured fused={sec_full*1e3:.2f}ms "
                  f"epilogue={sec_epi*1e3:.2f}ms "
                  f"xla={sec_xla*1e3:.2f}ms "
                  f"speedup={sec_xla/max(sec_full,1e-12):.2f}x",
                  file=sys.stderr)

    print(f"# OK: {len(rows)} variants", file=sys.stderr)


if __name__ == "__main__":
    main()
