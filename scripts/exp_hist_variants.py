"""Scratch experiments for the fused histogram kernel shape.

Variants of the hi/lo bf16 kernel: features-per-dot grouping, block size.
Not part of the library — results feed ops/pallas_hist.py tuning.
"""

import argparse
import functools
import time

import numpy as np

_PAD = 128


def make_variant(fg, blk):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(binsT_ref, rhs_ref, out_ref, *, f, b, c):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        rhs = rhs_ref[...]
        binsT = binsT_ref[...]
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (c, b), 1)
        for g in range(0, f, fg):
            k = min(fg, f - g)
            oh = jnp.concatenate(
                [(binsT[g + j, :].astype(jnp.int32)[:, None] == iota_b
                  ).astype(jnp.bfloat16) for j in range(k)], axis=1)
            acc = jax.lax.dot_general(
                oh, rhs, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            out_ref[g * b:(g + k) * b, :] += acc[:, :_PAD] + acc[:, _PAD:]

    @functools.partial(jax.jit, static_argnames=("num_bins",))
    def call(binsT, rhs, *, num_bins):
        f, n = binsT.shape
        nblk = n // blk
        return pl.pallas_call(
            functools.partial(kernel, f=f, b=num_bins, c=blk),
            grid=(nblk,),
            in_specs=[
                pl.BlockSpec((f, blk), lambda i: (0, i)),
                pl.BlockSpec((blk, 2 * _PAD), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((f * num_bins, _PAD), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((f * num_bins, _PAD), jnp.float32),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)),
        )(binsT, rhs)

    return call


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=2_000_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--bins", type=int, default=255)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--variants", type=str, default="2x2048,4x2048,4x1024,7x1024")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    n, f, b = args.rows, args.features, args.bins
    rng = np.random.RandomState(0)
    binsT = jnp.asarray(rng.randint(0, b, size=(f, n)).astype(np.uint8))
    rhs = jnp.asarray(rng.normal(size=(n, 2 * _PAD)).astype(np.float32)
                      ).astype(jnp.bfloat16)

    for spec in args.variants.split(","):
        fg, blk = (int(x) for x in spec.split("x"))
        npad = -n % blk
        binsT_p = jnp.pad(binsT, ((0, 0), (0, npad))) if npad else binsT
        rhs_p = jnp.pad(rhs, ((0, npad), (0, 0))) if npad else rhs
        try:
            call = make_variant(fg, blk)
            fn = lambda: call(binsT_p, rhs_p, num_bins=b)
            fn()
            _ = float(np.asarray(fn()).ravel()[0])
            t0 = time.time()
            for _ in range(args.reps):
                out = fn()
            _ = float(np.asarray(out).ravel()[0])
            dt = (time.time() - t0) / args.reps
            print(f"fg={fg} blk={blk}: {dt*1e3:9.1f} ms/pass")
        except Exception as e:
            print(f"fg={fg} blk={blk}: FAILED {type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
