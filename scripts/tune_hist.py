"""Sweep tile_leaves x hist_block on the real chip (the analog of the
reference's col-vs-row auto benchmark, dataset.cpp:591-689
TestMultiThreadingMethod, run offline instead of at startup).

Usage: python scripts/tune_hist.py [--rows 2000000] [--iters 5]
Prints sec/iter per (tile_leaves, hist_block, method) combo; feed the winner
back via params {"tile_leaves": ..., "hist_block": ...} or update the
defaults in models/grower.py / ops/pallas_hist.py.
"""

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=2_000_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--num-leaves", type=int, default=255)
    ap.add_argument("--max-bin", type=int, default=255)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--methods", type=str, default="pallas_hilo,onehot")
    ap.add_argument("--tiles", type=str, default="21,42")
    ap.add_argument("--blocks", type=str, default="1024,2048,4096")
    args = ap.parse_args()

    import jax
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    n, f = args.rows, args.features
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + rng.logistic(size=n) > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y, params={"max_bin": args.max_bin,
                                         "verbosity": -1})
    ds.construct()
    print(f"# device={jax.devices()[0]} rows={n}")

    results = {}
    for method in args.methods.split(","):
        for tile in (int(t) for t in args.tiles.split(",")):
            for block in (int(b) for b in args.blocks.split(",")):
                booster = lgb.Booster(params={
                    "objective": "binary", "num_leaves": args.num_leaves,
                    "max_bin": args.max_bin, "histogram_method": method,
                    "tile_leaves": tile, "hist_block": block,
                    "min_data_in_leaf": 100, "verbosity": -1,
                }, train_set=ds)
                try:
                    booster.update()          # compile
                    booster.update()
                    _ = float(booster._boosting.train_score[0])
                    t0 = time.time()
                    for _ in range(args.iters):
                        booster.update()
                    _ = float(booster._boosting.train_score[0])
                    dt = (time.time() - t0) / args.iters
                    results[(method, tile, block)] = dt
                    print(f"{method:12s} tile={tile:3d} block={block:5d}: "
                          f"{dt:8.3f} s/iter")
                except Exception as e:
                    print(f"{method:12s} tile={tile:3d} block={block:5d}: "
                          f"FAILED {type(e).__name__}: {str(e)[:120]}")

    if results:
        best = min(results, key=results.get)
        print(f"# best: {best} ({results[best]:.3f} s/iter)")


if __name__ == "__main__":
    main()
