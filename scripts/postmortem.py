#!/usr/bin/env python
"""Gang post-mortem CLI: point it at the breadcrumb directory of a dead
run (the supervisor diag dir, a telemetry_dir, or a checkpoint dir) and
get a classified verdict.

    python scripts/postmortem.py CKPT_OR_DIAG_DIR [MORE_DIRS...]
        [--checkpoint-dir D] [--json OUT.json] [--expect VERDICT]

Merges per-rank flight-recorder JSONLs (``flight_rank*.jsonl``,
incarnation suffixes included), watchdog/divergence diagnosis JSONs and
checkpoint-manifest health sections into one timeline, classifies the
failure (kill / hang / divergence / nan / oom), and names the
first-bad rank. Prints the human report to stdout; ``--json`` also
writes the machine document (the same file
``supervisor.run_supervised`` writes automatically on gang failure).

Exit codes: 0 = report produced; 1 = ``--expect`` mismatch (smoke
gates use it); 2 = no artifacts found under the given directories.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from lightgbm_tpu import postmortem  # noqa: E402  (no jax at import)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="classify a dead gang's breadcrumbs into a verdict")
    ap.add_argument("dirs", nargs="+",
                    help="directories holding flight_rank*.jsonl / "
                         "watchdog_rank*.json / divergence_rank*.json "
                         "(a checkpoint dir works: its supervisor_diag "
                         "and telemetry subdirs are scanned too)")
    ap.add_argument("--checkpoint-dir", default=None, dest="checkpoint_dir",
                    help="checkpoint directory whose manifests anchor "
                         "the 'last known good' marks (default: the "
                         "first positional dir when it contains ckpt_*)")
    ap.add_argument("--json", default=None, dest="json_out",
                    help="also write the machine JSON report here")
    ap.add_argument("--expect", default=None,
                    choices=postmortem.VERDICTS,
                    help="fail (exit 1) unless the verdict matches — "
                         "for smoke gates")
    ap.add_argument("--timeline", type=int, default=40,
                    help="max timeline events rendered (default 40)")
    args = ap.parse_args(argv)

    ck = args.checkpoint_dir
    if ck is None:
        import glob as _glob
        for d in args.dirs:
            if _glob.glob(os.path.join(d, "ckpt_*")):
                ck = d
                break
    pm = postmortem.analyze(args.dirs, checkpoint_dir=ck)
    if not pm.sources["flights"] and not pm.sources["diags"] \
            and not pm.sources["manifests"]:
        print(f"no post-mortem artifacts found under {args.dirs} "
              f"(looked for flight_rank*.jsonl, watchdog_rank*.json, "
              f"divergence_rank*.json, ckpt_*/MANIFEST.json)",
              file=sys.stderr)
        return 2
    sys.stdout.write(pm.render(max_timeline=args.timeline))
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(pm.to_json(), fh, indent=1, sort_keys=True,
                      default=str)
            fh.write("\n")
        print(f"# machine report: {args.json_out}")
    if args.expect and pm.verdict != args.expect:
        print(f"EXPECT FAILED: verdict {pm.verdict!r} != "
              f"{args.expect!r}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
