"""Generate docs/Parameters.md from the Config dataclass + alias table.

The analog of the reference's helpers/parameter_generator.py, which
code-generates config_auto.cpp AND docs/Parameters.rst from config.h's
structured comments (reference: SURVEY §2.1; helpers/parameter_generator.py).
Here the dataclass IS the single source of truth: this script introspects
fields, defaults and the alias table, and groups rows under the section
comments in config.py. CI-style check: tests assert the committed file is
current (python scripts/gen_params_doc.py --check).
"""

import argparse
import dataclasses
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from lightgbm_tpu.config import Config, PARAM_ALIASES  # noqa: E402

OUT = os.path.join(os.path.dirname(__file__), "..", "docs", "Parameters.md")


def _sections():
    """Field name -> section title, from the '# Section' comments that
    precede field groups in the dataclass body. A comment counts as a
    section title only when a BLANK line precedes it (section comments are
    blank-line-separated groups); continuation lines of multi-line field
    comments must not be promoted to headings."""
    import inspect
    src = inspect.getsource(Config)
    section = "Core"
    out = {}
    prev_blank = False
    for line in src.splitlines():
        stripped = line.strip()
        m = re.match(r"#\s+(.*)", stripped)
        if m and ":" not in stripped and prev_blank:
            section = m.group(1)
            prev_blank = False
            continue
        prev_blank = not stripped
        fm = re.match(r"(\w+)\s*:\s*\S", stripped)
        if fm and not stripped.startswith(("def ", "class ")):
            out[fm.group(1)] = section
    return out


def _fmt_default(v):
    if isinstance(v, str):
        return f'"{v}"' if v else '""'
    if isinstance(v, list):
        return "[]" if not v else repr(v)
    return repr(v)


def generate() -> str:
    aliases = {}
    for alias, canonical in PARAM_ALIASES.items():
        aliases.setdefault(canonical, []).append(alias)
    sections = _sections()
    rows_by_section = {}
    for f in dataclasses.fields(Config):
        default = (f.default if f.default is not dataclasses.MISSING
                   else f.default_factory())
        typ = getattr(f.type, "__name__", None) or str(f.type)
        row = (f.name, str(typ).replace("typing.", ""),
               _fmt_default(default),
               ", ".join(sorted(aliases.get(f.name, []))) or "—")
        rows_by_section.setdefault(sections.get(f.name, "Other"),
                                   []).append(row)

    lines = [
        "# Parameters",
        "",
        "Generated from `lightgbm_tpu/config.py` by "
        "`scripts/gen_params_doc.py` — do not edit by hand "
        "(the analog of the reference's `helpers/parameter_generator.py` "
        "-> `docs/Parameters.rst` pipeline). Defaults match the "
        "reference's `config.h`. Aliases resolve through `PARAM_ALIASES` "
        "exactly like the reference's `ParameterAlias` / "
        "`_ConfigAliases` tables.",
        "",
    ]
    for section, rows in rows_by_section.items():
        lines += [f"## {section}", "",
                  "| parameter | type | default | aliases |",
                  "|---|---|---|---|"]
        for name, typ, default, al in rows:
            lines.append(f"| `{name}` | {typ} | `{default}` | {al} |")
        lines.append("")
    n = sum(len(r) for r in rows_by_section.values())
    lines.append(f"*{n} parameters, "
                 f"{len(PARAM_ALIASES)} aliases.*")
    lines.append("")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if docs/Parameters.md is stale")
    args = ap.parse_args()
    text = generate()
    if args.check:
        with open(OUT) as fh:
            if fh.read() != text:
                print("docs/Parameters.md is stale; re-run "
                      "scripts/gen_params_doc.py", file=sys.stderr)
                sys.exit(1)
        print("docs/Parameters.md is current")
        return
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as fh:
        fh.write(text)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
