#!/usr/bin/env python
"""Serving-layer end-to-end smoke: fast knobs, ~30 s on CPU.

Drives the whole resilient-serving story through one frontend process:

  1. mixed load — concurrent small/large requests through the
     micro-batcher; every response must be BIT-IDENTICAL to the direct
     single-request ``booster.predict`` (padding never leaks across
     coalesced requests) and the flush must actually coalesce
     (#batches < #requests).
  2. slow dispatch — ``LGBM_TPU_FAULT_SLOW_PREDICT_MS`` armed: a request
     with a deadline must die in a diagnosable ServeTimeoutError naming
     its phase, and a burst that would overrun ``serve_max_queue_rows``
     must be SHED with a retriable ServeOverloadError; both must land in
     the health gauges and the degradation log.
  3. hot swap — a corrupt candidate file is REJECTED (old model keeps
     serving bit-identically); a valid candidate (round-tripped through
     a model file, like a real reload) swaps in atomically and post-swap
     serving is bit-identical to a cold-loaded engine of the new model.

Usage:  JAX_PLATFORMS=cpu python scripts/serve_smoke.py
Exits 0 on success, 1 with a diagnosis otherwise. The same paths run in
tier-1 as tests/test_serving.py (deadline/shed/swap/parity tests).
"""
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLOW_ENV = "LGBM_TPU_FAULT_SLOW_PREDICT_MS"
PARAMS = {"objective": "binary", "num_leaves": 8, "min_data_in_leaf": 10,
          "verbosity": -1, "seed": 5}
ROUNDS = 6


def main() -> int:
    import lightgbm_tpu as lgb
    from lightgbm_tpu import distributed
    from lightgbm_tpu.serving import (ServeFrontend, ServeOverloadError,
                                      ServeTimeoutError)
    from lightgbm_tpu.utils import profiling

    t0 = time.time()
    rng = np.random.RandomState(9)
    X = rng.normal(size=(400, 6))
    y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
    model = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y,
                                                params=dict(PARAMS)),
                      ROUNDS)
    new = lgb.train(dict(PARAMS, learning_rate=0.2),
                    lgb.Dataset(X, label=y, params=dict(PARAMS)), ROUNDS)

    fe = ServeFrontend(model, flush_ms=5.0, max_queue_rows=60)
    try:
        # ---- stanza 1: concurrent mixed load, bit-identical, coalesced
        fe.predict(X[:1])                      # warm (compile up front)
        fe.predict(X[:55])                     # biggest admissible bucket
        before_batches = fe.stats()["batches"]
        sizes = [1, 5, 13, 2, 20, 8]       # sums under the 60-row cap
        offs = np.cumsum([0] + sizes)
        res, errs = {}, {}

        def go(i):
            try:
                res[i] = fe.predict(X[offs[i]:offs[i + 1]])
            except BaseException as e:         # noqa: BLE001 — reported
                errs[i] = e
        ts = [threading.Thread(target=go, args=(i,))
              for i in range(len(sizes))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errs:
            print(f"FAIL: mixed load errored: {errs}")
            return 1
        for i in range(len(sizes)):
            if not np.array_equal(res[i],
                                  model.predict(X[offs[i]:offs[i + 1]])):
                print(f"FAIL: coalesced response {i} is not bit-identical "
                      f"to the direct predict")
                return 1
        n_batches = fe.stats()["batches"] - before_batches
        if n_batches >= len(sizes):
            print(f"FAIL: {len(sizes)} concurrent requests took "
                  f"{n_batches} dispatches — the batcher never coalesced")
            return 1

        # ---- stanza 2: slow dispatch -> deadline timeout + queue shed
        os.environ[SLOW_ENV] = "400"
        try:
            t_bg = threading.Thread(target=lambda: fe.predict(X[:30]))
            t_bg.start()                       # occupies the dispatcher
            time.sleep(0.15)
            try:
                fe.predict(X[:10], deadline_ms=80.0)
                print("FAIL: deadline request returned under a 400 ms "
                      "slow-predict fault")
                return 1
            except ServeTimeoutError as e:
                if e.phase not in ("queue-wait", "dispatch") \
                        or e.phase not in str(e):
                    print(f"FAIL: timeout names no phase: {e}")
                    return 1
            try:
                fe.predict(X[:55])             # 30 in flight + 55 > 60
                print("FAIL: overload request admitted past "
                      "serve_max_queue_rows")
                return 1
            except ServeOverloadError as e:
                if not e.retriable:
                    print("FAIL: shed error is not marked retriable")
                    return 1
            t_bg.join()
        finally:
            os.environ.pop(SLOW_ENV, None)
        st = fe.stats()
        if st["timeouts"] < 1 or st["shed"] < 1:
            print(f"FAIL: stats missed the injected faults: {st}")
            return 1
        serve = distributed.health_snapshot().get("serve", {})
        if serve.get("serve_shed_count", 0) < 1 \
                or serve.get("serve_timeout_count", 0) < 1:
            print(f"FAIL: health_snapshot() serve gauges missed the "
                  f"faults: {serve}")
            return 1
        if not any(d["kind"] == "serve_shed"
                   for d in distributed.degradations()):
            print("FAIL: shed episode never reached the degradation log")
            return 1

        # ---- stanza 3: rejected candidate, then a validated hot swap
        baseline = fe.predict(X[:40])
        with tempfile.TemporaryDirectory() as td:
            bad = os.path.join(td, "corrupt.txt")
            with open(bad, "w") as f:
                f.write("tree\nversion=v3\nTree=0\ngarbage\n")
            try:
                fe.swap("default", bad)
                print("FAIL: corrupt candidate was accepted")
                return 1
            except Exception:
                pass
            if fe.version() != 1 or not np.array_equal(
                    fe.predict(X[:40]), baseline):
                print("FAIL: rejected swap disturbed the serving model")
                return 1
            good = os.path.join(td, "new.txt")
            new.save_model(good)
            v = fe.swap("default", good)
            cold = lgb.Booster(model_file=good)
            if v != 2 or not np.array_equal(fe.predict(X[:40]),
                                            cold.predict(X[:40])):
                print("FAIL: post-swap serving is not bit-identical to a "
                      "cold-loaded engine of the new model")
                return 1
            if np.array_equal(fe.predict(X[:40]), baseline):
                print("FAIL: swap returned v2 but v1 bits still serve")
                return 1
    finally:
        fe.close()
    g = profiling.gauges()
    print(f"OK: {sum(sizes)} rows over {len(sizes)} concurrent requests "
          f"coalesced into {n_batches} dispatch(es) bit-identically; "
          f"slow-predict fault produced a phase-named timeout + a "
          f"retriable shed (gauges: shed "
          f"{g.get('serve_shed_count', 0):.0f}, timeout "
          f"{g.get('serve_timeout_count', 0):.0f}); corrupt hot-swap "
          f"candidate rejected with v1 serving, valid candidate swapped "
          f"to v2 bit-identical to a cold load ({time.time() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
