#!/usr/bin/env python
"""BENCH JSON regression gate: compare a candidate round against a
blessed baseline, with per-metric thresholds and backend sanity.

    python scripts/bench_compare.py BASELINE.json CANDIDATE.json [MORE...]
        [--pct 10] [--threshold metric=value] [--ignore-rows]

Every file may be a raw ``bench.py`` result line, a JSONL stream (the
LAST parseable line wins — bench.py prints enriched lines as probes
land), or a driver wrapper document holding the stream under ``tail`` /
the first line under ``parsed`` (the ``BENCH_rNN.json`` shape). Each
candidate (2nd file onward) is compared against the FIRST file.

Sanity gates (exit 2 — the comparison itself is invalid):
  - a CPU round can NEVER be judged against a TPU baseline: BENCH_r04
    and r05 silently fell back to CPU and published numbers under a
    TPU-looking filename; this gate makes that a hard failure, in both
    directions (backend mismatch either way is incomparable);
  - a round with ``tpu_required`` set but a non-TPU backend (bench.py
    exits 2 before writing such a round, but a hand-edited or truncated
    file must not pass);
  - a null headline ``value``, or a row-count mismatch (``--ignore-rows``
    downgrades the row check to a warning for cross-scale eyeballing).

Metric gates (exit 1 — a real regression): every metric present in BOTH
documents and listed in the direction tables is compared; lower-better
metrics fail when the candidate is more than the threshold above the
baseline, higher-better when more than the threshold below. Thresholds
are percent by default (``--pct``, default 10); AUC-family metrics use
ABSOLUTE tolerances (default 0.003) — percent noise on a 0.94 AUC would
hide a real quality loss. ``--threshold metric=value`` overrides one
metric (absolute for the AUC family, percent otherwise).

Exit codes: 0 = no regression; 1 = regression(s); 2 = sanity failure.
``--self-check`` runs the built-in synthetic scenarios (wired into
tests/run_suite.sh) and exits 0 only when every scenario gates
correctly.
"""

import argparse
import json
import os
import sys

# lower-is-better metrics (seconds, bytes, dispatch counts)
LOWER_BETTER = {
    "value", "sec_per_iter", "compact_sec_per_iter",
    "nocompact_sec_per_iter", "q8_sec_per_iter", "bin63_sec_per_iter",
    "bin63_q8_sec_per_iter", "first_iter_compile_s", "warm_start_s",
    "construct_sec", "dispatches_per_iter", "host_bytes_per_iter",
    "predict_host_bytes", "rows_streamed_per_tree",
    "hbm_peak_bytes", "host_rss_peak_bytes", "construct_peak_host_bytes",
    "sentinel_overhead_pct", "recorder_overhead_pct",
}
# higher-is-better metrics (throughput, utilization, quality)
HIGHER_BETTER = {
    "vs_baseline", "mfu_est", "mfu_bf16_est", "mfu_mode_est",
    "predict_rows_per_sec", "construct_rows_per_sec",
    "auc", "q8_auc", "q8_f32_ref_auc", "bin63_auc", "bin63_q8_auc",
    "trees_per_dispatch",
}
# AUC-family metrics compare on ABSOLUTE deltas (percent flatters them)
ABS_TOLERANCE = {"auc": 0.003, "q8_auc": 0.005, "q8_f32_ref_auc": 0.005,
                 "bin63_auc": 0.005, "bin63_q8_auc": 0.005}
DEFAULT_PCT = 10.0


def _last_json_line(text):
    out = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and "metric" in doc:
            out = doc
    return out


def load_bench(path):
    """Load one BENCH document: a result dict, a JSONL stream (last
    enriched line wins), or the driver wrapper ({"tail": ...,
    "parsed": ...}). Raises SystemExit(2) when nothing parseable is
    found — an unreadable round must not silently pass the gate."""
    with open(path) as fh:
        text = fh.read()
    doc = None
    try:
        doc = json.loads(text)
    except ValueError:
        pass
    if isinstance(doc, dict):
        if "metric" in doc:
            return doc
        # driver wrapper: prefer the LAST enriched line in tail over the
        # first-line "parsed" snapshot
        tail = doc.get("tail") or ""
        last = _last_json_line(tail)
        if last is not None:
            return last
        if isinstance(doc.get("parsed"), dict) and "metric" in doc["parsed"]:
            return doc["parsed"]
    last = _last_json_line(text)
    if last is not None:
        return last
    print(f"bench_compare: {path} holds no parseable BENCH result "
          f"(no JSON line with a 'metric' field)", file=sys.stderr)
    raise SystemExit(2)


def sanity(baseline, candidate, base_name, cand_name, ignore_rows=False):
    """Comparison-validity gates; returns a list of fatal messages."""
    fatal = []
    b_back = baseline.get("backend")
    c_back = candidate.get("backend")
    if b_back and c_back and b_back != c_back:
        fatal.append(
            f"backend mismatch: baseline {base_name} ran on "
            f"{b_back!r}, candidate {cand_name} on {c_back!r} — a "
            f"CPU-fallback round can never be judged against a TPU "
            f"baseline (the BENCH_r04/r05 failure shape); rerun with "
            f"bench.py --require-tpu")
    # the same gates apply to BOTH sides: a null-headline error record
    # or a tpu_required round that ran on CPU must not be blessable as a
    # baseline either — compare() would silently skip the headline and
    # every candidate would pass ungated
    for doc, name, role in ((baseline, base_name, "baseline"),
                            (candidate, cand_name, "candidate")):
        back = doc.get("backend")
        if doc.get("tpu_required") and back != "tpu":
            fatal.append(
                f"{role} {name} demanded a TPU (tpu_required=true) "
                f"but ran on {back!r}")
        if doc.get("value") is None:
            fatal.append(f"{role} {name} has a null headline value"
                         + (f" (error: {doc.get('error')})"
                            if doc.get("error") else ""))
    b_rows, c_rows = baseline.get("rows"), candidate.get("rows")
    if b_rows and c_rows and b_rows != c_rows:
        msg = (f"row-count mismatch: baseline {b_rows} vs candidate "
               f"{c_rows} — per-iteration metrics scale with rows, the "
               f"comparison is apples-to-oranges")
        if ignore_rows:
            print(f"# WARNING (--ignore-rows): {msg}", file=sys.stderr)
        else:
            fatal.append(msg)
    return fatal


def _threshold_for(metric, pct, overrides):
    if metric in overrides:
        return overrides[metric], metric in ABS_TOLERANCE
    if metric in ABS_TOLERANCE:
        return ABS_TOLERANCE[metric], True
    return pct, False


def compare(baseline, candidate, pct=DEFAULT_PCT, overrides=None):
    """Per-metric comparison; returns (regressions, improvements, rows)
    where rows is the printable table and regressions the failing
    metric names."""
    overrides = overrides or {}
    regressions, improvements, rows = [], [], []
    for metric in sorted(LOWER_BETTER | HIGHER_BETTER):
        b, c = baseline.get(metric), candidate.get(metric)
        if not isinstance(b, (int, float)) or not isinstance(c, (int, float)) \
                or isinstance(b, bool) or isinstance(c, bool):
            continue
        thr, absolute = _threshold_for(metric, pct, overrides)
        lower = metric in LOWER_BETTER
        delta = c - b
        if absolute:
            worse = (delta > thr) if lower else (delta < -thr)
            better = (delta < -thr) if lower else (delta > thr)
            shown = f"{delta:+.6g} (tol {thr:g} abs)"
        else:
            rel = (delta / abs(b) * 100.0) if b else (0.0 if not c
                                                      else float("inf"))
            worse = (rel > thr) if lower else (rel < -thr)
            better = (rel < -thr) if lower else (rel > thr)
            shown = f"{rel:+.1f}% (tol {thr:g}%)"
        flag = "REGRESSION" if worse else ("improved" if better else "ok")
        rows.append((metric, b, c, shown, flag))
        if worse:
            regressions.append(metric)
        elif better:
            improvements.append(metric)
    return regressions, improvements, rows


def run(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare BENCH JSONs with per-metric thresholds")
    ap.add_argument("files", nargs="*",
                    help="BASELINE then one or more CANDIDATE files")
    ap.add_argument("--pct", type=float, default=DEFAULT_PCT,
                    help=f"default percent tolerance (default "
                         f"{DEFAULT_PCT}); AUC metrics use absolute "
                         f"tolerances instead")
    ap.add_argument("--threshold", action="append", default=[],
                    metavar="METRIC=VALUE",
                    help="per-metric override (absolute for the AUC "
                         "family, percent otherwise); repeatable")
    ap.add_argument("--ignore-rows", action="store_true",
                    help="downgrade the row-count sanity gate to a "
                         "warning (cross-scale eyeballing only)")
    ap.add_argument("--self-check", action="store_true",
                    help="run the built-in synthetic gate scenarios")
    args = ap.parse_args(argv)
    if args.self_check:
        return self_check()
    if len(args.files) < 2:
        ap.error("need a BASELINE and at least one CANDIDATE file")
    overrides = {}
    for spec in args.threshold:
        metric, _, val = spec.partition("=")
        try:
            overrides[metric.strip()] = float(val)
        except ValueError:
            ap.error(f"bad --threshold {spec!r} (want METRIC=NUMBER)")

    baseline = load_bench(args.files[0])
    exit_code = 0
    for cand_path in args.files[1:]:
        candidate = load_bench(cand_path)
        print(f"== {cand_path} vs baseline {args.files[0]} "
              f"(backend {candidate.get('backend')!r} vs "
              f"{baseline.get('backend')!r}, rows "
              f"{candidate.get('rows')} vs {baseline.get('rows')})")
        fatal = sanity(baseline, candidate, args.files[0], cand_path,
                       ignore_rows=args.ignore_rows)
        if fatal:
            for msg in fatal:
                print(f"SANITY FAILURE: {msg}")
            exit_code = max(exit_code, 2)
            continue
        regressions, improvements, rows = compare(
            baseline, candidate, pct=args.pct, overrides=overrides)
        width = max((len(r[0]) for r in rows), default=6)
        for metric, b, c, shown, flag in rows:
            print(f"  {metric.ljust(width)}  {b:>14.6g}  ->  "
                  f"{c:>14.6g}  {shown:>22}  {flag}")
        if regressions:
            print(f"RESULT: {len(regressions)} regression(s): "
                  f"{', '.join(regressions)}")
            exit_code = max(exit_code, 1)
        else:
            print(f"RESULT: ok ({len(improvements)} improved, "
                  f"{len(rows)} compared)")
    return exit_code


# ------------------------------------------------------------ self-check

def _write(tmp, name, doc):
    path = os.path.join(tmp, name)
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path


def self_check() -> int:
    """Synthetic gate scenarios (wired into tests/run_suite.sh): the
    gate must pass an identical round, fail a slowed/regressed round
    with exit 1, and refuse a CPU-fallback round against a TPU baseline
    with exit 2."""
    import tempfile
    base = {"metric": "higgs10.5M_sec_per_iter", "value": 1.0,
            "rows": 10_500_000, "backend": "tpu", "tpu_required": True,
            "auc": 0.94, "mfu_est": 0.05, "first_iter_compile_s": 30.0,
            "hbm_peak_bytes": 8_000_000_000,
            "host_rss_peak_bytes": 4_000_000_000}
    ok = True

    def expect(label, code, want):
        nonlocal ok
        good = code == want
        print(f"[self-check] {label}: exit {code} "
              f"({'ok' if good else f'WANT {want}'})")
        ok = ok and good

    with tempfile.TemporaryDirectory(prefix="bench_compare_") as tmp:
        b = _write(tmp, "base.json", base)
        same = _write(tmp, "same.json", dict(base, value=1.02))
        expect("identical round passes", run([b, same]), 0)
        slow = _write(tmp, "slow.json",
                      dict(base, value=1.5, auc=0.94))
        expect("25%-slower round fails", run([b, slow]), 1)
        worse_auc = _write(tmp, "auc.json", dict(base, auc=0.93))
        expect("AUC -0.01 fails (absolute tolerance)",
               run([b, worse_auc]), 1)
        cpu = _write(tmp, "cpu.json",
                     dict(base, backend="cpu", rows=500_000, value=4.8,
                          tpu_required=False))
        expect("CPU fallback vs TPU baseline refused",
               run([b, cpu]), 2)
        null = _write(tmp, "null.json",
                      dict(base, value=None,
                           error="all ladder scales failed"))
        expect("null headline refused", run([b, null]), 2)
        expect("null BASELINE refused too", run([null, b]), 2)
        cpu_req = _write(tmp, "cpu_req.json",
                         dict(base, backend="cpu"))
        expect("tpu_required baseline that ran on CPU refused",
               run([cpu_req, cpu_req]), 2)
        more_mem = _write(tmp, "mem.json",
                          dict(base, hbm_peak_bytes=10_000_000_000))
        expect("25% more HBM peak fails", run([b, more_mem]), 1)
        loose = _write(tmp, "loose.json",
                       dict(base, hbm_peak_bytes=10_000_000_000))
        expect("per-metric override loosens the gate",
               run([b, loose, "--threshold", "hbm_peak_bytes=30"]), 0)
        # the BENCH_rNN driver-wrapper shape parses (last tail line wins)
        wrapper = _write(tmp, "wrap.json", {
            "n": 3, "rc": 0,
            "tail": json.dumps(dict(base, value=1.01)) + "\n"
                    + json.dumps(dict(base, value=1.03)) + "\n",
            "parsed": dict(base, value=99.0)})
        expect("driver-wrapper shape parses (last line wins)",
               run([b, wrapper]), 0)
    print(f"[self-check] {'ALL SCENARIOS PASSED' if ok else 'FAILED'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(run())
