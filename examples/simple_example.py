"""Train / validate / early-stop / predict / save — the minimum loop."""
import _backend  # noqa: F401  (backend selection, see _backend.py)
import numpy as np
import lightgbm_tpu as lgb

rng = np.random.RandomState(7)
X = rng.normal(size=(2000, 10))
y = (X[:, 0] + 0.6 * X[:, 1] - 0.4 * X[:, 2] + rng.normal(scale=0.4, size=2000) > 0).astype(float)
Xtr, Xva, ytr, yva = X[:1600], X[1600:], y[:1600], y[1600:]

train = lgb.Dataset(Xtr, label=ytr)
valid = lgb.Dataset(Xva, label=yva, reference=train)

evals = {}
booster = lgb.train(
    {"objective": "binary", "metric": ["auc", "binary_logloss"],
     "num_leaves": 31, "learning_rate": 0.1, "verbosity": -1},
    train, num_boost_round=120,
    valid_sets=[valid], valid_names=["valid"],
    callbacks=[lgb.early_stopping(stopping_rounds=10),
               lgb.record_evaluation(evals)])

print(f"best iteration: {booster.best_iteration}")
print(f"valid AUC at best: {evals['valid']['auc'][booster.best_iteration - 1]:.4f}")

pred = booster.predict(Xva, num_iteration=booster.best_iteration)
print("accuracy:", float(np.mean((pred > 0.5) == (yva > 0.5))))

booster.save_model("/tmp/simple_model.txt")
reloaded = lgb.Booster(model_file="/tmp/simple_model.txt")
assert np.allclose(reloaded.predict(Xva[:10]), pred[:10], rtol=1e-6)
print("saved, reloaded, predictions match")
