"""Shared backend selection for the example scripts.

Honor JAX_PLATFORMS explicitly: some environments (e.g. a TPU-tunnel
sitecustomize) override jax's backend selection, and a dead tunnel then
stalls interpreter startup for minutes; this restores standard env-var
behavior. A no-op everywhere else.
"""
import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
