"""Distributed training over pre-partitioned parts (the dask-analog flow,
run locally: each worker process sees ONLY its own partition).

The __main__ guard is required: worker processes are spawned with
multiprocessing's spawn start method, which re-imports this module.
"""
import _backend  # noqa: F401  (backend selection, see _backend.py)
import numpy as np
import lightgbm_tpu as lgb


def main():
    rng = np.random.RandomState(11)
    n = 2000
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)

    parts = [{"data": X[: n // 2], "label": y[: n // 2]},
             {"data": X[n // 2:], "label": y[n // 2:]}]
    booster = lgb.distributed.train_distributed(
        {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbosity": -1},
        parts, num_boost_round=10,
        devices_per_proc=4)   # 4 virtual CPU devices per worker for the demo

    pred = booster.predict(X[:8])
    print("distributed model trained;", booster.num_trees(), "trees;",
          "sample predictions:", np.round(pred, 3))


if __name__ == "__main__":
    main()
