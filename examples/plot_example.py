"""Importance / metric / tree plotting saved to PNG."""
import _backend  # noqa: F401  (backend selection, see _backend.py)
import numpy as np
import lightgbm_tpu as lgb

try:
    import matplotlib
    matplotlib.use("Agg")
except ImportError:
    print("matplotlib not installed; skipping plot example")
    raise SystemExit(0)

rng = np.random.RandomState(5)
X = rng.normal(size=(1500, 6))
y = (X[:, 0] - X[:, 1] > 0).astype(float)
train = lgb.Dataset(X[:1200], label=y[:1200])
valid = lgb.Dataset(X[1200:], label=y[1200:], reference=train)
evals = {}
booster = lgb.train({"objective": "binary", "metric": "binary_logloss",
                     "num_leaves": 15, "verbosity": -1},
                    train, 30, valid_sets=[valid], valid_names=["valid"],
                    callbacks=[lgb.record_evaluation(evals)])

lgb.plot_importance(booster).figure.savefig("/tmp/lgb_importance.png")
lgb.plot_metric(evals, metric="binary_logloss").figure.savefig("/tmp/lgb_metric.png")
lgb.plot_tree(booster, tree_index=0).figure.savefig("/tmp/lgb_tree.png")
print("wrote /tmp/lgb_importance.png /tmp/lgb_metric.png /tmp/lgb_tree.png")
