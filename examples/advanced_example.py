"""Categorical features, callbacks, continued training, importance, SHAP."""
import _backend  # noqa: F401  (backend selection, see _backend.py)
import numpy as np
import lightgbm_tpu as lgb

rng = np.random.RandomState(3)
n = 1500
X = rng.normal(size=(n, 6))
cat = rng.randint(0, 5, size=n).astype(float)        # categorical column
X = np.column_stack([X, cat])
y = (X[:, 0] + (cat == 2) * 1.5 + rng.normal(scale=0.3, size=n) > 0.5).astype(float)

params = {"objective": "binary", "num_leaves": 31, "verbosity": -1}
train = lgb.Dataset(X, label=y, params=params, categorical_feature=[6])

evals = {}
booster = lgb.train(
    params, train, 25,
    valid_sets=[train], valid_names=["train"],
    callbacks=[lgb.record_evaluation(evals),
               lgb.reset_parameter(learning_rate=lambda i: 0.1 * 0.98 ** i)])

# continued training from the in-memory model (init_model)
booster2 = lgb.train(params, lgb.Dataset(X, label=y, params=params,
                                         categorical_feature=[6]),
                     10, init_model=booster)
print("total trees after continuation:", booster2.num_trees())

imp = booster.feature_importance("gain")
print("gain importance (categorical col is #6):",
      np.round(imp / imp.sum(), 3))

dump = booster.dump_model()   # already a dict (json.dumps to serialize)
print("JSON dump trees:", len(dump["tree_info"]))

contrib = booster.predict(X[:5], pred_contrib=True)
print("SHAP row sums match raw scores:",
      np.allclose(contrib.sum(axis=1), booster.predict(X[:5], raw_score=True),
                  rtol=1e-4))
