"""The sklearn-style estimator wrappers."""
import _backend  # noqa: F401  (backend selection, see _backend.py)
import numpy as np
from lightgbm_tpu import LGBMClassifier, LGBMRegressor

rng = np.random.RandomState(1)
X = rng.normal(size=(2000, 8))
y_reg = X[:, 0] * 2 + np.sin(X[:, 1]) + rng.normal(scale=0.1, size=2000)
y_clf = (y_reg > 0.3).astype(int)

reg = LGBMRegressor(n_estimators=50, num_leaves=31, learning_rate=0.1)
reg.fit(X[:1600], y_reg[:1600],
        eval_set=[(X[1600:], y_reg[1600:])],
        callbacks=[])
print("regressor R^2 on held-out:", round(reg.score(X[1600:], y_reg[1600:]), 4))

clf = LGBMClassifier(n_estimators=50, num_leaves=31)
clf.fit(X[:1600], y_clf[:1600])
proba = clf.predict_proba(X[1600:])
acc = float(np.mean(clf.predict(X[1600:]) == y_clf[1600:]))
print("classifier accuracy:", round(acc, 4), "| proba shape:", proba.shape)
assert acc > 0.85
