"""Memory-bounded growth for wide datasets (the analog of the reference's
capped HistogramPool, feature_histogram.hpp:1095-1290): when the resident
[L, F, B, 3] histogram state would exceed histogram_pool_size, the grower
switches to feature-blocked passes that keep only per-leaf SplitInfo."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import lightgbm_tpu as lgb


def _wide_problem(n=2500, f=96, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.7 * X[:, 3] - 0.5 * X[:, 10]
         + 0.1 * rng.normal(size=n))
    return X, y


def test_blocked_mode_matches_resident():
    """A tiny histogram_pool_size forces the blocked mode; the trained
    model must closely match the default resident-state model (the
    resident run keeps histogram subtraction, whose f32 rounding differs,
    so the assertion is allclose — exact grower-level parity with
    subtraction disabled is test_blocked_grower_bit_parity)."""
    X, y = _wide_problem()
    base = {"objective": "regression", "num_leaves": 31,
            "min_data_in_leaf": 20, "verbosity": -1,
            "histogram_method": "scatter"}
    b_res = lgb.train(base, lgb.Dataset(X, label=y), 5)
    b_blk = lgb.train({**base, "histogram_pool_size": 0.05},
                      lgb.Dataset(X, label=y), 5)
    # the blocked mode disables histogram subtraction, whose f32 rounding
    # the resident mode's larger-sibling derivation carries — predictions
    # agree tightly but not bitwise
    np.testing.assert_allclose(b_blk.predict(X), b_res.predict(X),
                               rtol=1e-4, atol=1e-5)
    r2 = 1 - np.mean((b_blk.predict(X) - y) ** 2) / np.var(y)
    assert r2 > 0.5, r2


def test_blocked_mode_engagement_decision():
    X, y = _wide_problem(n=500, f=32)

    def block_of(extra):
        b = lgb.train({"objective": "regression", "num_leaves": 31,
                       "verbosity": -1, **extra},
                      lgb.Dataset(X, label=y, params={"verbosity": -1}), 1)
        return b._boosting._feature_block("scatter")

    # default cap (2 GiB) leaves narrow data resident
    assert block_of({}) == 0
    # a tiny pool engages blocking with a bounded column width
    fb = block_of({"histogram_pool_size": 0.05})
    assert 0 < fb <= 32, fb


def test_blocked_mode_wide_smoke():
    """A genuinely wide dataset (512 used features, 255 leaves) trains
    through the blocked path: the resident state would be
    255*512*256*3*4 = 382 MB against a 16 MB pool."""
    X, y = _wide_problem(n=1500, f=512, seed=3)
    params = {"objective": "regression", "num_leaves": 255,
              "min_data_in_leaf": 5, "verbosity": -1,
              "histogram_pool_size": 16,
              "histogram_method": "scatter"}
    b = lgb.train(params, lgb.Dataset(X, label=y), 3)
    p = b.predict(X)
    r2 = 1 - np.mean((p - y) ** 2) / np.var(y)
    assert r2 > 0.4, r2   # 3 informative of 512 features, 3 rounds


def test_blocked_mode_with_bagging_and_monotone():
    """Mask bagging and basic monotone constraints ride the blocked path."""
    X, y = _wide_problem(n=2000, f=64, seed=5)
    mono = [0] * 64
    mono[0] = 1
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 20, "verbosity": -1,
              "histogram_pool_size": 0.05,
              "bagging_freq": 1, "bagging_fraction": 0.8,
              "monotone_constraints": mono,
              "histogram_method": "scatter"}
    b = lgb.train(params, lgb.Dataset(X, label=y), 8)
    # monotonicity on feature 0
    rng = np.random.RandomState(0)
    pts = rng.normal(size=(30, 64)).astype(np.float32)
    grid = np.linspace(-2, 2, 20)
    preds = []
    for g in grid:
        Xg = pts.copy()
        Xg[:, 0] = g
        preds.append(b.predict(Xg))
    assert (np.diff(np.asarray(preds), axis=0) >= -1e-10).all()


def test_blocked_mode_unsupported_combo_falls_back():
    """CEGB forces the resident state (with a warning), not a crash."""
    X, y = _wide_problem(n=400, f=32)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 20, "verbosity": -1,
              "histogram_pool_size": 0.01,
              "cegb_tradeoff": 1.0, "cegb_penalty_split": 0.1,
              "histogram_method": "scatter"}
    b = lgb.train(params, lgb.Dataset(X, label=y), 2)
    assert b._boosting._feature_block("scatter") == 0


def test_blocked_grower_bit_parity():
    """grow_tree with feature_block set produces the IDENTICAL tree to the
    resident grower with subtraction disabled, at several block widths
    (including one block covering all features and a non-divisor width)."""
    import jax.numpy as jnp
    from lightgbm_tpu.models.grower import grow_tree
    from lightgbm_tpu.ops.split import FeatureMeta, SplitParams

    rng = np.random.RandomState(7)
    n, f, b = 2000, 50, 32
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    grad = rng.normal(size=n).astype(np.float32)
    hess = np.ones(n, np.float32)
    meta = FeatureMeta(
        num_bins=jnp.full((f,), b, jnp.int32),
        missing_type=jnp.zeros((f,), jnp.int32),
        default_bin=jnp.zeros((f,), jnp.int32),
        is_categorical=jnp.zeros((f,), bool),
        monotone=jnp.zeros((f,), jnp.int8),
        penalty=jnp.ones((f,), jnp.float32))
    params = SplitParams.from_config(
        lgb.Config.from_params({"min_data_in_leaf": 5}))
    common = dict(max_leaves=31, num_bins=b, hist_method="scatter")
    mask = np.ones((n,), np.float32)
    fmask = np.ones((f,), np.float32)
    mb = np.full((f,), -1, np.int32)
    t_res, _, _ = grow_tree(bins, grad, hess, mask, meta, params, fmask, mb,
                            hist_subtraction=False, **common)
    for fb in (16, 23, 64):
        t_blk, _, _ = grow_tree(bins, grad, hess, mask, meta, params, fmask,
                                mb, feature_block=fb, **common)
        assert int(t_blk.num_leaves) == int(t_res.num_leaves)
        np.testing.assert_array_equal(np.asarray(t_blk.node_feature),
                                      np.asarray(t_res.node_feature))
        np.testing.assert_array_equal(
            np.asarray(t_blk.node_threshold_bin),
            np.asarray(t_res.node_threshold_bin))
        np.testing.assert_allclose(np.asarray(t_blk.leaf_value),
                                   np.asarray(t_res.leaf_value), rtol=1e-6)
