"""BinMapper semantics tests (reference behaviors from src/io/bin.cpp)."""

import numpy as np

from lightgbm_tpu import binning


def test_distinct_values_get_own_bins():
    vals = np.array([1.0, 2.0, 3.0, 1.0, 2.0, 3.0, 1.0] * 10)
    m = binning.BinMapper()
    m.find_bin(vals, total_sample_cnt=len(vals), max_bin=255, min_data_in_bin=3)
    assert m.missing_type == binning.MISSING_NONE
    b = m.values_to_bins(np.array([1.0, 2.0, 3.0]))
    assert len(set(b.tolist())) == 3
    # ordering preserved
    assert b[0] < b[1] < b[2]


def test_bin_boundaries_monotone_and_count_balanced():
    rng = np.random.RandomState(0)
    vals = rng.normal(size=10000)
    m = binning.BinMapper()
    m.find_bin(vals, total_sample_cnt=len(vals), max_bin=64, min_data_in_bin=3)
    assert m.num_bin <= 64
    bounds = m.bin_upper_bound
    finite = bounds[np.isfinite(bounds)]
    assert np.all(np.diff(finite) > 0)
    bins = m.values_to_bins(vals)
    counts = np.bincount(bins, minlength=m.num_bin)
    # equal-count greedy: occupied bins roughly balanced (the dedicated zero
    # bin may be empty for continuous data, bin.cpp:256-314)
    occupied = counts[counts > 0]
    assert len(occupied) >= m.num_bin - 2
    assert occupied.max() < 10 * occupied.mean()


def test_zero_bin_dedicated():
    # sparse feature: zeros dominate, dedicated zero bin straddling +-1e-35
    vals = np.concatenate([np.zeros(900), np.linspace(1, 10, 100)])
    m = binning.BinMapper()
    m.find_bin(vals, total_sample_cnt=len(vals), max_bin=255, min_data_in_bin=3)
    zb = m.value_to_bin(0.0)
    assert m.default_bin == zb
    assert m.value_to_bin(1e-40) == zb
    assert m.value_to_bin(1.0) != zb
    assert m.sparse_rate >= 0.9


def test_nan_goes_to_last_bin():
    vals = np.concatenate([np.linspace(-5, 5, 900), np.full(100, np.nan)])
    m = binning.BinMapper()
    m.find_bin(vals, total_sample_cnt=len(vals), max_bin=255, min_data_in_bin=3,
               use_missing=True, zero_as_missing=False)
    assert m.missing_type == binning.MISSING_NAN
    assert m.value_to_bin(np.nan) == m.num_bin - 1
    assert m.value_to_bin(0.0) < m.num_bin - 1


def test_use_missing_false():
    vals = np.concatenate([np.linspace(-5, 5, 900), np.full(100, np.nan)])
    m = binning.BinMapper()
    m.find_bin(vals, total_sample_cnt=len(vals), max_bin=255, min_data_in_bin=3,
               use_missing=False)
    assert m.missing_type == binning.MISSING_NONE


def test_zero_as_missing():
    vals = np.concatenate([np.zeros(500), np.linspace(1, 10, 500)])
    m = binning.BinMapper()
    m.find_bin(vals, total_sample_cnt=len(vals), max_bin=255, min_data_in_bin=3,
               zero_as_missing=True)
    assert m.missing_type == binning.MISSING_ZERO
    # NaN maps to the zero/default bin in Zero mode (bin.h:479-481)
    assert m.value_to_bin(np.nan) == m.default_bin


def test_max_bin_respected():
    rng = np.random.RandomState(1)
    vals = rng.uniform(size=100000)
    for mb in (16, 63, 255):
        m = binning.BinMapper()
        m.find_bin(vals, total_sample_cnt=len(vals), max_bin=mb, min_data_in_bin=3)
        assert 2 <= m.num_bin <= mb


def test_trivial_feature():
    # constant feature: filtered out by pre-filter (bin.cpp:54-76 NeedFilter +
    # bin.cpp:500-503), since no threshold puts min_split_data on both sides
    vals = np.full(100, 7.0)
    m = binning.BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=255, min_data_in_bin=3,
               min_split_data=20, pre_filter=True)
    assert m.is_trivial


def test_categorical_by_count():
    # categories 0..4 with decreasing counts
    vals = np.concatenate([np.full(c, i) for i, c in enumerate([500, 300, 100, 50, 10])])
    m = binning.BinMapper()
    m.find_bin(vals, total_sample_cnt=len(vals), max_bin=255, min_data_in_bin=3,
               bin_type=binning.BIN_TYPE_CATEGORICAL)
    assert m.bin_type == binning.BIN_TYPE_CATEGORICAL
    # bin 0 reserved for NaN/other; most frequent category gets bin 1
    assert m.value_to_bin(0.0) == 1
    assert m.value_to_bin(1.0) == 2
    # unseen category maps to bin 0
    assert m.value_to_bin(99.0) == 0


def test_values_to_bins_roundtrip_boundaries():
    rng = np.random.RandomState(3)
    vals = rng.normal(size=5000)
    m = binning.BinMapper()
    m.find_bin(vals, total_sample_cnt=len(vals), max_bin=32, min_data_in_bin=3)
    bins = m.values_to_bins(vals)
    # every value's bin upper bound must be >= value, and previous bound < value
    ub = m.bin_upper_bound
    assert np.all(vals <= ub[bins])
    has_prev = bins > 0
    assert np.all(vals[has_prev] > ub[bins[has_prev] - 1])


def test_serialization_roundtrip():
    rng = np.random.RandomState(4)
    vals = np.concatenate([rng.normal(size=900), np.full(100, np.nan)])
    m = binning.BinMapper()
    m.find_bin(vals, total_sample_cnt=len(vals), max_bin=64, min_data_in_bin=3)
    m2 = binning.BinMapper.from_dict(m.to_dict())
    test_vals = np.array([-1.0, 0.0, 1.5, np.nan])
    np.testing.assert_array_equal(m.values_to_bins(test_vals),
                                  m2.values_to_bins(test_vals))


def test_bin_data_device_matches_host():
    """Device quantization (binning.bin_data_device) is bit-exact vs the
    host searchsorted path for float32 input across missing modes."""
    import jax
    from lightgbm_tpu import binning
    from lightgbm_tpu.config import Config
    rng = np.random.RandomState(11)
    for zam in (False, True):
        X = rng.normal(size=(4000, 7)).astype(np.float32)
        X[rng.uniform(size=X.shape) < 0.05] = np.nan
        X[rng.uniform(size=X.shape) < 0.25] = 0.0
        cfg = Config.from_params({"max_bin": 63, "zero_as_missing": zam})
        mappers = binning.find_bin_mappers(X.astype(np.float64), cfg, [])
        used_idx = [j for j, m in enumerate(mappers) if not m.is_trivial]
        used = [mappers[j] for j in used_idx]
        host = binning.bin_data(X[:, used_idx], used)
        dev = np.asarray(binning.bin_data_device(
            np.ascontiguousarray(X[:, used_idx]), used))
        np.testing.assert_array_equal(host, dev)
