"""Randomized-config robustness sweep: train -> save -> reload -> predict
parity over sampled parameter combinations (the interaction-coverage
complement to the per-feature matrix tests; seeds fixed, so failures
reproduce)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow


def _sample_params(rng):
    p = {"objective": "binary", "verbosity": -1,
         "num_leaves": int(rng.choice([4, 15, 31])),
         "min_data_in_leaf": int(rng.choice([1, 5, 40])),
         "learning_rate": float(rng.choice([0.05, 0.3])),
         "max_depth": int(rng.choice([-1, 3, 6])),
         "feature_fraction": float(rng.choice([1.0, 0.7])),
         "max_bin": int(rng.choice([15, 63, 255]))}
    if rng.rand() < 0.5:
        p.update(bagging_fraction=float(rng.choice([0.4, 0.8])),
                 bagging_freq=1)
    if rng.rand() < 0.3:
        p["extra_trees"] = True
    if rng.rand() < 0.3:
        p["min_gain_to_split"] = 0.1
    if rng.rand() < 0.3:
        p["lambda_l1"] = 0.5
    if rng.rand() < 0.3:
        p["lambda_l2"] = 5.0
    if rng.rand() < 0.25:
        p["monotone_constraints"] = [1, -1] + [0] * 6
    return p


@pytest.mark.parametrize("seed", range(8))
def test_random_config_roundtrip(seed):
    rng = np.random.RandomState(1000 + seed)
    n = 800
    X = rng.normal(size=(n, 8))
    if rng.rand() < 0.4:    # concentrated column (sparse-storage path)
        X[:, 5] = np.where(rng.uniform(size=n) < 0.93, 0.0,
                           rng.normal(size=n))
    if rng.rand() < 0.4:    # missing values
        X[rng.uniform(size=X.shape) < 0.05] = np.nan
    y = ((np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 1])) > 0
         ).astype(np.float64)
    params = _sample_params(rng)
    cats = [7] if rng.rand() < 0.4 else "auto"
    if cats != "auto":
        X[:, 7] = rng.randint(0, 5, size=n)
    ds = lgb.Dataset(X, label=y, params=params, categorical_feature=cats)
    booster = lgb.train(params, ds, 6)
    pred = booster.predict(X[:200])
    assert np.isfinite(pred).all(), params
    # text round trip preserves predictions
    clone = lgb.Booster(model_str=booster.model_to_string())
    np.testing.assert_allclose(clone.predict(X[:200]), pred, rtol=1e-6,
                               err_msg=str(params))
    # and the model is at least directionally learning when it can split
    first = booster.dump_model()["tree_info"][0]["num_leaves"] \
        if booster.num_trees() else 0
    if first > 1:
        acc = np.mean((booster.predict(X) > 0.5) == (y > 0.5))
        assert acc > 0.55, (acc, params)
