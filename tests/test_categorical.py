"""Categorical feature splits (one-hot and sorted many-vs-many modes).

Mirrors the reference's categorical coverage
(reference: tests/python_package_test/test_engine.py categorical tests;
semantics from src/treelearner/feature_histogram.hpp:277-515)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _cat_problem(n=2000, levels=10, seed=0):
    rng = np.random.RandomState(seed)
    cat = rng.randint(0, levels, size=n)
    num = rng.normal(size=n)
    y = (np.isin(cat, [1, 3, 7]).astype(float) * 2.0 - 1.0
         + 0.3 * rng.normal(size=n) > 0).astype(float)
    X = np.stack([cat.astype(float), num], axis=1)
    return X, y


BASE = {"objective": "binary", "num_leaves": 8, "min_data_in_leaf": 5,
        "verbosity": -1, "min_data_per_group": 1, "cat_smooth": 1.0}


@pytest.mark.parametrize("onehot", [4, 64])
def test_categorical_signal_recovery(onehot):
    """Both cat modes must find the {1,3,7}-vs-rest structure."""
    from sklearn.metrics import roc_auc_score
    X, y = _cat_problem()
    params = dict(BASE, max_cat_to_onehot=onehot)
    ds = lgb.Dataset(X, label=y, params=params, categorical_feature=[0],
                     free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=20)
    assert roc_auc_score(y, booster.predict(X)) > 0.99
    # the categorical feature must actually be used
    assert booster.feature_importance()[0] > 0


@pytest.mark.slow
def test_categorical_beats_numerical_treatment():
    """Scattered category ids {1,3,7} cannot be separated by one numeric
    threshold; categorical handling must win. (Slow tier: a quality
    claim — categorical split MECHANICS stay tier-1 via the other tests
    in this file.)"""
    from sklearn.metrics import roc_auc_score
    X, y = _cat_problem()
    params = dict(BASE, num_leaves=4)
    ds_cat = lgb.Dataset(X, label=y, params=params, categorical_feature=[0],
                         free_raw_data=False)
    cat_auc = roc_auc_score(y, lgb.train(params, ds_cat,
                                         num_boost_round=3).predict(X))
    ds_num = lgb.Dataset(X, label=y, params=params, categorical_feature=[],
                         free_raw_data=False)
    num_auc = roc_auc_score(y, lgb.train(params, ds_num,
                                         num_boost_round=3).predict(X))
    assert cat_auc > num_auc


def test_categorical_model_round_trip():
    X, y = _cat_problem()
    ds = lgb.Dataset(X, label=y, params=BASE, categorical_feature=[0],
                     free_raw_data=False)
    booster = lgb.train(BASE, ds, num_boost_round=10)
    s = booster.model_to_string()
    assert "cat_boundaries=" in s or "num_cat=1" in s
    loaded = lgb.Booster(model_str=s)
    np.testing.assert_allclose(booster.predict(X, raw_score=True),
                               loaded.predict(X, raw_score=True))


def test_unseen_and_nan_categories_route_right():
    """Unseen category values and NaN go to the non-membership side
    (reference: CategoricalDecision, tree.h:349-360)."""
    X, y = _cat_problem()
    ds = lgb.Dataset(X, label=y, params=BASE, categorical_feature=[0],
                     free_raw_data=False)
    booster = lgb.train(BASE, ds, num_boost_round=5)
    X_new = np.array([[99.0, 0.0], [np.nan, 0.0], [-5.0, 0.0]])
    p_new = booster.predict(X_new, raw_score=True)
    # all three must route identically (none is a member of any split set)
    assert p_new[0] == p_new[1] == p_new[2]
    loaded = lgb.Booster(model_str=booster.model_to_string())
    np.testing.assert_allclose(p_new, loaded.predict(X_new, raw_score=True))


def test_pandas_categorical_auto_detect():
    pd = pytest.importorskip("pandas")
    X, y = _cat_problem(n=800)
    df = pd.DataFrame({"c": pd.Categorical(X[:, 0].astype(int)),
                       "x": X[:, 1]})
    ds = lgb.Dataset(df, label=y, params=BASE, free_raw_data=False)
    booster = lgb.train(BASE, ds, num_boost_round=5)
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, booster.predict(df)) > 0.9


def test_categorical_contrib_sums():
    X, y = _cat_problem()
    ds = lgb.Dataset(X, label=y, params=BASE, categorical_feature=[0],
                     free_raw_data=False)
    booster = lgb.train(BASE, ds, num_boost_round=5)
    contrib = booster.predict(X[:30], pred_contrib=True)
    raw = booster.predict(X[:30], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-6, atol=1e-6)


def test_max_cat_threshold_limits_set_size():
    X, y = _cat_problem(levels=30)
    params = dict(BASE, max_cat_threshold=2, max_cat_to_onehot=1)
    ds = lgb.Dataset(X, label=y, params=params, categorical_feature=[0],
                     free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=3)
    model = booster.dump_model()

    def walk(node, sets):
        if "split_feature" in node:
            if node.get("decision_type") == "==":
                sets.append(node["threshold"])
            walk(node["left_child"], sets)
            walk(node["right_child"], sets)
        return sets

    for ti in model["tree_info"]:
        for thr in walk(ti["tree_structure"], []):
            assert len(thr.split("||")) <= 2
