"""Device-resident inference engine (models/predict_engine.py).

Coverage for the one-dispatch ensemble predict:

- bit-parity of the on-device f64 accumulation against the legacy
  host-f64 per-tree loop across gbdt / dart / multiclass / OVA, and at
  shape-bucket edge batch sizes (1, bucket-1, bucket, bucket+1);
- dispatch-count + device->host byte regression via the PR 3 telemetry
  hook (full-ensemble predict <= 3 dispatches, d2h <= N*K*8 + constant);
- shape-bucket compile cache (two batches in one bucket -> no new
  program), chunked streaming and sharded predict parity;
- CPU perf-smoke: depth-bounded fori_loop traversal produces IDENTICAL
  leaf indices to the while_loop path on a random deep tree, and
  eval-on-valid during training routes through the engine's one-dispatch
  valid-score program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.models.tree import (predict_leaf_bins,
                                      predict_leaf_bins_depth,
                                      predict_values_stacked)
from lightgbm_tpu.utils import profiling


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(7)
    X = rng.normal(size=(600, 8)).astype(np.float64)
    X[rng.uniform(size=X.shape) < 0.05] = np.nan      # missing routing
    y = ((np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 1])) > 0) \
        .astype(np.float64)
    y3 = np.digitize(np.nan_to_num(X[:, 0]) + 0.3 * np.nan_to_num(X[:, 2]),
                     [-0.5, 0.5]).astype(np.float64)
    return X, y, y3


def _train(X, y, extra, nround=6):
    p = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 10,
         "verbosity": -1}
    p.update(extra)
    return lgb.train(p, lgb.Dataset(X, label=y, params=p), nround)


def _legacy_raw(booster, X):
    """The pre-engine reference path: stacked per-tree f32 values fetched
    to the host, accumulated there in float64 IN TREE ORDER."""
    g = booster._boosting
    st = g._stacked()
    bins = jnp.asarray(g.train_set.bin_new_data(X))
    vals = np.asarray(predict_values_stacked(
        st, bins, g.train_set.missing_bin), np.float64)      # [T, n]
    k = g.num_tree_per_iteration
    out = np.zeros((X.shape[0], k), np.float64)
    for t in range(vals.shape[0]):
        out[:, t % k] += vals[t]
    return out if k > 1 else out[:, 0]


# ----------------------------------------------------------- bit parity
@pytest.mark.parametrize("extra,label", [
    ({}, "y"),                                                   # gbdt
    # dart/OVA exercise the SAME engine machinery (stacked traversal +
    # f64 carry; dart's tree scaling and OVA's conversion live upstream
    # of the engine): tier-1 keeps the gbdt + multiclass pair, the other
    # two boosting/objective spellings ride the slow tier (PR 5 budget
    # taming; their unique surfaces stay covered by test_boosting_modes
    # and test_objective_matrix)
    pytest.param({"boosting": "dart", "drop_rate": 0.5}, "y",
                 marks=pytest.mark.slow),                        # dart
    ({"objective": "multiclass", "num_class": 3}, "y3"),         # softmax
    pytest.param({"objective": "multiclassova", "num_class": 3}, "y3",
                 marks=pytest.mark.slow),                        # OVA
])
def test_engine_bit_parity(data, extra, label):
    X, y, y3 = data
    b = _train(X, y3 if label == "y3" else y, extra)
    got = b.predict(X[:257], raw_score=True)
    ref = _legacy_raw(b, X[:257])
    np.testing.assert_array_equal(got, ref)


def test_engine_bit_parity_bucket_edges(data):
    """Batch sizes at the shape-bucket edges (1, bucket-1, bucket,
    bucket+1) — row padding must never leak into results."""
    X, y, _ = data
    b = _train(X, y, {"predict_bucket_min_rows": 64})
    for n in (1, 63, 64, 65):
        got = b.predict(X[:n], raw_score=True)
        np.testing.assert_array_equal(got, _legacy_raw(b, X[:n]),
                                      err_msg=f"batch={n}")


def test_engine_score_dataset_parity(data):
    """Booster.eval routes score_dataset through the engine: on-device
    bias subtraction + f64 accumulation over the valid set's binned
    matrix must equal the legacy host loop bit for bit."""
    X, y, _ = data
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "metric": "binary_logloss"}
    dtr = lgb.Dataset(X[:500], label=y[:500], params=p)
    b = lgb.train(p, dtr, 5)
    dva = lgb.Dataset(X[500:], label=y[500:], reference=dtr)
    g = b._boosting
    score = np.asarray(g.score_dataset(dva), np.float64)
    # legacy: per-tree host accumulation with bias subtraction
    dva.construct()
    vals = np.asarray(predict_values_stacked(
        g._stacked(), g._traversal_bins(dva), dva.missing_bin), np.float64)
    biases = np.asarray(g.tree_bias, np.float64)
    ref = np.full(dva.num_data, g.init_scores[0], np.float64)
    for t in range(vals.shape[0]):
        ref += vals[t] - biases[t]
    np.testing.assert_array_equal(score, ref)
    # and the public eval surface still works on it
    res = b.eval(dva, "extra")
    assert res and np.isfinite(res[0][2])


@pytest.mark.slow
def test_engine_num_iteration_window(data):
    """num_iteration / start_iteration tree windows through the engine."""
    X, y, _ = data
    b = _train(X, y, {}, nround=8)
    full = b.predict(X[:100], raw_score=True)
    first3 = b.predict(X[:100], raw_score=True, num_iteration=3)
    g = b._boosting
    last5 = g.predict_raw(X[:100], start_iteration=3)
    np.testing.assert_allclose(first3 + last5, full, rtol=1e-12)
    assert not np.array_equal(first3, full)


def test_engine_chunked_streaming_parity(data):
    """predict_chunk_rows streams row chunks; results are bit-identical
    to the unchunked pass (rows are independent)."""
    X, y, _ = data
    b = _train(X, y, {"predict_chunk_rows": 77,
                      "predict_bucket_min_rows": 64})
    got = b.predict(X[:400], raw_score=True)
    g = b._boosting
    g.config.predict_chunk_rows = 0
    g._engine_cache.clear()
    np.testing.assert_array_equal(got, b.predict(X[:400], raw_score=True))


def test_engine_sharded_parity(data):
    """predict_sharded row-shards the scan over the 8-virtual-device mesh
    — bit-identical (per-row accumulation order unchanged)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs > 1 device")
    X, y, _ = data
    b = _train(X, y, {"predict_bucket_min_rows": 64})
    ref = b.predict(X[:301], raw_score=True)
    g = b._boosting
    g.config.predict_sharded = True
    g._engine_cache.clear()
    got = b.predict(X[:301], raw_score=True)
    np.testing.assert_array_equal(got, ref)


@pytest.mark.slow
def test_engine_accum_modes(data):
    """compensated (two-float f32) tracks the f64 reference far tighter
    than plain f32; both modes run end to end."""
    X, y, _ = data
    b = _train(X, y, {}, nround=20)
    ref = b.predict(X[:200], raw_score=True)            # float64 engine
    g = b._boosting

    def with_mode(mode):
        g.config.predict_accum = mode
        g._engine_cache.clear()
        return b.predict(X[:200], raw_score=True)

    comp = with_mode("compensated")
    f32 = with_mode("float32")
    g.config.predict_accum = "auto"
    g._engine_cache.clear()
    err_comp = np.max(np.abs(comp - ref))
    err_f32 = np.max(np.abs(f32 - ref))
    assert err_comp <= err_f32
    assert err_comp < 1e-5
    np.testing.assert_allclose(comp, ref, atol=1e-5)


def test_engine_early_stop_parity(data):
    """pred_early_stop on the engine: a never-triggering margin is
    bit-identical to the plain predict; margin 0 stops every row at the
    first check (== first-freq-iterations predict)."""
    X, y, _ = data
    b = _train(X, y, {}, nround=20)
    full = b.predict(X[:128], raw_score=True)
    same = b.predict(X[:128], raw_score=True, pred_early_stop=True,
                     pred_early_stop_freq=6, pred_early_stop_margin=1e30)
    np.testing.assert_array_equal(full, same)
    stopped = b.predict(X[:128], raw_score=True, pred_early_stop=True,
                        pred_early_stop_freq=6, pred_early_stop_margin=0.0)
    first6 = b.predict(X[:128], raw_score=True, num_iteration=6)
    np.testing.assert_allclose(stopped, first6, rtol=1e-12)


# ------------------------------------------------------ dispatch budget
@pytest.fixture
def dispatch_hook():
    if not profiling.install_dispatch_hook():
        pytest.skip("jax internals hook unavailable on this version")
    yield
    profiling.uninstall_dispatch_hook()


def test_predict_dispatch_and_host_bytes(data, dispatch_hook):
    """The acceptance numbers: a warm full-ensemble predict is <= 3
    compiled-program dispatches (ensemble scan [+ conversion] + row-pad
    slice) and its device->host traffic is the [N, K] result only
    (<= N*K*8 bytes + constant) — the [T, N] per-tree matrix never
    crosses."""
    X, y, _ = data
    b = _train(X, y, {"predict_bucket_min_rows": 256}, nround=10)
    n = 300
    for _ in range(2):                       # warm (compile)
        b.predict(X[:n], raw_score=True)
        b.predict(X[:n])
    with profiling.dispatch_scope() as d_raw:
        b.predict(X[:n], raw_score=True)
    assert d_raw["dispatches"] <= 3, d_raw
    assert d_raw["d2h_bytes"] <= n * 8 + 4096, d_raw
    with profiling.dispatch_scope() as d_conv:
        b.predict(X[:n])
    assert d_conv["dispatches"] <= 3, d_conv
    assert d_conv["d2h_bytes"] <= n * 8 + 4096, d_conv


@pytest.mark.slow
def test_multiclass_predict_dispatch(data, dispatch_hook):
    X, _, y3 = data
    b = _train(X, y3, {"objective": "multiclass", "num_class": 3,
                       "predict_bucket_min_rows": 256}, nround=4)
    n, k = 300, 3
    for _ in range(2):
        b.predict(X[:n], raw_score=True)
    with profiling.dispatch_scope() as d:
        b.predict(X[:n], raw_score=True)
    assert d["dispatches"] <= 3, d
    assert d["d2h_bytes"] <= n * k * 8 + 4096, d


def test_bucket_cache_no_recompile(data, dispatch_hook):
    """Two batch sizes inside one shape bucket reuse the SAME compiled
    program: the engine's program-key cache must not grow, and the
    second batch must not re-enter the jit compile path."""
    X, y, _ = data
    b = _train(X, y, {"predict_bucket_min_rows": 256}, nround=5)
    b.predict(X[:200], raw_score=True)                   # bucket 256
    eng = b._boosting._predict_engine()
    n_programs = len(eng._programs)
    with profiling.dispatch_scope() as d:
        b.predict(X[:230], raw_score=True)               # same bucket
    assert len(eng._programs) == n_programs
    assert d["dispatches"] <= 3, d                       # no compile chain
    b.predict(X[:257], raw_score=True)                   # next bucket: 512
    assert len(eng._programs) == n_programs + 1


# ------------------------------------------------- CPU perf-smoke (CI)
def _random_deep_tree(rng, n_leaves, n_feats, n_bins):
    """A random, deliberately UNBALANCED tree in TreeArrays encoding."""
    from lightgbm_tpu.models.tree import empty_tree
    t = jax.device_get(empty_tree(n_leaves))
    # grow by always splitting a random existing leaf (chain-heavy)
    leaves = [(~0, 0)]                                   # (encoded, depth)
    t = t._replace(num_leaves=np.int32(n_leaves))
    feat = np.zeros(n_leaves - 1, np.int32)
    thr = np.zeros(n_leaves - 1, np.int32)
    left = np.full(n_leaves - 1, -1, np.int32)
    right = np.full(n_leaves - 1, -1, np.int32)
    parent_link = {}                                     # leaf idx -> setter
    for node in range(n_leaves - 1):
        li = rng.randint(len(leaves))
        enc, depth = leaves.pop(li)
        leaf_idx = ~enc
        if leaf_idx in parent_link:
            arr, pos = parent_link.pop(leaf_idx)
            arr[pos] = node
        feat[node] = rng.randint(n_feats)
        thr[node] = rng.randint(n_bins - 1)
        new_leaf = node + 1                              # fresh leaf id
        left[node] = ~leaf_idx
        right[node] = ~new_leaf
        parent_link[leaf_idx] = (left, node)
        parent_link[new_leaf] = (right, node)
        leaves.append((~leaf_idx, depth + 1))
        leaves.append((~new_leaf, depth + 1))
    t = t._replace(node_feature=feat, node_threshold_bin=thr,
                   node_left=left, node_right=right,
                   num_leaves=np.int32(n_leaves))
    return jax.tree.map(jnp.asarray, t)


def test_depth_bounded_traversal_matches_while_loop():
    """Perf-smoke correctness anchor: the fori_loop depth-bounded
    traversal yields IDENTICAL leaf indices to the while_loop on a
    random deep (unbalanced) tree, at the exact depth bound and above."""
    rng = np.random.RandomState(3)
    n_leaves, n_feats, n_bins = 31, 6, 16
    tree = _random_deep_tree(rng, n_leaves, n_feats, n_bins)
    bins = jnp.asarray(rng.randint(0, n_bins, size=(512, n_feats))
                       .astype(np.uint8))
    mb = jnp.full((n_feats,), -1, jnp.int32)
    ref = np.asarray(predict_leaf_bins(tree, bins, mb))
    from lightgbm_tpu.models.predict_engine import host_tree_depth
    t = jax.device_get(tree)
    depth = host_tree_depth(t.node_left, t.node_right, int(t.num_leaves))
    assert depth > 3                                     # actually deep
    for d in (depth, depth + 1, n_leaves - 1):
        got = np.asarray(predict_leaf_bins_depth(tree, bins, mb, d))
        np.testing.assert_array_equal(got, ref, err_msg=f"depth={d}")


@pytest.mark.slow
def test_trained_ensemble_depth_bound_exact(data):
    """The engine's measured ensemble depth reproduces the while_loop
    leaves on every trained tree (leaf-level check of the trip count)."""
    X, y, _ = data
    b = _train(X, y, {"num_leaves": 15, "min_data_in_leaf": 2}, nround=4)
    g = b._boosting
    eng = g._predict_engine()
    bins = jnp.asarray(g.train_set.bin_new_data(X[:200]))
    mb = g.train_set.missing_bin
    for i, tree in enumerate(g.trees):
        ref = np.asarray(predict_leaf_bins(tree, bins, mb))
        got = np.asarray(predict_leaf_bins_depth(tree, bins, mb, eng.depth))
        np.testing.assert_array_equal(got, ref, err_msg=f"tree={i}")


def test_pred_leaf_routes_through_engine(data):
    """predict_leaf equals the per-tree while_loop traversal."""
    X, y, _ = data
    b = _train(X, y, {}, nround=4)
    g = b._boosting
    leaves = b.predict(X[:100], pred_leaf=True)
    bins = jnp.asarray(g.train_set.bin_new_data(X[:100]))
    ref = np.stack([np.asarray(predict_leaf_bins(
        t, bins, g.train_set.missing_bin)) for t in g.trees], axis=1)
    np.testing.assert_array_equal(leaves, ref)


def test_eval_on_valid_routes_through_engine(data, dispatch_hook):
    """Training-time eval rides the engine: one update() with a valid set
    attached costs <= 3 dispatches (fused grow + donated score add + ONE
    valid-score program) — the eager per-op traversal chain is gone."""
    X, y, _ = data
    p = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 10,
         "verbosity": -1}
    dtr = lgb.Dataset(X[:500], label=y[:500], params=p)
    dva = lgb.Dataset(X[500:], label=y[500:], reference=dtr)
    b = lgb.Booster(params=p, train_set=dtr)
    b.add_valid(dva, "v")
    for _ in range(2):                                   # warmup/compile
        b.update()
    _ = float(np.asarray(b._boosting.train_score).ravel()[0])
    with profiling.dispatch_scope() as d:
        b.update()
    assert d["dispatches"] <= 3, d
    # and the scores it maintains match a from-scratch engine rescore
    g = b._boosting
    cached = np.asarray(g._valid_scores[0], np.float64)
    rescored = np.asarray(g.score_dataset(dva), np.float64)
    np.testing.assert_allclose(cached, rescored, rtol=1e-5, atol=1e-6)


# ==================================================== input hardening
class TestPredictInputHardening:
    """predict on malformed raw features fails LOUDLY, naming the
    offending column/row, instead of silently binning garbage. NaN stays
    valid wherever the trained mappers can route it (missing bins,
    categorical other-bin); predict_disable_shape_check opts out."""

    @pytest.fixture(scope="class")
    def booster_nomissing(self):
        """Model trained WITHOUT missing values: NaN at predict has no
        bin to route to."""
        rng = np.random.RandomState(3)
        X = rng.normal(size=(400, 5))
        y = (X[:, 0] > 0).astype(np.float64)
        return _train(X, y, {}, nround=3), X

    def test_wrong_feature_count(self, booster_nomissing):
        b, X = booster_nomissing
        with pytest.raises(ValueError, match=r"4 feature columns.*5"):
            b.predict(X[:, :4])
        with pytest.raises(ValueError, match=r"7 feature columns.*5"):
            b.predict(np.hstack([X, X[:, :2]]))

    def test_wrong_dtype_names_column(self, booster_nomissing):
        b, X = booster_nomissing
        bad = X[:3].astype(object)
        bad[1, 2] = "not-a-number"
        with pytest.raises(ValueError, match=r"non-numeric"):
            b.predict(bad)

    def test_nan_on_nomissing_model_names_row_and_column(
            self, booster_nomissing):
        b, X = booster_nomissing
        bad = X[:10].copy()
        bad[4, 2] = np.nan
        with pytest.raises(ValueError, match=r"NaN at row 4, feature "
                                             r"column 2"):
            b.predict(bad)

    def test_inf_names_row_and_column(self, booster_nomissing):
        b, X = booster_nomissing
        bad = X[:10].copy()
        bad[7, 1] = np.inf
        with pytest.raises(ValueError, match=r"\+inf at row 7, feature "
                                             r"column 1"):
            b.predict(bad)
        bad = X[:10].copy()
        bad[2, 3] = -np.inf
        with pytest.raises(ValueError, match=r"-inf at row 2, feature "
                                             r"column 3"):
            b.predict(bad)

    def test_inf_in_sparse_input(self):
        sp = pytest.importorskip("scipy.sparse")
        rng = np.random.RandomState(5)
        X = sp.random(500, 30, density=0.05, random_state=rng,
                      format="csr",
                      data_rvs=lambda k: rng.uniform(0.5, 2.0, k))
        y = (np.asarray(X.sum(axis=1)).ravel() > 0.2).astype(np.float64)
        b = _train(X, y, {}, nround=3)
        bad = X[:20].tolil()
        bad[3, 11] = np.inf
        with pytest.raises(ValueError, match=r"row 3, feature column 11"):
            b.predict(bad.tocsr())

    # NaN-with-missing-routing staying valid needs no dedicated test:
    # every parity test in this file predicts the module fixture's
    # NaN-laden X through the hardened entry point.

    def test_nan_valid_in_categorical_column(self):
        """NaN/unseen categoricals route to the other-bin by design."""
        rng = np.random.RandomState(9)
        X = rng.normal(size=(400, 3))
        X[:, 1] = rng.randint(0, 5, size=400)
        y = (X[:, 0] + (X[:, 1] == 2) > 0.5).astype(np.float64)
        p = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 10,
             "verbosity": -1}
        ds = lgb.Dataset(X, label=y, params=p, categorical_feature=[1])
        b = lgb.train(p, ds, 3)
        bad = X[:5].copy()
        bad[2, 1] = np.nan                      # categorical: allowed
        assert np.isfinite(b.predict(bad)).all()

    def test_disable_shape_check_opts_out(self):
        """predict_disable_shape_check=true restores the old bin-whatever
        behavior (the reference's escape hatch)."""
        rng = np.random.RandomState(3)
        Xf = rng.normal(size=(400, 5))
        yf = (Xf[:, 0] > 0).astype(np.float64)
        bf = _train(Xf, yf, {"predict_disable_shape_check": True}, nround=3)
        bad = Xf[:10].copy()
        bad[4, 2] = np.nan
        assert np.isfinite(bf.predict(bad)).all()   # no raise: binned as-is
