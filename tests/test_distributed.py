"""Distributed data-parallel tests on the virtual 8-device CPU mesh
(the analog of the reference testing multi-node with an in-process Dask
LocalCluster, test_dask.py — here: real shard_map + psum over 8 XLA host
devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from lightgbm_tpu.parallel.data_parallel import grow_tree_dp, make_mesh
from lightgbm_tpu.models.grower import grow_tree

from test_grower import _make_meta, _make_params, _partition_signature


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def _data(seed, n=512, f=4, b=16):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    grad = rng.normal(size=n).astype(np.float32)
    hess = np.ones(n, dtype=np.float32)
    return bins, grad, hess


@pytest.mark.parametrize("exact", [True, False])
def test_dp_matches_single_device(mesh8, exact):
    """Distributed growth must produce the same tree as single-device growth
    (the analog of test_dask.py's distributed ~= local assertions, but exact:
    psum of f32 partial histograms is deterministic)."""
    bins, grad, hess = _data(0)
    n, f = bins.shape
    meta, missing_bin = _make_meta([16] * f)
    params = _make_params(min_data=5)
    args = (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.ones((n,), jnp.float32), meta, params,
            jnp.ones((f,), jnp.float32), jnp.asarray(missing_bin))
    tree_s, leaf_s, _aux = grow_tree(*args, max_leaves=16, num_bins=16, exact=exact)
    tree_d, leaf_d = grow_tree_dp(mesh8, *args, max_leaves=16, num_bins=16,
                                  exact=exact)
    assert int(tree_s.num_leaves) == int(tree_d.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree_s.node_feature),
                                  np.asarray(tree_d.node_feature))
    np.testing.assert_array_equal(np.asarray(tree_s.node_threshold_bin),
                                  np.asarray(tree_d.node_threshold_bin))
    np.testing.assert_array_equal(np.asarray(leaf_s), np.asarray(leaf_d))
    np.testing.assert_allclose(np.asarray(tree_s.leaf_value),
                               np.asarray(tree_d.leaf_value), rtol=1e-5,
                               atol=1e-7)


def test_dp_rows_not_divisible(mesh8):
    """Row counts not divisible by the mesh size are padded with zero-mass
    rows and must not change the result."""
    bins, grad, hess = _data(1, n=509)
    n, f = bins.shape
    meta, missing_bin = _make_meta([16] * f)
    params = _make_params(min_data=5)
    args = (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.ones((n,), jnp.float32), meta, params,
            jnp.ones((f,), jnp.float32), jnp.asarray(missing_bin))
    tree_s, leaf_s, _aux = grow_tree(*args, max_leaves=8, num_bins=16)
    tree_d, leaf_d = grow_tree_dp(mesh8, *args, max_leaves=8, num_bins=16)
    assert leaf_d.shape[0] == n
    np.testing.assert_array_equal(np.asarray(tree_s.node_feature)[:int(tree_s.num_leaves) - 1],
                                  np.asarray(tree_d.node_feature)[:int(tree_d.num_leaves) - 1])
    np.testing.assert_array_equal(np.asarray(leaf_s), np.asarray(leaf_d))


def test_dp_bagging_mask(mesh8):
    bins, grad, hess = _data(2)
    n, f = bins.shape
    rng = np.random.RandomState(3)
    mask = (rng.uniform(size=n) < 0.7).astype(np.float32)
    meta, missing_bin = _make_meta([16] * f)
    params = _make_params(min_data=5)
    args = (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask), meta, params,
            jnp.ones((f,), jnp.float32), jnp.asarray(missing_bin))
    tree_s, leaf_s, _aux = grow_tree(*args, max_leaves=8, num_bins=16)
    tree_d, leaf_d = grow_tree_dp(mesh8, *args, max_leaves=8, num_bins=16)
    np.testing.assert_array_equal(np.asarray(leaf_s), np.asarray(leaf_d))


# ---------------------------------------------------------------- learners
@pytest.mark.parametrize("mode,kwargs", [
    ("data", {}),                       # psum_scatter + owner search + sync
    ("feature", {}),                    # feature slices + sync_best_splits
    ("voting", {"vote_top_k": 3}),      # 2*top_k == F: full electorate ==
                                        # serial exactly
])
def test_parallel_learner_kernels_match_serial(mesh8, mode, kwargs):
    """All three parallel learner modes reproduce the serial tree on the
    8-device mesh (reference analog: test_dask.py's distributed ~= local
    matrix over data/voting learners)."""
    from lightgbm_tpu.parallel.learners import ParallelGrower
    bins, grad, hess = _data(4, n=512, f=6)
    n, f = bins.shape
    meta, missing_bin = _make_meta([16] * f)
    params = _make_params(min_data=5)
    args = (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.ones((n,), jnp.float32), meta, params,
            jnp.ones((f,), jnp.float32), jnp.asarray(missing_bin))
    tree_s, leaf_s, _aux = grow_tree(*args, max_leaves=8, num_bins=16)
    pg = ParallelGrower(mode, mesh8, axis="data")
    tree_d, leaf_d, _aux2 = pg(*args, max_leaves=8, num_bins=16, **kwargs)
    assert int(tree_s.num_leaves) == int(tree_d.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree_s.node_feature),
                                  np.asarray(tree_d.node_feature))
    np.testing.assert_array_equal(np.asarray(tree_s.node_threshold_bin),
                                  np.asarray(tree_d.node_threshold_bin))
    np.testing.assert_array_equal(np.asarray(leaf_s), np.asarray(leaf_d))
    np.testing.assert_allclose(np.asarray(tree_s.leaf_value),
                               np.asarray(tree_d.leaf_value), rtol=1e-5,
                               atol=1e-7)


def test_voting_restricts_to_electorate(mesh8):
    """With a tiny electorate the voting learner must only split on elected
    features (PV-tree semantics) while still producing a usable tree."""
    from lightgbm_tpu.parallel.learners import ParallelGrower
    bins, grad, hess = _data(5, n=512, f=6)
    n, f = bins.shape
    meta, missing_bin = _make_meta([16] * f)
    params = _make_params(min_data=5)
    args = (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.ones((n,), jnp.float32), meta, params,
            jnp.ones((f,), jnp.float32), jnp.asarray(missing_bin))
    pg = ParallelGrower("voting", mesh8, axis="data")
    tree_v, leaf_v, _aux = pg(*args, max_leaves=8, num_bins=16, vote_top_k=1)
    assert int(tree_v.num_leaves) >= 2


@pytest.mark.parametrize("mode", ["data", "feature", "voting"])
def test_tree_learner_public_api_matches_serial(mode):
    """lgb.train({"tree_learner": ...}) routes through the parallel grower
    and matches serial training end-to-end (VERDICT r2 item 3: the config
    must not be silently ignored)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(7)
    n, f = 600, 8
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.normal(size=n) > 0).astype(
        np.float64)

    def fit(extra):
        ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5,
                                             "verbosity": -1})
        booster = lgb.train({"objective": "binary", "num_leaves": 8,
                             "min_data_in_leaf": 5, "verbosity": -1, **extra},
                            ds, num_boost_round=5)
        return booster.predict(X, raw_score=True)

    extra = {"tree_learner": mode}
    if mode == "voting":
        extra["top_k"] = 4   # 2*top_k == F: full electorate
    np.testing.assert_allclose(fit({}), fit(extra), rtol=1e-4, atol=1e-6)


def test_voting_election_confines_splits(mesh8):
    """Discriminative PV-tree election check (voting_parallel_tree_learner
    .cpp:151-182 GlobalVoting): a feature with the highest GLOBAL gain but
    support on only one shard (1 vote) must lose the election to features
    that win votes across shards — the root split must come from the
    elected set, while serial growth picks the unelected global-best."""
    from lightgbm_tpu.parallel.learners import ParallelGrower
    rng = np.random.RandomState(11)
    n, f, b = 512, 6, 16
    shard_rows = n // 8
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    grad = 0.05 * rng.normal(size=n).astype(np.float32)
    hess = np.ones(n, dtype=np.float32)
    # f0: moderate signal on EVERY shard (wins most shards' top-1 vote)
    grad += 0.5 * np.where(bins[:, 0] < b // 2, -1.0, 1.0).astype(np.float32)
    # f1: strong signal only on shard 0 (1 vote)
    s0 = slice(0, shard_rows)
    grad[s0] += 2.0 * np.where(bins[s0, 1] < b // 2, -1.0, 1.0)
    # f5: HUGE signal only on shard 1 -> highest global gain, but 1 vote and
    # the highest feature index (loses the tie-break to f1)
    s1 = slice(shard_rows, 2 * shard_rows)
    grad[s1] += 20.0 * np.where(bins[s1, 5] < b // 2, -1.0, 1.0)

    meta, missing_bin = _make_meta([b] * f)
    params = _make_params(min_data=5)
    args = (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.ones((n,), jnp.float32), meta, params,
            jnp.ones((f,), jnp.float32), jnp.asarray(missing_bin))
    tree_s, _, _aux = grow_tree(*args, max_leaves=2, num_bins=b)
    assert int(np.asarray(tree_s.node_feature)[0]) == 5  # serial: global best
    pg = ParallelGrower("voting", mesh8, axis="data")
    tree_v, _, _aux2 = pg(*args, max_leaves=2, num_bins=b, vote_top_k=1)
    root_feat = int(np.asarray(tree_v.node_feature)[0])
    # electorate = top-2 by votes: f0 (6 votes) + f1 (tie-break by index)
    assert root_feat in (0, 1), root_feat


def test_voting_quality_near_serial():
    """PV-tree quality claim (voting_parallel_tree_learner.cpp): a
    RESTRICTED electorate (2*top_k < F) still trains nearly as well as
    serial when the informative features win votes."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(13)
    n, f = 2000, 10
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.7 * X[:, 1] + 0.15 * rng.normal(size=n) > 0).astype(
        np.float64)

    def fit(extra):
        ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5,
                                             "verbosity": -1})
        booster = lgb.train({"objective": "binary", "num_leaves": 15,
                             "min_data_in_leaf": 5, "verbosity": -1, **extra},
                            ds, num_boost_round=10)
        p = booster.predict(X)
        return float(np.mean((p > 0.5) == (y > 0.5)))

    acc_serial = fit({})
    acc_voting = fit({"tree_learner": "voting", "top_k": 2})  # electorate 4 < 10
    assert acc_voting >= acc_serial - 0.02, (acc_serial, acc_voting)


@pytest.mark.parametrize("mode,params_extra,data_kind", [
    ("data", {}, "sparse_efb"),            # EFB bundles under data-parallel
    ("feature", {}, "sparse_efb"),         # ... and feature-parallel
    ("voting", {"top_k": 4}, "categorical"),  # categorical under voting
    ("data", {"extra_trees": True}, "dense"),
])
def test_lifted_learner_restrictions_match_serial(mode, params_extra,
                                                  data_kind):
    """Round-4 lifted combos: EFB-bundled datasets, categorical x voting,
    and extra_trees now run under the parallel learners and must match
    serial training (the reference's distributed learners have no such
    restrictions, data_parallel_tree_learner.cpp)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(17)
    n, f = 800, 8
    if data_kind == "sparse_efb":
        import scipy.sparse as sp
        X = rng.normal(size=(n, f)) * (rng.uniform(size=(n, f)) < 0.15)
        y = X[:, 0] - X[:, 3] + 0.05 * rng.normal(size=n)
        make_X = lambda: sp.csr_matrix(X)
        obj = {"objective": "regression"}
        cats = {}
    elif data_kind == "categorical":
        X = rng.normal(size=(n, f))
        X[:, 2] = rng.randint(0, 5, size=n)
        y = (X[:, 0] + (X[:, 2] == 3) > 0.5).astype(np.float64)
        make_X = lambda: X.copy()
        obj = {"objective": "binary"}
        cats = {"categorical_feature": [2]}
    else:
        X = rng.normal(size=(n, f))
        y = X[:, 0] + np.sin(X[:, 1])
        make_X = lambda: X.copy()
        obj = {"objective": "regression"}
        cats = {}

    def fit(extra):
        ds = lgb.Dataset(make_X(), label=y,
                         params={"min_data_in_leaf": 5, "verbosity": -1},
                         **cats)
        booster = lgb.train({**obj, "num_leaves": 8, "min_data_in_leaf": 5,
                             "verbosity": -1, **extra},
                            ds, num_boost_round=4)
        return booster.predict(make_X(), raw_score=True)

    extra = {"tree_learner": mode, **params_extra}
    base = {k: v for k, v in params_extra.items()}
    p_base, p_dist = fit(base), fit(extra)
    if data_kind == "categorical":
        # the categorical many-vs-many scan sorts bins by grad/hess ratio,
        # where f32 psum reduction-order differences can flip ties in later
        # trees — assert quality parity, the reference's own distributed
        # test contract (test_dask.py distributed ~= local)
        acc_b = np.mean((p_base > 0) == (y > 0.5))
        acc_d = np.mean((p_dist > 0) == (y > 0.5))
        assert abs(acc_b - acc_d) < 0.01, (acc_b, acc_d)
        assert np.mean(np.abs(p_base - p_dist) > 1e-3) < 0.15
    else:
        np.testing.assert_allclose(p_base, p_dist, rtol=1e-4, atol=1e-6)


def test_forced_splits_under_data_parallel(tmp_path):
    """Forced splits now run under the data-parallel learner and match
    serial (ff holds global feature indices; owner search + sync)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    import json
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(19)
    n, f = 800, 6
    X = rng.normal(size=(n, f))
    y = X[:, 0] + np.sin(2 * X[:, 4]) + 0.05 * rng.normal(size=n)
    forced = {"feature": 4, "threshold": 0.0,
              "left": {"feature": 2, "threshold": -0.5}}
    p = tmp_path / "forced.json"
    p.write_text(json.dumps(forced))

    def fit(extra):
        ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
        booster = lgb.train({"objective": "regression", "num_leaves": 8,
                             "forcedsplits_filename": str(p),
                             "verbosity": -1, **extra},
                            ds, num_boost_round=3)
        feats = {int(v) for ht in booster._boosting.host_trees
                 for v in np.asarray(ht.split_feature)}
        return booster.predict(X, raw_score=True), feats

    p_s, feats_s = fit({})
    p_d, feats_d = fit({"tree_learner": "data"})
    assert 4 in feats_d        # the forced root split happened
    np.testing.assert_allclose(p_d, p_s, rtol=1e-4, atol=1e-6)
