"""Every example script must run end to end (the reference keeps
examples/python-guide runnable the same way)."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")
SCRIPTS = sorted(f for f in os.listdir(EXAMPLES) if f.endswith(".py"))


@pytest.mark.parametrize("script", SCRIPTS)
def test_example_runs(script):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(EXAMPLES) + os.pathsep + \
        env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, os.path.join(EXAMPLES, script)],
                       env=env, capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, f"{script}:\n{r.stdout[-2000:]}\n{r.stderr[-2000:]}"
