"""Fault-tolerance suite: atomic writes, checkpoint/resume bit-parity,
corruption fallback, numerics guard rails and the fault-injection harness
(utils/faults.py). The headline assertions implement the acceptance bar:
kill at iteration k + resume == the uninterrupted run, byte for byte, for
gbdt/dart/goss with bagging; and a corrupted latest checkpoint falls back
to the previous valid one with a clear warning.

Everything here runs on synthetic data (no /root/reference dependency).
Fast knobs run in tier-1; the real kill/respawn subprocess case is
additionally marked slow."""

import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.checkpoint import (CheckpointManager, dataset_fingerprint,
                                     params_hash)
from lightgbm_tpu.io.model_text import load_model
from lightgbm_tpu.utils import faults
from lightgbm_tpu.utils.atomic_write import atomic_write_text
from lightgbm_tpu.utils.log import LightGBMError

pytestmark = pytest.mark.faults

N, F = 400, 10


def _data(seed=0, binary=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(N, F)
    if binary:
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    else:
        y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.randn(N)
    return X, y


MODE_PARAMS = {
    "gbdt": {"objective": "regression", "bagging_fraction": 0.6,
             "bagging_freq": 2, "feature_fraction": 0.8},
    # fraction <= 0.5 takes the bagging-subset copy path; the kill at an
    # odd iteration lands mid-bagging-period, so resume must re-derive
    # the persisted subset from the last refresh iteration's key
    "gbdt_subset": {"objective": "regression", "bagging_fraction": 0.4,
                    "bagging_freq": 2, "feature_fraction": 0.8},
    "dart": {"objective": "regression", "boosting": "dart",
             "drop_rate": 0.5, "skip_drop": 0.3, "bagging_fraction": 0.6,
             "bagging_freq": 2, "feature_fraction": 0.8},
    # GOSS rejects bagging by design (goss.hpp); its own sampling is the
    # stochastic state under test (learning_rate 0.5 ends the warm-up
    # window after 2 iterations so sampling is live across the resume)
    "goss": {"objective": "regression", "boosting": "goss",
             "top_rate": 0.3, "other_rate": 0.2, "learning_rate": 0.5,
             "feature_fraction": 0.8},
}
BASE = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}


def _train(params, X, y, rounds, **kw):
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    return lgb.train(dict(params), ds, num_boost_round=rounds, **kw)


# ===================================================== utils/atomic_write
def test_atomic_write_creates_and_replaces(tmp_path):
    p = tmp_path / "out.txt"
    atomic_write_text(str(p), "first")
    assert p.read_text() == "first"
    atomic_write_text(str(p), "second")
    assert p.read_text() == "second"
    # no tmp droppings left behind
    assert os.listdir(tmp_path) == ["out.txt"]


def test_save_model_is_atomic_and_loadable(tmp_path):
    X, y = _data()
    booster = _train({**BASE, "objective": "regression"}, X, y, 3)
    out = tmp_path / "model.txt"
    booster.save_model(str(out))
    assert os.listdir(tmp_path) == ["model.txt"]   # no tmp leftovers
    again = lgb.Booster(model_file=str(out))
    assert again.num_trees() == 3


# ==================================================== corrupt model files
def test_load_model_truncations_raise_clear_error(tmp_path):
    X, y = _data(binary=True)
    text = _train({**BASE, "objective": "binary"}, X, y, 4).model_to_string()
    cap = text.index("end of trees")
    for frac in (0.2, 0.4, 0.6, 0.8, 0.95):
        truncated = text[:int(cap * frac)]
        try:
            load_model(truncated)
        except LightGBMError as e:
            assert "corrupt or truncated model file" in str(e), str(e)
        else:
            pytest.fail(f"truncation at {frac:.0%} of the tree region "
                        f"parsed without error")


def test_load_model_garbage_and_bitflips(tmp_path):
    with pytest.raises(LightGBMError, match="corrupt or truncated"):
        load_model("hello world\nthis is not a model\n")
    X, y = _data()
    text = _train({**BASE, "objective": "regression"}, X, y, 3).model_to_string()
    # damage a tree block's num_leaves so section lengths disagree
    bad = text.replace("num_leaves=7", "num_leaves=5", 1)
    if bad != text:
        with pytest.raises(LightGBMError, match="corrupt or truncated"):
            load_model(bad)


# ============================================ checkpoint/resume bit-parity
@pytest.mark.parametrize("mode", [
    "gbdt",
    # the subset variant is the heaviest of the family (~20 s: subset
    # redraw + compaction-ladder recompiles); the resume mechanics it
    # shares with the others stay covered in tier-1, so it rides the
    # slow tier with the kill/respawn subprocess cases
    pytest.param("gbdt_subset", marks=pytest.mark.slow),
    "dart",
    # goss rides slow too: its kill-resume is the same resume-mechanics
    # spelling as gbdt's (GOSS keeps no extra trainer state beyond the
    # shared RNG the gbdt/dart cases already round-trip); the
    # GOSS-specific machinery stays tier-1 via
    # test_goss_amplifies_small_gradients /
    # test_goss_weights_exact_counts_under_ties (test_boosting_modes)
    # and the K-scan GOSS parity test_kscan_parity_goss
    # (test_compile_wall)
    pytest.param("goss", marks=pytest.mark.slow)])
def test_kill_resume_bit_identical(mode, tmp_path):
    """The acceptance bar: training interrupted at iteration k resumes to
    a final model text byte-identical to the uninterrupted run's. k=5 is
    deliberately MID bagging period (bagging_freq=2), so the resume must
    reconstruct the mask/subset drawn at iteration 4."""
    X, y = _data()
    params = {**BASE, **MODE_PARAMS[mode]}
    full = _train(params, X, y, 10).model_to_string()
    ckdir = str(tmp_path / "ck")
    # "kill" after iteration 5: run 5 rounds with per-iteration checkpoints
    _train(params, X, y, 5,
           callbacks=[lgb.checkpoint_callback(ckdir, period=1)])
    resumed = _train(params, X, y, 10, resume_from=ckdir,
                     callbacks=[lgb.checkpoint_callback(ckdir, period=1)])
    assert resumed.model_to_string() == full
    assert resumed.current_iteration() == 10


def test_corrupt_latest_falls_back_to_previous_valid(tmp_path, caplog):
    X, y = _data()
    params = {**BASE, "objective": "regression", "bagging_fraction": 0.6,
              "bagging_freq": 2}
    full = _train(params, X, y, 10).model_to_string()
    ckdir = str(tmp_path / "ck")
    _train(params, X, y, 6,
           callbacks=[lgb.checkpoint_callback(ckdir, period=3)])
    mgr = CheckpointManager(ckdir)
    assert [it for it, _ in mgr.checkpoints()] == [3, 6]
    faults.corrupt_file(os.path.join(ckdir, "ckpt_00000006", "model.txt"))
    import logging
    import lightgbm_tpu.utils.log as _log
    logger = logging.getLogger("lgbm_tpu_test_ckpt")
    lgb.register_logger(logger)
    _log.set_verbosity(0)   # the trainings above set the global level to -1
    try:
        with caplog.at_level(logging.WARNING, logger=logger.name):
            ck = mgr.load_latest_valid()
            assert ck.iteration == 3
        assert any("corrupt or truncated" in r.message for r in caplog.records)
    finally:
        _log._logger = None
    resumed = _train(params, X, y, 10, resume_from=ckdir,
                     callbacks=[lgb.checkpoint_callback(ckdir, period=3)])
    assert resumed.model_to_string() == full


@pytest.mark.slow
def test_truncated_state_and_manifest_fall_back(tmp_path):
    """Slow: tier-1 sibling test_corrupt_latest_falls_back_to_previous_valid
    exercises the same damaged-checkpoint -> fall-back-to-previous-valid
    path (plus resume parity); this spelling adds the truncated-sidecar
    and unparseable-manifest damage kinds and the nothing-valid ->
    train-from-scratch exit."""
    X, y = _data()
    params = {**BASE, "objective": "regression"}
    ckdir = str(tmp_path / "ck")
    _train(params, X, y, 6,
           callbacks=[lgb.checkpoint_callback(ckdir, period=3)])
    # truncated sidecar: length check catches it
    faults.corrupt_file(os.path.join(ckdir, "ckpt_00000006", "state.pkl"),
                        truncate=True)
    assert CheckpointManager(ckdir).load_latest_valid().iteration == 3
    # unparseable manifest on the remaining one: no valid checkpoint left
    faults.corrupt_file(os.path.join(ckdir, "ckpt_00000003",
                                     "MANIFEST.json"), truncate=True)
    assert CheckpointManager(ckdir).load_latest_valid() is None
    # resume_from with nothing valid trains from scratch (with a warning)
    full = _train(params, X, y, 4).model_to_string()
    scratch = _train(params, X, y, 4, resume_from=ckdir)
    assert scratch.model_to_string() == full


def test_resume_rejects_params_and_dataset_mismatch(tmp_path):
    X, y = _data()
    params = {**BASE, "objective": "regression"}
    ckdir = str(tmp_path / "ck")
    _train(params, X, y, 4,
           callbacks=[lgb.checkpoint_callback(ckdir, period=2)])
    with pytest.raises(LightGBMError, match="different training parameters"):
        _train({**params, "num_leaves": 15}, X, y, 8, resume_from=ckdir)
    X2, y2 = _data(seed=7)
    with pytest.raises(LightGBMError, match="different training dataset"):
        _train(params, X2, y2, 8, resume_from=ckdir)


def test_resume_restores_eval_history_and_early_stopping(tmp_path):
    X, y = _data(binary=True)
    Xv, yv = _data(seed=5, binary=True)
    params = {**BASE, "objective": "binary", "metric": "binary_logloss"}

    def run(rounds, resume_from=None, ckdir=None):
        ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
        vs = lgb.Dataset(Xv, label=yv, params=params, reference=ds,
                         free_raw_data=False)
        hist = {}
        cbs = [lgb.checkpoint_callback(ckdir, period=1)] if ckdir else []
        booster = lgb.train(dict(params), ds, num_boost_round=rounds,
                            valid_sets=[vs], valid_names=["v"],
                            early_stopping_rounds=50, evals_result=hist,
                            verbose_eval=False, callbacks=cbs,
                            resume_from=resume_from)
        return booster, hist

    full, full_hist = run(8)
    ckdir = str(tmp_path / "ck")
    run(5, ckdir=ckdir)
    resumed, resumed_hist = run(8, resume_from=ckdir, ckdir=ckdir)
    # eval history continues seamlessly across the resume, and the
    # early-stopping outcome (best iteration tracking) is unchanged
    assert resumed_hist == full_hist
    assert len(resumed_hist["v"]["binary_logloss"]) == 8
    assert resumed.best_iteration == full.best_iteration
    assert resumed.best_score == full.best_score


# ======================================================== numerics guards
def test_check_numerics_names_iteration_and_count():
    X, y = _data()
    params = {**BASE, "objective": "regression", "check_numerics": True,
              "fault_nan_grad_at_iter": 2}
    with pytest.raises(LightGBMError, match=r"iteration 2.*non-finite"):
        _train(params, X, y, 6)


def test_check_numerics_catches_custom_fobj_nans():
    X, y = _data()
    params = {**BASE, "objective": "regression", "check_numerics": True}

    def bad_fobj(preds, ds):
        g = preds - np.asarray(ds.get_label())
        g[:3] = np.nan
        return g, np.ones_like(g)

    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    with pytest.raises(LightGBMError, match="3 non-finite gradient"):
        lgb.train(dict(params), ds, num_boost_round=3, fobj=bad_fobj)


def test_check_numerics_clean_run_unaffected():
    X, y = _data()
    base = {**BASE, "objective": "regression"}
    plain = _train(base, X, y, 5).model_to_string()
    checked = _train({**base, "check_numerics": True}, X, y, 5).model_to_string()
    # the guard rail must not change the model: trees identical, only the
    # echoed parameters block records the flag
    assert plain.split("\nparameters:")[0] == checked.split("\nparameters:")[0]


def test_nan_injection_env_overrides(monkeypatch):
    X, y = _data()
    monkeypatch.setenv("LGBM_TPU_FAULT_NAN_GRAD_AT_ITER", "1")
    monkeypatch.setenv("LGBM_TPU_FAULT_NAN_GRAD_COUNT", "5")
    params = {**BASE, "objective": "regression", "check_numerics": True}
    with pytest.raises(LightGBMError, match=r"iteration 1.*5 non-finite"):
        _train(params, X, y, 4)


def test_corrupt_checkpoint_injection_point(tmp_path):
    X, y = _data()
    ckdir = str(tmp_path / "ck")
    params = {**BASE, "objective": "regression",
              "fault_corrupt_checkpoint": True}
    _train(params, X, y, 4,
           callbacks=[lgb.checkpoint_callback(ckdir, period=2)])
    # every checkpoint was damaged post-write: none validates
    assert CheckpointManager(ckdir).load_latest_valid() is None


# ================================================== init_model continuity
def test_init_model_continuation_parity(tmp_path):
    """Satellite of the bit-identical criterion on the init_model path:
    train(10) vs train(5) -> save -> resume(5) via init_model. The loaded
    trees re-serialize byte-identically; the continued trees see a score
    cache rebuilt from a float64 host prediction sum (vs the uninterrupted
    run's sequential float32 adds), so the comparison here is tight
    numerical equality of predictions, not text equality — the exact-text
    bar is the checkpoint path's (test_kill_resume_bit_identical)."""
    X, y = _data()
    params = {**BASE, "objective": "regression"}
    full = _train(params, X, y, 10)
    part = _train(params, X, y, 5)
    path = str(tmp_path / "part.txt")
    part.save_model(path)
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    cont = lgb.train(dict(params), ds, num_boost_round=5, init_model=path)
    assert cont.num_trees() == full.num_trees() == 10
    # the first 5 tree blocks are the saved model's, byte for byte
    full_blocks = full.model_to_string().split("Tree=")[1:6]
    cont_blocks = cont.model_to_string().split("Tree=")[1:6]
    assert cont_blocks == full_blocks
    np.testing.assert_allclose(cont.predict(X), full.predict(X),
                               rtol=1e-5, atol=1e-7)


# ============================================== distributed init backoff
def test_distributed_init_retries_then_succeeds(monkeypatch):
    from lightgbm_tpu import distributed
    import jax
    calls = {"n": 0}

    def flaky(**kwargs):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("coordinator not up yet")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setattr(distributed, "_jax_already_initialized",
                        lambda: False)
    try:
        distributed.init(machines="127.0.0.1:45999", num_machines=1,
                         connect_retries=5, connect_backoff=0.01)
        assert calls["n"] == 3
    finally:
        distributed._initialized = False


def test_distributed_init_failure_names_coordinator(monkeypatch):
    from lightgbm_tpu import distributed
    import jax

    def always_down(**kwargs):
        raise RuntimeError("connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", always_down)
    monkeypatch.setattr(distributed, "_jax_already_initialized",
                        lambda: False)
    try:
        with pytest.raises(LightGBMError,
                           match=r"coordinator at 10\.99\.0\.1:45999"):
            distributed.init(machines="10.99.0.1:45999", num_machines=1,
                             process_id=0, connect_retries=3,
                             connect_backoff=0.01)
    finally:
        distributed._initialized = False


# ==================================================== real kill/respawn
_CHILD_SCRIPT = r"""
import numpy as np
import lightgbm_tpu as lgb
rng = np.random.RandomState(0)
X = rng.randn(400, 10)
y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.randn(400)
params = {{"objective": "regression", "num_leaves": 7, "min_data_in_leaf": 5,
          "verbosity": -1, "bagging_fraction": 0.6, "bagging_freq": 2,
          "feature_fraction": 0.8}}
ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
lgb.train(dict(params), ds, num_boost_round=10,
          callbacks=[lgb.checkpoint_callback({ckdir!r}, period=1)],
          resume_from={ckdir!r})
print("TRAINING_COMPLETE")
"""


@pytest.mark.slow
def test_subprocess_kill_and_respawn_bit_identical(tmp_path):
    """The full preemption shape: a child process is hard-killed
    (os._exit(137), no cleanup) mid-training by the fault harness, a fresh
    process auto-resumes from the checkpoint directory, and the final
    model text equals an uninterrupted run's byte for byte."""
    ckdir = str(tmp_path / "ck")
    script = _CHILD_SCRIPT.format(ckdir=ckdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               LGBM_TPU_FAULT_KILL_AT_ITER="6")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 137, proc.stderr[-2000:]
    assert "TRAINING_COMPLETE" not in proc.stdout
    # respawn without the fault armed: auto-resume finishes the run
    env.pop("LGBM_TPU_FAULT_KILL_AT_ITER")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "TRAINING_COMPLETE" in proc.stdout
    # the surviving checkpoint holds the full 10-iteration model,
    # bit-identical to an uninterrupted in-process run
    X, y = _data()
    params = {**BASE, **MODE_PARAMS["gbdt"]}
    full = _train(params, X, y, 10).model_to_string()
    ck = CheckpointManager(ckdir).load_latest_valid()
    assert ck.iteration == 10
    assert ck.model_text == full


# ============================================================= misc bits
def test_params_hash_ignores_io_knobs():
    a = lgb.Config.from_params({"num_leaves": 7, "verbosity": -1})
    b = lgb.Config.from_params({"num_leaves": 7, "verbosity": 2,
                                "output_model": "elsewhere.txt"})
    c = lgb.Config.from_params({"num_leaves": 9})
    assert params_hash(a) == params_hash(b)
    assert params_hash(a) != params_hash(c)
    # list-typed params participate too (to_params() omits them; the hash
    # must not): constraints change => different model => different hash
    d = lgb.Config.from_params({"num_leaves": 7,
                                "monotone_constraints": [1, -1, 0]})
    e = lgb.Config.from_params({"num_leaves": 7,
                                "max_bin_by_feature": [16, 32]})
    assert params_hash(d) != params_hash(a)
    assert params_hash(e) != params_hash(a)


def test_checkpoint_submodule_importable():
    # lgb.checkpoint must be the submodule on a fresh import (not only
    # after a Booster construction lazily pulls it in)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import lightgbm_tpu as lgb; "
         "assert lgb.checkpoint.CheckpointManager; "
         "assert lgb.checkpoint_callback"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]


def test_fault_env_overrides_in_both_directions(monkeypatch):
    cfg = lgb.Config.from_params({"fault_corrupt_checkpoint": True,
                                  "fault_kill_at_iter": 3})
    # env set to "off" values DISARMS config-armed faults (env wins)
    monkeypatch.setenv("LGBM_TPU_FAULT_CORRUPT_CHECKPOINT", "0")
    monkeypatch.setenv("LGBM_TPU_FAULT_KILL_AT_ITER", "-1")
    assert faults.plan_from(cfg) is None


def test_dataset_fingerprint_tracks_labels():
    X, y = _data()
    ds1 = lgb.Dataset(X, label=y).construct()
    ds2 = lgb.Dataset(X, label=y + 1.0).construct()
    ds3 = lgb.Dataset(X, label=y).construct()
    assert dataset_fingerprint(ds1) == dataset_fingerprint(ds3)
    assert dataset_fingerprint(ds1) != dataset_fingerprint(ds2)


# ====================================== checkpoint rotation robustness
def test_checkpoint_rotation_robustness(tmp_path):
    """Rotation robustness, one shared training: (a) a ckpt_N.tmp staging
    directory left by a killed writer is invisible to readers (never
    matches the checkpoint name filter); (b) keep-pruning counts only
    VALID checkpoints, so newer damaged ones cannot evict the newest one
    that actually works (the old name-ordered pruning would delete it and
    leave nothing resumable); (c) the next successful write reclaims the
    stale staging dir."""
    X, y = _data()
    params = {**BASE, "objective": "regression"}
    ckdir = str(tmp_path / "ck")
    _train(params, X, y, 3,
           callbacks=[lgb.checkpoint_callback(ckdir, period=1, keep=10)])
    # (a) fake a killed writer: a half-written staging dir newest by name
    stale = os.path.join(ckdir, "ckpt_00000009.tmp")
    os.makedirs(stale)
    with open(os.path.join(stale, "model.txt"), "w") as fh:
        fh.write("half a model")
    mgr = CheckpointManager(ckdir)
    assert [it for it, _ in mgr.checkpoints()] == [1, 2, 3]  # .tmp invisible
    assert mgr.load_latest_valid().iteration == 3
    # (b) damage the two NEWEST so structural validation fails (truncation
    # changes the byte length the manifest records)
    for it in (2, 3):
        faults.corrupt_file(
            os.path.join(ckdir, f"ckpt_{it:08d}", "state.pkl"),
            truncate=True)
    mgr = CheckpointManager(ckdir, keep=2)
    mgr._prune()
    remaining = [it for it, _ in mgr.checkpoints()]
    assert 1 in remaining, remaining      # newest VALID survived
    assert 2 not in remaining and 3 not in remaining   # damage reclaimed
    assert mgr.load_latest_valid().iteration == 1
    # (c) resume from the survivor; the next write cleans the stale .tmp
    _train(params, X, y, 3, resume_from=ckdir,
           callbacks=[lgb.checkpoint_callback(ckdir, period=1)])
    assert not [e for e in os.listdir(ckdir) if e.endswith(".tmp")]
    assert CheckpointManager(ckdir).load_latest_valid().iteration == 3


@pytest.mark.slow
def test_kill_during_checkpoint_write_recovers(tmp_path):
    """A writer hard-killed BETWEEN the payload writes and the manifest
    (the LGBM_TPU_FAULT_KILL_IN_CKPT_WRITE injection point) leaves only a
    stale staging dir; resume falls back to the previous checkpoint and
    reproduces the uninterrupted run bit-identically. (Slow tier —
    subprocess kill/respawn; the tier-1 siblings are the stale-.tmp and
    validity-aware-pruning tests above, which cover the same recovery
    surfaces in-process.)"""
    ckdir = str(tmp_path / "ck")
    script = _CHILD_SCRIPT.format(ckdir=ckdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               LGBM_TPU_FAULT_KILL_IN_CKPT_WRITE="4")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 137, proc.stderr[-2000:]
    # the iteration-4 checkpoint never materialized; its stage dir did
    names = os.listdir(ckdir)
    assert "ckpt_00000004" not in names
    assert "ckpt_00000004.tmp" in names
    mgr = CheckpointManager(ckdir)
    assert mgr.load_latest_valid().iteration == 3
    # resume in-process: bit-identical to an uninterrupted run, stage
    # dir cleaned by the next write
    X, y = _data()
    params = {**BASE, **MODE_PARAMS["gbdt"]}
    full = _train(params, X, y, 10).model_to_string()
    resumed = _train(params, X, y, 10, resume_from=ckdir,
                     callbacks=[lgb.checkpoint_callback(ckdir, period=1)])
    assert resumed.model_to_string() == full
    assert not [e for e in os.listdir(ckdir) if e.endswith(".tmp")]
