"""sklearn estimator API (reference: tests/python_package_test/test_sklearn.py)."""

import numpy as np
import pytest

from lightgbm_tpu import LGBMClassifier, LGBMRanker, LGBMRegressor

sklearn = pytest.importorskip("sklearn")
from sklearn.base import clone  # noqa: E402
from sklearn.datasets import make_classification, make_regression  # noqa: E402
from sklearn.metrics import r2_score, roc_auc_score  # noqa: E402


@pytest.mark.slow
def test_classifier_binary():
    """(Slow tier: the string-label classifier test below is a strict
    superset of this cell's wrapper plumbing — fit/predict/accuracy on a
    binary problem — and stays tier-1.)"""
    X, y = make_classification(n_samples=600, n_features=8, random_state=0)
    clf = LGBMClassifier(n_estimators=15, num_leaves=15, min_child_samples=5)
    clf.fit(X, y)
    proba = clf.predict_proba(X)
    assert proba.shape == (600, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
    assert roc_auc_score(y, proba[:, 1]) > 0.95
    assert set(np.unique(clf.predict(X))) <= set(clf.classes_.tolist())
    assert clf.n_classes_ == 2
    assert clf.feature_importances_.sum() > 0


def test_classifier_string_labels():
    X, y = make_classification(n_samples=400, n_features=6, random_state=1)
    ys = np.where(y > 0, "yes", "no")
    clf = LGBMClassifier(n_estimators=8, min_child_samples=5).fit(X, ys)
    assert list(clf.classes_) == ["no", "yes"]
    preds = clf.predict(X)
    assert set(preds) <= {"no", "yes"}
    assert (preds == ys).mean() > 0.9


@pytest.mark.slow
def test_classifier_multiclass():
    """(Slow tier: the sklearn WRAPPER surface stays tier-1 via the
    string-label classifier test, and multiclass training itself via the
    fused multiclass parity in test_fused_wide.py — this cell only
    combines the two.)"""
    X, y = make_classification(n_samples=900, n_features=8, n_informative=6,
                               n_classes=3, random_state=2)
    clf = LGBMClassifier(n_estimators=10, min_child_samples=5).fit(X, y)
    proba = clf.predict_proba(X)
    assert proba.shape == (900, 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
    assert (clf.predict(X) == y).mean() > 0.85


@pytest.mark.slow
def test_regressor_with_early_stopping():
    # ~23 s, the heaviest test in this file (many-round fit + eval per
    # round); the early-stopping machinery stays tier-1-covered by
    # test_fault_tolerance.test_resume_restores_eval_history_and_early_stopping
    # and the sklearn wrapper surface by this file's other tests
    X, y = make_regression(n_samples=600, n_features=8, noise=5.0,
                           random_state=1)
    reg = LGBMRegressor(n_estimators=100, num_leaves=15)
    reg.fit(X, y, eval_set=[(X[:300], y[:300])], early_stopping_rounds=5,
            verbose=False)
    assert r2_score(y, reg.predict(X)) > 0.8
    assert "valid_0" in reg.evals_result_


def test_regressor_score_api():
    X, y = make_regression(n_samples=400, n_features=6, noise=2.0,
                           random_state=3)
    reg = LGBMRegressor(n_estimators=20).fit(X, y)
    assert reg.score(X, y) > 0.8


def test_param_mapping_aliases():
    """sklearn names must reach the booster as canonical params."""
    X, y = make_regression(n_samples=300, n_features=5, random_state=4)
    reg = LGBMRegressor(n_estimators=5, reg_alpha=0.5, reg_lambda=0.7,
                        min_child_samples=7, colsample_bytree=0.8,
                        subsample=0.9, subsample_freq=1)
    reg.fit(X, y)
    cfg = reg.booster_._boosting.config
    assert cfg.lambda_l1 == 0.5
    assert cfg.lambda_l2 == 0.7
    assert cfg.min_data_in_leaf == 7
    assert cfg.feature_fraction == 0.8
    assert cfg.bagging_fraction == 0.9


def test_clone_and_get_params():
    clf = LGBMClassifier(n_estimators=12, num_leaves=9, cat_smooth=5.0)
    cloned = clone(clf)
    assert cloned.n_estimators == 12
    assert cloned.num_leaves == 9
    assert cloned.get_params()["cat_smooth"] == 5.0


@pytest.mark.slow
def test_custom_objective_callable():
    """(Slow tier: the fobj training path stays tier-1 via engine-level
    custom-objective coverage — e.g. test_fault_tolerance.py's fobj
    numerics guard — this spelling only adds the sklearn plumbing.)"""
    X, y = make_regression(n_samples=400, n_features=5, random_state=5)

    def l2_obj(y_true, y_pred):
        return y_pred - y_true, np.ones_like(y_true)

    reg = LGBMRegressor(n_estimators=20, objective=l2_obj).fit(X, y)
    ref = LGBMRegressor(n_estimators=20).fit(X, y)
    # custom L2 must track built-in L2 closely
    assert r2_score(y, reg.predict(X, raw_score=True)) > 0.8


def test_ranker():
    rng = np.random.RandomState(3)
    nq, qsize = 30, 10
    X = rng.normal(size=(nq * qsize, 5))
    rel = X[:, 0] + 0.5 * rng.normal(size=nq * qsize)
    y = np.clip((rel * 2).astype(int) - int(rel.min()), 0, 4)
    group = np.full(nq, qsize)
    rk = LGBMRanker(n_estimators=10, min_child_samples=3)
    rk.fit(X, y, group=group, eval_set=[(X, y)], eval_group=[group])
    assert rk.predict(X).shape == (nq * qsize,)
    # per-query ranking should correlate with relevance
    from scipy.stats import spearmanr
    rho = spearmanr(rk.predict(X), y).statistic
    assert rho > 0.3


def test_ranker_requires_group():
    X = np.random.RandomState(0).normal(size=(50, 3))
    y = np.zeros(50)
    with pytest.raises(ValueError, match="group"):
        LGBMRanker(n_estimators=2).fit(X, y)


def test_not_fitted_errors():
    from sklearn.exceptions import NotFittedError
    clf = LGBMClassifier()
    with pytest.raises(NotFittedError):
        clf.predict(np.zeros((2, 3)))
    with pytest.raises(NotFittedError):
        _ = clf.feature_importances_


def test_class_weight_balanced():
    X, y = make_classification(n_samples=600, n_features=6, weights=[0.9, 0.1],
                               random_state=6)
    clf = LGBMClassifier(n_estimators=10, class_weight="balanced",
                        min_child_samples=5).fit(X, y)
    proba = clf.predict_proba(X)[:, 1]
    assert roc_auc_score(y, proba) > 0.9
