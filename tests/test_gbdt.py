"""End-to-end training tests (the analog of the reference's
tests/python_package_test/test_engine.py behavior-level suite)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.mark.slow
def test_regression_learns(rng):
    """slow: a pure quality claim (50-round mse bar). Regression-objective
    mechanics stay tier-1 via test_l1_objective_with_renew, the sklearn
    LGBMRegressor surface (test_sklearn) and the reference-consistency
    regression cells; the full objective quality matrix is slow-tier by
    design (test_objective_matrix)."""
    n = 2000
    X = rng.normal(size=(n, 10))
    y = X[:, 0] * 3 + np.sin(X[:, 1] * 2) + 0.1 * rng.normal(size=n)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    booster = lgb.train({"objective": "regression", "num_leaves": 31,
                         "learning_rate": 0.1, "min_data_in_leaf": 20,
                         "verbosity": -1}, ds, num_boost_round=50)
    pred = booster.predict(X)
    mse = float(np.mean((pred - y) ** 2))
    base = float(np.var(y))
    assert mse < 0.15 * base


def test_binary_auc_on_reference_example(binary_example):
    """Quality on the reference's own example data
    (examples/binary_classification/train.conf: 7000x28 binary)."""
    X, y, Xt, yt = binary_example
    train = lgb.Dataset(X, label=y, free_raw_data=False)
    valid = lgb.Dataset(Xt, label=yt, reference=train)
    evals = {}
    booster = lgb.train(
        {"objective": "binary", "metric": ["auc", "binary_logloss"],
         "num_leaves": 63, "learning_rate": 0.1, "min_data_in_leaf": 50,
         "verbosity": -1},
        train, num_boost_round=50, valid_sets=[valid], valid_names=["test"],
        evals_result=evals, verbose_eval=False)
    auc = evals["test"]["auc"][-1]
    # sklearn's HistGradientBoosting reaches ~0.827 test AUC with this exact
    # config on this data; we should land in the same band
    assert auc > 0.80
    # prediction is a probability
    p = booster.predict(Xt)
    assert np.all((p >= 0) & (p <= 1))
    raw = booster.predict(Xt, raw_score=True)
    assert not np.all((raw >= 0) & (raw <= 1))


def test_binary_matches_sklearn_quality(binary_example):
    """Distributionally compare against sklearn's histogram GBDT — the same
    algorithm family; our AUC should be within noise of theirs."""
    from sklearn.ensemble import HistGradientBoostingClassifier
    from sklearn.metrics import roc_auc_score
    X, y, Xt, yt = binary_example
    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "binary", "num_leaves": 31,
                         "learning_rate": 0.1, "verbosity": -1},
                        train, num_boost_round=100)
    ours = roc_auc_score(yt, booster.predict(Xt))
    sk = HistGradientBoostingClassifier(max_iter=100, learning_rate=0.1,
                                        max_leaf_nodes=31)
    sk.fit(X, y)
    theirs = roc_auc_score(yt, sk.predict_proba(Xt)[:, 1])
    assert ours > theirs - 0.01


@pytest.mark.slow
def test_multiclass(rng):
    """(Slow tier: multiclass training runs tier-1 inside
    test_fused_wide.py::test_fused_parity_multiclass — which trains the
    SAME unfused program this test uses and asserts fused parity against
    it; the learning-quality claim alone rides here.)"""
    n, k = 1500, 4
    X = rng.normal(size=(n, 8))
    logits = X[:, :k] * 2.0
    y = np.argmax(logits + 0.5 * rng.normal(size=(n, k)), axis=1)
    ds = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "multiclass", "num_class": k,
                         "num_leaves": 15, "verbosity": -1},
                        ds, num_boost_round=30)
    p = booster.predict(X)
    assert p.shape == (n, k)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    acc = float(np.mean(np.argmax(p, axis=1) == y))
    assert acc > 0.85


def test_l1_objective_with_renew(rng):
    n = 1000
    X = rng.normal(size=(n, 5))
    y = X[:, 0] * 2 + rng.standard_cauchy(size=n) * 0.05  # heavy-tailed noise
    ds = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "regression_l1", "num_leaves": 15,
                         "learning_rate": 0.2, "verbosity": -1},
                        ds, num_boost_round=40)
    pred = booster.predict(X)
    mae = float(np.mean(np.abs(pred - y)))
    base = float(np.mean(np.abs(y - np.median(y))))
    assert mae < 0.4 * base


def test_early_stopping(binary_example):
    X, y, Xt, yt = binary_example
    train = lgb.Dataset(X, label=y)
    valid = lgb.Dataset(Xt, label=yt, reference=train)
    booster = lgb.train(
        {"objective": "binary", "metric": "binary_logloss",
         "learning_rate": 0.5, "num_leaves": 63, "verbosity": -1},
        train, num_boost_round=200, valid_sets=[valid],
        early_stopping_rounds=5, verbose_eval=False)
    assert booster.best_iteration > 0
    assert booster.best_iteration <= 200


@pytest.mark.slow
def test_weights_change_model(rng):
    """Slow: the weight plumbing stays tier-1 via
    test_boosting_modes.py::test_goss_weights_exact_counts_under_ties
    (weighted gradient scaling), test_sklearn.py::test_class_weight_balanced
    (sample-weight end-to-end) and test_cli.py::test_cli_weight_side_file;
    this spelling only adds the mean-shift sanity check."""
    n = 800
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    w = np.where(y > 0, 10.0, 1.0)
    ds_w = lgb.Dataset(X, label=y, weight=w)
    ds_u = lgb.Dataset(X, label=y)
    pw = lgb.train({"objective": "binary", "verbosity": -1}, ds_w,
                   num_boost_round=10).predict(X, raw_score=True)
    pu = lgb.train({"objective": "binary", "verbosity": -1}, ds_u,
                   num_boost_round=10).predict(X, raw_score=True)
    # weighting positives up must raise scores on average
    assert pw.mean() > pu.mean()


def test_bagging_and_feature_fraction(binary_example):
    X, y, Xt, yt = binary_example
    from sklearn.metrics import roc_auc_score
    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "binary", "bagging_fraction": 0.6,
                         "bagging_freq": 1, "feature_fraction": 0.7,
                         "num_leaves": 31, "verbosity": -1},
                        train, num_boost_round=50)
    auc = roc_auc_score(yt, booster.predict(Xt))
    # full-data training reaches ~0.82 on this dataset; sampling should stay
    # in the same band
    assert auc > 0.78


def test_custom_objective(binary_example):
    X, y, _, _ = binary_example

    def fobj(score, ds):
        label = ds.get_label()
        p = 1.0 / (1.0 + np.exp(-score))
        return p - label, p * (1.0 - p)

    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "none", "verbosity": -1}, train,
                        num_boost_round=20, fobj=fobj)
    raw = booster.predict(X, raw_score=True)
    from sklearn.metrics import roc_auc_score
    # train AUC after 20 rounds of custom-fobj logloss (built-in reaches ~0.89)
    assert roc_auc_score(y, raw) > 0.82


def test_min_gain_to_split_limits_growth(rng):
    n = 500
    X = rng.normal(size=(n, 3))
    y = rng.normal(size=n) * 0.01  # almost pure noise
    ds = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "regression", "min_gain_to_split": 100.0,
                         "verbosity": -1}, ds, num_boost_round=5)
    # huge gain requirement -> no splits anywhere
    assert all(ht.num_leaves == 1 for ht in booster._boosting.host_trees)


def test_feature_importance(binary_example):
    X, y, _, _ = binary_example
    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "binary", "verbosity": -1}, train,
                        num_boost_round=10)
    imp_split = booster.feature_importance("split")
    imp_gain = booster.feature_importance("gain")
    assert imp_split.shape == (X.shape[1],)
    assert imp_split.sum() > 0
    assert imp_gain.sum() > 0


def test_init_score(rng):
    n = 600
    X = rng.normal(size=(n, 4))
    y = X[:, 0] + 5.0
    init = np.full(n, 5.0)
    ds = lgb.Dataset(X, label=y, init_score=init)
    booster = lgb.train({"objective": "regression", "boost_from_average": False,
                         "verbosity": -1}, ds, num_boost_round=20)
    # prediction on new data does not include init_score (reference behavior)
    pred_raw = booster.predict(X, raw_score=True)
    assert abs(float(np.mean(pred_raw + 5.0 - y))) < 0.5


def test_bagging_subset_path_end_to_end(binary_example):
    """bagging_fraction <= 0.5 takes the subset-copy path (compact
    histogram rows) and still trains a healthy model with deterministic
    repeats."""
    X, y, _, _ = binary_example
    params = {"objective": "binary", "bagging_fraction": 0.3,
              "bagging_freq": 1, "num_leaves": 15, "verbosity": -1}

    def run():
        booster = lgb.train(params, lgb.Dataset(X, label=y), 10)
        assert booster._boosting._bag_sub is not None   # subset path active
        return booster.predict(X)

    p1, p2 = run(), run()
    np.testing.assert_array_equal(p1, p2)               # device PRNG seeded
    acc = np.mean((p1 > 0.5) == (y > 0.5))
    assert acc > 0.70, acc   # no-bagging baseline is 0.707 at these settings


def test_all_features_prefiltered_constant_trees(rng):
    """min_data_in_leaf too large for the data pre-filters EVERY feature
    as trivial (reference: feature_pre_filter, dataset_loader.cpp:647-648).
    The reference then trains splitless constant trees and stops; the
    0-column device matrix must not crash the grower or predict."""
    X = rng.normal(size=(200, 5)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    booster = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                                  "min_data_in_leaf": 150, "verbosity": -1},
                          train_set=lgb.Dataset(X, label=y))
    assert booster.update() is True          # no split -> early stoppable
    import math
    avg = math.log(y.mean() / (1 - y.mean()))
    pred = booster.predict(X[:4], raw_score=True)
    np.testing.assert_allclose(pred, avg, rtol=1e-5)
    assert (booster.predict(X[:4], pred_leaf=True) == 0).all()
    assert "tree" in booster.model_to_string()


def test_fused_step_bit_parity(rng):
    """The single-dispatch fused iteration (gradients -> growth -> shrunk
    delta in one jitted program, gbdt._fused_step_fn) must be bit-identical
    to the unfused phase-by-phase path — including under bagging masks."""
    from lightgbm_tpu.models.gbdt import GBDT
    X = rng.normal(size=(2000, 8)).astype(np.float64)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 10,
              "bagging_fraction": 0.8, "bagging_freq": 2, "verbosity": -1}

    def fit():
        return lgb.train(params, lgb.Dataset(X, label=y, params=params), 8)

    b_fused = fit()
    assert b_fused._boosting._fused_cache, "fused path did not engage"
    orig = GBDT._fused_ok
    GBDT._fused_ok = lambda self, g: False
    try:
        b_plain = fit()
    finally:
        GBDT._fused_ok = orig
    assert not b_plain._boosting._fused_cache
    assert b_fused.model_to_string() == b_plain.model_to_string()
    np.testing.assert_array_equal(b_fused.predict(X[:128]),
                                  b_plain.predict(X[:128]))
