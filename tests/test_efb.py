"""Exclusive Feature Bundling + sparse input tests
(reference: dataset.cpp:100-303 FindGroups/FastFeatureBundling,
sparse_bin.hpp storage; VERDICT r2 item 5)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.bundling import fast_feature_bundling

sp = pytest.importorskip("scipy.sparse")


def _onehotish(rng, n, f, density=0.02):
    """Mutually sparse columns: each row activates a few features."""
    m = sp.random(n, f, density=density, random_state=rng, format="csr",
                  data_rvs=lambda k: rng.uniform(0.5, 2.0, k))
    return m


def test_greedy_bundling_exclusive_features():
    """Perfectly exclusive features must land in one bundle."""
    rows = [np.array([0, 1, 2]), np.array([3, 4, 5]), np.array([6, 7])]
    bundles = fast_feature_bundling(rows, [3, 4, 5], np.ones(3, bool), 100)
    assert len(bundles) == 1
    b = bundles[0]
    assert sorted(b.members) == [0, 1, 2]
    # bin 0 shared; each member = 1 phantom + (num_bin - 1) data bins
    assert b.num_bin == 1 + 3 + 4 + 5


def test_conflicting_features_not_bundled():
    rows = [np.arange(60), np.arange(50, 100)]   # 10 overlapping rows
    bundles = fast_feature_bundling(rows, [3, 3], np.ones(2, bool), 100)
    assert len(bundles) == 2


def test_sparse_construct_no_densify():
    rng = np.random.RandomState(0)
    X = _onehotish(rng, 2000, 300, density=0.01)
    y = (np.asarray(X.sum(axis=1)).ravel() > 0.2).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5,
                                         "verbosity": -1})
    ds.construct()
    assert ds.bundles is not None
    ncols = ds.num_used_features()
    nused = len(ds.used_features)
    assert ncols < nused, (ncols, nused)   # bundling actually merged columns
    booster = lgb.train({"objective": "binary", "num_leaves": 8,
                         "min_data_in_leaf": 5, "verbosity": -1},
                        ds, num_boost_round=5)
    pred_sparse = booster.predict(X, raw_score=True)
    pred_dense = booster.predict(X.toarray(), raw_score=True)
    np.testing.assert_allclose(pred_sparse, pred_dense, rtol=1e-6)
    assert np.std(pred_sparse) > 0


def _tree_structure(booster):
    """The structural lines of every tree (split features, thresholds,
    topology) — everything but the f32 value/weight/gain numerics."""
    keys = ("split_feature=", "threshold=", "decision_type=",
            "left_child=", "right_child=", "num_leaves=", "split_gain=")
    out = []
    for block in booster.model_to_string().split("Tree=")[1:]:
        out.append([ln for ln in block.splitlines()
                    if ln.startswith(keys[:-1])])
    return out


def test_bundled_matches_unbundled_training():
    """Small-case parity: with a zero conflict budget the bundled model
    grows the EXACT same trees (features, thresholds, topology) as
    training on the same data with bundling disabled, and its leaf values
    agree to the f32 scan-noise bound.

    Exact VALUE equality is not attainable with float32 histograms: the
    split scan derives each candidate's complement side from the leaf
    totals (left = total - right, the reference's FixHistogram shape), so
    a bundle-segment scan and the plain/sparse-column scan round the SAME
    real sums differently at eps(leaf_total) — ~3e-5 absolute on a
    360-mass leaf, ~1e-5 relative on leaf outputs (the reference hides
    this under float64 hist_t; gpu_use_dp is this codebase's analog).
    What MUST be invariant is the chosen structure — including exact
    gain-tie resolution, which the per-bin preference tables in
    BundleMeta (pref_fwd/pref_rev) pin to the unbundled feature-major
    order (see test_bundle_tie_breaks_to_lowest_feature)."""
    rng = np.random.RandomState(1)
    n, f = 1500, 40
    X = _onehotish(rng, n, f, density=0.03)
    w = rng.normal(size=f)
    y = (X @ w + 0.1 * rng.normal(size=n) > 0).astype(np.float64)

    def fit(data, extra):
        ds = lgb.Dataset(data, label=y, params={"min_data_in_leaf": 5,
                                                "verbosity": -1, **extra})
        return lgb.train({"objective": "binary", "num_leaves": 8,
                          "min_data_in_leaf": 5, "verbosity": -1, **extra},
                         ds, num_boost_round=8)

    b_bundled = fit(X, {})
    b_plain = fit(X.toarray(), {})
    ds_check = b_bundled._boosting.train_set
    assert ds_check.bundles is not None
    assert ds_check.num_used_features() < len(ds_check.used_features)
    # tree structure: byte-identical, tree by tree
    assert _tree_structure(b_bundled) == _tree_structure(b_plain)
    # values: within the per-split eps(leaf_total) noise accumulated over
    # 8 trees (measured max ~3e-6; bound leaves 6x headroom)
    Xt = _onehotish(np.random.RandomState(2), 500, f, density=0.03).toarray()
    np.testing.assert_allclose(b_bundled.predict(Xt, raw_score=True),
                               b_plain.predict(Xt, raw_score=True),
                               rtol=1e-4, atol=2e-5)


def test_bundle_tie_breaks_to_lowest_feature():
    """Regression for the within-bundle tie-break divergence: two mutually
    exclusive features engineered to EXACTLY tie in gain must split on the
    LOWER original feature index, bundled or not. The bundle scan's raw
    column-major argmax prefers the highest bundle bin — i.e. the
    highest-OFFSET member, the opposite of the unbundled feature loop —
    which the BundleMeta preference tables correct."""
    n = 400
    X = np.zeros((n, 3))
    X[:100, 0] = 1.0          # feature 0 active on rows 0..99
    X[100:200, 1] = 1.0       # feature 1 active on rows 100..199
    y = np.zeros(n)
    y[:100] = 1.0             # identical y pattern on each -> equal gains
    y[100:200] = 1.0
    params = {"objective": "regression", "num_leaves": 4,
              "min_data_in_leaf": 5, "verbosity": -1,
              "boost_from_average": False}

    def root_features(enable_bundle):
        p = dict(params, enable_bundle=enable_bundle)
        ds = lgb.Dataset(sp.csr_matrix(X), label=y, params=p)
        booster = lgb.train(p, ds, num_boost_round=1)
        tree = booster.model_to_string().split("Tree=")[1]
        line = [ln for ln in tree.splitlines()
                if ln.startswith("split_feature=")][0]
        return [int(v) for v in line.split("=")[1].split()]

    bundled = root_features(True)
    plain = root_features(False)
    assert bundled[0] == 0, bundled     # lower feature wins the tie
    assert bundled == plain


def test_enable_bundle_false_on_sparse():
    rng = np.random.RandomState(3)
    X = _onehotish(rng, 800, 50, density=0.05)
    y = rng.normal(size=800)
    ds = lgb.Dataset(X, label=y, params={"enable_bundle": False,
                                         "verbosity": -1})
    ds.construct()
    # sparse path still used (no densify) but every column is a single
    assert ds.bundles is not None
    assert all(len(b.members) == 1 for b in ds.bundles)


def test_bundled_model_text_roundtrip(tmp_path):
    """Saved models are bundle-free (original features, real thresholds) and
    reload to the same predictions."""
    rng = np.random.RandomState(4)
    n, f = 1200, 30
    X = _onehotish(rng, n, f, density=0.05)
    y = (np.asarray(X.sum(axis=1)).ravel()
         + 0.1 * rng.normal(size=n) > 0.5).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"min_data_in_leaf": 5,
                                         "verbosity": -1})
    booster = lgb.train({"objective": "binary", "num_leaves": 8,
                         "min_data_in_leaf": 5, "verbosity": -1},
                        ds, num_boost_round=5)
    path = str(tmp_path / "model.txt")
    booster.save_model(path)
    loaded = lgb.Booster(model_file=path)
    Xt = X.toarray()[:200]
    np.testing.assert_allclose(loaded.predict(Xt, raw_score=True),
                               booster.predict(Xt, raw_score=True),
                               rtol=1e-5, atol=1e-7)


@pytest.mark.slow
def test_allstate_shaped_constructs_and_trains():
    """A wide-sparse synthetic (VERDICT: 'Allstate-shaped ... constructs
    within memory, bundles to O(100) effective columns, trains'). Scaled to
    test-size (the full 13.2Mx4228 is the benchmark's job). (Slow tier: a
    shape/scale smoke — EFB correctness stays tier-1 via the
    bundled-vs-unbundled parity tests in this file.)"""
    rng = np.random.RandomState(5)
    n, f = 60_000, 2000
    X = _onehotish(rng, n, f, density=0.001)   # ~99.9% sparse
    y = (np.asarray((X != 0).sum(axis=1)).ravel() % 2).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    ds.construct()
    ncols = ds.num_used_features()
    assert ncols <= 200, ncols
    booster = lgb.train({"objective": "binary", "num_leaves": 16,
                         "verbosity": -1}, ds, num_boost_round=3)
    p = booster.predict(X[:100], raw_score=True)
    assert p.shape == (100,)
