"""TPU-vs-CPU training parity — the analog of the reference's env-gated
dual-device test (reference: tests/python_package_test/test_dual.py:14-33,
CPU vs GPU score parity in one build, enabled by an env var because the
second device may be absent).

Enable with LIGHTGBM_TPU_DUAL_TEST=1 on a host with a live TPU backend:
trains the same data on the TPU (subprocess without the CPU pin) and on
CPU, and asserts held-out AUC parity within the same tolerance the
reference accepts between its CPU and GPU paths
(docs/GPU-Performance.rst:133-140)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(os.environ.get("LIGHTGBM_TPU_DUAL_TEST") != "1",
                       reason="set LIGHTGBM_TPU_DUAL_TEST=1 on a host "
                              "with a live TPU backend"),
]

_CHILD = """
import json
import numpy as np
import jax
import lightgbm_tpu as lgb
rng = np.random.RandomState(0)
n, nv, f = 100_000, 20_000, 20
X = rng.normal(size=(n + nv, f)).astype(np.float32)
w = rng.normal(size=f)
y = ((X @ w + rng.logistic(size=n + nv)) > 0).astype(np.float32)
params = {"objective": "binary", "num_leaves": 63, "verbosity": -1,
          "min_data_in_leaf": 50}
b = lgb.train(params, lgb.Dataset(X[:n], label=y[:n], params=params), 30)
from sklearn.metrics import roc_auc_score
auc = roc_auc_score(y[n:], b.predict(X[n:], raw_score=True))
print("RESULT " + json.dumps({"backend": jax.default_backend(),
                              "auc": float(auc)}))
"""


def _run(platforms):
    env = dict(os.environ)
    if platforms:
        env["JAX_PLATFORMS"] = platforms
    else:
        env.pop("JAX_PLATFORMS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                       capture_output=True, text=True, timeout=1800)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    line = [ln for ln in r.stdout.splitlines() if ln.startswith("RESULT ")]
    return json.loads(line[-1][len("RESULT "):])


def test_tpu_cpu_training_parity():
    tpu = _run(None)            # default platform resolution (TPU first)
    cpu = _run("cpu")
    assert tpu["backend"] == "tpu", tpu
    assert cpu["backend"] == "cpu", cpu
    # the reference's CPU-vs-GPU tolerance: AUC within ~5e-4 at parity
    # configs (GPU-Performance.rst: CPU-255 0.845612 vs GPU-255 0.845612;
    # our hilo kernel rounds inputs coarser, so allow 2e-3)
    assert abs(tpu["auc"] - cpu["auc"]) < 2e-3, (tpu, cpu)
