"""Tree-learner constraint features: monotone, interaction, feature_contri,
extra_trees, CEGB, per-node feature sampling.

Mirrors the reference coverage (reference: tests/python_package_test/
test_engine.py:1256 monotone, interaction-constraint and cegb tests;
semantics from src/treelearner/monotone_constraints.hpp,
col_sampler.hpp, cost_effective_gradient_boosting.hpp)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.RandomState(0)
    n = 2000
    X = rng.uniform(-2, 2, size=(n, 4))
    y = (2 * X[:, 0] - 1.5 * X[:, 1] + 0.5 * np.sin(3 * X[:, 2])
         + 0.2 * rng.normal(size=n))
    return X, y


BASE = {"objective": "regression", "num_leaves": 31, "min_data_in_leaf": 5,
        "verbosity": -1}


def _sweep(booster, feat, lo=-2.0, hi=2.0, npts=60):
    base = np.zeros((npts, 4))
    base[:, feat] = np.linspace(lo, hi, npts)
    return booster.predict(base)


def test_monotone_constraints_enforced(reg_data):
    X, y = reg_data
    params = dict(BASE, monotone_constraints=[1, -1, 0, 0])
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=30)
    from sklearn.metrics import r2_score
    assert r2_score(y, booster.predict(X)) > 0.9
    p0 = _sweep(booster, 0)
    assert np.all(np.diff(p0) >= -1e-10), "monotone +1 violated"
    p1 = _sweep(booster, 1)
    assert np.all(np.diff(p1) <= 1e-10), "monotone -1 violated"


def test_monotone_unconstrained_differs(reg_data):
    """Sanity: the constraint must actually bind (sin feature would wiggle)."""
    X, y = reg_data
    params = dict(BASE, monotone_constraints=[0, 0, 1, 0])
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=30)
    p2 = _sweep(booster, 2)
    assert np.all(np.diff(p2) >= -1e-10)
    free = lgb.train(BASE, lgb.Dataset(X, label=y, params=BASE,
                                       free_raw_data=False),
                     num_boost_round=30)
    p2f = _sweep(free, 2)
    assert not np.all(np.diff(p2f) >= -1e-10), \
        "unconstrained model should follow the non-monotone sin signal"


def test_monotone_model_round_trip(reg_data):
    X, y = reg_data
    params = dict(BASE, monotone_constraints=[1, -1, 0, 0])
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=10)
    s = booster.model_to_string()
    assert "monotone_constraints=1 -1 0 0" in s
    loaded = lgb.Booster(model_str=s)
    np.testing.assert_allclose(booster.predict(X), loaded.predict(X))


def _tree_paths(model):
    out = []

    def walk(node, cur):
        if "split_feature" in node:
            cur = cur | {node["split_feature"]}
            walk(node["left_child"], cur)
            walk(node["right_child"], cur)
        else:
            out.append(cur)
    for ti in model["tree_info"]:
        walk(ti["tree_structure"], set())
    return out


def test_interaction_constraints(reg_data):
    X, y = reg_data
    params = dict(BASE, num_leaves=15, interaction_constraints=[[0, 1], [2, 3]])
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=10)
    for path_feats in _tree_paths(booster.dump_model()):
        assert path_feats <= {0, 1} or path_feats <= {2, 3}, path_feats


def test_feature_contri_zero_excludes_feature(reg_data):
    X, y = reg_data
    params = dict(BASE, num_leaves=15, feature_contri=[0.0, 1.0, 1.0, 1.0])
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=5)
    assert booster.feature_importance()[0] == 0


def test_cegb_coupled_penalty_excludes_feature(reg_data):
    X, y = reg_data
    params = dict(BASE, num_leaves=15, cegb_tradeoff=1.0,
                  cegb_penalty_feature_coupled=[1e9, 0.0, 0.0, 0.0])
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=5)
    assert booster.feature_importance()[0] == 0


def test_cegb_split_penalty_shrinks_trees(reg_data):
    X, y = reg_data
    free = lgb.train(BASE, lgb.Dataset(X, label=y, params=BASE,
                                       free_raw_data=False), num_boost_round=5)
    params = dict(BASE, cegb_tradeoff=1.0, cegb_penalty_split=10.0)
    pen = lgb.train(params, lgb.Dataset(X, label=y, params=params,
                                        free_raw_data=False), num_boost_round=5)
    assert pen.feature_importance().sum() < free.feature_importance().sum()


def test_cegb_lazy_trains(reg_data):
    X, y = reg_data
    params = dict(BASE, num_leaves=15, cegb_tradeoff=1.0,
                  cegb_penalty_feature_lazy=[0.001, 0.0, 0.0, 0.0])
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=5)
    from sklearn.metrics import r2_score
    assert r2_score(y, booster.predict(X)) > 0.3


def test_extra_trees(reg_data):
    X, y = reg_data
    params = dict(BASE, num_leaves=15, extra_trees=True)
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=20)
    from sklearn.metrics import r2_score
    assert r2_score(y, booster.predict(X)) > 0.8
    # deterministic under the same extra_seed
    ds2 = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster2 = lgb.train(params, ds2, num_boost_round=20)
    np.testing.assert_allclose(booster.predict(X), booster2.predict(X))


def test_feature_fraction_bynode(reg_data):
    X, y = reg_data
    params = dict(BASE, num_leaves=15, feature_fraction_bynode=0.5)
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=10)
    from sklearn.metrics import r2_score
    assert r2_score(y, booster.predict(X)) > 0.5


def test_monotone_penalty(reg_data):
    """monotone_penalty=2 makes monotone-feature splits at depth 0 and 1
    worthless (factor ~kEpsilon, monotone_constraints.hpp:355-364), so the
    monotone feature must not appear in the top two tree levels."""
    X, y = reg_data
    params = dict(BASE, monotone_constraints=[1, 0, 0, 0],
                  monotone_penalty=2.0)
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=10)
    p0 = _sweep(booster, 0)
    assert np.all(np.diff(p0) >= -1e-10)

    def shallow_feats(node, depth, out):
        if "split_feature" in node:
            if depth <= 1:
                out.append(node["split_feature"])
                shallow_feats(node["left_child"], depth + 1, out)
                shallow_feats(node["right_child"], depth + 1, out)
        return out

    for ti in booster.dump_model()["tree_info"]:
        feats = shallow_feats(ti["tree_structure"], 0, [])
        assert 0 not in feats, f"monotone feature split at depth<=1: {feats}"
    # the (unpenalized) baseline does use f0 shallow — the penalty binds
    params_np = dict(BASE, monotone_constraints=[1, 0, 0, 0])
    base = lgb.train(params_np, lgb.Dataset(X, label=y, params=params_np,
                                            free_raw_data=False),
                     num_boost_round=10)
    base_shallow = []
    for ti in base.dump_model()["tree_info"]:
        base_shallow += shallow_feats(ti["tree_structure"], 0, [])
    assert 0 in base_shallow


def test_monotone_intermediate_enforced(reg_data):
    """Intermediate mode keeps the monotone guarantee (sweep check) while
    constraining less than basic (monotone_constraints.hpp:514
    IntermediateLeafConstraints: children bounded by actual sibling
    outputs, other leaves re-bounded from real outputs)."""
    X, y = reg_data
    params = dict(BASE, monotone_constraints=[1, -1, 0, 0],
                  monotone_constraints_method="intermediate")
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=20)
    p0 = _sweep(booster, 0)
    assert np.all(np.diff(p0) >= -1e-6), "monotone +1 violated"
    p1 = _sweep(booster, 1)
    assert np.all(np.diff(p1) <= 1e-6), "monotone -1 violated"
    # 2-d monotonicity on a grid: fix x1, vary x0 and vice versa
    g = np.zeros((40, 4))
    g[:, 0] = np.linspace(-2, 2, 40)
    for x1 in (-1.5, 0.0, 1.5):
        g[:, 1] = x1
        pv = booster.predict(g)
        assert np.all(np.diff(pv) >= -1e-6)


def test_monotone_intermediate_beats_basic():
    """The reference's motivation for the mode (test_engine.py:1256-style):
    basic's midpoint bounds over-constrain, so intermediate must fit the
    same monotone data at least as well — and strictly better on data
    designed to expose the over-constraint (a steep monotone step plus a
    strong secondary feature)."""
    rng = np.random.RandomState(3)
    n = 3000
    X = rng.uniform(-2, 2, size=(n, 3))
    # steep monotone step in x0 + large additive x1 effect: basic's
    # midpoint propagation forces wide dead zones around the step
    y = (4.0 * (X[:, 0] > 0) + X[:, 0] + 2.5 * np.sin(2 * X[:, 1])
         + 0.1 * rng.normal(size=n))

    def fit(method):
        params = {"objective": "regression", "num_leaves": 31,
                  "min_data_in_leaf": 5, "verbosity": -1,
                  "monotone_constraints": [1, 0, 0],
                  "monotone_constraints_method": method}
        ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
        booster = lgb.train(params, ds, num_boost_round=30)
        mse = float(np.mean((booster.predict(X) - y) ** 2))
        return mse, booster

    mse_basic, _ = fit("basic")
    mse_inter, b_inter = fit("intermediate")
    assert mse_inter <= mse_basic * 1.001, (mse_basic, mse_inter)
    assert mse_inter < mse_basic * 0.95, (
        "intermediate should fit notably better here", mse_basic, mse_inter)
    # and the constraint still holds
    g = np.zeros((50, 3))
    g[:, 0] = np.linspace(-2, 2, 50)
    for x1 in (-1.0, 1.0):
        g[:, 1] = x1
        assert np.all(np.diff(b_inter.predict(g)) >= -1e-6)


def test_advanced_child_bounds_match_bruteforce_oracle():
    """advanced_child_bounds vs a brute-force oracle applying the
    slice-contiguity definition directly: l' bounds a child region when it
    overlaps the region in every feature except exactly one monotone
    feature where it lies strictly on one side (the semantics of the
    reference's AdvancedLeafConstraints threshold-sliced constraints,
    monotone_constraints.hpp:856-1171)."""
    import jax.numpy as jnp
    from lightgbm_tpu.models.grower import advanced_child_bounds, F32_MAX

    rng = np.random.RandomState(3)
    F, B = 4, 16
    monotone = np.array([1, -1, 0, 1], np.int8)
    mono_features = (0, 1, 3)

    # build leaf boxes by random axis-aligned splits of the bin space
    boxes = [(np.zeros(F, np.int64), np.full(F, B - 1, np.int64))]
    for _ in range(12):
        i = rng.randint(len(boxes))
        lo, hi = boxes[i]
        g = rng.randint(F)
        if hi[g] <= lo[g]:
            continue
        t = rng.randint(lo[g], hi[g])          # split bin in [lo, hi-1]
        llo, lhi = lo.copy(), hi.copy()
        rlo, rhi = lo.copy(), hi.copy()
        lhi[g] = t
        rlo[g] = t + 1
        boxes[i] = (llo, lhi)
        boxes.append((rlo, rhi))
    L = 16
    lo = np.zeros((L, F), np.int32)
    hi = np.full((L, F), B - 1, np.int32)
    act = np.zeros(L, bool)
    for i, (blo, bhi) in enumerate(boxes):
        lo[i], hi[i] = blo, bhi
        act[i] = True
    out = rng.normal(size=L)

    lmin, lmax, rmin, rmax = (np.asarray(a) for a in advanced_child_bounds(
        jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(out, jnp.float32),
        jnp.asarray(act), jnp.asarray(monotone), B, mono_features))

    def oracle(l, g, t, side):
        # child region of leaf l after splitting feature g at bin t
        rlo, rhi = lo[l].copy(), hi[l].copy()
        if side == "left":
            rhi[g] = t
        else:
            rlo[g] = t + 1
        mn, mx = -np.inf, np.inf
        for lp in range(L):
            if not act[lp] or lp == l:
                continue
            seps = []
            ok = True
            for f2 in range(F):
                overlap = lo[lp, f2] <= rhi[f2] and rlo[f2] <= hi[lp, f2]
                if not overlap:
                    if monotone[f2] == 0:
                        ok = False
                        break
                    seps.append(f2)
            if not ok or len(seps) != 1:
                continue
            m = seps[0]
            below = hi[lp, m] < rlo[m]
            if (monotone[m] > 0) == below:
                mn = max(mn, out[lp])
            else:
                mx = min(mx, out[lp])
        return mn, mx

    checked = 0
    for l in range(L):
        if not act[l]:
            continue
        for g in range(F):
            for t in range(lo[l, g], hi[l, g]):      # valid split bins
                omn, omx = oracle(l, g, t, "left")
                vmn = lmin[l, g, t] if lmin[l, g, t] > -F32_MAX / 2 else -np.inf
                vmx = lmax[l, g, t] if lmax[l, g, t] < F32_MAX / 2 else np.inf
                assert np.isclose(vmn, omn, rtol=1e-6) or (
                    np.isinf(omn) and np.isinf(vmn)), (l, g, t, vmn, omn)
                assert np.isclose(vmx, omx, rtol=1e-6) or (
                    np.isinf(omx) and np.isinf(vmx)), (l, g, t, vmx, omx)
                omn, omx = oracle(l, g, t, "right")
                vmn = rmin[l, g, t] if rmin[l, g, t] > -F32_MAX / 2 else -np.inf
                vmx = rmax[l, g, t] if rmax[l, g, t] < F32_MAX / 2 else np.inf
                assert np.isclose(vmn, omn, rtol=1e-6) or (
                    np.isinf(omn) and np.isinf(vmn)), ("R", l, g, t, vmn, omn)
                assert np.isclose(vmx, omx, rtol=1e-6) or (
                    np.isinf(omx) and np.isinf(vmx)), ("R", l, g, t, vmx, omx)
                checked += 2
    assert checked > 200


def test_monotone_advanced_enforced(reg_data):
    X, y = reg_data
    params = dict(objective="regression", num_leaves=15,
                  min_data_in_leaf=20, verbosity=-1,
                  monotone_constraints=[1, -1, 0, 0],
                  monotone_constraints_method="advanced")
    b = lgb.train(params, lgb.Dataset(X, label=y), 12)
    rng = np.random.RandomState(0)
    base = rng.uniform(-1, 1, size=(40, X.shape[1]))
    grid = np.linspace(-1, 1, 25)
    for feat, sign in ((0, 1), (1, -1)):
        preds = []
        for g in grid:
            Xg = base.copy()
            Xg[:, feat] = g
            preds.append(b.predict(Xg))
        d = np.diff(np.asarray(preds), axis=0) * sign
        assert (d >= -1e-10).all(), (feat, float(d.min()))


def test_monotone_advanced_at_least_intermediate():
    """Advanced (threshold-sliced) constraints are never more restrictive
    than intermediate leaf-level bounds in aggregate: the fit should be at
    least as good on a monotone-constrained problem."""
    rng = np.random.RandomState(11)
    n = 2500
    X = rng.uniform(-1, 1, size=(n, 4))
    y = (2 * X[:, 0] - 1.5 * X[:, 1] + np.sin(3 * X[:, 2])
         + 0.1 * rng.normal(size=n))

    def fit(method):
        b = lgb.train({"objective": "regression", "num_leaves": 31,
                       "min_data_in_leaf": 20, "verbosity": -1,
                       "monotone_constraints": [1, -1, 0, 0],
                       "monotone_constraints_method": method},
                      lgb.Dataset(X, label=y), 25)
        return float(np.mean((b.predict(X) - y) ** 2))

    mse_inter = fit("intermediate")
    mse_adv = fit("advanced")
    assert mse_adv <= mse_inter * 1.02, (mse_adv, mse_inter)


def test_monotone_advanced_data_parallel():
    """advanced mode under tree_learner=data (feature-sharded search): the
    per-threshold bound tensors are sliced to each shard's feature window.
    Regression test for a trace-time shape crash; exact serial equality is
    not asserted because the data learner's psum reduction order perturbs
    near-tied gains for EVERY monotone mode (pre-existing f32 property)."""
    rng = np.random.RandomState(13)
    n = 1200
    X = rng.uniform(-1, 1, size=(n, 4))
    y = (2 * X[:, 0] - X[:, 1] + 0.3 * np.sin(3 * X[:, 2])
         + 0.1 * rng.normal(size=n))
    base = {"objective": "regression", "num_leaves": 15,
            "min_data_in_leaf": 20, "verbosity": -1,
            "monotone_constraints": [1, -1, 0, 0],
            "monotone_constraints_method": "advanced",
            "histogram_method": "scatter"}
    b_serial = lgb.train({**base, "tree_learner": "serial"},
                         lgb.Dataset(X, label=y), 8)
    b_data = lgb.train({**base, "tree_learner": "data"},
                       lgb.Dataset(X, label=y), 8)
    np.testing.assert_allclose(b_serial.predict(X), b_data.predict(X),
                               rtol=0.05, atol=0.05)
    # monotonicity holds under the sharded search
    grid = np.linspace(-1, 1, 25)
    pts = rng.uniform(-1, 1, size=(40, 4))
    for feat, sign in ((0, 1), (1, -1)):
        preds = []
        for g in grid:
            Xg = pts.copy()
            Xg[:, feat] = g
            preds.append(b_data.predict(Xg))
        d = np.diff(np.asarray(preds), axis=0) * sign
        assert (d >= -1e-10).all(), (feat, float(d.min()))
