#!/bin/sh
# Run the test suite one pytest process per file. Isolates the XLA-CPU
# compiler's many-programs segfault (see conftest.py) and makes a crash
# attributable to a single file instead of killing the whole run.
set -u
fail=0
for f in "$(dirname "$0")"/test_*.py; do
  echo "=== $f"
  python -u -m pytest "$f" -q --no-header || fail=1
done
# supervisor gang-restart + elastic + integrity smoke (fast knobs,
# ~90 s): kill a rank mid-iter -> relaunch from checkpoint ->
# bit-identical final model; fail a rank's spawn permanently -> gang
# shrinks to world size 1 and completes (the shrink recorded in the
# SupervisorReport); flip one score-cache bit on rank 1 of a 3-rank gang
# -> the cross-rank divergence vote names exactly that rank (exit 95) ->
# the supervisor restores the gang from the last valid checkpoint ->
# training completes with model text bit-identical to the fault-free run
echo "=== scripts/supervisor_smoke.py"
python -u "$(dirname "$0")/../scripts/supervisor_smoke.py" || fail=1
# Pallas histogram-kernel roofline smoke (fast knobs, ~30 s on CPU): runs
# all three modes x {full, in-kernel gather} through the interpreter at a
# tiny shape and asserts the modeled fused-vs-XLA traffic ratio >= 5x
echo "=== scripts/kernel_bench.py"
python -u "$(dirname "$0")/../scripts/kernel_bench.py" --fast --interpret \
  || fail=1
# compile-wall smoke (~20 s, CPU backend): cold process trains K=4
# blocks-per-dispatch against a fresh persistent compile cache +
# checkpoint; a SECOND process resumes from the checkpoint against the
# same cache and must perform ZERO fused-step XLA compiles (disk hits
# only) while continuing bit-identically to an uninterrupted run — the
# supervisor-relaunch warm path at its smallest shape
echo "=== scripts/compile_wall_smoke.py"
python -u "$(dirname "$0")/../scripts/compile_wall_smoke.py" || fail=1
# serving-layer end-to-end smoke (fast knobs, ~10 s): concurrent mixed
# load coalesces bit-identically -> injected slow dispatch produces a
# phase-named timeout + a retriable shed in the health gauges -> corrupt
# hot-swap candidate rejected with the old model serving -> valid
# candidate swaps in bit-identical to a cold load
echo "=== scripts/serve_smoke.py"
python -u "$(dirname "$0")/../scripts/serve_smoke.py" || fail=1
# streaming-construct smoke (fast knobs, ~20 s on CPU): chunked
# two-pass construct -> 3 boosting rounds, bit-identical mappers/bins/
# model text vs monolithic; raw-chunk residency <= 2 chunks (weakref
# census + construct_peak_bytes gauge); sketch/bin/h2d telemetry on
# record; compacted-sketch rank error within the documented budget;
# free_dataset / construct re-entry audited on the chunked path
echo "=== scripts/construct_smoke.py"
python -u "$(dirname "$0")/../scripts/construct_smoke.py" || fail=1
# telemetry smoke (fast knobs, ~20 s on CPU): kill-at-iteration flushes
# a flight-recorder JSONL that schema-validates and names the in-flight
# iteration; a clean run flushes at train end with the health snapshot
# referencing the JSONL; a trace_window capture around two boosting
# iterations writes perfetto artifacts (or records the profiler error —
# jax.profiler no-op tolerance); the Prometheus exposition renders
echo "=== scripts/telemetry_smoke.py"
python -u "$(dirname "$0")/../scripts/telemetry_smoke.py" || fail=1
# post-mortem smoke (fast knobs, ~40 s on CPU): a 2-process supervised
# gang has rank 1 hard-killed with no restart budget -> GangFailedError
# carries an auto-generated post-mortem classifying the failure 'kill'
# and naming rank 1; rerunning scripts/postmortem.py offline over the
# diag dir reaches the same verdict (the operator workflow)
echo "=== scripts/postmortem_smoke.py"
python -u "$(dirname "$0")/../scripts/postmortem_smoke.py" || fail=1
# bench regression gate self-check (<5 s, no jax): identical round
# passes, a synthetic regression exits 1, a CPU-fallback round against
# a TPU baseline is refused with exit 2, AUC gates on absolute deltas,
# per-metric overrides work, the BENCH_rNN wrapper shape parses
echo "=== scripts/bench_compare.py --self-check"
python -u "$(dirname "$0")/../scripts/bench_compare.py" --self-check \
  || fail=1
# serve bench smoke (fast knobs, ~15 s on CPU): open-loop mixed-size load
# through the micro-batching frontend; asserts it completes and reports
# serve_p50_ms / serve_p99_ms / serve_rows_per_sec / serve_shed_count JSON
echo "=== bench_serve.py --fast"
python -u "$(dirname "$0")/../bench_serve.py" --fast || fail=1
exit $fail
