"""Fused-iteration coverage for the widened one-dispatch fast path (PR 3).

Bit-parity regressions for every config newly admitted to the fused
gradients -> growth -> score-update program (models/gbdt.py _fused_ok):
multiclass (K > 1), the data/feature/voting parallel learners on the
virtual 8-device mesh, the bagging subset copy, CEGB, and forced splits —
each fused run's model text must equal the unfused phase-by-phase run's
bit for bit (``fused_iteration=false`` is the reference side; the dumped
param line itself is the one intended difference).

Plus the telemetry this PR adds: dispatches/host-bytes per iteration
(utils/profiling.py install_dispatch_hook) and the data/voting learners'
collective receive volume (GrowAux.coll_bytes).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils import profiling

from test_grower import _make_meta, _make_params


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(42)
    # deliberately NOT divisible by the 8-device mesh (exercises padding)
    X = rng.normal(size=(900, 8)).astype(np.float64)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    y3 = np.digitize(X[:, 0] + 0.3 * X[:, 2], [-0.5, 0.5]).astype(np.float64)
    return X, y, y3


def _strip(model_text: str) -> str:
    """Drop the one INTENDED difference between the two runs' dumps."""
    return "\n".join(l for l in model_text.splitlines()
                     if not l.startswith("[fused_iteration"))


def _fit(X, y, extra, nround):
    p = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 10,
         "verbosity": -1}
    p.update(extra)
    return lgb.train(p, lgb.Dataset(X, label=y, params=p), nround)


def _assert_parity(X, y, extra, nround=3):
    fused = _fit(X, y, extra, nround)
    plain = _fit(X, y, {**extra, "fused_iteration": False}, nround)
    assert fused._boosting._fused_cache, "fused path did not engage"
    assert not plain._boosting._fused_cache, "unfused run engaged fused"
    assert _strip(fused.model_to_string()) == _strip(plain.model_to_string())
    np.testing.assert_array_equal(fused.predict(X[:64]), plain.predict(X[:64]))
    return fused, plain


# --------------------------------------------------- newly admitted configs
def test_fused_parity_multiclass(data):
    """K > 1: all class trees grow inside ONE program (lax.scan over the
    class axis) — bit-identical to the per-class unfused loop."""
    X, _, y3 = data
    fused, _ = _assert_parity(
        X, y3, {"objective": "multiclass", "num_class": 3})
    assert len(fused._boosting.trees) == 3 * 3   # nround x num_class


@pytest.mark.slow
def test_fused_parity_multiclassova(data):
    X, _, y3 = data
    _assert_parity(X, y3, {"objective": "multiclassova", "num_class": 3})


def test_fused_parity_bagging_subset(data):
    """The bagging subset copy (gbdt.cpp:810-818) drawn in-program from
    the period-start key, vs the host-side _update_bagging draw."""
    X, y, _ = data
    fused, plain = _assert_parity(
        X, y, {"bagging_fraction": 0.4, "bagging_freq": 2})
    assert plain._boosting._bag_sub is not None   # subset path active
    assert fused._boosting._bag_sub is None       # never left the device


@pytest.mark.slow
def test_fused_parity_bagging_mask_posneg(data):
    X, y, _ = data
    _assert_parity(X, y, {"pos_bagging_fraction": 0.7,
                          "neg_bagging_fraction": 0.9, "bagging_freq": 2})


def test_fused_parity_cegb(data):
    """CEGB's cross-iteration used-feature aux as device-resident fused
    loop state (operand in, operand out)."""
    X, y, _ = data
    _assert_parity(X, y, {"cegb_tradeoff": 0.9, "cegb_penalty_split": 0.01,
                          "cegb_penalty_feature_coupled": [0.1] * 8})


@pytest.mark.slow
def test_fused_parity_forced_splits(data, tmp_path):
    X, y, _ = data
    fn = tmp_path / "forced.json"
    fn.write_text(json.dumps({"feature": 0, "threshold": 0.0}))
    _assert_parity(X, y, {"forcedsplits_filename": str(fn)})


@pytest.mark.parametrize("mode,extra", [
    ("data", {}),
    ("feature", {}),
    # the voting cell rides the slow tier: the fused embedding it shares
    # with data/feature stays tier-1 above, and voting-specific behavior
    # is pinned tier-1 by the mesh-8 voting collective-volume regression
    # below (plus the full voting matrix in test_distributed.py, slow)
    pytest.param("voting", {"top_k": 3}, marks=pytest.mark.slow),
])
def test_fused_parity_parallel(data, mode, extra):
    """The parallel learners' fused step embeds the SAME shard_map'd
    grower the unfused path dispatches (ParallelGrower.get_shard_fn) —
    one program per iteration over the virtual mesh."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    X, y, _ = data
    fused, _ = _assert_parity(X, y, {"tree_learner": mode, **extra},
                              nround=3)
    coll = fused._boosting.coll_bytes_total
    if mode == "feature":
        assert coll == 0.0    # only the O(L)-scalar best-split sync
    else:
        assert coll > 0.0     # data/voting move histogram planes


@pytest.mark.slow
def test_fused_parity_data_multiclass(data):
    """Multiclass x data-parallel: the scan over classes wraps the
    shard_map'd grower."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    X, _, y3 = data
    _assert_parity(X, y3, {"tree_learner": "data", "objective": "multiclass",
                           "num_class": 3}, nround=3)


@pytest.mark.slow
def test_fused_resume_unfused_midperiod_bagging(data):
    """Switching fused -> unfused mid-bagging-period re-derives the same
    mask (the period-start key draw): train 2 fused iters, flip the gate,
    continue unfused — identical to the all-unfused run."""
    X, y, _ = data
    p = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 10,
         "bagging_fraction": 0.8, "bagging_freq": 4, "verbosity": -1}
    b = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
    for _ in range(2):
        b.update()
    assert b._boosting._fused_cache
    b._boosting.config.fused_iteration = False    # mid-period flip
    for _ in range(2):
        b.update()
    plain = _fit(X, y, {"bagging_fraction": 0.8, "bagging_freq": 4,
                        "fused_iteration": False}, 4)
    assert _strip(b.model_to_string()) == _strip(plain.model_to_string())


@pytest.mark.slow
def test_fused_bynode_reset_parameter_parity(data):
    """A reset_parameter change to feature_fraction_bynode mid-training
    must retrace the fused step (the fraction is a closed-over constant,
    keyed in the fused cache) — review finding: without the key the
    cached program silently kept the old fraction."""
    from lightgbm_tpu import callback
    X, y, _ = data
    sched = [0.9, 0.9, 0.3, 0.3, 0.3]

    def fit(fused):
        p = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 10,
             "feature_fraction_bynode": 0.9, "verbosity": -1,
             "fused_iteration": fused}
        cbs = [callback.reset_parameter(feature_fraction_bynode=sched)]
        return lgb.train(p, lgb.Dataset(X, label=y, params=p), len(sched),
                         callbacks=cbs)

    b1, b0 = fit(True), fit(False)
    assert b1._boosting._fused_cache
    assert _strip(b1.model_to_string()) == _strip(b0.model_to_string())


# ----------------------------------------------------- dispatch telemetry
@pytest.fixture
def dispatch_hook():
    """Install the counting hooks for one test, then restore the jax
    fastpath so the rest of the suite doesn't pay the Python round trip."""
    if not profiling.install_dispatch_hook():
        pytest.skip("jax internals hook unavailable on this version")
    yield
    profiling.uninstall_dispatch_hook()


def test_dispatch_telemetry_fused_vs_unfused(data, dispatch_hook):
    """The acceptance numbers: a fused iteration is <= 2 compiled-program
    dispatches (the grow step + the donated score add); the unfused path
    pays 3+ (gradients, growth, finalize/score eager ops). Guards the
    one-dispatch property against regression."""
    X, y, _ = data

    def measure(extra, n_meas=3):
        p = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 10,
             "verbosity": -1}
        p.update(extra)
        b = lgb.Booster(params=p,
                        train_set=lgb.Dataset(X, label=y, params=p))
        for _ in range(2):                       # warmup (compile)
            b.update()
        _ = float(np.asarray(b._boosting.train_score).ravel()[0])
        before = profiling.dispatch_stats()
        for _ in range(n_meas):
            b.update()
        # snapshot BEFORE any sync fetch: dispatches count at call time
        delta = profiling.dispatch_delta(before)
        return delta["dispatches"] / n_meas

    assert measure({}) <= 2.0
    assert measure({"fused_iteration": False}) >= 3.0


def test_dispatch_telemetry_counts_transfers(dispatch_hook):
    before = profiling.dispatch_stats()
    arr = jnp.asarray(np.ones((1000,), np.float32))   # host -> device
    _ = jax.device_get(arr)                           # device -> host
    d = profiling.dispatch_delta(before)
    assert d["h2d_bytes"] >= 4000
    assert d["d2h_bytes"] >= 4000
    assert d["device_gets"] >= 1


# ------------------------------------------------- collective volume
def _grow_parallel(mode, d, n, f=8, B=16, top_k=2):
    """One L=2 tree via ParallelGrower on a d-device mesh: exactly one
    histogram tile pass (root) + one split phase, so the expected
    collective volume is a closed formula."""
    from lightgbm_tpu.parallel.data_parallel import make_mesh
    from lightgbm_tpu.parallel.learners import ParallelGrower
    rng = np.random.RandomState(3)
    bins = rng.randint(0, B, size=(n, f)).astype(np.uint8)
    grad = rng.normal(size=n).astype(np.float32)
    hess = np.ones(n, dtype=np.float32)
    meta, missing_bin = _make_meta([B] * f)
    params = _make_params(min_data=5)
    pg = ParallelGrower(mode, mesh=make_mesh(d), axis="data")
    _tree, _leaf, aux = pg(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones((n,), jnp.float32), meta, params,
        jnp.ones((f,), jnp.float32), jnp.asarray(missing_bin),
        max_leaves=2, num_bins=B, hist_method="scatter",
        vote_top_k=top_k)
    return float(aux.coll_bytes)


def _data_volume_expected(d, L=2, f=8, B=16, S=3, itemsize=4):
    """Histogram size / devices — the ReduceScatter design volume."""
    return L * f * B * S * itemsize / d


def _voting_volume_expected(top_k, L=2, f=8, B=16, S=3, itemsize=4):
    """Vote-tally allreduce + elected 2k-column histogram sum."""
    return L * f * 4 + L * min(2 * top_k, f) * B * S * itemsize


@pytest.mark.parametrize("d", [1, 2, 4])
@pytest.mark.slow
def test_collective_volume_data_learner_small_meshes(d):
    """Row-count independence (the n=1024 re-run) + the /d formula at the
    remaining mesh sizes — the slow half of the mesh-1/2/4/8 sweep."""
    if len(jax.devices()) < d:
        pytest.skip(f"needs {d} virtual devices")
    assert _grow_parallel("data", d, n=256) == _data_volume_expected(d)
    assert _grow_parallel("data", d, n=1024) == _data_volume_expected(d)


def test_collective_volume_data_learner(data):
    """Data learner: per-iteration psum_scatter receive volume ==
    histogram size / devices, independent of row count (the reference
    ReduceScatter's bytes, data_parallel_tree_learner.cpp:184-186) —
    the scaling-efficiency evidence VERDICT item 7 asked for. Mesh sizes
    1/2/4 and the row-independence re-runs live in the slow tier (same
    formula, one shard-program compile each)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    assert _grow_parallel("data", 8, n=256) == _data_volume_expected(8)


@pytest.mark.parametrize("d", [1, 2, 4, 8])
@pytest.mark.slow
def test_collective_volume_voting_rows_independent(d):
    if len(jax.devices()) < d:
        pytest.skip(f"needs {d} virtual devices")
    assert _grow_parallel("voting", d, n=1024, top_k=2) == \
        _voting_volume_expected(2)


def test_collective_volume_voting_learner(data):
    """Voting learner: the vote-tally allreduce plus the elected 2k
    columns' histogram sum (GlobalVoting/CopyLocalHistogram,
    voting_parallel_tree_learner.cpp:151-184) — independent of BOTH rows
    and mesh size, the whole point of PV-tree. Mesh sizes 1/2/4 and the
    row-independence re-runs live in the slow tier."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    assert _grow_parallel("voting", 8, n=256, top_k=2) == \
        _voting_volume_expected(2)


def test_collective_volume_zero_for_serial(data):
    """Serial growth moves no histogram bytes between devices (the
    feature learner's zero is asserted where its program is already
    compiled — see test_fused_parity_parallel)."""
    X, y, _ = data
    b = _fit(X, y, {}, 2)
    assert b._boosting.coll_bytes_total == 0.0
