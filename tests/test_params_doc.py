"""The generated parameter docs must stay current (the reference's CI
checks config_auto.cpp / Parameters.rst are regenerated; SURVEY §2.1
helpers/parameter_generator.py)."""

import subprocess
import sys


def test_parameters_md_is_current():
    r = subprocess.run(
        [sys.executable, "scripts/gen_params_doc.py", "--check"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
