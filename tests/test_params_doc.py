"""The generated parameter docs must stay current (the reference's CI
checks config_auto.cpp / Parameters.rst are regenerated; SURVEY §2.1
helpers/parameter_generator.py)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parameters_md_is_current():
    # absolute path: another test in the same pytest process may have
    # changed the working directory
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "gen_params_doc.py"),
         "--check"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
