"""Differential tests: jitted grower vs brute-force numpy oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
from lightgbm_tpu.models.grower import grow_tree
from lightgbm_tpu.models.tree import predict_leaf_bins

from reference_impl import grow_tree_reference


def _make_params(l1=0.0, l2=0.0, min_data=1, min_hess=1e-3, min_gain=0.0):
    f32 = jnp.float32
    return SplitParams(
        lambda_l1=f32(l1), lambda_l2=f32(l2), max_delta_step=f32(0.0),
        path_smooth=f32(0.0), min_data_in_leaf=f32(min_data),
        min_sum_hessian_in_leaf=f32(min_hess), min_gain_to_split=f32(min_gain),
        cat_l2=f32(10.0), cat_smooth=f32(10.0),
        max_cat_threshold=jnp.int32(32), min_data_per_group=f32(100.0),
        max_cat_to_onehot=jnp.int32(4), monotone_penalty=f32(0.0),
        cegb_tradeoff=f32(1.0), cegb_penalty_split=f32(0.0))


def _make_meta(num_bins, missing_types=None, default_bins=None):
    f = len(num_bins)
    nb = np.asarray(num_bins, dtype=np.int32)
    mt = np.asarray(missing_types if missing_types is not None else np.zeros(f),
                    dtype=np.int32)
    db = np.asarray(default_bins if default_bins is not None else np.zeros(f),
                    dtype=np.int32)
    mode_a = (nb > 2) & (mt != 0)
    missing_bin = np.where(mode_a & (mt == 2), nb - 1,
                           np.where(mode_a & (mt == 1), db, -1)).astype(np.int32)
    meta = FeatureMeta(
        num_bins=jnp.asarray(nb), missing_type=jnp.asarray(mt),
        default_bin=jnp.asarray(db),
        is_categorical=jnp.zeros((f,), dtype=bool),
        monotone=jnp.zeros((f,), dtype=jnp.int8),
        penalty=jnp.ones((f,), dtype=jnp.float32))
    return meta, missing_bin


def _run_both(bins, grad, hess, num_bins_per_feat, num_leaves, seed_missing=None,
              l1=0.0, l2=0.0, min_data=1, min_hess=1e-3, min_gain=0.0,
              hist_method="scatter", exact=True):
    """exact=True matches the oracle's strict best-first order even when the
    num_leaves budget binds (the batched mode deliberately deviates there)."""
    n, f = bins.shape
    mt = seed_missing if seed_missing is not None else np.zeros(f, dtype=np.int32)
    meta, missing_bin = _make_meta(num_bins_per_feat, mt)
    params = _make_params(l1, l2, min_data, min_hess, min_gain)
    B = int(max(num_bins_per_feat))
    tree, leaf_id, _aux = grow_tree(
        jnp.asarray(bins.astype(np.uint8)), jnp.asarray(grad, dtype=jnp.float32),
        jnp.asarray(hess, dtype=jnp.float32), jnp.ones((n,), dtype=jnp.float32),
        meta, params, jnp.ones((f,), dtype=jnp.float32),
        jnp.asarray(missing_bin),
        max_leaves=num_leaves, num_bins=B, hist_method=hist_method, exact=exact)
    ref_leaf, ref_values, ref_splits = grow_tree_reference(
        bins, grad.astype(np.float64), hess.astype(np.float64),
        num_bins_per_feat, mt, np.zeros(f, dtype=np.int64), missing_bin,
        num_leaves, l1, l2, min_data, min_hess, min_gain)
    return tree, np.asarray(leaf_id), ref_leaf, ref_values, ref_splits


def _partition_signature(leaf_id):
    """Order-independent signature: map rows -> canonical leaf label."""
    _, canon = np.unique(leaf_id, return_inverse=True)
    # canonicalize by first occurrence order
    first_seen = {}
    out = np.empty_like(leaf_id)
    nxt = 0
    for i, l in enumerate(leaf_id):
        if l not in first_seen:
            first_seen[l] = nxt
            nxt += 1
        out[i] = first_seen[l]
    return out


@pytest.mark.parametrize("hist_method", ["scatter", "binloop"])
def test_single_split_exact(hist_method):
    rng = np.random.RandomState(0)
    n = 200
    bins = rng.randint(0, 8, size=(n, 3))
    # target correlated with feature 0
    grad = (bins[:, 0] < 4).astype(np.float64) * 2 - 1
    hess = np.ones(n)
    tree, leaf_id, ref_leaf, ref_values, ref_splits = _run_both(
        bins, grad, hess, [8, 8, 8], num_leaves=2, hist_method=hist_method)
    assert int(tree.num_leaves) == 2
    assert len(ref_splits) == 1
    assert int(tree.node_feature[0]) == ref_splits[0][1]
    assert int(tree.node_threshold_bin[0]) == ref_splits[0][2]
    np.testing.assert_array_equal(_partition_signature(leaf_id),
                                  _partition_signature(ref_leaf))


@pytest.mark.parametrize("num_leaves", [4, 8, 16])
def test_multi_split_partition_matches_oracle(num_leaves):
    rng = np.random.RandomState(1)
    n, f = 500, 5
    bins = rng.randint(0, 16, size=(n, f))
    grad = rng.normal(size=n)
    hess = np.ones(n)
    tree, leaf_id, ref_leaf, ref_values, _ = _run_both(
        bins, grad, hess, [16] * f, num_leaves=num_leaves)
    assert int(tree.num_leaves) == len(ref_values)
    np.testing.assert_array_equal(_partition_signature(leaf_id),
                                  _partition_signature(ref_leaf))


def test_leaf_values_match_oracle():
    rng = np.random.RandomState(2)
    n, f = 400, 4
    bins = rng.randint(0, 10, size=(n, f))
    grad = rng.normal(size=n)
    hess = np.ones(n) + rng.uniform(size=n)
    tree, leaf_id, ref_leaf, ref_values, _ = _run_both(
        bins, grad, hess, [10] * f, num_leaves=6, l2=1.0)
    # match leaf values by row partition: for each jit leaf, find ref leaf of
    # its rows and compare values
    lv = np.asarray(tree.leaf_value)
    for leaf in np.unique(leaf_id):
        rows = leaf_id == leaf
        ref_leaves = np.unique(ref_leaf[rows])
        assert len(ref_leaves) == 1
        np.testing.assert_allclose(lv[leaf], ref_values[int(ref_leaves[0])],
                                   rtol=2e-4, atol=1e-6)


def test_min_data_in_leaf_respected():
    rng = np.random.RandomState(3)
    n = 300
    bins = rng.randint(0, 16, size=(n, 3))
    grad = rng.normal(size=n)
    hess = np.ones(n)
    min_data = 30
    tree, leaf_id, ref_leaf, ref_values, _ = _run_both(
        bins, grad, hess, [16] * 3, num_leaves=16, min_data=min_data)
    counts = np.bincount(leaf_id, minlength=int(tree.num_leaves))
    active = counts[:int(tree.num_leaves)]
    assert active.min() >= min_data
    assert int(tree.num_leaves) == len(ref_values)


def test_lambda_l1_l2_match_oracle():
    rng = np.random.RandomState(4)
    n = 400
    bins = rng.randint(0, 12, size=(n, 4))
    grad = rng.normal(size=n)
    hess = np.ones(n)
    tree, leaf_id, ref_leaf, ref_values, _ = _run_both(
        bins, grad, hess, [12] * 4, num_leaves=8, l1=0.5, l2=2.0, min_data=10)
    np.testing.assert_array_equal(_partition_signature(leaf_id),
                                  _partition_signature(ref_leaf))


def test_nan_missing_routing():
    rng = np.random.RandomState(5)
    n = 400
    nb = 10  # last bin (9) is the NaN bin
    bins = rng.randint(0, 9, size=(n, 2))
    nan_rows = rng.uniform(size=n) < 0.2
    bins[nan_rows, 0] = 9
    # make NaN rows strongly negative-gradient so routing matters
    grad = rng.normal(size=n)
    grad[nan_rows] -= 3.0
    hess = np.ones(n)
    mt = np.array([2, 0], dtype=np.int32)  # feature 0 has NaN missing
    tree, leaf_id, ref_leaf, ref_values, ref_splits = _run_both(
        bins, grad, hess, [nb, 9], num_leaves=4, seed_missing=mt)
    np.testing.assert_array_equal(_partition_signature(leaf_id),
                                  _partition_signature(ref_leaf))


def test_predict_leaf_consistency():
    """Traversal on the tree must reproduce the training partition."""
    rng = np.random.RandomState(6)
    n = 500
    bins = rng.randint(0, 16, size=(n, 4)).astype(np.uint8)
    grad = rng.normal(size=n)
    hess = np.ones(n)
    meta, missing_bin = _make_meta([16] * 4)
    params = _make_params(min_data=5)
    tree, leaf_id, _aux = grow_tree(
        jnp.asarray(bins), jnp.asarray(grad, dtype=jnp.float32),
        jnp.asarray(hess, dtype=jnp.float32), jnp.ones((n,), jnp.float32),
        meta, params, jnp.ones((4,), jnp.float32), jnp.asarray(missing_bin),
        max_leaves=8, num_bins=16)
    leaves = predict_leaf_bins(tree, jnp.asarray(bins), jnp.asarray(missing_bin))
    np.testing.assert_array_equal(np.asarray(leaves), np.asarray(leaf_id))


def test_batched_equals_exact_when_budget_not_binding():
    """Batched-round growth produces the identical tree when every positive-
    gain split fits in the budget (order independence; grower docstring)."""
    rng = np.random.RandomState(8)
    n = 300
    bins = rng.randint(0, 8, size=(n, 3))
    grad = rng.normal(size=n)
    hess = np.ones(n)
    # min_data large => tree terminates naturally well below num_leaves
    te, le, rl, rv, _ = _run_both(bins, grad, hess, [8] * 3, num_leaves=64,
                                  min_data=40, exact=True)
    tb, lb, _, _, _ = _run_both(bins, grad, hess, [8] * 3, num_leaves=64,
                                min_data=40, exact=False)
    assert int(te.num_leaves) == int(tb.num_leaves) == len(rv)
    np.testing.assert_array_equal(_partition_signature(le),
                                  _partition_signature(lb))
    np.testing.assert_array_equal(_partition_signature(le),
                                  _partition_signature(rl))


def test_no_split_when_constant_gradient_zero():
    n = 100
    bins = np.random.RandomState(7).randint(0, 8, size=(n, 2))
    grad = np.zeros(n)
    hess = np.ones(n)
    tree, leaf_id, ref_leaf, ref_values, _ = _run_both(
        bins, grad, hess, [8, 8], num_leaves=8)
    assert int(tree.num_leaves) == 1
    assert np.all(leaf_id == 0)


def test_bagging_subset_matches_mask():
    """grow_tree with a compacted bagging subset (sub_idx/sub_bins) must
    grow the identical tree as the mask formulation over the same selected
    rows (gbdt.cpp:810-818 subset copy semantics)."""
    rng = np.random.RandomState(23)
    n, f, b = 1200, 5, 16
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    grad = rng.normal(size=n).astype(np.float32)
    hess = np.ones(n, dtype=np.float32)
    sel = rng.uniform(size=n) < 0.4
    sub_idx = np.nonzero(sel)[0].astype(np.int32)
    meta, missing_bin = _make_meta([b] * f)
    params = _make_params(min_data=5)

    common = (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess))
    tree_m, leaf_m, _ = grow_tree(
        *common, jnp.asarray(sel.astype(np.float32)), meta, params,
        jnp.ones((f,), jnp.float32), jnp.asarray(missing_bin),
        max_leaves=8, num_bins=b)
    sub_bins = jnp.asarray(bins[sub_idx])
    tree_s, leaf_s, _ = grow_tree(
        *common, jnp.ones((n,), jnp.float32), meta, params,
        jnp.ones((f,), jnp.float32), jnp.asarray(missing_bin),
        max_leaves=8, num_bins=b,
        sub_idx=jnp.asarray(sub_idx), sub_bins=sub_bins,
        sub_binsT=jnp.asarray(np.ascontiguousarray(bins[sub_idx].T)))
    assert int(tree_m.num_leaves) == int(tree_s.num_leaves)
    np.testing.assert_array_equal(np.asarray(tree_m.node_feature),
                                  np.asarray(tree_s.node_feature))
    np.testing.assert_array_equal(np.asarray(tree_m.node_threshold_bin),
                                  np.asarray(tree_s.node_threshold_bin))
    np.testing.assert_allclose(np.asarray(tree_m.leaf_value),
                               np.asarray(tree_s.leaf_value),
                               rtol=1e-5, atol=1e-7)
    # full-row routing agrees (out-of-bag rows included in the score update)
    np.testing.assert_array_equal(np.asarray(leaf_m), np.asarray(leaf_s))
