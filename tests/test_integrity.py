"""Training-integrity layer suite: in-program numerics sentinels,
cross-rank divergence detection, and OOM-aware graceful degradation.

Three properties under test:

1. SENTINELS — ``check_numerics`` now runs WITH ``fused_iteration``: the
   fused step computes a packed NaN/Inf flag word in-program (gradients /
   hessians / histogram plane / leaf outputs / score delta) and the host
   fail-fasts naming iteration + source. Guard off => the grown trees are
   BIT-IDENTICAL to the pre-guard fused path, and the fused iteration
   stays at 2 dispatches with the guard on.
2. DIVERGENCE — every ``integrity_check_period`` iterations ranks
   exchange a model-state fingerprint (tree-structure hash + score-cache
   checksum over the rank's rows) and majority-vote mismatches; a
   bit-flipped rank in a 3-rank gang is named exactly, and the supervisor
   restores it from the last valid checkpoint bit-identically (the
   kill-the-job demo, tier-1 with fast knobs; the unsupervised spawn
   spelling and the budget-exhausted shrink ride the slow tier — their
   verdict mechanics are covered by the unit layer here).
3. OOM DEGRADATION — a RESOURCE_EXHAUSTED from the boosting step walks
   the documented ladder (smaller hist block -> XLA scatter -> chunked
   predict buckets) in order, records every event in health_snapshot()
   and the gauges, and the degraded configuration rides the trainer
   state (bit-identical-restart contract).
"""

import os
import tempfile

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import distributed, supervisor
from lightgbm_tpu.utils import faults, profiling
from lightgbm_tpu.utils.log import LightGBMError

pytestmark = pytest.mark.faults

BASE = {"objective": "binary", "num_leaves": 8, "min_data_in_leaf": 5,
        "verbosity": -1}


def _data(n=400, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    return X, y


def _trees(model_text: str) -> str:
    """The tree section of a model dump (the params header legitimately
    records guard flags like check_numerics; the trees must not move)."""
    return model_text.split("end of parameters", 1)[1]


def _fit(params, rounds=6, n=400):
    X, y = _data(n)
    p = dict(BASE, **params)
    return lgb.train(dict(p), lgb.Dataset(X, label=y, params=p), rounds)


# ===================================================== numerics sentinels
def test_sentinel_parity_fused_bit_identical():
    """Guard off => current fused path; guard on => same trees, bit for
    bit (the sentinel reductions ride the program epilogue and must not
    perturb growth), and the fused path is actually taken (the PR 3
    exclusion is lifted)."""
    b_off = _fit({})
    b_on = _fit({"check_numerics": True})
    assert b_on._boosting._fused_cache, \
        "check_numerics unexpectedly unfused the iteration"
    assert _trees(b_off.model_to_string()) == _trees(b_on.model_to_string())


def test_fused_ok_admits_check_numerics():
    X, y = _data()
    p = dict(BASE, check_numerics=True)
    b = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
    assert b._boosting._fused_ok(None)


def test_sentinel_catches_in_program_nan_fused():
    """The traced NaN injection (NAN_HIST fault) is invisible to host-side
    checks — only the in-program sentinel word can see it, and the error
    must name the iteration and the source."""
    with pytest.raises(LightGBMError) as ei:
        _fit({"check_numerics": True, "fault_nan_hist_at_iter": 2})
    msg = str(ei.value)
    assert "iteration 2" in msg
    assert "in-program sentinels" in msg
    assert "gradients" in msg


@pytest.mark.slow
def test_sentinel_nan_hist_unfused_host_check():
    """The unfused spelling of the same fault: the host-side counting
    check catches it (the two paths share the fault twin). Slow: tier-1
    siblings cover both halves — test_sentinel_catches_in_program_nan_fused
    (this fault twin, fused) and test_fault_tolerance.py::
    test_check_numerics_names_iteration_and_count (the unfused host-side
    counting check)."""
    with pytest.raises(LightGBMError) as ei:
        _fit({"check_numerics": True, "fused_iteration": False,
              "fault_nan_hist_at_iter": 1})
    assert "iteration 1" in str(ei.value)


@pytest.mark.slow
def test_sentinel_multiclass_fused():
    """Sentinels cover the multiclass lax.scan spelling too (per-class
    aux sentinels are summed into the flag word). Slow: tier-1 siblings
    cover the halves — test_sentinel_catches_in_program_nan_fused (the
    fused in-program catch, binary) and test_fused_wide.py::
    test_fused_parity_multiclass (the multiclass fused-scan growth)."""
    rng = np.random.RandomState(3)
    X = rng.normal(size=(300, 6))
    y = rng.randint(0, 3, size=300).astype(np.float64)
    p = dict(BASE, objective="multiclass", num_class=3,
             check_numerics=True, fault_nan_hist_at_iter=1)
    with pytest.raises(LightGBMError) as ei:
        lgb.train(dict(p), lgb.Dataset(X, label=y, params=p), 4)
    assert "iteration 1" in str(ei.value)


@pytest.fixture
def dispatch_hook():
    if not profiling.install_dispatch_hook():
        pytest.skip("jax internals hook unavailable on this version")
    yield
    profiling.uninstall_dispatch_hook()


def test_sentinel_dispatch_count_stays_two(dispatch_hook):
    """The acceptance number: the sentinel flag word rides the fused
    step's own results — check_numerics must not add a dispatch (still
    grow step + donated score add = 2)."""
    X, y = _data()
    p = dict(BASE, check_numerics=True)
    b = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
    for _ in range(2):
        b.update()
    _ = float(np.asarray(b._boosting.train_score).ravel()[0])
    before = profiling.dispatch_stats()
    n_meas = 3
    for _ in range(n_meas):
        b.update()
    delta = profiling.dispatch_delta(before)
    assert delta["dispatches"] / n_meas <= 2.0


def test_sentinel_flag_word_sources():
    """Bit -> source naming used by the fail-fast message."""
    X, y = _data(n=64)
    p = dict(BASE)
    b = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
    with pytest.raises(LightGBMError) as ei:
        b._boosting._check_sentinel_flags(0b10001)
    msg = str(ei.value)
    assert "gradients" in msg and "score delta" in msg
    assert "hessians" not in msg
    b._boosting._check_sentinel_flags(0)        # clean word: no raise


# ================================================== divergence: unit layer
def _entry(rank, trees="T", score="S", row_start=0, row_count=100):
    return {"rank": rank, "trees": trees, "score": score,
            "row_start": row_start, "row_count": row_count}


def test_verdict_world3_score_minority():
    """2 honest / 1 flipped at world 3: the minority rank is named, with
    a strict majority (not indeterminate)."""
    entries = [_entry(0), _entry(1, score="S'"), _entry(2)]
    corrupt, indet = distributed.divergence_verdict(entries)
    assert corrupt == [1] and not indet


def test_verdict_world3_tree_minority():
    entries = [_entry(0, trees="T'"), _entry(1), _entry(2)]
    corrupt, indet = distributed.divergence_verdict(entries)
    assert corrupt == [0] and not indet


def test_verdict_world2_indeterminate():
    """A 1:1 split has no majority: both ranks are implicated and the
    verdict is flagged indeterminate (restart the whole gang)."""
    entries = [_entry(0), _entry(1, score="S'")]
    corrupt, indet = distributed.divergence_verdict(entries)
    assert corrupt == [0, 1] and indet


def test_verdict_prepartitioned_disjoint_rows_not_compared():
    """Pre-partitioned ranks hold disjoint row ranges whose score
    checksums differ BY DESIGN — only the (rank-symmetric) tree hash may
    vote across them."""
    entries = [_entry(0, score="A", row_start=0, row_count=50),
               _entry(1, score="B", row_start=50, row_count=50),
               _entry(2, score="C", row_start=100, row_count=50)]
    corrupt, indet = distributed.divergence_verdict(entries)
    assert corrupt == [] and not indet
    entries[1]["trees"] = "T'"                  # but a tree mismatch votes
    corrupt, indet = distributed.divergence_verdict(entries)
    assert corrupt == [1] and not indet


def test_verdict_clean():
    corrupt, indet = distributed.divergence_verdict(
        [_entry(r) for r in range(4)])
    assert corrupt == [] and not indet


def test_flip_score_fault_is_one_bit_involution():
    """The FLIP_SCORE fault moves exactly one bit and undoes itself when
    applied twice (so the test harness can verify placement)."""
    import jax.numpy as jnp
    plan = faults.FaultPlan(flip_score_rank=(0, 3))
    s = jnp.asarray(np.arange(8, dtype=np.float32))
    assert faults.maybe_flip_score(plan, 2, s) is None      # wrong iter
    f1 = faults.maybe_flip_score(plan, 3, s)
    bits = (np.asarray(f1).view(np.uint32)
            ^ np.asarray(s).view(np.uint32))
    assert np.count_nonzero(bits) == 1 and bits.sum() == 1
    f2 = faults.maybe_flip_score(plan, 3, f1)
    assert np.array_equal(np.asarray(f2), np.asarray(s))


def test_model_fingerprint_moves_with_state():
    """The fingerprint is sensitive to both halves it claims to cover:
    score-cache bits and tree structure."""
    b = _fit({}, rounds=2, n=200)
    fp1 = distributed.model_fingerprint(b._boosting)
    import jax.numpy as jnp
    arr = np.array(np.asarray(b._boosting.train_score), copy=True)
    arr.reshape(-1).view(np.uint32)[0] ^= 1
    b._boosting.train_score = jnp.asarray(arr)
    fp2 = distributed.model_fingerprint(b._boosting)
    assert fp1["score"] != fp2["score"] and fp1["trees"] == fp2["trees"]
    b2 = _fit({}, rounds=3, n=200)
    assert distributed.model_fingerprint(b2._boosting)["trees"] \
        != fp1["trees"]


# ======================================== divergence: supervised gang demo
GANG_PARAMS = {"objective": "binary", "num_leaves": 8,
               "min_data_in_leaf": 5, "boost_from_average": False,
               "histogram_method": "scatter", "verbosity": -1,
               "integrity_check_period": 1,
               "heartbeat_interval": 0.4, "collective_deadline": 12.0}
GANG_ROUNDS = 3                     # flip fires after iter 2 (the last
                                    # round): fast knobs, same mechanics


def _gang_data():
    rng = np.random.RandomState(7)
    X = rng.normal(size=(320, 6))
    y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
    return X, y


def _integrity_gang_fn(rank, ckdir):
    """Module-level so distributed.spawn can pickle it: checkpointed,
    resumable replicated-serial training with the divergence check on."""
    import lightgbm_tpu as lgb
    X, y = _gang_data()
    ds = lgb.Dataset(X, label=y, params=dict(GANG_PARAMS),
                     free_raw_data=False)
    booster = lgb.train(dict(GANG_PARAMS), ds, GANG_ROUNDS,
                        callbacks=[lgb.checkpoint_callback(ckdir, period=1)],
                        resume_from=ckdir)
    return booster.model_to_string()


def _divergence_probe_fn(rank):
    """Unsupervised spelling: every rank must raise RankDivergenceError
    naming the flipped rank."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu import distributed as dist
    X, y = _gang_data()
    ds = lgb.Dataset(X, label=y, params=dict(GANG_PARAMS),
                     free_raw_data=False)
    try:
        lgb.train(dict(GANG_PARAMS), ds, GANG_ROUNDS)
        return ("no-error", None)
    except dist.RankDivergenceError as e:
        return ("diverged", (e.iteration, e.corrupt_ranks, e.indeterminate))


def _reference_gang_model() -> str:
    """Fault-free reference: the gang trains the SERIAL learner on
    replicated data, so every rank's model equals a plain single-process
    run with the same params."""
    X, y = _gang_data()
    ds = lgb.Dataset(X, label=y, params=dict(GANG_PARAMS),
                     free_raw_data=False)
    return lgb.train(dict(GANG_PARAMS), ds, GANG_ROUNDS).model_to_string()


@pytest.mark.slow
def test_supervised_corrupt_rank_restart_bit_identical():
    """The kill-the-job demo (fast knobs): one score-cache bit
    flipped on rank 1 of a 3-rank gang -> the divergence check names
    exactly that rank (exit DIVERGENCE_EXIT_CODE + a divergence diagnosis
    naming it), the supervisor restores the gang from the last valid
    checkpoint, and the final model text is BIT-IDENTICAL to the
    fault-free run's.

    Slow (the heaviest single tier-1 test at ~29 s): the identical
    3-rank FLIP_SCORE drill runs on every CI pass as stanza 3 of
    scripts/supervisor_smoke.py (tests/run_suite.sh), the vote logic
    stays tier-1 via the test_verdict_* unit tests above, and the same
    fault's artifact/classification spelling is tier-1 in
    test_postmortem.py::test_classify_flip_score_divergence (with the
    supervised-gang twin riding slow there as
    test_gang_flip_score_postmortem)."""
    ref = _reference_gang_model()
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "ck")
        os.environ["LGBM_TPU_FAULT_FLIP_SCORE_RANK"] = "1:2"
        try:
            report = supervisor.run_supervised(
                _integrity_gang_fn, nproc=3, args=(ck,),
                devices_per_proc=1, checkpoint_dir=ck, max_restarts=2,
                timeout=240)
        finally:
            os.environ.pop("LGBM_TPU_FAULT_FLIP_SCORE_RANK", None)
    assert report.restarts == 1
    assert report.failures[0].exit_codes.get(1) \
        == distributed.DIVERGENCE_EXIT_CODE
    assert "diverged" in report.failures[0].reason
    divs = [d for f in report.failures for d in f.watchdog
            if d.get("kind") == "divergence"]
    assert divs and divs[0]["corrupt_ranks"] == [1] \
        and divs[0]["rank"] == 1
    assert report.shrinks == []                 # budget 1: restart, not shrink
    assert report.result == ref


@pytest.mark.slow
def test_divergence_unsupervised_raises_everywhere():
    """Slow subprocess spelling (tier-1 siblings: the verdict unit layer
    + the supervised gang above): without a supervisor, every rank raises
    RankDivergenceError naming the flipped rank."""
    os.environ["LGBM_TPU_FAULT_FLIP_SCORE_RANK"] = "1:2"
    try:
        res = distributed.spawn(_divergence_probe_fn, nproc=3,
                                devices_per_proc=1, timeout=240)
    finally:
        os.environ.pop("LGBM_TPU_FAULT_FLIP_SCORE_RANK", None)
    assert res == ("diverged", (2, [1], False))


@pytest.mark.slow
def test_divergence_shrink_after_budget():
    """Slow subprocess spelling (tier-1 siblings: the supervised restart
    above + the supervisor-shrink suite): with rank_restart_budget=0 a
    single divergence classifies the rank permanently lost and the gang
    SHRINKS 3 -> 2 instead of retrying it."""
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "ck")
        os.environ["LGBM_TPU_FAULT_FLIP_SCORE_RANK"] = "1:2"
        try:
            report = supervisor.run_supervised(
                _integrity_gang_fn, nproc=3, args=(ck,),
                devices_per_proc=1, checkpoint_dir=ck, max_restarts=2,
                rank_restart_budget=0, timeout=300)
        finally:
            os.environ.pop("LGBM_TPU_FAULT_FLIP_SCORE_RANK", None)
    assert report.shrinks and report.shrinks[0].lost_ranks == [1]
    assert report.shrinks[0].from_nproc == 3 \
        and report.shrinks[0].to_nproc == 2
    assert report.world_size == 2
    assert report.result is not None


# ==================================================== OOM degradation
def test_oom_ladder_ordering_and_telemetry():
    """count=3 consecutive simulated RESOURCE_EXHAUSTEDs walk the ladder
    in the documented order (block -> scatter -> predict chunk), training
    completes on the 4th attempt, and every event lands in
    health_snapshot()/gauges."""
    b = _fit({"fault_oom_at_iter": 1, "fault_oom_count": 3}, rounds=4)
    bb = b._boosting
    assert bb._oom_level == 3
    # _init_train resets the process-level log, so these are exactly this
    # run's events (an earlier booster's history must not leak into a new
    # run's health snapshots / manifests)
    events = distributed.degradations()
    assert [e["level"] for e in events] == [1, 2, 3]
    assert "hist_block" in events[0]["action"]
    assert "scatter" in events[1]["action"]
    assert "predict_chunk_rows" in events[2]["action"]
    assert all(e["iteration"] == 1 for e in events)
    assert bb._oom_block > 0 and bb._oom_hm == "scatter" \
        and bb._oom_predict_chunk > 0
    assert bb._hist_method() == "scatter"
    health = distributed.health_snapshot()
    assert [e["action"] for e in health["degradations"][-3:]] \
        == [e["action"] for e in events]
    assert profiling.gauges().get("hist_oom_degrade_level") == 3.0
    # the degraded booster still trains and predicts
    X, _ = _data(n=50)
    assert b.predict(X).shape == (50,)


@pytest.mark.slow
def test_oom_ladder_exhausted_reraises():
    """A 4th consecutive OOM after the last rung re-raises: degradation
    is bounded, not an infinite retry loop. Slow: tier-1 siblings —
    test_oom_fallback_gate_off_reraises exercises the same re-raise exit
    and test_oom_ladder_ordering_and_telemetry walks every rung (the
    bound itself is the `_oom_level >= 3` check both paths share)."""
    with pytest.raises(faults.SimulatedResourceExhausted):
        _fit({"fault_oom_at_iter": 1, "fault_oom_count": 5}, rounds=4)


def test_oom_fallback_gate_off_reraises():
    with pytest.raises(faults.SimulatedResourceExhausted):
        _fit({"fault_oom_at_iter": 0, "fault_oom_count": 1,
              "hist_oom_fallback": False}, rounds=2)


def test_oom_classifier_matches_xla_not_everything():
    assert faults.is_resource_exhausted(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating"))
    assert faults.is_resource_exhausted(
        faults.SimulatedResourceExhausted("x"))
    assert not faults.is_resource_exhausted(ValueError("shape mismatch"))


@pytest.mark.slow
def test_oom_degrade_state_rides_trainer_state():
    """The degraded configuration is numerics (block size / method change
    accumulation shape): a resumed incarnation must reuse it — same
    contract as the measured histogram method. Slow: tier-1 sibling
    test_oom_predict_rung_independent_of_training_ladder asserts the same
    oom_degrade dict rides get_trainer_state (predict-rung case; the
    get/set round trip here adds the full-ladder level/block/hm
    fields)."""
    b = _fit({"fault_oom_at_iter": 1, "fault_oom_count": 2}, rounds=2,
             n=200)
    state = b._boosting.get_trainer_state()
    assert state["oom_degrade"]["level"] == 2
    X, y = _data(n=200)
    p = dict(BASE)
    b2 = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
    b2._boosting.set_trainer_state(state)
    assert b2._boosting._oom_level == 2
    assert b2._boosting._oom_hm == "scatter"
    assert b2._boosting._oom_block == b._boosting._oom_block
    # an undegraded run records nothing
    b3 = _fit({}, rounds=1, n=200)
    assert b3._boosting.get_trainer_state()["oom_degrade"] is None


@pytest.mark.slow
def test_oom_degraded_run_still_learns():
    """Degrading mid-run keeps the model usable: the scatter-degraded run
    produces the same tree COUNT and a finite, sane model (values differ
    from the undegraded run — accumulation order changed, which is the
    documented price of running degraded). Slow: tier-1 sibling
    test_oom_ladder_ordering_and_telemetry trains through the full
    ladder AND predicts from the degraded booster."""
    b = _fit({"fault_oom_at_iter": 2, "fault_oom_count": 2}, rounds=5)
    assert len(b._boosting.trees) == 5
    X, _ = _data(n=64)
    assert np.isfinite(b.predict(X, raw_score=True)).all()


def test_oom_training_ladder_single_process_only(monkeypatch):
    """Gangs FAIL-STOP on a training OOM: one rank degrading alone would
    change its accumulation numerics and be named corrupt by the
    divergence vote — the supervisor's restart/shrink path owns rank-
    local resource failures."""
    import jax
    b = _fit({}, rounds=1)
    bb = b._boosting
    monkeypatch.setattr(jax, "process_count", lambda: 3)
    exc = faults.SimulatedResourceExhausted("RESOURCE_EXHAUSTED: sim")
    assert bb._maybe_degrade_oom(exc, len(bb.trees)) is False
    assert bb._oom_level == 0


def test_oom_predict_rung_independent_of_training_ladder():
    """A serve-time OOM shrinks the predict chunk WITHOUT consuming the
    training ladder (predict chunking is numerics-exact): a later
    training OOM must still have rungs 1-3 available; and the
    predict-only degraded configuration still rides the trainer state."""
    b = _fit({}, rounds=2)
    bb = b._boosting
    exc = faults.SimulatedResourceExhausted("RESOURCE_EXHAUSTED: sim")
    assert bb._maybe_degrade_predict_oom(exc)
    assert bb._oom_level == 0 and bb._oom_predict_chunk > 0
    state = bb.get_trainer_state()
    assert state["oom_degrade"]["level"] == 0
    assert state["oom_degrade"]["predict_chunk"] == bb._oom_predict_chunk
    # ...and restores on a fresh incarnation (set-side of the contract;
    # the full-ladder fields ride the same dict — slow sibling)
    X, y = _data(n=200)
    p = dict(BASE)
    b2 = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
    b2._boosting.set_trainer_state(state)
    assert b2._boosting._oom_predict_chunk == bb._oom_predict_chunk
    # the training ladder starts at rung 1, untouched by the serve OOM
    assert bb._maybe_degrade_oom(exc, len(bb.trees))
    assert bb._oom_level == 1 and bb._oom_block > 0


def test_oom_fallback_method_mapping():
    from lightgbm_tpu.ops.histogram import oom_fallback_method
    assert oom_fallback_method("pallas_hilo") == "scatter"
    assert oom_fallback_method("onehot") == "scatter"
    assert oom_fallback_method("pallas_q8") == "onehot_q8"
    assert oom_fallback_method("onehot_q8") == "onehot_q8"
    from lightgbm_tpu.ops.pallas_hist import oom_shrink_block
    assert oom_shrink_block(0) == 512
    assert oom_shrink_block(2048) == 512
    assert oom_shrink_block(600) == 256
    assert oom_shrink_block(100) == 256


# ================================================ review-fix regressions
def test_growaux_unpickles_without_sentinel_field():
    """Pre-sentinel checkpoints pickled a 4-field GrowAux (the CEGB aux in
    state.pkl); the class must keep accepting 4 positional fields, and
    set_trainer_state must normalize the missing sentinel to a real array
    so the fused step's operand structure stays trace-stable."""
    import jax.numpy as jnp
    from lightgbm_tpu.models.grower import GrowAux
    old = GrowAux(jnp.zeros((3,), bool), jnp.zeros((1, 1), bool),
                  jnp.float32(0.0), jnp.float32(0.0))
    assert old.sentinel is None
    b = _fit({"cegb_tradeoff": 0.1}, rounds=2, n=200)
    state = b._boosting.get_trainer_state()
    assert state["cegb_aux"] is not None
    state["cegb_aux"] = type(state["cegb_aux"])(*state["cegb_aux"][:4])
    X, y = _data(n=200)
    p = dict(BASE, cegb_tradeoff=0.1)
    b2 = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
    b2._boosting.set_trainer_state(state)
    assert b2._boosting._cegb_aux.sentinel is not None
    assert float(b2._boosting._cegb_aux.sentinel) == 0.0


def test_step_retry_rearms_watchdog_clock():
    """The OOM degrade-and-retry loop re-arms the step clock
    (notify_step_retry): the retry phase carries a fresh timestamp and the
    ``step-retry:`` label the watchdog exempts (the retry recompiles the
    degraded programs), and completion accounting is untouched."""
    import time
    prog = distributed._progress
    prog.reset()
    distributed.notify_step_begin(5)
    time.sleep(0.05)
    distributed.notify_step_retry(5)
    snap = prog.snapshot()
    assert snap["phase"].startswith("step-retry:5")
    assert snap["phase_elapsed"] < 0.05       # fresh clock
    assert snap["steps_done"] == 0            # no phantom completion
    assert snap["step"] == 5                  # still reported in-flight
    distributed.notify_step_end(5)
    snap = prog.snapshot()
    assert snap["phase"] is None and snap["steps_done"] == 1
    prog.reset()


def test_checkpoint_callback_votes_before_save(tmp_path, monkeypatch):
    """A checkpoint written BETWEEN integrity votes must not capture
    uncertified state: with integrity_check_period on, the checkpoint
    callback runs the divergence vote before saving — unless engine.train
    already voted this very iteration (the dedup marker)."""
    from lightgbm_tpu.callback import CallbackEnv
    X, y = _data(n=200)
    p = dict(BASE, integrity_check_period=3)
    ds = lgb.Dataset(X, label=y, params=p)
    b = lgb.train(dict(p), ds, 2, keep_training_booster=True)
    calls = []
    monkeypatch.setattr(distributed, "check_model_integrity",
                        lambda boosting, it, **kw: calls.append(it))
    cb = lgb.checkpoint_callback(str(tmp_path / "ck"), period=1)
    env = CallbackEnv(model=b, params=dict(p), iteration=1,
                      begin_iteration=0, end_iteration=2,
                      evaluation_result_list=[])
    cb(env)
    assert calls == [1]
    # engine.train voted at this iteration already -> no second exchange
    b._boosting._integrity_checked_iter = 1
    cb(env)
    assert calls == [1]
