"""Streaming chunked dataset construction (ISSUE 14).

Parity bars:
- sketch-fitted BinMappers are BIT-IDENTICAL to the sampled
  ``find_bin_mappers`` whenever one chunk covers the sample (exact
  sketches, sample = all rows);
- compacted sketches stay within the documented rank-error budget
  (~2 * compactions / sketch_max_size);
- chunked-vs-monolithic construct trains to bit-identical model text
  (gbdt), on both the device f32 writer path and the f64 host fallback;
- host residency of raw chunk data is O(chunk): <= 2 chunks alive at
  any moment (weakref census) and the ``construct_peak_bytes`` gauge
  records it;
- the per-feature sketches JSON-round-trip bit-exactly and merge
  associatively — the ``distributed.exchange_host`` rank-merge protocol
  (exercised cross-process by the slow 2-rank test below).
"""

import json
import os
import subprocess
import sys
import weakref

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import binning
from lightgbm_tpu.config import Config
from lightgbm_tpu.utils import profiling


TRAIN = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbosity": -1}


def _data(rng, n=3000, f=6, dtype=np.float32):
    X = rng.normal(size=(n, f)).astype(dtype)
    X[:, f - 2] *= (rng.rand(n) < 0.3)          # zero-heavy column
    X[rng.rand(n) < 0.05, f - 1] = np.nan       # NaN column
    y = (np.nan_to_num(X[:, 0] + 0.5 * X[:, 1] - X[:, f - 2]) > 0) \
        .astype(np.float64)
    return X, y


def _mapper_json(mappers):
    return json.dumps([m.to_dict() for m in mappers])


def test_sketch_mappers_bit_identical_when_sample_fits_one_chunk(rng):
    """Exact sketches (no compaction, sample covers all rows) fit the
    SAME mappers as the sampled monolithic path — bit for bit."""
    X, y = _data(rng, n=2000, f=5)
    ds_m = lgb.Dataset(X.copy(), label=y, params={"verbosity": -1})
    ds_m.construct()
    chunks = [(X[s:s + 700], y[s:s + 700]) for s in range(0, len(X), 700)]
    ds_c = lgb.Dataset.from_chunks(chunks, params={"verbosity": -1})
    ds_c.construct()
    assert _mapper_json(ds_m.mappers) == _mapper_json(ds_c.mappers)
    assert np.array_equal(np.asarray(ds_m.bins), np.asarray(ds_c.bins))


@pytest.mark.slow
def test_chunked_vs_monolithic_model_text_identical(rng):
    """One monolithic reference training; BOTH streaming front ends —
    ``from_chunks`` and the ``construct_streaming``/``construct_chunk_rows``
    params on array input — must train to bit-identical model text, and
    the chunked dataset passes the free_dataset / re-entry audit (no
    stale raw or chunk-source reference pinned).

    Slow: the identical drill (chunked stream -> bit-identical mappers /
    bin matrix / model text vs monolithic + the free_dataset / re-entry
    audit) runs on every CI pass as scripts/construct_smoke.py
    (tests/run_suite.sh), and the mapper/bin-matrix parity mechanics
    stay tier-1 via
    test_sketch_mappers_bit_identical_when_sample_fits_one_chunk above
    and test_load_partitioned_chunks_single_process_parity below."""
    X, y = _data(rng, n=2000, f=5)
    b_m = lgb.train(dict(TRAIN),
                    lgb.Dataset(X.copy(), label=y, params={"verbosity": -1}),
                    num_boost_round=4)
    chunks = [(X[s:s + 700], y[s:s + 700]) for s in range(0, len(X), 700)]
    ds_c = lgb.Dataset.from_chunks(chunks, params={"verbosity": -1})
    b_c = lgb.train(dict(TRAIN), ds_c, num_boost_round=4)
    assert b_m.model_to_string() == b_c.model_to_string()
    ds_s = lgb.Dataset(X.copy(), label=y,
                       params={"verbosity": -1, "construct_streaming": True,
                               "construct_chunk_rows": 700})
    b_s = lgb.train(dict(TRAIN), ds_s, num_boost_round=4)
    assert b_m.model_to_string() == b_s.model_to_string()
    # free_dataset / construct re-entry audit on the chunked path
    assert ds_c.data is None and ds_c._chunk_source is None
    assert ds_c.raw_data_np is None
    assert ds_c.construct() is ds_c
    b_c.free_dataset()
    assert ds_c.bins is None and ds_c._chunk_source is None
    assert ds_c.label is None
    _ = b_c.predict(X[:32])                 # binning metadata survives


def test_host_fallback_f64_chunks_identical(rng):
    """Non-f32 chunks take the host per-chunk bin_data path: mappers and
    bin matrix bit-identical to the monolithic f64 construct (model-text
    parity for this path rides test_load_partitioned_chunks_* — which
    bins f64 chunks host-side — and scripts/construct_smoke.py)."""
    X, y = _data(rng, n=2000, f=5, dtype=np.float64)
    ds_m = lgb.Dataset(X.copy(), label=y, params={"verbosity": -1})
    ds_m.construct()
    chunks = [X[s:s + 700].astype(np.float64) for s in range(0, len(X), 700)]
    ds_c = lgb.Dataset.from_chunks(chunks, label=y,
                                   params={"verbosity": -1})
    ds_c.construct()
    assert _mapper_json(ds_m.mappers) == _mapper_json(ds_c.mappers)
    assert np.array_equal(np.asarray(ds_m.bins), np.asarray(ds_c.bins))


def test_valid_set_aligns_to_streaming_reference(rng):
    """A validation set referencing a streaming-constructed train set
    adopts its mappers (the dense alignment contract)."""
    X, y = _data(rng, n=2000, f=5)
    chunks = [(X[s:s + 700], y[s:s + 700]) for s in range(0, len(X), 700)]
    ds = lgb.Dataset.from_chunks(chunks, params={"verbosity": -1})
    Xv, yv = _data(np.random.RandomState(9), n=700, f=5)
    ev = {}
    lgb.train(dict(TRAIN), ds, num_boost_round=3,
              valid_sets=[ds.create_valid(Xv, label=yv)],
              valid_names=["v"], evals_result=ev)
    assert "v" in ev and len(next(iter(ev["v"].values()))) == 3


def test_sketch_compaction_rank_error_budget():
    """A compacted sketch's cumulative ranks stay within the documented
    ~2*compactions/max_size of exact, and the fitted mapper keeps a
    healthy bin count."""
    col = np.random.RandomState(5).normal(size=20000)
    sk = binning.FeatureSketch(max_size=256)
    for s in range(0, len(col), 2500):
        sk.fold(col[s:s + 2500])
    assert sk.compactions > 0 and len(sk.values) <= 256
    sv = np.sort(col)
    sketch_rank = np.cumsum(sk.counts) / sk.total_cnt
    true_rank = np.searchsorted(sv, sk.values, side="right") / len(col)
    err = float(np.max(np.abs(sketch_rank - true_rank)))
    assert err <= 2.0 * sk.compactions / sk.max_size, err
    cfg = Config.from_params({"verbosity": -1})
    m = binning.fit_mappers_from_sketches([sk], len(col), cfg)[0]
    assert m.num_bin > 200


def test_sketch_zero_slot_survives_compaction():
    rng = np.random.RandomState(2)
    col = np.where(rng.rand(10000) < 0.4, 0.0, rng.normal(size=10000))
    sk = binning.FeatureSketch(max_size=64)
    for s in range(0, len(col), 1000):
        sk.fold(col[s:s + 1000])
    zi = np.searchsorted(sk.values, 0.0)
    assert zi < len(sk.values) and sk.values[zi] == 0.0


def test_sketch_json_roundtrip_and_merge():
    """to_dict/from_dict round-trips f64 bit-exactly (the exchange_host
    payload), and merging two half-sketches equals folding the whole."""
    rng = np.random.RandomState(3)
    col = rng.normal(size=2000)
    whole = binning.FeatureSketch()
    whole.fold(col)
    a, b = binning.FeatureSketch(), binning.FeatureSketch()
    a.fold(col[:1100])
    b.fold(col[1100:])
    a.merge(binning.FeatureSketch.from_dict(
        json.loads(json.dumps(b.to_dict()))))
    assert a.total_cnt == whole.total_cnt
    assert np.array_equal(a.values, whole.values)
    assert np.array_equal(a.counts, whole.counts)
    rt = binning.FeatureSketch.from_dict(json.loads(json.dumps(
        whole.to_dict())))
    assert np.array_equal(rt.values, whole.values)


def test_merge_feature_sketches_single_process():
    from lightgbm_tpu import distributed
    sk = binning.FeatureSketch()
    sk.fold(np.arange(10.0))
    merged = distributed.merge_feature_sketches([sk])
    assert merged[0] is sk or np.array_equal(merged[0].values, sk.values)


def test_streaming_memory_bounded_and_gauges(rng):
    """<= 2 raw chunks alive at any moment (weakref census over a
    generator source) and the construct gauges record the peak."""
    X, y = _data(rng, n=2000, f=5)
    chunk = 700
    live, peak_live = set(), [0]

    def factory():
        def gen():
            for s in range(0, len(X), chunk):
                c = np.array(X[s:s + chunk])
                live.add(id(c))
                weakref.finalize(c, live.discard, id(c))
                peak_live[0] = max(peak_live[0], len(live))
                yield c, np.array(y[s:s + chunk])
        return gen()

    ds = lgb.Dataset.from_chunks(factory, params={"verbosity": -1})
    ds.construct()
    assert peak_live[0] <= 2, f"{peak_live[0]} chunks alive"
    g = profiling.gauges()
    assert 0 < g["construct_peak_bytes"] <= 2 * chunk * X.shape[1] * 4
    assert g["construct_rows"] == len(X)
    for k in ("construct_sketch_s", "construct_bin_s",
              "construct_h2d_overlap_s"):
        assert k in g
    from lightgbm_tpu import telemetry
    snap = telemetry.construct_snapshot()
    assert snap["rows"] == len(X) and "rows_per_sec" in snap
    assert {"sketch_pass", "bin_pass", "h2d_overlap"} <= set(snap)
    # per-DATASET attribution: the stats ride the dataset itself (the
    # flight-recorder header reads these), and a LATER monolithic
    # construct — e.g. a valid set constructed after the train set —
    # must not wipe or substitute them
    stats = ds.construct_stats
    assert stats["rows"] == len(X) and stats["peak_host_bytes"] > 0
    lgb.Dataset(X[:300].copy(), label=y[:300],
                params={"verbosity": -1}).construct()
    assert ds.construct_stats == stats
    assert telemetry.construct_snapshot() == snap


def test_streaming_timetag_subscopes(rng):
    X, y = _data(rng, n=2000, f=5)
    was = profiling.enabled()
    profiling.reset()
    profiling.enable(True)
    try:
        lgb.train(dict(TRAIN),
                  lgb.Dataset(X, label=y,
                              params={"verbosity": -1,
                                      "construct_streaming": True,
                                      "construct_chunk_rows": 700}),
                  num_boost_round=2)
        sc = profiling.scopes()
    finally:
        profiling.enable(was)
        profiling.reset()
    assert {"construct", "sketch_pass", "bin_pass", "h2d_overlap"} <= set(sc)


def test_streaming_rejections(rng):
    from lightgbm_tpu.utils.log import LightGBMError
    X, y = _data(rng, n=400, f=5)
    with pytest.raises(LightGBMError, match="linear_tree"):
        lgb.Dataset(X, label=y, params={"verbosity": -1,
                                        "linear_tree": True,
                                        "construct_streaming": True}) \
            .construct()
    with pytest.raises(LightGBMError, match="re-iterable"):
        lgb.Dataset.from_chunks(iter([X]), params={"verbosity": -1}) \
            .construct()
    with pytest.raises(LightGBMError, match="one or the other"):
        lgb.Dataset.from_chunks([(X, y)], label=y,
                                params={"verbosity": -1}).construct()


def test_load_partitioned_chunks_single_process_parity(rng):
    """1-process chunked prepart loader == monolithic load_partitioned
    (enable_bundle off so both sides bin plain columns)."""
    from lightgbm_tpu import distributed
    X, y = _data(rng, n=400, f=5, dtype=np.float64)
    params = {"min_data_in_leaf": 5, "verbosity": -1,
              "enable_bundle": False}
    tr = {"objective": "binary", "num_leaves": 8, "tree_learner": "data",
          "min_data_in_leaf": 5, "boost_from_average": False,
          "verbosity": -1, "histogram_method": "scatter"}
    ds_m = distributed.load_partitioned(X, label=y, params=dict(params))
    b_m = lgb.train(dict(tr), ds_m, 2)
    chunks = [(X[s:s + 150], y[s:s + 150]) for s in range(0, len(X), 150)]
    ds_c = distributed.load_partitioned_chunks(chunks, params=dict(params))
    assert ds_c.is_pre_partitioned and ds_c.num_data == len(X)
    b_c = lgb.train(dict(tr), ds_c, 2)
    assert b_m.model_to_string() == b_c.model_to_string()


# ---------------------------------------------------------- 2-rank merge
_CHILD_CHUNKED = """
import json, sys, hashlib
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import lightgbm_tpu as lgb

port, rank, nproc, mode = (int(sys.argv[1]), int(sys.argv[2]),
                           int(sys.argv[3]), sys.argv[4])
machines = ",".join(f"127.0.0.1:{port}" for _ in range(nproc))
lgb.distributed.init(machines=machines, num_machines=nproc, process_id=rank)

# full problem is 400 rows x 6 features; each process owns its contiguous
# slice, fed to the loader as TWO chunks (so the cross-rank sketch merge
# over exchange_host really merges multi-chunk sketches)
rng = np.random.RandomState(13)
n, f = 400, 6
X_full = rng.normal(size=(n, f))
X_full[:, 4] *= (rng.rand(n) < 0.3)
y_full = (X_full[:, 0] + 0.5 * X_full[:, 1] - X_full[:, 4] > 0).astype(
    np.float64)
n_loc = n // nproc
lo, hi = rank * n_loc, (rank + 1) * n_loc
X, y = X_full[lo:hi], y_full[lo:hi]

params = {"min_data_in_leaf": 5, "verbosity": -1, "enable_bundle": False}
if mode == "chunks":
    c = n_loc // 2
    src = [(X[:c], y[:c]), (X[c:], y[c:])]
    ds = lgb.distributed.load_partitioned_chunks(src, params=params)
else:
    ds = lgb.distributed.load_partitioned(X, label=y, params=params)
assert ds.num_data == n
mh = hashlib.md5(json.dumps([m.to_dict() for m in ds.mappers],
                            sort_keys=True, default=str).encode()).hexdigest()
# the full matrix binned through the agreed mappers: identical digests
# across ranks AND world sizes prove the merged fit is the same function
bins_full = ds.bin_new_data(X_full)
bh = hashlib.md5(np.ascontiguousarray(bins_full).tobytes()).hexdigest()
out = {"rank": rank, "mappers_digest": mh, "bins_digest": bh}
# this container's CPU backend has no cross-process XLA collectives
# (ROADMAP note), so the training half runs at world size 1 only — the
# 2-rank half proves the exchange_host sketch-merge construct
if nproc == 1:
    booster = lgb.train({"objective": "binary", "num_leaves": 8,
                         "tree_learner": "data", "min_data_in_leaf": 5,
                         "boost_from_average": False, "verbosity": -1,
                         "histogram_method": "scatter"}, ds,
                        num_boost_round=4)
    model = booster.model_to_string()
    out["model_digest"] = hashlib.md5(model.encode()).hexdigest()
print("RESULT " + json.dumps(out))
"""


def _run_chunked(nproc, devices_per_proc, mode, timeout=420):
    from lightgbm_tpu.distributed import free_port, prepare_cpu_device_env
    port = free_port()
    env = dict(os.environ)
    prepare_cpu_device_env(env, devices_per_proc)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD_CHUNKED, str(port), str(r),
         str(nproc), mode],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for r in range(nproc)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    results = []
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert line, out[-3000:]
        results.append(json.loads(line[-1][len("RESULT "):]))
    return results


@pytest.mark.slow
def test_two_rank_sketch_merge_over_exchange_host():
    """The pre-partitioned 2-rank merge: each rank folds its half of the
    rows as TWO chunks, sketches merge over ``exchange_host`` (pure
    gRPC — this works cross-process even on this container's CPU
    backend, unlike the monolithic loader's XLA sample allgather), and
    the merged fit is the SAME function everywhere: mappers and
    full-matrix binning digests identical across ranks and across world
    sizes, and at world size 1 (where the grower's collectives exist)
    the chunked loader trains to model text bit-identical to the
    monolithic ``load_partitioned``."""
    rc2 = _run_chunked(2, 4, "chunks")
    rc1 = _run_chunked(1, 8, "chunks")
    rm1 = _run_chunked(1, 8, "mono")
    # identical mappers on both ranks (the exchange_host merge agreed)
    assert rc2[0]["mappers_digest"] == rc2[1]["mappers_digest"]
    assert rc2[0]["bins_digest"] == rc2[1]["bins_digest"]
    # world-size invariance: the 2-rank merged fit == 1-process fit
    assert rc2[0]["mappers_digest"] == rc1[0]["mappers_digest"]
    assert rc2[0]["bins_digest"] == rc1[0]["bins_digest"]
    # chunked == monolithic (mappers, binning, trained model text)
    assert rc1[0]["mappers_digest"] == rm1[0]["mappers_digest"]
    assert rc1[0]["bins_digest"] == rm1[0]["bins_digest"]
    assert rc1[0]["model_digest"] == rm1[0]["model_digest"]
