"""Leaf-partitioned row compaction (the DataPartition analog,
data_partition.hpp:21-60): parity, ladder dispatch, and the rows-streamed
telemetry.

Parity model: ``compact_rows`` preserves the kept rows' ORIGINAL order, so
the scatter backend (the CPU production default) accumulates every
histogram cell's contributions in exactly the full-pass order — training
with and without compaction is asserted BIT-IDENTICAL (model-text
equality) there. The matmul backends (onehot/binloop) regroup partial sums
when the scan-block partition changes, so compaction perturbs grad/hess
sums at f32 accumulation-order level — the same tolerance the repo accepts
between its own dense/sparse and CPU/TPU paths (test_sparse_storage's
parity model): those cells assert identical STRUCTURE (split features,
thresholds, counts) and prediction parity."""

import re

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb


def _data(rng, n=4000, f=5, cat_col=None):
    X = rng.normal(size=(n, f)).astype(np.float64)
    if cat_col is not None:
        X[:, cat_col] = rng.randint(0, 8, size=n)
        y = (X[:, 0] + (X[:, cat_col] > 3) + 0.1 * rng.normal(size=n) > 0.5)
    else:
        y = (X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.normal(size=n) > 0.3)
    return X, y.astype(np.float64)


def _train(X, y, extra, rounds=4):
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    params.update(extra)
    ds = lgb.Dataset(X, label=y, params=params)
    b = lgb.Booster(params=params, train_set=ds)
    for _ in range(rounds):
        b.update()
    return b


def _tree_text(b):
    """Model text up to the parameters block (the trees — the parameters
    section records hist_compaction itself, which differs by design)."""
    return b.model_to_string().split("\nparameters:")[0]


def _structure_text(b):
    """Tree text with the f32-accumulated value lines stripped (gains,
    leaf values/weights, internal values) — split features, thresholds,
    counts and topology remain."""
    txt = _tree_text(b)
    drop = ("split_gain=", "leaf_value=", "leaf_weight=",
            "internal_value=", "internal_weight=", "tree_sizes=",
            "shrinkage=")
    return "\n".join(l for l in txt.splitlines()
                     if not l.startswith(drop))


BIT_EXACT_CELLS = {
    "scatter": {"histogram_method": "scatter"},
    "scatter_nosub": {"histogram_method": "scatter",
                      "hist_subtraction": False},
    # the subset cell rides the slow tier below: the subset-copy machinery
    # is tier-1 in test_gbdt's bagging tests and the compaction rungs it
    # exercises are shared with the tier-1 scatter/nosub/categorical cells
    "scatter_bag_subset": {"histogram_method": "scatter",
                           "bagging_fraction": 0.4, "bagging_freq": 1},
    "scatter_categorical": {"histogram_method": "scatter",
                            "categorical_feature": [3]},
    "scatter_exact_mode": {"histogram_method": "scatter",
                           "tree_growth_mode": "exact"},
}


@pytest.mark.parametrize("cell", [
    # the exact-growth-mode and bagging-subset cells ride the slow tier:
    # exact-mode growth has its own tier-1 coverage (test_grower), the
    # subset copy has test_gbdt's bagging tier-1 coverage, and the
    # compaction machinery both share stays tier-1 via the other cells
    pytest.param(c, marks=pytest.mark.slow)
    if c in ("scatter_exact_mode", "scatter_bag_subset") else c
    for c in sorted(BIT_EXACT_CELLS)])
def test_compaction_parity_bit_exact(rng, cell):
    """Compacted and full-pass training yield IDENTICAL model text on the
    scatter backend across subtraction x bagging-subset x categorical x
    growth mode."""
    extra = BIT_EXACT_CELLS[cell]
    cat = extra.get("categorical_feature", [None])[0]
    X, y = _data(rng, cat_col=cat)
    b_on = _train(X, y, {**extra, "hist_compaction": True})
    b_off = _train(X, y, {**extra, "hist_compaction": False})
    assert _tree_text(b_on) == _tree_text(b_off)
    # and compaction actually engaged (fewer rows streamed) except in the
    # no-subtraction cell, where both children of every split stay pending
    # so non-root passes still cover ~all rows
    if "hist_subtraction" not in extra:
        assert (b_on._boosting.rows_streamed_per_tree
                < b_off._boosting.rows_streamed_per_tree)


@pytest.mark.parametrize("method", [
    "onehot",
    # binloop rides the slow tier: its grower-level parity stays tier-1
    # (test_grower's scatter/binloop matrix) and the compaction
    # structural-parity machinery stays tier-1 via the onehot cell
    pytest.param("binloop", marks=pytest.mark.slow)])
def test_compaction_parity_matmul_structural(rng, method):
    """The matmul backends: identical tree structure + prediction parity
    (accumulation-order tolerance on the value fields — see the module
    docstring)."""
    X, y = _data(rng)
    b_on = _train(X, y, {"histogram_method": method,
                         "hist_compaction": True})
    b_off = _train(X, y, {"histogram_method": method,
                          "hist_compaction": False})
    assert _structure_text(b_on) == _structure_text(b_off)
    np.testing.assert_allclose(b_on.predict(X), b_off.predict(X),
                               rtol=1e-3, atol=1e-3)
    assert (b_on._boosting.rows_streamed_per_tree
            < b_off._boosting.rows_streamed_per_tree)


def test_ladder_fallback_rung(rng):
    """A ladder whose rungs are all smaller than any pending tile must
    take the full-N fallback every round — identical model text AND the
    uncompacted rows-streamed count — and stay correct."""
    X, y = _data(rng)
    b_tiny = _train(X, y, {"histogram_method": "scatter",
                           "hist_compaction": True,
                           "hist_compaction_ladder": [0.001]})
    b_off = _train(X, y, {"histogram_method": "scatter",
                          "hist_compaction": False})
    assert _tree_text(b_tiny) == _tree_text(b_off)
    assert (b_tiny._boosting.rows_streamed_per_tree
            == b_off._boosting.rows_streamed_per_tree)


def test_compact_rows_unit(rng):
    """compact_rows: stable order, padded slots inert, scatter-backend
    tile bitwise-equal to the full pass, onehot allclose."""
    from lightgbm_tpu.ops.histogram import compact_rows, histogram_tiles

    n, f, b_bins, L = 1500, 4, 16, 8
    bins = jnp.asarray(rng.randint(0, b_bins, size=(n, f)).astype(np.uint8))
    binsT = jnp.asarray(np.asarray(bins).T)
    stats = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))
    leaf_ids = jnp.asarray(rng.randint(0, L, size=n).astype(np.int32))
    sel = jnp.asarray(np.asarray([2, 5, -1, -1], np.int32))
    keep = np.isin(np.asarray(leaf_ids), [2, 5])
    size = 1024
    assert keep.sum() <= size

    bc, btc, sc, lc = compact_rows(bins, binsT, stats, jnp.asarray(leaf_ids),
                                   jnp.asarray(keep), size)
    k = int(keep.sum())
    # stable original order of the kept rows
    np.testing.assert_array_equal(np.asarray(bc)[:k],
                                  np.asarray(bins)[keep])
    np.testing.assert_array_equal(np.asarray(btc)[:, :k],
                                  np.asarray(binsT)[:, keep])
    np.testing.assert_array_equal(np.asarray(lc)[:k],
                                  np.asarray(leaf_ids)[keep])
    # padding: zero stats, leaf id -2 (matches no sel entry)
    assert np.all(np.asarray(sc)[k:] == 0.0)
    assert np.all(np.asarray(lc)[k:] == -2)

    full = histogram_tiles(bins, stats, leaf_ids, sel, b_bins,
                           method="scatter")
    comp = histogram_tiles(bc, sc, lc, sel, b_bins, method="scatter")
    np.testing.assert_array_equal(np.asarray(full), np.asarray(comp))

    full_o = histogram_tiles(bins, stats, leaf_ids, sel, b_bins,
                             method="onehot")
    comp_o = histogram_tiles(bc, sc, lc, sel, b_bins, method="onehot")
    np.testing.assert_allclose(np.asarray(full_o), np.asarray(comp_o),
                               rtol=1e-5, atol=1e-5)


def test_grower_ladder_fallback_direct(rng):
    """Direct grow_tree: a mixed ladder where only SOME rungs can ever fit
    produces the same tree as no ladder (fallback + engaged rungs are both
    correct), on the scatter backend bit-exactly."""
    import jax
    from lightgbm_tpu.models.grower import grow_tree
    from lightgbm_tpu.ops.split import FeatureMeta, SplitParams

    n, f, B = 3000, 4, 32
    bins = jnp.asarray(rng.randint(0, B, size=(n, f)).astype(np.uint8))
    grad = jnp.asarray(rng.normal(size=n).astype(np.float32))
    hess = jnp.ones((n,), jnp.float32)
    f32 = jnp.float32
    params = SplitParams(
        lambda_l1=f32(0.0), lambda_l2=f32(0.0), max_delta_step=f32(0.0),
        path_smooth=f32(0.0), min_data_in_leaf=f32(5),
        min_sum_hessian_in_leaf=f32(1e-3), min_gain_to_split=f32(0.0),
        cat_l2=f32(10.0), cat_smooth=f32(10.0),
        max_cat_threshold=jnp.int32(32), min_data_per_group=f32(100.0),
        max_cat_to_onehot=jnp.int32(4), monotone_penalty=f32(0.0),
        cegb_tradeoff=f32(1.0), cegb_penalty_split=f32(0.0))
    meta = FeatureMeta(
        num_bins=jnp.full((f,), B, jnp.int32),
        missing_type=jnp.zeros((f,), jnp.int32),
        default_bin=jnp.zeros((f,), jnp.int32),
        is_categorical=jnp.zeros((f,), bool),
        monotone=jnp.zeros((f,), jnp.int8),
        penalty=jnp.ones((f,), jnp.float32))
    common = dict(max_leaves=8, num_bins=B, hist_method="scatter")
    args = (bins, grad, hess, jnp.ones((n,), jnp.float32), meta, params,
            jnp.ones((f,), jnp.float32), jnp.full((f,), -1, jnp.int32))
    t_base, l_base, aux_base = grow_tree(*args, **common)
    # 64 can never hold a pending tile here; 1536 holds every non-root one
    t_lad, l_lad, aux_lad = grow_tree(*args, **common,
                                      compaction_ladder=(64, 1536))
    for a, b in zip(jax.tree_util.tree_leaves(t_base),
                    jax.tree_util.tree_leaves(t_lad)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(l_base), np.asarray(l_lad))
    assert float(aux_lad.rows_streamed) < float(aux_base.rows_streamed)


@pytest.mark.slow
def test_rows_streamed_perf_smoke(rng):
    """CPU perf smoke: on a synthetic 50k-row problem the compaction
    ladder must cut rows streamed per tree well below the uncompacted
    O(N * rounds) count. (Slow tier: a wall-clock smoke — that compaction
    actually engages is asserted per-cell by the tier-1 bit-exact parity
    tests above via their rows_streamed_per_tree checks.)"""
    n, fdim = 50_000, 6
    X = rng.normal(size=(n, fdim)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + np.sin(2 * X[:, 2])
         + 0.2 * rng.normal(size=n) > 0.2).astype(np.float32)

    def rows_per_tree(compaction):
        b = _train(X, y, {"histogram_method": "scatter",
                          "num_leaves": 31,
                          "hist_compaction": compaction}, rounds=3)
        return b._boosting.rows_streamed_per_tree

    compacted = rows_per_tree(True)
    uncompacted = rows_per_tree(False)
    assert compacted > 0
    # every non-root pass covers only the smaller siblings => well below
    # the full-N-per-round count
    assert compacted < 0.75 * uncompacted, (compacted, uncompacted)


def test_profiling_counter_surface(rng):
    """The rows-streamed telemetry reaches the profiling counter table."""
    from lightgbm_tpu.utils import profiling
    X, y = _data(rng, n=1500)
    profiling.reset()
    profiling.enable(True)
    try:
        _train(X, y, {"histogram_method": "scatter"}, rounds=2)
        counts = profiling.counters()
        assert counts.get("hist_rows_streamed", 0) > 0
        assert re.search(r"hist_rows_streamed", profiling.table())
    finally:
        profiling.enable(False)
        profiling.reset()


def test_compaction_rejected_for_parallel_learners(rng):
    """The grower refuses a ladder under any parallel mode (the gbdt layer
    never passes one there; the assert is the backstop)."""
    from lightgbm_tpu.models.grower import grow_tree
    with pytest.raises(AssertionError, match="serial-only"):
        grow_tree(
            jnp.zeros((8, 1), jnp.uint8), jnp.zeros((8,), jnp.float32),
            jnp.ones((8,), jnp.float32), jnp.ones((8,), jnp.float32),
            None, None, jnp.ones((1,), jnp.float32),
            jnp.full((1,), -1, jnp.int32),
            max_leaves=2, num_bins=2, axis_name="d",
            compaction_ladder=(64,))
