"""Linear trees: leaves hold linear models on branch features
(reference: src/treelearner/linear_tree_learner.cpp; tested via
tests/python_package_test/test_engine.py:2540)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

BASE = {"objective": "regression", "num_leaves": 8, "min_data_in_leaf": 20,
        "verbosity": -1}


@pytest.fixture(scope="module")
def piecewise_linear():
    rng = np.random.RandomState(0)
    n = 2000
    X = rng.uniform(-2, 2, size=(n, 4))
    y = (np.where(X[:, 0] > 0, 3 * X[:, 1] + 1, -2 * X[:, 1])
         + 0.1 * rng.normal(size=n))
    return X, y


@pytest.mark.slow
def test_linear_beats_plain_on_linear_target(piecewise_linear):
    """slow: a pure quality claim (linear leaves beat constant leaves on
    a piecewise-linear target — the same class as the
    categorical-beats-numerical claim moved in PR 6). Linear-tree
    mechanics stay tier-1 via the model round trip, NaN fallback,
    valid-eval consistency, binary objective and device-vs-predict
    scoring tests in this file."""
    from sklearn.metrics import r2_score
    X, y = piecewise_linear
    plain = lgb.train(BASE, lgb.Dataset(X, label=y, params=BASE,
                                        free_raw_data=False),
                      num_boost_round=10)
    lin_p = dict(BASE, linear_tree=True, linear_lambda=0.01)
    lin = lgb.train(lin_p, lgb.Dataset(X, label=y, params=lin_p,
                                       free_raw_data=False),
                    num_boost_round=10)
    assert r2_score(y, lin.predict(X)) > r2_score(y, plain.predict(X))


def test_linear_model_round_trip(piecewise_linear):
    X, y = piecewise_linear
    lin_p = dict(BASE, linear_tree=True)
    booster = lgb.train(lin_p, lgb.Dataset(X, label=y, params=lin_p,
                                           free_raw_data=False),
                        num_boost_round=8)
    s = booster.model_to_string()
    assert "is_linear=1" in s
    assert "leaf_coeff=" in s
    loaded = lgb.Booster(model_str=s)
    np.testing.assert_allclose(booster.predict(X), loaded.predict(X),
                               rtol=1e-6, atol=1e-7)


def test_linear_nan_fallback(piecewise_linear):
    X, y = piecewise_linear
    lin_p = dict(BASE, linear_tree=True)
    booster = lgb.train(lin_p, lgb.Dataset(X, label=y, params=lin_p,
                                           free_raw_data=False),
                        num_boost_round=5)
    Xn = X[:10].copy()
    Xn[:, :] = np.nan
    p = booster.predict(Xn)
    assert np.isfinite(p).all()


def test_linear_valid_eval_consistent(piecewise_linear):
    X, y = piecewise_linear
    lin_p = dict(BASE, linear_tree=True)
    tr = lgb.Dataset(X, label=y, params=lin_p, free_raw_data=False)
    vs = lgb.Dataset(X, label=y, params=lin_p, reference=tr,
                     free_raw_data=False)
    ev = {}
    booster = lgb.train(lin_p, tr, 8, valid_sets=[vs], evals_result=ev)
    true_l2 = np.mean((booster.predict(X) - y) ** 2)
    assert abs(ev["valid_0"]["l2"][-1] - true_l2) < 1e-5


def test_linear_tree_binary_objective(piecewise_linear):
    X, _ = piecewise_linear
    y = (X[:, 1] + 0.3 * np.random.RandomState(1).normal(size=len(X)) > 0)
    lin_p = dict(BASE, objective="binary", linear_tree=True)
    booster = lgb.train(lin_p, lgb.Dataset(X, label=y.astype(float),
                                           params=lin_p, free_raw_data=False),
                        num_boost_round=10)
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, booster.predict(X)) > 0.95


def test_linear_tree_rejects_l1():
    X = np.random.RandomState(0).normal(size=(200, 3))
    y = X[:, 0]
    lin_p = dict(BASE, objective="regression_l1", linear_tree=True)
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        lgb.train(lin_p, lgb.Dataset(X, label=y, params=lin_p,
                                     free_raw_data=False), num_boost_round=2)


def test_linear_tree_rejects_dart():
    X = np.random.RandomState(0).normal(size=(200, 3))
    y = X[:, 0]
    lin_p = dict(BASE, boosting="dart", linear_tree=True)
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        lgb.train(lin_p, lgb.Dataset(X, label=y, params=lin_p,
                                     free_raw_data=False), num_boost_round=2)


def test_linear_valid_scoring_device_matches_predict():
    """Per-iteration valid-set scoring for linear-leaf trees runs on device
    (dense coefficient tables) and must agree with the host predict path
    used for final predictions."""
    rng = np.random.RandomState(41)
    n = 1500
    X = rng.uniform(-2, 2, size=(n, 4))
    X[rng.uniform(size=X.shape) < 0.03] = np.nan   # exercise the fallback
    y = 2.0 * np.nan_to_num(X[:, 0]) + np.sin(np.nan_to_num(X[:, 1])) \
        + 0.1 * rng.normal(size=n)
    Xv, yv = X[:400].copy(), y[:400]
    params = {"objective": "regression", "num_leaves": 15,
              "linear_tree": True, "metric": ["l2"], "verbosity": -1}
    train = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    valid = lgb.Dataset(Xv, label=yv, reference=train)
    evals = {}
    booster = lgb.train(params, train, num_boost_round=8,
                        valid_sets=[valid], valid_names=["v"],
                        evals_result=evals)
    # the recorded per-iteration metric must match an l2 computed from the
    # final prediction path (host ModelTree walk)
    pred = booster.predict(Xv)
    l2_direct = float(np.mean((pred - yv) ** 2))
    l2_recorded = evals["v"]["l2"][-1]
    np.testing.assert_allclose(l2_recorded, l2_direct, rtol=1e-4)
