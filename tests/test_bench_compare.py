"""BENCH regression gate (scripts/bench_compare.py): per-metric
thresholds, backend/tpu_required sanity (a CPU-fallback round can never
be blessed against a TPU baseline), and the driver-wrapper/JSONL file
shapes. Pure host logic — no jax work."""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "bench_compare", os.path.join(REPO, "scripts", "bench_compare.py"))
bench_compare = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_compare)

BASE = {"metric": "higgs10.5M_sec_per_iter", "value": 1.0,
        "rows": 10_500_000, "backend": "tpu", "tpu_required": True,
        "auc": 0.94, "mfu_est": 0.05, "hbm_peak_bytes": 8_000_000_000}


def _write(tmp_path, name, doc):
    p = str(tmp_path / name)
    with open(p, "w") as fh:
        if isinstance(doc, str):
            fh.write(doc)
        else:
            json.dump(doc, fh)
    return p


def test_identical_round_passes(tmp_path):
    b = _write(tmp_path, "b.json", BASE)
    c = _write(tmp_path, "c.json", dict(BASE, value=1.01))
    assert bench_compare.run([b, c]) == 0


def test_synthetic_regression_exits_nonzero(tmp_path):
    """The acceptance criterion: a synthetic regression exits non-zero."""
    b = _write(tmp_path, "b.json", BASE)
    c = _write(tmp_path, "c.json", dict(BASE, value=1.5))
    assert bench_compare.run([b, c]) == 1


def test_cpu_fallback_vs_tpu_baseline_refused(tmp_path):
    """The acceptance criterion: a CPU-fallback round compared against
    a TPU baseline exits non-zero (sanity code 2), regardless of its
    numbers."""
    b = _write(tmp_path, "b.json", BASE)
    c = _write(tmp_path, "c.json",
               dict(BASE, backend="cpu", value=0.5, tpu_required=False))
    assert bench_compare.run([b, c]) == 2


def test_tpu_required_but_cpu_backend_refused(tmp_path):
    b = _write(tmp_path, "b.json", dict(BASE, backend="cpu",
                                        tpu_required=False))
    c = _write(tmp_path, "c.json", dict(BASE, backend="cpu",
                                        tpu_required=True))
    assert bench_compare.run([b, c]) == 2


def test_auc_uses_absolute_tolerance(tmp_path):
    b = _write(tmp_path, "b.json", BASE)
    ok = _write(tmp_path, "ok.json", dict(BASE, auc=0.938))    # -0.002
    bad = _write(tmp_path, "bad.json", dict(BASE, auc=0.93))   # -0.010
    assert bench_compare.run([b, ok]) == 0
    assert bench_compare.run([b, bad]) == 1


def test_memory_metrics_gate(tmp_path):
    b = _write(tmp_path, "b.json", BASE)
    c = _write(tmp_path, "c.json",
               dict(BASE, hbm_peak_bytes=10_000_000_000))
    assert bench_compare.run([b, c]) == 1
    assert bench_compare.run([b, c, "--threshold",
                              "hbm_peak_bytes=30"]) == 0


def test_rows_mismatch_refused_unless_ignored(tmp_path):
    b = _write(tmp_path, "b.json", BASE)
    c = _write(tmp_path, "c.json", dict(BASE, rows=500_000))
    assert bench_compare.run([b, c]) == 2
    assert bench_compare.run([b, c, "--ignore-rows"]) == 0


def test_null_value_refused(tmp_path):
    b = _write(tmp_path, "b.json", BASE)
    c = _write(tmp_path, "c.json", dict(BASE, value=None, error="died"))
    assert bench_compare.run([b, c]) == 2


def test_multiple_candidates_worst_exit_wins(tmp_path):
    b = _write(tmp_path, "b.json", BASE)
    ok = _write(tmp_path, "ok.json", dict(BASE, value=1.02))
    bad = _write(tmp_path, "bad.json", dict(BASE, value=2.0))
    assert bench_compare.run([b, ok, bad]) == 1


def test_wrapper_and_jsonl_shapes(tmp_path):
    """BENCH_rNN driver wrappers (tail + parsed) and raw bench.py JSONL
    streams both load; the LAST enriched line wins over earlier ones."""
    wrapper = _write(tmp_path, "wrap.json", {
        "n": 3, "rc": 0,
        "tail": json.dumps(dict(BASE, value=5.0)) + "\n"
                + json.dumps(dict(BASE, value=1.0)) + "\n",
        "parsed": dict(BASE, value=99.0)})
    assert bench_compare.load_bench(wrapper)["value"] == 1.0
    jsonl = _write(tmp_path, "stream.json",
                   "# comment\n" + json.dumps(dict(BASE, value=3.0))
                   + "\n" + json.dumps(dict(BASE, value=2.0)) + "\n")
    assert bench_compare.load_bench(jsonl)["value"] == 2.0
    garbage = _write(tmp_path, "garbage.json", "not json at all\n")
    with pytest.raises(SystemExit):
        bench_compare.load_bench(garbage)


def test_real_bench_round_loads():
    """The committed BENCH_r03 driver wrapper parses (guards the loader
    against the real on-disk shape drifting from the synthetic one)."""
    doc = bench_compare.load_bench(os.path.join(REPO, "BENCH_r03.json"))
    assert doc["metric"] == "higgs10.5M_sec_per_iter"
    assert doc["value"] == 7.1677


def test_self_check_passes():
    assert bench_compare.self_check() == 0
