"""Compile-wall coverage: K-iterations-per-dispatch scan blocks +
persistent AOT compile cache (ISSUE 10).

The contracts pinned here:

- K-block training (``boost_rounds_per_dispatch`` K >= 4) is BIT-IDENTICAL
  (model text) to K separate fused iterations — for plain gbdt,
  multiclass, mask bagging, subset bagging and GOSS (whose sampling now
  runs in-program, newly admitting it to the fused path at K=1 too);
- a warm K-block costs <= 2 compiled-program dispatches (measured via the
  PR 3 dispatch hook; the block itself is ONE — score carried in-program);
- the traced fused program embeds (almost) NO constants: the dataset
  arrays (objective label/derived tables, feature meta, bins) are
  OPERANDS, so XLA has nothing dataset-sized to constant-fold at compile
  time (the BENCH_r04 >6 s alarms);
- a checkpoint period that is not a multiple of K is rejected with a
  clear error (a K-block is one atomic dispatch — no mid-block state
  exists to capture), and block-boundary checkpoints resume
  bit-identically;
- a SECOND process with a warm persistent compilation cache
  (``compile_cache_dir``) resumes from a checkpoint with ZERO fused-step
  XLA compiles (cache hits only) — the supervisor/gang-relaunch warm
  path, asserted on the per-module compile counters.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import callback as callback_mod
from lightgbm_tpu.utils import profiling
from lightgbm_tpu.utils.log import LightGBMError


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(7)
    X = rng.normal(size=(1500, 8)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(size=1500) * 0.3 > 0)
    y3 = np.digitize(X[:, 0] + 0.3 * X[:, 2], [-0.5, 0.5])
    return X, y.astype(np.float32), y3.astype(np.float32)


def _strip(model_text: str) -> str:
    """Drop the intended param-dump differences between the two runs."""
    drop = ("[boost_rounds_per_dispatch", "[fused_iteration",
            "[compile_cache_dir")
    return "\n".join(l for l in model_text.splitlines()
                     if not l.startswith(drop))


def _fit(X, y, extra, nround=8):
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 10,
         "verbosity": -1}
    p.update(extra)
    return lgb.train(p, lgb.Dataset(X, label=y, params=p), nround)


def _assert_block_parity(X, y, extra, nround=8, K=4):
    blocked = _fit(X, y, {**extra, "boost_rounds_per_dispatch": K}, nround)
    single = _fit(X, y, extra, nround)
    assert _strip(blocked.model_to_string()) == \
        _strip(single.model_to_string())
    return blocked, single


# ------------------------------------------------------- K-scan parity
def test_kscan_parity_gbdt(data):
    X, y, _ = data
    blocked, _ = _assert_block_parity(X, y, {})
    assert blocked._boosting._fused_cache, "block path did not engage"


# slow: class-scan spelling of the same block machinery tier-1's
# test_kscan_parity_gbdt pins (multiclass parity also rides
# test_fused_wide's tier-1 fused coverage)
@pytest.mark.slow
def test_kscan_parity_multiclass(data):
    X, _, y3 = data
    _assert_block_parity(X, y3, {"objective": "multiclass",
                                 "num_class": 3}, nround=6, K=4)


def test_kscan_parity_bagging_mask(data):
    X, y, _ = data
    _assert_block_parity(X, y, {"bagging_freq": 2,
                                "bagging_fraction": 0.7})


# slow: the subset draw is the same in-program fold_in stream the
# tier-1 mask spelling exercises; full parity still runs in the slow
# tier and the manual combo sweep
@pytest.mark.slow
def test_kscan_parity_bagging_subset(data):
    X, y, _ = data
    _assert_block_parity(X, y, {"bagging_freq": 2,
                                "bagging_fraction": 0.4})


def test_kscan_parity_goss(data):
    X, y, _ = data
    # learning_rate 0.3 -> the 1/lr warm-up gate flips INSIDE the run
    # (iteration 3), exercising both cond arms of the in-program sampler
    blocked, single = _assert_block_parity(
        X, y, {"boosting": "goss", "learning_rate": 0.3})
    assert blocked._boosting._fused_cache, "GOSS block did not fuse"


# slow: tier-1's test_kscan_parity_goss already proves the fused
# in-program sampler bit-matches (block == K singles == its model);
# this is the explicit fused-vs-unfused spelling
@pytest.mark.slow
def test_goss_now_fused_and_matches_unfused(data):
    """GOSS's in-program sampling newly admits it to the fused path —
    and the fused run must stay bit-identical to the phase-by-phase
    reference (the same contract every other fused config carries)."""
    X, y, _ = data
    fused = _fit(X, y, {"boosting": "goss", "learning_rate": 0.3})
    plain = _fit(X, y, {"boosting": "goss", "learning_rate": 0.3,
                        "fused_iteration": False})
    assert fused._boosting._fused_cache, "GOSS did not take the fused path"
    assert not plain._boosting._fused_cache
    assert _strip(fused.model_to_string()) == _strip(plain.model_to_string())


# slow: the K-mask pre-draw is exercised by the tier-1 gbdt parity
# via _feature_mask_np order (and the multiclass slow sibling)
@pytest.mark.slow
def test_kscan_parity_feature_fraction(data):
    """Column sampling draws from a stateful host rng: the block must
    pre-draw K masks in the exact per-iteration order."""
    X, y, _ = data
    _assert_block_parity(X, y, {"feature_fraction": 0.6})


# slow: remainder truncation is pinned cheaply by
# test_manual_update_keeps_single_iteration_semantics + the resume
# parity sibling; the full 7-round parity rides the slow tier
@pytest.mark.slow
def test_kscan_remainder_rounds(data):
    """num_boost_round not a multiple of K: the last block truncates
    (never over-trains) and stays bit-identical."""
    X, y, _ = data
    blocked, single = _assert_block_parity(X, y, {}, nround=7, K=4)
    assert len(blocked._boosting.trees) == 7
    assert len(single._boosting.trees) == 7


def test_manual_update_keeps_single_iteration_semantics(data):
    """Only engine.train may drive block consumption: a manual
    Booster.update loop must advance exactly one iteration per call even
    with boost_rounds_per_dispatch set (cv()'s round counting depends on
    it)."""
    X, y, _ = data
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "boost_rounds_per_dispatch": 4}
    b = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
    b.update()
    assert b._boosting.iter == 1


# ------------------------------------------------- dispatch-count budget
def test_block_dispatch_budget(data):
    """A warm K-block is <= 2 dispatches (it is ONE: the score add rides
    the scan carry; the per-iteration mode's budget was 2)."""
    X, y, _ = data
    if not profiling.install_dispatch_hook():
        pytest.skip("dispatch hook unavailable on this jax")
    try:
        p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "boost_rounds_per_dispatch": 4}
        b = lgb.Booster(params=p,
                        train_set=lgb.Dataset(X, label=y, params=p))
        bo = b._boosting
        bo._block_target = 12
        b.update()                      # block 0-3 (compiles)
        with profiling.dispatch_scope() as d:
            b.update()                  # block 4-7, warm
        assert bo.iter == 8
        assert d["dispatches"] <= 2, d
    finally:
        profiling.uninstall_dispatch_hook()


# ------------------------------------------- constant-folding hoist
def test_fused_program_has_no_dataset_constants(data):
    """The traced fused block must close over (almost) nothing: every
    dataset-sized array — objective label/weight/derived tables, feature
    meta, bundle/forced/CEGB tables — enters as an operand. Closure
    constants become HLO constants whose label-derived subexpressions
    XLA constant-folds at COMPILE time (>6 s per instruction at 10.5M
    rows, BENCH_r04); this pins the hoist."""
    import jax
    X, y, _ = data
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    b = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
    bo = b._boosting
    step, bind = bo._fused_step_fn(bo._hist_method(), False, k_rounds=4)
    jaxpr = jax.make_jaxpr(step.__wrapped__)(
        *bo._fused_call_args(None, bind))
    const_bytes = sum(np.asarray(c).nbytes for c in jaxpr.consts)
    # a handful of scalars (PRNG keys fold in as pairs) is fine; a single
    # retained [N] array would be 6000 bytes at this shape
    assert const_bytes < 1024, (
        f"{const_bytes} bytes of closure constants in the fused program: "
        f"{[np.asarray(c).shape for c in jaxpr.consts]}")
    # and the objective's device tables really are operands
    assert "label_sign" in bind["obj_consts"]


# ------------------------------------------------- checkpoint alignment
def test_checkpoint_period_not_multiple_of_k_rejected(data, tmp_path):
    X, y, _ = data
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "boost_rounds_per_dispatch": 4}
    cb = callback_mod.checkpoint(str(tmp_path), period=3)
    with pytest.raises(LightGBMError, match="multiple of"):
        lgb.train(p, lgb.Dataset(X, label=y, params=p), 8, callbacks=[cb])


def test_misaligned_period_ok_when_schedule_disables_blocks(data, tmp_path):
    """A reset_parameter schedule disables blocking, making the run
    per-iteration — a checkpoint period that is not a multiple of K must
    then be ACCEPTED (review fix: the rejection used to fire before the
    schedule fallback was decided)."""
    X, y, _ = data
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "boost_rounds_per_dispatch": 4}
    cb = callback_mod.checkpoint(str(tmp_path / "ck"), period=3)
    b = lgb.train(p, lgb.Dataset(X, label=y, params=p), 6,
                  callbacks=[cb], learning_rates=[0.1] * 6)
    assert b._boosting.iter == 6
    assert (tmp_path / "ck").exists()


# slow: boundary resume parity is CI-proven every run by
# scripts/compile_wall_smoke.py (run_suite.sh): resume + zero-
# recompile + bit-identical continuation in two real processes
@pytest.mark.slow
def test_checkpoint_block_boundary_resume_parity(data, tmp_path):
    """Kill-at-boundary + resume under K-blocks reproduces the
    uninterrupted blocked run bit-identically (checkpoints exist only at
    block boundaries, so the resumed run re-enters on a fresh aligned
    block)."""
    X, y, _ = data
    p = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 10,
         "verbosity": -1, "boost_rounds_per_dispatch": 4}
    full = _fit(X, y, p, nround=8)
    ck = str(tmp_path / "ck")
    lgb.train(p, lgb.Dataset(X, label=y, params=p), 4,
              callbacks=[callback_mod.checkpoint(ck, period=4)])
    resumed = lgb.train(p, lgb.Dataset(X, label=y, params=p), 8,
                        callbacks=[callback_mod.checkpoint(ck, period=4)],
                        resume_from=ck)
    assert resumed._boosting.iter == 8
    assert _strip(resumed.model_to_string()) == _strip(full.model_to_string())


# slow: the fallback flag is a one-line engine gate; the parity
# spelling rides the slow tier
@pytest.mark.slow
def test_reset_parameter_schedule_disables_blocks(data):
    """A per-iteration learning_rate schedule cannot ride a block
    dispatch: engine.train falls back to K=1 and the result matches the
    unblocked schedule run exactly."""
    X, y, _ = data
    rates = [0.1 + 0.01 * i for i in range(6)]
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    a = lgb.train({**p, "boost_rounds_per_dispatch": 4},
                  lgb.Dataset(X, label=y, params=p), 6,
                  learning_rates=rates)
    b = lgb.train(p, lgb.Dataset(X, label=y, params=p), 6,
                  learning_rates=rates)
    assert _strip(a.model_to_string()) == _strip(b.model_to_string())


def test_block_sentinel_names_mid_block_iteration(data):
    """The in-program NaN injection at an iteration INSIDE a block is
    caught by the [K] sentinel flag vector and named exactly."""
    X, y, _ = data
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "boost_rounds_per_dispatch": 4, "check_numerics": True,
         "fault_nan_hist_at_iter": 5}
    with pytest.raises(LightGBMError, match="iteration 5"):
        lgb.train(p, lgb.Dataset(X, label=y, params=p), 8)


# ------------------------------------------------- persistent cache
_CHILD = r"""
import json, os, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
import lightgbm_tpu as lgb
from lightgbm_tpu import callback as callback_mod
from lightgbm_tpu import compile_cache

cfg = json.loads(sys.argv[1])
rng = np.random.RandomState(7)
X = rng.normal(size=(1500, 8)).astype(np.float32)
y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(size=1500) * 0.3 > 0)
y = y.astype(np.float32)
p = {{"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 10,
     "verbosity": -1, "boost_rounds_per_dispatch": 4,
     "compile_cache_dir": cfg["cache_dir"]}}

if cfg.get("aot"):
    # in-process AOT drill (the reset_cache regression): compile ONCE
    # with NO cache configured (jax pins its cache object at the first
    # compile), then configure the cache, AOT-warm, and train one block
    # — the block must HIT what warm_start just filled, which only
    # works if configure() reset jax's pinned (dir-less) cache
    p0 = dict(p); p0.pop("compile_cache_dir")
    lgb.train(p0, lgb.Dataset(X, label=y, params=p0), 4)
    compile_cache.configure(cache_dir=cfg["cache_dir"])
    b = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
    bo = b._boosting
    assert bo.warm_start(k_rounds=4)
    before = compile_cache.module_count("misses", "jit__fused")
    bo._block_target = 4
    b.update()
    assert bo.iter == 4
    out = {{"warm_miss_delta":
           compile_cache.module_count("misses", "jit__fused") - before,
           "fused_hits": compile_cache.module_count("hits", "jit__fused")}}
else:
    cb = callback_mod.checkpoint(cfg["ckpt_dir"], period=4)
    t0 = time.time()
    b = lgb.train(p, lgb.Dataset(X, label=y, params=p), cfg["rounds"],
                  callbacks=[cb],
                  resume_from=cfg["ckpt_dir"] if cfg["resume"] else None)
    out = {{
        "wall_s": time.time() - t0,
        "iter": b._boosting.iter,
        "model": b.model_to_string(),
        "fused_misses": compile_cache.module_count("misses", "jit__fused"),
        "fused_hits": compile_cache.module_count("hits", "jit__fused"),
        "total_misses": compile_cache.totals()["misses"],
    }}
with open(cfg["out"], "w") as fh:
    json.dump(out, fh)
"""


def _run_child(cfg):
    import os
    code = _CHILD.format(repo=str(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run([sys.executable, "-c", code, json.dumps(cfg)],
                       capture_output=True, text=True, env=env,
                       timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    with open(cfg["out"]) as fh:
        return json.load(fh)


# slow: the two-process acceptance drill runs on every CI pass via
# scripts/compile_wall_smoke.py (run_suite.sh); tier-1 keeps the
# one-process AOT child (test_warm_start_aot)
@pytest.mark.slow
def test_warm_process_zero_fused_recompiles(data, tmp_path):
    """The acceptance contract (and the supervisor/gang-relaunch warm
    path): a SECOND process resuming the same-shape training from a
    checkpoint with a warm persistent cache performs ZERO fused-step XLA
    compiles — the restore-time AOT warmup and the first block both hit
    the disk cache — and continues bit-identically to the uninterrupted
    blocked run."""
    X, y, _ = data
    cache = str(tmp_path / "cache")
    ckpt = str(tmp_path / "ckpt")
    cold = _run_child({"cache_dir": cache, "ckpt_dir": ckpt, "rounds": 4,
                       "resume": False, "out": str(tmp_path / "c.json")})
    assert cold["iter"] == 4
    assert cold["fused_misses"] >= 1          # the cold compile, cached
    warm = _run_child({"cache_dir": cache, "ckpt_dir": ckpt, "rounds": 8,
                       "resume": True, "out": str(tmp_path / "w.json")})
    assert warm["iter"] == 8
    assert warm["fused_misses"] == 0, (
        f"warm incarnation recompiled the fused step: {warm}")
    assert warm["fused_hits"] >= 1
    # and the continuation is the uninterrupted run, bit for bit
    full = _fit(X, y, {"boost_rounds_per_dispatch": 4}, nround=8)
    assert _strip(warm["model"]) == _strip(full.model_to_string())


def test_warm_start_aot(tmp_path):
    """warm_start() AOT-compiles the exact program the training loop
    dispatches: the first block after it adds NO fused-step miss (it
    re-traces, but the XLA compile is served from the cache warm_start
    just filled). Runs in a SUBPROCESS because configuring the
    persistent cache is process-global (pointing the whole pytest
    process at a test-scoped dir would tax every later compile) — and
    the child first compiles WITHOUT the cache, pinning jax's dir-less
    cache object, which regression-tests configure()'s reset_cache."""
    out = _run_child({"cache_dir": str(tmp_path / "cache"), "aot": True,
                      "out": str(tmp_path / "aot.json")})
    assert out["warm_miss_delta"] == 0, out
    assert out["fused_hits"] >= 1, out


@pytest.mark.slow
def test_engine_warm_aot(data):
    """PredictEngine.warm_aot compiles the serve bucket's accumulation
    program ahead of traffic (keyed like the bucket cache)."""
    X, y, _ = data
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    b = _fit(X, y, p, nround=3)
    bo = b._boosting
    eng = bo._predict_engine()
    ts = bo.train_set
    assert eng.warm_aot(4096, ts.num_used_features(), np.int32,
                        ts.missing_bin)
    # the serve variant (donated carry — the program _serve_chunk
    # dispatches; a different HLO module from the plain one)
    assert eng.warm_aot(4096, ts.num_used_features(), np.int32,
                        ts.missing_bin, serve=True)
