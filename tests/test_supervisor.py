"""Distributed training supervisor suite: heartbeat liveness, the
collective-deadline watchdog, and gang-restart from checkpoint.

The fault matrix (kill-rank-mid-iter, hang-rank, kill-during-checkpoint-
write, clean-run-no-restart) runs REAL 2-process localhost gangs through
``supervisor.run_supervised`` and asserts the headline property: after the
supervisor relaunches the gang from the latest valid checkpoint, the final
model text is BIT-IDENTICAL to an uninterrupted run's. The gangs train on
replicated data (the reference's ``pre_partition=false`` mode — every
rank's trainer state is identical, which is what makes a rank-0 checkpoint
restore the whole gang exactly; this container's CPU backend cannot run
cross-process XLA collectives, so the cross-process coordination exercised
here is jax.distributed init + the coordination-service barrier + the
heartbeat side-channel, which is also everything the supervisor itself
relies on).

Fast knobs run in tier-1 (clean + kill cases, the single-process watchdog,
and the unit layer); the hang and kill-during-checkpoint-write gangs ride
the slow tier — their detection mechanics (watchdog firing, suspect
naming, stale-.tmp recovery) each have a fast tier-1 sibling below."""

import os
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import distributed, supervisor
from lightgbm_tpu.checkpoint import CheckpointManager
from lightgbm_tpu.distributed import (CollectiveWatchdog,
                                      DistributedTimeoutError,
                                      HeartbeatMonitor, _progress)

pytestmark = pytest.mark.faults


def _data():
    rng = np.random.RandomState(7)
    n, f = 320, 6
    X = rng.normal(size=(n, f))
    y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
    return X, y


GANG_PARAMS = {"objective": "binary", "num_leaves": 8,
               "min_data_in_leaf": 5, "boost_from_average": False,
               "histogram_method": "scatter", "verbosity": -1,
               # the deadline is judged at every checkpoint barrier: on
               # this loaded 1-core container a 5 s deadline occasionally
               # fired on a HEALTHY slow peer mid-suite, burning a
               # spurious incarnation (restarts==2 flake) — 12 s still
               # detects the hang-rank case in seconds, far under the
               # test timeouts
               "heartbeat_interval": 0.4, "collective_deadline": 12.0}
GANG_ROUNDS = 4


def _gang_train_fn(rank, ckdir):
    """Module-level so distributed.spawn can pickle it: checkpointed,
    resumable training over the full replicated dataset."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(7)
    n, f = 256, 5
    X = rng.normal(size=(n, f))
    y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params=dict(GANG_PARAMS),
                     free_raw_data=False)
    booster = lgb.train(dict(GANG_PARAMS), ds, GANG_ROUNDS,
                        callbacks=[lgb.checkpoint_callback(ckdir, period=1)],
                        resume_from=ckdir)
    return booster.model_to_string()


_CLEAN_CACHE = {}


def _reference_model() -> str:
    """The uninterrupted run's model text. The gang trains the serial
    learner on REPLICATED data, so every rank's model equals a plain
    single-process train of the same params — computed in-process once
    (~3 s) instead of launching a reference gang per test; the slow
    clean-run gang test asserts the gang itself reproduces this text."""
    if "model" not in _CLEAN_CACHE:
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            _CLEAN_CACHE["model"] = _gang_train_fn(
                0, os.path.join(td, "unused_ck"))
    return _CLEAN_CACHE["model"]


def _run_faulted_gang(fault_env: dict, ckdir: str,
                      max_restarts: int = 2) -> supervisor.SupervisorReport:
    saved = {k: os.environ.get(k) for k in fault_env}
    os.environ.update(fault_env)
    try:
        return supervisor.run_supervised(
            _gang_train_fn, nproc=2, args=(ckdir,), devices_per_proc=1,
            checkpoint_dir=ckdir, max_restarts=max_restarts, timeout=180)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# =================================================== gang restart matrix
@pytest.mark.slow
def test_gang_clean_run_no_restart(tmp_path):
    """Clean-run-no-restart case of the matrix: an unfaulted gang runs to
    completion with zero restarts and reproduces the single-process
    reference text. (Slow tier: the kill test below and the tier-1
    integrity gang demo launch the same gang machinery; this case only
    adds the no-fault baseline.)"""
    ckdir = str(tmp_path / "ck")
    report = supervisor.run_supervised(
        _gang_train_fn, nproc=2, args=(ckdir,), devices_per_proc=1,
        checkpoint_dir=ckdir, max_restarts=0, timeout=180)
    assert report.restarts == 0
    assert report.failures == []
    assert report.result.count("Tree=") == GANG_ROUNDS
    assert report.result == _reference_model()


@pytest.mark.slow
def test_gang_kill_rank_mid_iter_bit_identical(tmp_path):
    """PR 5's acceptance bar: rank 1 is hard-killed (os._exit 137) at the
    start of iteration 3; the supervisor reaps the gang, relaunches it
    once with the fault disarmed, the gang resumes from the latest
    checkpoint, and the final model text equals the uninterrupted run's
    byte for byte. Slow: tier-1 siblings cover the machinery —
    test_integrity.py::test_supervised_corrupt_rank_restart_bit_identical
    (the same supervisor restart-from-checkpoint -> bit-identical path on
    a 3-rank gang, driven by a divergence exit instead of a kill) and
    test_gang_shrink_on_spawn_fail (exit-code classification + relaunch);
    the kill-specific 137 classification is one table entry both share."""
    clean = _reference_model()
    ckdir = str(tmp_path / "ck")
    report = _run_faulted_gang(
        {"LGBM_TPU_FAULT_KILL_RANK_AT_ITER": "1:3"}, ckdir)
    assert report.restarts == 1
    assert len(report.failures) == 1
    fl = report.failures[0]
    assert 1 in fl.failed_ranks
    assert fl.exit_codes.get(1) == 137
    assert report.result == clean
    # telemetry: the restart count is on record as a health gauge (the
    # bench.py health JSON reads it)
    from lightgbm_tpu.utils import profiling
    assert profiling.gauges().get("supervisor_restarts") == 1.0
    # flight recorder (telemetry acceptance): the killed rank flushed
    # its per-iteration ring into the diag dir before os._exit — the
    # JSONL validates and its last record names the in-flight iteration
    # with phase/health state (the relaunched incarnation writes
    # .r1.jsonl files, so the post-mortem survives the restart)
    from lightgbm_tpu import telemetry
    flight = os.path.join(ckdir, "supervisor_diag", "flight_rank1.jsonl")
    assert os.path.exists(flight), "killed rank left no flight recorder"
    recs, errors = telemetry.validate_flight_jsonl(flight)
    assert errors == []
    flush = recs[-1]
    assert flush["type"] == "flush"
    assert "at iteration 3" in flush["reason"]
    assert flush["health"]["last_iteration"] == 2
    iters = [r for r in recs if r["type"] == "iter"]
    assert iters and iters[-1]["iteration"] == 2


@pytest.mark.slow
def test_gang_hang_rank_watchdog_fires_bit_identical(tmp_path):
    """Hung-rank case: rank 1 hangs at iteration 2. Rank 0 proceeds to the
    next checkpoint barrier and its collective_deadline expires there; the
    watchdog diagnosis (written for the supervisor) names the suspect rank
    and the last completed iteration, the gang relaunches, and the final
    model is bit-identical. (Fast tier-1 siblings: the single-process
    watchdog tests + suspect-table unit tests below.)"""
    clean = _reference_model()
    ckdir = str(tmp_path / "ck")
    t0 = time.time()
    report = _run_faulted_gang(
        {"LGBM_TPU_FAULT_HANG_RANK_AT_ITER": "1:2"}, ckdir)
    assert report.restarts == 1
    fl = report.failures[0]
    assert fl.watchdog_fired
    # the watchdog terminated the stall within the deadline (plus launch
    # overheads), not after the supervisor's 180s incarnation timeout
    assert time.time() - t0 < 120
    diags = fl.watchdog
    assert diags, "no watchdog diagnosis written"
    d = diags[0]
    assert d["suspects"] == [1]
    assert d["iteration"] >= 1          # completed iters before the stall
    assert d["deadline"] == GANG_PARAMS["collective_deadline"]
    # the diagnosis references the firing rank's flushed flight-recorder
    # JSONL (telemetry.py): stall verdict + per-iteration post-mortem
    # travel together through the supervisor report
    assert d.get("flight_recorder"), "diagnosis lacks flight_recorder ref"
    assert report.failures[0].flight_recorders
    from lightgbm_tpu import telemetry
    _, errors = telemetry.validate_flight_jsonl(d["flight_recorder"])
    assert errors == []
    assert report.result == clean


@pytest.mark.slow
def test_gang_kill_during_checkpoint_write_bit_identical(tmp_path):
    """Writer killed MID-CHECKPOINT (payload files staged, manifest not):
    the stale ckpt_N.tmp is ignored, the gang restarts from the previous
    valid checkpoint, the next write cleans the staging dir, and the final
    model is bit-identical. (Fast tier-1 sibling: the staging-dir
    recovery tests in test_fault_tolerance.py.)"""
    clean = _reference_model()
    ckdir = str(tmp_path / "ck")
    report = _run_faulted_gang(
        {"LGBM_TPU_FAULT_KILL_IN_CKPT_WRITE": "3"}, ckdir)
    assert report.restarts == 1
    assert report.failures[0].exit_codes.get(0) == 137   # writer = rank 0
    assert report.result == clean
    # no staging junk survived the run
    assert not [e for e in os.listdir(ckdir) if e.endswith(".tmp")]


def _gang_train_fn_always_dies(rank, ckdir):
    """Kill armed through CONFIG, so the supervisor's env-stripping cannot
    disarm it on relaunch — every incarnation dies at iteration 0."""
    import lightgbm_tpu as lgb
    X = np.zeros((100, 3))
    y = np.zeros(100)
    params = {"objective": "regression", "num_leaves": 4, "verbosity": -1,
              "fault_kill_at_iter": 0}
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    lgb.train(dict(params), ds, 3)
    return "unreachable"


@pytest.mark.slow
def test_supervisor_gives_up_after_max_restarts(tmp_path):
    """A fault armed through CONFIG (not env, so restart-stripping cannot
    disarm it) kills every incarnation: the supervisor must stop at
    max_restarts and raise with the failure history, not loop forever.
    (Slow tier: the restart loop + exit-code classification it exercises
    also run in the tier-1 kill test above; only the give-up branch is
    unique here.)"""
    ckdir = str(tmp_path / "ck")
    with pytest.raises(supervisor.GangFailedError) as ei:
        supervisor.run_supervised(
            _gang_train_fn_always_dies, nproc=2, args=(ckdir,),
            devices_per_proc=1, checkpoint_dir=ckdir, max_restarts=1,
            timeout=180)
    err = ei.value
    assert len(err.failures) == 2                 # initial + 1 restart
    assert all(137 in f.exit_codes.values() for f in err.failures)
    assert "max_restarts=1" in str(err)
    assert ckdir in str(err)                      # names the resumable dir


# ======================================================= elastic gangs
@pytest.mark.slow
def test_gang_shrink_on_spawn_fail(tmp_path):
    """A rank whose SPAWN fails (exit 96) is classified permanently lost
    on the spot: the supervisor shrinks the gang 2 -> 1, the survivor
    completes training, and the SupervisorReport records the shrink (plus
    the supervisor_world_size health gauge). The final model equals the
    uninterrupted reference — replicated-serial gangs train the same
    model at every world size.

    Slow: the identical drill (permanent spawn-fail of rank 1 -> one
    2->1 shrink recorded in the SupervisorReport -> survivor completes)
    runs on every CI pass as the elastic stanza of
    scripts/supervisor_smoke.py (tests/run_suite.sh), which asserts the
    same world_size / shrinks / lost_ranks fields."""
    clean = _reference_model()
    ckdir = str(tmp_path / "ck")
    report = _run_faulted_gang(
        {"LGBM_TPU_FAULT_SPAWN_FAIL_RANK": "1"}, ckdir)
    assert report.restarts == 1
    assert report.world_size == 1
    assert len(report.shrinks) == 1
    sh = report.shrinks[0]
    assert (sh.from_nproc, sh.to_nproc, sh.lost_ranks) == (2, 1, [1])
    assert "spawn failed" in sh.reason
    fl = report.failures[0]
    assert fl.exit_codes.get(1) == distributed.SPAWN_FAIL_EXIT_CODE
    assert fl.spawn_failed_ranks == [1]
    assert report.result == clean
    from lightgbm_tpu.utils import profiling
    assert profiling.gauges().get("supervisor_world_size") == 1.0
    assert profiling.gauges().get("supervisor_shrinks") == 1.0


def _gang_train_fn_rank1_machine_dead(rank, ckdir):
    """Rank 1's 'machine' is permanently down: it dies whenever it exists,
    across every incarnation (fn-level, so the supervisor's one-shot env
    stripping cannot disarm it) — the budget-exhaustion shrink shape."""
    if rank == 1:
        os._exit(137)
    return _gang_train_fn(rank, ckdir)


@pytest.mark.slow
def test_gang_shrink_on_rank_budget_exhausted(tmp_path):
    """max_restarts accounting ACROSS a shrink: rank 1 dies every
    incarnation; with rank_restart_budget=1 the supervisor burns one
    same-size relaunch (failure 1 <= budget), then classifies rank 1
    permanently lost (failure 2 > budget), shrinks 2 -> 1, and the world-1
    gang completes — 2 restarts total, both counted against max_restarts.
    (Tier-1 sibling: test_gang_shrink_on_spawn_fail covers the shrink
    relaunch machinery; only the budget arithmetic is unique here.)"""
    clean = _reference_model()
    ckdir = str(tmp_path / "ck")
    report = supervisor.run_supervised(
        _gang_train_fn_rank1_machine_dead, nproc=2, args=(ckdir,),
        devices_per_proc=1, checkpoint_dir=ckdir, max_restarts=3,
        timeout=180, rank_restart_budget=1)
    assert report.restarts == 2
    assert report.world_size == 1
    assert len(report.shrinks) == 1
    assert report.shrinks[0].incarnation == 1      # 2nd failure triggered it
    assert "budget 1" in report.shrinks[0].reason
    assert [f.world_size for f in report.failures] == [2, 2]
    assert report.result == clean


@pytest.mark.slow
def test_shrink_respects_min_world_size_and_max_restarts(tmp_path):
    """Accounting edges: with min_world_size=2 a lost rank CANNOT shrink
    a 2-gang, so max_restarts=0 exhausts immediately — the error carries
    the failure (world size recorded, spawn-fail classified) and no
    shrink is recorded. (Slow tier: the shrink relaunch machinery is
    tier-1 via test_gang_shrink_on_spawn_fail; the give-up branch via
    test_supervisor_gives_up_after_max_restarts.)"""
    ckdir = str(tmp_path / "ck")
    os.environ["LGBM_TPU_FAULT_SPAWN_FAIL_RANK"] = "1"
    try:
        with pytest.raises(supervisor.GangFailedError) as ei:
            supervisor.run_supervised(
                _gang_train_fn, nproc=2, args=(ckdir,), devices_per_proc=1,
                checkpoint_dir=ckdir, max_restarts=0, timeout=180,
                min_world_size=2)
    finally:
        os.environ.pop("LGBM_TPU_FAULT_SPAWN_FAIL_RANK", None)
    err = ei.value
    assert len(err.failures) == 1
    assert err.failures[0].world_size == 2
    assert err.failures[0].spawn_failed_ranks == [1]


def test_heartbeat_after_shrink_no_ghost_suspects():
    """After a 3 -> 2 shrink the new gang's monitors are built for
    nproc=2 with renumbered ranks: a fully current 2-rank table implicates
    nobody — the departed rank 3 numbering must NOT resurface as a
    'missing' suspect."""
    hb = HeartbeatMonitor(0, 2, "127.0.0.1:1", interval=0.5)
    now = time.monotonic()
    _progress.reset()
    _progress.begin("step:4", 4)
    try:
        hb._server_table = {
            0: {"iter": 3, "step": 4, "recv": now},
            1: {"iter": 3, "step": 4, "recv": now},
        }
        assert hb.suspects(my_step=4, my_iter=3) == []
    finally:
        _progress.end(4)
        _progress.reset()


def test_suspects_during_relaunch_window():
    """In the window between teardown and the next incarnation's first
    ANSWERED heartbeat a non-zero rank's table is EMPTY: suspects() must
    answer None (unknown), never implicate every rank (including the
    caller). Rank 0's own table always contains at least itself, so a
    freshly relaunched rank 0 names only genuinely absent peers."""
    hb = HeartbeatMonitor(1, 2, "127.0.0.1:1", interval=0.5)
    assert hb.suspects(my_step=0, my_iter=-1) is None


# ============================================ single-process watchdog
def test_watchdog_hang_names_rank_and_iteration():
    """collective_deadline terminates a hang within the deadline and the
    error names the rank and the last completed iteration — the
    single-process shape of the acceptance criterion."""
    X, y = _data()
    params = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
              "collective_deadline": 2.0, "fault_hang_at_iter": 2}
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    t0 = time.time()
    with pytest.raises(DistributedTimeoutError) as ei:
        lgb.train(dict(params), ds, num_boost_round=6)
    elapsed = time.time() - t0
    e = ei.value
    assert e.rank == 0
    assert e.iteration == 1                     # completed 0 and 1
    assert "rank 0" in str(e)
    assert "last completed iteration 1" in str(e)
    # fired within the deadline plus compile/monitor slack, not a test
    # timeout later
    assert elapsed < 60, elapsed


def test_watchdog_clean_run_unaffected(tmp_path):
    """An armed watchdog must not perturb training: same trees as a run
    without it (only the echoed parameters block may differ). The watched
    run also checkpoints, covering the manifest health snapshot (restart
    count + progress recorded for postmortems) in the same trainings."""
    import json as _json
    X, y = _data()
    base = {"objective": "regression", "num_leaves": 7, "verbosity": -1}
    ds1 = lgb.Dataset(X, label=y, params=base, free_raw_data=False)
    plain = lgb.train(dict(base), ds1, 4).model_to_string()
    armed = {**base, "collective_deadline": 120.0, "heartbeat_interval": 0.3}
    ckdir = str(tmp_path / "ck")
    ds2 = lgb.Dataset(X, label=y, params=armed, free_raw_data=False)
    watched = lgb.train(dict(armed), ds2, 4,
                        callbacks=[lgb.checkpoint_callback(ckdir, period=2)]
                        ).model_to_string()
    assert plain.split("\nparameters:")[0] == watched.split("\nparameters:")[0]
    health = CheckpointManager(ckdir).load_latest_valid() \
        .manifest.get("health")
    assert health is not None
    assert health["restart_count"] == 0
    assert health["last_iteration"] >= 0
    assert health["collective_deadline"] == 120.0


def test_watchdog_exempts_first_step_compile(monkeypatch):
    """The first boosting step includes jit compile; a deadline shorter
    than compile time must not fire during it (step phases are judged only
    after one completed step). Verified at the unit level: a fresh
    progress state inside a long-running step:0 does not fire."""
    fired = []
    wd = CollectiveWatchdog(0.2, rank=0, supervised=False)
    monkeypatch.setattr(wd, "_fire", lambda snap: fired.append(snap))
    _progress.reset()
    _progress.begin("step:0", 0)
    try:
        wd.start()
        time.sleep(1.0)
        assert fired == []                       # exempt: no completed step
    finally:
        wd.stop()
        _progress.end(0)
    # after one completed step, a stalled step IS judged
    _progress.begin("step:1", 1)
    try:
        wd2 = CollectiveWatchdog(0.2, rank=0, supervised=False)
        monkeypatch.setattr(wd2, "_fire", lambda snap: fired.append(snap))
        wd2.start()
        time.sleep(1.0)
        assert fired and fired[0]["phase"] == "step:1"
    finally:
        wd2.stop()
        _progress.end(1)
        _progress.reset()


def test_barrier_covered_by_watchdog_phase():
    """Barriers register on the progress stack so the watchdog times them
    (the checkpoint barrier is where survivors of a dead rank stall)."""
    _progress.reset()
    with distributed.watchdog_phase("barrier:test"):
        snap = _progress.snapshot()
        assert snap["phase"] == "barrier:test"
        assert snap["phase_elapsed"] >= 0.0
    assert _progress.snapshot()["phase"] is None


# ================================================= heartbeat / suspects
def test_heartbeat_roundtrip_localhost():
    """A rank-0 server and a rank-1 client exchange liveness over the TCP
    side-channel; both ends converge on a 2-rank table."""
    port = distributed.free_port()
    hb0 = HeartbeatMonitor(0, 2, f"127.0.0.1:{port}", interval=0.2)
    hb1 = HeartbeatMonitor(1, 2, f"127.0.0.1:{port}", interval=0.2)
    _progress.reset()
    try:
        hb0.start()
        hb1.start()
        deadline = time.time() + 10
        while time.time() < deadline:
            if set(hb1.table()) == {0, 1} and set(hb0.table()) == {0, 1}:
                break
            time.sleep(0.1)
        assert set(hb0.table()) == {0, 1}
        assert set(hb1.table()) == {0, 1}      # reply carries the table
    finally:
        hb0.stop()
        hb1.stop()


def test_suspects_dead_missing_and_lagging():
    """Suspect classification over a fabricated table: a rank with a stale
    heartbeat, a rank that never reported, and a rank whose progress is
    behind the stalled step are all implicated; current ranks are not."""
    hb = HeartbeatMonitor(0, 4, "127.0.0.1:1", interval=0.5)
    now = time.monotonic()
    _progress.reset()
    _progress.begin("step:5", 5)
    try:
        hb._server_table = {
            0: {"iter": 4, "step": 5, "recv": now},
            1: {"iter": 4, "step": 5, "recv": now},          # current
            2: {"iter": 4, "step": 5, "recv": now - 60.0},   # dead
            3: {"iter": 2, "step": -1, "recv": now},         # lagging/hung
        }
        assert hb.suspects(my_step=5, my_iter=4) == [2, 3]
        # rank 4 missing entirely would also be a suspect
        hb.nproc = 5
        assert hb.suspects(my_step=5, my_iter=4) == [2, 3, 4]
    finally:
        _progress.end(5)
        _progress.reset()


def test_timeout_error_carries_diagnosis():
    e = DistributedTimeoutError(rank=3, iteration=17, suspects=[1, 2],
                                phase="step:18")
    assert e.rank == 3 and e.iteration == 17 and e.suspects == [1, 2]
    s = str(e)
    assert "rank 3" in s and "iteration 17" in s and "1, 2" in s


# ==================================================== health telemetry
def test_health_snapshot_restart_count_env(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_RESTART_COUNT", "3")
    assert distributed.health_snapshot()["restart_count"] == 3


def test_health_gauges_unit():
    """set_gauge/gauges last-value semantics + reset clears them."""
    from lightgbm_tpu.utils import profiling
    profiling.set_gauge("test_gauge", 1)
    profiling.set_gauge("test_gauge", 4.5)
    assert profiling.gauges()["test_gauge"] == 4.5
    was_enabled = profiling.enabled()
    profiling.reset()
    profiling.enable(was_enabled)
    assert "test_gauge" not in profiling.gauges()
