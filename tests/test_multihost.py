"""Multi-host bootstrap tests: 2 processes x 4 virtual CPU devices each,
connected by ``lightgbm_tpu.distributed.init`` (jax.distributed over
localhost gRPC), must grow the SAME tree as 1 process x 8 devices — the
in-process analog of the reference's two-machine socket test setup
(examples/parallel_learning/, dask.py LocalCluster tests)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

_CHILD = """
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.parallel.learners import ParallelGrower
from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
import jax.numpy as jnp

port, rank, nproc = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
machines = ",".join(f"127.0.0.1:{port}" for _ in range(nproc))
lgb.distributed.init(machines=machines, num_machines=nproc, process_id=rank)
assert jax.process_count() == nproc
assert len(jax.devices()) == 8, len(jax.devices())

rng = np.random.RandomState(21)
n, f, b = 512, 6, 16
bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
grad = rng.normal(size=n).astype(np.float32)
hess = np.ones(n, dtype=np.float32)
meta = FeatureMeta(
    num_bins=jnp.full((f,), b, jnp.int32),
    missing_type=jnp.zeros((f,), jnp.int32),
    default_bin=jnp.zeros((f,), jnp.int32),
    is_categorical=jnp.zeros((f,), bool),
    monotone=jnp.zeros((f,), jnp.int8),
    penalty=jnp.ones((f,), jnp.float32))
params = SplitParams.from_config(lgb.Config.from_params(
    {"min_data_in_leaf": 5}))
pg = ParallelGrower("data")
tree, leaf_id, _aux = pg(
    bins, grad, hess, np.ones((n,), np.float32), meta, params,
    np.ones((f,), np.float32), np.full((f,), -1, np.int32),
    max_leaves=8, num_bins=b, hist_method="scatter")
out = {
    "rank": rank,
    "num_leaves": int(tree.num_leaves),
    "features": np.asarray(tree.node_feature).tolist(),
    "thresholds": np.asarray(tree.node_threshold_bin).tolist(),
    "leaf_values": np.asarray(tree.leaf_value).tolist(),
}

# full Booster flow: multiple rounds exercise the score update + next-round
# gradients over the replicated leaf ids (every process runs the same SPMD
# program on the same full-host data)
rng2 = np.random.RandomState(5)
Xb = rng2.normal(size=(400, 5))
yb = (Xb[:, 0] + 0.5 * Xb[:, 1] > 0).astype(np.float64)
booster = lgb.train({"objective": "binary", "num_leaves": 8,
                     "tree_learner": "data", "min_data_in_leaf": 5,
                     "verbosity": -1},
                    lgb.Dataset(Xb, label=yb, params={"verbosity": -1}),
                    num_boost_round=3)
out["booster_pred"] = booster.predict(Xb[:16], raw_score=True).tolist()
print("RESULT " + json.dumps(out))
"""


def _run_procs(nproc, devices_per_proc, timeout=420, src=None):
    from lightgbm_tpu.distributed import free_port, prepare_cpu_device_env
    src = _CHILD if src is None else src
    port = free_port()
    env = dict(os.environ)
    prepare_cpu_device_env(env, devices_per_proc)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", src, str(port), str(r), str(nproc)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for r in range(nproc)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    results = []
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert line, out[-3000:]
        results.append(json.loads(line[-1][len("RESULT "):]))
    return results


def test_two_process_parity_with_single_process():
    r2 = _run_procs(2, 4)          # 2 hosts x 4 devices = global mesh of 8
    r1 = _run_procs(1, 8)          # 1 host  x 8 devices
    assert r2[0]["num_leaves"] == r1[0]["num_leaves"]
    assert r2[0]["features"] == r1[0]["features"]
    assert r2[0]["thresholds"] == r1[0]["thresholds"]
    np.testing.assert_allclose(r2[0]["leaf_values"], r1[0]["leaf_values"],
                               rtol=1e-5, atol=1e-7)
    # both ranks computed the identical replicated tree
    assert r2[0]["features"] == r2[1]["features"]
    # end-to-end Booster training (3 rounds) matches across process counts
    np.testing.assert_allclose(r2[0]["booster_pred"], r1[0]["booster_pred"],
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(r2[0]["booster_pred"], r2[1]["booster_pred"],
                               rtol=1e-6)


def test_rank_from_machines_matches_local_ip():
    from lightgbm_tpu.distributed import _rank_from_machines
    assert _rank_from_machines(["10.255.1.2:1", "127.0.0.1:2"]) == 1
    assert _rank_from_machines(["10.255.1.2:1", "10.255.1.3:2"]) is None


_CHILD_PREPART = """
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.parallel.learners import ParallelGrower
import jax.numpy as jnp

port, rank, nproc = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
machines = ",".join(f"127.0.0.1:{port}" for _ in range(nproc))
lgb.distributed.init(machines=machines, num_machines=nproc, process_id=rank)

# full problem is 512 rows; each process owns its contiguous half
rng = np.random.RandomState(31)
n, f = 512, 6
X_full = rng.normal(size=(n, f))
y_full = (X_full[:, 0] + 0.5 * X_full[:, 1] > 0).astype(np.float64)
n_loc = n // nproc
lo, hi = rank * n_loc, (rank + 1) * n_loc
X, y = X_full[lo:hi], y_full[lo:hi]

ds = lgb.distributed.load_partitioned(
    X, label=y, params={"min_data_in_leaf": 5, "verbosity": -1,
                        "bin_construct_sample_cnt": 100000})
assert ds.num_data == n
assert not ds.bins.is_fully_addressable or nproc == 1
mh = [m.to_dict() for m in ds.mappers]

# grow one tree: grad/hess are the LOCAL slices
grad = (0.5 - y).astype(np.float32)
hess = np.full((n_loc,), 0.25, np.float32)
pg = ParallelGrower("data")
from lightgbm_tpu.ops.split import SplitParams
params = SplitParams.from_config(lgb.Config.from_params(
    {"min_data_in_leaf": 5}))
tree, leaf_id, _aux = pg(
    ds.bins, grad, hess, np.ones((n_loc,), np.float32), ds.feature_meta,
    params, np.ones((ds.bins.shape[1],), np.float32), ds.missing_bin,
    max_leaves=8, num_bins=ds.max_num_bins, hist_method="scatter")
out = {
    "rank": rank,
    "mappers_digest": __import__("hashlib").md5(
        json.dumps(mh, sort_keys=True).encode()).hexdigest(),
    "features": np.asarray(tree.node_feature).tolist(),
    "thresholds": np.asarray(tree.node_threshold_bin).tolist(),
    "leaf_values": np.asarray(tree.leaf_value).tolist(),
}
print("RESULT " + json.dumps(out))
"""


def test_pre_partitioned_loading_parity():
    """distributed.load_partitioned: 2 processes each holding HALF the rows
    (bin mappers agreed via sample allgather, global row-sharded bins) must
    grow the same tree as 1 process holding everything — the analog of the
    reference's pre-partitioned loading + distributed bin finding
    (dataset_loader.cpp:843, :1046-1128)."""
    r2 = _run_procs(2, 4, src=_CHILD_PREPART)
    r1 = _run_procs(1, 8, src=_CHILD_PREPART)
    # identical mappers on both ranks (distributed bin finding agreement)
    assert r2[0]["mappers_digest"] == r2[1]["mappers_digest"]
    # and the same tree as the single-process full-data run
    assert r2[0]["features"] == r1[0]["features"]
    assert r2[0]["thresholds"] == r1[0]["thresholds"]
    np.testing.assert_allclose(r2[0]["leaf_values"], r1[0]["leaf_values"],
                               rtol=1e-5, atol=1e-7)


_CHILD_PREPART_BOOSTER = """
import json, sys, hashlib
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import lightgbm_tpu as lgb

port, rank, nproc = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
machines = ",".join(f"127.0.0.1:{port}" for _ in range(nproc))
lgb.distributed.init(machines=machines, num_machines=nproc, process_id=rank)

# full problem is 512 rows x 8 features (4 of them sparse for EFB); each
# process owns its contiguous slice
rng = np.random.RandomState(31)
n, f = 512, 8
X_full = rng.normal(size=(n, f))
X_full[:, 4:] = X_full[:, 4:] * (rng.rand(n, 4) < 0.2)
y_full = (X_full[:, 0] + 0.5 * X_full[:, 1] + X_full[:, 4] > 0).astype(np.float64)
n_loc = n // nproc
lo, hi = rank * n_loc, (rank + 1) * n_loc

ds = lgb.distributed.load_partitioned(
    X_full[lo:hi], label=y_full[lo:hi],
    params={"min_data_in_leaf": 5, "verbosity": -1,
            "bin_construct_sample_cnt": 100000})
assert ds.bundles is not None                      # EFB is ON
# boost_from_average is the reference's GlobalSyncUpByMean of per-machine
# init scores (gbdt.cpp:338-341) — mean of local log-odds differs from the
# pooled log-odds BY DESIGN, so exact 1-vs-2-process parity disables it
booster = lgb.train({"objective": "binary", "num_leaves": 8,
                     "tree_learner": "data", "min_data_in_leaf": 5,
                     "boost_from_average": False,
                     "verbosity": -1, "histogram_method": "scatter"},
                    ds, num_boost_round=4)
gb = booster._boosting
# scores (and everything per-row) stay process-local: no O(N_global) array
assert gb.train_score.shape[0] == n_loc, gb.train_score.shape
model = booster.model_to_string()
out = {
    "rank": rank,
    "score_rows": int(gb.train_score.shape[0]),
    "model_digest": hashlib.md5(model.encode()).hexdigest(),
    "pred": booster.predict(X_full[:16], raw_score=True).tolist(),
}
print("RESULT " + json.dumps(out))
"""


def test_pre_partitioned_booster_parity():
    """Full Booster training over a pre-partitioned Dataset (2 processes,
    half the rows each, EFB on, process-local scores) produces the
    bit-identical model of a single-process run on the full data — the
    Criteo-class scaling story (Experiments.rst:228-242: memory per
    machine falls with machine count)."""
    r2 = _run_procs(2, 4, src=_CHILD_PREPART_BOOSTER)
    r1 = _run_procs(1, 8, src=_CHILD_PREPART_BOOSTER)
    # identical model text on every process and across process counts
    assert r2[0]["model_digest"] == r2[1]["model_digest"]
    assert r2[0]["model_digest"] == r1[0]["model_digest"]
    np.testing.assert_allclose(r2[0]["pred"], r1[0]["pred"], rtol=1e-6)
    # each process held only its partition's scores
    assert r2[0]["score_rows"] == 256
    assert r1[0]["score_rows"] == 512


def _spawn_train_fn(rank, nproc):
    """Module-level so distributed.spawn can pickle it: each rank loads
    its half of the rows pre-partitioned and trains the full Booster."""
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(77)
    n, f = 400, 6
    X = rng.normal(size=(n, f))
    y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
    n_loc = n // nproc
    sl = slice(rank * n_loc, (rank + 1) * n_loc)
    ds = lgb.distributed.load_partitioned(
        X[sl], label=y[sl], params={"min_data_in_leaf": 5, "verbosity": -1,
                                    "bin_construct_sample_cnt": 100000})
    b = lgb.train({"objective": "binary", "num_leaves": 8,
                   "tree_learner": "data", "min_data_in_leaf": 5,
                   "boost_from_average": False, "verbosity": -1,
                   "histogram_method": "scatter"}, ds, 3)
    return b.model_to_string()


def test_spawn_orchestration():
    """distributed.spawn: the dask-analog local orchestrator (port
    discovery + machines injection + per-worker fit + rank-0 result,
    dask.py:211-330) runs a 2-process pre-partitioned Booster end to end
    and returns rank 0's model."""
    import lightgbm_tpu as lgb
    model = lgb.distributed.spawn(_spawn_train_fn, nproc=2, args=(2,),
                                  devices_per_proc=4)
    assert isinstance(model, str) and "tree" in model
    assert model.count("Tree=") == 3


def test_train_distributed_end_to_end():
    """distributed.train_distributed: the full dask-analog entry point
    (python-package/lightgbm/dask.py:211-330 _train) — per-worker data
    parts, spawned cluster, rank-0 model — returns a Booster whose model
    is bit-identical to a single-part run on the concatenated data, and
    each worker is shipped ONLY its own part (spawn per_rank_args)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(31)
    n, f = 400, 6
    X = rng.normal(size=(n, f))
    y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 8,
              "min_data_in_leaf": 5, "boost_from_average": False,
              "histogram_method": "scatter", "verbosity": -1,
              "bin_construct_sample_cnt": 100000}
    parts = [{"data": X[:n // 2], "label": y[:n // 2]},
             {"data": X[n // 2:], "label": y[n // 2:]}]
    b2 = lgb.distributed.train_distributed(params, parts, 3,
                                           devices_per_proc=4)
    b1 = lgb.distributed.train_distributed(
        params, [{"data": X, "label": y}], 3, devices_per_proc=8)
    assert b2.model_to_string() == b1.model_to_string()
    pred = b2.predict(X[:16])
    assert pred.shape == (16,) and np.isfinite(pred).all()


def test_train_distributed_rejects_serial_learner():
    import pytest
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        lgb.distributed.train_distributed(
            {"tree_learner": "serial"}, [{"data": np.zeros((4, 2)),
                                          "label": np.zeros(4)}], 1)
