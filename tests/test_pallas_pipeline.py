"""Pallas-first histogram pipeline: parity, traffic accounting, and the
quantized-gradient training mode (ops/pallas_hist.py, the primary TPU path).

Kernel-level checks run the REAL kernels through the Pallas interpreter
(``interpret=True``) so the fused leaf-channel build and the in-kernel DMA
row gather are exercised on CPU hosts; end-to-end checks train through
``hist_pallas_interpret=true``. Precision contracts under test:

- "highest": bit-exact vs the scatter reference whenever the sums are
  exactly representable (the claim a matmul formulation can actually make;
  with full-mantissa inputs the difference is f32 accumulation-order
  rounding, bounded here at the prediction level) — and bit-exact model
  TEXT vs the XLA onehot formulation of the same contraction end to end.
- "hilo": ~2^-17 relative input rounding (documented bound), counts exact.
- "q8": exact int32 accumulation — integer equality vs a numpy reference.

The ``pallas`` marker selects this suite; the TPU compile checks skip
off-TPU (run ``-m pallas`` on a TPU host to cover them).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops import pallas_hist
from lightgbm_tpu.ops.histogram import (compact_indices, histogram_tiles,
                                        resolve_method)

pytestmark = pytest.mark.pallas


def _mk(n, f, b, n_leaves=12, seed=0, representable=False, int8=False):
    """Synthetic tile-pass inputs. ``representable=True`` draws stats as
    multiples of 2^-10 with |sum| << 2^14, so every partial sum is exactly
    representable in f32 and ANY accumulation grouping gives the same
    bits — the precondition for the highest-mode bit-exactness claim."""
    rng = np.random.RandomState(seed)
    binsT = rng.randint(0, b, size=(f, n)).astype(np.uint8)
    if int8:
        stats = rng.randint(-127, 128, size=(n, 3)).astype(np.int8)
    elif representable:
        stats = (rng.randint(-1023, 1024, size=(n, 3)) / 1024.0
                 ).astype(np.float32)
        stats[:, 2] = 1.0
    else:
        stats = rng.randn(n, 3).astype(np.float32)
        stats[:, 2] = 1.0
    leaf = rng.randint(0, n_leaves, n).astype(np.int32)
    sel = np.array([0, 2, 5, 7, 9, 11, -1, -1], np.int32)
    return (jnp.asarray(binsT),
            jnp.asarray(np.ascontiguousarray(binsT.T)),
            jnp.asarray(stats), jnp.asarray(leaf), jnp.asarray(sel))


# adversarial shapes: N not a multiple of the block, F not a multiple of
# the bin-packing group (63 bins -> g=2), bins at both production settings
SHAPES = [
    pytest.param(3001, 5, 63, 512, id="n3001-f5-b63"),
    pytest.param(2049, 4, 255, 1024, id="n2049-f4-b255"),
]


@pytest.mark.parametrize("n,f,b,blk", SHAPES)
def test_highest_bit_exact_vs_scatter(n, f, b, blk):
    """Full-pass fused kernel, HIGHEST mode: bit-exact vs the scatter
    reference on exactly-representable stats."""
    binsT, bins, stats, leaf, sel = _mk(n, f, b, representable=True)
    h = pallas_hist.histogram_tiles_pallas_mode(
        binsT, stats, leaf, sel, b, block=blk, mode="highest",
        interpret=True)
    ref = histogram_tiles(bins, stats, leaf, sel, b, method="scatter")
    np.testing.assert_array_equal(np.asarray(h), np.asarray(ref))


@pytest.mark.parametrize("n,f,b,blk", SHAPES)
def test_hilo_documented_bound(n, f, b, blk):
    """Full-pass fused kernel, hilo mode: values within the documented
    ~2^-17 input-rounding bound (signed-sum cancellation amplifies the
    relative error on small cells, hence the max-scaled atol); the count
    channel is exact."""
    binsT, bins, stats, leaf, sel = _mk(n, f, b, seed=1)
    h = np.asarray(pallas_hist.histogram_tiles_pallas_mode(
        binsT, stats, leaf, sel, b, block=blk, mode="hilo", interpret=True))
    ref = np.asarray(histogram_tiles(bins, stats, leaf, sel, b,
                                     method="scatter"))
    np.testing.assert_allclose(h, ref, rtol=1e-3,
                               atol=1e-3 * np.abs(ref).max())
    np.testing.assert_array_equal(h[..., 2], ref[..., 2])


@pytest.mark.parametrize("n,f,b,blk", SHAPES)
def test_q8_exact_integer(n, f, b, blk):
    """Full-pass fused kernel, q8 mode: EXACT int32 accumulation — integer
    equality vs a numpy int64 reference."""
    binsT, bins, stats, leaf, sel = _mk(n, f, b, seed=2, int8=True)
    h = np.asarray(pallas_hist.histogram_tiles_pallas_mode(
        binsT, stats, leaf, sel, b, block=blk, mode="q8", interpret=True))
    bins_np, stats_np, leaf_np = (np.asarray(bins), np.asarray(stats),
                                  np.asarray(leaf))
    ref = np.zeros((8, f, b, 3), np.int64)
    for p_i, lv in enumerate(np.asarray(sel)):
        if lv < 0:
            continue
        rows = np.nonzero(leaf_np == lv)[0]
        for j in range(f):
            np.add.at(ref[p_i, j], bins_np[rows, j],
                      stats_np[rows].astype(np.int64))
    np.testing.assert_array_equal(h.astype(np.int64), ref)


@pytest.mark.parametrize("rung", [1, 2, 8])
@pytest.mark.parametrize("mode", ["highest", "q8"])
def test_gather_kernel_parity_rungs(rung, mode):
    """The in-kernel DMA row gather at compaction rungs 1/2/8: bit-exact
    (highest on representable stats; q8 integer) vs scatter over the same
    kept rows. The index buffer is built exactly as the grower's ladder
    builds it (compact_indices: stable order, padded with N)."""
    n, f, b = 2881, 5, 63
    binsT, bins, stats, leaf, sel = _mk(
        n, f, b, seed=3 + rung, representable=(mode == "highest"),
        int8=(mode == "q8"))
    # deeper rungs get fewer pending leaves — exactly the grower's regime
    # (subtraction makes deep tiles small) and it keeps every rung's
    # kept-row count under its buffer so the rung would really be chosen
    keep_leaves = {1: [0, 2, 5], 2: [0, 2], 8: [0]}[rung]
    keep = jnp.asarray(np.isin(np.asarray(leaf), keep_leaves))
    m = -(-(n // rung) // 64) * 64
    assert int(jnp.sum(keep)) <= m, "fixture bug: rung must fit kept rows"
    idx = compact_indices(keep, m)
    h = np.asarray(pallas_hist.histogram_tiles_pallas_mode(
        binsT, stats, leaf, sel, b, block=256, mode=mode, idx=idx,
        interpret=True))
    zero = jnp.int8(0) if mode == "q8" else jnp.float32(0.0)
    masked = jnp.where(keep[:, None], stats, zero)
    ref_m = ("onehot_q8" if mode == "q8" else "scatter")
    ref = np.asarray(histogram_tiles(bins, masked, leaf, sel, b,
                                     method=ref_m))
    n_kept_slots = len(keep_leaves)
    np.testing.assert_array_equal(h[:n_kept_slots], ref[:n_kept_slots])
    # slots whose leaves were NOT kept accumulate nothing from kept rows
    assert np.all(h[n_kept_slots:6] == 0)


def test_gather_all_padding_is_zero():
    """An index buffer of pure padding (idx == N everywhere) must produce
    an all-zero histogram: padding rows clamp to row N-1 for the DMA but
    are masked out of the leaf match."""
    n, f, b = 700, 3, 16
    binsT, bins, stats, leaf, sel = _mk(n, f, b, seed=9)
    idx = jnp.full((128,), n, jnp.int32)
    h = np.asarray(pallas_hist.histogram_tiles_pallas_mode(
        binsT, stats, leaf, sel, b, block=128, mode="hilo", idx=idx,
        interpret=True))
    assert np.all(h == 0)


def test_hilo_gather_matches_full():
    """Gather over an all-rows index buffer == the full pass, bit-for-bit
    (same block size -> same accumulation grouping)."""
    n, f, b = 1024, 4, 32
    binsT, bins, stats, leaf, sel = _mk(n, f, b, seed=5)
    idx = jnp.arange(n, dtype=jnp.int32)
    h_g = np.asarray(pallas_hist.histogram_tiles_pallas_mode(
        binsT, stats, leaf, sel, b, block=256, mode="hilo", idx=idx,
        interpret=True))
    h_f = np.asarray(pallas_hist.histogram_tiles_pallas_mode(
        binsT, stats, leaf, sel, b, block=256, mode="hilo", interpret=True))
    np.testing.assert_array_equal(h_g, h_f)


# ------------------------------------------------------- traffic accounting

def _walk_jaxpr_shapes(jaxpr, skip_primitives=("pallas_call",)):
    """All intermediate (shape, dtype) pairs produced OUTSIDE the skipped
    primitives, recursing through scan/cond/while bodies."""
    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in skip_primitives:
            continue
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                out.append((tuple(aval.shape), np.dtype(aval.dtype).name))
        for pv in eqn.params.values():
            inner = getattr(pv, "jaxpr", None)
            if inner is not None:
                out.append(_walk_jaxpr_shapes(inner, skip_primitives))
            if isinstance(pv, (list, tuple)):
                for item in pv:
                    inner = getattr(item, "jaxpr", None)
                    if inner is not None:
                        out.append(_walk_jaxpr_shapes(inner,
                                                      skip_primitives))
    flat = []
    for item in out:
        flat.extend(item if isinstance(item, list) else [item])
    return flat


def test_no_rhs_no_compacted_copy_in_jaxpr():
    """The fusion claims, asserted on the traced program: the Pallas path
    materializes neither the [N, 128] leaf-channel RHS (fusion 1) nor the
    compacted [F, M] bin-matrix copy (fusion 2) outside the kernel, while
    the XLA fallback path — the positive control that the detector works —
    does build the compacted copy."""
    n, f, b, m = 2048, 5, 63, 512
    binsT, bins, stats, leaf, sel = _mk(n, f, b)
    idx = compact_indices(leaf < 3, m)

    def fused(bins, stats, leaf, sel, binsT, idx):
        return histogram_tiles(bins, stats, leaf, sel, b,
                               method="pallas_hilo", binsT=binsT,
                               gather_idx=idx, block=256, interpret=True)

    shapes = _walk_jaxpr_shapes(
        jax.make_jaxpr(fused)(bins, stats, leaf, sel, binsT, idx).jaxpr)
    for shp, dt in shapes:
        # fusion 1: no [rows, 128] float RHS at any row count
        assert not (len(shp) == 2 and shp[1] in (128, 256)
                    and shp[0] >= m and dt in ("float32", "bfloat16")), (
            f"leaf-channel RHS materialized outside the kernel: {shp} {dt}")
        # fusion 2: no compacted bin-matrix copy in either orientation
        assert not (len(shp) == 2 and dt in ("int8", "uint8")
                    and (shp in ((f, m), (m, f)))), (
            f"compacted bin copy materialized outside the kernel: {shp}")

    def fallback(bins, stats, leaf, sel, binsT, idx):
        return histogram_tiles(bins, stats, leaf, sel, b, method="onehot",
                               binsT=binsT, gather_idx=idx, block=256)

    fb_shapes = _walk_jaxpr_shapes(
        jax.make_jaxpr(fallback)(bins, stats, leaf, sel, binsT, idx).jaxpr)
    assert any(len(shp) == 2 and dt in ("int8", "uint8")
               and shp in ((f, m), (m, f)) for shp, dt in fb_shapes), (
        "detector broken: the XLA fallback should materialize the "
        "compacted copy")


def test_traffic_model_5x_at_higgs_shape():
    """Acceptance: modeled post-fusion HBM bytes/pass <= bin matrix +
    stats + leaf ids + output, and >= 5x below the XLA onehot path at the
    Higgs0.5M shape (500k x 28 x 255 bins x 42-leaf tile)."""
    n, f, b, p, s = 500_000, 28, 255, 42, 3
    for mode in ("hilo", "highest", "q8"):
        t = pallas_hist.traffic_model(n, f, b, p, s, mode)
        stat_b = 1 if mode == "q8" else 4
        budget = n * f + n * s * stat_b + n * 4 + t["output"]
        assert t["fused"] <= budget, (mode, t)
        assert t["xla_onehot"] / t["fused"] >= 5, (mode, t)
        # and the pre-fusion kernel (XLA-side [N,128] RHS) is also beaten
        assert t["prefusion"] / t["fused"] >= 5, (mode, t)


# ------------------------------------------------------------- end to end

def _tree_text(booster):
    """Model text with the embedded parameter dump stripped (it names the
    histogram method, which legitimately differs between parity runs)."""
    return "\n".join(l for l in booster.model_to_string().splitlines()
                     if not l.startswith("[") and l != "end of parameters")


@pytest.fixture(scope="module")
def e2e_models():
    """One small well-separated training per backend under comparison —
    shared across the e2e parity tests so the interpreter cost is paid
    once. Compaction stays ON (default ladder) so the Pallas run drives
    the gather kernel inside grow_tree's rung dispatch."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(4)
    n = 1500
    X = rng.normal(size=(n, 5))
    # well-SEPARATED split gains (distinct per-feature step sizes, little
    # noise) so structure comparisons test the backends, not coin flips
    # between near-tied noise splits
    y = (2.0 * (X[:, 0] > 0.3) + 1.0 * (X[:, 1] > -0.2)
         + 0.5 * (X[:, 2] > 0.5) + 0.01 * rng.normal(size=n))
    out = {}
    for name, params in [
        ("scatter", {"histogram_method": "scatter"}),
        ("onehot", {"histogram_method": "onehot"}),
        ("pallas", {"histogram_method": "pallas",
                    "hist_pallas_interpret": True}),
        ("pallas_nocompact", {"histogram_method": "pallas",
                              "hist_pallas_interpret": True,
                              "hist_compaction": False}),
    ]:
        ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
        booster = lgb.train({"objective": "regression", "num_leaves": 8,
                             "verbosity": -1, **params},
                            ds, num_boost_round=4)
        out[name] = (_tree_text(booster), booster.predict(X))
    return out


def test_e2e_text_parity_vs_onehot(e2e_models):
    """hist_method=pallas (HIGHEST) model text is BIT-IDENTICAL to the XLA
    onehot formulation end to end — the kernel is a drop-in replacement
    for its reference formulation, compaction rungs included."""
    assert e2e_models["pallas"][0] == e2e_models["onehot"][0]


def test_e2e_gather_path_is_inert(e2e_models):
    """Compaction ON (gather kernel inside the ladder) vs OFF (full-pass
    kernel only): identical split structure, predictions within f32
    accumulation-order rounding. (Not bit-text: the full pass interleaves
    the non-tile rows as zero contributions, which lands the kept rows in
    different SIMD reduction lanes than the compacted pass — the same
    pass-shape tolerance test_compaction documents for the XLA ladder.)"""
    def structure(text):
        return [l for l in text.splitlines()
                if l.startswith(("split_feature", "threshold"))]
    assert structure(e2e_models["pallas"][0]) == \
        structure(e2e_models["pallas_nocompact"][0])
    np.testing.assert_allclose(e2e_models["pallas"][1],
                               e2e_models["pallas_nocompact"][1],
                               rtol=1e-6, atol=1e-6)


def test_e2e_structure_parity_vs_scatter(e2e_models):
    """vs the scatter reference: identical split structure (features +
    thresholds), predictions within f32 accumulation-order rounding (the
    matmul formulations regroup partial sums; same bound test_compaction
    documents for the onehot backend)."""
    def structure(text):
        return [l for l in text.splitlines()
                if l.startswith(("split_feature", "threshold",
                                 "decision_type", "left_child",
                                 "right_child", "num_leaves"))]
    assert structure(e2e_models["pallas"][0]) == \
        structure(e2e_models["scatter"][0])
    np.testing.assert_allclose(e2e_models["pallas"][1],
                               e2e_models["scatter"][1],
                               rtol=1e-6, atol=1e-6)


def test_quantized_grad_resolution():
    """Config.quantized_grad maps every method family onto its q8 twin:
    the Pallas kernel wherever kernels run (TPU, or interpret for tests),
    the XLA int8 contraction elsewhere — never silently non-quantized."""
    on_cpu = jax.default_backend() != "tpu"
    want_plain = "onehot_q8" if on_cpu else "pallas_q8"
    assert resolve_method("auto", quantized=True) == want_plain
    assert resolve_method("auto", quantized=True,
                          interpret=True) == "pallas_q8"
    assert resolve_method("pallas_hilo", quantized=True,
                          interpret=True) == "pallas_q8"
    assert resolve_method("scatter", quantized=True) == "onehot_q8"
    assert resolve_method("onehot_hilo", quantized=True) == "onehot_q8"
    # and without the flag, auto off-TPU keeps the scatter fast path
    # unless interpret asks for the kernel pipeline
    if on_cpu:
        assert resolve_method("auto") == "scatter"
        assert resolve_method("auto", interpret=True) == "pallas_hilo"
        assert resolve_method("auto", deterministic=True,
                              interpret=True) == "pallas"


@pytest.mark.slow
def test_quantized_grad_end_to_end():
    """quantized_grad=true trains end to end (int8 stochastic-rounding
    grad/hess, exact int32 histograms, f32 rescale at split time) with
    accuracy close to full precision.

    Slow: a pure quality claim (two 15-round trainings for an accuracy
    bar). The q8 MECHANICS stay tier-1: end-to-end q8 training via
    test_split_fusion.py::test_e2e_fusion_bit_parity_xla[q8] (both
    fusion legs train q8), the in-kernel dequant via the q8 epilogue
    unit parity there, and the kernel smoke
    (scripts/kernel_bench.py --fast --interpret, every CI pass) runs
    the q8 mode. The refusal contract is tier-1 below
    (test_quantized_grad_refuses_f64_hist)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(7)
    n = 3000
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.6 * X[:, 1] + 0.2 * rng.normal(size=n) > 0).astype(
        np.float64)

    def acc(params):
        ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
        booster = lgb.train({"objective": "binary", "num_leaves": 31,
                             "verbosity": -1, **params},
                            ds, num_boost_round=15)
        return float(np.mean((booster.predict(X) > 0.5) == (y > 0.5)))

    a_full = acc({})
    a_q8 = acc({"quantized_grad": True})
    assert a_q8 >= a_full - 0.01, (a_full, a_q8)


def test_quantized_grad_refuses_f64_hist():
    """The contradictory int8-grad + f64-histogram combination is
    refused at train start (extracted from the slow end-to-end quality
    test so the contract stays tier-1)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(7)
    X = rng.normal(size=(80, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    with pytest.raises(ValueError, match="quantized_grad and gpu_use_dp"):
        ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
        lgb.train({"objective": "binary", "quantized_grad": True,
                   "gpu_use_dp": True, "verbosity": -1}, ds,
                  num_boost_round=1)


def test_autotune_hook():
    """autotune_hist: a no-op off-TPU (no timing, defaults returned);
    force_measure runs the interpreter candidates, returns a candidate
    block + the structural 128-lane leaf batch, and caches per shape
    bucket — KEYED on the epilogue flag (ISSUE 12: a block tuned for the
    plane-returning kernel must never replay into the epilogue kernel)."""
    rng = np.random.RandomState(8)
    binsT = jnp.asarray(rng.randint(0, 16, size=(3, 600)).astype(np.int8))
    if jax.default_backend() != "tpu":
        assert pallas_hist.autotune_hist(binsT, 16) == \
            {"block": 0, "tile_leaves": 0, "epilogue": False}
    tuned = pallas_hist.autotune_hist(binsT, 16, mode="hilo",
                                      block_candidates=(512, 1024),
                                      force_measure=True)
    assert tuned["tile_leaves"] == 42                 # 128 // 3
    assert tuned["block"] in (0, 512, 1024)
    assert tuned["epilogue"] is False
    key = (3, 16, 600 .bit_length(), "hilo", False)
    assert pallas_hist._tuned[key] == tuned
    # cache hit: identical dict back without re-measuring
    assert pallas_hist.autotune_hist(binsT, 16, mode="hilo",
                                     force_measure=True) == tuned
    # the epilogue form sweeps and caches under its OWN key: the two
    # kernel forms never share a tuned block
    tuned_epi = pallas_hist.autotune_hist(binsT, 16, mode="hilo",
                                          block_candidates=(512,),
                                          force_measure=True,
                                          epilogue=True)
    assert tuned_epi["epilogue"] is True
    key_epi = (3, 16, 600 .bit_length(), "hilo", True)
    assert pallas_hist._tuned[key_epi] == tuned_epi
    assert key != key_epi and pallas_hist._tuned[key] == tuned


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="real Mosaic compile needs a TPU backend")
def test_tpu_compile_all_modes():
    """TPU-only: both kernel forms COMPILE (Mosaic, not interpreter) for
    every mode at a production-like small shape. Kept out of tier-1 by the
    skip; ``-m pallas`` on a TPU host runs it."""
    n, f, b = 4096, 8, 255
    binsT, bins, stats, leaf, sel = _mk(n, f, b)
    stats8 = jnp.asarray(np.random.RandomState(0).randint(
        -127, 128, size=(n, 3)).astype(np.int8))
    idx = jnp.arange(2048, dtype=jnp.int32)
    for mode in ("hilo", "highest", "q8"):
        st = stats8 if mode == "q8" else stats
        h = pallas_hist.histogram_tiles_pallas_mode(
            binsT, st, leaf, sel, b, block=1024, mode=mode)
        h.block_until_ready()
        hg = pallas_hist.histogram_tiles_pallas_mode(
            binsT, st, leaf, sel, b, block=1024, mode=mode, idx=idx)
        hg.block_until_ready()
