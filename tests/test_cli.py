"""CLI application, native parser, refit, codegen, save_binary.

Mirrors the reference's CLI consistency harness (reference:
tests/cpp_tests/{train,predict}.conf + test.py comparing prediction files,
tests/python_package_test/test_consistency.py)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import main as cli_main
from lightgbm_tpu.native import native_available, parse_text_file

from conftest import REFERENCE_DATA_REASON, reference_data_available

EXAMPLES = "/root/reference/examples"

# tests binding to the reference's example files skip cleanly when the
# checkout is absent (previously: 2 OSError FAILURES in the native-parser
# tests + a fixture ERROR per workdir consumer — environment noise, not
# regressions). The csv/qid tests below are self-contained and still run.
needs_reference_data = pytest.mark.skipif(
    not reference_data_available(), reason=REFERENCE_DATA_REASON)


@pytest.fixture(scope="module")
def workdir(tmp_path_factory):
    if not reference_data_available():
        pytest.skip(REFERENCE_DATA_REASON)
    d = tmp_path_factory.mktemp("cli")
    for f in ("binary.train", "binary.test"):
        src = os.path.join(EXAMPLES, "binary_classification", f)
        (d / f).write_bytes(open(src, "rb").read())
    # tests chdir into the workdir; restore so the leaked CWD cannot
    # break later tests sharing the pytest process (relative paths)
    orig = os.getcwd()
    yield d
    os.chdir(orig)


@needs_reference_data
def test_native_parser_matches_numpy():
    path = os.path.join(EXAMPLES, "binary_classification", "binary.train")
    mat, fmt = parse_text_file(path)
    ref = np.loadtxt(path)
    assert fmt == "tsv"
    np.testing.assert_allclose(mat, ref)


@needs_reference_data
def test_native_parser_libsvm():
    path = os.path.join(EXAMPLES, "lambdarank", "rank.train")
    mat, fmt = parse_text_file(path)
    assert fmt == "libsvm"
    from sklearn.datasets import load_svmlight_file
    X, y = load_svmlight_file(path, zero_based=False)
    dense = np.asarray(X.todense())
    np.testing.assert_allclose(mat[:, 0], y)
    # raw index j maps to our column j+1; sklearn (1-based) col j-1
    np.testing.assert_allclose(mat[:, 2:2 + dense.shape[1]], dense)


def test_native_parser_csv_missing(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("a,b,c\n1,2.5,3\n4,,6\n7,8,na\n")
    mat, fmt = parse_text_file(str(p), has_header=True)
    assert fmt == "csv"
    assert mat.shape == (3, 3)
    assert np.isnan(mat[1, 1]) and np.isnan(mat[2, 2])


def test_cli_train_predict_consistency(workdir):
    """CLI-trained model must match Python-trained predictions
    (the reference's consistency-test contract)."""
    os.chdir(workdir)
    cli_main(["task=train", "objective=binary", "data=binary.train",
              "num_trees=10", "num_leaves=15", "output_model=model.txt",
              "verbosity=-1"])
    assert os.path.exists("model.txt")
    cli_main(["task=predict", "data=binary.test", "input_model=model.txt",
              "output_result=preds.txt", "verbosity=-1"])
    cli_preds = np.loadtxt("preds.txt")

    tr = np.loadtxt("binary.train")
    te = np.loadtxt("binary.test")
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    ds = lgb.Dataset(tr[:, 1:], label=tr[:, 0], params=params)
    booster = lgb.train(params, ds, num_boost_round=10)
    py_preds = booster.predict(te[:, 1:])
    np.testing.assert_allclose(cli_preds, py_preds, rtol=1e-5, atol=1e-7)


def test_cli_save_binary_round_trip(workdir):
    os.chdir(workdir)
    cli_main(["task=save_binary", "data=binary.train", "verbosity=-1"])
    assert os.path.exists("binary.train.bin")
    # training from the .bin file gives identical results to text
    cli_main(["task=train", "objective=binary", "data=binary.train.bin",
              "num_trees=5", "output_model=model_bin.txt", "verbosity=-1"])
    cli_main(["task=train", "objective=binary", "data=binary.train",
              "num_trees=5", "output_model=model_txt.txt", "verbosity=-1"])
    m1 = open("model_bin.txt").read().split("feature_importances")[0]
    m2 = open("model_txt.txt").read().split("feature_importances")[0]
    assert m1 == m2


def test_cli_snapshot(workdir):
    # snapshot_freq now rides the atomic checkpoint subsystem: manifest-
    # validated ckpt_N directories under <output_model>.ckpt instead of
    # in-place .snapshot_iter_N dumps
    os.chdir(workdir)
    cli_main(["task=train", "objective=binary", "data=binary.train",
              "num_trees=6", "snapshot_freq=2", "output_model=snap.txt",
              "verbosity=-1"])
    from lightgbm_tpu.checkpoint import CheckpointManager
    mgr = CheckpointManager("snap.txt.ckpt")
    # keep=2 (checkpoint_keep default) retains the two newest checkpoints
    assert [it for it, _ in mgr.checkpoints()] == [4, 6]
    ck = mgr.load_latest_valid()
    assert ck.iteration == 6
    snap = lgb.Booster(model_str=ck.model_text)
    assert snap.num_trees() == 6
    # rerunning the same command auto-resumes from the checkpoint (nothing
    # left to train) and still writes the final model
    cli_main(["task=train", "objective=binary", "data=binary.train",
              "num_trees=6", "snapshot_freq=2", "output_model=snap.txt",
              "verbosity=-1"])
    assert lgb.Booster(model_file="snap.txt").num_trees() == 6


def test_refit_improves_on_shifted_labels(workdir):
    os.chdir(workdir)
    tr = np.loadtxt("binary.train")
    X, y = tr[:, 1:], tr[:, 0]
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    booster = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                        num_boost_round=10)
    # refit leaf values on flipped labels: predictions must track the flip
    y_flip = 1.0 - y
    refitted = booster.refit(X, y_flip, decay_rate=0.0)
    from sklearn.metrics import log_loss
    orig_ll = log_loss(y_flip, booster.predict(X))
    refit_ll = log_loss(y_flip, refitted.predict(X))
    assert refit_ll < orig_ll
    # structure unchanged: identical leaf assignments
    np.testing.assert_array_equal(booster.predict(X[:50], pred_leaf=True),
                                  refitted.predict(X[:50], pred_leaf=True))


def test_convert_model_compiles_and_matches(workdir, tmp_path):
    os.chdir(workdir)
    cli_main(["task=train", "objective=binary", "data=binary.train",
              "num_trees=5", "num_leaves=7", "output_model=m5.txt",
              "verbosity=-1"])
    cli_main(["task=convert_model", "input_model=m5.txt",
              "convert_model=m5.cpp", "verbosity=-1"])
    code = open("m5.cpp").read()
    assert "PredictTree0" in code and "double Predict(" in code
    harness = tmp_path / "main.cpp"
    harness.write_text(
        '#include <cstdio>\n#include "m5.cpp"\n'
        "int main(){double f[28];double l;FILE*fp=fopen(\"binary.test\",\"r\");"
        "for(int r=0;r<20;++r){fscanf(fp,\"%lf\",&l);"
        "for(int i=0;i<28;++i)fscanf(fp,\"%lf\",&f[i]);"
        'printf("%.10f\\n",lightgbm_tpu_model::Predict(f));}return 0;}\n')
    exe = tmp_path / "m5run"
    proc = subprocess.run(["g++", "-O1", "-std=c++17", str(harness),
                           f"-I{workdir}", "-o", str(exe)],
                          capture_output=True, cwd=workdir)
    if proc.returncode != 0:
        pytest.fail(f"codegen did not compile: {proc.stderr.decode()[:500]}")
    out = subprocess.run([str(exe)], capture_output=True, cwd=workdir)
    cpp = np.array([float(x) for x in out.stdout.split()])
    booster = lgb.Booster(model_file="m5.txt")
    te = np.loadtxt("binary.test")
    np.testing.assert_allclose(cpp, booster.predict(te[:20, 1:]),
                               rtol=1e-5, atol=1e-7)


def test_cli_weight_side_file(workdir):
    os.chdir(workdir)
    tr = np.loadtxt("binary.train")
    w = np.ones(len(tr))
    w[:100] = 5.0
    np.savetxt("binary.train.weight", w)
    try:
        cli_main(["task=train", "objective=binary", "data=binary.train",
                  "num_trees=3", "output_model=mw.txt", "verbosity=-1"])
        assert os.path.exists("mw.txt")
    finally:
        os.remove("binary.train.weight")


def test_two_round_loading_matches_in_memory(workdir):
    """task=train with use_two_round_loading=true streams the file twice
    (sample + bin per chunk; raw matrix never resident) and must produce
    the same model as in-memory loading when the bin sample covers all
    rows (reference: dataset_loader.cpp:225-244)."""
    os.chdir(workdir)
    common = ["task=train", "data=binary.train", "objective=binary",
              "num_leaves=15", "num_iterations=5", "verbosity=-1",
              "bin_construct_sample_cnt=100000"]
    cli_main(common + ["output_model=m_mem.txt"])
    cli_main(common + ["two_round=true", "output_model=m_2r.txt"])
    b_mem = lgb.Booster(model_file=str(workdir / "m_mem.txt"))
    b_2r = lgb.Booster(model_file=str(workdir / "m_2r.txt"))
    X = np.loadtxt(str(workdir / "binary.test"))[:, 1:]
    np.testing.assert_allclose(b_2r.predict(X), b_mem.predict(X), rtol=1e-6)


def test_two_round_small_chunks(workdir, monkeypatch):
    """Chunk boundaries must not change the result: force tiny chunks so
    every code path (carry lines, many chunks) is exercised."""
    import lightgbm_tpu.cli as cli_mod
    os.chdir(workdir)
    orig = cli_mod._iter_parsed_chunks

    def tiny_chunks(path, config, chunk_bytes=64 << 20):
        return orig(path, config, chunk_bytes=8192)

    monkeypatch.setattr(cli_mod, "_iter_parsed_chunks", tiny_chunks)
    common = ["task=train", "data=binary.train", "objective=binary",
              "num_leaves=15", "num_iterations=5", "verbosity=-1",
              "bin_construct_sample_cnt=100000"]
    cli_main(common + ["two_round=true", "output_model=m_2r_tiny.txt"])
    cli_main(common + ["output_model=m_mem_tiny.txt"])   # self-contained
    b_tiny = lgb.Booster(model_file=str(workdir / "m_2r_tiny.txt"))
    b_mem = lgb.Booster(model_file=str(workdir / "m_mem_tiny.txt"))
    X = np.loadtxt(str(workdir / "binary.test"))[:, 1:]
    np.testing.assert_allclose(b_tiny.predict(X), b_mem.predict(X),
                               rtol=1e-6)


def test_two_round_valid_sets_match_in_memory(workdir):
    """two_round also streams VALIDATION files (binned against the train
    mappers); recorded metrics must match the in-memory path."""
    os.chdir(workdir)
    common = ["task=train", "data=binary.train", "valid=binary.test",
              "objective=binary", "metric=auc", "num_leaves=15",
              "num_iterations=5", "verbosity=-1",
              "bin_construct_sample_cnt=100000"]
    cli_main(common + ["output_model=m_v_mem.txt"])
    cli_main(common + ["two_round=true", "output_model=m_v_2r.txt"])
    b1 = lgb.Booster(model_file=str(workdir / "m_v_mem.txt"))
    b2 = lgb.Booster(model_file=str(workdir / "m_v_2r.txt"))
    X = np.loadtxt(str(workdir / "binary.test"))[:, 1:]
    np.testing.assert_allclose(b2.predict(X), b1.predict(X), rtol=1e-6)


def test_qid_group_column_run_order(tmp_path):
    """Query-id columns convert to group boundaries by consecutive runs in
    FILE order, not sorted id order (metadata.cpp query column)."""
    from lightgbm_tpu.cli import _qid_to_group
    np.testing.assert_array_equal(_qid_to_group(np.array([7, 7, 7, 1, 1])),
                                  [3, 2])
    np.testing.assert_array_equal(_qid_to_group(np.array([2, 2, 9, 2])),
                                  [2, 1, 1])
    np.testing.assert_array_equal(_qid_to_group(np.array([])), [])
