"""Model text save/load, JSON dump, SHAP contribs, continued training.

Mirrors the reference's model-IO behavior tests (reference:
tests/python_package_test/test_basic.py model string round trips,
test_engine.py:623-714 continued training, :1011-1117 SHAP contribs)."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_binary(n=600, f=6, seed=0, with_nan=True):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    if with_nan:
        X[::7, 2] = np.nan
    y = (X[:, 0] + 0.5 * np.nan_to_num(X[:, 2])
         + 0.1 * rng.normal(size=n) > 0).astype(float)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
          "verbosity": -1}


@pytest.fixture(scope="module")
def trained():
    X, y = _make_binary()
    ds = lgb.Dataset(X, label=y, params=PARAMS, free_raw_data=False)
    booster = lgb.train(PARAMS, ds, num_boost_round=10)
    return X, y, booster


def test_model_text_round_trip(trained):
    X, y, booster = trained
    p1 = booster.predict(X, raw_score=True)
    s = booster.model_to_string()
    assert s.startswith("tree\nversion=v3\n")
    assert "end of trees" in s
    loaded = lgb.Booster(model_str=s)
    p2 = loaded.predict(X, raw_score=True)
    np.testing.assert_allclose(p1, p2, rtol=0, atol=0)
    # converted (sigmoid) predictions too
    np.testing.assert_allclose(booster.predict(X), loaded.predict(X))


def test_model_file_round_trip(tmp_path, trained):
    X, y, booster = trained
    path = str(tmp_path / "model.txt")
    booster.save_model(path)
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(booster.predict(X), loaded.predict(X))
    assert loaded.num_trees() == booster.num_trees()
    assert loaded.feature_name() == booster.feature_name()


def test_model_string_reserialize_identical(trained):
    """Dump -> load -> dump must be byte-stable (text fixpoint)."""
    _, _, booster = trained
    s1 = booster.model_to_string()
    s2 = lgb.Booster(model_str=s1).model_to_string()
    # header + trees identical; parameters block may echo differently
    head1 = s1.split("feature_importances:")[0]
    head2 = s2.split("feature_importances:")[0]
    assert head1 == head2


def test_json_dump(trained):
    X, _, booster = trained
    model = booster.dump_model()
    assert model["version"] == "v3"
    assert model["num_class"] == 1
    assert len(model["tree_info"]) == booster.num_trees()
    # json must be serializable and the root structure navigable
    js = json.dumps(model)
    root = model["tree_info"][0]["tree_structure"]
    assert "split_feature" in root and "left_child" in root


def test_num_iteration_predict_window(trained):
    X, _, booster = trained
    p_first5 = booster.predict(X, raw_score=True, num_iteration=5)
    s = booster.model_to_string(num_iteration=5)
    loaded = lgb.Booster(model_str=s)
    assert loaded.num_trees() == 5
    np.testing.assert_allclose(p_first5, loaded.predict(X, raw_score=True))


def test_predict_contrib_sums_to_raw(trained):
    """SHAP contract: contributions + bias column == raw prediction
    (reference: test_engine.py:1011+)."""
    X, _, booster = trained
    contrib = booster.predict(X[:50], pred_contrib=True)
    assert contrib.shape == (50, X.shape[1] + 1)
    raw = booster.predict(X[:50], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-6, atol=1e-6)


def test_predict_contrib_multiclass():
    rng = np.random.RandomState(1)
    X = rng.normal(size=(300, 5))
    y = np.abs(X[:, 0] + 0.3 * rng.normal(size=300)).astype(int) % 3
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "min_data_in_leaf": 5, "verbosity": -1}
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=5)
    contrib = booster.predict(X[:20], pred_contrib=True)
    assert contrib.shape == (20, 3 * (5 + 1))
    raw = booster.predict(X[:20], raw_score=True)
    for c in range(3):
        np.testing.assert_allclose(contrib[:, c * 6:(c + 1) * 6].sum(axis=1),
                                   raw[:, c], rtol=1e-6, atol=1e-6)


def test_continued_training(trained):
    """init_model continues the ensemble (reference: test_engine.py:623-714)."""
    X, y, booster = trained
    ds2 = lgb.Dataset(X, label=y, params=PARAMS, free_raw_data=False)
    cont = lgb.train(PARAMS, ds2, num_boost_round=5, init_model=booster)
    assert cont.num_trees() == 15
    assert cont.current_iteration() == 15
    # the continued model must outperform (or match) the base on train data
    from sklearn.metrics import log_loss
    base_ll = log_loss(y, booster.predict(X))
    cont_ll = log_loss(y, cont.predict(X))
    assert cont_ll <= base_ll + 1e-6
    # save/load of the combined model is exact
    loaded = lgb.Booster(model_str=cont.model_to_string())
    np.testing.assert_allclose(cont.predict(X, raw_score=True),
                               loaded.predict(X, raw_score=True))


def test_continued_training_from_file(tmp_path, trained):
    X, y, booster = trained
    path = str(tmp_path / "init.txt")
    booster.save_model(path)
    ds2 = lgb.Dataset(X, label=y, params=PARAMS, free_raw_data=False)
    cont = lgb.train(PARAMS, ds2, num_boost_round=3, init_model=path)
    assert cont.num_trees() == 13


def test_multiclass_model_round_trip():
    rng = np.random.RandomState(3)
    X = rng.normal(size=(400, 5))
    y = (np.abs(X[:, 0]) * 2 + np.abs(X[:, 1])).astype(int) % 3
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "min_data_in_leaf": 5, "verbosity": -1}
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=4)
    loaded = lgb.Booster(model_str=booster.model_to_string())
    np.testing.assert_allclose(booster.predict(X), loaded.predict(X))
    assert loaded._boosting.num_tree_per_iteration == 3


def test_feature_importance(trained):
    X, _, booster = trained
    split_imp = booster.feature_importance("split")
    gain_imp = booster.feature_importance("gain")
    assert split_imp.sum() > 0
    assert gain_imp.sum() > 0
    assert split_imp.dtype == np.int32
    # model text echoes the same split importances
    s = booster.model_to_string()
    section = s.split("feature_importances:")[1]
    total = sum(int(line.split("=")[1]) for line in section.splitlines()
                if "=" in line and not line.startswith("["))
    assert total == split_imp.sum()


def test_predict_leaf_index(trained):
    X, _, booster = trained
    leaves = booster.predict(X[:30], pred_leaf=True)
    assert leaves.shape == (30, booster.num_trees())
    assert leaves.min() >= 0
    # loaded model produces identical leaf assignments
    loaded = lgb.Booster(model_str=booster.model_to_string())
    np.testing.assert_array_equal(leaves, loaded.predict(X[:30], pred_leaf=True))


def test_rf_average_output_round_trip():
    X, y = _make_binary(seed=5, with_nan=False)
    params = {"objective": "binary", "boosting": "rf", "num_leaves": 15,
              "bagging_freq": 1, "bagging_fraction": 0.7,
              "min_data_in_leaf": 5, "verbosity": -1}
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    booster = lgb.train(params, ds, num_boost_round=6)
    s = booster.model_to_string()
    assert "average_output" in s
    loaded = lgb.Booster(model_str=s)
    np.testing.assert_allclose(booster.predict(X, raw_score=True),
                               loaded.predict(X, raw_score=True), rtol=1e-6)
