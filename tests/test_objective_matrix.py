"""Per-objective behavior matrix over the regression/xentropy families —
the analog of the reference's giant parametrized objective coverage
(reference: tests/python_package_test/test_engine.py: test_regression,
test_quantile, test_huber, test_poisson/gamma/tweedie, test_mape,
test_xentropy; semantics from src/objective/regression_objective.hpp and
xentropy_objective.hpp)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import lightgbm_tpu as lgb


def _positive_problem(seed, n=1200):
    rng = np.random.RandomState(seed)
    X = rng.uniform(-1, 1, size=(n, 4))
    mu = np.exp(0.8 * X[:, 0] - 0.5 * X[:, 1])
    return X, mu, rng


def _train(X, y, objective, extra=None, rounds=40):
    params = {"objective": objective, "num_leaves": 15,
              "min_data_in_leaf": 20, "learning_rate": 0.1,
              "verbosity": -1, **(extra or {})}
    evals = {}
    booster = lgb.train(params, lgb.Dataset(X, label=y), rounds,
                        valid_sets=[lgb.Dataset(X, label=y)],
                        valid_names=["t"], evals_result=evals)
    return booster, evals["t"]


@pytest.mark.parametrize("objective,metric", [
    ("regression", "l2"), ("regression_l1", "l1"), ("huber", "huber"),
    ("fair", "fair"), ("mape", "mape"),
])
def test_regression_family_metric_improves(objective, metric):
    rng = np.random.RandomState(11)
    n = 1200
    X = rng.uniform(-2, 2, size=(n, 4))
    y = 2 * X[:, 0] + np.sin(2 * X[:, 1]) + 0.2 * rng.normal(size=n)
    if objective == "mape":
        y = y + 6.0          # mape needs labels away from 0
    _, ev = _train(X, y, objective)
    hist = ev[metric]
    assert hist[-1] < hist[0] * 0.8, (objective, hist[0], hist[-1])


@pytest.mark.parametrize("objective", ["poisson", "gamma", "tweedie"])
def test_positive_objectives_log_link(objective):
    """Poisson/gamma/tweedie predict via exp(score): predictions must be
    positive and the deviance metric must improve
    (regression_objective.hpp:398,677,712)."""
    X, mu, rng = _positive_problem(13)
    if objective == "poisson":
        y = rng.poisson(mu).astype(np.float64)
    else:
        y = mu * rng.gamma(2.0, 0.5, size=len(mu))
    booster, ev = _train(X, y, objective)
    pred = booster.predict(X)
    assert np.all(pred > 0)
    hist = ev[objective]
    assert hist[-1] < hist[0], (hist[0], hist[-1])
    # predictions track the conditional mean scale
    assert 0.3 < np.mean(pred) / np.mean(y) < 3.0


@pytest.mark.parametrize("alpha", [0.1, 0.5, 0.9])
def test_quantile_coverage(alpha):
    """Quantile regression's empirical coverage must approximate alpha
    (regression_objective.hpp:478 + test_engine.py quantile tests)."""
    rng = np.random.RandomState(17)
    n = 4000
    X = rng.uniform(-1, 1, size=(n, 3))
    y = X[:, 0] + rng.normal(scale=0.5, size=n)
    booster, _ = _train(X, y, "quantile", extra={"alpha": alpha}, rounds=60)
    cover = float(np.mean(y <= booster.predict(X)))
    assert abs(cover - alpha) < 0.08, (alpha, cover)


def test_huber_less_outlier_sensitive_than_l2():
    rng = np.random.RandomState(19)
    n = 2000
    X = rng.uniform(-1, 1, size=(n, 3))
    y = X[:, 0].copy()
    out_rows = rng.choice(n, 40, replace=False)
    y[out_rows] += 60.0 * rng.choice([-1, 1], size=40)   # gross outliers
    clean = np.setdiff1d(np.arange(n), out_rows)

    def clean_mse(objective):
        b, _ = _train(X, y, objective)
        p = b.predict(X)
        return float(np.mean((p[clean] - X[clean, 0]) ** 2))

    assert clean_mse("huber") < clean_mse("regression") * 0.8


def test_cross_entropy_objectives():
    """xentropy/xentlambda accept soft labels in [0, 1]
    (xentropy_objective.hpp:44,152)."""
    rng = np.random.RandomState(23)
    n = 1500
    X = rng.uniform(-2, 2, size=(n, 4))
    p_true = 1.0 / (1.0 + np.exp(-(X[:, 0] + 0.5 * X[:, 1])))
    y = np.clip(p_true + 0.1 * rng.normal(size=n), 0, 1)   # soft labels
    for objective, metric in (("cross_entropy", "cross_entropy"),
                              ("cross_entropy_lambda",
                               "cross_entropy_lambda")):
        booster, ev = _train(X, y, objective)
        pred = booster.predict(X)
        if objective == "cross_entropy":
            # sigmoid output (xentropy_objective.hpp:102-104)
            assert np.all((pred >= 0) & (pred <= 1))
        else:
            # xentlambda converts via log1p(exp(.)) — positive, unbounded
            # (xentropy_objective.hpp:233-235)
            assert np.all(pred >= 0)
        hist = ev[metric]
        assert hist[-1] < hist[0], (objective, hist[0], hist[-1])
        # predictions correlate with the underlying probability
        assert np.corrcoef(pred, p_true)[0, 1] > 0.85


def test_reg_sqrt_label_transform():
    """reg_sqrt trains on sqrt(label) and squares predictions back
    (regression_objective.hpp reg_sqrt handling)."""
    rng = np.random.RandomState(29)
    n = 1500
    X = rng.uniform(0, 1, size=(n, 3))
    y = (3 * X[:, 0] + 0.1 * rng.normal(size=n)) ** 2
    b_sqrt, _ = _train(X, y, "regression", extra={"reg_sqrt": True})
    pred = b_sqrt.predict(X)
    r2 = 1 - np.mean((pred - y) ** 2) / np.var(y)
    assert r2 > 0.8, r2


def test_objective_alias_resolution():
    """Objective aliases map like the reference's ParseObjectiveAlias."""
    rng = np.random.RandomState(31)
    X = rng.normal(size=(400, 3))
    y = X[:, 0]
    for alias in ("mse", "l2", "mean_squared_error"):
        b = lgb.train({"objective": alias, "num_leaves": 7,
                       "verbosity": -1}, lgb.Dataset(X, label=y), 3)
        assert b._boosting.objective.name in ("regression", "l2"), alias


def test_metric_formulas_match_reference_pointwise():
    """Pointwise numeric audit of the regression metric formulas against
    the reference LossOnPoint definitions (regression_metric.hpp) — the
    gamma sign and gamma_deviance scale bugs were caught this way."""
    from lightgbm_tpu import metrics as M
    from lightgbm_tpu.config import Config
    rng = np.random.RandomState(0)
    label = np.abs(rng.normal(size=300)) + 0.5
    score = np.abs(rng.normal(size=300)) + 0.5
    cfg = Config.from_params({"alpha": 0.9, "fair_c": 1.0,
                              "tweedie_variance_power": 1.5})

    d = score - label
    x = np.abs(d)
    theta = -1.0 / score
    tmp = label / (score + 1e-9)
    rho = 1.5
    expect = {
        "l2": np.mean(d ** 2),
        "l1": np.mean(x),
        "huber": np.mean(np.where(x <= 0.9, 0.5 * d * d,
                                  0.9 * (x - 0.45))),
        "fair": np.mean(x - np.log1p(x)),
        "poisson": np.mean(score - label * np.log(score)),
        "mape": np.mean(x / np.maximum(1.0, np.abs(label))),
        "gamma": np.mean(-((label * theta + np.log(-theta)) / 1.0
                           + (np.log(label) - np.log(label)))),
        # AverageLoss override: sum_loss * 2, sum_weights IGNORED
        # (regression_metric.hpp:291-293) — 2x the SUM, not a mean
        "gamma_deviance": 2.0 * np.sum(tmp - np.log(tmp) - 1.0),
        "tweedie": np.mean(-label * score ** (1 - rho) / (1 - rho)
                           + score ** (2 - rho) / (2 - rho)),
    }
    for name, ref in expect.items():
        m = M.create_metric(name, cfg)
        m.init(label, None)
        got = float(m.eval(score, None))
        np.testing.assert_allclose(got, ref, rtol=1e-9, err_msg=name)

    # weighted gamma_deviance: loss is weighted per row, but the final
    # AverageLoss divides by nothing — 2 * sum(w * loss)
    w = np.abs(rng.normal(size=300)) + 0.1
    m = M.create_metric("gamma_deviance", cfg)
    m.init(label, w)
    got = float(m.eval(score, None))
    np.testing.assert_allclose(
        got, 2.0 * np.sum(w * (tmp - np.log(tmp) - 1.0)), rtol=1e-9)


def test_gradient_formulas_match_reference_pointwise():
    """Pointwise audit of regression-family gradients/hessians against the
    reference GetGradients formulas (regression_objective.hpp:127-751)."""
    import jax.numpy as jnp
    from lightgbm_tpu import objectives as O
    from lightgbm_tpu.config import Config
    rng = np.random.RandomState(0)
    n = 300
    label_pos = np.abs(rng.normal(size=n)) + 0.5
    label_any = rng.normal(size=n)
    score = rng.normal(size=n) * 0.8
    rho = 1.5
    d = score - label_any
    checks = {
        "regression": (label_any, d, np.ones(n)),
        "regression_l1": (label_any, np.sign(d), np.ones(n)),
        "huber": (label_any,
                  np.where(np.abs(d) <= 0.9, d, np.sign(d) * 0.9),
                  np.ones(n)),
        "fair": (label_any, d / (np.abs(d) + 1.0),
                 1.0 / (np.abs(d) + 1.0) ** 2),
        "poisson": (label_pos, np.exp(score) - label_pos,
                    np.exp(score + 0.7)),
        # delta = score - label (regression_objective.hpp:495-500)
        "quantile": (label_any,
                     np.where(d >= 0, 1 - 0.9, -0.9), np.ones(n)),
        "gamma": (label_pos, 1.0 - label_pos * np.exp(-score),
                  label_pos * np.exp(-score)),
        "tweedie": (label_pos,
                    -label_pos * np.exp((1 - rho) * score)
                    + np.exp((2 - rho) * score),
                    -label_pos * (1 - rho) * np.exp((1 - rho) * score)
                    + (2 - rho) * np.exp((2 - rho) * score)),
    }
    for name, (lab, g_ref, h_ref) in checks.items():
        cfg = Config.from_params({"objective": name, "alpha": 0.9,
                                  "fair_c": 1.0,
                                  "tweedie_variance_power": 1.5,
                                  "poisson_max_delta_step": 0.7})
        obj = O.create_objective(cfg)
        obj.init(lab, None)
        g, h = obj.get_grad_hess(jnp.asarray(score))
        np.testing.assert_allclose(np.asarray(g), g_ref, rtol=1e-5,
                                   atol=1e-6, err_msg=f"{name} grad")
        np.testing.assert_allclose(np.asarray(h), h_ref, rtol=1e-5,
                                   atol=1e-6, err_msg=f"{name} hess")


def test_binary_multiclass_gradients_match_reference():
    """Binary (sigmoid + scale_pos_weight) and multiclass softmax gradients
    pinned to the reference formulas (binary_objective.hpp:105-121,
    multiclass_objective.hpp softmax factor k/(k-1))."""
    import jax.numpy as jnp
    from lightgbm_tpu import objectives as O
    from lightgbm_tpu.config import Config
    rng = np.random.RandomState(0)
    n = 300
    y = (rng.uniform(size=n) > 0.6).astype(np.float64)
    score = rng.normal(size=n)
    cfg = Config.from_params({"objective": "binary", "sigmoid": 2.0,
                              "scale_pos_weight": 1.3, "verbosity": -1})
    obj = O.create_objective(cfg)
    obj.init(y, None)
    g, h = obj.get_grad_hess(jnp.asarray(score))
    lab = np.where(y > 0, 1.0, -1.0)
    lw = np.where(y > 0, 1.3, 1.0)
    sig = 2.0
    resp = -lab * sig / (1.0 + np.exp(lab * sig * score))
    np.testing.assert_allclose(np.asarray(g), resp * lw, rtol=1e-4,
                               atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(h), np.abs(resp) * (sig - np.abs(resp)) * lw,
        rtol=1e-4, atol=1e-6)

    K = 3
    yk = rng.randint(0, K, size=n).astype(np.float64)
    objm = O.create_objective(Config.from_params(
        {"objective": "multiclass", "num_class": K, "verbosity": -1}))
    objm.init(yk, None)
    S = rng.normal(size=(n, K))
    gm, hm = objm.get_grad_hess(jnp.asarray(S))
    P = np.exp(S - S.max(1, keepdims=True))
    P /= P.sum(1, keepdims=True)
    Y = np.eye(K)[yk.astype(int)]
    np.testing.assert_allclose(np.asarray(gm), P - Y, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(hm),
                               K / (K - 1.0) * P * (1 - P),
                               rtol=1e-4, atol=1e-6)


def test_cv_runs_and_improves():
    """lgb.cv: stratified folds, mean/stdv curves (engine.py:392-470)."""
    rng = np.random.RandomState(7)
    n = 1200
    X = rng.normal(size=(n, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.normal(size=n) > 0).astype(
        np.float64)
    res = lgb.cv({"objective": "binary", "num_leaves": 15, "metric": "auc",
                  "verbosity": -1},
                 lgb.Dataset(X, label=y, free_raw_data=False),
                 num_boost_round=15, nfold=3, stratified=True, seed=3)
    key = [k for k in res if k.endswith("auc-mean")][0]
    curve = res[key]
    assert len(curve) == 15
    assert curve[-1] > 0.85 and curve[-1] >= curve[0] - 1e-9
    sd_key = [k for k in res if k.endswith("auc-stdv")][0]
    assert len(res[sd_key]) == 15


def test_average_precision_matches_reference_sweep():
    """average_precision must follow the reference's threshold-group sweep
    exactly (tied scores form one group whose precision is taken AFTER the
    whole group, binary_metric.hpp:270+) — ours deviated on ties."""
    from lightgbm_tpu import metrics as M
    from lightgbm_tpu.config import Config

    def ref_ap(y, score, w=None):
        order = np.argsort(-score, kind="stable")
        wv = np.ones(len(y)) if w is None else w
        cur_pos = cur_neg = sum_pos = sum_pred = accum = 0.0
        thr = score[order[0]]
        for i in order:
            if score[i] != thr:
                thr = score[i]
                sum_pos += cur_pos
                sum_pred += cur_pos + cur_neg
                accum += cur_pos * (sum_pos / sum_pred)
                cur_pos = cur_neg = 0.0
            if y[i] > 0:
                cur_pos += wv[i]
            else:
                cur_neg += wv[i]
        sum_pos += cur_pos
        sum_pred += cur_pos + cur_neg
        accum += cur_pos * (sum_pos / sum_pred)
        sw = wv.sum()
        return accum / sum_pos if (sum_pos > 0 and sum_pos != sw) else 1.0

    rng = np.random.RandomState(0)
    for use_w in (False, True):
        y = (rng.uniform(size=400) > 0.5).astype(np.float64)
        score = np.round(rng.normal(size=400), 1)       # heavy ties
        w = rng.uniform(0.5, 2.0, size=400) if use_w else None
        m = M.create_metric("average_precision", Config.from_params({}))
        m.init(y, w)
        np.testing.assert_allclose(m.eval(score, None), ref_ap(y, score, w),
                                   rtol=1e-12)


def test_ranking_metrics_match_reference():
    """NDCG@k (2^l - 1 gains, log2 discounts, ideal from sorted labels,
    empty-gain queries = 1; dcg_calculator.cpp) and MAP@k
    (map_metric.hpp:74-104 CalMapAtK denominator min(npos, k)) pinned to
    literal reference transcriptions."""
    from lightgbm_tpu import metrics as M
    from lightgbm_tpu.config import Config
    rng = np.random.RandomState(0)
    groups = np.array([10, 7, 13, 10])
    n = groups.sum()
    y_rel = rng.randint(0, 4, size=n).astype(np.float64)
    y_bin = (rng.uniform(size=n) > 0.6).astype(np.float64)
    score = rng.normal(size=n)

    def ref_ndcg(k):
        out, s = [], 0
        for g in groups:
            yy, ss = y_rel[s:s+g], score[s:s+g]
            s += g
            kk = min(k, g)
            order = np.argsort(-ss, kind="stable")
            dcg = sum((2 ** yy[order[i]] - 1) / np.log2(2 + i)
                      for i in range(kk))
            ideal = np.sort(yy)[::-1]
            idcg = sum((2 ** ideal[i] - 1) / np.log2(2 + i)
                       for i in range(kk))
            out.append(1.0 if idcg <= 0 else dcg / idcg)
        return float(np.mean(out))

    def ref_map(k):
        out, s = [], 0
        for g in groups:
            yy, ss = y_bin[s:s+g], score[s:s+g]
            s += g
            order = np.argsort(-ss, kind="stable")
            kk = min(k, g)
            npos = int(np.sum(yy > 0.5))
            hit, sap = 0, 0.0
            for j in range(kk):
                if yy[order[j]] > 0.5:
                    hit += 1
                    sap += hit / (j + 1.0)
            out.append(sap / min(npos, kk) if npos > 0 else 1.0)
        return float(np.mean(out))

    for k in (1, 3, 5):
        m = M.create_metric("ndcg", Config.from_params({"eval_at": [k]}))
        m.init(y_rel, None, groups)
        got = m.eval(score, None)
        got = got[0] if isinstance(got, (list, tuple, np.ndarray)) else got
        np.testing.assert_allclose(got, ref_ndcg(k), rtol=1e-9)
        m2 = M.create_metric("map", Config.from_params({"eval_at": [k]}))
        m2.init(y_bin, None, groups)
        got2 = m2.eval(score, None)
        got2 = got2[0] if isinstance(got2, (list, tuple, np.ndarray)) \
            else got2
        np.testing.assert_allclose(got2, ref_map(k), rtol=1e-9)


def test_lambdarank_lambdas_match_reference():
    """Lambdarank pairwise lambdas/hessians pinned to a literal
    transcription of the reference per-query loop (rank_objective.hpp:
    140-226: truncation, deltaNDCG with score-distance regularization,
    sigmoid-table-free exact sigmoid, log2 lambda normalization). Our
    get_grad_hess returns the reference's lambdas verbatim (the boosting
    loop consumes them with the same sign convention)."""
    import jax.numpy as jnp
    from lightgbm_tpu import objectives as O
    from lightgbm_tpu.config import Config
    label_gain = [2 ** i - 1 for i in range(32)]

    def ref(y, score, groups, sigmoid=2.0, trunc=30):
        g_out = np.zeros_like(score)
        h_out = np.zeros_like(score)
        s = 0
        for g in groups:
            yy, ss = y[s:s+g], score[s:s+g]
            order = np.argsort(-ss, kind="stable")
            ideal = np.sort(yy)[::-1]
            maxdcg = sum(label_gain[int(ideal[i])] / np.log2(2.0 + i)
                         for i in range(min(trunc, g)))
            inv = 1.0 / maxdcg if maxdcg > 0 else 0.0
            lam, hes = np.zeros(g), np.zeros(g)
            best, worst = ss[order[0]], ss[order[g - 1]]
            sum_lam = 0.0
            for i in range(min(g - 1, trunc)):
                for j in range(i + 1, g):
                    if yy[order[i]] == yy[order[j]]:
                        continue
                    hi_r, lo_r = ((i, j) if yy[order[i]] > yy[order[j]]
                                  else (j, i))
                    hi, lo = order[hi_r], order[lo_r]
                    d = ss[hi] - ss[lo]
                    gap = label_gain[int(yy[hi])] - label_gain[int(yy[lo])]
                    pdisc = abs(1 / np.log2(2.0 + hi_r)
                                - 1 / np.log2(2.0 + lo_r))
                    dndcg = gap * pdisc * inv
                    if best != worst:
                        dndcg /= (0.01 + abs(d))
                    p = 1.0 / (1.0 + np.exp(sigmoid * d))
                    pl = -sigmoid * dndcg * p
                    ph = sigmoid * sigmoid * dndcg * p * (1 - p)
                    lam[lo] -= pl
                    hes[lo] += ph
                    lam[hi] += pl
                    hes[hi] += ph
                    sum_lam -= 2 * pl
            if sum_lam > 0:
                nf = np.log2(1 + sum_lam) / sum_lam
                lam *= nf
                hes *= nf
            g_out[s:s+g], h_out[s:s+g] = lam, hes
            s += g
        return g_out, h_out

    rng = np.random.RandomState(0)
    groups = np.array([12, 8, 15])
    y = rng.randint(0, 4, size=groups.sum()).astype(np.float64)
    score = rng.normal(size=groups.sum())
    obj = O.create_objective(Config.from_params(
        {"objective": "lambdarank", "sigmoid": 2.0,
         "lambdarank_truncation_level": 30}))
    obj.init(y, None, groups)
    g, h = obj.get_grad_hess(jnp.asarray(score))
    g_ref, h_ref = ref(y, score, groups)
    np.testing.assert_allclose(np.asarray(g), g_ref, rtol=2e-3, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), h_ref, rtol=2e-3, atol=1e-5)


def test_auc_mu_raw_scores_and_weight_matrix():
    """auc_mu ranks by raw-score hyperplane distances (no softmax) and
    honors auc_mu_weights (multiclass_metric.hpp:238-266: decision value
    (W_i - W_j) . score scaled by t1)."""
    from lightgbm_tpu import metrics as M
    from lightgbm_tpu.config import Config
    rng = np.random.RandomState(0)
    K, n = 3, 300
    y = rng.randint(0, K, size=n).astype(np.float64)
    S = rng.normal(size=(n, K))
    m = M.create_metric("auc_mu", Config.from_params({"num_class": K}))
    m.init(y, None)
    base = m.eval(S, None)
    # raw-score ranking is invariant to per-row shifts (softmax probs are
    # not order-equivalent across rows; the old implementation failed this)
    shifted = m.eval(S + rng.normal(size=(n, 1)), None)
    np.testing.assert_allclose(base, shifted, rtol=1e-12)
    # uniform default equals mean pairwise AUC of score differences
    from sklearn.metrics import roc_auc_score
    aucs = []
    for a in range(K):
        for b in range(a + 1, K):
            mask = (y == a) | (y == b)
            aucs.append(roc_auc_score((y[mask] == a).astype(float),
                                      S[mask, a] - S[mask, b]))
    np.testing.assert_allclose(base, np.mean(aucs), rtol=1e-9)
    # a custom weight matrix changes the decision values
    mw = M.create_metric("auc_mu", Config.from_params(
        {"num_class": K, "auc_mu_weights": [0, 1, 5, 1, 0, 1, 5, 1, 0]}))
    mw.init(y, None)
    assert abs(mw.eval(S, None) - base) > 1e-4


def test_treeshap_matches_bruteforce_shapley():
    """pred_contrib equals brute-force path-dependent Shapley values
    (exact subset enumeration with cover-weighted conditional expectations
    — the semantics of the reference's TreeSHAP, tree.cpp PredictContrib)."""
    import math
    from itertools import combinations
    rng = np.random.RandomState(0)
    n, F = 600, 4
    X = rng.normal(size=(n, F))
    y = X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + 0.1 * rng.normal(size=n)
    b = lgb.train({"objective": "regression", "num_leaves": 8,
                   "min_data_in_leaf": 20, "verbosity": -1},
                  lgb.Dataset(X, label=y), 1)
    contrib = b.predict(X[:5], pred_contrib=True)
    tree = b._boosting.host_trees[0]
    sf = np.asarray(tree.split_feature)
    thr = np.asarray(tree.threshold)
    lc = np.asarray(tree.left_child)
    rc = np.asarray(tree.right_child)
    lv = np.asarray(tree.leaf_value)
    lcount = np.asarray(tree.leaf_count, float)
    icount = np.asarray(tree.internal_count, float)

    def cover(node):
        return icount[node] if node >= 0 else lcount[~node]

    def exp_f(x, S, node=0):
        if node < 0:
            return lv[~node]
        f = sf[node]
        if f in S:
            return exp_f(x, S, lc[node] if x[f] <= thr[node] else rc[node])
        wl, wr = cover(lc[node]), cover(rc[node])
        return (wl * exp_f(x, S, lc[node])
                + wr * exp_f(x, S, rc[node])) / (wl + wr)

    for r in range(5):
        phis = np.zeros(F + 1)
        for i in range(F):
            others = [f for f in range(F) if f != i]
            for k in range(F):
                for S in combinations(others, k):
                    w = (math.factorial(k) * math.factorial(F - k - 1)
                         / math.factorial(F))
                    phis[i] += w * (exp_f(X[r], set(S) | {i})
                                    - exp_f(X[r], set(S)))
        phis[F] = exp_f(X[r], set())
        np.testing.assert_allclose(contrib[r], phis, rtol=1e-5, atol=1e-7)


def test_rank_xendcg_matches_reference_pointwise():
    """Literal transcription of RankXENDCG::GetGradientsForOneQuery
    (rank_objective.hpp:301-358: softmax rho, Phi(l,g)=2^int(l)-g, the
    three cascaded correction sweeps) vs our vectorized padded program,
    sharing the same per-doc gamma draws."""
    import jax.numpy as jnp
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.ranking import RankXENDCG

    rng = np.random.RandomState(5)
    groups = np.array([1, 7, 12, 3, 2, 9])
    n = int(groups.sum())
    label = rng.randint(0, 4, size=n).astype(np.float64)
    score = np.round(rng.normal(size=n), 1)          # tie-heavy scores

    obj = RankXENDCG(Config.from_params({"objective": "rank_xendcg",
                                         "seed": 7}))
    obj.init(label, None, groups)
    gamma_pad = rng.uniform(size=obj.q_mask.shape).astype(np.float32)
    lam_pad, hess_pad = obj._padded_grads(
        jnp.asarray(score, jnp.float32)[obj.doc_index],
        jnp.asarray(gamma_pad))
    lam, hess = obj._scatter_grads(lam_pad, hess_pad)
    lam, hess = np.asarray(lam), np.asarray(hess)

    def ref_one_query(cnt, lab, sc, gam):
        lambdas = np.zeros(cnt)
        hessians = np.zeros(cnt)
        if cnt <= 1:                       # rank_objective.hpp:305-311
            return lambdas, hessians
        rho = np.exp(sc - sc.max())        # Common::Softmax (common.h:567)
        rho = rho / rho.sum()
        params = np.empty(cnt)
        inv_denominator = 0.0
        for i in range(cnt):
            params[i] = 2.0 ** int(lab[i]) - gam[i]   # Phi, :356-358
            inv_denominator += params[i]
        inv_denominator = 1.0 / max(1e-15, inv_denominator)  # kEpsilon
        sum_l1 = 0.0
        for i in range(cnt):
            term = -params[i] * inv_denominator + rho[i]
            lambdas[i] = np.float32(term)
            params[i] = term / (1.0 - rho[i])
            sum_l1 += params[i]
        sum_l2 = 0.0
        for i in range(cnt):
            term = rho[i] * (sum_l1 - params[i])
            lambdas[i] += np.float32(term)
            params[i] = term / (1.0 - rho[i])
            sum_l2 += params[i]
        for i in range(cnt):
            lambdas[i] += np.float32(rho[i] * (sum_l2 - params[i]))
            hessians[i] = np.float32(rho[i] * (1.0 - rho[i]))
        return lambdas, hessians

    bounds = np.concatenate([[0], np.cumsum(groups)])
    for q in range(len(groups)):
        b0, b1 = bounds[q], bounds[q + 1]
        cnt = b1 - b0
        ref_lam, ref_hess = ref_one_query(
            cnt, label[b0:b1], score[b0:b1], gamma_pad[q, :cnt])
        np.testing.assert_allclose(lam[b0:b1], ref_lam,
                                   rtol=2e-4, atol=2e-6,
                                   err_msg=f"query {q} lambdas")
        np.testing.assert_allclose(hess[b0:b1], ref_hess,
                                   rtol=2e-4, atol=2e-6,
                                   err_msg=f"query {q} hessians")


def test_percentile_functions_match_reference():
    """Literal transcriptions of PercentileFun / WeightedPercentileFun
    (regression_objective.hpp:18-88) pinned against our implementations on
    tie-heavy data, including the label_t (f32) result rounding of the
    BoostFromScore instantiation (regression_objective.hpp:241-246)."""
    from lightgbm_tpu.objectives import _percentile, _weighted_percentile

    def ref_percentile(data, alpha, T=np.float64):
        # PercentileFun: ArgMaxAtK partitions descending (array_args.h:128
        # "k=0 means get the max"); a full descending sort is the same
        # selection, and both branches of `pos > cnt/2` pick
        # v1=desc[pos-1], v2=desc[pos]
        data = np.asarray(data, T)
        cnt = len(data)
        if cnt <= 1:
            return T(data[0])
        desc = np.sort(data)[::-1]
        float_pos = (1.0 - alpha) * cnt
        pos = int(float_pos)
        if pos < 1:
            return desc[0]                       # ArgMax
        if pos >= cnt:
            return desc[-1]                      # ArgMin
        bias = float_pos - pos
        v1, v2 = desc[pos - 1], desc[pos]
        return T(v1 - (v1 - v2) * bias)

    def ref_weighted_percentile(data, weight, alpha, T=np.float64):
        data = np.asarray(data, T)
        cnt = len(data)
        if cnt <= 1:
            return T(data[0])
        sorted_idx = np.argsort(data, kind="stable")   # std::stable_sort
        weighted_cdf = np.cumsum(np.asarray(weight, np.float64)[sorted_idx])
        threshold = weighted_cdf[cnt - 1] * alpha
        pos = int(np.searchsorted(weighted_cdf, threshold, side="right"))
        pos = min(pos, cnt - 1)
        if pos == 0 or pos == cnt - 1:
            return T(data[sorted_idx[pos]])
        assert threshold >= weighted_cdf[pos - 1]      # CHECK_GE
        assert threshold < weighted_cdf[pos]           # CHECK_LT
        v1 = data[sorted_idx[pos - 1]]
        v2 = data[sorted_idx[pos]]
        if weighted_cdf[pos + 1] - weighted_cdf[pos] >= 1.0:
            return T((threshold - weighted_cdf[pos])
                     / (weighted_cdf[pos + 1] - weighted_cdf[pos])
                     * (v2 - v1) + v1)
        return T(v2)

    rng = np.random.RandomState(11)
    alphas = [0.05, 0.1, 0.5, 0.9, 0.95]
    for trial in range(40):
        n = int(rng.choice([1, 2, 3, 5, 10, 101, 500]))
        # heavy ties: values drawn from a tiny grid
        data = np.round(rng.normal(size=n) * 2.0, 1)
        # weights spanning tiny-to-large so the cdf-gap >= 1.0 branch and
        # the v2 branch are both exercised
        weight = np.exp(rng.uniform(-3, 2, size=n))
        for alpha in alphas:
            ours = _percentile(data, alpha)
            ref = ref_percentile(data, alpha)
            np.testing.assert_allclose(ours, ref, rtol=0, atol=0,
                                       err_msg=f"n={n} alpha={alpha}")
            ours_w = _weighted_percentile(data, weight, alpha)
            ref_w = ref_weighted_percentile(data, weight, alpha)
            np.testing.assert_allclose(ours_w, ref_w, rtol=0, atol=0,
                                       err_msg=f"weighted n={n} alpha={alpha}")
            # the BoostFromScore instantiation stores label_t (f32) data
            # and casts the result back to label_t; its C++ `v1 - v2` also
            # rounds to f32 BEFORE the double interpolation (float-float
            # arithmetic stays float), while our pipeline interpolates
            # fully in f64 — the rounding error scales with the data
            # SPREAD (ulp of v1-v2), not the result, so bound absolutely
            f32 = np.float32
            np.testing.assert_allclose(
                f32(_percentile(data.astype(f32), alpha)),
                ref_percentile(data, alpha, T=f32), rtol=0,
                atol=1.2e-7 * max(1.0, float(np.ptp(data))),
                err_msg=f"f32 n={n} alpha={alpha}")
