"""Unified telemetry layer (lightgbm_tpu/telemetry.py): snapshot schema,
flight-recorder ring + crash flushes, trace capture, Prometheus
exposition, and the overhead contract (the recorder reads only
already-fetched host values — zero extra dispatches per iteration).

Crash-flush coverage reuses the utils/faults.py harness: a hard kill at
iteration k (subprocess), a NaN gradient under check_numerics, and an
OOM ladder exhaustion must each leave a flushed JSONL that exists,
parses, schema-validates, and names the faulty iteration."""

import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry
from lightgbm_tpu.utils import profiling
from lightgbm_tpu.utils.log import LightGBMError

pytestmark = pytest.mark.faults


def _data(n=3000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(size=n) > 0).astype(np.float32)
    return X, y


def _train(params=None, rounds=6, n=3000, **kwargs):
    X, y = _data(n=n)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 20}
    p.update(params or {})
    return lgb.train(p, ds, rounds, **kwargs)


# ------------------------------------------------------------- snapshot

def test_snapshot_schema():
    snap = telemetry.snapshot()
    assert snap["schema"] == telemetry.SCHEMA_VERSION
    for key in ("time", "scopes", "counters", "gauges", "dispatch",
                "health"):
        assert key in snap
    # the dispatch plane carries the four monotonic counters even when
    # the hook is not installed (zeros)
    assert set(snap["dispatch"]) == {"dispatches", "device_gets",
                                     "d2h_bytes", "h2d_bytes"}
    # health embeds progress scalars the Prometheus renderer needs
    assert "restart_count" in snap["health"]


def test_prometheus_text_renders_gauges_and_scopes():
    profiling.set_gauge("serve_p99_ms", 12.5)
    profiling.set_gauge("serve_p50_ms", 3.25)
    # monotonic counters past 1e6 must keep FULL precision ('%g' would
    # freeze them at 6 significant digits and blind rate()/increase())
    profiling.set_gauge("serve_requests", 1234567.0)
    try:
        text = telemetry.prometheus_text()
    finally:
        profiling.reset()
    assert "lightgbm_tpu_serve_p99_ms 12.5" in text
    assert "lightgbm_tpu_serve_p50_ms 3.25" in text
    assert "lightgbm_tpu_serve_requests 1234567" in text
    assert "lightgbm_tpu_dispatches_total" in text
    assert text.startswith("# lightgbm_tpu telemetry schema")
    # every non-comment line is "name[{labels}] value"
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        name, _, value = line.rpartition(" ")
        assert name.startswith("lightgbm_tpu_"), line
        float(value)


def test_gang_snapshot_single_process():
    out = telemetry.gang_snapshot("test_single")
    assert len(out) == 1
    assert out[0]["schema"] == telemetry.SCHEMA_VERSION


def _gang_snapshot_fn(rank):
    """Module-level so spawn can pickle it: each rank tags a gauge with
    its own rank, then allgathers snapshots in lockstep."""
    from lightgbm_tpu import telemetry as tele
    from lightgbm_tpu.utils import profiling as prof
    prof.set_gauge("gang_probe_rank", float(rank))
    snaps = tele.gang_snapshot("tele_gang_test")
    return [(s["schema"], s["gauges"].get("gang_probe_rank"))
            for s in snaps]


@pytest.mark.slow
def test_gang_snapshot_two_process():
    """Rank-0 gang aggregation over the coordination service: a REAL
    2-process gang exchanges snapshots through exchange_host and every
    rank sees both, in rank order. (Tier-1 sibling: the single-process
    spelling above runs the same code path minus the gRPC hop.)"""
    from lightgbm_tpu import distributed
    out = distributed.spawn(_gang_snapshot_fn, nproc=2,
                            devices_per_proc=1, timeout=240)
    assert out == [(telemetry.SCHEMA_VERSION, 0.0),
                   (telemetry.SCHEMA_VERSION, 1.0)]


# ------------------------------------------------------- flight recorder

def test_recorder_rides_training_and_flushes(tmp_path):
    d = str(tmp_path / "tele")
    _train({"telemetry_dir": d, "telemetry_ring_size": 4}, rounds=7)
    rec = telemetry.recorder()
    assert rec is not None
    records = rec.records()
    # ring bounded at 4 despite 7 iterations
    assert len(records) == 4
    assert records[-1]["iteration"] == 6
    assert all(r["completed"] for r in records)
    # resolved run context filled after the first step
    assert rec.has_context
    # clean train end flushed (a durable dir was configured)
    path = os.path.join(d, "flight_rank0.jsonl")
    assert os.path.exists(path)
    recs, errors = telemetry.validate_flight_jsonl(path)
    assert errors == []
    assert recs[0]["type"] == "run"
    assert recs[0]["context"]["backend"] == "cpu"
    assert recs[-1]["type"] == "flush"
    assert recs[-1]["reason"] == "train-end"
    # the manifest/bench embed point: health names the JSONL by reference
    from lightgbm_tpu import distributed
    assert distributed.health_snapshot().get("flight_recorder") == path


def test_recorder_disabled_by_param(tmp_path):
    _train({"telemetry_flight_recorder": False,
            "telemetry_dir": str(tmp_path)}, rounds=3)
    assert telemetry.recorder() is None
    assert not os.path.exists(str(tmp_path / "flight_rank0.jsonl"))


@pytest.mark.slow
def test_recorder_no_flush_without_dir(tmp_path):
    """A clean run with NO durable dir configured leaves no JSONL litter
    (event flushes still would — tested by the fault cases). Slow:
    tier-1 siblings cover both sides of the switch
    (test_recorder_rides_training_and_flushes asserts the WITH-dir
    flush, test_recorder_disabled_by_param the off-param) — this case
    only adds the no-dir/no-litter default."""
    _train(rounds=3)
    rec = telemetry.recorder()
    assert rec is not None and len(rec.records()) == 3
    assert rec.path() is None            # no dir resolved, never flushed


@pytest.mark.slow
def test_kill_fault_flushes_jsonl(tmp_path):
    """A supervised-style hard kill (utils/faults _hard_exit) leaves a
    flushed flight-recorder JSONL that validates and names the in-flight
    iteration — the crashed-gang post-mortem contract. Slow: the tier-1
    sibling test_postmortem.py::test_classify_kill_rank runs the same
    subprocess kill and asserts the same JSONL validation + in-flight
    iteration on top of the analyzer verdict."""
    d = str(tmp_path / "tele")
    code = (
        "import numpy as np, lightgbm_tpu as lgb\n"
        "rng = np.random.RandomState(0)\n"
        "X = rng.normal(size=(2000, 8)).astype(np.float32)\n"
        "y = (X[:, 0] > 0).astype(np.float32)\n"
        "ds = lgb.Dataset(X, label=y, params={'verbosity': -1})\n"
        "lgb.train({'objective': 'binary', 'num_leaves': 15,\n"
        "           'verbosity': -1, 'telemetry_dir': %r,\n"
        "           'fault_kill_at_iter': 3}, ds, 10)\n" % d)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 137, r.stderr[-2000:]
    path = os.path.join(d, "flight_rank0.jsonl")
    assert os.path.exists(path), "kill fault did not flush the recorder"
    recs, errors = telemetry.validate_flight_jsonl(path)
    assert errors == []
    flush = recs[-1]
    assert flush["type"] == "flush"
    # the last record names the in-flight iteration: the kill fired at
    # the start of iteration 3, after 3 completed records
    assert "at iteration 3" in flush["reason"]
    iters = [r for r in recs if r["type"] == "iter"]
    assert iters and iters[-1]["iteration"] == 2
    assert flush["health"]["last_iteration"] == 2


@pytest.mark.slow
def test_nan_grad_error_flush_names_iteration(tmp_path):
    """check_numerics fail-fast: the train-error flush lands with the
    sentinel/NaN verdict in the reason, even without a durable dir.
    Slow: tier-1 siblings exercise the same engine train-error flush
    through the OOM-exhaustion raise (test_oom_exhaustion_flushes) and
    the check_numerics judge through the sentinel back-fill test —
    this case only adds the NaN-specific reason text."""
    with pytest.raises(LightGBMError, match="iteration 2"):
        _train({"check_numerics": True, "fault_nan_grad_at_iter": 2},
               rounds=6)
    rec = telemetry.recorder()
    assert rec is not None
    path = rec.path()                  # created by the event flush
    assert path is not None and os.path.exists(path)
    recs, errors = telemetry.validate_flight_jsonl(path)
    assert errors == []
    reasons = [r["reason"] for r in recs if r["type"] == "flush"]
    assert any("train-error" in r and "iteration 2" in r for r in reasons)


def test_sentinel_verdict_backfills_record():
    """The fused path's lazy sentinel drain back-fills 'ok' verdicts
    into the covering flight records (rides the drain — no extra
    fetches)."""
    booster = _train({"check_numerics": True}, rounds=5)
    booster._boosting._flush_sentinel()
    rec = telemetry.recorder()
    iters = [r for r in rec.records() if r["type"] == "iter"]
    assert iters
    # every verdict judged by now; none may still read "pending"/"off"
    assert all(r["sentinel"] == "ok" for r in iters)


@pytest.mark.slow
def test_oom_exhaustion_flushes(tmp_path):
    """Spending the whole OOM ladder flushes an 'oom-exhausted' event
    before the error unwinds, with the degradation rungs in the ring.
    Slow: the tier-1 sibling
    test_postmortem.py::test_classify_oom_exhaustion drives the same
    exhaustion and asserts the same flush + [1, 2, 3] ladder history on
    top of the analyzer verdict (plus the memory/predicted-bytes
    enrichment)."""
    from lightgbm_tpu.utils.faults import SimulatedResourceExhausted
    with pytest.raises(SimulatedResourceExhausted):
        _train({"telemetry_dir": str(tmp_path / "t"),
                "fault_oom_at_iter": 2, "fault_oom_count": 4}, rounds=6)
    rec = telemetry.recorder()
    path = rec.path()
    assert path is not None and os.path.exists(path)
    recs, errors = telemetry.validate_flight_jsonl(path)
    assert errors == []
    reasons = [r["reason"] for r in recs if r["type"] == "flush"]
    assert any(r.startswith("oom-exhausted") for r in reasons)
    # the exhaustion flush carries the full ladder history in health
    flush = next(r for r in recs if r["type"] == "flush"
                 and r["reason"].startswith("oom-exhausted"))
    degr = flush["health"].get("degradations") or []
    assert [d["level"] for d in degr if d["kind"] == "oom"] == [1, 2, 3]


def test_flush_is_idempotent_and_cumulative(tmp_path):
    """Each flush rewrites the file with the full ring + EVERY flush
    event so far — an early event flush survives into the final one."""
    d = str(tmp_path)
    rec = telemetry.FlightRecorder(capacity=8, directory=d, rank=0)
    rec.record(iteration=0, wall_s=0.1)
    p1 = rec.flush("first-event")
    rec.record(iteration=1, wall_s=0.1)
    p2 = rec.flush("second-event")
    assert p1 == p2
    recs, errors = telemetry.validate_flight_jsonl(p2)
    assert errors == []
    reasons = [r["reason"] for r in recs if r["type"] == "flush"]
    assert reasons == ["first-event", "second-event"]
    assert sum(1 for r in recs if r["type"] == "iter") == 2
    # periodic flushes are TRANSIENT: written into their own file, never
    # retained into later flushes (a long run must not accumulate one
    # permanent event per period — quadratic file growth)
    p3 = rec.flush("periodic", retain_event=False)
    recs, _ = telemetry.validate_flight_jsonl(p3)
    assert [r["reason"] for r in recs if r["type"] == "flush"] \
        == ["first-event", "second-event", "periodic"]
    p4 = rec.flush("third-event")
    recs, _ = telemetry.validate_flight_jsonl(p4)
    assert [r["reason"] for r in recs if r["type"] == "flush"] \
        == ["first-event", "second-event", "third-event"]


def test_periodic_flush_cadence(tmp_path):
    """flush_period=4: no flush at iteration 0 (the off-by-one the
    review caught); checkpoints land on period crossings only."""
    rec = telemetry.FlightRecorder(capacity=8, directory=str(tmp_path),
                                   rank=0, flush_period=4)
    rec.record(iteration=0, wall_s=0.0)
    assert not os.path.exists(rec.path())     # first record: no flush
    for i in range(1, 4):
        rec.record(iteration=i, wall_s=0.0)
    assert not os.path.exists(rec.path())     # still inside period 0
    rec.record(iteration=4, wall_s=0.0)       # crossing -> checkpoint
    recs, errors = telemetry.validate_flight_jsonl(rec.path())
    assert errors == []
    assert [r["reason"] for r in recs if r["type"] == "flush"] \
        == ["periodic"]


def test_validate_rejects_bad_jsonl(tmp_path):
    p = str(tmp_path / "bad.jsonl")
    with open(p, "w") as fh:
        fh.write('{"type": "iter", "iteration": 0}\nnot json\n')
    recs, errors = telemetry.validate_flight_jsonl(p)
    assert errors   # missing fields + unparseable + no header/flush
    assert any("unparseable" in e for e in errors)
    assert any("run" in e for e in errors)


# ------------------------------------------------------ memory telemetry

def test_snapshot_has_memory_plane():
    snap = telemetry.snapshot()
    mem = snap["memory"]
    for key in ("hbm_bytes_in_use", "hbm_peak_bytes", "host_rss_bytes",
                "host_rss_peak_bytes"):
        assert key in mem
    # CPU backend: HBM fields are null (Device.memory_stats() returns
    # None), host fields are real — the None-tolerance contract
    assert mem["hbm_bytes_in_use"] is None
    assert isinstance(mem["host_rss_bytes"], int)
    assert mem["host_rss_peak_bytes"] >= mem["host_rss_bytes"] > 0


def test_recorder_records_memory_fields(tmp_path):
    """Every flight record carries the memory sample (HBM fields null
    on CPU, host RSS real), the always-on gauges mirror the latest
    sample, and health_snapshot()/prometheus_text() surface them — the
    checkpoint-manifest and /metrics embed points."""
    from lightgbm_tpu import distributed
    _train({"telemetry_dir": str(tmp_path / "t")}, rounds=3)
    rec = telemetry.recorder()
    iters = [r for r in rec.records() if r["type"] == "iter"]
    assert iters
    for r in iters:
        mem = r["mem"]
        assert mem["hbm_bytes_in_use"] is None      # CPU: null, no crash
        assert mem["hbm_peak_bytes"] is None
        assert mem["host_rss_bytes"] > 0
    health = distributed.health_snapshot()
    assert health["memory"]["host_rss_bytes"] > 0
    assert health["memory"]["host_rss_peak_bytes"] \
        >= health["memory"]["host_rss_bytes"]
    text = telemetry.prometheus_text()
    assert "lightgbm_tpu_host_rss_bytes" in text
    # the nulls stay out of the exposition (a gauge is only set from a
    # non-null sample)
    assert "lightgbm_tpu_hbm_bytes_in_use" not in text


def test_memory_off_by_param(tmp_path):
    """telemetry_memory=false: records carry no mem field at all."""
    _train({"telemetry_memory": False,
            "telemetry_dir": str(tmp_path / "t")}, rounds=3)
    rec = telemetry.recorder()
    iters = [r for r in rec.records() if r["type"] == "iter"]
    assert iters and all("mem" not in r for r in iters)


def test_memory_stats_failure_forces_none_path(monkeypatch, tmp_path):
    """The satellite contract, forced: a device whose memory_stats()
    RAISES (not just returns None) must record null fields and never
    crash training — and the failed probe is cached so it is not
    retried per record."""
    from lightgbm_tpu.utils import profiling

    class _Exploding:
        calls = 0

        def memory_stats(self):
            _Exploding.calls += 1
            raise RuntimeError("memory_stats unavailable on this backend")

    monkeypatch.setattr(profiling, "_mem_device", _Exploding())
    monkeypatch.setattr(profiling, "_mem_device_ok", None)
    sample = profiling.sample_memory()
    assert sample["hbm_bytes_in_use"] is None
    assert sample["hbm_peak_bytes"] is None
    assert sample["host_rss_bytes"] > 0        # host source is independent
    # a full recorder-on training run survives the exploding device
    _train({"telemetry_dir": str(tmp_path / "t")}, rounds=3)
    iters = [r for r in telemetry.recorder().records()
             if r["type"] == "iter"]
    assert all(r["mem"]["hbm_bytes_in_use"] is None for r in iters)
    assert _Exploding.calls == 1               # probe cached, not per-record


def test_phase_hbm_watermarks_under_timetag(monkeypatch):
    """Per-phase HBM watermarks: sampled at TIMETAG scope exits from a
    stub allocator, the per-scope PEAK is retained and surfaces in the
    snapshot's memory plane; profiling.reset() clears them with the
    scopes they annotate."""
    from lightgbm_tpu.utils import profiling

    class _Stub:
        seq = iter([100, 400, 200])

        def memory_stats(self):
            return {"bytes_in_use": 50, "peak_bytes_in_use": next(self.seq)}

    monkeypatch.setattr(profiling, "_mem_device", _Stub())
    monkeypatch.setattr(profiling, "_mem_device_ok", None)
    profiling.reset()
    profiling.enable(True)
    try:
        for _ in range(3):
            with profiling.timer("pm_test_phase"):
                pass
        marks = profiling.memory_watermarks()
        assert marks["pm_test_phase"] == 400          # the peak, kept
        assert telemetry.snapshot()["memory"]["phase_hbm_peak"][
            "pm_test_phase"] == 400
    finally:
        profiling.enable(False)
        profiling.reset()
    assert profiling.memory_watermarks() == {}


def test_degradation_event_enrichment():
    """OOM rung events carry the memory snapshot at failure, the
    traffic model's predicted per-pass bytes, wall + monotonic stamps
    and the active iteration (the satellite ordering contract)."""
    from lightgbm_tpu import distributed
    with pytest.raises(Exception):
        _train({"fault_oom_at_iter": 1, "fault_oom_count": 4}, rounds=4)
    degr = [d for d in distributed.degradations() if d["kind"] == "oom"]
    assert [d["level"] for d in degr] == [1, 2, 3]
    for d in degr:
        assert d["iteration"] == 1
        assert d["t"] > 0 and d["t_mono"] > 0
        assert d["memory"]["host_rss_bytes"] > 0
        assert d["memory"]["hbm_bytes_in_use"] is None    # CPU
        assert d["predicted_hist_bytes"] > 0
    assert degr[0]["t_mono"] <= degr[1]["t_mono"] <= degr[2]["t_mono"]


# -------------------------------------------------- overhead contract

def test_recorder_adds_zero_dispatches():
    """The acceptance bar: recorder-on training must not add a single
    compiled-program dispatch or device fetch per iteration (it reads
    only already-fetched host values). Measured with the dispatch hook
    over the same warm fused loop, recorder off vs on."""
    X, y = _data(n=4000)
    counts = {}
    for on in (False, True):
        ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
        booster = lgb.Booster(params={
            "objective": "binary", "num_leaves": 15, "verbosity": -1,
            "telemetry_flight_recorder": on}, train_set=ds)
        booster.update()
        booster.update()                      # warm (compile)
        _ = float(np.asarray(booster._boosting.train_score).ravel()[0])
        live = profiling.install_dispatch_hook()
        try:
            with profiling.dispatch_scope() as d:
                for _ in range(4):
                    booster.update()
            _ = float(np.asarray(
                booster._boosting.train_score).ravel()[0])
        finally:
            profiling.uninstall_dispatch_hook()
        if not live:
            pytest.skip("dispatch hook unavailable on this jax")
        # device_gets are deliberately NOT compared: the lazy host-mirror
        # drain (_flush_pending only_ready=True) fetches whichever
        # mirrors finished during the window, so their attribution
        # shifts with any per-iteration host timing — the same mirrors
        # get fetched either way. Dispatches are the budget.
        counts[on] = d["dispatches"]
    assert counts[True] == counts[False], (
        f"recorder-on dispatched {counts[True]} programs vs recorder-off "
        f"{counts[False]}: the recorder touched the device")
    # and the fused budget itself holds with the recorder on
    assert counts[True] <= 2 * 4


# ------------------------------------------------------- trace capture

@pytest.mark.slow
def test_trace_window_captures_on_cpu(tmp_path):
    """Slow: scripts/telemetry_smoke.py (tests/run_suite.sh) runs this
    exact capture end-to-end on every CI pass; tier-1 keeps the instant
    bad-dir tolerance case below."""
    d = str(tmp_path / "trace")
    booster = _train(rounds=2)
    with telemetry.trace_window(d, iters=2) as tw:
        booster.update()
        booster.update()
    # jax's CPU profiler works in this image; if a backend cannot trace,
    # the contract is a recorded error — never a raise
    if not tw.ok:
        assert tw.error
        pytest.skip(f"profiler unavailable: {tw.error}")
    assert tw.to_json()["iters"] == 2
    assert telemetry.trace_files(d), "no trace artifacts written"


def test_trace_window_tolerates_bad_dir():
    with telemetry.trace_window("/proc/definitely/not/writable") as tw:
        pass
    assert not tw.ok and tw.error


# -------------------------------------------------- profiling satellites

def test_profiling_counters_thread_safe():
    """The satellite fix: _counters/_gauges read-modify-writes are now
    lock-protected — hammering them from threads loses no updates."""
    import threading
    profiling.reset()
    profiling.enable(True)
    try:
        n_threads, n_iter = 8, 400

        def work():
            for _ in range(n_iter):
                profiling.counter("ts_test", 1.0)
                profiling.inc_gauge("ts_gauge", 1.0)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert profiling.counters()["ts_test"] == n_threads * n_iter
        assert profiling.gauges()["ts_gauge"] == n_threads * n_iter
    finally:
        profiling.enable(False)
        profiling.reset()


def test_reset_leaves_dispatch_counters():
    """reset() keeps the monotonic dispatch counters (documented
    contract); reset_dispatch() is the explicit test-only origin."""
    before = profiling.dispatch_stats()
    profiling.reset()
    assert profiling.dispatch_stats() == before
    profiling.reset_dispatch()
    assert all(v == 0 for v in profiling.dispatch_stats().values())


# ------------------------------------------------------- serve /metrics

def test_serve_metrics_endpoint():
    booster = _train(rounds=3)
    from lightgbm_tpu import ServeFrontend
    fe = ServeFrontend(booster, metrics=True, metrics_port=0)
    try:
        addr = fe.metrics_addr
        assert addr is not None
        _ = fe.predict(_data(n=8)[0])
        body = urllib.request.urlopen(
            f"http://{addr}/metrics", timeout=10).read().decode()
        assert "lightgbm_tpu_serve_p50_ms" in body
        assert "lightgbm_tpu_serve_requests 1" in body
        # unknown paths 404
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://{addr}/nope", timeout=10)
        # direct render equals the endpoint's source of truth
        assert "lightgbm_tpu_serve_requests" in fe.metrics_text()
    finally:
        fe.close()
    assert fe.metrics_addr is None      # listener shut down with close()
