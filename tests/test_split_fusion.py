"""Fused split-finding epilogue + level-batched frontier growth (ISSUE 12).

The fusion contract under test, at three levels:

- UNIT: numerical_candidates + candidates_to_splitinfo reproduce
  find_best_splits bit-for-bit on the numerical non-bundled search, and
  the Pallas epilogue kernel (interpret) matches the XLA twin bit-for-bit
  — including in-pass sibling derivation (parent - computed), exact on
  representable sums.
- E2E: split_fusion=on model text is BIT-IDENTICAL to split_fusion=off
  across the split-semantics edge-config matrix (monotone, missing both
  directions, min_data/min_hessian, l1/path-smooth/max-delta, subset
  bagging, interactions, exact mode, q8), on both the XLA twin (scatter)
  and the in-kernel path (pallas interpret).
- GATING: "auto" falls back to the classic phase for the configurations
  whose semantics stay in find_best_splits (categorical, EFB, forced
  splits, CEGB, extra_trees) — still training correctly — while "on"
  refuses them loudly; the autotune trainer-state ride keys on the
  epilogue flag; the phased grower is bit-identical and launches one
  histogram pass per frontier LEVEL, not per leaf.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import MISSING_NONE
from lightgbm_tpu.config import Config
from lightgbm_tpu.ops import pallas_hist
from lightgbm_tpu.ops.histogram import (histogram_tiles,
                                        histogram_tiles_with_candidates)
from lightgbm_tpu.ops.split import (CAND_CHANNELS, FeatureMeta, SplitParams,
                                    candidates_to_splitinfo,
                                    find_best_splits, numerical_candidates)

pytestmark = pytest.mark.pallas


# ------------------------------------------------------------------- unit

def _rand_hist(rng, L, F, B):
    h = rng.rand(L, F, B, 3).astype(np.float32)
    h[..., 2] = rng.randint(0, 50, size=(L, F, B)).astype(np.float32)
    h[..., 1] = np.abs(h[..., 1]) * h[..., 2]
    return jnp.asarray(h)


def _meta(F, B, missing=MISSING_NONE, monotone=None):
    from lightgbm_tpu.binning import MISSING_NAN, MISSING_ZERO
    mt = {"none": MISSING_NONE, "nan": MISSING_NAN,
          "zero": MISSING_ZERO}[missing] if isinstance(missing, str) \
        else missing
    return FeatureMeta(
        num_bins=jnp.full((F,), B, jnp.int32),
        missing_type=jnp.full((F,), mt, jnp.int32),
        default_bin=jnp.full((F,), 1, jnp.int32),
        is_categorical=jnp.zeros((F,), bool),
        monotone=(jnp.zeros((F,), jnp.int8) if monotone is None
                  else jnp.asarray(monotone, jnp.int8)),
        penalty=jnp.ones((F,), jnp.float32))


@pytest.mark.parametrize("missing", ["none", "nan", "zero"])
@pytest.mark.parametrize("mono", [None, [1, -1, 0, 1]])
def test_candidates_match_find_best_splits(missing, mono):
    """The shared scan + table consumer == find_best_splits, field by
    field, bit for bit — the factored code paths cannot drift."""
    rng = np.random.RandomState(3)
    L, F, B = 6, 4, 17
    hist = _rand_hist(rng, L, F, B)
    sum_g = jnp.asarray(hist[:, 0, :, 0].sum(axis=1))
    sum_h = jnp.asarray(hist[:, 0, :, 1].sum(axis=1))
    cnt = jnp.asarray(hist[:, 0, :, 2].sum(axis=1))
    out = jnp.asarray(rng.randn(L).astype(np.float32) * 0.1)
    depth = jnp.asarray(rng.randint(0, 3, L).astype(np.int32))
    meta = _meta(F, B, missing, mono)
    p = SplitParams.from_config(Config.from_params(
        {"min_data_in_leaf": 5, "min_sum_hessian_in_leaf": 1e-3,
         "lambda_l1": 0.1, "lambda_l2": 0.3, "path_smooth": 1.5,
         "max_delta_step": 0.8}))
    with_mono = mono is not None
    lmin = (jnp.full((L,), -0.5) if with_mono else None)
    lmax = (jnp.full((L,), 0.5) if with_mono else None)
    fmask = jnp.ones((L, F), jnp.float32)

    ref = find_best_splits(hist, sum_g, sum_h, cnt, out, depth, meta, p,
                           fmask, max_depth=4,
                           leaf_min=lmin, leaf_max=lmax)
    cand = numerical_candidates(
        hist, sum_g, sum_h, cnt, out, meta.num_bins, meta.missing_type,
        meta.default_bin, meta.monotone.astype(jnp.int32), p,
        with_monotone=with_mono, leaf_min=lmin, leaf_max=lmax)
    assert cand.shape == (L, F, CAND_CHANNELS)
    got = candidates_to_splitinfo(
        cand, sum_g, sum_h, cnt, out, depth, meta, p, fmask, max_depth=4,
        with_monotone=with_mono, leaf_min=lmin, leaf_max=lmax)
    for name in ("gain", "feature", "threshold", "default_left",
                 "left_sum_g", "left_sum_h", "left_count", "right_sum_g",
                 "right_sum_h", "right_count", "left_output",
                 "right_output"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            err_msg=name)


def _epi_inputs(n=3001, f=5, b=63, seed=0, int8=False):
    """Representable (or int8) stats with a POSITIVE hessian channel —
    real training stats, so every leaf has valid split candidates."""
    rng = np.random.RandomState(seed)
    binsT = rng.randint(0, b, size=(f, n)).astype(np.uint8)
    if int8:
        stats = rng.randint(-127, 128, size=(n, 3)).astype(np.int8)
        stats[:, 1] = rng.randint(1, 128, size=n)
        stats[:, 2] = 1
    else:
        stats = (rng.randint(-1023, 1024, size=(n, 3)) / 1024.0
                 ).astype(np.float32)
        stats[:, 1] = rng.randint(1, 1024, size=n) / 1024.0
        stats[:, 2] = 1.0
    leaf = rng.randint(0, 3, n).astype(np.int32)
    return binsT, np.ascontiguousarray(binsT.T), stats, leaf


@pytest.mark.parametrize("mode,method,with_mono", [
    ("highest", "pallas", False),
    ("highest", "pallas", True),
    ("q8", "pallas_q8", False)])
def test_epilogue_kernel_matches_xla_twin_and_derives_exactly(mode, method,
                                                              with_mono):
    """The in-kernel epilogue == the XLA twin bit-for-bit on representable
    sums, AND the derived sibling's plane (parent - computed, the static
    lane shift) equals its directly-built histogram exactly."""
    n, f, b = 3001, 5, 63
    binsT, bins, stats, leaf = _epi_inputs(int8=(mode == "q8"))
    jb, jbT = jnp.asarray(bins), jnp.asarray(binsT)
    jst, jl = jnp.asarray(stats), jnp.asarray(leaf)
    # pair: leaf 0 computed at slot 0, leaf 1 derived at slot 1; leaf 2
    # computed alone at slot 2
    sel = jnp.asarray(np.array([0, 1, 2, -1, -1, -1], np.int32))
    derive = jnp.asarray(np.array([0, 1, 0, 0, 0, 0], bool))
    # the parent's plane (leaves 0+1 merged) — f32, as resident in the
    # grower's state after dequantization
    parent_leaf = jnp.asarray(np.where(np.isin(leaf, [0, 1]), 0, 2)
                              .astype(np.int32))
    st_f = jnp.asarray(stats.astype(np.float32))
    hp = histogram_tiles(jb, st_f, parent_leaf, jnp.asarray([0], jnp.int32),
                         b, method="scatter")
    parent = jnp.zeros((6, f, b, 3), jnp.float32).at[1].set(hp[0])

    sums = np.zeros((6, 3), np.float32)
    for p_i, lv in enumerate([0, 1, 2]):
        sums[p_i] = stats[leaf == lv].astype(np.float64).sum(0)
    la = pallas_hist.pack_leaf_aux(
        *(jnp.asarray(sums[:, i]) for i in range(3)), jnp.zeros((6,)),
        leaf_min=jnp.full((6,), -0.4) if with_mono else None,
        leaf_max=jnp.full((6,), 0.4) if with_mono else None)
    fmeta = pallas_hist.pack_feature_meta(
        jnp.full((f,), b, jnp.int32), jnp.zeros((f,), jnp.int32),
        jnp.zeros((f,), jnp.int32),
        (jnp.asarray([1, -1, 0, 1, -1], jnp.int32) if with_mono
         else jnp.zeros((f,), jnp.int32)))
    pvec = pallas_hist.pack_scan_params(
        SplitParams.from_config(Config.from_params({})))
    qsc = jnp.ones((3,), jnp.float32) if mode == "q8" else None

    # both arms jitted: the grower always runs them inside one compiled
    # program, and eager-vs-jit would differ in FMA contraction, not in
    # the math under test
    kw = dict(num_bins=b, block=512, with_monotone=with_mono, q_scale=qsc)
    run_k = jax.jit(lambda *a: histogram_tiles_with_candidates(
        *a, method=method, binsT=jbT, interpret=True, **kw))
    xla_m = "onehot_q8" if mode == "q8" else "scatter"
    run_x = jax.jit(lambda *a: histogram_tiles_with_candidates(
        *a, method=xla_m, binsT=jbT, **kw))
    tile_k, cand_k = run_k(jb, jst, jl, sel, derive, parent, la, fmeta,
                           pvec)
    tile_x, cand_x = run_x(jb, jst, jl, sel, derive, parent, la, fmeta,
                           pvec)
    np.testing.assert_array_equal(np.asarray(tile_k), np.asarray(tile_x))
    np.testing.assert_array_equal(np.asarray(cand_k), np.asarray(cand_x))
    # sibling-derivation exactness: the derived plane == leaf 1's
    # directly-built histogram (representable/integer sums -> exact
    # subtraction)
    direct = histogram_tiles(jb, st_f, jl, jnp.asarray([1], jnp.int32), b,
                             method="scatter")
    np.testing.assert_array_equal(np.asarray(tile_k[1]),
                                  np.asarray(direct[0]))
    # and the candidate table for the derived slot is populated
    assert np.isfinite(np.asarray(cand_k)[1, :, 0]).any()
    # acceptance floor from the REAL buffers: per-leaf plane bytes the
    # classic search streams vs the candidate row the fused search reads
    plane_per_leaf = tile_k.nbytes / tile_k.shape[0]
    cand_per_leaf = cand_k.nbytes / cand_k.shape[0]
    assert plane_per_leaf / cand_per_leaf >= b / 4, (
        plane_per_leaf, cand_per_leaf, b)


def test_search_bytes_floor():
    """Acceptance: split-search consumer bytes reduced >= B/4x — per-leaf
    [F, B, 4] planes vs the [F, CAND_CHANNELS] candidate row."""
    for b in (63, 255):
        t = pallas_hist.traffic_model(500_000, 28, b, 42, 3)
        ratio = t["search_in_planes"] / t["search_in_cand"]
        assert ratio >= b / 4, (b, ratio)


# ------------------------------------------------------------------- e2e

def _tree_text(booster):
    return "\n".join(l for l in booster.model_to_string().splitlines()
                     if not l.startswith("[") and l != "end of parameters")


def _data(seed=4, n=1400, f=5, with_nan=False, with_zero=False):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    if with_zero:
        X[rng.rand(n, f) < 0.3] = 0.0
    y = (2.0 * (X[:, 0] > 0.3) + 1.0 * (X[:, 1] > -0.2)
         + 0.5 * (X[:, 2] > 0.5) + 0.01 * rng.normal(size=n))
    if with_nan:
        X[rng.rand(n, f) < 0.15] = np.nan
    return X, y


def _train_text(X, y, params, rounds=3):
    # fused_iteration off: the parity under test lives in the GROWER, and
    # the unfused path dispatches the module-level grow_tree jit — its
    # cache is shared across every config in this file that maps to the
    # same statics, so the matrix costs compiles only where the statics
    # actually differ (the fused-step program is per-booster and would
    # recompile for every single cell)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1, **{
        k: params[k] for k in ("max_bin", "zero_as_missing")
        if k in params}})
    booster = lgb.train({"objective": "regression", "num_leaves": 8,
                         "verbosity": -1, "fused_iteration": False,
                         **params}, ds, num_boost_round=rounds)
    return _tree_text(booster)


EDGE_CONFIGS = [
    pytest.param({}, {}, id="default"),
    pytest.param({"monotone_constraints": [1, -1, 0, 0, 0]}, {},
                 id="monotone-basic"),
    pytest.param({}, {"with_nan": True}, id="missing-nan"),
    pytest.param({"zero_as_missing": True}, {"with_zero": True},
                 id="missing-zero"),
    pytest.param({"min_data_in_leaf": 60,
                  "min_sum_hessian_in_leaf": 5.0}, {}, id="min-data-hess"),
    pytest.param({"lambda_l1": 0.5, "lambda_l2": 1.3, "path_smooth": 2.0,
                  "max_delta_step": 0.3}, {}, id="l1-smooth-delta"),
    pytest.param({"max_depth": 3}, {}, id="max-depth"),
    pytest.param({"tree_growth_mode": "exact"}, {}, id="exact"),
    pytest.param({"bagging_fraction": 0.4, "bagging_freq": 1}, {},
                 id="subset-bagging"),
    pytest.param({"feature_fraction": 0.6}, {}, id="col-sampling"),
    pytest.param({"interaction_constraints": [[0, 1], [2, 3, 4]]}, {},
                 id="interactions"),
    pytest.param({"quantized_grad": True}, {}, id="q8"),
]


@pytest.mark.parametrize("params,dkw", EDGE_CONFIGS)
def test_e2e_fusion_bit_parity_xla(params, dkw):
    """split_fusion on == off, model text bit-identical, on the XLA twin
    (scatter backend) across the split-semantics edge-config matrix."""
    X, y = _data(**dkw)
    base = {"histogram_method": "scatter", **params}
    t_on = _train_text(X, y, {**base, "split_fusion": "on"})
    t_off = _train_text(X, y, {**base, "split_fusion": "off"})
    assert t_on == t_off


@pytest.mark.parametrize("params,dkw", [
    pytest.param({}, {}, id="default"),
    # q8 rides slow: its in-kernel dequant is pinned tier-1 by the q8
    # epilogue unit parity above and e2e by the XLA-twin q8 case (same
    # scan function), and scripts/kernel_bench.py --fast --interpret
    # runs the q8 kernel mode on every CI pass; the interpret-kernel
    # LAUNCH mechanics stay tier-1 via the default case below
    pytest.param({"quantized_grad": True}, {}, id="q8",
                 marks=pytest.mark.slow),
])
def test_e2e_fusion_bit_parity_kernel(params, dkw):
    """split_fusion on == off through the IN-KERNEL epilogue (pallas
    interpret, compaction ladder on so the gather-epilogue kernel runs
    inside the rung dispatch). The missing-direction/monotone/etc edge
    matrix is covered bit-for-bit on the XLA twin above — the kernel
    runs the SAME scan function, and its plane assembly + monotone aux
    are pinned by the kernel-vs-twin unit test — so this matrix only
    needs the configs that change the KERNEL's own launch shape (the
    default pass and q8's in-kernel dequant)."""
    X, y = _data(**dkw)
    base = {"histogram_method": "pallas", "hist_pallas_interpret": True,
            **params}
    t_on = _train_text(X, y, {**base, "split_fusion": "on"}, rounds=2)
    t_off = _train_text(X, y, {**base, "split_fusion": "off"}, rounds=2)
    assert t_on == t_off


def test_degenerate_shapes():
    """All-leaves-dead (root fails the 2x min_data guard -> splitless
    tree) and the single-pending-leaf launch shape (num_leaves=2) — both
    fused == classic."""
    X, y = _data(n=600)
    dead = {"histogram_method": "scatter", "min_data_in_leaf": 2000}
    t_on = _train_text(X, y, {**dead, "split_fusion": "on"}, rounds=2)
    t_off = _train_text(X, y, {**dead, "split_fusion": "off"}, rounds=2)
    assert t_on == t_off
    assert "num_leaves=1" in t_on
    two = {"histogram_method": "scatter", "num_leaves": 2}
    t_on = _train_text(X, y, {**two, "split_fusion": "on",
                              "num_leaves": 2}, rounds=2)
    t_off = _train_text(X, y, {**two, "split_fusion": "off",
                               "num_leaves": 2}, rounds=2)
    assert t_on == t_off


# ---------------------------------------------------------------- gating

def _cat_data(seed=5, n=1200):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, 4))
    X[:, 3] = rng.randint(0, 6, n)
    y = (X[:, 0] > 0) * 1.0 + (X[:, 3] == 2) * 2.0
    return X, y


def test_auto_falls_back_and_on_refuses():
    """The configurations whose split semantics stay in find_best_splits:
    'auto' silently keeps the classic phase (training equals explicit
    'off'), 'on' raises naming the blocker."""
    X, y = _cat_data()

    def train(params, sf):
        ds = lgb.Dataset(X, label=y, params={"verbosity": -1},
                         categorical_feature=[3])
        return lgb.train({"objective": "regression", "num_leaves": 8,
                          "verbosity": -1, "split_fusion": sf,
                          "fused_iteration": False, **params},
                         ds, num_boost_round=2)

    t_auto = _tree_text(train({}, "auto"))
    t_off = _tree_text(train({}, "off"))
    assert t_auto == t_off
    with pytest.raises(ValueError, match="split_fusion=on is unsupported"):
        train({}, "on").model_to_string()

    # extra_trees / CEGB / non-positive feature_contri blockers,
    # numerical data (the contri multiplier only commutes with the
    # fused per-feature argmax when positive — see
    # candidates_to_splitinfo)
    Xn, yn = _data()
    for blocker in ({"extra_trees": True},
                    {"cegb_tradeoff": 0.5, "cegb_penalty_split": 0.1},
                    {"feature_contri": [1.0, 0.0, 1.0, 1.0, 1.0]}):
        ds = lgb.Dataset(Xn, label=yn, params={"verbosity": -1})
        with pytest.raises(ValueError,
                           match="split_fusion=on is unsupported"):
            lgb.train({"objective": "regression", "verbosity": -1,
                       "split_fusion": "on", **blocker}, ds,
                      num_boost_round=1)
    # and 'auto' with a non-positive contri entry falls back to the
    # classic phase (same trees as explicit off)
    contri = {"feature_contri": [1.0, -0.5, 1.0, 1.0, 1.0],
              "histogram_method": "scatter"}
    t_auto = _train_text(Xn, yn, {**contri, "split_fusion": "auto"},
                         rounds=2)
    t_off2 = _train_text(Xn, yn, {**contri, "split_fusion": "off"},
                         rounds=2)
    assert t_auto == t_off2


def test_hist_tuned_ride_keys_on_epilogue():
    """The autotune trainer-state ride: a ``_hist_tuned`` dict from a
    pre-fusion checkpoint (no epilogue key) must NOT replay its block
    into the epilogue kernel — _hist_tuning discards and re-tunes; a
    matching-flag dict rides through untouched."""
    X, y = _data(n=600)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    ds.construct()
    booster = lgb.Booster(params={"objective": "regression",
                                  "verbosity": -1}, train_set=ds)
    gb = booster._boosting
    # pre-fusion checkpoint ride: tuned for the plane-returning kernel
    gb._hist_tuned = {"block": 4096, "tile_leaves": 42}
    tile, blk = gb._hist_tuning("pallas_hilo", epilogue=True)
    assert blk != 4096, "pre-fusion block replayed into the epilogue kernel"
    assert gb._hist_tuned.get("epilogue") is True
    # matching flag: the ride is honored
    gb._hist_tuned = {"block": 2048, "tile_leaves": 42, "epilogue": False}
    tile, blk = gb._hist_tuning("pallas_hilo", epilogue=False)
    assert (tile, blk) == (42, 2048)


# ------------------------------------------------------- phased profiling

def test_phased_grower_bit_parity_and_frontier_launches():
    """TIMETAG profiling routes growth through the host-phased grower:
    bit-identical model text, hist_pass/split_search/apply_split scopes
    recorded, and the dispatch-count regression — histogram launches per
    tree track frontier LEVELS (well under one per leaf/split)."""
    from lightgbm_tpu.utils import profiling
    X, y = _data()
    params = {"objective": "regression", "num_leaves": 16,
              "verbosity": -1, "histogram_method": "scatter",
              "fused_iteration": False}
    rounds = 2

    def run(profile):
        profiling.reset()
        profiling.enable(profile)
        try:
            ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
            booster = lgb.train(params, ds, num_boost_round=rounds)
            return _tree_text(booster), profiling.scopes()
        finally:
            profiling.enable(False)
            profiling.reset()

    t_plain, _ = run(False)
    t_phased, scopes = run(True)
    assert t_phased == t_plain
    for name in ("hist_pass", "split_search", "apply_split"):
        assert scopes.get(name, {}).get("calls", 0) > 0, (name, scopes)
    # one histogram launch per frontier level: far fewer than one per
    # split (15 splits/tree at 16 leaves)
    hist_launches_per_tree = scopes["hist_pass"]["calls"] / rounds
    assert hist_launches_per_tree < 15, scopes["hist_pass"]
    assert hist_launches_per_tree >= 1


@pytest.mark.slow
def test_phased_equals_monolithic_under_fusion():
    """Phased + split_fusion: same trees as the monolithic fused grower
    (the phased programs run the same _grower_fns phases).

    Slow: a combination spelling of two contracts that each stay
    tier-1 — phased-vs-monolithic bit parity
    (test_phased_grower_bit_parity_and_frontier_launches) and
    fusion-on == fusion-off e2e bit parity
    (test_e2e_fusion_bit_parity_xla matrix); the phased driver runs the
    SAME _grower_fns phase programs either way, so the cross term has
    no mechanics of its own."""
    from lightgbm_tpu.utils import profiling
    X, y = _data(n=900)
    params = {"objective": "regression", "num_leaves": 8, "verbosity": -1,
              "histogram_method": "scatter", "split_fusion": "on",
              "fused_iteration": False}

    def run(profile):
        profiling.reset()
        profiling.enable(profile)
        try:
            ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
            return _tree_text(lgb.train(params, ds, num_boost_round=2))
        finally:
            profiling.enable(False)
            profiling.reset()

    assert run(True) == run(False)
