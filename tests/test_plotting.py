"""Plotting API (reference: tests/python_package_test/test_plotting.py)."""

import matplotlib

matplotlib.use("Agg")

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(500, 5))
    y = X[:, 0] + 0.5 * X[:, 1]
    params = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    vs = lgb.Dataset(X, label=y, params=params, reference=ds,
                     free_raw_data=False)
    evals = {}
    booster = lgb.train(params, ds, 10, valid_sets=[vs], evals_result=evals)
    return booster, evals


def test_plot_importance(fitted):
    booster, _ = fitted
    ax = lgb.plot_importance(booster)
    assert len(ax.patches) > 0
    ax2 = lgb.plot_importance(booster, importance_type="gain",
                              max_num_features=2)
    assert len(ax2.patches) <= 2


def test_plot_split_value_histogram(fitted):
    booster, _ = fitted
    ax = lgb.plot_split_value_histogram(booster, 0)
    assert len(ax.patches) > 0
    with pytest.raises(ValueError):
        lgb.plot_split_value_histogram(booster, 4)  # likely unused feature


def test_plot_metric(fitted):
    _, evals = fitted
    ax = lgb.plot_metric(evals)
    assert len(ax.lines) >= 1
    with pytest.raises(TypeError):
        lgb.plot_metric(fitted[0])  # Booster not accepted (reference parity)


def test_plot_tree_and_digraph(fitted):
    booster, _ = fitted
    ax = lgb.plot_tree(booster)
    assert ax is not None
    try:
        graph = lgb.create_tree_digraph(booster, show_info=["internal_count"])
        assert "node0" in graph.source
    except ImportError:
        pytest.skip("graphviz unavailable")
    with pytest.raises(IndexError):
        lgb.create_tree_digraph(booster, tree_index=999)
