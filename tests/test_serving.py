"""Resilient serving layer (lightgbm_tpu/serving.py ServeFrontend).

The serve-side acceptance contract:

- coalesced (micro-batched) responses are BIT-IDENTICAL to unbatched
  single-request predicts — padding never leaks across requests;
- a request past its deadline raises ServeTimeoutError NAMING the phase
  (queue-wait vs dispatch), driven deterministically by the
  LGBM_TPU_FAULT_SLOW_PREDICT_MS injection point;
- queue overflow sheds with a retriable ServeOverloadError, increments
  the health gauges and lands in health_snapshot()'s degradation log;
- the hot-swap state machine: a failing candidate is rejected with the
  OLD model still serving bit-identically, in-flight requests complete
  on the version they were admitted under, and post-swap predictions are
  bit-identical to a cold-built engine of the new model;
- the predict engine's caches are thread-safe: concurrent first-touch of
  one shape bucket compiles exactly once, and a swapped-in model with
  the same ensemble shape re-uses the old version's compiled programs;
- a serve-time RESOURCE_EXHAUSTED rides the predict-chunk degradation
  rung (PR 8) without consuming the training rungs.
"""

import os
import threading
import time

import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import distributed
from lightgbm_tpu.models import predict_engine as pe
from lightgbm_tpu.serving import (ServeFrontend, ServeOverloadError,
                                  ServeSwapError, ServeTimeoutError)
from lightgbm_tpu.utils import faults, profiling

SLOW_ENV = "LGBM_TPU_FAULT_SLOW_PREDICT_MS"
OOM_ENV = "LGBM_TPU_FAULT_OOM_AT_PREDICT"


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(11)
    X = rng.normal(size=(360, 6)).astype(np.float64)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(np.float64)
    return X, y


def _train(X, y, seed=1, nround=5, **extra):
    p = {"objective": "binary", "num_leaves": 5, "min_data_in_leaf": 10,
         "verbosity": -1, "seed": seed}
    p.update(extra)
    return lgb.train(p, lgb.Dataset(X, label=y, params=p), nround)


@pytest.fixture(scope="module")
def model(data):
    X, y = data
    return _train(X, y)


@pytest.fixture()
def frontend(model):
    fe = ServeFrontend(model, flush_ms=5.0)
    yield fe
    fe.close()


# ------------------------------------------------------- batching parity
def test_single_request_bit_identical(frontend, model, data):
    X, _ = data
    assert np.array_equal(frontend.predict(X[:37]), model.predict(X[:37]))
    assert np.array_equal(frontend.predict(X[:37], raw_score=True),
                          model.predict(X[:37], raw_score=True))


def test_coalesced_bit_identical(model, data):
    """Concurrent small requests coalesce into fewer dispatches, and every
    response is bit-identical to the unbatched single-request predict."""
    X, _ = data
    fe = ServeFrontend(model, flush_ms=30.0)
    try:
        fe.predict(X[:1])                     # warm (compile outside race)
        before = fe.stats()["batches"]
        sizes = [1, 3, 17, 40, 8]
        res = {}
        errs = {}

        def go(i, a, b):
            try:
                res[i] = fe.predict(X[a:b])
            except BaseException as e:       # noqa: BLE001 — reported
                errs[i] = e

        offs = np.cumsum([0] + sizes)
        ts = [threading.Thread(target=go, args=(i, offs[i], offs[i + 1]))
              for i in range(len(sizes))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs
        for i in range(len(sizes)):
            direct = model.predict(X[offs[i]:offs[i + 1]])
            assert np.array_equal(res[i], direct), f"request {i}"
        # the 5 requests flushed as fewer engine dispatches (coalesced)
        assert fe.stats()["batches"] - before < len(sizes)
    finally:
        fe.close()


def test_padding_never_leaks_across_batch_sizes(frontend, model, data):
    """A small request served from a serve slot previously filled by a
    bigger batch must read zero padding, not the stale rows."""
    X, _ = data
    big = frontend.predict(X[:100])
    one = frontend.predict(X[200:201])
    assert np.array_equal(big, model.predict(X[:100]))
    assert np.array_equal(one, model.predict(X[200:201]))


def test_donated_serve_slots_reused(frontend, model, data):
    """Steady-state serving keeps one donated buffer slot per shape
    bucket instead of allocating per call."""
    X, _ = data
    for _ in range(3):
        frontend.predict(X[:50])
    eng = model._boosting._predict_engine()
    assert eng.serve_mode
    assert len(eng._serve_slots) == 1
    (slot,) = eng._serve_slots.values()
    assert slot["staging"].shape[0] == eng.bucket_rows(50)


# ---------------------------------------------------------- deadlines
@pytest.mark.faults
def test_deadline_dispatch_phase(frontend, data):
    """A slow dispatch (injected) blows the per-request deadline: the
    caller gets a ServeTimeoutError naming the dispatch phase."""
    X, _ = data
    frontend.predict(X[:10])                 # warm: compile is not the test
    os.environ[SLOW_ENV] = "400"
    try:
        with pytest.raises(ServeTimeoutError) as ei:
            frontend.predict(X[:10], deadline_ms=100.0)
    finally:
        del os.environ[SLOW_ENV]
    assert ei.value.phase == "dispatch"
    assert "dispatch" in str(ei.value)
    assert profiling.gauges().get("serve_timeout_count", 0) >= 1


@pytest.mark.faults
def test_deadline_queue_wait_phase(frontend, data):
    """A request stuck BEHIND a slow dispatch dies in queue-wait — and the
    error says so (the diagnosable half of the deadline contract)."""
    X, _ = data
    frontend.predict(X[:10])                 # warm
    os.environ[SLOW_ENV] = "500"
    try:
        t = threading.Thread(target=lambda: frontend.predict(X[:10]))
        t.start()
        time.sleep(0.15)                     # t now inside the slow dispatch
        with pytest.raises(ServeTimeoutError) as ei:
            frontend.predict(X[10:20], deadline_ms=80.0)
        t.join()
    finally:
        del os.environ[SLOW_ENV]
    assert ei.value.phase == "queue-wait"
    assert "queue-wait" in str(ei.value)


# ------------------------------------------------------------- shedding
@pytest.mark.faults
def test_queue_overflow_sheds_retriable(model, data):
    X, _ = data
    fe = ServeFrontend(model, flush_ms=2.0, max_queue_rows=50)
    try:
        fe.predict(X[:10])                   # warm
        os.environ[SLOW_ENV] = "400"
        shed_before = distributed.degradations()
        t1 = threading.Thread(target=lambda: fe.predict(X[:30]))
        t1.start()
        time.sleep(0.15)                     # 30 rows in flight
        t2 = threading.Thread(target=lambda: fe.predict(X[30:45]))
        t2.start()
        time.sleep(0.05)                     # +15 rows queued
        with pytest.raises(ServeOverloadError) as ei:
            fe.predict(X[45:60])             # +15 would exceed 50
        t1.join()
        t2.join()
    finally:
        del os.environ[SLOW_ENV]
        fe.close()
    assert ei.value.retriable is True
    assert ei.value.limit == 50
    assert fe.stats()["shed"] >= 1
    # gauges + the degradation log both carry the overload
    assert profiling.gauges().get("serve_shed_count", 0) >= 1
    sheds = [d for d in distributed.degradations()
             if d["kind"] == "serve_shed" and d not in shed_before]
    assert sheds and sheds[-1]["limit"] == 50
    assert "serve_shed_count" in \
        distributed.health_snapshot().get("serve", {})


def test_shed_episode_count_reaches_degradation_log(model, data):
    """A shed burst updates ONE recorded episode's count in place — the
    stored dict, not a copy (record_degradation returns the stored dict
    precisely so the in-place updates are visible in the log)."""
    X, _ = data
    fe = ServeFrontend(model, flush_ms=2.0, max_queue_rows=50)
    try:
        with fe._lock:
            fe._record_shed("default", 10, 50)
            fe._record_shed("default", 10, 50)
            fe._record_shed("default", 10, 50)
        ev = [d for d in distributed.degradations()
              if d["kind"] == "serve_shed"][-1]
        assert ev["count"] == 3
        assert fe.stats()["shed"] == 3
    finally:
        fe.close()


def test_dispatcher_survives_dispatch_crash(model, data):
    """An exception escaping _dispatch (e.g. MemoryError concatenating
    the coalesced batch) must be relayed to the batch's waiters, NOT
    kill the dispatcher thread — a dead dispatcher strands every later
    request forever."""
    X, _ = data
    fe = ServeFrontend(model, flush_ms=2.0)
    try:
        fe.predict(X[:5])                    # healthy warm-up
        orig = fe._dispatch

        def boom(batch):
            raise MemoryError("simulated coalesce allocation failure")
        fe._dispatch = boom
        with pytest.raises(MemoryError):
            fe.predict(X[:5])
        fe._dispatch = orig
        assert fe._thread.is_alive()
        assert np.array_equal(fe.predict(X[:5]), model.predict(X[:5]))
    finally:
        fe.close()


def test_oversized_lone_request_admitted(model, data):
    """A single request bigger than serve_max_queue_rows on an IDLE
    frontend must dispatch (alone) instead of being shed with a
    'retriable' error that could never come true."""
    X, _ = data
    fe = ServeFrontend(model, flush_ms=2.0, max_queue_rows=30)
    try:
        out = fe.predict(X[:80])
        assert np.array_equal(out, model.predict(X[:80]))
        assert fe.stats()["shed"] == 0
    finally:
        fe.close()


# ------------------------------------------------------------- hot swap
def test_swap_success_bit_identical_to_cold_engine(model, data):
    """Post-swap serving is bit-identical to a COLD-built engine of the
    new model (an identically-trained clone with its own fresh engine)."""
    X, y = data
    fe = ServeFrontend(model, flush_ms=2.0)
    try:
        fe.predict(X[:20])
        new = _train(X, y, learning_rate=0.2)
        cold = _train(X, y, learning_rate=0.2)   # deterministic clone,
        #                                          own cold engine
        v = fe.swap("default", new)
        assert v == 2 and fe.version() == 2
        out = fe.predict(X[:50])
        assert np.array_equal(out, cold.predict(X[:50]))
        # and it genuinely changed the serving model
        assert not np.array_equal(out, model.predict(X[:50]))
    finally:
        fe.close()


@pytest.mark.slow
def test_swap_validation_failure_keeps_old_serving(model, data, tmp_path):
    """Every rejection shape leaves the registry untouched and the old
    version serving bit-identically: load failure (corrupt file), wrong
    feature count, wrong class arity, non-finite probe output.

    Slow: the rejected-swap drill (corrupt candidate refused, old model
    keeps serving, then a valid candidate swaps in) runs end-to-end on
    every CI pass in scripts/serve_smoke.py (tests/run_suite.sh), and
    the ACCEPT side of the same _validate path stays tier-1 via
    test_swap_success_bit_identical_to_cold_engine /
    test_swap_same_shape_reuses_compiled_programs."""
    X, y = data
    # candidates trained UP FRONT: _init_train resets the process
    # degradation log, so training between swap attempts would wipe the
    # rejection events this test counts
    narrow = _train(X[:, :4], y)                         # feature count
    multi = lgb.train(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 5,
         "verbosity": -1},
        lgb.Dataset(X, label=(X[:, 0] > 0).astype(float)
                    + (X[:, 1] > 0)), 3)
    # candidate whose probe output is non-finite: poison a leaf value
    import re
    poisoned = re.sub(r"(leaf_value=)([-0-9.e+]+)", r"\1inf",
                      model.model_to_string(), count=1)
    fe = ServeFrontend(model, flush_ms=2.0)
    try:
        baseline = fe.predict(X[:40])
        deg_before = len(distributed.degradations())

        bad_file = tmp_path / "corrupt.txt"
        bad_file.write_text("tree\nversion=v3\nTree=0\ngarbage")
        with pytest.raises(ServeSwapError):
            fe.swap("default", str(bad_file))

        with pytest.raises(ServeSwapError, match="failed to predict"):
            fe.swap("default", narrow)

        with pytest.raises(ServeSwapError, match="arity"):
            fe.swap("default", multi)

        with pytest.raises(ServeSwapError, match="non-finite"):
            fe.swap("default", lgb.Booster(model_str=poisoned))

        assert fe.version() == 1
        assert np.array_equal(fe.predict(X[:40]), baseline)
        rejects = [d for d in distributed.degradations()[deg_before:]
                   if d["kind"] == "serve_swap_rejected"]
        assert len(rejects) == 4
    finally:
        fe.close()


@pytest.mark.faults
def test_inflight_requests_complete_on_admitted_version(model, data):
    """A request admitted under v1 that is still dispatching when the
    swap lands must return v1's bits (batches hold the entry reference,
    not the name)."""
    X, y = data
    fe = ServeFrontend(model, flush_ms=2.0)
    try:
        fe.predict(X[:12])                   # warm v1
        new = _train(X, y, learning_rate=0.2)    # genuinely different bits
        _ = new.predict(X[:12])              # warm v2 outside the window
        os.environ[SLOW_ENV] = "400"
        res = {}
        t = threading.Thread(
            target=lambda: res.update(r1=fe.predict(X[:12])))
        t.start()
        time.sleep(0.15)                     # r1 is inside the slow dispatch
        del os.environ[SLOW_ENV]             # swap validation runs fast
        fe.swap("default", new)
        t.join()
        assert np.array_equal(res["r1"], model.predict(X[:12]))
        assert np.array_equal(fe.predict(X[:12]), new.predict(X[:12]))
    finally:
        os.environ.pop(SLOW_ENV, None)
        fe.close()


def test_swap_same_shape_reuses_compiled_programs(data):
    """Model versions with the same ensemble shape (tree count, depth,
    bucket) share the module-level jitted programs: the swap costs ZERO
    accumulation compiles — the no-recompile-storm-on-reload contract."""
    X, y = data
    a = _train(X, y, seed=21, max_depth=2)
    b = _train(X, y, seed=22, max_depth=2)
    ea = a._boosting._predict_engine()
    eb = b._boosting._predict_engine()
    if (ea.depth, ea.T, ea.k) != (eb.depth, eb.T, eb.k):
        pytest.skip("ensembles trained to different static shapes")
    fe = ServeFrontend(a, flush_ms=2.0)
    try:
        fe.predict(X[:33])                   # compiles v1's bucket program
        before = dict(pe.TRACE_COUNTS)
        fe.swap("default", b)                # probe: same bucket statics
        fe.predict(X[:33])
        delta = {k: pe.TRACE_COUNTS[k] - before[k] for k in before}
        assert delta["accum"] == 0, delta
    finally:
        fe.close()


# ------------------------------------------------- engine thread safety
def test_concurrent_first_call_compiles_once(data):
    """Concurrent FIRST-touch of one shape bucket from many threads must
    compile its program exactly once (the engine lock serializes the
    first dispatch of each new program key)."""
    X, y = data
    booster = _train(X, y, seed=31, num_leaves=6, nround=7)
    jax.clear_caches()                       # unique trace, no stale hits
    pe._compiled_keys.clear()                # sentinel must match the cache
    barrier = threading.Barrier(4)
    errs = []
    outs = [None] * 4

    def go(i):
        try:
            barrier.wait(timeout=10)
            outs[i] = booster.predict(X[:61], raw_score=True)
        except BaseException as e:           # noqa: BLE001 — reported
            errs.append(e)

    before = pe.TRACE_COUNTS["accum"]
    ts = [threading.Thread(target=go, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    assert pe.TRACE_COUNTS["accum"] - before == 1
    for o in outs[1:]:
        assert np.array_equal(outs[0], o)


# --------------------------------------------------------- serve-time OOM
@pytest.mark.faults
def test_serve_oom_rides_predict_chunk_ladder(data):
    """A RESOURCE_EXHAUSTED inside a serve dispatch shrinks the predict
    chunk (PR 8 rung), records the degradation, answers the request —
    and never consumes the TRAINING rungs."""
    X, y = data
    booster = _train(X, y, seed=41)
    fe = ServeFrontend(booster, flush_ms=2.0)
    try:
        fe.predict(X[:25])                   # warm
        deg_before = len(distributed.degradations())
        faults.reset_predict_oom()
        os.environ[OOM_ENV] = "1"
        try:
            out = fe.predict(X[:25])
        finally:
            del os.environ[OOM_ENV]
        assert np.array_equal(out, booster.predict(X[:25]))
        ooms = [d for d in distributed.degradations()[deg_before:]
                if d["kind"] == "oom_predict"]
        assert len(ooms) == 1
        g = booster._boosting
        assert g._oom_level == 0             # training rungs untouched
        assert g._oom_predict_chunk > 0
    finally:
        faults.reset_predict_oom()
        fe.close()


@pytest.mark.faults
def test_file_loaded_model_oom_rides_ladder(model, data, tmp_path):
    """A hot-swapped FILE-loaded model (LoadedGBDT host loop, no engine)
    must honor the same contract: serve-time RESOURCE_EXHAUSTED shrinks
    its predict chunk, records the degradation, answers the request."""
    X, _ = data
    path = tmp_path / "m.txt"
    model.save_model(str(path))
    fe = ServeFrontend(model, flush_ms=2.0)
    try:
        fe.swap("default", str(path))
        loaded = lgb.Booster(model_file=str(path))
        fe.predict(X[:25])                   # warm the swapped entry
        deg_before = len(distributed.degradations())
        faults.reset_predict_oom()
        os.environ[OOM_ENV] = "1"
        try:
            out = fe.predict(X[:25])
        finally:
            del os.environ[OOM_ENV]
        assert np.array_equal(out, loaded.predict(X[:25]))
        ooms = [d for d in distributed.degradations()[deg_before:]
                if d["kind"] == "oom_predict"]
        assert len(ooms) == 1
    finally:
        faults.reset_predict_oom()
        fe.close()


# ------------------------------------------------------------ lifecycle
def test_health_gauges_and_stats(frontend, data):
    X, _ = data
    for n in (5, 30):
        frontend.predict(X[:n])
    serve = distributed.health_snapshot().get("serve", {})
    for k in ("serve_requests", "serve_batches", "serve_p50_ms",
              "serve_p99_ms", "serve_queue_rows", "serve_inflight_rows"):
        assert k in serve, k
    st = frontend.stats()
    assert st["requests"] >= 2 and st["p50_ms"] > 0
    assert st["queued_rows"] == 0 and st["inflight_rows"] == 0


def test_close_releases_serve_resources(model, data):
    """close() must not leave the booster pinning donated per-bucket
    device buffers or routing later direct predicts through the serve
    path (no dispatcher exists anymore)."""
    X, _ = data
    fe = ServeFrontend(model, flush_ms=2.0)
    fe.predict(X[:40])
    eng = model._boosting._predict_engine()
    assert eng.serve_mode and eng._serve_slots
    fe.close()
    assert not eng.serve_mode and not eng._serve_slots
    assert np.array_equal(model.predict(X[:40]), model.predict(X[:40]))


def test_unknown_model_and_closed_frontend(model, data):
    X, _ = data
    fe = ServeFrontend(model, flush_ms=2.0)
    with pytest.raises(KeyError, match="unknown model"):
        fe.predict(X[:3], model="nope")
    with pytest.raises(KeyError, match="unknown model"):
        fe.swap("nope", model)
    fe.close()
    with pytest.raises(RuntimeError, match="closed"):
        fe.predict(X[:3])


def test_config_params_steer_policy(data):
    """serve_* params flow from the registered booster's config when no
    kwarg overrides are given."""
    X, y = data
    b = _train(X, y, serve_flush_ms=7.0, serve_max_batch_rows=123,
               serve_max_queue_rows=456, serve_deadline_ms=0.0)
    fe = ServeFrontend(b)
    try:
        assert fe.flush_s == pytest.approx(0.007)
        assert fe.max_batch_rows == 123
        assert fe.max_queue_rows == 456
    finally:
        fe.close()


def test_two_models_served_independently(model, data):
    X, y = data
    other = _train(X, y, seed=77, nround=3)
    fe = ServeFrontend(model, flush_ms=2.0)
    try:
        fe.register("other", other)
        assert np.array_equal(fe.predict(X[:20], model="other"),
                              other.predict(X[:20]))
        assert np.array_equal(fe.predict(X[:20]), model.predict(X[:20]))
        assert fe.stats()["models"] == {"default": 1, "other": 1}
    finally:
        fe.close()
