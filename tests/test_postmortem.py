"""Automated gang post-mortem (lightgbm_tpu/postmortem.py): one
classification test per injected fault class — KILL_RANK, HANG_RANK,
FLIP_SCORE divergence, NAN_HIST, OOM_AT_ITER exhaustion — each driven
through the utils/faults.py harness and asserting the correct verdict
AND the named rank, plus timeline-ordering and gate unit tests.

Tier-1 runs the single-process spelling of each fault (the artifacts —
flight JSONLs, watchdog/divergence diagnoses — are byte-identical to
what a gang rank writes); the supervised multi-process spellings ride
the slow tier and scripts/postmortem_smoke.py (run_suite.sh).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import distributed, postmortem, telemetry
from lightgbm_tpu.utils import faults
from lightgbm_tpu.utils.log import LightGBMError

pytestmark = pytest.mark.faults


def _data(n=2000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(size=n) > 0).astype(np.float32)
    return X, y


def _train(params=None, rounds=6, n=2000, **kwargs):
    X, y = _data(n=n)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 20}
    p.update(params or {})
    return lgb.train(p, ds, rounds, **kwargs)


# ---------------------------------------------- fault-class verdicts

def test_classify_kill_rank(tmp_path):
    """KILL_RANK: a rank hard-killed mid-run (the harness's rank-
    targeted os._exit(137)) leaves a fault-kill flight flush the
    analyzer classifies 'kill', naming the rank and the in-flight
    iteration. Also asserts the flushed JSONL schema-validates and
    names the in-flight iteration — the coverage
    test_telemetry.py::test_kill_fault_flushes_jsonl (now slow) used
    to carry in tier-1."""
    d = str(tmp_path / "tele")
    code = (
        "import numpy as np, lightgbm_tpu as lgb\n"
        "rng = np.random.RandomState(0)\n"
        "X = rng.normal(size=(2000, 8)).astype(np.float32)\n"
        "y = (X[:, 0] > 0).astype(np.float32)\n"
        "ds = lgb.Dataset(X, label=y, params={'verbosity': -1})\n"
        "lgb.train({'objective': 'binary', 'num_leaves': 15,\n"
        "           'verbosity': -1, 'telemetry_dir': %r,\n"
        "           'fault_kill_rank_at_iter': '0:3'}, ds, 10)\n" % d)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 137, r.stderr[-2000:]
    # the flushed JSONL validates and names the in-flight iteration
    path = os.path.join(d, "flight_rank0.jsonl")
    assert os.path.exists(path)
    recs, errors = telemetry.validate_flight_jsonl(path)
    assert errors == []
    assert "at iteration 3" in recs[-1]["reason"]
    # the analyzer reaches the kill verdict and names rank 0 / iter 3,
    # folding the supervisor-style exit-code evidence in
    failures = [{"incarnation": 0, "failed_ranks": [0],
                 "exit_codes": {0: 137}, "reason": "rank 0 exit 137",
                 "watchdog": []}]
    pm = postmortem.analyze(d, failures=failures)
    assert pm.verdict == "kill"
    assert pm.rank == 0
    assert pm.iteration == 3
    assert any("fault-kill" in e for e in pm.evidence)
    # memory trend from the per-iteration samples is on the report
    assert pm.memory and "rss" in pm.memory


def test_classify_hang_rank(tmp_path, monkeypatch):
    """HANG_RANK: the rank-targeted hang stalls the loop, the
    collective-deadline watchdog fires and writes its diagnosis, and
    the analyzer classifies 'hang' naming the stalled rank."""
    d = str(tmp_path / "diag")
    monkeypatch.setenv(distributed._DIAG_DIR_ENV, d)
    with pytest.raises(distributed.DistributedTimeoutError):
        _train({"collective_deadline": 2.0,
                "fault_hang_rank_at_iter": "0:2"}, rounds=6)
    assert os.path.exists(os.path.join(d, "watchdog_rank0.json"))
    pm = postmortem.analyze(d)
    assert pm.verdict == "hang"
    assert pm.rank == 0
    assert any("watchdog" in e for e in pm.evidence)
    # the watchdog diagnosis carries wall + monotonic stamps so the
    # timeline can order it against flight records and OOM rungs
    with open(os.path.join(d, "watchdog_rank0.json")) as fh:
        diag = json.load(fh)
    assert diag["t"] > 0 and diag["t_mono"] > 0
    assert diag["kind"] == "watchdog"


def test_classify_flip_score_divergence(tmp_path):
    """FLIP_SCORE divergence, single-process spelling: the harness's
    one-bit score flip drives a real fingerprint vote whose verdict
    (rank 1 corrupt) is written in the exact divergence_rank*.json
    shape check_model_integrity emits — the analyzer must classify
    'divergence' and name the corrupt rank. (The full 3-rank supervised
    gang spelling of the same vote runs in tier-1 as
    test_integrity.py::test_supervised_corrupt_rank_restart_bit_identical
    and slow here as test_gang_flip_score_postmortem.)"""
    booster = _train(rounds=3)
    boosting = booster._boosting
    fp_good = distributed.model_fingerprint(boosting)
    plan = faults.FaultPlan(flip_score_rank=(0, 2))
    flipped = faults.maybe_flip_score(plan, 2, boosting.train_score)
    assert flipped is not None
    boosting.train_score = flipped
    fp_bad = distributed.model_fingerprint(boosting)
    assert fp_bad["score"] != fp_good["score"]
    entries = [dict(fp_good, rank=0), dict(fp_bad, rank=1),
               dict(fp_good, rank=2)]
    corrupt, indeterminate = distributed.divergence_verdict(entries)
    assert (corrupt, indeterminate) == ([1], False)
    d = str(tmp_path / "diag")
    os.makedirs(d)
    table = {str(e["rank"]): {"trees": e["trees"][:16],
                              "score": e["score"][:16]} for e in entries}
    import time as _time
    with open(os.path.join(d, "divergence_rank1.json"), "w") as fh:
        json.dump({"rank": 1, "iteration": 2, "corrupt_ranks": corrupt,
                   "fingerprints": table, "kind": "divergence",
                   "t": _time.time(), "t_mono": _time.monotonic()}, fh)
    pm = postmortem.analyze(d)
    assert pm.verdict == "divergence"
    assert pm.rank == 1
    assert pm.iteration == 2
    assert any("corrupt_ranks=[1]" in e for e in pm.evidence)


def test_classify_nan_hist(tmp_path):
    """NAN_HIST: the in-program NaN injection trips the fused path's
    sentinels; the train-error flush names the poisoned iteration and
    the analyzer classifies 'nan' on rank 0."""
    with pytest.raises(LightGBMError, match="iteration 2"):
        _train({"check_numerics": True, "fault_nan_hist_at_iter": 2,
                "telemetry_dir": str(tmp_path / "tele")}, rounds=6)
    pm = postmortem.analyze(str(tmp_path / "tele"))
    assert pm.verdict == "nan"
    assert pm.rank == 0
    assert pm.iteration == 2
    assert any("sentinel" in e or "non-finite" in e for e in pm.evidence)


def test_classify_oom_exhaustion(tmp_path):
    """OOM_AT_ITER exhaustion: spending the whole ladder flushes
    'oom-exhausted' with the rung history; the analyzer classifies
    'oom' on rank 0 with the rung evidence (traffic-model predicted
    bytes included) and a memory trend. Also asserts the exhaustion
    flush + full [1, 2, 3] ladder history — the coverage
    test_telemetry.py::test_oom_exhaustion_flushes (now slow) used to
    carry in tier-1."""
    d = str(tmp_path / "tele")
    with pytest.raises(faults.SimulatedResourceExhausted):
        _train({"telemetry_dir": d, "fault_oom_at_iter": 2,
                "fault_oom_count": 4}, rounds=6)
    # the exhaustion flush carries the full ladder history
    rec = telemetry.recorder()
    recs, errors = telemetry.validate_flight_jsonl(rec.path())
    assert errors == []
    flush = next(r for r in recs if r["type"] == "flush"
                 and r["reason"].startswith("oom-exhausted"))
    degr = flush["health"].get("degradations") or []
    assert [x["level"] for x in degr if x["kind"] == "oom"] == [1, 2, 3]
    # every rung is explainable: memory snapshot + predicted bytes ride
    # the event (HBM fields null on CPU — the None-tolerance contract)
    for x in degr:
        assert "memory" in x and "host_rss_bytes" in x["memory"]
        assert x["predicted_hist_bytes"] > 0
        assert x["t_mono"] > 0
    pm = postmortem.analyze(d)
    assert pm.verdict == "oom"
    assert pm.rank == 0
    assert pm.iteration == 2
    assert any("predicted" in e for e in pm.evidence)
    assert any("rung" in e for e in pm.evidence)
    assert pm.memory and pm.memory["rss"]["samples"] >= 1


@pytest.mark.slow
def test_gang_flip_score_postmortem(tmp_path):
    """Slow: the REAL 3-rank supervised FLIP_SCORE gang (the divergence
    vote itself is tier-1 via test_integrity.py's supervised restart
    test; the single-process artifact spelling is tier-1 above) — a
    no-restart-budget gang must raise GangFailedError carrying an
    auto-generated post-mortem that classifies 'divergence' and names
    the flipped rank."""
    from lightgbm_tpu import supervisor
    params = {"objective": "binary", "num_leaves": 8,
              "min_data_in_leaf": 5, "boost_from_average": False,
              "histogram_method": "scatter", "verbosity": -1,
              "integrity_check_period": 1, "heartbeat_interval": 0.4,
              "collective_deadline": 12.0}
    ck = str(tmp_path / "ck")
    os.environ["LGBM_TPU_FAULT_FLIP_SCORE_RANK"] = "1:2"
    try:
        with pytest.raises(supervisor.GangFailedError) as ei:
            supervisor.run_supervised(
                _gang_train_fn, nproc=3, args=(params, ck),
                devices_per_proc=1, checkpoint_dir=ck, max_restarts=0,
                timeout=240)
    finally:
        os.environ.pop("LGBM_TPU_FAULT_FLIP_SCORE_RANK", None)
    err = ei.value
    assert err.postmortem and os.path.exists(err.postmortem)
    with open(err.postmortem) as fh:
        report = json.load(fh)
    assert report["verdict"] == "divergence"
    assert report["rank"] == 1


def _gang_train_fn(rank, params, ckdir):
    import lightgbm_tpu as lgb_mod
    rng = np.random.RandomState(7)
    X = rng.normal(size=(320, 6))
    y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(np.float64)
    ds = lgb_mod.Dataset(X, label=y, params=dict(params),
                         free_raw_data=False)
    booster = lgb_mod.train(
        dict(params), ds, 4,
        callbacks=[lgb_mod.checkpoint_callback(ckdir, period=1)],
        resume_from=ckdir)
    return booster.model_to_string()


# ------------------------------------------------- analyzer unit tests

def test_analyze_empty_dir_is_unknown(tmp_path):
    pm = postmortem.analyze(str(tmp_path))
    assert pm.verdict == "unknown"
    assert pm.rank is None
    assert pm.render()                  # renders without artifacts


def test_timeline_orders_degradations_against_watchdog(tmp_path):
    """The satellite contract: record_degradation events carry wall +
    monotonic timestamps and the active iteration, so a post-mortem
    timeline orders OOM rungs against watchdog fires."""
    d = str(tmp_path)
    rec = telemetry.FlightRecorder(capacity=8, directory=d, rank=0)
    distributed.reset_degradations()
    e1 = distributed.record_degradation({"kind": "oom", "level": 1,
                                         "action": "hist_block -> 256"})
    assert e1["t"] > 0 and e1["t_mono"] > 0 and "iteration" in e1
    rec.record(iteration=0, wall_s=0.1)
    rec.flush("test-event")
    import time as _time
    _time.sleep(0.01)
    with open(os.path.join(d, "watchdog_rank0.json"), "w") as fh:
        json.dump({"rank": 0, "iteration": 1, "phase": "step:1",
                   "elapsed": 9.9, "deadline": 5.0, "suspects": [0],
                   "kind": "watchdog", "t": _time.time(),
                   "t_mono": _time.monotonic()}, fh)
    distributed.reset_degradations()
    pm = postmortem.analyze(d)
    kinds = [e["kind"] for e in pm.timeline if e["t"] is not None]
    # the rung (recorded first) sorts before the watchdog fire
    assert kinds.index("degradation") < kinds.index("watchdog")


def test_monotonic_orders_degradations(monkeypatch):
    """Two rungs recorded in sequence carry strictly increasing
    monotonic stamps (wall clocks can step backwards; t_mono cannot)."""
    distributed.reset_degradations()
    a = distributed.record_degradation({"kind": "oom", "level": 1,
                                        "action": "a"})
    b = distributed.record_degradation({"kind": "oom", "level": 2,
                                        "action": "b"})
    assert b["t_mono"] > a["t_mono"]
    assert b["seq"] == a["seq"] + 1
    distributed.reset_degradations()


def test_incarnation_suffixed_flights_both_gathered(tmp_path):
    """A supervised relaunch writes flight_rank0.r1.jsonl next to the
    dead incarnation's flight_rank0.jsonl — the analyzer reads both,
    newest incarnation last."""
    d = str(tmp_path)
    for inc in (0, 1):
        rec = telemetry.FlightRecorder(capacity=4, directory=d, rank=0,
                                       incarnation=inc)
        rec.record(iteration=inc * 10, wall_s=0.1)
        rec.flush("train-end")
    flights = postmortem.gather_flights([d])
    assert [(f.rank, f.incarnation) for f in flights] == [(0, 0), (0, 1)]


def test_write_report_roundtrip(tmp_path):
    pm = postmortem.analyze(str(tmp_path))
    path = postmortem.write_report(pm, str(tmp_path / "out"))
    with open(path) as fh:
        doc = json.load(fh)
    assert doc["verdict"] == "unknown"
    assert os.path.exists(os.path.join(str(tmp_path / "out"),
                                       postmortem.REPORT_TEXT))
