"""Histogram precision (gpu_use_dp analog) + profiling subsystem
(VERDICT r2 item 10)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _auc(pred, y):
    order = np.argsort(pred)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(pred) + 1)
    pos = y > 0
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def test_f32_hist_auc_parity(binary_example):
    """The float32 histogram path must track the float64 path's AUC closely
    (the reference's documented f32-GPU vs f64-CPU parity,
    docs/GPU-Performance.rst:133-140: identical to 6 digits at 255 bins).
    The f64 run executes in a subprocess with JAX_ENABLE_X64 so the global
    x64 switch cannot leak into this test session."""
    Xtr, ytr, Xte, yte = binary_example
    ds = lgb.Dataset(Xtr, label=ytr, params={"verbosity": -1})
    b32 = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1}, ds, num_boost_round=60)
    auc32 = _auc(b32.predict(Xte, raw_score=True), yte)
    assert auc32 > 0.80, auc32

    code = f"""
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {REPO!r})
import lightgbm_tpu as lgb
tr = np.loadtxt("/root/reference/examples/binary_classification/binary.train")
te = np.loadtxt("/root/reference/examples/binary_classification/binary.test")
ds = lgb.Dataset(tr[:, 1:], label=tr[:, 0], params={{"verbosity": -1}})
b = lgb.train({{"objective": "binary", "num_leaves": 31, "verbosity": -1,
               "gpu_use_dp": True}}, ds, num_boost_round=60)
np.save("/tmp/_dp_pred.npy", b.predict(te[:, 1:], raw_score=True))
"""
    env = dict(os.environ, JAX_ENABLE_X64="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env, timeout=900,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    auc64 = _auc(np.load("/tmp/_dp_pred.npy"), yte)
    # near-tie splits flip between precisions so trees legitimately diverge
    # (the reference's 6-digit f32/f64 parity is measured on 500k-row test
    # sets; on this 500-row set one flipped split moves AUC ~5e-3)
    assert auc64 > 0.80, auc64
    assert abs(auc32 - auc64) < 1e-2, (auc32, auc64)


def test_gpu_use_dp_without_x64_warns_and_trains(binary_example):
    Xtr, ytr, _, _ = binary_example
    ds = lgb.Dataset(Xtr, label=ytr, params={"verbosity": -1})
    booster = lgb.train({"objective": "binary", "num_leaves": 8,
                         "gpu_use_dp": True, "verbosity": -1},
                        ds, num_boost_round=2)
    assert booster._boosting.host_trees[0].num_leaves > 1


def test_profiling_timer_table(binary_example):
    from lightgbm_tpu.utils import profiling
    Xtr, ytr, _, _ = binary_example
    profiling.reset()
    profiling.enable(True)
    try:
        ds = lgb.Dataset(Xtr[:1000], label=ytr[:1000],
                         params={"verbosity": -1})
        lgb.train({"objective": "binary", "num_leaves": 8, "verbosity": -1},
                  ds, num_boost_round=3)
        tab = profiling.table()
    finally:
        profiling.enable(False)
        profiling.reset()
    # the fused fast path folds the gradients phase INTO grow_tree (one
    # jitted program per iteration, gbdt._fused_step_fn), so the table
    # shows grow/finalize/score scopes; "gradients" only appears on the
    # phase-by-phase path
    assert "grow_tree" in tab
    assert "score_update" in tab and "finalize_tree" in tab
