"""Forced splits, forced bins and prediction early stopping
(reference: serial_tree_learner.cpp:450 ForceSplits,
dataset_loader.cpp:1373 GetForcedBins, prediction_early_stop.cpp;
VERDICT r2 items 8-9). Driven by the reference's own example JSON files."""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

from conftest import REFERENCE_DATA_REASON

FORCED_SPLITS = "/root/reference/examples/binary_classification/forced_splits.json"
FORCED_BINS = "/root/reference/examples/regression/forced_bins.json"
FORCED_BINS2 = "/root/reference/examples/regression/forced_bins2.json"

# these tests are driven by the reference's own example JSON files; when
# the checkout is absent they must SKIP, not fail on the missing file
needs_forced_jsons = pytest.mark.skipif(
    not os.path.exists(FORCED_BINS), reason=REFERENCE_DATA_REASON)


def test_forced_splits_shape_tree(binary_example):
    """The first two tree levels must follow the forced-splits JSON
    (feature 25 @ 1.30, then feature 26 @ 0.85 on both sides)."""
    Xtr, ytr, _, _ = binary_example
    ds = lgb.Dataset(Xtr, label=ytr, params={"verbosity": -1})
    booster = lgb.train({"objective": "binary", "num_leaves": 16,
                         "forcedsplits_filename": FORCED_SPLITS,
                         "verbosity": -1}, ds, num_boost_round=3)
    for ht in booster._boosting.host_trees:
        feats = [int(ht.feature_indices[s]) for s in ht.split_feature]
        # node 0 = root forced to feature 25; nodes 1-2 = its children
        # forced to feature 26
        assert feats[0] == 25
        assert feats[1] == 26 and feats[2] == 26
        # thresholds bin-resolve at/above the forced values
        assert ht.threshold[0] >= 1.30 - 0.2
        assert abs(ht.threshold[1] - ht.threshold[2]) < 1e-9
    # the model still learns (forced top + free growth below)
    pred = booster.predict(Xtr, raw_score=True)
    auc_like = np.corrcoef(pred, ytr)[0, 1]
    assert auc_like > 0.2


def test_forced_splits_invalid_feature_warns_and_trains(tmp_path):
    """A forced split on an unusable feature drops that subtree, not the
    training run."""
    import json
    p = tmp_path / "fs.json"
    p.write_text(json.dumps({"feature": 9999, "threshold": 1.0}))
    rng = np.random.RandomState(0)
    X = rng.normal(size=(500, 5))
    y = (X[:, 0] > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    booster = lgb.train({"objective": "binary", "num_leaves": 8,
                         "forcedsplits_filename": str(p), "verbosity": -1},
                        ds, num_boost_round=2)
    assert booster._boosting.host_trees[0].num_leaves > 1


@needs_forced_jsons
def test_forced_bins():
    """Behavioral port of the reference's forced-bins scenario
    (test_engine.py:2258): forced boundaries on feature 0 make fine
    distinctions available there, while feature 1's forced range leaves
    coarse bins elsewhere."""
    x = np.zeros((100, 2))
    x[:, 0] = np.arange(0, 1, 0.01)
    x[:, 1] = -np.arange(0, 1, 0.01)
    y = np.arange(0, 1, 0.01)
    params = {"objective": "regression_l1", "max_bin": 5,
              "forcedbins_filename": FORCED_BINS, "num_leaves": 2,
              "min_data_in_leaf": 1, "verbosity": -1}
    ds = lgb.Dataset(x, label=y, params=params)
    est = lgb.train(params, ds, num_boost_round=20)
    # forced bounds 0.3/0.35/0.4 on feature 0 separate these three rows
    new_x = np.zeros((3, 2))
    new_x[:, 0] = [0.31, 0.37, 0.41]
    assert len(np.unique(est.predict(new_x))) == 3
    # feature 1's forced bounds (-0.1/-0.15/-0.2) leave these in one bin
    new_x = np.zeros((3, 2))
    new_x[:, 1] = [-0.9, -0.6, -0.3]
    assert len(np.unique(est.predict(new_x))) == 1
    # mapper-level check: forced bounds are present as bin boundaries
    m = ds._boosting_mappers if hasattr(ds, "_boosting_mappers") else ds.mappers
    for b in (0.3, 0.35, 0.4):
        assert np.any(np.isclose(m[0].bin_upper_bound, b)), m[0].bin_upper_bound


@needs_forced_jsons
def test_forced_bins_even_distribution():
    """forced_bins2.json (evenly spaced bounds) yields near-even bin
    occupancy (reference: test_engine.py:2288-2295)."""
    x = np.arange(0, 1, 0.01).reshape(-1, 1)
    y = np.arange(0, 1, 0.01)
    params = {"objective": "regression_l1", "max_bin": 11,
              "forcedbins_filename": FORCED_BINS2, "num_leaves": 2,
              "min_data_in_leaf": 1, "verbosity": -1}
    est = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                    num_boost_round=50)
    predicted = est.predict(x[1:])
    _, counts = np.unique(predicted, return_counts=True)
    assert min(counts) >= 9
    assert max(counts) <= 11


def test_prediction_early_stop(binary_example):
    Xtr, ytr, Xte, _ = binary_example
    ds = lgb.Dataset(Xtr, label=ytr, params={"verbosity": -1})
    booster = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbosity": -1}, ds, num_boost_round=40)
    full = booster.predict(Xte, raw_score=True)
    # a huge margin threshold never triggers: identical output
    same = booster.predict(Xte, raw_score=True, pred_early_stop=True,
                           pred_early_stop_freq=5,
                           pred_early_stop_margin=1e30)
    np.testing.assert_array_equal(full, same)
    # a zero margin stops every row at the first check round: equal to
    # predicting with only the first check-round's iterations
    stopped = booster.predict(Xte, raw_score=True, pred_early_stop=True,
                              pred_early_stop_freq=5,
                              pred_early_stop_margin=0.0)
    first5 = booster.predict(Xte, raw_score=True, num_iteration=5)
    np.testing.assert_allclose(stopped, first5, rtol=1e-12)
    # decisions stay consistent at a reasonable margin
    mid = booster.predict(Xte, raw_score=True, pred_early_stop=True,
                          pred_early_stop_freq=5, pred_early_stop_margin=4.0)
    assert np.mean((mid > 0) == (full > 0)) > 0.95
