"""Cross-implementation consistency against the ACTUAL reference binary
(the reference's own strategy: tests/python_package_test/test_consistency.py
compares Python training to CLI-trained outputs from examples/* configs).

These tests need a compiled reference `lightgbm` CLI. The recipe that
works in this image (the reference's fmt/fast_double_parser/eigen
submodules are not checked out; fmt 8.1 + Eigen come from the tensorflow
package's bundled headers, fast_double_parser is a 10-line strtod shim,
and `-I/tmp/refshim/pad/a/b` makes the relative
"../../../external_libs/..." includes resolve into the shim tree):

    mkdir -p /tmp/refshim/pad/a/b \
             /tmp/refshim/external_libs/fast_double_parser/include
    ln -sfn /opt/venv/lib/python3.12/site-packages/tensorflow/include/\
external/fmt /tmp/refshim/external_libs/fmt
    # write the strtod-based fast_double_parser.h shim (see git history)
    mkdir -p /tmp/refbuild && cd /tmp/refbuild
    TF_INC=/opt/venv/lib/python3.12/site-packages/tensorflow/include
    cmake -G Ninja -DCMAKE_BUILD_TYPE=Release \
          -DCMAKE_CXX_FLAGS="-I/tmp/refshim/pad/a/b -I$TF_INC" \
          -DCMAKE_CXX_FLAGS_RELEASE="-O3 -DNDEBUG -std=c++14" \
          -DEXECUTABLE_OUTPUT_PATH=/tmp/refbuild /root/reference
    ninja lightgbm

Tests auto-skip when the binary is absent, like the reference's own
env-gated GPU tests.

What is proven here:
- LOAD compat: a model trained by the reference C++ loads into our
  Booster and predicts within float tolerance of the reference's own
  predictions.
- SAVE compat: a model trained by US loads into the reference binary and
  its predictions match ours.
- Quality parity: same data, same params, reference vs us — held-out
  binary logloss/AUC within a small delta.
"""

import os
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb

REF_BIN = os.environ.get("LIGHTGBM_REF_BINARY", "/tmp/refbuild/lightgbm")
EXAMPLES = "/root/reference/examples/binary_classification"

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not os.path.exists(REF_BIN),
                       reason=f"reference binary not built at {REF_BIN}"),
]


def _run_ref(workdir, *args):
    r = subprocess.run([REF_BIN, *args], cwd=workdir, capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    return r.stdout


@pytest.fixture(scope="module")
def ref_model(tmp_path_factory):
    """Train the reference CLI on its own binary_classification example."""
    wd = tmp_path_factory.mktemp("refrun")
    _run_ref(wd, "task=train", f"data={EXAMPLES}/binary.train",
             "objective=binary", "num_trees=20", "num_leaves=31",
             "learning_rate=0.1", "min_data_in_leaf=20", "verbosity=-1",
             f"output_model={wd}/ref_model.txt")
    _run_ref(wd, "task=predict", f"data={EXAMPLES}/binary.test",
             f"input_model={wd}/ref_model.txt",
             f"output_result={wd}/ref_pred.txt")
    pred = np.loadtxt(wd / "ref_pred.txt")
    return wd, str(wd / "ref_model.txt"), pred


def test_load_reference_model_prediction_parity(ref_model, binary_example):
    """A reference-trained v3 model file loads here and predicts the
    reference's own probabilities (float tolerance: our traversal
    accumulates f64 like the reference's)."""
    _, model_file, ref_pred = ref_model
    _, _, Xte, _ = binary_example
    booster = lgb.Booster(model_file=model_file)
    ours = booster.predict(Xte)
    np.testing.assert_allclose(ours, ref_pred, rtol=1e-5, atol=1e-7)


def test_reference_loads_our_model(ref_model, binary_example, tmp_path):
    """SAVE compat: the reference binary consumes OUR model text and
    reproduces our predictions."""
    wd, _, _ = ref_model
    Xtr, ytr, Xte, _ = binary_example
    params = {"objective": "binary", "num_leaves": 31,
              "learning_rate": 0.1, "min_data_in_leaf": 20,
              "verbosity": -1}
    booster = lgb.train(params, lgb.Dataset(Xtr, label=ytr), 20)
    ours = booster.predict(Xte)
    model_path = tmp_path / "our_model.txt"
    booster.save_model(str(model_path))
    _run_ref(tmp_path, "task=predict", f"data={EXAMPLES}/binary.test",
             f"input_model={model_path}",
             f"output_result={tmp_path}/their_pred.txt")
    theirs = np.loadtxt(tmp_path / "their_pred.txt")
    np.testing.assert_allclose(theirs, ours, rtol=1e-5, atol=1e-7)


def test_training_quality_parity(ref_model, binary_example):
    """Same data, same params: held-out AUC within 0.01 of the reference.
    (Bit-identical trees are NOT expected — float accumulation order and
    histogram precision differ, the same tolerance the reference accepts
    between its own CPU and GPU paths, docs/GPU-Performance.rst:133-140.)"""
    from sklearn.metrics import roc_auc_score
    _, _, ref_pred = ref_model
    Xtr, ytr, Xte, yte = binary_example

    def auc(score):
        return roc_auc_score(yte, score)

    params = {"objective": "binary", "num_leaves": 31,
              "learning_rate": 0.1, "min_data_in_leaf": 20,
              "verbosity": -1}
    booster = lgb.train(params, lgb.Dataset(Xtr, label=ytr), 20)
    a_ref, a_ours = auc(ref_pred), auc(booster.predict(Xte))
    assert abs(a_ref - a_ours) < 0.01, (a_ref, a_ours)
    assert a_ours > 0.75


@pytest.mark.parametrize("task", [
    # (example dir, train file, test file, extra params)
    ("regression", "regression.train", "regression.test",
     {"objective": "regression", "metric": "l2"}),
    ("multiclass_classification", "multiclass.train", "multiclass.test",
     {"objective": "multiclass", "num_class": 5}),
    ("lambdarank", "rank.train", "rank.test",
     {"objective": "lambdarank", "metric": "ndcg"}),
    ("xendcg", "rank.train", "rank.test",
     {"objective": "rank_xendcg", "metric": "ndcg"}),
], ids=["regression", "multiclass", "lambdarank", "xendcg"])
def test_cross_load_parity_all_objectives(task, tmp_path):
    """Reference-trained models for the OTHER objective families load here
    with prediction parity — regression, multiclass softmax (5 classes,
    K trees/iter) and lambdarank (query files, LibSVM input)."""
    exdir, train, test, extra = task
    base = f"/root/reference/examples/{exdir}"
    args = [f"data={base}/{train}", "num_trees=15", "num_leaves=15",
            "min_data_in_leaf=20", "verbosity=-1",
            f"output_model={tmp_path}/model.txt"]
    args += [f"{k}={v}" for k, v in extra.items()]
    _run_ref(tmp_path, "task=train", *args)
    _run_ref(tmp_path, "task=predict", f"data={base}/{test}",
             f"input_model={tmp_path}/model.txt",
             f"output_result={tmp_path}/pred.txt")
    ref_pred = np.loadtxt(tmp_path / "pred.txt")

    booster = lgb.Booster(model_file=str(tmp_path / "model.txt"))
    # the test files are LibSVM/TSV with a label column; parse like the
    # reference's Predictor (sparse LibSVM for lambdarank)
    if exdir in ("lambdarank", "xendcg"):
        from sklearn.datasets import load_svmlight_file
        # the reference reads LibSVM indices literally as 0-based columns
        # (parser.cpp); sklearn's auto-detection would shift them by one
        X, _ = load_svmlight_file(f"{base}/{test}", zero_based=True,
                                  n_features=booster.num_feature())
        X = np.asarray(X.todense())
    else:
        X = np.loadtxt(f"{base}/{test}")[:, 1:]
    ours = booster.predict(X, raw_score=exdir in ("lambdarank", "xendcg"))
    np.testing.assert_allclose(ours, ref_pred, rtol=1e-4, atol=1e-6)


def test_cli_consumes_reference_conf(tmp_path):
    """CONFIG-FILE compat: our CLI trains from the reference's own
    examples/binary_classification/train.conf UNCHANGED (relative data
    paths, metric lists, bagging/feature-fraction settings), and the two
    CLIs' held-out accuracies agree — the reference's consistency-harness
    flow (tests/python_package_test/test_consistency.py FileLoader)."""
    import sys
    conf = f"{EXAMPLES}/train.conf"
    # ours: same conf, fewer trees for speed, outputs into tmp
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", f"config={conf}",
         "num_trees=20", f"output_model={tmp_path}/ours.txt",
         "verbosity=-1"],
        cwd=EXAMPLES, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__))) + os.pathsep
             + os.environ.get("PYTHONPATH", "")})
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    _run_ref(EXAMPLES, "task=train", f"config={conf}", "num_trees=20",
             f"output_model={tmp_path}/ref.txt", "verbosity=-1")
    # both models predict the held-out file through the REFERENCE binary
    # (prediction parity for our model text is proven elsewhere)
    for name in ("ours", "ref"):
        _run_ref(EXAMPLES, "task=predict", "data=binary.test",
                 f"input_model={tmp_path}/{name}.txt",
                 f"output_result={tmp_path}/{name}_pred.txt")
    yte = np.loadtxt(f"{EXAMPLES}/binary.test")[:, 0]
    acc = {}
    for name in ("ours", "ref"):
        p = np.loadtxt(tmp_path / f"{name}_pred.txt")
        acc[name] = float(np.mean((p > 0.5) == (yte > 0.5)))
    assert acc["ours"] > 0.7, acc
    assert abs(acc["ours"] - acc["ref"]) < 0.05, acc
